#!/bin/sh
# Round-5 tunnel watcher: probe until the TPU answers, then run the queued
# measurement sequence exactly once. Never leaves two TPU processes running
# (each probe is `timeout`-killed before the next; the measurement script
# runs stages sequentially).
cd "$(dirname "$0")/.." || exit 1
LOG=artifacts/tunnel_watch.log
MARKER=artifacts/tunnel_healthy.marker
: > "$LOG"
while true; do
  date >> "$LOG"
  if timeout 150 python -c "
import jax, jax.numpy as jnp
d = jax.devices()
assert d and d[0].platform == 'tpu', d
x = jnp.ones((256, 256), dtype=jnp.bfloat16)
print('probe ok', float((x @ x).sum()))
" >> "$LOG" 2>&1; then
    echo "TUNNEL HEALTHY $(date)" >> "$LOG"
    touch "$MARKER"
    sh artifacts/run_r4_measurements.sh >> "$LOG" 2>&1
    echo "MEASUREMENTS DONE rc=$? $(date)" >> "$LOG"
    exit 0
  fi
  echo "probe failed/wedged $(date)" >> "$LOG"
  sleep 240
done
