#!/usr/bin/env bash
# Flagship 1,000-sample ensemble evaluation (the reference's headline artifact:
# Code/C-DAC Server/combiner_fp.py:429-474 over natural_questions_1000.csv).
#
# Runs in 100-sample segments, each a FRESH process that RESUMES from
# artifacts/results_synthetic.jsonl — this both exercises the harness's
# resume path (SURVEY.md §5.4) for real and bounds per-process compile-cache
# growth (a prior single-process run died at row ~152 with an LLVM
# "Cannot allocate memory" during a late compile; see eval_seg1.log history).
#
# Models are SYNTHETIC (random-init tiny transformers, one per role) because
# this environment ships no trained checkpoints and has no network egress —
# the artifact demonstrates the full harness machinery (3-agent ensemble,
# 9 metrics incl. model-based embeddings, JSONL persistence, resume,
# zero-fill policy, aggregate report), NOT quality parity with BASELINE.md
# Tables 1-2. See README.md "Flagship evaluation artifact" for the honest
# comparison.
set -u
cd "$(dirname "$0")/.."
OUT=artifacts/results_synthetic.jsonl
LOG=artifacts/eval_flagship.log
REPORT=artifacts/report_synthetic.json
: > "$LOG"
for seg in $(seq 1 10); do
  n=$((seg * 100))
  echo "=== segment $seg (samples <= $n) $(date -u +%FT%TZ) ===" >> "$LOG"
  JAX_PLATFORMS=cpu python -m edgemesh.cli eval \
    --config examples/ensemble_synthetic.yaml \
    --embedder synthetic \
    --eval.num_samples "$n" \
    --eval.batch_size 8 \
    --eval.output_jsonl "$OUT" >> "$LOG" 2>&1
  rc=$?
  echo "segment $seg rc=$rc" >> "$LOG"
done
# The last segment's printed report aggregates all 1,000 rows.
grep -E '^\{' "$LOG" | tail -1 > "$REPORT"
echo "done: $(wc -l < "$OUT") rows; report -> $REPORT"
