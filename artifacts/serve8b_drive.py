"""Llama-3-8B continuous-serving drive (single chip): fabricated int8
weights, 4-slot paged engine, 12 requests. Measured 2026-07-31: 130.2 tok/s
aggregate, 2.7 req/s, p50 3.0s, p95 4.4s (artifacts/serving8b_2026-07-31.json).
Run from the repo root on a healthy tunnel: python artifacts/serve8b_drive.py"""
import json, time
from edgemesh.utils.platform import ensure_device_ready, tree_sync
ensure_device_ready()
import numpy as np
from edgemesh.agents.orchestrator import Agent
from edgemesh.benchmarks import PRESETS, fabricate_int8_params
from edgemesh.config import SamplingParams
from edgemesh.models.families import config_for_family
from edgemesh.models.tokenizer import ByteTokenizer
from edgemesh.serve.continuous import ContinuousEngine

cfg = config_for_family("llama", **PRESETS["llama8b"]).replace(dtype="bfloat16")
cfg = cfg.replace(max_seq_len=1024)
params = fabricate_int8_params(cfg)
tree_sync(params)
agent = Agent(role="qa", cfg=cfg, params=params, tokenizer=ByteTokenizer(),
              sampling=SamplingParams(max_new_tokens=48, temperature=0.7, top_k=50,
                                      top_p=0.9, repetition_penalty=1.2, do_sample=True),
              prefix_cache=False)
eng = ContinuousEngine(agent, slots=4, chunk=24, kv_backend="paged",
                       page_size=64, total_pages=96)
q = "benchmark question number {i:02d}, please answer at length?"
try:
    eng.answer(q.format(i=99))
    n = 12
    t0 = time.perf_counter()
    futs = [eng.submit(q.format(i=i)) for i in range(n)]
    results = [f.result() for f in futs]
    wall = time.perf_counter() - t0
    gen = sum(r["generated"] for r in results)
    lats = [r["t_end"] - r["t_start"] + r["queue_s"] for r in results]
    print(json.dumps({
        "metric": "serving_tok_s_llama8b_int8_paged",
        "value": round(gen / wall, 2), "generated": gen,
        "req_s": round(n / wall, 3),
        "latency_s_p50": round(float(np.percentile(lats, 50)), 3),
        "latency_s_p95": round(float(np.percentile(lats, 95)), 3),
        "stats": eng.stats(),
    }))
finally:
    eng.close()
