"""Llama-3-8B continuous-serving drive (single chip): fabricated int8
weights, 4-slot paged engine, 3 waves x 16 requests (median-of-waves, the
round-4 variance protocol). Round-3 baseline on the synchronous engine:
130.2 tok/s aggregate (artifacts/serving8b_2026-07-31.json). Run from the
repo root on a healthy tunnel: python artifacts/serve8b_drive.py"""
import json, os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from edgemesh.utils.platform import ensure_device_ready, tree_sync
ensure_device_ready()
import numpy as np
from edgemesh.agents.orchestrator import Agent
from edgemesh.benchmarks import PRESETS, fabricate_int8_params
from edgemesh.config import SamplingParams
from edgemesh.models.families import config_for_family
from edgemesh.models.tokenizer import ByteTokenizer
from edgemesh.serve.continuous import ContinuousEngine

cfg = config_for_family("llama", **PRESETS["llama8b"]).replace(dtype="bfloat16")
cfg = cfg.replace(max_seq_len=1024)
params = fabricate_int8_params(cfg)
tree_sync(params)
agent = Agent(role="qa", cfg=cfg, params=params, tokenizer=ByteTokenizer(),
              sampling=SamplingParams(max_new_tokens=48, temperature=0.7, top_k=50,
                                      top_p=0.9, repetition_penalty=1.2, do_sample=True),
              prefix_cache=False)
eng = ContinuousEngine(agent, slots=4, chunk=24, kv_backend="paged",
                       page_size=64, total_pages=96)
q = "benchmark question number {i:03d}, please answer at length?"
try:
    eng.answer(q.format(i=999))  # warmup, same length bucket as timed
    n, waves = 16, 3
    wave_tok_s, results = [], []
    t0_all = time.perf_counter()
    for w in range(waves):
        t0 = time.perf_counter()
        futs = [eng.submit(q.format(i=w * n + i)) for i in range(n)]
        wave = [f.result() for f in futs]
        wall = time.perf_counter() - t0
        wave_tok_s.append(sum(r["generated"] for r in wave) / wall)
        results.extend(wave)
    wall_all = time.perf_counter() - t0_all
    gen = sum(r["generated"] for r in results)
    lats = [r["t_end"] - r["t_start"] + r["queue_s"] for r in results]
    med = float(np.median(wave_tok_s))
    out = {
        "metric": "serving_tok_s_llama8b_int8_paged",
        "value": round(med, 2),
        "wave_tok_s": [round(t, 2) for t in wave_tok_s],
        "spread_pct": round(100 * (max(wave_tok_s) - min(wave_tok_s)) / med, 1),
        "generated": gen,
        "req_s": round(len(results) / wall_all, 3),
        "latency_s_p50": round(float(np.percentile(lats, 50)), 3),
        "latency_s_p95": round(float(np.percentile(lats, 95)), 3),
        "stats": eng.stats(),
    }
    print(json.dumps(out))
    from pathlib import Path

    from edgemesh.utils.record import archive_result

    archive_result(out, "serving8b", Path(__file__).parent)
finally:
    eng.close()
