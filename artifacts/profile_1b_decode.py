"""VERDICT r3 weakness #4: the 1B b8 decode loop shows ~49% HBM util while
8B shows ~77%; PERFORMANCE.md blamed "dispatch latency" but the loop is ONE
compiled program. Capture a device profile of a long decode window plus
blocking-timer evidence to find the 1.4 ms/step wall-vs-busy gap.

Run from the repo root on a healthy tunnel:
    python artifacts/profile_1b_decode.py
Writes the trace to artifacts/profile_1b/ and prints a timing table.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from edgemesh.utils.platform import ensure_device_ready

ensure_device_ready()
import jax
import jax.numpy as jnp

from edgemesh.benchmarks import _build
from edgemesh.config import SamplingParams
from edgemesh.runtime.generate import generate
from edgemesh.utils.platform import device_sync
from edgemesh.utils.tracing import capture_profile

cfg, params = _build("llama1b", "int8", "w8a16")
sampling = SamplingParams(max_new_tokens=512, temperature=0.7, top_k=50,
                          top_p=0.9, repetition_penalty=1.2, do_sample=True)
tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                            cfg.vocab_size, jnp.int32)
lengths = jnp.full((8,), 32, jnp.int32)

r = generate(cfg, params, tokens, lengths, sampling)  # compile
print(f"warm: {r.decode_tok_s:.0f} tok/s")

# A: whole-program wall per step at several window lengths — if per-step
# wall shrinks as the window grows, the overhead is per-PROGRAM (dispatch/
# sync), not per-step.
for steps in (64, 128, 512):
    s = SamplingParams(max_new_tokens=steps, temperature=0.7, top_k=50,
                       top_p=0.9, repetition_penalty=1.2, do_sample=True)
    generate(cfg, params, tokens, lengths, s)  # compile this window
    best = 0.0
    for _ in range(3):
        rr = generate(cfg, params, tokens, lengths, s)
        best = max(best, rr.decode_tok_s)
    print(f"steps={steps}: {best:.0f} tok/s = {8 * steps / best * 1e3 / steps:.3f} ms/step")

# B: back-to-back programs with ONE sync at the end (pure device time).
from edgemesh.runtime.generate import _decode_loop
from edgemesh.models.transformer import forward_prefill, init_kv_cache
from edgemesh.ops.sampling import TokenMaskState

cache = init_kv_cache(cfg, 8, cfg.max_seq_len)
logits, cache = forward_prefill(cfg, params, tokens, lengths, cache)
logits = logits.astype(jnp.float32)
mask = TokenMaskState.init(8, cfg.vocab_size).mask
rng = jax.random.PRNGKey(0)
s128 = SamplingParams(max_new_tokens=128, temperature=0.7, top_k=50,
                      top_p=0.9, repetition_penalty=1.2, do_sample=True)
out, counts, cache, _, mask, prev, fin = _decode_loop(
    cfg, params, s128, 128, -1, logits, cache, mask, rng)
device_sync(out)
t0 = time.perf_counter()
N = 4
for i in range(N):
    out, counts, cache, _, mask, prev, fin = _decode_loop(
        cfg, params, s128, 128, -1, logits, cache, mask,
        jax.random.fold_in(rng, i))
device_sync(out)
per = (time.perf_counter() - t0) / (N * 128)
print(f"chained loops, one sync: {1e3 * per:.3f} ms/step = {8 / per:.0f} tok/s")

# C: isolate the per-step non-matmul tail. Leading hypothesis for the 49%
# HBM util: lax.top_k(50) over the 128,256-wide vocab EVERY step (a
# sort-based lowering on TPU) — 8B pays the same vocab cost against 4.7x
# the weight time, which would explain its better (0.75) util. Time the
# jitted sampling transform alone on bench-shaped logits, and the exact
# top_k alone vs approx_max_k (the TPU-native MIPS op, sampling.py's
# opt-in approx_top_k=True path).
from edgemesh.ops.sampling import sample_token
from edgemesh.config import SamplingParams as _SP

lg = jax.random.normal(jax.random.PRNGKey(2), (8, cfg.vocab_size), jnp.float32)


def _time(fn, *args, iters=50):
    fn(*args)  # compile
    device_sync(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        r = fn(*args)
    device_sync(r)
    return (time.perf_counter() - t0) / iters


import dataclasses

sp_exact = _SP(max_new_tokens=1, temperature=0.7, top_k=50, top_p=0.9,
               repetition_penalty=1.2, do_sample=True)
sp_approx = dataclasses.replace(sp_exact, approx_top_k=True)
t_samp = _time(jax.jit(lambda r, l: sample_token(r, l, sp_exact)), rng, lg)
t_samp_a = _time(jax.jit(lambda r, l: sample_token(r, l, sp_approx)), rng, lg)
t_topk = _time(jax.jit(lambda l: jax.lax.top_k(l, 50)[0]), lg)
t_approx = _time(jax.jit(lambda l: jax.lax.approx_max_k(l, 50)[0]), lg)
print(f"sampling transform alone: exact {1e3 * t_samp:.3f} ms/step vs "
      f"approx {1e3 * t_samp_a:.3f} ms/step; "
      f"bare exact top_k(50): {1e3 * t_topk:.3f} ms; "
      f"bare approx_max_k(50): {1e3 * t_approx:.3f} ms "
      f"(decode step total ~{1e3 * per:.3f} ms)")

# D: device profile of one 512-step window.
with capture_profile("artifacts/profile_1b"):
    generate(cfg, params, tokens, lengths, sampling)
print("profile -> artifacts/profile_1b/")
