#!/usr/bin/env python
"""Offline quality experiment: reproduce the reference's headline findings
with models trained by `edgemesh train` (docs/QUALITY.md is the writeup).

The reference's flagship artifact is a 1,000-sample Natural Questions sweep
over trained models showing (a) ensemble > best single model and (b) int8 ≈
fp quality (Code/C-DAC Server/combiner_fp.py:429-474; ACL paper Tables 1-2).
This environment has no network egress, so no pretrained checkpoints exist;
the surrogate: tiny byte-level models finetuned from scratch on NQ train
splits through the framework's own training loop, then evaluated by the
framework's own harness over the full 1,000 rows.

Design (complementary knowledge — the reference's multi-agent premise):
- Stage 1: qa_a trains on rows 0-499, qa_b on rows 500-999 (disjoint
  splits, distinct role-seeded inits). Each single model can only answer
  the half it studied.
- Stage 2: both QA models draft answers for ALL rows; a refiner corpus is
  built from the ensemble's OWN refiner prompts (question + both drafts)
  with the gold answer as target — the refiner learns to merge/select
  candidates, the role the reference gives its Llama refiner.
- Stage 3: evals over all 1,000 rows: singles, max-confidence selection
  ensemble (refinerless Ensemble mode), refiner ensemble, and quantized
  rows (int8 w8a16 / w8a8 / w8a8+SmoothQuant / int4) reusing the SAME
  trained checkpoints via ModelSpec.train_checkpoint — quality deltas
  isolate the numeric transform exactly as the reference's base-vs-quant
  runner pairs do.

Deviations from the reference protocol, recorded for honesty: models are
~0.7M-param byte-level LMs trained from scratch (memorization regime —
recall of trained knowledge, not open-domain QA), decoding is greedy with
repetition_penalty 1.0, the QA prompt template matches the training format
exactly (tiny models cannot bridge template shift), and cosine/BERTScore
use the pinned synthetic ModelEmbedder (no MiniLM checkpoint on disk; the
bert-family ingest exists for when one is).

Run: JAX_PLATFORMS=cpu python artifacts/quality/run_quality.py
Env: EDGEMESH_QUALITY_STEPS (default 2200), EDGEMESH_QUALITY_REFINER_STEPS
     (default 800), EDGEMESH_QUALITY_ROWS (1000),
     EDGEMESH_QUALITY_DIR (artifacts/quality).
"""

import json
import os
import sys
import time
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax

jax.config.update("jax_platforms", "cpu")

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))

from edgemesh.agents.orchestrator import (  # noqa: E402
    Ensemble,
    REFINER_TEMPLATE,
    build_agent,
)
from edgemesh.config import (  # noqa: E402
    AgentSpec,
    EdgeMeshConfig,
    ModelSpec,
    SamplingParams,
    TrainSpec,
)
from edgemesh.eval.data import load_qa_csv, resolve_dataset_path  # noqa: E402
from edgemesh.eval.embedder import build_embedder  # noqa: E402
from edgemesh.eval.harness import run_eval  # noqa: E402
from edgemesh.training import run_training  # noqa: E402

STEPS = int(os.environ.get("EDGEMESH_QUALITY_STEPS", "2200"))
R_STEPS = int(os.environ.get("EDGEMESH_QUALITY_REFINER_STEPS", "800"))
ROWS = int(os.environ.get("EDGEMESH_QUALITY_ROWS", "1000"))
OUT = Path(os.environ.get("EDGEMESH_QUALITY_DIR", str(REPO / "artifacts/quality")))

ARCH = dict(num_layers=4, hidden_size=128, num_heads=4, num_kv_heads=4,
            intermediate_size=256, max_seq_len=384)
# The exact training format (training.py builds "Question: {q}\nAnswer: {a}")
# — tiny byte-level models cannot bridge a template shift at eval time.
QA_TEMPLATE = "Question: {question}\nAnswer:"
SAMPLING = SamplingParams(max_new_tokens=64, do_sample=False,
                          repetition_penalty=1.0)
METRICS = ["rouge1", "rouge2", "rougeL", "avg_rouge", "bleu", "cosine",
           "confidence", "bertscore", "tps"]


def log(msg: str) -> None:
    print(f"[quality +{time.perf_counter() - T0:7.1f}s] {msg}", flush=True)


def train(role: str, skip: int, take: int, steps: int, seq_len: int = 96,
          corpus: str = "", batch: int = 32) -> str:
    ckpt = str(OUT / f"ckpt_{role}")
    cfg = EdgeMeshConfig(
        agents=[AgentSpec(role=role, model=ModelSpec(precision="fp32", **ARCH))],
        train=TrainSpec(steps=steps, batch_size=batch, seq_len=seq_len, lr=3e-3,
                        num_samples=take, skip_samples=skip,
                        corpus_jsonl=corpus,
                        checkpoint_dir=ckpt, checkpoint_every=max(steps // 3, 1),
                        log_every=max(steps // 10, 1)),
    )
    r = run_training(cfg)
    log(f"trained {role} (skip={skip} take={take} steps={steps}): "
        f"loss {r['first_loss']} -> {r['final_loss']} "
        f"(resumed_from={r['resumed_from']})")
    return ckpt


def agent(role: str, ckpt: str, precision: str = "fp32",
          calibration: str = "", template: str = QA_TEMPLATE) -> object:
    spec = AgentSpec(
        role=role,
        model=ModelSpec(precision=precision, train_checkpoint=ckpt,
                        calibration=calibration, **ARCH),
        sampling=SAMPLING,
        prompt_template=template,
    )
    return build_agent(spec)


def evaluate(name: str, ensemble: Ensemble, samples, embedder) -> dict:
    out_jsonl = OUT / f"results_{name}.jsonl"
    if out_jsonl.exists():
        out_jsonl.unlink()  # fresh run; resume is for crashes mid-run
    report = run_eval(
        samples, ensemble.answer, output_jsonl=str(out_jsonl), resume=True,
        metrics=METRICS, embedder=embedder,
        answer_batch_fn=ensemble.answer_batch, batch_size=16,
    )
    (OUT / f"report_{name}.json").write_text(json.dumps(report, indent=2))
    log(f"eval {name}: avg_rouge={report['avg_rouge']:.4f} "
        f"bleu={report['bleu']:.4f} bertscore={report['bertscore']:.4f} "
        f"cosine={report['cosine']:.4f} conf={report['confidence']:.4f}")
    return report


def build_refiner_corpus(a, b, samples) -> str:
    """Stage 2: draft answers from both QA models for every row, then emit
    refiner-formatted training rows (the ensemble's exact refiner prompt +
    the gold answer) — the refiner learns to merge/select candidates."""
    path = OUT / "refiner_corpus.jsonl"
    rows = []
    bs = 16
    for i in range(0, len(samples), bs):
        chunk = samples[i : i + bs]
        qs = [s.question for s in chunk]
        da = a.answer_batch(qs)
        db = b.answer_batch(qs)
        for s, ra, rb in zip(chunk, da, db):
            candidates = f"Answer 1: {ra['answer']}\nAnswer 2: {rb['answer']}\n"
            prompt = REFINER_TEMPLATE.format(question=s.question,
                                             candidates=candidates)
            rows.append({"text": f"{prompt} {s.answer}"})
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    log(f"refiner corpus: {len(rows)} rows -> {path}")
    return str(path)


def main() -> None:
    OUT.mkdir(parents=True, exist_ok=True)
    samples = load_qa_csv(resolve_dataset_path(""), limit=ROWS)
    half = max(1, len(samples) // 2)

    ck_a = train("qa_a", 0, half, STEPS, seq_len=128)
    # take exactly the second half of the EVAL window — take=0 ("the rest")
    # would spill past ROWS whenever ROWS < the CSV size and break the
    # disjoint-half symmetry the ensemble claim rests on.
    ck_b = train("qa_b", half, len(samples) - half, STEPS, seq_len=128)

    a_fp = agent("qa_a", ck_a)
    b_fp = agent("qa_b", ck_b)

    corpus = build_refiner_corpus(a_fp, b_fp, samples)
    # Refiner rows are ~360 bytes (template + two 64-byte drafts + gold);
    # seq 384 with batch 16 keeps the step affordable on this host.
    ck_r = train("refiner", 0, 0, R_STEPS, seq_len=384, corpus=corpus, batch=16)

    # SmoothQuant calibration prompts: deployment-style prompts spread over
    # the corpus (works at any ROWS).
    calib = OUT / "calibration.txt"
    stride = max(1, len(samples) // 32)
    calib.write_text("\n".join(
        f"Question: {s.question}\nAnswer:" for s in samples[::stride][:32]
    ))

    embedder = build_embedder("synthetic")
    reports: dict[str, dict] = {}

    def ens(*agents_, refiner=None):
        return Ensemble(qa_agents=list(agents_), refiner=refiner)

    reports["single_a_fp32"] = evaluate("single_a_fp32", ens(a_fp), samples, embedder)
    reports["single_b_fp32"] = evaluate("single_b_fp32", ens(b_fp), samples, embedder)
    reports["ensemble_select_fp32"] = evaluate(
        "ensemble_select_fp32", ens(a_fp, b_fp), samples, embedder)
    r_fp = agent("refiner", ck_r, template="")  # role default: REFINER_TEMPLATE
    reports["ensemble_refiner_fp32"] = evaluate(
        "ensemble_refiner_fp32", ens(a_fp, b_fp, refiner=r_fp), samples, embedder)
    del a_fp, b_fp, r_fp

    # Quantized rows: SAME checkpoints, numeric transform only.
    for prec, cal, name in (
        ("int8", "", "single_a_int8"),
        ("int8_w8a8", "", "single_a_w8a8"),
        ("int8_w8a8", str(calib), "single_a_w8a8_smoothquant"),
        ("int4", "", "single_a_int4"),
    ):
        a_q = agent("qa_a", ck_a, precision=prec, calibration=cal)
        reports[name] = evaluate(name, ens(a_q), samples, embedder)
        del a_q
    a_q8 = agent("qa_a", ck_a, precision="int8")
    b_q8 = agent("qa_b", ck_b, precision="int8")
    reports["ensemble_select_int8"] = evaluate(
        "ensemble_select_int8", ens(a_q8, b_q8), samples, embedder)
    del a_q8, b_q8

    # LoRA arm (round 4): adapt qa_a — trained on the FIRST half — to the
    # SECOND half with rank-8 adapters over its frozen trained base
    # (ModelSpec.lora_base + train_checkpoint = the adapter run), the
    # finetune-a-trained-model flow the xlsx roadmap planned and round 3
    # could not express. The kilobyte adapter should recover cross-split
    # quality the frozen base never saw.
    lora_steps = max(STEPS // 2, 1)
    ck_lora = str(OUT / "ckpt_qa_a_lora_b")
    lora_fields = dict(precision="fp32", lora_rank=8, lora_alpha=16.0,
                       lora_targets="q,k,v,o", lora_base=ck_a, **ARCH)
    lcfg = EdgeMeshConfig(
        agents=[AgentSpec(role="qa_a", model=ModelSpec(**lora_fields))],
        train=TrainSpec(steps=lora_steps, batch_size=32, seq_len=128, lr=3e-3,
                        num_samples=len(samples) - half, skip_samples=half,
                        checkpoint_dir=ck_lora,
                        checkpoint_every=max(lora_steps // 3, 1),
                        log_every=max(lora_steps // 10, 1)),
    )
    rl = run_training(lcfg)
    log(f"lora-adapted qa_a -> split b: loss {rl['first_loss']} -> "
        f"{rl['final_loss']} ({rl['lora_rank']=} adapters only)")
    a_lora = build_agent(AgentSpec(
        role="qa_a",
        model=ModelSpec(train_checkpoint=ck_lora, **lora_fields),
        sampling=SAMPLING, prompt_template=QA_TEMPLATE))
    reports["single_a_lora_to_b"] = evaluate(
        "single_a_lora_to_b", ens(a_lora), samples, embedder)
    del a_lora

    summary = {
        "steps": STEPS, "refiner_steps": R_STEPS, "rows": ROWS, "arch": ARCH,
        "sampling": {"max_new_tokens": SAMPLING.max_new_tokens,
                     "greedy": not SAMPLING.do_sample},
        "reports": {k: {m: v[m] for m in
                        ("avg_rouge", "rouge1", "rouge2", "rougeL", "bleu",
                         "bertscore", "cosine", "confidence", "tps",
                         "wall_time_s", "num_samples")}
                    for k, v in reports.items()},
    }
    (OUT / "summary.json").write_text(json.dumps(summary, indent=2))
    best_single = max(reports["single_a_fp32"]["avg_rouge"],
                      reports["single_b_fp32"]["avg_rouge"])
    log(f"DONE. ensemble_select avg_rouge="
        f"{reports['ensemble_select_fp32']['avg_rouge']:.4f} vs best single "
        f"{best_single:.4f}; int8 delta="
        f"{reports['single_a_int8']['avg_rouge'] - reports['single_a_fp32']['avg_rouge']:+.4f}")


T0 = time.perf_counter()
if __name__ == "__main__":
    main()
