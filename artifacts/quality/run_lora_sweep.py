#!/usr/bin/env python
"""LoRA calibration sweep (round 5): turn round 4's single-point negative
into a measured result.

Round 4's arm reported single_a_lora_to_b avg_rouge 0.0555 vs frozen base
0.1226 — but that aggregate conflates two effects. Decomposed by training
split (index < 500 = split a = the base's training half):

    single_a_fp32        split_a 0.2090   split_b 0.0362
    single_a_lora_to_b   split_a 0.0556   split_b 0.0555

On the ADAPTATION TARGET the rank-8 adapter beat its frozen base by +53%
(0.0362 -> 0.0555); the aggregate fell because adapting through q/k/v/o
destroyed the base's split-a knowledge (0.2090 -> 0.0556, catastrophic
interference — the adapter output is added on every input, split-a prompts
included). This sweep measures both axes properly:

- rank sweep {8, 32, 128} x steps (env) on the full split-b adaptation,
  reporting split_a (forgetting) and split_b (gain) separately;
- a capacity-matched positive control: rank 8 on a 100-row subset of
  split b, evaluated on those 100 rows — can a ~100KB adapter memorize a
  workload sized to its capacity?

Reference tie-in: the reference roadmap's unstarted finetuning rows
(Others/.xlsx "QA and Tasks to Do") planned exactly this adapt-a-trained-
model flow; the reference never measured it.

Run:   JAX_PLATFORMS=cpu python artifacts/quality/run_lora_sweep.py
Env:   EDGEMESH_LORA_RANKS   (default "8,32,128")
       EDGEMESH_LORA_STEPS   (default 2200)
       EDGEMESH_LORA_CONTROL (default 1 — run the 100-row positive control)
       EDGEMESH_QUALITY_DIR  (default artifacts/quality; must hold ckpt_qa_a)
Writes report_lora_r{rank}_s{steps}.json (+ _control) and lora_sweep.json.
"""

import json
import os
import sys
import time
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax

jax.config.update("jax_platforms", "cpu")

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO))

from edgemesh.agents.orchestrator import build_agent  # noqa: E402
from edgemesh.config import (  # noqa: E402
    AgentSpec,
    EdgeMeshConfig,
    ModelSpec,
    SamplingParams,
    TrainSpec,
)
from edgemesh.eval.data import load_qa_csv, resolve_dataset_path  # noqa: E402
from edgemesh.eval.embedder import build_embedder  # noqa: E402
from edgemesh.eval.harness import run_eval  # noqa: E402
from edgemesh.training import run_training  # noqa: E402

RANKS = [int(r) for r in os.environ.get("EDGEMESH_LORA_RANKS", "8,32,128").split(",")]
STEPS = int(os.environ.get("EDGEMESH_LORA_STEPS", "2200"))
CONTROL = os.environ.get("EDGEMESH_LORA_CONTROL", "1") == "1"
OUT = Path(os.environ.get("EDGEMESH_QUALITY_DIR", str(REPO / "artifacts/quality")))
CONTROL_ROWS = 100

# Must match run_quality.py exactly: same arch, same frozen base, same
# greedy sampling, same training prompt format.
ARCH = dict(num_layers=4, hidden_size=128, num_heads=4, num_kv_heads=4,
            intermediate_size=256, max_seq_len=384)
QA_TEMPLATE = "Question: {question}\nAnswer:"
SAMPLING = SamplingParams(max_new_tokens=64, do_sample=False,
                          repetition_penalty=1.0)
METRICS = ["rouge1", "rouge2", "rougeL", "avg_rouge", "bleu", "cosine",
           "confidence", "bertscore", "tps"]
T0 = time.perf_counter()


def log(msg: str) -> None:
    print(f"[lora-sweep +{time.perf_counter() - T0:7.1f}s] {msg}", flush=True)


def train_adapter(name: str, rank: int, steps: int, skip: int, take: int,
                  base_ckpt: str) -> tuple[str, dict]:
    ckpt = str(OUT / f"ckpt_{name}")
    fields = dict(precision="fp32", lora_rank=rank, lora_alpha=2.0 * rank,
                  lora_targets="q,k,v,o", lora_base=base_ckpt, **ARCH)
    cfg = EdgeMeshConfig(
        agents=[AgentSpec(role="qa_a", model=ModelSpec(**fields))],
        train=TrainSpec(steps=steps, batch_size=32, seq_len=128, lr=3e-3,
                        num_samples=take, skip_samples=skip,
                        checkpoint_dir=ckpt,
                        checkpoint_every=max(steps // 3, 1),
                        log_every=max(steps // 10, 1)),
    )
    r = run_training(cfg)
    log(f"{name}: rank={rank} steps={steps} skip={skip} take={take} "
        f"loss {r['first_loss']:.3f} -> {r['final_loss']:.4f}")
    return ckpt, fields


def eval_split(name: str, agent, samples, embedder, boundary: int) -> dict:
    out_jsonl = OUT / f"results_{name}.jsonl"
    if out_jsonl.exists():
        out_jsonl.unlink()
    report = run_eval(
        samples, agent.answer, output_jsonl=str(out_jsonl), resume=True,
        metrics=METRICS, embedder=embedder,
        answer_batch_fn=agent.answer_batch, batch_size=16,
    )
    # Per-split decomposition straight from the per-sample rows.
    rows = [json.loads(line) for line in open(out_jsonl)]
    seg_a = [r["avg_rouge"] for r in rows if r["index"] < boundary]
    seg_b = [r["avg_rouge"] for r in rows if r["index"] >= boundary]
    report["avg_rouge_split_a"] = sum(seg_a) / len(seg_a) if seg_a else None
    report["avg_rouge_split_b"] = sum(seg_b) / len(seg_b) if seg_b else None
    (OUT / f"report_{name}.json").write_text(json.dumps(report, indent=2))
    log(f"eval {name}: overall={report['avg_rouge']:.4f} "
        f"split_a={report['avg_rouge_split_a']} "
        f"split_b={report['avg_rouge_split_b']}")
    return report


def main() -> None:
    base_ckpt = str(OUT / "ckpt_qa_a")
    if not Path(base_ckpt).exists():
        raise SystemExit(f"frozen base {base_ckpt} missing — run run_quality.py first")
    samples = load_qa_csv(resolve_dataset_path(""), limit=1000)
    half = len(samples) // 2
    embedder = build_embedder("synthetic")
    sweep: dict[str, dict] = {}

    for rank in RANKS:
        name = f"lora_r{rank}_s{STEPS}"
        ckpt, fields = train_adapter(name, rank, STEPS, skip=half,
                                     take=len(samples) - half, base_ckpt=base_ckpt)
        agent = build_agent(AgentSpec(
            role="qa_a", model=ModelSpec(train_checkpoint=ckpt, **fields),
            sampling=SAMPLING, prompt_template=QA_TEMPLATE))
        sweep[name] = eval_split(name, agent, samples, embedder, half)
        del agent

    if CONTROL:
        # Capacity-matched positive control: rank 8, 100 rows, evaluated on
        # exactly those rows (plus split a for the forgetting axis).
        name = f"lora_r8_control{CONTROL_ROWS}_s{STEPS}"
        ckpt, fields = train_adapter(name, 8, STEPS, skip=half,
                                     take=CONTROL_ROWS, base_ckpt=base_ckpt)
        agent = build_agent(AgentSpec(
            role="qa_a", model=ModelSpec(train_checkpoint=ckpt, **fields),
            sampling=SAMPLING, prompt_template=QA_TEMPLATE))
        subset = samples[:half] + samples[half : half + CONTROL_ROWS]
        rep = eval_split(name, agent, subset, embedder, half)
        # here split_b == the 100 adaptation rows
        sweep[name] = rep
        del agent

    (OUT / "lora_sweep.json").write_text(json.dumps(
        {"ranks": RANKS, "steps": STEPS,
         "baseline_split_decomposition": {
             "single_a_fp32": {"split_a": 0.2090, "split_b": 0.0362},
             "single_a_lora_to_b_r4": {"split_a": 0.0556, "split_b": 0.0555},
         },
         "reports": {k: {m: v.get(m) for m in
                         ("avg_rouge", "avg_rouge_split_a", "avg_rouge_split_b",
                          "bleu", "bertscore", "confidence", "num_samples",
                          "wall_time_s")}
                     for k, v in sweep.items()}}, indent=2))
    log("DONE")


if __name__ == "__main__":
    main()
