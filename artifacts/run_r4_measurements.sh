#!/bin/sh
# Round-4 measurement sequence — run on a HEALTHY tunnel, one process at a
# time (never two TPU processes). Each stage appends to r4_measurements.log.
set -x
cd "$(dirname "$0")/.." || exit 1
date >> artifacts/r4_measurements.log
python bench.py 2>>artifacts/r4_measurements.log | tee -a artifacts/r4_measurements.log
python artifacts/serve8b_drive.py 2>>artifacts/r4_measurements.log | tee -a artifacts/r4_measurements.log
python artifacts/profile_1b_decode.py 2>>artifacts/r4_measurements.log | tee -a artifacts/r4_measurements.log
