#!/bin/sh
# Round-5 measurement sequence — run on a HEALTHY tunnel, one TPU process at
# a time (never two). Each stage appends to r4_measurements.log. Any running
# LoRA-sweep CPU training is SIGSTOPped for the duration: bench serving/
# dispatch numbers are host-loop sensitive and must not time CPU contention.
set -x
cd "$(dirname "$0")/.." || exit 1
SWEEP_PIDS=$(pgrep -f run_lora_sweep.py)
resume_sweep() { [ -n "$SWEEP_PIDS" ] && kill -CONT $SWEEP_PIDS 2>/dev/null; }
# ALWAYS resume the sweep, even when a stage dies or the shell is hung up —
# a missed CONT would freeze the CPU training silently forever. On a real
# signal, resume and TERMINATE: continuing the remaining stages with the
# sweep running again would time CPU contention into the measurements.
trap resume_sweep EXIT
trap 'resume_sweep; trap - EXIT; exit 130' INT TERM HUP
[ -n "$SWEEP_PIDS" ] && kill -STOP $SWEEP_PIDS
date >> artifacts/r4_measurements.log
python bench.py 2>>artifacts/r4_measurements.log | tee -a artifacts/r4_measurements.log
python artifacts/serve8b_drive.py 2>>artifacts/r4_measurements.log | tee -a artifacts/r4_measurements.log
python artifacts/profile_1b_decode.py 2>>artifacts/r4_measurements.log | tee -a artifacts/r4_measurements.log
