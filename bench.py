#!/usr/bin/env python
"""Driver benchmark entry: prints the result JSON line
{"metric", "value", "unit", "vs_baseline"} — re-printed refreshed after
every completed stage, so the LAST JSON line on stdout is always the most
complete result even if a later stage stalls (the watchdog then exits rc=3
after re-printing the partial line, instead of losing the run).

Runs on the real TPU chip (axon platform — do NOT force cpu here). The
headline int8 decode stage runs FIRST; bf16 and the remaining paths
(w8a8, fused Pallas w8a8, paged KV, batch sweep, long context, int4,
Llama-3-8B) follow, each fenced so one failure cannot discard the rest.
The primary metric is the fastest int8 path's tokens/sec, compared against
the reference's published 25.83 tok/s for the same model quantized on A100
(BASELINE.md Table 3).
"""

import json
import sys
from pathlib import Path

def _stale_fallback(err: BaseException) -> int:
    """The device tunnel is unreachable RIGHT NOW (it has wedged for hours at
    a stretch this round, entirely outside this process's control). Rather
    than hand the driver nothing, emit the most recent result measured on
    this same chip — explicitly labeled: ``stale`` is true, the measurement
    timestamp rides along, and the exit code is 4 (not 0) so a stale echo
    can never masquerade as a fresh run."""
    # Dated names sort chronologically; newest first. A corrupt file (these
    # get written during the very outages this fallback exists for) skips to
    # the next candidate.
    for path in sorted(
        (Path(__file__).parent / "artifacts").glob("bench_*.json"), reverse=True
    ):
        try:
            with open(path) as f:
                result = json.load(f)
            if not isinstance(result, dict) or "metric" not in result:
                continue
        except (OSError, json.JSONDecodeError):
            continue
        # Date from the filename when the artifact predates the field.
        result.setdefault("measured_at_utc", path.name.split("_")[1])
        result["stale"] = True
        result["stale_reason"] = (
            f"device unreachable at bench time ({err}); value was "
            f"measured on this session's chip earlier — see artifacts/{path.name}"
        )
        print(json.dumps(result))
        return 4
    print(json.dumps({"error": f"device unreachable and no prior artifact: {err}"}))
    return 1


def main() -> int:
    from edgemesh.benchmarks import headline_benchmark, start_stall_watchdog
    from edgemesh.utils.platform import DeviceUnavailableError, ensure_device_ready

    # A wedged tunnel at first contact fails in minutes with a clear message
    # (no partial result exists yet to protect); mid-run stalls are the
    # watchdog's job, which re-prints the partial JSON before exiting rc=3.
    try:
        ensure_device_ready()
    except (DeviceUnavailableError, Exception) as err:
        # Timeout (wedged tunnel) raises DeviceUnavailableError; a FAST
        # failure (tunnel process down → immediate backend-init error)
        # surfaces as an ordinary exception — both are "device dead at
        # start" and both fall back to the stale echo.
        return _stale_fallback(err)
    # Continuous self-archiving: emit_partial rewrites one dated artifact
    # after EVERY completed stage (see edgemesh/benchmarks.py), so stall
    # exits and stage wedges still leave the freshest partial on disk.
    import os

    os.environ["EDGEMESH_BENCH_ARCHIVE"] = "1"
    start_stall_watchdog()
    result = headline_benchmark()
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
