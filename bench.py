#!/usr/bin/env python
"""Driver benchmark entry: prints ONE JSON line
{"metric", "value", "unit", "vs_baseline"}.

Runs on the real TPU chip (axon platform — do NOT force cpu here). Measures
bf16 AND all int8 decode paths on a Llama-3.2-1B-shaped model; the primary
metric is the fastest int8 path's tokens/sec, compared against the
reference's published 25.83 tok/s for the same model quantized on A100
(BASELINE.md Table 3). Extra keys record bf16 vs int8, per-path numbers,
batch sweep, TTFT, and HBM-bandwidth utilization.
"""

import json
import sys


def main() -> int:
    from edgemesh.benchmarks import headline_benchmark, start_stall_watchdog

    start_stall_watchdog()
    result = headline_benchmark()
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
