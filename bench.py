#!/usr/bin/env python
"""Driver benchmark entry: prints the result JSON line
{"metric", "value", "unit", "vs_baseline"} — re-printed refreshed after
every completed stage, so the LAST JSON line on stdout is always the most
complete result even if a later stage stalls (the watchdog then exits rc=3
after re-printing the partial line, instead of losing the run).

Runs on the real TPU chip (axon platform — do NOT force cpu here). The
headline int8 decode stage runs FIRST; bf16 and the remaining paths
(w8a8, fused Pallas w8a8, paged KV, batch sweep, long context, int4,
Llama-3-8B) follow, each fenced so one failure cannot discard the rest.
The primary metric is the fastest int8 path's tokens/sec, compared against
the reference's published 25.83 tok/s for the same model quantized on A100
(BASELINE.md Table 3).
"""

import json
import sys


def main() -> int:
    from edgemesh.benchmarks import headline_benchmark, start_stall_watchdog
    from edgemesh.utils.platform import ensure_device_ready

    # A wedged tunnel at first contact fails in minutes with a clear message
    # (no partial result exists yet to protect); mid-run stalls are the
    # watchdog's job, which re-prints the partial JSON before exiting rc=3.
    ensure_device_ready()
    start_stall_watchdog()
    result = headline_benchmark()
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
