#!/usr/bin/env python
"""Driver benchmark entry: prints ONE JSON line
{"metric", "value", "unit", "vs_baseline"}.

Runs on the real TPU chip (axon platform — do NOT force cpu here). Measures
int8 decode tokens/sec on a Llama-3.2-1B-shaped model, compared against the
reference's published 25.83 tok/s for the same model quantized on A100
(BASELINE.md Table 3).
"""

import json
import sys


def main() -> int:
    from edgemesh.benchmarks import decode_benchmark

    result = decode_benchmark()
    print(
        json.dumps(
            {
                "metric": result["metric"],
                "value": result["value"],
                "unit": result["unit"],
                "vs_baseline": result["vs_baseline"],
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
