"""Prompt templates for the QA + refiner ensemble — the ONE declaration.

These lived inline in ``agents/orchestrator.py``; the fleet-side ensemble
coordinator (fleet/ensemble.py) composes the same refiner prompt from
candidates gathered over HTTP, and forking the strings would let the
in-process and over-the-fleet ensembles drift apart silently. This module
is stdlib-only on purpose: the fleet package must stay importable on hosts
with no accelerator, so it cannot reach through ``agents.orchestrator``
(which imports jax at module scope).
"""

from __future__ import annotations

from typing import Sequence

REFINER_ROLE = "refiner"

DEFAULT_QA_TEMPLATE = "Question: {question}\nGive a short, factual answer.\nAnswer:"
REFINER_TEMPLATE = (
    "Two assistants answered the same question. Merge their answers into one "
    "clear, accurate response.\n"
    "Question: {question}\n"
    "{candidates}"
    "Merged answer:"
)

#: The replica-side passthrough template: a gateway whose coordinator
#: composes the full prompt fleet-side (the refiner pool behind
#: ``POST /ensemble``) serves the question verbatim instead of wrapping it
#: in a role template a second time.
PASSTHROUGH_TEMPLATE = "{question}"


def format_refiner_prompt(question: str, answers: Sequence[str],
                          template: str = REFINER_TEMPLATE) -> str:
    """The refiner's merge prompt over candidate answers — the reference's
    per-question block (combiner_fp.py:436-442), shared by the in-process
    ``Ensemble`` and the fleet ensemble coordinator."""
    candidates = "".join(
        f"Answer {i + 1}: {a}\n" for i, a in enumerate(answers)
    )
    return template.format(question=question, candidates=candidates)
