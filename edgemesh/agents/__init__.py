"""Multi-agent orchestration (the reference's L4 / core contribution).

The orchestrator imports jax at module scope, but the prompt templates it
shares with the fleet-side ensemble coordinator live in the stdlib-only
``edgemesh.agents.prompts`` — so the package init resolves the orchestrator
names lazily (PEP 562) instead of eagerly importing jax onto every host
that merely wants the templates.
"""

_ORCHESTRATOR_NAMES = ("Agent", "Ensemble", "build_agent", "build_ensemble")

__all__ = list(_ORCHESTRATOR_NAMES)


def __getattr__(name):
    if name in _ORCHESTRATOR_NAMES:
        from edgemesh.agents import orchestrator

        return getattr(orchestrator, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
