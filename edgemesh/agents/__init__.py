"""Multi-agent orchestration (the reference's L4 / core contribution)."""

from edgemesh.agents.orchestrator import (  # noqa: F401
    Agent,
    Ensemble,
    build_agent,
    build_ensemble,
)
