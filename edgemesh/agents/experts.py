"""Request-level expert routing: domain experts behind a question classifier.

The reference PLANNED this and never built it: the ``Expert Models`` sheet of
``Others/Distributed LLM Evaluations and Results - Partha.xlsx`` lays out 13
text-expert domains x {base, quant} x {summarizer, classifier} routing = 52
configs (SURVEY.md §2.3, EP row). This module is the working half the sheet
describes at the REQUEST level — each incoming question is classified into a
domain and dispatched to that domain's expert agent — complementing the
device-level token-routed MoE in ops/moe.py (the two halves of "expert
parallelism": per-request expert agents on submeshes, per-token experts over
the ``ep`` mesh axis).

Routing strategies (the sheet's "classifier vs summarizer" axis):
- ``KeywordClassifier``: deterministic host-side scoring — zero model cost,
  the right default for the 1-chip serving path.
- ``EmbeddingClassifier``: cosine similarity between the question embedding
  and each domain's descriptor embedding, through the SAME pluggable embedder
  the metrics suite uses (eval/metrics.py) — model-based when a model
  embedder is configured, hashing fallback otherwise.
- ``summarizer`` mode: skip classification, ask EVERY expert, merge with a
  refiner — exactly the ensemble path (agents/orchestrator.py), provided
  here as ``route_all``.

TPU mapping: each expert is an ordinary Agent bound to its own submesh
(parallel/mesh.submeshes), so concurrent questions to different experts run
on disjoint chips.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

import numpy as np

log = logging.getLogger("edgemesh.agents")

# The 13 text domains of the reference's Expert Models sheet.
DEFAULT_DOMAINS: tuple[str, ...] = (
    "science", "history", "geography", "sports", "politics",
    "entertainment", "technology", "health", "finance", "literature",
    "law", "religion", "general",
)

_DOMAIN_KEYWORDS: dict[str, tuple[str, ...]] = {
    "science": ("atom", "chemical", "physics", "biology", "species", "planet",
                "energy", "cell", "theory", "experiment", "element", "gene"),
    "history": ("war", "empire", "century", "ancient", "revolution", "king",
                "queen", "dynasty", "historical", "founded", "battle"),
    "geography": ("country", "river", "mountain", "capital", "ocean", "city",
                  "continent", "island", "border", "population", "located"),
    "sports": ("team", "league", "championship", "player", "game", "season",
               "olympic", "cup", "score", "coach", "tournament"),
    "politics": ("president", "election", "government", "parliament", "senate",
                 "minister", "law", "policy", "vote", "party", "congress"),
    "entertainment": ("movie", "film", "song", "album", "actor", "actress",
                      "band", "show", "series", "director", "singer", "tv"),
    "technology": ("computer", "software", "internet", "phone", "digital",
                   "robot", "code", "website", "app", "device", "network"),
    "health": ("disease", "medicine", "doctor", "symptom", "treatment",
               "vaccine", "virus", "body", "blood", "cancer", "drug"),
    "finance": ("money", "bank", "stock", "market", "currency", "economy",
                "tax", "price", "dollar", "investment", "company"),
    "literature": ("book", "novel", "author", "poem", "wrote", "writer",
                   "published", "character", "story", "play", "shakespeare"),
    "law": ("court", "judge", "legal", "crime", "trial", "constitution",
            "rights", "lawyer", "supreme", "justice", "amendment"),
    "religion": ("church", "god", "bible", "religion", "prayer", "temple",
                 "holy", "faith", "pope", "mosque", "worship"),
    "general": (),
}


@dataclass
class ExpertSpec:
    """One domain expert: a domain name, the agent serving it, and the
    keyword/descriptor vocabulary the classifiers route on."""

    domain: str
    agent: Any  # agents.orchestrator.Agent (duck-typed: .answer(question))
    keywords: tuple[str, ...] = ()
    descriptor: str = ""

    def __post_init__(self):
        if not self.keywords:
            self.keywords = _DOMAIN_KEYWORDS.get(self.domain, ())
        if not self.descriptor:
            self.descriptor = f"{self.domain}: " + " ".join(self.keywords[:8])


class KeywordClassifier:
    """Deterministic domain scoring: count keyword hits, ties broken by
    domain order; no hits -> fallback domain."""

    def __init__(self, experts: Sequence[ExpertSpec], fallback: str = "general"):
        self.experts = list(experts)
        self.fallback = fallback

    def __call__(self, question: str) -> str:
        words = set(question.lower().replace("?", " ").replace(",", " ").split())
        best, best_score = self.fallback, 0
        for spec in self.experts:
            score = sum(1 for k in spec.keywords if k in words)
            if score > best_score:
                best, best_score = spec.domain, score
        return best


class EmbeddingClassifier:
    """Route by cosine similarity of question vs domain-descriptor embeddings
    (the model-based classifier of the Expert Models sheet). ``embedder`` is
    any eval.metrics-compatible embedder: a callable ``[texts] -> [n, d]``."""

    def __init__(
        self,
        experts: Sequence[ExpertSpec],
        embedder: Any,
        fallback: str = "general",
        min_sim: float = 0.0,
    ):
        self.experts = list(experts)
        self.embedder = embedder
        self.fallback = fallback
        self.min_sim = min_sim
        self._domain_vecs = np.asarray(
            embedder([s.descriptor for s in self.experts]), np.float32
        )

    def __call__(self, question: str) -> str:
        q = np.asarray(self.embedder([question]), np.float32)[0]
        dv = self._domain_vecs
        denom = np.linalg.norm(dv, axis=1) * (np.linalg.norm(q) + 1e-8) + 1e-8
        sims = dv @ q / denom
        i = int(np.argmax(sims))
        if sims[i] <= self.min_sim:
            return self.fallback
        return self.experts[i].domain


@dataclass
class ExpertRouter:
    """Registry + dispatch. ``classifier`` maps question -> domain name;
    unknown domains fall back to ``fallback`` (or the first expert)."""

    experts: list[ExpertSpec]
    classifier: Callable[[str], str] | None = None
    fallback: str = "general"
    _by_domain: dict[str, ExpertSpec] = field(default_factory=dict, repr=False)

    def __post_init__(self):
        if not self.experts:
            raise ValueError("ExpertRouter needs at least one expert")
        self._by_domain = {s.domain: s for s in self.experts}
        if self.classifier is None:
            self.classifier = KeywordClassifier(self.experts, self.fallback)

    def route(self, question: str) -> ExpertSpec:
        domain = self.classifier(question)
        spec = self._by_domain.get(domain) or self._by_domain.get(self.fallback)
        return spec if spec is not None else self.experts[0]

    def answer(self, question: str) -> dict[str, Any]:
        """Classifier mode: one expert serves the question."""
        spec = self.route(question)
        out = spec.agent.answer(question)
        out["domain"] = spec.domain
        return out

    def route_all(self, question: str, refiner: Any | None = None) -> dict[str, Any]:
        """Summarizer mode: every expert answers; a refiner (or best
        confidence) merges — the sheet's alternative routing axis, sharing
        the ensemble merge semantics (orchestrator.Ensemble.answer). The
        Ensemble (and its thread pool) is built once per (router, refiner)
        and reused across questions."""
        from edgemesh.agents.orchestrator import Ensemble

        cached = getattr(self, "_route_all_ensemble", None)
        if cached is None or cached.refiner is not refiner:
            cached = Ensemble(qa_agents=[s.agent for s in self.experts], refiner=refiner)
            self._route_all_ensemble = cached
        return cached.answer(question)


def router_from_config(
    config, classifier: str = "keyword", embedder: Any | None = None
) -> ExpertRouter:
    """Build a router straight from an EdgeMeshConfig: each ``agents`` entry
    becomes one expert with its ``role`` as the domain name
    (examples/experts.yaml). Submeshes are assigned like the ensemble's —
    disjoint per expert when the device count allows."""
    from edgemesh.agents.orchestrator import build_agent
    from edgemesh.parallel.mesh import submeshes

    specs = config.agents
    if not specs:
        raise ValueError("router_from_config needs at least one agent entry")
    roles = [s.role for s in specs]
    dupes = {r for r in roles if roles.count(r) > 1}
    if dupes:
        raise ValueError(
            f"duplicate expert domains {sorted(dupes)}: each agents[] entry's "
            "role names one domain (an ensemble config with repeated 'qa' "
            "roles is not an expert registry)"
        )
    meshes: list = [None] * len(specs)
    if len(specs) > 1:
        try:
            meshes = submeshes(len(specs))
        except ValueError:
            log.warning(
                "not enough devices for %d expert submeshes; experts share "
                "devices (throughput serializes)", len(specs),
            )
    agents = {
        s.role: build_agent(s, mesh=m) for s, m in zip(specs, meshes)
    }
    return build_expert_router(agents, classifier=classifier, embedder=embedder)


def build_expert_router(
    specs_by_domain: dict[str, Any],
    classifier: str = "keyword",
    embedder: Any | None = None,
) -> ExpertRouter:
    """Assemble a router from {domain: Agent}. ``classifier``: "keyword" or
    "embedding" (requires ``embedder``)."""
    experts = [ExpertSpec(domain=d, agent=a) for d, a in specs_by_domain.items()]
    if classifier == "embedding":
        if embedder is None:
            raise ValueError("embedding classifier needs an embedder")
        clf: Callable[[str], str] | None = EmbeddingClassifier(experts, embedder)
    elif classifier == "keyword":
        clf = None  # router defaults to KeywordClassifier
    else:
        raise ValueError(f"unknown classifier {classifier!r}")
    return ExpertRouter(experts=experts, classifier=clf)
