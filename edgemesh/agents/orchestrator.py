"""Multi-agent ensemble: N QA agents + a refiner, concurrent on submeshes.

Capability parity (the reference's core contribution, SURVEY.md §2.3 row 1):
two QA models answer independently and a refiner model merges their answers
(``Code/C-DAC Server/combiner_fp.py:328-377``). Two deliberate departures:

1. **Concurrency.** The reference calls its agents back-to-back on one GPU
   (combiner_fp.py:436 then :439 — sequential, its paper §5.1 Q1 names the
   parallelization as future work). Here each agent owns a DISJOINT submesh
   (edgemesh.parallel.mesh.submeshes) and agents run under a thread pool; JAX
   dispatch is async per-device, so the QA forward passes genuinely overlap.

2. **Roles are data.** phi/pythia/refiner were hardcoded; here any number of
   ``AgentSpec`` rows, with ``role == "refiner"`` marking the merger.

Prompt behavior mirrors the reference's templates (QA prompt:
combiner_fp.py:329-332; refiner prompt injecting the question + both candidate
answers: :356-363) with original wording.
"""

from __future__ import annotations

import logging
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp

from edgemesh.config import AgentSpec, EdgeMeshConfig, ModelSpec, SamplingParams
from edgemesh.models.families import config_for_family, tiny_config
from edgemesh.models.hf_ingest import load_params
from edgemesh.models.tokenizer import load_tokenizer
from edgemesh.models.transformer import ModelConfig, init_params
from edgemesh.ops.int8 import quantize_params
from edgemesh.parallel.mesh import submeshes
from edgemesh.parallel.sharding import shard_params
from edgemesh.runtime import generate

log = logging.getLogger("edgemesh.agents")

REFINER_ROLE = "refiner"

DEFAULT_QA_TEMPLATE = "Question: {question}\nGive a short, factual answer.\nAnswer:"
REFINER_TEMPLATE = (
    "Two assistants answered the same question. Merge their answers into one "
    "clear, accurate response.\n"
    "Question: {question}\n"
    "{candidates}"
    "Merged answer:"
)


@dataclass
class Agent:
    """One model bound to a role, a (sub)mesh, and sampling params.

    With a ``draft_cfg``/``draft_params`` pair set, generation runs
    speculative decoding (runtime/speculative.py): the draft proposes
    ``spec_gamma`` tokens per round, the main model verifies them in one
    chunk — same output distribution, fewer full-model steps."""

    role: str
    cfg: ModelConfig
    params: Any
    tokenizer: Any
    sampling: SamplingParams
    prompt_template: str = DEFAULT_QA_TEMPLATE
    mesh: Any = None
    draft_cfg: ModelConfig | None = None
    draft_params: Any = None
    spec_gamma: int = 4

    def format_prompt(self, question: str, **extra) -> str:
        return self.prompt_template.format(question=question, **extra)

    def answer(self, question: str, prompt: str | None = None) -> dict[str, Any]:
        t_start = time.perf_counter()
        prompt = prompt if prompt is not None else self.format_prompt(question)
        max_ctx = self.cfg.max_seq_len
        if self.draft_cfg is not None:
            # Both caches hold the full sequence; budget against the smaller
            # context, plus the speculative chunk's overshoot headroom.
            max_ctx = min(max_ctx, self.draft_cfg.max_seq_len) - (self.spec_gamma + 1)
        max_prompt = max_ctx - self.sampling.max_new_tokens
        if max_prompt < 1:
            raise ValueError(
                f"max_new_tokens {self.sampling.max_new_tokens} leaves no room "
                f"for a prompt within max_seq_len {self.cfg.max_seq_len}"
            )
        ids = self.tokenizer.encode(prompt, max_len=max_prompt)
        # Pad the prompt up to a static bucket: jit specializes on shapes, so
        # raw per-question lengths would compile a fresh prefill per unique
        # length — unbounded compile-cache growth that OOMs a small host over
        # a 1,000-sample sweep. Buckets bound it to a handful of programs.
        bucket = 16
        while bucket < len(ids) and bucket < max_prompt:
            bucket *= 2
        bucket = min(bucket, max_prompt)
        pad = getattr(self.tokenizer, "pad_id", 0)
        padded = ids + [pad] * (bucket - len(ids))
        tokens = jnp.asarray([padded], dtype=jnp.int32)
        lengths = jnp.asarray([len(ids)], dtype=jnp.int32)
        eos_id = getattr(self.tokenizer, "eos_id", -1)
        if self.draft_cfg is not None:
            from edgemesh.runtime.speculative import generate_speculative

            result, _ = generate_speculative(
                self.cfg, self.params, self.draft_cfg, self.draft_params,
                tokens, lengths, self.sampling, gamma=self.spec_gamma,
                eos_id=eos_id,
            )
        else:
            result = generate(
                self.cfg, self.params, tokens, lengths, self.sampling,
                eos_id=eos_id,
            )
        n = int(result.num_generated[0])
        text = self.tokenizer.decode(result.tokens[0][:n])
        return {
            "answer": text.strip(),
            "role": self.role,
            "tps": result.tokens_per_sec,
            "ttft_s": result.prefill_time_s,
            "confidence": float(result.confidence[0]),
            # Wall-clock span of this agent's work — lets callers verify that
            # ensemble agents actually overlapped (tests/benchmarks assert
            # interval overlap / concurrent-vs-serial ratio).
            "t_start": t_start,
            "t_end": time.perf_counter(),
        }


@dataclass
class Ensemble:
    """QA agents + optional refiner. ``answer`` is the drop-in analog of the
    reference's per-question block (combiner_fp.py:436-442)."""

    qa_agents: list[Agent]
    refiner: Agent | None = None
    _pool: ThreadPoolExecutor | None = field(default=None, repr=False)

    def __post_init__(self):
        self._pool = ThreadPoolExecutor(max_workers=max(1, len(self.qa_agents)))

    def answer(self, question: str) -> dict[str, Any]:
        futures = [
            self._pool.submit(agent.answer, question) for agent in self.qa_agents
        ]
        drafts = [f.result() for f in futures]

        if self.refiner is None:
            best = max(drafts, key=lambda d: d["confidence"])
            return {**best, "drafts": drafts}

        candidates = "".join(
            f"Answer {i + 1}: {d['answer']}\n" for i, d in enumerate(drafts)
        )
        prompt = self.refiner.prompt_template.format(
            question=question, candidates=candidates
        )
        refined = self.refiner.answer(question, prompt=prompt)
        tps_values = [d["tps"] for d in drafts] + [refined["tps"]]
        return {
            "answer": refined["answer"],
            "confidence": refined["confidence"],
            "tps": sum(tps_values) / len(tps_values),  # mean-of-models, try.py:317-326
            "ttft_s": drafts[0]["ttft_s"],
            "drafts": drafts,
        }


def _materialize(ms: ModelSpec, role_seed: str, mesh=None) -> tuple[ModelConfig, Any, Any]:
    """(cfg, params, tokenizer) for one ModelSpec: HF checkpoint if ``path``
    is set, otherwise a synthetic random-init model with the byte tokenizer."""
    if ms.path:
        cfg, params = load_params(ms.path)
        tokenizer = load_tokenizer(ms.path)
    else:
        overrides = {
            k: v
            for k, v in dict(
                vocab_size=ms.vocab_size,
                num_layers=ms.num_layers,
                hidden_size=ms.hidden_size,
                num_heads=ms.num_heads,
                num_kv_heads=ms.num_kv_heads,
                intermediate_size=ms.intermediate_size,
                max_seq_len=ms.max_seq_len,
            ).items()
            if v is not None
        }
        family = ms.family if ms.family != "auto" else "llama"
        tokenizer = load_tokenizer(None)
        overrides.setdefault("vocab_size", tokenizer.vocab_size + 1)
        overrides.setdefault("max_seq_len", 512)
        cfg = tiny_config(family, **overrides)
        # crc32, not builtin hash(): PYTHONHASHSEED randomizes hash() per
        # process, which would give a resumed eval a different model than the
        # one that produced the already-persisted rows.
        from zlib import crc32

        params = init_params(cfg, jax.random.PRNGKey(crc32(role_seed.encode()) % (2**31)))

    if ms.precision == "int4":
        from edgemesh.ops.int4 import quantize_params_int4

        params = quantize_params_int4(params)
    elif ms.precision in ("int8", "int8_w8a8", "int8_w8a8_pallas"):
        params = quantize_params(params)
        # "int8" = weight-only (w8a16); the suffixed variants run activations
        # in int8 too — XLA dynamic quant or the fused Pallas kernel.
        if ms.precision != "int8":
            cfg = cfg.replace(quant_mode=ms.precision.removeprefix("int8_"))
    elif ms.precision in ("bf16", "fp16", "fp32"):
        dtype = {"bf16": jnp.bfloat16, "fp16": jnp.float16, "fp32": jnp.float32}[ms.precision]
        if cfg.activation_dtype != dtype:
            cfg = cfg.replace(dtype={"bf16": "bfloat16", "fp16": "float16", "fp32": "float32"}[ms.precision])
            params = jax.tree.map(
                lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
                params,
            )
    if mesh is not None:
        params = shard_params(params, cfg, mesh)
    return cfg, params, tokenizer


def build_agent(spec: AgentSpec, mesh=None) -> Agent:
    """Materialize one agent (plus its speculative draft model when
    ``spec.draft`` is set — same materialization path, shared tokenizer)."""
    cfg, params, tokenizer = _materialize(spec.model, spec.role, mesh)
    draft_cfg = draft_params = None
    if spec.draft is not None:
        draft_cfg, draft_params, _ = _materialize(
            spec.draft, spec.role + "/draft", mesh
        )
        if draft_cfg.vocab_size != cfg.vocab_size:
            raise ValueError(
                f"agent {spec.role!r}: draft vocab {draft_cfg.vocab_size} != "
                f"model vocab {cfg.vocab_size}; speculative decoding needs a "
                "shared tokenizer"
            )
    # Custom template wins; "" (unset) resolves by role.
    default_template = REFINER_TEMPLATE if spec.role == REFINER_ROLE else DEFAULT_QA_TEMPLATE
    template = spec.prompt_template or default_template
    return Agent(
        role=spec.role,
        cfg=cfg,
        params=params,
        tokenizer=tokenizer,
        sampling=spec.sampling,
        prompt_template=template,
        mesh=mesh,
        draft_cfg=draft_cfg,
        draft_params=draft_params,
        spec_gamma=spec.spec_gamma,
    )


def build_ensemble(config: EdgeMeshConfig, use_submeshes: bool = True) -> Ensemble:
    """Build all agents from config; QA agents get disjoint submeshes when the
    device count allows (concurrent execution), the refiner gets the full
    device set after the drafts are in."""
    specs = config.agents or [
        AgentSpec(role="qa"),
        AgentSpec(role="qa2"),
        AgentSpec(role=REFINER_ROLE),
    ]
    qa_specs = [s for s in specs if s.role != REFINER_ROLE]
    refiner_spec = next((s for s in specs if s.role == REFINER_ROLE), None)

    meshes: list = [None] * len(qa_specs)
    if use_submeshes and len(qa_specs) > 1:
        try:
            meshes = submeshes(len(qa_specs))
        except ValueError:
            log.warning("not enough devices for %d submeshes; agents share devices", len(qa_specs))
            meshes = [None] * len(qa_specs)

    qa_agents = [build_agent(s, m) for s, m in zip(qa_specs, meshes)]
    refiner = None
    if refiner_spec:
        # The refiner runs AFTER the drafts are in, so it gets the whole
        # device set (tensor-parallel over every chip) rather than a submesh.
        refiner_mesh = None
        if use_submeshes:
            from edgemesh.parallel.mesh import auto_mesh

            refiner_mesh = auto_mesh()
        refiner = build_agent(refiner_spec, refiner_mesh)
    return Ensemble(qa_agents=qa_agents, refiner=refiner)
