"""Multi-agent ensemble: N QA agents + a refiner, concurrent on submeshes.

Capability parity (the reference's core contribution, SURVEY.md §2.3 row 1):
two QA models answer independently and a refiner model merges their answers
(``Code/C-DAC Server/combiner_fp.py:328-377``). Two deliberate departures:

1. **Concurrency.** The reference calls its agents back-to-back on one GPU
   (combiner_fp.py:436 then :439 — sequential, its paper §5.1 Q1 names the
   parallelization as future work). Here each agent owns a DISJOINT submesh
   (edgemesh.parallel.mesh.submeshes) and agents run under a thread pool; JAX
   dispatch is async per-device, so the QA forward passes genuinely overlap.

2. **Roles are data.** phi/pythia/refiner were hardcoded; here any number of
   ``AgentSpec`` rows, with ``role == "refiner"`` marking the merger.

Prompt behavior mirrors the reference's templates (QA prompt:
combiner_fp.py:329-332; refiner prompt injecting the question + both candidate
answers: :356-363) with original wording.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from edgemesh.agents.prompts import (
    DEFAULT_QA_TEMPLATE,
    REFINER_ROLE,
    REFINER_TEMPLATE,
    format_refiner_prompt,
)
from edgemesh.config import AgentSpec, EdgeMeshConfig, ModelSpec, SamplingParams
from edgemesh.models.families import config_for_family, tiny_config
from edgemesh.models.hf_ingest import load_params
from edgemesh.models.tokenizer import load_tokenizer
from edgemesh.models.transformer import ModelConfig, init_params
from edgemesh.ops.int8 import quantize_params
from edgemesh.parallel.mesh import submeshes
from edgemesh.parallel.sharding import shard_params
from edgemesh.runtime import generate

log = logging.getLogger("edgemesh.agents")

# Template strings live in edgemesh.agents.prompts (jax-free, shared with
# the fleet ensemble coordinator); re-exported here for back-compat.
__all__ = [
    "Agent",
    "Ensemble",
    "build_agent",
    "build_ensemble",
    "REFINER_ROLE",
    "DEFAULT_QA_TEMPLATE",
    "REFINER_TEMPLATE",
]


@dataclass
class Agent:
    """One model bound to a role, a (sub)mesh, and sampling params.

    With a ``draft_cfg``/``draft_params`` pair set, generation runs
    speculative decoding (runtime/speculative.py): the draft proposes
    ``spec_gamma`` tokens per round, the main model verifies them in one
    chunk — same output distribution, fewer full-model steps."""

    role: str
    cfg: ModelConfig
    params: Any
    tokenizer: Any
    sampling: SamplingParams
    prompt_template: str = DEFAULT_QA_TEMPLATE
    mesh: Any = None
    draft_cfg: ModelConfig | None = None
    draft_params: Any = None
    spec_gamma: int = 4
    # Reuse the prompt template's KV across requests (runtime/prefix_cache.py):
    # single-request answers chunk-append only the question suffix. Exact —
    # matching is on token ids.
    prefix_cache: bool = True
    _prefix: Any = field(default=None, repr=False)
    _prefix_lock: Any = field(default_factory=threading.Lock, repr=False)
    # Shape signatures this agent has already generated with. The first call
    # at a new (rows, bucket) pays the XLA compile inside its measured
    # prefill window; results from such calls carry ``compiled: True`` so
    # latency consumers (eval/harness.aggregate) can report steady-state
    # serving percentiles separately from compile events.
    _seen_shapes: set = field(default_factory=set, repr=False)

    def format_prompt(self, question: str, **extra) -> str:
        return self.prompt_template.format(question=question, **extra)

    def _max_prompt(self) -> int:
        max_ctx = self.cfg.max_seq_len
        if self.draft_cfg is not None:
            # Both caches hold the full sequence; budget against the smaller
            # context, plus the speculative chunk's overshoot headroom.
            max_ctx = min(max_ctx, self.draft_cfg.max_seq_len) - (self.spec_gamma + 1)
        max_prompt = max_ctx - self.sampling.max_new_tokens
        if max_prompt < 1:
            raise ValueError(
                f"max_new_tokens {self.sampling.max_new_tokens} leaves no room "
                f"for a prompt within max_seq_len {self.cfg.max_seq_len}"
            )
        return max_prompt

    def _prepare_batch(self, prompts: list[str]):
        """Tokenize + bucket a prompt batch: shared prompt-length bucket
        (jit specializes on shapes — raw per-question lengths would compile
        a fresh prefill per unique length, unbounded compile-cache growth
        that OOMs a small host over a 1,000-sample sweep) and power-of-2 row
        count with dummy fill rows. Returns (tokens, lengths, n_real)."""
        max_prompt = self._max_prompt()
        ids_list = [self.tokenizer.encode(p, max_len=max_prompt) for p in prompts]
        longest = max(len(ids) for ids in ids_list)
        bucket = 16
        while bucket < longest and bucket < max_prompt:
            bucket *= 2
        bucket = min(bucket, max_prompt)
        n = len(ids_list)
        rows = 1
        while rows < n:
            rows *= 2
        pad = getattr(self.tokenizer, "pad_id", 0)
        padded = [ids + [pad] * (bucket - len(ids)) for ids in ids_list]
        padded += [padded[-1]] * (rows - n)  # dummy rows fill the batch bucket
        tokens = jnp.asarray(padded, dtype=jnp.int32)
        # lengths stay HOST-side (numpy): every consumer either passes them
        # into a jit call (auto-transferred) or reads them as ints — and a
        # device-resident lengths made serving admission pay one blocking
        # ~0.13s tunnel readback per request just for `int(lengths[0])`.
        lengths = np.asarray(
            [len(ids) for ids in ids_list] + [len(ids_list[-1])] * (rows - n),
            dtype=np.int32,
        )
        return tokens, lengths, n

    def answer(self, question: str, prompt: str | None = None) -> dict[str, Any]:
        prompts = None if prompt is None else [prompt]
        return self.answer_batch([question], prompts=prompts)[0]

    def _template_prefix(self):
        """Lazily-built KV cache of the prompt template's static prefix
        (text before the first placeholder); None when disabled or the
        prefix is too short to pay for the seeding copy."""
        if not self.prefix_cache:
            return None
        if self._prefix is None:
            # The REST server answers concurrently (ThreadingHTTPServer);
            # confine the one-time prefill+compile to a single thread.
            with self._prefix_lock:
                if self._prefix is None and self.prefix_cache:
                    from edgemesh.runtime.prefix_cache import build_prefix_cache

                    static = self.prompt_template.split("{", 1)[0]
                    ids = self.tokenizer.encode(static) if static else []
                    if len(ids) < 8:
                        self.prefix_cache = False
                        return None
                    self._prefix = build_prefix_cache(self.cfg, self.params, ids)
        return self._prefix

    def answer_stream(self, question: str, prompt: str | None = None, chunk: int = 16):
        """Yield ``{"delta": str}`` increments as the answer decodes, then a
        final ``{"answer": full_text, "done": True, ...}`` record. Text
        deltas re-decode the cumulative token prefix each chunk so
        multi-byte/multi-token characters split across a chunk boundary
        never emit garbage halves.

        With a speculative draft configured, streaming rides the segmented
        speculative loop (runtime/speculative.generate_speculative_stream):
        deltas arrive per verify-round segment and keep the draft-model
        acceleration — the two marquee decode features compose. ``chunk``
        maps onto the segment budget (a round emits up to gamma+1 tokens),
        so chunk=1 streams every round and larger chunks batch rounds."""
        from edgemesh.runtime.stream import generate_stream

        prompt = prompt if prompt is not None else self.format_prompt(question)
        tokens, lengths, _ = self._prepare_batch([prompt])
        eos = getattr(self.tokenizer, "eos_id", -1)
        if self.draft_cfg is not None:
            from edgemesh.runtime.speculative import generate_speculative_stream

            segments = generate_speculative_stream(
                self.cfg, self.params, self.draft_cfg, self.draft_params,
                tokens, lengths, self.sampling, gamma=self.spec_gamma,
                eos_id=eos,
                rounds_per_segment=max(1, chunk // (self.spec_gamma + 1)),
            )
        else:
            segments = generate_stream(
                self.cfg, self.params, tokens, lengths, self.sampling,
                eos_id=eos, chunk=chunk,
            )
        all_ids: list[int] = []
        text = ""
        t_start = time.perf_counter()
        for seg in segments:
            n = int(seg.counts[0])
            # Bulk-fetch the segment's tokens: iterating the device array
            # directly costs one tunnel readback PER TOKEN (~0.13s each).
            all_ids.extend(np.asarray(seg.tokens[0][:n]).tolist())
            new_text = self.tokenizer.decode(all_ids)
            # Hold back trailing replacement chars (a multi-byte character
            # split across the chunk boundary decodes as U+FFFD until its
            # remaining bytes arrive) and anything after a prefix mismatch —
            # only stream text that can no longer change.
            stable_end = len(new_text)
            while stable_end > 0 and new_text[stable_end - 1] == "�":
                stable_end -= 1
            stable = new_text[:stable_end]
            # Emit from the common prefix: normally stable extends text and
            # this is the plain suffix. If a re-decode REWROTE earlier output
            # (e.g. tokenizer cleanup joining across the boundary), emit a
            # rewind marker with the corrected tail — aware clients drop the
            # last ``rewind`` chars first; unaware ones show a small
            # artifact and the final ``answer`` stays authoritative.
            cp = 0
            limit = min(len(stable), len(text))
            while cp < limit and stable[cp] == text[cp]:
                cp += 1
            if cp == len(text) or len(stable) > len(text):
                item = {"delta": stable[cp:]}
                if cp < len(text):
                    item["rewind"] = len(text) - cp
                text = stable
                if item["delta"] or "rewind" in item:
                    yield item
        final_text = self.tokenizer.decode(all_ids)
        if final_text.startswith(text) and final_text[len(text):]:
            yield {"delta": final_text[len(text):]}
        wall = time.perf_counter() - t_start
        yield {
            "answer": final_text.strip(),
            "role": self.role,
            "done": True,
            "tps": len(all_ids) / wall if wall > 0 else 0.0,
            "t_start": t_start,
            "t_end": time.perf_counter(),
        }

    def answer_batch(
        self, questions: list[str], prompts: list[str] | None = None
    ) -> list[dict[str, Any]]:
        """Answer several questions in ONE batched generate — the decode
        loop's weight reads amortize over the whole batch (decode is
        HBM-bound, so n questions cost barely more than one). Row count pads
        to a power-of-2 bucket and prompt length to the usual length bucket,
        so jit compiles stay bounded at (log batch x log length) programs."""
        t_start = time.perf_counter()
        prompts = prompts if prompts is not None else [
            self.format_prompt(q) for q in questions
        ]
        tokens, lengths, n = self._prepare_batch(prompts)
        sig = tokens.shape
        first_compile = sig not in self._seen_shapes
        self._seen_shapes.add(sig)
        eos_id = getattr(self.tokenizer, "eos_id", -1)
        if self.draft_cfg is not None:
            from edgemesh.runtime.speculative import generate_speculative

            result, _ = generate_speculative(
                self.cfg, self.params, self.draft_cfg, self.draft_params,
                tokens, lengths, self.sampling, gamma=self.spec_gamma,
                eos_id=eos_id,
            )
        else:
            prefix = self._template_prefix() if n == 1 and tokens.shape[0] == 1 else None
            if prefix is not None:
                from edgemesh.runtime.prefix_cache import generate_with_prefix

                result = generate_with_prefix(
                    self.cfg, self.params, tokens, lengths, self.sampling,
                    prefix, eos_id=eos_id,
                )
            else:
                result = generate(
                    self.cfg, self.params, tokens, lengths, self.sampling,
                    eos_id=eos_id,
                )
        t_end = time.perf_counter()
        wall = max(t_end - t_start, 1e-9)
        out = []
        # One bulk device→host fetch for the whole batch (single pytree call
        # = one blocking round trip); per-row slicing of the device array
        # would cost a tunnel round trip per row (and the tokenizer's
        # per-element guard would still pay one per ROW).
        tokens_h, num_gen_h, conf_h = jax.device_get(
            (result.tokens, result.num_generated, result.confidence)
        )
        for i in range(n):
            n_tok = int(num_gen_h[i])
            text = self.tokenizer.decode(tokens_h[i][:n_tok])
            out.append(
                {
                    "answer": text.strip(),
                    "role": self.role,
                    # THIS row's tokens over the batch wall time — the honest
                    # per-request rate, so batched and sequential eval
                    # reports stay comparable. (batch_tps uses generate()'s
                    # inner wall and counts dummy fill rows; the two are
                    # different bases, not a sum identity.)
                    "tps": n_tok / wall,
                    "batch_tps": result.tokens_per_sec,
                    "batch_size": n,
                    "ttft_s": result.prefill_time_s,
                    # First call at this shape: the measured window includes
                    # the XLA compile — flagged so latency aggregation can
                    # split compile events from steady-state serving.
                    "compiled": first_compile,
                    "confidence": float(conf_h[i]),
                    # Wall-clock span of this agent's work — lets callers
                    # verify ensemble agents actually overlapped (tests /
                    # benchmarks assert interval overlap).
                    "t_start": t_start,
                    "t_end": t_end,
                }
            )
        return out


@dataclass
class Ensemble:
    """QA agents + optional refiner. ``answer`` is the drop-in analog of the
    reference's per-question block (combiner_fp.py:436-442)."""

    qa_agents: list[Agent]
    refiner: Agent | None = None
    _pool: ThreadPoolExecutor | None = field(default=None, repr=False)

    def __post_init__(self):
        self._pool = ThreadPoolExecutor(max_workers=max(1, len(self.qa_agents)))

    def answer(self, question: str) -> dict[str, Any]:
        return self.answer_batch([question])[0]

    def _refiner_prompt(self, question: str, drafts) -> str:
        return format_refiner_prompt(
            question,
            [d["answer"] for d in drafts],
            template=self.refiner.prompt_template,
        )

    def answer_stream(self, question: str, chunk: int = 16):
        """Stream the user-visible final answer, matching ``answer``'s
        selection semantics: with a refiner, QA drafts complete first (they
        feed the refiner's prompt, so they cannot stream) and the refiner's
        generation streams chunk by chunk; with exactly one QA agent it
        streams directly; with several QA agents and no refiner the
        max-confidence draft is only known after all finish, so the result
        arrives as a single ``done`` event."""
        if self.refiner is None:
            if len(self.qa_agents) == 1:
                final = None
                for item in self.qa_agents[0].answer_stream(question, chunk=chunk):
                    if item.get("done"):
                        final = item
                    else:
                        yield item
                yield {**final, "drafts": [final]}
                return
            yield {**self.answer(question), "done": True}
            return
        drafts = self.answer_drafts(question)
        prompt = self._refiner_prompt(question, drafts)
        for item in self.refiner.answer_stream(question, prompt=prompt, chunk=chunk):
            if item.get("done"):
                item = {**item, "drafts": drafts}
            yield item

    def answer_drafts(self, question: str) -> list[dict[str, Any]]:
        futures = [
            self._pool.submit(agent.answer, question) for agent in self.qa_agents
        ]
        return [f.result() for f in futures]

    def answer_batch(self, questions: list[str]) -> list[dict[str, Any]]:
        """The reference's per-question block (combiner_fp.py:436-442) over a
        whole request batch: QA agents run concurrently (disjoint submeshes)
        AND each agent batches all questions into one generate."""
        futures = [
            self._pool.submit(agent.answer_batch, questions)
            for agent in self.qa_agents
        ]
        per_agent = [f.result() for f in futures]  # [n_agents][n_questions]
        by_question = list(zip(*per_agent))

        if self.refiner is None:
            return [
                {**max(drafts, key=lambda d: d["confidence"]), "drafts": list(drafts)}
                for drafts in by_question
            ]

        prompts = [
            self._refiner_prompt(question, drafts)
            for question, drafts in zip(questions, by_question)
        ]
        refined = self.refiner.answer_batch(questions, prompts=prompts)
        out = []
        for drafts, ref in zip(by_question, refined):
            tps_values = [d["tps"] for d in drafts] + [ref["tps"]]
            out.append(
                {
                    "answer": ref["answer"],
                    "confidence": ref["confidence"],
                    "tps": sum(tps_values) / len(tps_values),  # mean-of-models, try.py:317-326
                    "ttft_s": drafts[0]["ttft_s"],
                    "compiled": any(d.get("compiled") for d in drafts)
                    or bool(ref.get("compiled")),
                    "batch_size": ref.get("batch_size", 1),
                    "drafts": list(drafts),
                }
            )
        return out


def _materialize(ms: ModelSpec, role_seed: str, mesh=None) -> tuple[ModelConfig, Any, Any]:
    """(cfg, params, tokenizer) for one ModelSpec: HF checkpoint if ``path``
    is set, otherwise a synthetic random-init model with the byte tokenizer."""
    if ms.path:
        cfg, params = load_params(ms.path)
        tokenizer = load_tokenizer(ms.path)
    else:
        overrides = {
            k: v
            for k, v in dict(
                vocab_size=ms.vocab_size,
                num_layers=ms.num_layers,
                hidden_size=ms.hidden_size,
                num_heads=ms.num_heads,
                num_kv_heads=ms.num_kv_heads,
                intermediate_size=ms.intermediate_size,
                max_seq_len=ms.max_seq_len,
                sliding_window=ms.sliding_window,
                num_experts=ms.num_experts,
                experts_per_token=ms.experts_per_token,
            ).items()
            if v is not None
        }
        family = ms.family if ms.family != "auto" else "llama"
        tokenizer = load_tokenizer(None)
        overrides.setdefault("vocab_size", tokenizer.vocab_size + 1)
        overrides.setdefault("max_seq_len", 512)
        if overrides["vocab_size"] < tokenizer.vocab_size:
            # A model vocab smaller than the tokenizer's id range makes
            # EOS/PAD ids index past the embedding — XLA's clamped gathers
            # turn that into silent garbage (NaN losses in training, junk
            # samples at decode), so refuse loudly instead.
            raise ValueError(
                f"model vocab_size {overrides['vocab_size']} < tokenizer "
                f"vocab {tokenizer.vocab_size} (byte tokenizer ids run to "
                f"{tokenizer.vocab_size - 1}); set vocab_size >= "
                f"{tokenizer.vocab_size} or leave it unset"
            )
        cfg = tiny_config(family, **overrides)
        # crc32, not builtin hash(): PYTHONHASHSEED randomizes hash() per
        # process, which would give a resumed eval a different model than the
        # one that produced the already-persisted rows.
        from zlib import crc32

        params = init_params(cfg, jax.random.PRNGKey(crc32(role_seed.encode()) % (2**31)))

    if ms.lora_base:
        # LoRA-over-a-trained-model: restore a FULL checkpoint as the frozen
        # base FIRST. With lora_rank > 0, ``train_checkpoint`` then stays
        # the ADAPTER tree trained on top of exactly this base — without
        # this field, finetuning a previously trained model was
        # inexpressible (train_checkpoint can only mean one of the two).
        from edgemesh.runtime.checkpoint import TrainCheckpointManager
        from edgemesh.training import init_train_state, make_optimizer

        if ms.lora_rank <= 0 and ms.train_checkpoint:
            raise ValueError(
                "lora_base with lora_rank == 0 AND train_checkpoint is "
                "ambiguous (two full checkpoints); point train_checkpoint "
                "at the adapter run and set lora_rank, or drop lora_base"
            )
        mgr = TrainCheckpointManager(ms.lora_base)
        restored = mgr.restore_latest(
            init_train_state(cfg, params, make_optimizer())
        )
        mgr.close()
        if restored is None:
            raise ValueError(
                f"no full checkpoint found under lora_base={ms.lora_base!r} "
                "(expected an `edgemesh train` run with lora_rank 0)"
            )
        params = restored[0].params
        log.info("%s: restored lora_base weights from %s (step %d)",
                 role_seed, ms.lora_base, restored[1])

    if ms.train_checkpoint:
        # Swap in finetuned weights from an `edgemesh train` run BEFORE any
        # precision transform below, so int8/int4 rows quantize the TRAINED
        # weights. The synthetic/HF init above is the restore template —
        # architecture fields must match the training run's spec.
        from edgemesh.runtime.checkpoint import TrainCheckpointManager
        from edgemesh.training import init_train_state, make_optimizer

        mgr = TrainCheckpointManager(ms.train_checkpoint)
        if ms.lora_rank > 0:
            # LoRA checkpoints hold only the adapter tree; rebuild its
            # structure from the spec (rank/alpha/targets must match the
            # training run), restore, and MERGE into the base kernels so
            # inference — and the precision transform below — see the
            # finetuned weights at zero serving cost (ops/lora.py).
            from edgemesh.ops.lora import (
                init_lora_params,
                make_lora_optimizer,
                merge_lora,
            )

            template = init_train_state(
                cfg,
                init_lora_params(params, ms.lora_rank, ms.lora_alpha, ms.lora_targets),
                make_lora_optimizer(),
            )
        else:
            template = init_train_state(cfg, params, make_optimizer())
        restored = mgr.restore_latest(template)
        mgr.close()
        if restored is None:
            raise ValueError(
                f"no training checkpoint found under {ms.train_checkpoint!r} "
                "(run `edgemesh train` with train.checkpoint_dir first)"
            )
        if ms.lora_rank > 0:
            params = merge_lora(params, restored[0].params)
        else:
            params = restored[0].params
        log.info("%s: restored trained params from %s (step %d)",
                 role_seed, ms.train_checkpoint, restored[1])

    if ms.precision == "int4":
        from edgemesh.ops.int4 import quantize_params_int4

        params = quantize_params_int4(params, group_size=ms.int4_group_size)
    elif ms.precision in ("int8", "int8_w8a8", "int8_w8a8_pallas",
                          "int8_w8a8_pallas_pre", "int8_w8a8_auto"):
        if ms.calibration:
            if ms.precision == "int8":
                # Weight-only (w8a16) keeps activations in fp: smoothing has
                # no activation quantization to help and the W*s inflation
                # coarsens the WEIGHT quantization — strictly worse. Refuse
                # rather than silently degrade.
                raise ValueError(
                    "calibration (SmoothQuant) only benefits the w8a8 "
                    "precisions; use precision: int8_w8a8, int8_w8a8_pallas, "
                    "int8_w8a8_pallas_pre, or int8_w8a8_auto"
                )
            from edgemesh.models.tokenizer import encode_batch
            from edgemesh.ops.smoothquant import calibrate_and_quantize

            with open(ms.calibration) as f:
                prompts = [line.strip() for line in f if line.strip()]
            if not prompts:
                raise ValueError(f"calibration file {ms.calibration!r} has no prompts")
            ctoks, clens = encode_batch(tokenizer, prompts, max_len=cfg.max_seq_len)
            params = calibrate_and_quantize(cfg, params, ctoks, clens)
        else:
            params = quantize_params(params)
        # "int8" = weight-only (w8a16); the suffixed variants run activations
        # in int8 too — XLA dynamic quant, the fused Pallas kernel, or
        # "_auto": measure both on this model's shapes and take the winner
        # (ops/int8.measure_w8a8_mode; off-TPU resolves to the XLA path).
        if ms.precision == "int8_w8a8_auto":
            from edgemesh.ops.int8 import measure_w8a8_mode

            mode = measure_w8a8_mode(params)
            # Prefill compiles separately, so it gets its own measured
            # winner at prefill-like shapes (M = 8 x 512 rows) — the fused
            # Pallas kernel's big-tile regime (docs/PERFORMANCE.md ADR).
            pmode = measure_w8a8_mode(params, seq=512)
            log.info("%s: w8a8 auto-pick -> decode %s / prefill %s",
                     role_seed, mode, pmode)
            cfg = cfg.replace(quant_mode=mode, prefill_quant_mode=pmode)
        elif ms.precision != "int8":
            cfg = cfg.replace(quant_mode=ms.precision.removeprefix("int8_"))
    elif ms.precision in ("bf16", "fp16", "fp32"):
        dtype = {"bf16": jnp.bfloat16, "fp16": jnp.float16, "fp32": jnp.float32}[ms.precision]
        if cfg.activation_dtype != dtype:
            cfg = cfg.replace(dtype={"bf16": "bfloat16", "fp16": "float16", "fp32": "float32"}[ms.precision])
            params = jax.tree.map(
                lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
                params,
            )
    if ms.quantize_embed and ms.precision.startswith("int"):
        from edgemesh.ops.int8 import quantize_embedding

        params = quantize_embedding(params)
    noise = float(os.environ.get("EDGEMESH_QUALITY_NOISE", "0") or "0")
    if noise > 0.0:
        # Fault injection for the quality observatory's e2e
        # (tests/test_quality_e2e.py): gaussian noise on the output head
        # makes answers garbage while latency, /readyz, and memory
        # behavior stay normal — the degraded-but-healthy replica the
        # canary prober and drift detector exist to catch. Gated on an
        # env var so only a process launched with it set is degraded.
        target = "lm_head" if "lm_head" in params else "embed"
        key = jax.random.PRNGKey(0)
        params = {
            **params,
            target: jax.tree.map(
                lambda x: (
                    x + (noise * jax.random.normal(
                        key, x.shape, jnp.float32)).astype(x.dtype)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x
                ),
                params[target],
            ),
        }
        log.warning("%s: EDGEMESH_QUALITY_NOISE=%g — %s perturbed "
                    "(answers will be garbage by design)",
                    role_seed, noise, target)
    if mesh is not None:
        params = shard_params(params, cfg, mesh)
    return cfg, params, tokenizer


def build_agent(spec: AgentSpec, mesh=None) -> Agent:
    """Materialize one agent (plus its speculative draft model when
    ``spec.draft`` is set — same materialization path, shared tokenizer)."""
    cfg, params, tokenizer = _materialize(spec.model, spec.role, mesh)
    draft_cfg = draft_params = None
    if spec.draft is not None:
        draft_cfg, draft_params, _ = _materialize(
            spec.draft, spec.role + "/draft", mesh
        )
        if draft_cfg.vocab_size != cfg.vocab_size:
            raise ValueError(
                f"agent {spec.role!r}: draft vocab {draft_cfg.vocab_size} != "
                f"model vocab {cfg.vocab_size}; speculative decoding needs a "
                "shared tokenizer"
            )
    # Custom template wins; "" (unset) resolves by role.
    default_template = REFINER_TEMPLATE if spec.role == REFINER_ROLE else DEFAULT_QA_TEMPLATE
    template = spec.prompt_template or default_template
    return Agent(
        role=spec.role,
        cfg=cfg,
        params=params,
        tokenizer=tokenizer,
        sampling=spec.sampling,
        prompt_template=template,
        mesh=mesh,
        draft_cfg=draft_cfg,
        draft_params=draft_params,
        spec_gamma=spec.spec_gamma,
    )


def build_ensemble(config: EdgeMeshConfig, use_submeshes: bool = True) -> Ensemble:
    """Build all agents from config; QA agents get disjoint submeshes when the
    device count allows (concurrent execution), the refiner gets the full
    device set after the drafts are in."""
    specs = config.agents or [
        AgentSpec(role="qa"),
        AgentSpec(role="qa2"),
        AgentSpec(role=REFINER_ROLE),
    ]
    qa_specs = [s for s in specs if s.role != REFINER_ROLE]
    refiner_spec = next((s for s in specs if s.role == REFINER_ROLE), None)

    meshes: list = [None] * len(qa_specs)
    if use_submeshes and len(qa_specs) > 1:
        try:
            meshes = submeshes(len(qa_specs))
        except ValueError:
            log.warning("not enough devices for %d submeshes; agents share devices", len(qa_specs))
            meshes = [None] * len(qa_specs)

    qa_agents = [build_agent(s, m) for s, m in zip(qa_specs, meshes)]
    refiner = None
    if refiner_spec:
        # The refiner runs AFTER the drafts are in, so it gets the whole
        # device set (tensor-parallel over every chip) rather than a submesh.
        refiner_mesh = None
        if use_submeshes:
            from edgemesh.parallel.mesh import auto_mesh

            refiner_mesh = auto_mesh()
        refiner = build_agent(refiner_spec, refiner_mesh)
    return Ensemble(qa_agents=qa_agents, refiner=refiner)
