"""Prompt-prefix KV reuse: skip re-prefilling the shared template prefix.

Every QA request in the reference re-runs the full prompt through the model
(HF ``generate`` per question, ``combiner_fp.py:328-352``), yet the prompt
template's prefix — everything before the question text — is identical
across requests. Here the prefix's KV state is computed once and each
request chunk-appends only its suffix (``transformer.forward_verify``, the
same one-forward append the speculative decoder uses), cutting TTFT by the
prefix share of the prompt.

Exactness: matching is on TOKEN ids (longest common prefix between the
request's tokens and the cached prefix tokens), so byte-level BPE merges
across the template/question boundary simply shorten the match — the reused
KV always corresponds to the request's own tokens, and in fp32 the warm
path's greedy output is bit-identical to the cold path (pinned in tests).
In bf16 the chunked append reorders reductions relative to the one-shot
prefill (exactly like chunked prefill in any serving stack), so greedy
tokens can occasionally flip where top-1/top-2 logits are within rounding —
semantically equivalent, not bit-equal. Suffixes pad to power-of-two
buckets to bound jit specializations; padded slots either sit beyond every
real query's causal horizon during the append or are overwritten by the
first decode steps, and ``kv_valid`` masks them meanwhile (same argument as
the speculative rewind protocol).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from edgemesh.config import SamplingParams
from edgemesh.models.transformer import (
    KVCache,
    ModelConfig,
    forward_prefill,
    forward_verify,
    init_kv_cache,
)
from edgemesh.runtime.generate import GenerateResult, generate


class PrefixCache(NamedTuple):
    """Cached KV for one token prefix (batch 1, capacity = prefix length)."""

    tokens: np.ndarray  # [L] int32 — the exact prefix token ids
    k: jnp.ndarray  # [num_layers, 1, L, kv_heads, head_dim]
    v: jnp.ndarray

    @property
    def length(self) -> int:
        return int(self.tokens.shape[0])


def build_prefix_cache(cfg: ModelConfig, params, prefix_tokens) -> PrefixCache:
    """One-time prefill of the shared prefix. ``prefix_tokens``: 1-D ids."""
    ids = np.asarray(prefix_tokens, np.int32).reshape(-1)
    L = int(ids.shape[0])
    if L < 1:
        raise ValueError("prefix must contain at least one token")
    cache = init_kv_cache(cfg, 1, L)
    _, cache = forward_prefill(
        cfg, params, jnp.asarray(ids)[None, :], jnp.asarray([L], jnp.int32), cache
    )
    return PrefixCache(tokens=ids, k=cache.k, v=cache.v)


def common_token_prefix(prefix_ids, tokens) -> int:
    """Longest common TOKEN prefix between ``prefix_ids`` and one prompt row,
    capped so at least one suffix token remains to prefill (chunk appends
    need a chunk; generate needs last-token logits). Shared by the dense
    warm path below and the paged serving engine's template sharing
    (serve/continuous.py)."""
    ids = np.asarray(prefix_ids, np.int32).reshape(-1)
    row = np.asarray(tokens, np.int32).reshape(-1)
    limit = min(ids.shape[0], row.shape[0] - 1)
    if limit <= 0:
        return 0
    neq = np.nonzero(row[:limit] != ids[:limit])[0]
    return int(neq[0]) if neq.size else int(limit)


def match_length(prefix: PrefixCache, tokens) -> int:
    """Longest common TOKEN prefix between the cache and one prompt row."""
    return common_token_prefix(prefix.tokens, tokens)


def _bucket(n: int) -> int:
    b = 8
    while b < n:
        b *= 2
    return b


def generate_with_prefix(
    cfg: ModelConfig,
    params,
    tokens: jax.Array,  # [1, s] right-padded prompt (single request)
    lengths: jax.Array,  # [1]
    sampling: SamplingParams,
    prefix: PrefixCache,
    eos_id: int = -1,
    rng: jax.Array | None = None,
    min_match: int = 8,
) -> GenerateResult:
    """generate() that warm-starts from the cached prefix KV.

    Single-request path (batch 1 — the Agent.answer shape); falls back to the
    plain prefill when the prompt shares fewer than ``min_match`` tokens with
    the cached prefix. Greedy output is token-identical to the cold path."""
    if tokens.shape[0] != 1:
        raise ValueError("generate_with_prefix is a single-request (batch 1) path")
    true_len = int(lengths[0])
    L = match_length(prefix, np.asarray(tokens[0, :true_len]))
    if L < min_match:
        return generate(cfg, params, tokens, lengths, sampling, eos_id=eos_id, rng=rng)

    suffix_len = true_len - L
    pad_len = _bucket(suffix_len)
    needed = true_len + int(sampling.max_new_tokens)
    # generate() validates capacity against the PADDED prompt width
    # (tokens.shape[1] may exceed true_len under the caller's length
    # bucketing), so cover whichever is larger.
    capacity = max(L + pad_len, int(tokens.shape[1])) + int(sampling.max_new_tokens)

    # Seed a right-sized cache with the prefix rows.
    cache = init_kv_cache(cfg, 1, capacity)
    cache = KVCache(
        k=cache.k.at[:, :, :L].set(prefix.k[:, :, :L]),
        v=cache.v.at[:, :, :L].set(prefix.v[:, :, :L]),
        lengths=jnp.asarray([L], jnp.int32),
    )
    suffix = jnp.zeros((1, pad_len), jnp.int32)
    suffix = jax.lax.dynamic_update_slice(suffix, tokens[:, L:true_len], (0, 0))

    def prefill_fn(cfg, params, _tokens, _lengths, cache):
        # Chunk-append the suffix at the prefix boundary; logits at the last
        # REAL suffix position seed the decode loop. Padded slots beyond it
        # are invisible (causality) and the decode loop overwrites them.
        logits_all, cache = forward_verify(cfg, params, suffix, cache)
        last = logits_all[jnp.arange(1), suffix_len - 1]
        return last, KVCache(cache.k, cache.v, jnp.asarray([true_len], jnp.int32))

    def check_cache(cache, needed_tokens):
        if cache.k.shape[2] < needed_tokens:
            raise ValueError(
                f"prefix-seeded cache capacity {cache.k.shape[2]} < {needed_tokens}"
            )

    if needed > cfg.max_seq_len:
        raise ValueError(
            f"prompt {true_len} + max_new {sampling.max_new_tokens} exceeds "
            f"max_seq_len {cfg.max_seq_len}"
        )
    return generate(
        cfg, params, tokens, lengths, sampling, eos_id=eos_id, rng=rng,
        cache=cache, prefill_fn=prefill_fn, check_cache=check_cache,
    )
