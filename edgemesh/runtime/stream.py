"""Streaming generation: tokens surface in chunks while decode continues.

The reference returns answers only when ``model.generate`` completes
(``Code/C-DAC Server/combiner_fp.py:338-347``) — a 100-token answer keeps
the user staring for its full decode. The jitted whole-loop decode
(runtime/generate.py) is the fastest batch path but equally all-or-nothing,
so streaming runs the SAME compiled loop in segments: each segment decodes
``chunk`` tokens in one device program, yields them, and a single bridging
``forward_decode`` of the segment's last token restarts the next segment
exactly where the previous stopped (greedy streaming is token-for-token
identical to the non-streamed path — pinned by tests). Host round-trips are
one per ``chunk`` tokens, not one per token.
"""

from __future__ import annotations

from collections.abc import Iterator
from typing import NamedTuple

import jax
import jax.numpy as jnp

from edgemesh.config import SamplingParams
from edgemesh.models.transformer import (
    ModelConfig,
    forward_decode,
    forward_prefill,
    init_kv_cache,
)
from edgemesh.ops.sampling import TokenMaskState
from edgemesh.runtime.generate import _decode_loop


class StreamChunk(NamedTuple):
    tokens: jax.Array  # [b, m] — this segment's output slots (eos-padded)
    counts: jax.Array  # [b] tokens actually emitted this segment
    finished: jax.Array  # [b] rows done (EOS) after this segment
    elapsed_s: float  # wall time since generate_stream was called


def generate_stream(
    cfg: ModelConfig,
    params,
    tokens: jax.Array,  # [b, s] right-padded prompts
    lengths: jax.Array,  # [b]
    sampling: SamplingParams,
    eos_id: int = -1,
    rng: jax.Array | None = None,
    chunk: int = 16,
    meter=None,
) -> Iterator[StreamChunk]:
    """Yield decode output every ``chunk`` tokens. Totals across chunks match
    ``generate``'s budget/EOS semantics; greedy output matches it exactly.

    ``meter`` is an :class:`edgemesh.obs.StreamMeter` (one fresh instance
    per stream; default: process-default registry) — each yielded chunk
    feeds the TTFT/TPOT histograms under ``engine="stream"`` and a normal
    completion records the SLO verdict, so raw streaming callers report
    serving quality through the same families the engines do."""
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    batch, prompt_len = tokens.shape
    max_new = int(sampling.max_new_tokens)
    needed = prompt_len + max_new
    if needed > cfg.max_seq_len:
        raise ValueError(
            f"prompt {prompt_len} + max_new {max_new} exceeds max_seq_len {cfg.max_seq_len}"
        )
    rng = rng if rng is not None else jax.random.PRNGKey(sampling.seed)

    from edgemesh.utils.platform import device_sync
    from edgemesh.utils.tracing import Stopwatch

    if meter is None:
        from edgemesh.obs import StreamMeter

        meter = StreamMeter()
    # EM107: the elapsed window flows through the obs substrate's stopwatch
    # instead of raw perf_counter reads in the serving stack.
    wall = Stopwatch()
    cache = init_kv_cache(cfg, batch, needed)
    first_logits, cache = forward_prefill(cfg, params, tokens, lengths, cache)
    valid = jnp.arange(prompt_len)[None, :] < lengths[:, None]
    token_mask = (
        TokenMaskState.init(batch, cfg.vocab_size).add_sequence(tokens, valid).mask
    )

    finished = jnp.zeros((batch,), bool)
    remaining = max_new
    while remaining > 0:
        m = min(chunk, remaining)
        rng, seg_rng = jax.random.split(rng)
        out, counts, cache, _, token_mask, prev, finished = _decode_loop(
            cfg, params, sampling, m, int(eos_id), first_logits, cache,
            token_mask, seg_rng, None, finished,
        )
        device_sync(out)
        elapsed = wall.elapsed()
        meter.chunk(elapsed, int(jnp.sum(counts)))
        yield StreamChunk(
            tokens=out, counts=counts, finished=finished,
            elapsed_s=elapsed,
        )
        remaining -= m
        if remaining <= 0 or bool(jnp.all(finished)):
            meter.finish("ok")
            return
        # Bridge: the segment's last sampled token never had its forward run
        # (the loop stops before a wasted trailing step); run it now so the
        # next segment's slot 0 samples from the correct logits.
        first_logits, cache = forward_decode(cfg, params, prev, cache)
