"""Autoregressive generation: one jitted prefill + one jitted decode loop.

The reference's hot loop re-enters Python for every sample and every token
(HF ``model.generate`` per question, ``Code/C-DAC Server/combiner_fp.py:338-347``).
Here the entire token loop is a ``lax.while_loop`` compiled once per
(model config, sampling config, shapes) triple: the host submits two XLA
programs per batch — prefill, then the whole decode loop — and only reads back
the finished token buffer. Early exit when every row has emitted EOS.

Timing: prefill wall time is TTFT (the BASELINE.json latency metric); decode
wall time / generated tokens is tokens-per-sec, counted over GENERATED tokens
only — the combiner-runner convention (combiner_fp.py:349), not the
prompt-inclusive variant of the single-model runners
(``Code/Base Models/Llama_bf16_updated.py:89-90``, a known reference
inconsistency recorded in BASELINE.md).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from edgemesh.config import SamplingParams
from edgemesh.models.transformer import (
    KVCache,
    ModelConfig,
    forward_decode,
    forward_prefill,
    init_kv_cache,
)
from edgemesh.ops.sampling import TokenMaskState, sample_token


class GenerateResult(NamedTuple):
    tokens: jax.Array  # [b, max_new_tokens] int32; padded with pad_id after EOS
    num_generated: jax.Array  # [b] int32 (includes the EOS token if emitted)
    prefill_time_s: float
    decode_time_s: float
    # Reference convention (combiner_fp.py:349): generated tokens over the
    # FULL generate() wall time (prefill + decode). Used by the eval harness.
    tokens_per_sec: float
    # Pure decode throughput: tokens produced BY decode forwards over decode
    # time. The first token per row comes from prefill logits, so the decode
    # window runs (total - batch) forwards; dividing total tokens by it would
    # overcount. Used by bench.py.
    decode_tok_s: float = 0.0
    confidence: jax.Array = None  # [b] mean per-token max softmax prob
    # (the reference's confidence_score metric, combiner_fp.py:318-325 — there
    # it needs a SECOND forward pass over the generated text; here it falls out
    # of the decode loop for free)


class _LoopState(NamedTuple):
    step: jax.Array  # index of the NEXT output slot to fill
    prev_token: jax.Array  # [b] — last sampled token (input to next forward)
    cache: KVCache
    rng: jax.Array
    out: jax.Array  # [b, max_new]
    finished: jax.Array  # [b] bool
    num_generated: jax.Array  # [b]
    token_mask: jax.Array  # [b, vocab] repetition-penalty presence mask
    conf_sum: jax.Array  # [b] running sum of per-step max softmax prob
    conf_min: jax.Array  # [b] running min of per-step max softmax prob
    ent_sum: jax.Array  # [b] running sum of per-step token entropy (nats)


@partial(jax.jit, static_argnums=(0, 2, 3, 4, 9), donate_argnums=(6, 7))
def _decode_loop(
    cfg: ModelConfig,
    params,
    sampling: SamplingParams,
    max_new: int,
    eos_id: int,
    first_logits: jax.Array,
    cache,  # any cache pytree understood by decode_fn
    token_mask: jax.Array,
    rng: jax.Array,
    decode_fn=None,  # static: (cfg, params, tokens[b], cache) -> (logits, cache)
    finished0: jax.Array | None = None,  # [b] rows already done (streaming)
) -> tuple[jax.Array, jax.Array, KVCache, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Carries the last TOKEN (not logits): the model forward for output slot
    ``i`` runs at the top of iteration ``i``, so when the loop exits (EOS
    everywhere or budget reached) no trailing forward is wasted — the naive
    sample-then-forward ordering burns one full transformer step per call.

    Returns (out, num_generated, cache, quality, token_mask, prev_token,
    finished) — the trailing three let ``generate_stream`` continue decoding
    in a later segment exactly where this one stopped. ``quality`` is the
    [b, 3] per-row quality accumulator (sum of max-softmax confidence, min
    max-softmax confidence, sum of token entropy in nats) over the tokens
    THIS call generated — raw sums/min, not means, so segment callers (the
    continuous engine) can fold segments together host-side and one-shot
    callers (``generate``) divide by ``num_generated`` once.

    ``cache`` and ``token_mask`` are DONATED: the loop-carry copy at entry
    (the whole multi-GB cache, once per serving segment) reuses the input
    buffers instead. Callers must treat the passed-in arrays as dead and
    use the returned ones — every current caller already reassigns; the
    continuous engine additionally re-inits both on a failed segment."""
    batch, vocab = first_logits.shape
    decode_fn = decode_fn or forward_decode

    def sample_and_record(logits, step_rng, s_out, idx, finished,
                          num_generated, token_mask, conf_sum, conf_min,
                          ent_sum):
        token = sample_token(step_rng, logits, sampling, token_mask)
        token = jnp.where(finished, eos_id, token).astype(jnp.int32)
        s_out = s_out.at[:, idx].set(jnp.where(finished, s_out[:, idx], token))
        # One softmax feeds both quality signals — a [b, vocab] elementwise
        # tail riding the forward's output, never a separate launch.
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        step_conf = jnp.max(probs, axis=-1)
        step_ent = -jnp.sum(probs * jnp.log(probs + 1e-9), axis=-1)
        conf_sum = conf_sum + jnp.where(finished, 0.0, step_conf)
        conf_min = jnp.where(finished, conf_min,
                             jnp.minimum(conf_min, step_conf))
        ent_sum = ent_sum + jnp.where(finished, 0.0, step_ent)
        num_generated = num_generated + jnp.where(finished, 0, 1)
        finished = finished | (token == eos_id)
        token_mask = TokenMaskState(token_mask).add(token).mask
        return (token, s_out, finished, num_generated, token_mask, conf_sum,
                conf_min, ent_sum)

    # Slot 0 comes straight from the prefill logits — no decode forward yet.
    rng, step_rng = jax.random.split(rng)
    out = jnp.full((batch, max_new), eos_id, jnp.int32)
    finished_init = (
        jnp.zeros((batch,), bool) if finished0 is None else finished0
    )
    (token0, out, finished, num_generated, token_mask, conf_sum, conf_min,
     ent_sum) = sample_and_record(
        first_logits, step_rng, out, 0,
        finished_init, jnp.zeros((batch,), jnp.int32),
        token_mask, jnp.zeros((batch,), jnp.float32),
        jnp.ones((batch,), jnp.float32), jnp.zeros((batch,), jnp.float32),
    )

    def cond(s: _LoopState):
        return (s.step < max_new) & ~jnp.all(s.finished)

    def body(s: _LoopState):
        logits, cache = decode_fn(cfg, params, s.prev_token, s.cache)
        # Freeze finished rows' lengths: their forward still runs (static
        # shapes) but the garbage write stays AT the frozen position instead
        # of marching on. Keeps finished rows' cache state exact, and — the
        # serving engine's whole page-accounting story — idle pool rows
        # never cross page boundaries, so they never allocate pages
        # (serve/continuous.py keeps idle rows parked at length 1).
        cache = cache._replace(
            lengths=jnp.where(s.finished, s.cache.lengths, cache.lengths)
        )
        rng, step_rng = jax.random.split(s.rng)
        (token, out, finished, num_generated, token_mask, conf_sum, conf_min,
         ent_sum) = sample_and_record(
            logits, step_rng, s.out, s.step, s.finished, s.num_generated,
            s.token_mask, s.conf_sum, s.conf_min, s.ent_sum,
        )
        return _LoopState(
            s.step + 1, token, cache, rng, out, finished, num_generated,
            token_mask, conf_sum, conf_min, ent_sum,
        )

    init = _LoopState(
        step=jnp.array(1, jnp.int32),
        prev_token=token0,
        cache=cache,
        rng=rng,
        out=out,
        finished=finished,
        num_generated=num_generated,
        token_mask=token_mask,
        conf_sum=conf_sum,
        conf_min=conf_min,
        ent_sum=ent_sum,
    )
    final = jax.lax.while_loop(cond, body, init)
    quality = jnp.stack(
        [final.conf_sum, final.conf_min, final.ent_sum], axis=-1)
    return (
        final.out, final.num_generated, final.cache, quality,
        final.token_mask, final.prev_token, final.finished,
    )


def generate(
    cfg: ModelConfig,
    params,
    tokens: jax.Array,  # [b, s] right-padded prompts
    lengths: jax.Array,  # [b] true prompt lengths
    sampling: SamplingParams,
    eos_id: int = -1,  # -1 → never matches: generate exactly max_new_tokens
    rng: jax.Array | None = None,
    cache: KVCache | None = None,
    prefill_fn=None,  # (cfg, params, tokens, lengths, cache) -> (logits, cache)
    decode_fn=None,  # (cfg, params, token[b], cache) -> (logits, cache)
    make_cache=None,  # (cfg, batch, needed_tokens) -> cache
    check_cache=None,  # (cache, needed_tokens) -> None, raises on undercapacity
) -> GenerateResult:
    """Generate up to ``sampling.max_new_tokens`` per row.

    Device work is two compiled programs (prefill; whole decode loop). All
    sampling knobs (temperature/top_k/top_p/repetition_penalty — the reference's
    full set, config_2.yaml:11-14) execute on device.

    The four ``*_fn`` hooks default to the dense-cache forwards; alternate KV
    backends (the paged cache, runtime/paged_generate.py) pass their own and
    inherit this function's validation, timing, and throughput conventions
    unchanged.

    Note: the returned cache holds K/V for the prompt and all generated tokens
    EXCEPT the final one (its forward pass never runs — it would be wasted
    compute unless generation continues from it).
    """
    prefill_fn = prefill_fn or forward_prefill
    batch, prompt_len = tokens.shape
    max_new = int(sampling.max_new_tokens)
    if max_new < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new}")
    needed = prompt_len + max_new
    if needed > cfg.max_seq_len:
        raise ValueError(
            f"prompt {prompt_len} + max_new {max_new} exceeds max_seq_len {cfg.max_seq_len}"
        )
    if cache is None:
        cache = (make_cache or (lambda c, b, n: init_kv_cache(c, b, n)))(cfg, batch, needed)
    elif check_cache is not None:
        check_cache(cache, needed)
    elif cache.k.shape[2] < needed:
        # Out-of-capacity scatter writes would be silently DROPPED under jit
        # (XLA out-of-bounds scatter semantics) — fail loudly instead.
        raise ValueError(
            f"KV cache capacity {cache.k.shape[2]} < prompt {prompt_len} + max_new {max_new}"
        )
    rng = rng if rng is not None else jax.random.PRNGKey(sampling.seed)

    from edgemesh.utils.platform import device_sync
    from edgemesh.utils.tracing import Stopwatch, trace

    # Per-phase int8 path: prefill is its own compiled program, so it may
    # run a different quant_mode than decode (ModelConfig.prefill_quant_mode
    # — e.g. the fused Pallas w8a8 kernel at prefill's MXU-bound tiles, XLA
    # dynamic quant at decode's bandwidth-bound ones).
    pcfg = (
        cfg.replace(quant_mode=cfg.prefill_quant_mode)
        if cfg.prefill_quant_mode and cfg.prefill_quant_mode != cfg.quant_mode
        else cfg
    )
    # Timing goes through the obs substrate (EM107): the trace() handles
    # carry each phase's wall time — the same numbers that land in the
    # edgemesh_phase_seconds histogram — and the stopwatch owns the
    # end-to-end window.
    # The compute observatory (obs/compute.py): when a caller installed an
    # ambient ledger (ledger_scope — the benches do), both launches run
    # through it with measure=True: this path fences each phase anyway, so
    # the ledger's cost capture + attribution ride the sync already paid.
    from edgemesh.obs.compute import ambient_ledger

    led = ambient_ledger()
    wall = Stopwatch()
    with trace("edgemesh/prefill") as prefill_t:
        if led is not None:
            first_logits, cache = led.launch(
                "prefill", prefill_fn, pcfg, params, tokens, lengths, cache,
                key=f"b{batch}p{prompt_len}", tokens=batch * prompt_len,
                measure=True,
            )
        else:
            first_logits, cache = prefill_fn(pcfg, params, tokens, lengths, cache)
        # NOT block_until_ready: on the tunneled TPU platform that returns
        # before the program finishes, silently shrinking the timed window
        # (utils/platform.device_sync). A 1-element readback is a real fence.
        device_sync(first_logits)

    valid = jnp.arange(prompt_len)[None, :] < lengths[:, None]
    token_mask = (
        TokenMaskState.init(batch, cfg.vocab_size).add_sequence(tokens, valid).mask
    )
    with trace("edgemesh/decode") as decode_t:
        if led is not None:
            out, num_generated, cache, quality, _, _, _ = led.launch(
                "decode_loop", _decode_loop,
                cfg, params, sampling, max_new, int(eos_id), first_logits,
                cache, token_mask, rng, decode_fn,
                key=f"b{batch}c{max_new}", tokens=batch * max_new,
                measure=True,
            )
        else:
            out, num_generated, cache, quality, _, _, _ = _decode_loop(
                cfg, params, sampling, max_new, int(eos_id), first_logits,
                cache, token_mask, rng, decode_fn,
            )
        # The quality slot ships raw per-row sums; the public result keeps
        # the reference's confidence convention (mean max softmax).
        confidence = quality[:, 0] / jnp.maximum(num_generated, 1)
        device_sync(out)
    # Snapshot the window HERE — the jnp.sum readback below is bookkeeping,
    # not generation, and must not deflate tokens_per_sec.
    wall_s = wall.elapsed()

    total_generated = int(jnp.sum(num_generated))
    decode_s = decode_t.elapsed_s
    decode_forward_tokens = max(total_generated - batch, 0)
    return GenerateResult(
        tokens=out,
        num_generated=num_generated,
        prefill_time_s=prefill_t.elapsed_s,
        decode_time_s=decode_s,
        tokens_per_sec=total_generated / wall_s if wall_s > 0 else 0.0,
        decode_tok_s=decode_forward_tokens / decode_s if decode_s > 0 else 0.0,
        confidence=confidence,
    )
