"""Int8-quantized dense KV cache: half the KV bytes per decode step.

Long-context decode reads the whole cache every step, so KV bytes become the
bandwidth floor once contexts outgrow the weight set (SURVEY.md §5.7's
long-context mandate; the reference's HeadInfer paper attacks the same
problem by offloading heads). Here K/V rows quantize to int8 on write with
one fp32 scale per (position, kv-head) — absmax over head_dim, the axis
read back as a contiguous vector — and dequantize inside the attention
einsum's operand read (the same fuse-into-the-matmul trick as the w8a16
weight path, ops/int8.py). Accuracy: per-row symmetric int8 on K/V is the
standard serving configuration (~0.4% relative error per element); the
parity test pins generated tokens against the bf16 cache on a tiny model.

Same two-program structure as runtime/generate.py, cache threaded through
``models/transformer._layer_fn``'s pluggable attention hook exactly like the
paged backend (runtime/paged_generate.py).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from edgemesh.config import SamplingParams
from edgemesh.models.transformer import (
    ModelConfig,
    _layer_fn,
    _mlp,
    dense,
    embed_tokens,
    layer_scan_alt_windows,
    lm_head_logits,
    qkv_proj,
)
from edgemesh.ops.attention import LayerKV, attend
from edgemesh.runtime.generate import GenerateResult, generate

INT8_MAX = 127.0


class QuantKVCache(NamedTuple):
    """Whole-model int8 cache: k/v are int8 [L, b, max_seq, kh, hd];
    k_scale/v_scale fp32 [L, b, max_seq, kh]; lengths [b]."""

    k: jnp.ndarray
    v: jnp.ndarray
    k_scale: jnp.ndarray
    v_scale: jnp.ndarray
    lengths: jnp.ndarray


def init_quant_kv_cache(cfg: ModelConfig, batch: int, max_seq: int | None = None) -> QuantKVCache:
    max_seq = max_seq or cfg.max_seq_len
    shape = (cfg.num_layers, batch, max_seq, cfg.num_kv_heads, cfg.head_size)
    return QuantKVCache(
        k=jnp.zeros(shape, jnp.int8),
        v=jnp.zeros(shape, jnp.int8),
        k_scale=jnp.zeros(shape[:-1], jnp.float32),
        v_scale=jnp.zeros(shape[:-1], jnp.float32),
        lengths=jnp.zeros((batch,), jnp.int32),
    )


def quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[..., hd] → (int8 [..., hd], fp32 scale [...]): symmetric absmax over
    the head_dim vector."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(absmax / INT8_MAX, 1e-8)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def _dequant(q: jnp.ndarray, scale: jnp.ndarray, dtype) -> jnp.ndarray:
    # Elementwise convert+mul: fuses into the attention einsum's operand
    # stream, so HBM only ever holds the int8 copy.
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


class _QuantLayerKV(NamedTuple):
    k: jnp.ndarray
    v: jnp.ndarray
    k_scale: jnp.ndarray
    v_scale: jnp.ndarray


def _quant_attention(
    cfg: ModelConfig,
    layer,
    x: jnp.ndarray,  # [b, s, h]
    positions: jnp.ndarray,  # [b, s]
    cache: _QuantLayerKV,
    kv_valid: jnp.ndarray,  # [b, max_seq]
    lengths: jnp.ndarray,  # [b] decode write offsets
    is_decode: bool,
):
    """Drop-in attention backend for _layer_fn over one layer's int8 cache."""
    b, s, _ = x.shape
    nh, hd = cfg.num_heads, cfg.head_size
    q, k, v = qkv_proj(cfg, layer, x, positions)
    k_q, k_s = quantize_kv(k)
    v_q, v_s = quantize_kv(v)

    # write_prefill/write_decode centralize the scatter index arithmetic; the
    # indexing is agnostic to trailing dims, so the [.., kh] scale arrays ride
    # the same helpers as the [.., kh, hd] data arrays.
    from edgemesh.ops.attention import write_decode, write_prefill

    write = (
        (lambda c, a, b2: write_decode(c, a, b2, lengths))
        if is_decode
        else write_prefill
    )
    data = write(LayerKV(cache.k, cache.v), k_q, v_q)
    scale = write(LayerKV(cache.k_scale, cache.v_scale), k_s, v_s)
    cache = _QuantLayerKV(data.k, data.v, scale.k, scale.v)

    dtype = cfg.activation_dtype
    layer_kv = LayerKV(
        _dequant(cache.k, cache.k_scale, dtype),
        _dequant(cache.v, cache.v_scale, dtype),
    )
    out = attend(
        q, layer_kv, positions, kv_valid, scale=cfg.query_scale,
        sliding_window=cfg.sliding_window, soft_cap=cfg.attn_soft_cap,
    )
    return dense(layer["o"], out.reshape(b, s, nh * hd), cfg.quant_mode), cache


def _quant_forward(
    cfg: ModelConfig,
    params,
    tokens: jnp.ndarray,  # [b, s]
    positions: jnp.ndarray,
    cache: QuantKVCache,
    kv_valid: jnp.ndarray,
    is_decode: bool,
):
    x = embed_tokens(cfg, params, tokens, positions)

    def one_layer(fn_cfg, h, layer, kv4):
        fn = _layer_fn
        if cfg.remat:
            fn = jax.checkpoint(fn, static_argnums=(0, 7, 8, 9))
        return fn(
            fn_cfg, h, layer, _QuantLayerKV(*kv4), positions, kv_valid,
            cache.lengths, is_decode, _quant_attention, _mlp,
        )

    def body(layer_cfg, h, scanned):
        layer = scanned[0]
        h, new_kv, _aux = one_layer(layer_cfg, h, layer, tuple(scanned[1:]))
        return h, tuple(new_kv)

    x, (new_k, new_v, new_ks, new_vs) = layer_scan_alt_windows(
        cfg, body, x,
        (params["layers"], cache.k, cache.v, cache.k_scale, cache.v_scale),
    )
    logits = lm_head_logits(cfg, params, x)
    return logits, cache._replace(k=new_k, v=new_v, k_scale=new_ks, v_scale=new_vs)


@partial(jax.jit, static_argnums=(0,))
def forward_prefill_quant(
    cfg: ModelConfig,
    params,
    tokens: jnp.ndarray,  # [b, s] right-padded prompts
    lengths: jnp.ndarray,  # [b]
    cache: QuantKVCache,
) -> tuple[jnp.ndarray, QuantKVCache]:
    b, s = tokens.shape
    max_seq = cache.k.shape[2]
    positions = jnp.minimum(
        jnp.broadcast_to(jnp.arange(s)[None, :], (b, s)), (lengths - 1)[:, None]
    )
    kv_valid = jnp.arange(max_seq)[None, :] < lengths[:, None]
    logits, cache = _quant_forward(
        cfg, params, tokens, positions, cache, kv_valid, is_decode=False
    )
    last = logits[jnp.arange(b), lengths - 1]
    return last, cache._replace(lengths=lengths)


@partial(jax.jit, static_argnums=(0,))
def forward_decode_quant(
    cfg: ModelConfig,
    params,
    tokens: jnp.ndarray,  # [b]
    cache: QuantKVCache,
) -> tuple[jnp.ndarray, QuantKVCache]:
    max_seq = cache.k.shape[2]
    positions = cache.lengths[:, None]
    kv_valid = jnp.arange(max_seq)[None, :] <= cache.lengths[:, None]
    logits, cache = _quant_forward(
        cfg, params, tokens[:, None], positions, cache, kv_valid, is_decode=True
    )
    return logits[:, 0], cache._replace(lengths=cache.lengths + 1)


def generate_quant_kv(
    cfg: ModelConfig,
    params,
    tokens: jax.Array,
    lengths: jax.Array,
    sampling: SamplingParams,
    eos_id: int = -1,
    rng: jax.Array | None = None,
    cache: QuantKVCache | None = None,
) -> GenerateResult:
    """generate() with the int8 KV cache plugged in — validation, timing,
    and throughput conventions all inherited from runtime.generate."""

    def check_cache(cache, needed):
        if cache.k.shape[2] < needed:
            raise ValueError(
                f"quant KV cache capacity {cache.k.shape[2]} < prompt + max_new = {needed}"
            )

    return generate(
        cfg, params, tokens, lengths, sampling, eos_id=eos_id, rng=rng,
        cache=cache, prefill_fn=forward_prefill_quant,
        decode_fn=forward_decode_quant,
        make_cache=init_quant_kv_cache,
        check_cache=check_cache,
    )
