"""Generation over the paged KV cache (HeadInfer analog, runtime/paged_kv.py).

Same two-program structure as runtime/generate.py — one jitted prefill, one
jitted whole-token-loop decode — but the cache is a shared page pool instead
of a dense ``[b, max_seq]`` slab, so one preallocated HBM region serves many
variable-length sequences (the serving memory model the reference lacks; its
HF ``generate`` reallocates per call, combiner_fp.py:338-347).

The transformer layer wiring is NOT duplicated: models/transformer._layer_fn
takes the attention backend as a parameter, and this module supplies
``_paged_attention`` (write into pages + Pallas page-table-walking kernel on
TPU, gather fallback elsewhere). Page allocation happens once per decode step
— before the layer scan — because the page table is shared by all layers.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from edgemesh.config import SamplingParams
from edgemesh.models.transformer import (
    ModelConfig,
    _layer_fn,
    _use_flash,
    dense,
    embed_tokens,
    layer_scan_alt_windows,
    lm_head_logits,
    qkv_proj,
)
from edgemesh.ops.attention import LayerKV, attend
from edgemesh.utils.platform import on_tpu
from edgemesh.ops.paged_attention import (
    paged_decode_attention,
    paged_decode_attention_xla,
    ragged_paged_attention,
    ragged_paged_attention_xla,
)
from edgemesh.runtime.generate import GenerateResult, generate
from edgemesh.runtime.paged_kv import (
    PagedKVCache,
    QuantPagedKVCache,
    allocate,
    init_paged_cache,
    init_quant_paged_cache,
    page_nbytes,
    pages_needed,
    write_tokens,
    write_tokens_quant,
)


def _paged_attention(
    cfg: ModelConfig,
    layer,
    x: jnp.ndarray,  # [b, s, h]
    positions: jnp.ndarray,  # [b, s]
    cache,  # (k_pages, v_pages, [k_scales, v_scales,] page_table, kv_lens)
    kv_valid,  # unused (validity is kv_lens in the paged world)
    lengths: jnp.ndarray,  # [b] write offset (0 for prefill, cur len for decode)
    is_decode: bool,
):
    """Drop-in attention backend for _layer_fn over one layer's page arrays.

    A 6-tuple cache marks the int8 pool (QuantPagedKVCache): writes quantize
    per token row, the decode kernel dequantizes in-page, and prefill attends
    over the quantize→dequantize roundtrip of the fresh k/v so its logits
    match the dense int8-KV backend (runtime/quant_kv.py) exactly."""
    quant = len(cache) == 6
    if quant:
        k_pages, v_pages, k_sc, v_sc, table, kv_lens = cache
    else:
        k_pages, v_pages, table, kv_lens = cache
    b, s, _ = x.shape
    nh, hd = cfg.num_heads, cfg.head_size
    q, k, v = qkv_proj(cfg, layer, x, positions)

    if is_decode:
        if quant:
            from edgemesh.runtime.quant_kv import quantize_kv

            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            k_pages, v_pages, k_sc, v_sc = write_tokens_quant(
                k_pages, v_pages, k_sc, v_sc, kq, ks, vq, vs, table,
                start=lengths, valid_len=jnp.ones((b,), jnp.int32),
            )
        else:
            k_pages, v_pages = write_tokens(
                k_pages, v_pages, k, v, table, start=lengths,
                valid_len=jnp.ones((b,), jnp.int32),
            )
        scales = dict(k_scales=k_sc, v_scales=v_sc) if quant else {}
        if _use_flash(cfg):
            out = paged_decode_attention(
                q[:, 0], k_pages, v_pages, table, kv_lens,
                scale=cfg.query_scale,
                interpret=cfg.attention_impl == "flash"
                and not on_tpu(),
                sliding_window=cfg.sliding_window,
                soft_cap=cfg.attn_soft_cap,
                **scales,
            )
        else:
            out = paged_decode_attention_xla(
                q[:, 0], k_pages, v_pages, table, kv_lens,
                scale=cfg.query_scale,
                sliding_window=cfg.sliding_window,
                soft_cap=cfg.attn_soft_cap,
                **scales,
            )
        out = out[:, None]
    else:
        # Prefill: pages start empty, so the fresh k/v are the whole visible
        # prefix — attend over them directly (flash kernel on TPU), then
        # scatter them into the pages for the decode loop to extend.
        if quant:
            from edgemesh.runtime.quant_kv import quantize_kv

            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            k_pages, v_pages, k_sc, v_sc = write_tokens_quant(
                k_pages, v_pages, k_sc, v_sc, kq, ks, vq, vs, table,
                start=jnp.zeros((b,), jnp.int32), valid_len=kv_lens,
            )
            # Attend over the same values decode will read back: the int8
            # roundtrip of the fresh k/v (dense quant-KV backend parity).
            k = (kq.astype(jnp.float32) * ks[..., None]).astype(k.dtype)
            v = (vq.astype(jnp.float32) * vs[..., None]).astype(v.dtype)
        else:
            k_pages, v_pages = write_tokens(
                k_pages, v_pages, k, v, table, start=jnp.zeros((b,), jnp.int32),
                valid_len=kv_lens,
            )
        if _use_flash(cfg):
            from edgemesh.ops.flash_attention import flash_attention

            out = flash_attention(
                q, k, v, kv_lens, causal=True, scale=cfg.query_scale,
                interpret=cfg.attention_impl == "flash"
                and not on_tpu(),
                sliding_window=cfg.sliding_window,
                soft_cap=cfg.attn_soft_cap,
            )
        else:
            prompt_valid = jnp.arange(s)[None, :] < kv_lens[:, None]
            out = attend(
                q, LayerKV(k, v), positions, prompt_valid,
                scale=cfg.query_scale,
                sliding_window=cfg.sliding_window,
                soft_cap=cfg.attn_soft_cap,
            )
    proj = dense(layer["o"], out.reshape(b, s, nh * hd), cfg.quant_mode)
    if quant:
        return proj, (k_pages, v_pages, k_sc, v_sc, table, kv_lens)
    return proj, (k_pages, v_pages, table, kv_lens)


# Captured ONCE at import: the flag participates in jitted forwards as a
# trace-time constant, so a mid-process env change would otherwise create a
# silent shape-dependent mix of cached gather-path and kernel-path
# executables. Set it before the process starts; tests monkeypatch this
# module attribute and clear jit caches.
_CHUNK_KERNEL_OPTIN = __import__("os").environ.get("EDGEMESH_PAGED_CHUNK_KERNEL") == "1"


def _use_chunk_kernel(cfg: ModelConfig, quant: bool) -> bool:
    """Route chunk appends through the page-walking chunk kernel
    (ops/paged_attention.paged_chunk_attention) instead of the dense-gather
    oracle. OPT-IN via EDGEMESH_PAGED_CHUNK_KERNEL=1 (at process start).

    Measured on-chip 2026-07-31 (speculative decode over the paged pool,
    llama1b bf16, b1, gamma 4, best-of-3): gather 82.5 vs kernel 81.7
    tok/s at 32-token prompts, gather 71.2 vs kernel 69.1 at 1536-token
    prompts — the kernel never wins, even in the long-context regime it
    was built for (one big contiguous gather DMA + XLA attention beats
    the per-page walk at verify-chunk query counts). The gather stays the
    DEFAULT by measurement; the kernel stays opt-in for future shapes.
    Full-causal configs only (no window in the chunk kernel; both bf16
    and int8 pools), and only where the repo runs Pallas at all
    (_use_flash: respects attention_impl="xla" and the GSPMD opt-out)."""
    del quant  # int8 pools take the kernel too (scales fold in like decode)
    return (
        _CHUNK_KERNEL_OPTIN
        and cfg.sliding_window == 0
        and not cfg.alt_sliding_window
        and _use_flash(cfg)
    )


def _paged_suffix_attention(
    cfg: ModelConfig,
    layer,
    x: jnp.ndarray,  # [b, s, h] suffix chunk
    positions: jnp.ndarray,  # [b, s] ABSOLUTE positions (start + offset)
    cache,  # (k_pages, v_pages, [k_scales, v_scales,] page_table, kv_lens)
    kv_valid,  # [b, max_pages*page_size] — col < final kv_lens
    lengths: jnp.ndarray,  # [b] tokens ALREADY in the pages (suffix start)
    is_decode: bool,
):
    """Chunk-append attention over pages: write the suffix into its rows'
    pages, then attend over the GATHERED dense view (existing prefix pages +
    the fresh writes, read back exactly as decode will read them — int8
    roundtrip included for the quant pool). This is what lets rows
    warm-start from SHARED template pages (serve/continuous.py prefix
    sharing) and what backs the speculative verify chunk
    (forward_verify_paged).

    The gather is the dense-oracle DEFAULT: fine where appends are rare
    (admission: batch-1, once per request) and an accepted BANDWIDTH
    tradeoff where they are per-round (speculative verify gathers each
    row's full KV every round — the single-token decode loop keeps the
    page-walking kernel). A chunk-query page-walk kernel exists behind
    EDGEMESH_PAGED_CHUNK_KERNEL=1 (_use_chunk_kernel; parity-pinned, and
    measured slower than this gather on-chip at both short and long
    context — see _use_chunk_kernel for the numbers)."""
    from edgemesh.runtime.paged_kv import gather_dense, gather_dense_scales

    quant = len(cache) == 6
    if quant:
        k_pages, v_pages, k_sc, v_sc, table, kv_lens = cache
    else:
        k_pages, v_pages, table, kv_lens = cache
    b, s, _ = x.shape
    nh, hd = cfg.num_heads, cfg.head_size
    q, k, v = qkv_proj(cfg, layer, x, positions)
    suffix_len = kv_lens - lengths
    if quant:
        from edgemesh.runtime.quant_kv import quantize_kv

        kq, ks = quantize_kv(k)
        vq, vs = quantize_kv(v)
        k_pages, v_pages, k_sc, v_sc = write_tokens_quant(
            k_pages, v_pages, k_sc, v_sc, kq, ks, vq, vs, table,
            start=lengths, valid_len=suffix_len,
        )
    else:
        k_pages, v_pages = write_tokens(
            k_pages, v_pages, k, v, table, start=lengths, valid_len=suffix_len,
        )
    if _use_chunk_kernel(cfg, quant):
        from edgemesh.ops.paged_attention import paged_chunk_attention

        scales = dict(k_scales=k_sc, v_scales=v_sc) if quant else {}
        out = paged_chunk_attention(
            q, k_pages, v_pages, table, lengths, kv_lens,
            scale=cfg.query_scale,
            interpret=cfg.attention_impl == "flash" and not on_tpu(),
            soft_cap=cfg.attn_soft_cap,
            **scales,
        )
    else:
        if quant:
            dense_k = gather_dense(k_pages, table).astype(jnp.float32)
            dense_v = gather_dense(v_pages, table).astype(jnp.float32)
            dks = gather_dense_scales(k_sc, table)
            dvs = gather_dense_scales(v_sc, table)
            dense_k = (dense_k * dks[..., None]).astype(x.dtype)
            dense_v = (dense_v * dvs[..., None]).astype(x.dtype)
        else:
            dense_k = gather_dense(k_pages, table)
            dense_v = gather_dense(v_pages, table)
        out = attend(
            q, LayerKV(dense_k, dense_v), positions, kv_valid,
            scale=cfg.query_scale, sliding_window=cfg.sliding_window,
            soft_cap=cfg.attn_soft_cap,
        )
    proj = dense(layer["o"], out.reshape(b, s, nh * hd), cfg.quant_mode)
    if quant:
        return proj, (k_pages, v_pages, k_sc, v_sc, table, kv_lens)
    return proj, (k_pages, v_pages, table, kv_lens)


def _paged_forward(
    cfg: ModelConfig,
    params,
    tokens: jnp.ndarray,  # [b, s]
    positions: jnp.ndarray,
    cache: PagedKVCache,
    kv_lens: jnp.ndarray,  # [b] valid tokens AFTER this call's writes
    is_decode: bool,
    attention=_paged_attention,
    kv_valid=None,
):
    x = embed_tokens(cfg, params, tokens, positions)
    quant = isinstance(cache, QuantPagedKVCache)

    def body(layer_cfg, h, scanned):
        layer, *kv = scanned
        state = (*kv, cache.page_table, kv_lens)
        h, new_state, _aux = _layer_fn(
            layer_cfg, h, layer, state, positions, kv_valid, cache.lengths,
            is_decode, attention,
        )
        return h, tuple(new_state[:-2])  # drop table/kv_lens (not scanned)

    # Gemma-2's alternating windows ride the shared pair scan (each half's
    # window a static constant); plain configs take the ordinary scan.
    scanned = (params["layers"], cache.k, cache.v)
    if quant:
        scanned += (cache.k_scale, cache.v_scale)
    x, new_kv = layer_scan_alt_windows(cfg, body, x, scanned)
    if quant:
        new_k, new_v, new_ks, new_vs = new_kv
        cache = cache._replace(k=new_k, v=new_v, k_scale=new_ks, v_scale=new_vs)
    else:
        new_k, new_v = new_kv
        cache = cache._replace(k=new_k, v=new_v)
    return lm_head_logits(cfg, params, x), cache


@partial(jax.jit, static_argnums=(0,))
def forward_prefill_paged(
    cfg: ModelConfig,
    params,
    tokens: jnp.ndarray,  # [b, s] right-padded prompts
    lengths: jnp.ndarray,  # [b] true prompt lengths
    cache: PagedKVCache,
) -> tuple[jnp.ndarray, PagedKVCache]:
    """Allocate prompt pages, run the prompt, return last-real-token logits."""
    b, s = tokens.shape
    cache = allocate(cache, pages_needed(cache.lengths, lengths, cache.page_size))
    positions = jnp.minimum(
        jnp.broadcast_to(jnp.arange(s)[None, :], (b, s)), (lengths - 1)[:, None]
    )
    if _use_flash(cfg):
        logits, cache = _paged_forward_prefill_hoisted(
            cfg, params, tokens, positions, cache, lengths
        )
    else:
        logits, cache = _paged_forward(
            cfg, params, tokens, positions, cache, lengths, is_decode=False
        )
    last = logits[jnp.arange(b), lengths - 1]
    return last, cache._replace(lengths=lengths)


def _paged_append(
    cfg: ModelConfig,
    params,
    tokens: jnp.ndarray,  # [b, s] right-padded chunk
    lengths: jnp.ndarray,  # [b] true chunk lengths
    cache: PagedKVCache,
    start: jnp.ndarray,  # [b] tokens already present in each row's pages
) -> tuple[jnp.ndarray, PagedKVCache]:
    """Append a chunk at position ``start`` per row and attend over the full
    (existing pages + chunk) prefix; returns ALL chunk logits [b, s, vocab]
    and the cache advanced to start + lengths."""
    b, s = tokens.shape
    cache = cache._replace(lengths=start)
    cache = allocate(cache, pages_needed(start, lengths, cache.page_size))
    offsets = jnp.minimum(jnp.arange(s)[None, :], (lengths - 1)[:, None])
    positions = start[:, None] + offsets
    kv_lens = start + lengths
    max_cols = cache.max_pages * cache.page_size
    kv_valid = jnp.arange(max_cols)[None, :] < kv_lens[:, None]
    quant = isinstance(cache, QuantPagedKVCache)
    if _use_flash(cfg) and not _use_chunk_kernel(cfg, quant):
        # Hoisted-write path (default on TPU): gather-overlay attention +
        # one chunk-RMW kernel. The opt-in chunk kernel reads pages
        # directly, so it keeps the write-in-scan semantics.
        logits, cache = _paged_forward_suffix_hoisted(
            cfg, params, tokens, positions, cache, kv_lens, start, kv_valid
        )
    else:
        logits, cache = _paged_forward(
            cfg, params, tokens, positions, cache, kv_lens, is_decode=False,
            attention=_paged_suffix_attention, kv_valid=kv_valid,
        )
    return logits, cache._replace(lengths=kv_lens)


@partial(jax.jit, static_argnums=(0,))
def forward_prefill_paged_at(
    cfg: ModelConfig,
    params,
    tokens: jnp.ndarray,  # [b, s] right-padded SUFFIX tokens
    lengths: jnp.ndarray,  # [b] true suffix lengths
    cache: PagedKVCache,
    start: jnp.ndarray,  # [b] tokens already present in each row's pages
) -> tuple[jnp.ndarray, PagedKVCache]:
    """Suffix prefill: append ``tokens`` at position ``start`` per row and
    attend over the full (existing pages + suffix) prefix. The warm half of
    paged prefix sharing — rows whose tables already map shared template
    pages prefill only their question suffix (serve/continuous.py)."""
    b, s = tokens.shape
    logits, cache = _paged_append(cfg, params, tokens, lengths, cache, start)
    last = logits[jnp.arange(b), lengths - 1]
    return last, cache


@partial(jax.jit, static_argnums=(0,))
def forward_verify_paged(
    cfg: ModelConfig,
    params,
    tokens: jnp.ndarray,  # [b, s] chunk of already-chosen tokens per row
    cache: PagedKVCache,
) -> tuple[jnp.ndarray, PagedKVCache]:
    """Chunk-append decode over the paged cache — the speculative verify
    step (models/transformer.forward_verify's paged twin): s tokens per row
    in ONE forward, logits for every position, cache advanced by s. Callers
    rewind rejected suffixes by lowering ``lengths``; the rewind-idempotent
    allocator reuses the slots' pages when decoding re-advances.

    Attention rides the chunk-append hook — by default each verify round
    reads the row's full KV through a dense gather rather than a page walk
    (see _paged_suffix_attention's contract note; the opt-in chunk kernel
    changes that): exact tokens, bandwidth traded for composition."""
    b, s = tokens.shape
    full = jnp.full((b,), s, jnp.int32)
    return _paged_append(cfg, params, tokens, full, cache, cache.lengths)


def _paged_forward_prefill_hoisted(
    cfg: ModelConfig,
    params,
    tokens: jnp.ndarray,  # [b, s] right-padded chunk
    positions: jnp.ndarray,
    cache,
    kv_lens: jnp.ndarray,  # [b] valid tokens AFTER this call's writes
):
    """Cold prefill with hoisted page writes (the chunk twin of
    _paged_forward_decode_hoisted): pages start empty for these rows, so
    attention runs over the fresh prompt K/V alone — the pool is never
    read OR written inside the scan. The scan emits per-layer fresh K/V and
    ONE aliased chunk-RMW kernel (ops/paged_write.write_chunk_all_layers)
    commits them, replacing the per-layer scatter whose cost scaled with
    pool bytes × layers (~8 ms per admission at serving shapes)."""
    from edgemesh.ops.paged_write import write_chunk_all_layers

    pool = cache
    x = embed_tokens(cfg, params, tokens, positions)
    quant = isinstance(pool, QuantPagedKVCache)
    interp = cfg.attention_impl == "flash" and not on_tpu()
    b, s = tokens.shape
    nh, hd = cfg.num_heads, cfg.head_size

    def attention(acfg, layer, ax, apos, cache, kv_valid, lengths, is_decode):
        q, k, v = qkv_proj(acfg, layer, ax, apos)
        if quant:
            from edgemesh.runtime.quant_kv import quantize_kv

            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            fresh = (kq, vq, ks, vs)
            # Attend over the values decode will read back: the int8
            # roundtrip (dense quant-KV backend parity).
            k = (kq.astype(jnp.float32) * ks[..., None]).astype(k.dtype)
            v = (vq.astype(jnp.float32) * vs[..., None]).astype(v.dtype)
        else:
            fresh = (k, v)
        if _use_flash(acfg):
            from edgemesh.ops.flash_attention import flash_attention

            out = flash_attention(
                q, k, v, kv_lens, causal=True, scale=acfg.query_scale,
                interpret=interp, sliding_window=acfg.sliding_window,
                soft_cap=acfg.attn_soft_cap,
            )
        else:
            prompt_valid = jnp.arange(s)[None, :] < kv_lens[:, None]
            out = attend(
                q, LayerKV(k, v), apos, prompt_valid, scale=acfg.query_scale,
                sliding_window=acfg.sliding_window, soft_cap=acfg.attn_soft_cap,
            )
        proj = dense(layer["o"], out.reshape(b, s, nh * hd), acfg.quant_mode)
        return proj, fresh

    def body(layer_cfg, h, layer):
        h, fresh, _aux = _layer_fn(
            layer_cfg, h, layer, None, positions, None, None, False, attention
        )
        return h, fresh

    x, fresh = layer_scan_alt_windows(cfg, body, x, params["layers"])
    zeros = jnp.zeros_like(kv_lens)
    if quant:
        fk, fv, fks, fvs = fresh
        pool = write_chunk_all_layers(
            pool, fk, fv, zeros, kv_lens, fks, fvs, interpret=interp
        )
    else:
        fk, fv = fresh
        pool = write_chunk_all_layers(pool, fk, fv, zeros, kv_lens, interpret=interp)
    return lm_head_logits(cfg, params, x), pool


def _paged_forward_suffix_hoisted(
    cfg: ModelConfig,
    params,
    tokens: jnp.ndarray,  # [b, s] right-padded suffix chunk
    positions: jnp.ndarray,  # [b, s] absolute positions
    cache,
    kv_lens: jnp.ndarray,  # [b] valid tokens AFTER this call's writes
    start: jnp.ndarray,  # [b] tokens already present per row
    kv_valid: jnp.ndarray,  # [b, max_pages*ps]
):
    """Suffix/verify chunk append with hoisted page writes: the scan READS
    the old pages (dense gather, as the oracle path always has) and overlays
    the fresh chunk onto the gathered view with a masked where — never
    writing pages in-scan. One chunk-RMW kernel commits all layers after.
    This is what the speculative verify step pays every round, so the
    scatter's pool-sized cost mattered even more here than at admission."""
    from edgemesh.ops.paged_write import write_chunk_all_layers
    from edgemesh.runtime.paged_kv import gather_dense, gather_dense_scales

    pool = cache
    x = embed_tokens(cfg, params, tokens, positions)
    quant = isinstance(pool, QuantPagedKVCache)
    interp = cfg.attention_impl == "flash" and not on_tpu()
    b, s = tokens.shape
    nh, hd = cfg.num_heads, cfg.head_size
    max_cols = pool.max_pages * pool.page_size
    cols = jnp.arange(max_cols)[None, :]
    in_chunk = (cols >= start[:, None]) & (cols < kv_lens[:, None])
    tidx = jnp.clip(cols - start[:, None], 0, s - 1)  # [b, max_cols]

    def overlay(dense_view, fresh_chunk):
        full = jnp.take_along_axis(
            fresh_chunk.astype(dense_view.dtype),
            tidx[..., None, None], axis=1,
        )
        return jnp.where(in_chunk[..., None, None], full, dense_view)

    def attention(acfg, layer, ax, apos, cache, kv_valid, lengths, is_decode):
        kv = cache  # per-layer page slices from the scan xs (read-only)
        q, k, v = qkv_proj(acfg, layer, ax, apos)
        if quant:
            from edgemesh.runtime.quant_kv import quantize_kv

            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            fresh = (kq, vq, ks, vs)
            k_r = (kq.astype(jnp.float32) * ks[..., None]).astype(k.dtype)
            v_r = (vq.astype(jnp.float32) * vs[..., None]).astype(v.dtype)
            dense_k = gather_dense(kv[0], pool.page_table).astype(jnp.float32)
            dense_v = gather_dense(kv[1], pool.page_table).astype(jnp.float32)
            dks = gather_dense_scales(kv[2], pool.page_table)
            dvs = gather_dense_scales(kv[3], pool.page_table)
            dense_k = (dense_k * dks[..., None]).astype(ax.dtype)
            dense_v = (dense_v * dvs[..., None]).astype(ax.dtype)
        else:
            fresh = (k, v)
            k_r, v_r = k, v
            dense_k = gather_dense(kv[0], pool.page_table)
            dense_v = gather_dense(kv[1], pool.page_table)
        dense_k = overlay(dense_k, k_r)
        dense_v = overlay(dense_v, v_r)
        out = attend(
            q, LayerKV(dense_k, dense_v), apos, kv_valid,
            scale=acfg.query_scale, sliding_window=acfg.sliding_window,
            soft_cap=acfg.attn_soft_cap,
        )
        proj = dense(layer["o"], out.reshape(b, s, nh * hd), acfg.quant_mode)
        return proj, fresh

    def body(layer_cfg, h, scanned):
        layer, *kv = scanned
        h, fresh, _aux = _layer_fn(
            layer_cfg, h, layer, tuple(kv), positions, kv_valid, start,
            False, attention,
        )
        return h, fresh

    xs = (params["layers"], pool.k, pool.v)
    if quant:
        xs += (pool.k_scale, pool.v_scale)
    x, fresh = layer_scan_alt_windows(cfg, body, x, xs)
    if quant:
        fk, fv, fks, fvs = fresh
        pool = write_chunk_all_layers(
            pool, fk, fv, start, kv_lens - start, fks, fvs, interpret=interp
        )
    else:
        fk, fv = fresh
        pool = write_chunk_all_layers(
            pool, fk, fv, start, kv_lens - start, interpret=interp
        )
    return lm_head_logits(cfg, params, x), pool


def _paged_forward_decode_hoisted(
    cfg: ModelConfig,
    params,
    tokens: jnp.ndarray,  # [b, 1]
    positions: jnp.ndarray,  # [b, 1]
    cache,
    kv_lens: jnp.ndarray,  # [b] valid tokens INCLUDING the current one
):
    """Hoisted-write decode forward — the TPU kernel path.

    The original decode scattered each layer's fresh K/V into its page slice
    INSIDE the layer scan; XLA:TPU lowers that data-dependent scatter so
    badly the paged backend paid ~8 ms/step extra at Llama-1B serving shapes
    (the whole round-3 paged tax — measurement in ops/paged_write.py). Here
    the scan only READS the pool (the attention kernel addresses layer
    blocks of the full stacked array directly, so no per-layer slice ever
    materializes) and folds the current token in as a virtual page; the
    scan's ys are the tiny per-layer fresh K/V, and ONE aliased RMW kernel
    (ops/paged_write.write_decode_all_layers) commits them after the scan.
    Same numerics as write-then-attend — only the flash accumulation order
    differs."""
    from edgemesh.ops.paged_write import write_decode_all_layers

    pool = cache
    x = embed_tokens(cfg, params, tokens, positions)
    quant = isinstance(pool, QuantPagedKVCache)
    interp = cfg.attention_impl == "flash" and not on_tpu()
    b = tokens.shape[0]
    nh, hd = cfg.num_heads, cfg.head_size

    def attention(acfg, layer, ax, apos, cache, kv_valid, lengths, is_decode):
        l = cache  # scalar layer index (scanned); the pool rides the closure
        q, k, v = qkv_proj(acfg, layer, ax, apos)
        if quant:
            from edgemesh.runtime.quant_kv import quantize_kv

            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            fresh = (kq[:, 0], vq[:, 0], ks[:, 0], vs[:, 0])
            kwargs = dict(
                zip(("fresh_k", "fresh_v", "fresh_ks", "fresh_vs"), fresh),
                k_scales=pool.k_scale, v_scales=pool.v_scale,
            )
        else:
            fresh = (k[:, 0], v[:, 0])
            kwargs = dict(zip(("fresh_k", "fresh_v"), fresh))
        out = paged_decode_attention(
            q[:, 0], pool.k, pool.v, pool.page_table, kv_lens,
            scale=acfg.query_scale, interpret=interp,
            sliding_window=acfg.sliding_window, soft_cap=acfg.attn_soft_cap,
            layer=l, **kwargs,
        )
        proj = dense(layer["o"], out[:, None].reshape(b, 1, nh * hd), acfg.quant_mode)
        return proj, (l, fresh)

    def body(layer_cfg, h, scanned):
        layer, l = scanned
        h, state, _aux = _layer_fn(
            layer_cfg, h, layer, l, positions, None, pool.lengths,
            True, attention,
        )
        return h, state[1]  # ys = the fresh K/V tuple

    n_layers = jax.tree.leaves(params["layers"])[0].shape[0]
    x, fresh = layer_scan_alt_windows(
        cfg, body, x, (params["layers"], jnp.arange(n_layers, dtype=jnp.int32))
    )
    if quant:
        fk, fv, fks, fvs = fresh
        pool = write_decode_all_layers(pool, fk, fv, fks, fvs, interpret=interp)
    else:
        fk, fv = fresh
        pool = write_decode_all_layers(pool, fk, fv, interpret=interp)
    return lm_head_logits(cfg, params, x), pool


@partial(jax.jit, static_argnums=(0,))
def forward_decode_paged(
    cfg: ModelConfig,
    params,
    tokens: jnp.ndarray,  # [b] one new token per row
    cache: PagedKVCache,
) -> tuple[jnp.ndarray, PagedKVCache]:
    """One autoregressive step; grows each row's table when it crosses a page
    boundary (pure array ops — safe inside the scanned decode loop)."""
    cache = allocate(
        cache, pages_needed(cache.lengths, jnp.ones_like(cache.lengths), cache.page_size)
    )
    positions = cache.lengths[:, None]
    if _use_flash(cfg):
        logits, cache = _paged_forward_decode_hoisted(
            cfg, params, tokens[:, None], positions, cache, cache.lengths + 1
        )
    else:
        logits, cache = _paged_forward(
            cfg, params, tokens[:, None], positions, cache, cache.lengths + 1,
            is_decode=True,
        )
    return logits[:, 0], cache._replace(lengths=cache.lengths + 1)


@partial(jax.jit, static_argnums=(0, 5))
def forward_ragged_paged(
    cfg: ModelConfig,
    params,
    tokens: jnp.ndarray,  # [T] int32 — token-major packed segments
    cu_q_lens: jnp.ndarray,  # [b+1] int32 — segment i = rows [cu[i], cu[i+1])
    cache: PagedKVCache,
    s_cap: int,  # static: max segment length this compile handles
) -> tuple[jnp.ndarray, PagedKVCache]:
    """ONE forward for a ragged batch of mixed prefill chunks and decode
    rows over the page pool — the serving-boundary program that replaces the
    prefill / suffix-prefill / decode-bridge triplet (serve/continuous.py):
    a freshly admitted prompt (or warm template suffix) and every resident
    row's next decode token ride the same launch.

    ``cache.lengths`` holds each row's committed token count; segment i
    appends ``cu[i+1] - cu[i]`` tokens at positions ``lengths[i] + j``.
    Returns (last-token logits [b, vocab], cache advanced per row). Rows
    with zero-length segments pass through untouched (their logits row is
    garbage — callers track liveness host-side).

    On TPU the layer scan never touches the pool: attention is the ragged
    Pallas kernel (ops/paged_attention.ragged_paged_attention) addressing
    layer blocks of the stacked pool directly with the chunk's K/V folded in
    as packed fresh blocks, and ONE aliased chunk-RMW kernel commits every
    layer's writes after the scan (the hoisted-write discipline of
    _paged_forward_decode_hoisted — the in-scan scatter it replaces was the
    whole round-3 paged tax). Off-TPU the gather oracle path writes in-scan
    and attends through ragged_paged_attention_xla. ``s_cap`` only shapes
    the post-scan write gather ([L, b, s_cap] fresh view) — keep it at the
    batch's max segment length, bucketed, so compile variants stay bounded.
    """
    b = cache.page_table.shape[0]
    T = tokens.shape[0]
    cu = cu_q_lens.astype(jnp.int32)
    q_lens = cu[1:] - cu[:-1]
    start = cache.lengths
    kv_lens = start + q_lens
    cache = allocate(cache, pages_needed(start, q_lens, cache.page_size))
    t = jnp.arange(T, dtype=jnp.int32)
    seq = jnp.clip(jnp.searchsorted(cu, t, side="right") - 1, 0, b - 1)
    positions = jnp.clip(start[seq] + t - cu[seq], 0, cfg.max_seq_len - 1)
    if _use_flash(cfg):
        logits, cache = _ragged_forward_hoisted(
            cfg, params, tokens, positions, cu, q_lens, kv_lens, cache, s_cap
        )
    else:
        logits, cache = _ragged_forward_xla(
            cfg, params, tokens, positions, seq, cu, q_lens, kv_lens, cache
        )
    last = logits[jnp.clip(cu[1:] - 1, 0, T - 1)]
    return last, cache._replace(lengths=kv_lens)


def _ragged_forward_hoisted(
    cfg: ModelConfig,
    params,
    tokens: jnp.ndarray,  # [T] packed
    positions: jnp.ndarray,  # [T] absolute positions
    cu: jnp.ndarray,  # [b+1]
    q_lens: jnp.ndarray,  # [b]
    kv_lens: jnp.ndarray,  # [b] lengths AFTER this call's writes
    cache,
    s_cap: int,
):
    """Ragged forward with hoisted page writes (TPU kernel path): the scan
    only READS the pool through the ragged kernel (layer-block addressing,
    fresh chunk folded from packed blocks); ys are the per-layer packed
    fresh K/V, committed by one chunk-RMW kernel after the scan."""
    from edgemesh.ops.paged_write import write_chunk_all_layers

    pool = cache
    x = embed_tokens(cfg, params, tokens[None, :], positions[None, :])
    quant = isinstance(pool, QuantPagedKVCache)
    interp = cfg.attention_impl == "flash" and not on_tpu()
    b = pool.page_table.shape[0]
    T = tokens.shape[0]
    nh, hd = cfg.num_heads, cfg.head_size

    def attention(acfg, layer, ax, apos, cache, kv_valid, lengths, is_decode):
        l = cache  # scalar layer index (scanned); the pool rides the closure
        q, k, v = qkv_proj(acfg, layer, ax, apos)
        if quant:
            from edgemesh.runtime.quant_kv import quantize_kv

            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            fresh = (kq[0], vq[0], ks[0], vs[0])
            kwargs = dict(
                zip(("fresh_k", "fresh_v", "fresh_ks", "fresh_vs"), fresh),
                k_scales=pool.k_scale, v_scales=pool.v_scale,
            )
        else:
            fresh = (k[0], v[0])
            kwargs = dict(zip(("fresh_k", "fresh_v"), fresh))
        out = ragged_paged_attention(
            q[0], pool.k, pool.v, pool.page_table, kv_lens, cu,
            scale=acfg.query_scale, interpret=interp,
            sliding_window=acfg.sliding_window, soft_cap=acfg.attn_soft_cap,
            layer=l, **kwargs,
        )
        proj = dense(layer["o"], out[None].reshape(1, T, nh * hd), acfg.quant_mode)
        return proj, (l, fresh)

    def body(layer_cfg, h, scanned):
        layer, l = scanned
        h, state, _aux = _layer_fn(
            layer_cfg, h, layer, l, positions[None, :], None, pool.lengths,
            True, attention,
        )
        return h, state[1]  # ys = the packed fresh K/V tuple

    n_layers = jax.tree.leaves(params["layers"])[0].shape[0]
    x, fresh = layer_scan_alt_windows(
        cfg, body, x, (params["layers"], jnp.arange(n_layers, dtype=jnp.int32))
    )
    # Packed [L, T, ...] fresh → per-row [L, b, s_cap, ...] for the chunk-RMW
    # writer (segment i's token j = packed row cu[i]+j; pad rows clamp onto
    # the last real token and are masked dead by valid_len).
    idx = jnp.clip(
        cu[:-1, None] + jnp.minimum(
            jnp.arange(s_cap, dtype=jnp.int32)[None, :],
            jnp.maximum(q_lens - 1, 0)[:, None],
        ),
        0, T - 1,
    )  # [b, s_cap]

    def unpack(a):
        return jnp.take(a, idx.reshape(-1), axis=1).reshape(
            a.shape[0], b, s_cap, *a.shape[2:]
        )

    start = kv_lens - q_lens
    if quant:
        fk, fv, fks, fvs = fresh
        pool = write_chunk_all_layers(
            pool, unpack(fk), unpack(fv), start, q_lens,
            unpack(fks), unpack(fvs), interpret=interp,
        )
    else:
        fk, fv = fresh
        pool = write_chunk_all_layers(
            pool, unpack(fk), unpack(fv), start, q_lens, interpret=interp
        )
    return lm_head_logits(cfg, params, x)[0], pool


def _ragged_forward_xla(
    cfg: ModelConfig,
    params,
    tokens: jnp.ndarray,  # [T] packed
    positions: jnp.ndarray,  # [T]
    seq: jnp.ndarray,  # [T] owning sequence per packed token
    cu: jnp.ndarray,  # [b+1]
    q_lens: jnp.ndarray,  # [b]
    kv_lens: jnp.ndarray,  # [b]
    cache,
):
    """Ragged forward, gather-oracle path (non-TPU / forced-XLA configs):
    per layer, scatter the packed chunk into its pages (write-then-attend —
    the read-back is exactly what decode sees, int8 roundtrip included),
    then attend through ragged_paged_attention_xla's dense gather."""
    pool = cache
    x = embed_tokens(cfg, params, tokens[None, :], positions[None, :])
    quant = isinstance(pool, QuantPagedKVCache)
    T = tokens.shape[0]
    ps = pool.page_size
    nh, hd = cfg.num_heads, cfg.head_size
    table = pool.page_table
    # Per-token physical (page, slot); the packed tail past cu[b] lands on
    # the trash page like every other invalid write.
    logical = jnp.minimum(positions // ps, table.shape[1] - 1)
    pp = jnp.where(
        jnp.arange(T) < cu[-1], table[seq, logical], 0
    )
    ss = positions % ps

    def attention(acfg, layer, ax, apos, cache, kv_valid, lengths, is_decode):
        kv = cache  # per-layer page slices from the scan xs
        q, k, v = qkv_proj(acfg, layer, ax, apos)
        if quant:
            from edgemesh.runtime.quant_kv import quantize_kv

            kq, ks = quantize_kv(k)
            vq, vs = quantize_kv(v)
            kp = kv[0].at[pp, :, ss, :].set(kq[0])
            vp = kv[1].at[pp, :, ss, :].set(vq[0])
            ksp = kv[2].at[pp, :, 0, ss].set(ks[0].astype(kv[2].dtype))
            vsp = kv[3].at[pp, :, 0, ss].set(vs[0].astype(kv[3].dtype))
            new_kv = (kp, vp, ksp, vsp)
            scales = dict(k_scales=ksp, v_scales=vsp)
        else:
            kp = kv[0].at[pp, :, ss, :].set(k[0].astype(kv[0].dtype))
            vp = kv[1].at[pp, :, ss, :].set(v[0].astype(kv[1].dtype))
            new_kv = (kp, vp)
            scales = {}
        out = ragged_paged_attention_xla(
            q[0], kp, vp, table, kv_lens, cu, scale=acfg.query_scale,
            sliding_window=acfg.sliding_window, soft_cap=acfg.attn_soft_cap,
            **scales,
        )
        proj = dense(layer["o"], out[None].reshape(1, T, nh * hd), acfg.quant_mode)
        return proj, new_kv

    def body(layer_cfg, h, scanned):
        layer, *kv = scanned
        h, state, _aux = _layer_fn(
            layer_cfg, h, layer, tuple(kv), positions[None, :], None,
            pool.lengths, True, attention,
        )
        return h, tuple(state)

    xs = (params["layers"], pool.k, pool.v)
    if quant:
        xs += (pool.k_scale, pool.v_scale)
    x, new_kv = layer_scan_alt_windows(cfg, body, x, xs)
    if quant:
        pool = pool._replace(
            k=new_kv[0], v=new_kv[1], k_scale=new_kv[2], v_scale=new_kv[3]
        )
    else:
        pool = pool._replace(k=new_kv[0], v=new_kv[1])
    return lm_head_logits(cfg, params, x)[0], pool


def generate_paged(
    cfg: ModelConfig,
    params,
    tokens: jax.Array,  # [b, s] right-padded prompts
    lengths: jax.Array,  # [b] true prompt lengths
    sampling: SamplingParams,
    eos_id: int = -1,
    rng: jax.Array | None = None,
    cache: PagedKVCache | QuantPagedKVCache | None = None,
    page_size: int = 64,
    kv_quant: bool = False,
) -> GenerateResult:
    """generate() over the paged cache: delegates to runtime.generate.generate
    with the paged forwards plugged in, so validation, timing, and the
    throughput conventions live in exactly one place. Sliding-window configs
    (Mistral) work end-to-end — the page-table kernel never DMAs pages
    outside a row's window — and Gemma-2's full dial set (score soft cap,
    fixed query scale, ALTERNATING windows via the shared pair scan) runs
    here too, pinned against the dense backend in tests/test_paged_kv.py.
    ``kv_quant=True`` (or passing a QuantPagedKVCache) stores pages as int8
    with per-token scales — half the page-walk bytes, same table machinery."""

    def make_cache(cfg, batch, needed):
        per_row = (needed + page_size - 1) // page_size
        init = init_quant_paged_cache if kv_quant else init_paged_cache
        return init(
            cfg, batch, total_pages=1 + batch * per_row, page_size=page_size,
            max_pages=per_row,
        )

    def check_cache(cache, needed):
        batch = cache.page_table.shape[0]
        capacity = cache.max_pages * cache.page_size
        if capacity < needed:
            raise ValueError(
                f"paged cache capacity {capacity} (max_pages x page_size) < "
                f"prompt + max_new = {needed}"
            )
        free = cache.free_stack.shape[0] - int(cache.free_top)
        want = int(jnp.sum(pages_needed(
            cache.lengths, jnp.full((batch,), needed, jnp.int32), cache.page_size
        )))
        if want > free:
            raise ValueError(
                f"page pool exhausted: need {want} pages, {free} free "
                f"({page_nbytes(cache)} bytes/page) — size total_pages "
                "for prompt+max_new across the batch"
            )

    return generate(
        cfg, params, tokens, lengths, sampling, eos_id=eos_id, rng=rng,
        cache=cache, prefill_fn=forward_prefill_paged,
        decode_fn=forward_decode_paged, make_cache=make_cache,
        check_cache=check_cache,
    )


# Boundary catalog: the jitted entry points the serving stack dispatches for
# paged attention, keyed by the ledger boundary name each one is launched
# under (see edgemesh.obs.compute).  Tests use these handles to pin that
# ``aot_cost_analysis`` yields flops/bytes for the real paged boundaries on
# CPU, without standing up an engine.
LEDGER_BOUNDARIES = {
    "paged_prefill": forward_prefill_paged,
    "paged_splice": forward_prefill_paged_at,
    "paged_decode": forward_decode_paged,
    "ragged_boundary": forward_ragged_paged,
    "paged_verify": forward_verify_paged,
}
