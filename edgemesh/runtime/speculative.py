"""Speculative decoding: a draft model proposes, the target model verifies.

The reference's ensemble keeps several small models resident and still decodes
one token per target forward (HF ``generate`` per agent,
``Code/C-DAC Server/combiner_fp.py:338-347``). Speculative decoding spends the
same weights differently: the DRAFT model autoregresses ``gamma`` cheap steps,
then the TARGET scores all proposals in ONE chunk forward
(models/transformer.py:forward_verify) — on TPU that turns ``gamma``
bandwidth-bound batch-8 matmuls into one MXU-friendly batch-8×(gamma+1)
matmul, so accepted tokens cost a fraction of a full decode step.

Exactness: the emitted sequence follows the TARGET's sampling distribution
exactly (Leviathan et al. 2023 rejection scheme) — accept draft token ``d``
with prob ``min(1, p(d)/q(d))``; on first rejection resample from
``norm(max(p − q, 0))``; if all gamma accepted, draw one bonus token from the
target's next distribution. All distributions here are the POST-FILTER ones
(temperature/top-k/top-p/repetition-penalty), evaluated on their ≤top_k
candidate supports (ops/sampling.py:filtered_candidates), so nothing touches
the full vocab: p(d) is a [k]-sized match, the residual's support is the
target's candidate set. Greedy mode degenerates to exact token equality and
reproduces greedy target decoding token-for-token (pinned by tests).

The whole loop — draft steps, verify chunk, acceptance, commit — is one
jitted ``lax.while_loop``; per-row variable acceptance rides the per-row
cache ``lengths`` (chunk writes land at per-row offsets, rejected suffixes
are rewound by lowering lengths — stale slots stay masked by kv_valid until
the next chunk overwrites them).
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from edgemesh.config import SamplingParams
from edgemesh.models.transformer import (
    KVCache,
    ModelConfig,
    forward_decode,
    forward_prefill,
    forward_verify,
    init_kv_cache,
)
from edgemesh.ops.sampling import TokenMaskState, filtered_candidates, sample_token
from edgemesh.runtime.generate import GenerateResult


class SpecStats(NamedTuple):
    proposed: int  # draft tokens proposed
    accepted: int  # draft tokens accepted
    rounds: int

    @property
    def accept_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0


class _SpecState(NamedTuple):
    pending: jax.Array  # [b] last committed token, not yet in any cache
    t_cache: KVCache
    d_cache: KVCache
    out: jax.Array  # [b, cap]
    n_emit: jax.Array  # [b] tokens emitted (incl. slot 0)
    finished: jax.Array  # [b]
    mask: jax.Array  # [b, vocab] repetition-penalty presence mask
    rng: jax.Array
    conf_sum: jax.Array  # [b]
    accepted: jax.Array  # [] int32
    proposed: jax.Array  # [] int32
    rounds: jax.Array  # [] int32


def _match_prob(idx: jnp.ndarray, probs: jnp.ndarray, token: jnp.ndarray) -> jnp.ndarray:
    """probs[token] for a sparse candidate dist: [b,k] idx/probs, [b] token."""
    return jnp.sum(jnp.where(idx == token[:, None], probs, 0.0), axis=-1)


@partial(jax.jit, static_argnums=(0, 1, 2, 3))
def _spec_init(
    # Slot 0 needs only the prefill logits — no configs or weights. (They
    # used to ride along for signature symmetry with _spec_rounds; edgelint
    # EM104 flagged the weight pytrees as dead traced args, each a full
    # model's worth of transfer/donation keying for zero effect.)
    sampling: SamplingParams,
    gamma: int,
    max_new: int,
    eos_id: int,
    first_logits: jax.Array,
    t_cache: KVCache,
    d_cache: KVCache,
    mask: jax.Array,
    rng: jax.Array,
) -> _SpecState:
    """Initial loop state: slot 0 sampled from the TARGET's prefill logits —
    same as the dense path."""
    batch, _ = first_logits.shape
    cap = max_new + gamma + 1
    rng, r0 = jax.random.split(rng)
    token0 = sample_token(r0, first_logits, sampling, mask).astype(jnp.int32)
    out = jnp.full((batch, cap), eos_id, jnp.int32).at[:, 0].set(token0)
    conf0 = jnp.max(jax.nn.softmax(first_logits.astype(jnp.float32), axis=-1), axis=-1)
    finished = token0 == eos_id
    mask = TokenMaskState(mask).add(token0).mask
    return _SpecState(
        pending=token0,
        t_cache=t_cache,
        d_cache=d_cache,
        out=out,
        n_emit=jnp.ones((batch,), jnp.int32),
        finished=finished,
        mask=mask,
        rng=rng,
        conf_sum=conf0,
        accepted=jnp.zeros((), jnp.int32),
        proposed=jnp.zeros((), jnp.int32),
        rounds=jnp.zeros((), jnp.int32),
    )


def _make_spec_body(
    cfg_t: ModelConfig,
    cfg_d: ModelConfig,
    params_t,
    params_d,
    sampling: SamplingParams,
    gamma: int,
    max_new: int,
    eos_id: int,
    vocab: int,
    cap: int,
    verify_fn=forward_verify,
    decode_fn=forward_decode,
):
    """One draft→verify→accept→commit round as a while_loop body — shared by
    the run-to-completion loop and the segmented streaming loop. The cache
    backend is pluggable: (verify_fn, decode_fn) default to the dense pair;
    the paged pair (runtime/paged_generate.forward_verify_paged /
    forward_decode_paged) rides the same body — the rewind (a lengths
    rollback) is safe on pages because the allocator reuses slots that kept
    their pages."""

    def body(s: _SpecState):
        batch = s.pending.shape[0]
        active = ~s.finished & (s.n_emit < max_new)
        L_t, L_d = s.t_cache.lengths, s.d_cache.lengths
        rng, r_draft, r_acc, r_res = jax.random.split(s.rng, 4)

        # --- draft: gamma proposals + one cache-fill step -----------------
        def draft_step(j, carry):
            d_cache, cur, dmask, d_toks, q_sel, q_idx, q_probs = carry
            logits, d_cache = decode_fn(cfg_d, params_d, cur, d_cache)
            idx, probs = filtered_candidates(logits, sampling, dmask)
            if sampling.do_sample:
                choice = jax.random.categorical(
                    jax.random.fold_in(r_draft, j), jnp.log(jnp.maximum(probs, 1e-30))
                )
            else:
                choice = jnp.zeros((batch,), jnp.int32)
            nxt = jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)
            d_toks = d_toks.at[:, j].set(nxt)
            q_sel = q_sel.at[:, j].set(
                jnp.take_along_axis(probs, choice[:, None], axis=-1)[:, 0]
            )
            q_idx = q_idx.at[:, j].set(idx)
            q_probs = q_probs.at[:, j].set(probs)
            dmask = TokenMaskState(dmask).add(nxt).mask
            return d_cache, nxt, dmask, d_toks, q_sel, q_idx, q_probs

        q_k = 1 if not sampling.do_sample else sampling.top_k
        init = (
            s.d_cache, s.pending, s.mask,
            jnp.zeros((batch, gamma), jnp.int32),
            jnp.zeros((batch, gamma), jnp.float32),
            jnp.zeros((batch, gamma, q_k), jnp.int32),
            jnp.zeros((batch, gamma, q_k), jnp.float32),
        )
        d_cache, last_d, _, d_toks, q_sel, q_idx, q_probs = jax.lax.fori_loop(
            0, gamma, draft_step, init
        )
        # Extra draft forward so the draft cache also holds d_gamma's KV
        # (needed when every proposal is accepted; logits unused).
        _, d_cache = decode_fn(cfg_d, params_d, last_d, d_cache)

        # --- target: one verify chunk over [pending, d_1..d_gamma] --------
        chunk = jnp.concatenate([s.pending[:, None], d_toks], axis=1)  # [b, g+1]
        t_logits, t_cache = verify_fn(cfg_t, params_t, chunk, s.t_cache)

        # Per-position penalty masks: position j's mask includes d_1..d_j.
        d_onehots = jnp.cumsum(
            jax.nn.one_hot(d_toks, vocab, dtype=jnp.float32), axis=1
        ) > 0  # [b, gamma, vocab] — mask_j for j>=1 adds d_1..d_j
        pos_masks = jnp.concatenate(
            [s.mask[:, None], s.mask[:, None] | d_onehots], axis=1
        )  # [b, gamma+1, vocab]
        p_idx, p_probs = filtered_candidates(t_logits, sampling, pos_masks)

        # --- acceptance (Leviathan et al.) --------------------------------
        p_of_d = jnp.stack(
            [
                _match_prob(p_idx[:, j], p_probs[:, j], d_toks[:, j])
                for j in range(gamma)
            ],
            axis=1,
        )  # [b, gamma] — target prob of each proposal on its candidate set
        u = jax.random.uniform(r_acc, (batch, gamma))
        accept = u * jnp.maximum(q_sel, 1e-30) < p_of_d  # [b, gamma]
        n = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)  # [b]

        # Residual dist at the rejection position (support = target cands).
        rej = jnp.minimum(n, gamma - 1)  # index of first rejection (if any)
        p_rej_idx = jnp.take_along_axis(
            p_idx, rej[:, None, None], axis=1
        )[:, 0]  # [b, k_t]
        p_rej = jnp.take_along_axis(p_probs, rej[:, None, None], axis=1)[:, 0]
        q_rej_idx = jnp.take_along_axis(q_idx, rej[:, None, None], axis=1)[:, 0]
        q_rej = jnp.take_along_axis(q_probs, rej[:, None, None], axis=1)[:, 0]
        # q evaluated on the target's candidate tokens: [b, k_t]
        q_on_p = jnp.sum(
            jnp.where(p_rej_idx[:, :, None] == q_rej_idx[:, None, :], q_rej[:, None, :], 0.0),
            axis=-1,
        )
        residual = jnp.maximum(p_rej - q_on_p, 0.0)
        # All-zero residual (p==q on the support) → resample from p itself.
        residual = jnp.where(
            jnp.sum(residual, axis=-1, keepdims=True) > 1e-30, residual, p_rej
        )
        bonus_idx, bonus_probs = p_idx[:, gamma], p_probs[:, gamma]
        all_acc = n == gamma
        e_idx = jnp.where(all_acc[:, None], bonus_idx, p_rej_idx)
        e_probs = jnp.where(all_acc[:, None], bonus_probs, residual)
        if sampling.do_sample:
            choice = jax.random.categorical(r_res, jnp.log(jnp.maximum(e_probs, 1e-30)))
        else:
            choice = jnp.argmax(e_probs, axis=-1)
        e = jnp.take_along_axis(e_idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)

        # --- commit: emitted = d_1..d_n then e, truncated at EOS ----------
        em = jnp.concatenate([d_toks, e[:, None]], axis=1)  # [b, gamma+1]
        em = jnp.where(jnp.arange(gamma + 1)[None, :] == n[:, None], e[:, None], em)
        j_idx = jnp.arange(gamma + 1)[None, :]
        in_round = j_idx <= n[:, None]
        eos_before = jnp.cumsum((em == eos_id).astype(jnp.int32), axis=1) - (
            em == eos_id
        ).astype(jnp.int32) > 0
        commit = in_round & ~eos_before & active[:, None]  # [b, gamma+1]
        n_commit = jnp.sum(commit.astype(jnp.int32), axis=1)  # [b]
        # d-tokens committed (e is pending, not cached): cache advance counts
        # x0 plus every committed d (a committed e contributes nothing yet).
        d_commit = jnp.sum(
            (commit & (j_idx < n[:, None])).astype(jnp.int32), axis=1
        )
        slots = s.n_emit[:, None] + j_idx
        out = s.out.at[
            jnp.arange(batch)[:, None], jnp.minimum(slots, cap - 1)
        ].set(jnp.where(commit, em, s.out[jnp.arange(batch)[:, None], jnp.minimum(slots, cap - 1)]))
        mask = TokenMaskState(s.mask).add_sequence(em, commit).mask

        # Confidence: target's raw max-softmax at the emitted positions.
        t_conf = jnp.max(
            jax.nn.softmax(t_logits.astype(jnp.float32), axis=-1), axis=-1
        )  # [b, gamma+1]
        conf_sum = s.conf_sum + jnp.sum(jnp.where(commit, t_conf, 0.0), axis=1)

        new_finished = s.finished | (jnp.sum((em == eos_id) & commit, axis=1) > 0)
        adv = jnp.where(active, d_commit + 1, 0)
        t_cache = t_cache._replace(lengths=L_t + adv)
        d_cache = d_cache._replace(lengths=L_d + adv)
        pending = jnp.where(active & ~new_finished, e, s.pending)
        return _SpecState(
            pending=pending,
            t_cache=t_cache,
            d_cache=d_cache,
            out=out,
            n_emit=s.n_emit + n_commit,
            finished=new_finished,
            mask=mask,
            rng=rng,
            conf_sum=conf_sum,
            accepted=s.accepted + jnp.sum(jnp.where(active, n, 0)),
            proposed=s.proposed + gamma * jnp.sum(active.astype(jnp.int32)),
            rounds=s.rounds + 1,
        )

    return body


@partial(jax.jit, static_argnums=(0, 1, 4, 5, 6, 7, 8, 9, 12, 13))
def _spec_rounds(
    cfg_t: ModelConfig,
    cfg_d: ModelConfig,
    params_t,
    params_d,
    sampling: SamplingParams,
    gamma: int,
    max_new: int,
    eos_id: int,
    vocab: int,
    cap: int,
    state: _SpecState,
    budget: jax.Array,  # [] int32 — run at most this many MORE rounds
    verify_fn=forward_verify,
    decode_fn=forward_decode,
) -> _SpecState:
    """Advance the acceptance loop until every row is done or ``budget``
    additional rounds have run. ``budget = max_new`` runs to completion (a
    round always commits ≥1 token per active row); small budgets are the
    streaming segments."""
    body = _make_spec_body(
        cfg_t, cfg_d, params_t, params_d, sampling, gamma, max_new, eos_id,
        vocab, cap, verify_fn, decode_fn,
    )
    until = state.rounds + budget

    def cond(s: _SpecState):
        return (~jnp.all(s.finished | (s.n_emit >= max_new))) & (s.rounds < until)

    return jax.lax.while_loop(cond, body, state)


def generate_speculative(
    cfg_target: ModelConfig,
    params_target,
    cfg_draft: ModelConfig,
    params_draft,
    tokens: jax.Array,  # [b, s] right-padded prompts
    lengths: jax.Array,  # [b]
    sampling: SamplingParams,
    gamma: int = 4,
    eos_id: int = -1,
    rng: jax.Array | None = None,
    kv_backend: str = "dense",
    page_size: int = 64,
) -> tuple[GenerateResult, SpecStats]:
    """Speculative decode: emits the target's distribution exactly, several
    tokens per verify chunk when the draft agrees. Both models must share a
    tokenizer/vocab (standard speculative constraint). ``kv_backend="paged"``
    runs both caches as page pools (serving memory model; same tokens)."""
    verify_fn, decode_fn = _spec_fns(kv_backend)
    state, wall_sw, prefill_s = _spec_prefill(
        cfg_target, params_target, cfg_draft, params_draft, tokens, lengths,
        sampling, gamma, eos_id, rng, kv_backend, page_size,
    )
    from edgemesh.utils.platform import device_sync
    from edgemesh.utils.tracing import trace

    batch, prompt_len = tokens.shape
    max_new = int(sampling.max_new_tokens)
    cap = max_new + gamma + 1
    # Ambient compute ledger (obs/compute.py): the benches wrap this call
    # in a ledger_scope so the fused round loop lands in the launch ledger
    # as the spec_rounds boundary — measure=True because the fence below
    # is paid regardless.
    from edgemesh.obs.compute import ambient_ledger

    led = ambient_ledger()
    with trace("edgemesh/spec_decode") as decode_t:
        # A round commits >=1 token per active row, so max_new rounds always
        # run to completion.
        spec_args = (
            cfg_target, cfg_draft, params_target, params_draft, sampling,
            int(gamma), max_new, int(eos_id), cfg_target.vocab_size, cap,
            state, jnp.asarray(max_new, jnp.int32), verify_fn, decode_fn,
        )
        if led is not None:
            final = led.launch(
                "spec_rounds", _spec_rounds, *spec_args,
                key=f"b{batch}n{max_new}", tokens=batch * max_new,
                measure=True,
            )
        else:
            final = _spec_rounds(*spec_args)
        device_sync(final.out)
    # Snapshot HERE — the jnp.sum readback below is bookkeeping, not
    # generation, and must not deflate tokens_per_sec.
    wall = wall_sw.elapsed()

    n_gen = jnp.minimum(final.n_emit, max_new)
    confidence = final.conf_sum / jnp.maximum(final.n_emit, 1)
    total = int(jnp.sum(n_gen))
    decode_s = decode_t.elapsed_s
    stats = SpecStats(
        proposed=int(final.proposed), accepted=int(final.accepted),
        rounds=int(final.rounds),
    )
    return (
        GenerateResult(
            tokens=final.out[:, :max_new],
            num_generated=n_gen,
            prefill_time_s=prefill_s,
            decode_time_s=decode_s,
            tokens_per_sec=total / wall if wall > 0 else 0.0,
            decode_tok_s=(total - batch) / decode_s if decode_s > 0 else 0.0,
            confidence=confidence,
        ),
        stats,
    )


def _spec_fns(kv_backend: str):
    """(verify_fn, decode_fn) for a cache backend. The paged pair serves
    both fp and int8 pools — forward_verify_paged/forward_decode_paged
    dispatch on the cache pytree type at trace time, so ``paged_int8``
    needs no separate functions, only an int8 pool from the caller."""
    if kv_backend == "dense":
        return forward_verify, forward_decode
    if kv_backend in ("paged", "paged_int8"):
        from edgemesh.runtime.paged_generate import (
            forward_decode_paged,
            forward_verify_paged,
        )

        return forward_verify_paged, forward_decode_paged
    raise ValueError(
        f"unknown kv_backend {kv_backend!r} (dense | paged | paged_int8)"
    )


def _spec_prefill(
    cfg_target, params_target, cfg_draft, params_draft, tokens, lengths,
    sampling, gamma, eos_id, rng, kv_backend="dense", page_size=64,
):
    """Validation + both prefills + initial loop state (shared by the
    run-to-completion and streaming entries). Returns
    ``(state, wall_stopwatch, prefill_s)`` — the stopwatch starts at entry
    so callers can read the end-to-end window off it (EM107: timing flows
    through utils.tracing, not raw clock reads). ``kv_backend="paged"``
    holds BOTH models' caches as page pools (runtime/paged_kv.py) — the
    serving memory model under speculative decoding."""
    if cfg_target.vocab_size != cfg_draft.vocab_size:
        raise ValueError(
            f"draft vocab {cfg_draft.vocab_size} != target vocab "
            f"{cfg_target.vocab_size}; speculative decoding needs a shared vocab"
        )
    if sampling.do_sample and not 0 < sampling.top_k < cfg_target.vocab_size:
        raise ValueError(
            "speculative sampling needs bounded support: set top_k in [1, vocab)"
        )
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    batch, prompt_len = tokens.shape
    max_new = int(sampling.max_new_tokens)
    needed = prompt_len + max_new + gamma + 1  # chunk overshoot headroom
    for cfg in (cfg_target, cfg_draft):
        if needed > cfg.max_seq_len:
            raise ValueError(
                f"prompt {prompt_len} + max_new {max_new} + gamma overshoot "
                f"{gamma + 1} exceeds max_seq_len {cfg.max_seq_len}"
            )
    rng = rng if rng is not None else jax.random.PRNGKey(sampling.seed)

    from edgemesh.utils.platform import device_sync
    from edgemesh.utils.tracing import Stopwatch, trace

    wall_sw = Stopwatch()
    with trace("edgemesh/spec_prefill") as prefill_t:
        if kv_backend in ("paged", "paged_int8"):
            from edgemesh.runtime.paged_generate import forward_prefill_paged
            from edgemesh.runtime.paged_kv import (
                init_paged_cache,
                init_quant_paged_cache,
            )

            per_row = -(-needed // page_size)
            init = (
                init_quant_paged_cache if kv_backend == "paged_int8"
                else init_paged_cache
            )

            def make(cfg):
                return init(
                    cfg, batch, total_pages=1 + batch * per_row,
                    page_size=page_size, max_pages=per_row,
                )

            t_cache = make(cfg_target)
            d_cache = make(cfg_draft)
            first_logits, t_cache = forward_prefill_paged(
                cfg_target, params_target, tokens, lengths, t_cache
            )
            _, d_cache = forward_prefill_paged(
                cfg_draft, params_draft, tokens, lengths, d_cache
            )
        else:
            t_cache = init_kv_cache(cfg_target, batch, needed)
            d_cache = init_kv_cache(cfg_draft, batch, needed)
            first_logits, t_cache = forward_prefill(cfg_target, params_target, tokens, lengths, t_cache)
            _, d_cache = forward_prefill(cfg_draft, params_draft, tokens, lengths, d_cache)
        device_sync(first_logits)

    valid = jnp.arange(prompt_len)[None, :] < lengths[:, None]
    mask = TokenMaskState.init(batch, cfg_target.vocab_size).add_sequence(tokens, valid).mask
    state = _spec_init(
        sampling, int(gamma), max_new, int(eos_id), first_logits,
        t_cache, d_cache, mask, rng,
    )
    return state, wall_sw, prefill_t.elapsed_s


def generate_speculative_stream(
    cfg_target: ModelConfig,
    params_target,
    cfg_draft: ModelConfig,
    params_draft,
    tokens: jax.Array,  # [b, s] right-padded prompts
    lengths: jax.Array,  # [b]
    sampling: SamplingParams,
    gamma: int = 4,
    eos_id: int = -1,
    rng: jax.Array | None = None,
    rounds_per_segment: int = 4,
    kv_backend: str = "dense",
    page_size: int = 64,
):
    """Streaming speculative decode: yields ``runtime.stream.StreamChunk``
    records as verify rounds commit tokens, then a final ``(GenerateResult,
    SpecStats)`` is available via the generator's ``value`` (StopIteration)
    — or use :func:`edgemesh.agents.Agent.answer_stream`, which consumes
    this and yields text deltas.

    Each segment runs up to ``rounds_per_segment`` draft→verify rounds in
    ONE jitted program (the same ``_spec_rounds`` while_loop as the
    non-streamed path, budget-bounded), so acceptance-dependent variable
    emission arrives chunk by chunk with one host round-trip per segment.
    The emitted sequence is the target's distribution exactly; under greedy
    decoding it is token-for-token the plain greedy output.

    The final GenerateResult's decode timing accumulates DEVICE time across
    segments only — consumer time between yields (a slow SSE client) does
    not deflate the reported tokens/sec."""
    import numpy as np

    from edgemesh.runtime.stream import StreamChunk
    from edgemesh.utils.platform import device_sync
    from edgemesh.utils.tracing import trace

    if rounds_per_segment < 1:
        raise ValueError(f"rounds_per_segment must be >= 1, got {rounds_per_segment}")
    verify_fn, decode_fn = _spec_fns(kv_backend)
    state, wall_sw, prefill_s = _spec_prefill(
        cfg_target, params_target, cfg_draft, params_draft, tokens, lengths,
        sampling, gamma, eos_id, rng, kv_backend, page_size,
    )
    batch, _ = tokens.shape
    max_new = int(sampling.max_new_tokens)
    cap = max_new + gamma + 1
    emitted = np.zeros((batch,), np.int32)
    decode_s = 0.0
    while True:
        with trace("edgemesh/spec_decode") as seg_t:
            state = _spec_rounds(
                cfg_target, cfg_draft, params_target, params_draft, sampling,
                int(gamma), max_new, int(eos_id), cfg_target.vocab_size, cap,
                state, jnp.asarray(int(rounds_per_segment), jnp.int32),
                verify_fn, decode_fn,
            )
            device_sync(state.out)
        decode_s += seg_t.elapsed_s
        n_emit = np.minimum(np.asarray(state.n_emit), max_new)
        out = np.asarray(state.out)
        new = n_emit - emitted
        width = int(new.max()) if new.size else 0
        seg = np.full((batch, max(width, 1)), eos_id, np.int32)
        for b in range(batch):
            seg[b, : new[b]] = out[b, emitted[b] : n_emit[b]]
        finished = np.asarray(state.finished) | (n_emit >= max_new)
        yield StreamChunk(
            tokens=jnp.asarray(seg),
            counts=jnp.asarray(new),
            finished=jnp.asarray(finished),
            elapsed_s=wall_sw.elapsed(),
        )
        emitted = n_emit
        if bool(finished.all()):
            break

    n_gen = jnp.minimum(state.n_emit, max_new)
    confidence = state.conf_sum / jnp.maximum(state.n_emit, 1)
    total = int(np.sum(np.asarray(n_gen)))
    wall = prefill_s + decode_s  # device time only, not consumer stalls
    return (
        GenerateResult(
            tokens=state.out[:, :max_new],
            num_generated=n_gen,
            prefill_time_s=prefill_s,
            decode_time_s=decode_s,
            tokens_per_sec=total / wall if wall > 0 else 0.0,
            decode_tok_s=(total - batch) / decode_s if decode_s > 0 else 0.0,
            confidence=confidence,
        ),
        SpecStats(proposed=int(state.proposed), accepted=int(state.accepted),
                  rounds=int(state.rounds)),
    )
