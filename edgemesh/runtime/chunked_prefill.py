"""Chunked prefill: process long prompts in fixed-size cache appends.

One-shot prefill materializes full-sequence logits [b, s, vocab] — at
s=2048, b=8, a 128k vocab that is ~4 GB of HBM for activations that are
thrown away (only the last real token's row seeds decode). Chunked prefill
runs the prompt through ``transformer.forward_verify`` (the same
cache-append forward the speculative verifier and prefix cache use) in
fixed ``chunk``-sized pieces: peak logits memory is chunk×vocab, and the
compile cache holds ONE program per chunk size instead of one per
prompt-length bucket.

Numerics: identical math to one-shot prefill up to reduction order (each
chunk's queries attend the cache + the in-chunk prefix — the same mask),
with the XLA attention path (the flash kernel is a prefill-only kernel; for
chunked appends the dense-cache attend applies). Ragged batches hold the
usual invariant: pad-position queries produce discarded rows, garbage KV
slots beyond a row's true length sit outside every real query's causal
horizon and are overwritten by decode.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from edgemesh.config import SamplingParams
from edgemesh.models.transformer import KVCache, ModelConfig, forward_verify
from edgemesh.runtime.generate import GenerateResult, generate


def prefill_chunked(
    cfg: ModelConfig,
    params,
    tokens: jnp.ndarray,  # [b, s] right-padded prompts
    lengths: jnp.ndarray,  # [b]
    cache: KVCache,
    chunk: int = 256,
) -> tuple[jnp.ndarray, KVCache]:
    """forward_prefill's contract (last-real-token logits + filled cache),
    executed as ceil(s/chunk) cache appends."""
    b, s = tokens.shape
    if cache.k.shape[2] < s:
        raise ValueError(f"cache capacity {cache.k.shape[2]} < prompt width {s}")
    last = jnp.zeros((b, cfg.vocab_size), jnp.float32)
    cache = KVCache(cache.k, cache.v, jnp.zeros((b,), jnp.int32))
    for off in range(0, s, chunk):
        m = min(chunk, s - off)
        seg = jax.lax.slice_in_dim(tokens, off, off + m, axis=1)
        logits, cache = forward_verify(cfg, params, seg, cache)
        # Rows whose last real token falls inside this chunk take its logits.
        idx = jnp.clip(lengths - 1 - off, 0, m - 1)
        in_chunk = (lengths - 1 >= off) & (lengths - 1 < off + m)
        picked = logits[jnp.arange(b), idx].astype(jnp.float32)
        last = jnp.where(in_chunk[:, None], picked, last)
    return last.astype(logits.dtype), KVCache(cache.k, cache.v, lengths)


def generate_chunked_prefill(
    cfg: ModelConfig,
    params,
    tokens: jax.Array,
    lengths: jax.Array,
    sampling: SamplingParams,
    eos_id: int = -1,
    rng: jax.Array | None = None,
    cache: KVCache | None = None,
    prefill_chunk: int = 256,
) -> GenerateResult:
    """generate() with the chunked prefill plugged in (decode unchanged)."""
    if prefill_chunk < 1:
        raise ValueError(f"prefill_chunk must be >= 1, got {prefill_chunk}")

    def prefill_fn(cfg, params, tokens, lengths, cache):
        return prefill_chunked(cfg, params, tokens, lengths, cache, prefill_chunk)

    return generate(
        cfg, params, tokens, lengths, sampling, eos_id=eos_id, rng=rng,
        cache=cache, prefill_fn=prefill_fn,
    )
