"""Inference runtime: generation engine, batching, timing."""

from edgemesh.runtime.generate import GenerateResult, generate  # noqa: F401
