"""ctypes bindings for the native runtime library (native/edgemesh_native.cpp).

Provides the framework's own native data loader (RFC-4180 CSV) and byte-level
BPE tokenizer — the capabilities the reference outsources to pandas' C engine
(``Code/C-DAC Server/try.py:292``) and HF's Rust tokenizers
(``combiner_fp.py:276``). The library is built lazily with ``make -C native``
on first use; every entry point degrades gracefully to pure Python when no
compiler or library is available, so nothing here is a hard dependency.
"""

from __future__ import annotations

import ctypes
import logging
import subprocess
import threading
from pathlib import Path

log = logging.getLogger("edgemesh.native")

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libedgemesh_native.so"

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_tried = False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.em_csv_open.restype = ctypes.c_void_p
    lib.em_csv_open.argtypes = [ctypes.c_char_p]
    lib.em_csv_rows.restype = ctypes.c_long
    lib.em_csv_rows.argtypes = [ctypes.c_void_p]
    lib.em_csv_cols.restype = ctypes.c_long
    lib.em_csv_cols.argtypes = [ctypes.c_void_p, ctypes.c_long]
    lib.em_csv_cell.restype = ctypes.c_void_p  # char*; sliced via ctypes.string_at
    lib.em_csv_cell.argtypes = [
        ctypes.c_void_p, ctypes.c_long, ctypes.c_long, ctypes.POINTER(ctypes.c_long),
    ]
    lib.em_csv_close.argtypes = [ctypes.c_void_p]

    lib.em_bpe_open.restype = ctypes.c_void_p
    lib.em_bpe_open.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.em_bpe_vocab_size.restype = ctypes.c_long
    lib.em_bpe_vocab_size.argtypes = [ctypes.c_void_p]
    lib.em_bpe_token_id.restype = ctypes.c_long
    lib.em_bpe_token_id.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.em_bpe_encode.restype = ctypes.c_long
    lib.em_bpe_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_long,
    ]
    lib.em_bpe_decode.restype = ctypes.c_long
    lib.em_bpe_decode.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_long,
        ctypes.c_char_p, ctypes.c_long,
    ]
    lib.em_bpe_close.argtypes = [ctypes.c_void_p]
    return lib


def load_native() -> ctypes.CDLL | None:
    """Load (building if needed) the native library; None when unavailable."""
    global _lib, _lib_tried
    with _lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        if not _LIB_PATH.exists() and (_NATIVE_DIR / "Makefile").exists():
            try:
                # Building under _lock is the point: a second caller must
                # WAIT for the one build (then find _lib/_lib_tried set),
                # not race a concurrent `make` over the same .so. Reviewed
                # blocking-under-lock, not an oversight.
                subprocess.run(  # edgelint: disable=EM303
                    ["make", "-C", str(_NATIVE_DIR)], check=True,
                    capture_output=True, timeout=120,
                )
            except Exception as exc:  # no compiler / make failure → fallback
                log.info("native build unavailable (%s); using pure Python", exc)
                return None
        if not _LIB_PATH.exists():
            return None
        try:
            _lib = _configure(ctypes.CDLL(str(_LIB_PATH)))
        except OSError as exc:  # wrong arch, truncated build, ...
            log.warning("failed to load %s: %s", _LIB_PATH, exc)
            _lib = None
        return _lib


class NativeCSV:
    """Parsed CSV file held in native memory; cells decoded on access."""

    def __init__(self, path: str | Path):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.em_csv_open(str(path).encode())
        if not self._h:
            raise FileNotFoundError(path)

    @property
    def num_rows(self) -> int:
        return int(self._lib.em_csv_rows(self._h))

    def num_cols(self, row: int) -> int:
        return int(self._lib.em_csv_cols(self._h, row))

    def cell(self, row: int, col: int) -> str:
        ln = ctypes.c_long()
        ptr = self._lib.em_csv_cell(self._h, row, col, ctypes.byref(ln))
        if not ptr:
            raise IndexError((row, col))
        return ctypes.string_at(ptr, ln.value).decode("utf-8", errors="replace")

    def header(self) -> list[str]:
        return [self.cell(0, c) for c in range(self.num_cols(0))]

    def close(self):
        if self._h:
            self._lib.em_csv_close(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


class NativeBPE:
    """GPT-2-format byte-level BPE tokenizer backed by the C++ engine.

    Satisfies the same protocol as models.tokenizer.HFTokenizer
    (vocab_size / eos_id / pad_id / encode / decode), loading the standard
    ``vocab.json`` + ``merges.txt`` pair from a checkpoint directory.
    """

    def __init__(self, path: str | Path, eos_token: str = "<|endoftext|>"):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        p = Path(path)
        vocab = p / "vocab.json" if p.is_dir() else p
        merges = p / "merges.txt" if p.is_dir() else p.parent / "merges.txt"
        self._h = lib.em_bpe_open(str(vocab).encode(), str(merges).encode())
        if not self._h:
            raise FileNotFoundError(f"vocab/merges not loadable under {path}")
        eos = int(lib.em_bpe_token_id(self._h, eos_token.encode()))
        self._eos = eos if eos >= 0 else int(lib.em_bpe_vocab_size(self._h)) - 1

    @property
    def vocab_size(self) -> int:
        return int(self._lib.em_bpe_vocab_size(self._h))

    @property
    def eos_id(self) -> int:
        return self._eos

    @property
    def pad_id(self) -> int:
        return self._eos  # GPT-2-family convention: pad with EOS

    def encode(self, text: str, max_len: int | None = None) -> list[int]:
        data = text.encode("utf-8")
        cap = max(len(data) + 8, 16)
        buf = (ctypes.c_int32 * cap)()
        n = int(self._lib.em_bpe_encode(self._h, data, len(data), buf, cap))
        if n < 0:
            raise RuntimeError(f"native BPE encode failed (rc={n}) for {len(data)}-byte input")
        ids = list(buf[: min(n, cap)])
        if max_len is not None:
            ids = ids[: max(0, max_len)]
        return ids

    def decode(self, ids) -> str:
        ids = [int(i) for i in ids]
        arr = (ctypes.c_int32 * max(len(ids), 1))(*ids)
        cap = 16 * len(ids) + 16
        out = ctypes.create_string_buffer(cap)
        n = int(self._lib.em_bpe_decode(self._h, arr, len(ids), out, cap))
        if n > cap:  # retry with the exact size the library reported
            cap = n
            out = ctypes.create_string_buffer(cap)
            n = int(self._lib.em_bpe_decode(self._h, arr, len(ids), out, cap))
        if n < 0:
            raise RuntimeError(f"native BPE decode failed (rc={n}) for {len(ids)} ids")
        return out.raw[: min(n, cap)].decode("utf-8", errors="replace")

    def close(self):
        if self._h:
            self._lib.em_bpe_close(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
