"""Block-paged HBM-resident KV cache — the HeadInfer analog (BASELINE.json
configs[3], SURVEY.md §5.7).

HeadInfer scales context on small GPUs by offloading KV heads to host DRAM;
the TPU reinterpretation keeps the cache HBM-resident, paged, and head-wise
sharded: page arrays are laid out page-major ``[layers, pages, kv_heads,
page_size, head_dim]`` so one physical page holds every kv head's slice
contiguously — the paged-attention kernel then fetches a whole page in ONE
kh·ps·hd DMA per grid step (the r2 head-major layout forced kh separate
ps·hd DMAs, ~8 KB each, too small for HBM bandwidth) — and a
``P(None, None, "tp")`` sharding still slices the pool head-wise per chip.
The kernel walks each sequence's page table instead of a dense
``[b, max_seq]`` slab.

Everything here is functional and statically shaped so the decode loop jits
once (the design rule the whole runtime follows, models/transformer.py):

- ``PagedKVCache`` carries the page arrays, one page table shared by all
  layers, per-row lengths, and the free-page stack.
- Physical page 0 is the TRASH page: writes for padded/invalid positions land
  there, reads of unallocated table slots DMA it harmlessly (always masked).
- ``allocate`` pops pages for rows that need them — callable INSIDE a scanned
  decode step (pure array ops, no data-dependent shapes).

The reference has no cache management at all — HF ``generate`` reallocates
per call (``Code/C-DAC Server/combiner_fp.py:338-347``); this module is what
lets one preallocated HBM pool serve many variable-length sequences.
"""

from __future__ import annotations

import struct
from functools import partial
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from edgemesh.models.transformer import ModelConfig
from edgemesh.utils.bucketing import POW2_FLOOR, bucket_pow2


class PagedKVCache(NamedTuple):
    """k/v: [L, total_pages, kv_heads, page_size, head_dim].

    ``page_table``: [b, max_pages] int32 — physical page of each logical page
    (0 = unallocated → trash page). ``lengths``: [b] tokens written per row.
    ``free_stack``: [total_pages] int32 physical page ids; ``free_top`` is the
    next unpopped stack index (monotone within one batch's lifetime; the host
    rebuilds the stack between serving batches).
    """

    k: jnp.ndarray
    v: jnp.ndarray
    page_table: jnp.ndarray
    lengths: jnp.ndarray
    free_stack: jnp.ndarray
    free_top: jnp.ndarray

    @property
    def page_size(self) -> int:
        return self.k.shape[3]

    @property
    def max_pages(self) -> int:
        return self.page_table.shape[1]


def init_paged_cache(
    cfg: ModelConfig,
    batch: int,
    total_pages: int,
    page_size: int = 64,
    max_pages: int | None = None,
    dtype=None,
) -> PagedKVCache:
    """Preallocate the page pool. ``total_pages`` includes the trash page."""
    dtype = dtype or cfg.activation_dtype
    max_pages = max_pages or (cfg.max_seq_len + page_size - 1) // page_size
    shape = (cfg.num_layers, total_pages, cfg.num_kv_heads, page_size, cfg.head_size)
    return PagedKVCache(
        k=jnp.zeros(shape, dtype),
        v=jnp.zeros(shape, dtype),
        page_table=jnp.zeros((batch, max_pages), jnp.int32),
        lengths=jnp.zeros((batch,), jnp.int32),
        free_stack=jnp.arange(total_pages, dtype=jnp.int32),  # entry 0 = trash
        free_top=jnp.asarray(1, jnp.int32),  # skip the trash page
    )


class QuantPagedKVCache(NamedTuple):
    """Int8 page pool: k/v int8 [L, P, kh, ps, hd]; k_scale/v_scale fp32
    [L, P, kh, 1, ps] (one symmetric absmax scale per written token row,
    runtime/quant_kv.quantize_kv — the [·, 1, ps] shape keeps the kernel's
    per-page scale read a 2D [1, ps] vector). Halves the page-walk DMA bytes
    on top of the page-major layout, marrying the two long-context levers
    (SURVEY.md §5.7: HeadInfer-analog paging + int8 KV). Table/length/free
    bookkeeping is identical to PagedKVCache, so allocate()/pages_needed()
    serve both."""

    k: jnp.ndarray
    v: jnp.ndarray
    k_scale: jnp.ndarray
    v_scale: jnp.ndarray
    page_table: jnp.ndarray
    lengths: jnp.ndarray
    free_stack: jnp.ndarray
    free_top: jnp.ndarray

    @property
    def page_size(self) -> int:
        return self.k.shape[3]

    @property
    def max_pages(self) -> int:
        return self.page_table.shape[1]


def init_quant_paged_cache(
    cfg: ModelConfig,
    batch: int,
    total_pages: int,
    page_size: int = 64,
    max_pages: int | None = None,
) -> QuantPagedKVCache:
    """Preallocate the int8 page pool. ``total_pages`` includes the trash page."""
    max_pages = max_pages or (cfg.max_seq_len + page_size - 1) // page_size
    shape = (cfg.num_layers, total_pages, cfg.num_kv_heads, page_size, cfg.head_size)
    sshape = (cfg.num_layers, total_pages, cfg.num_kv_heads, 1, page_size)
    return QuantPagedKVCache(
        k=jnp.zeros(shape, jnp.int8),
        v=jnp.zeros(shape, jnp.int8),
        k_scale=jnp.zeros(sshape, jnp.float32),
        v_scale=jnp.zeros(sshape, jnp.float32),
        page_table=jnp.zeros((batch, max_pages), jnp.int32),
        lengths=jnp.zeros((batch,), jnp.int32),
        free_stack=jnp.arange(total_pages, dtype=jnp.int32),
        free_top=jnp.asarray(1, jnp.int32),
    )


def page_nbytes(cache) -> int:
    """Device bytes ONE pool page occupies across every pool-shaped array
    in the cache — k/v (and the quant pools' scale planes), summed over
    layers. This is the price the memory observatory (obs/memory.py) uses
    to reconcile its page ledger against the device's own ``memory_stats``
    bytes-in-use, so ledger-vs-HBM drift is a reported number.

    Works on any paged cache NamedTuple: a field counts as pool-shaped
    when its second axis is the pool axis (``k.shape[1]`` pages);
    per-row bookkeeping (tables, lengths, free stack) is excluded.
    """
    total_pages = int(cache.k.shape[1])
    nbytes = 0
    for arr in cache:
        shape = getattr(arr, "shape", ())
        if len(shape) >= 4 and int(shape[1]) == total_pages:
            nbytes += (int(arr.size) // total_pages) * int(arr.dtype.itemsize)
    return nbytes


def pool_overflowed(cache: PagedKVCache) -> bool:
    """Host-side overflow check: True if any allocate() ran past the free
    stack. Those rows were handed the trash page — their KV beyond the
    overflow point is invalid and results must be discarded."""
    return int(cache.free_top) > cache.free_stack.shape[0]


def pages_needed(lengths: jnp.ndarray, new_tokens: jnp.ndarray, page_size: int) -> jnp.ndarray:
    """How many fresh pages each row needs to hold ``new_tokens`` more tokens."""
    have = (lengths + page_size - 1) // page_size
    want = (lengths + new_tokens + page_size - 1) // page_size
    return want - have


def allocate(cache: PagedKVCache, n_pages: jnp.ndarray) -> PagedKVCache:
    """Pop pages so row i's next ``n_pages[i]`` logical slots are backed.

    Statically bounded by ``max_pages`` logical slots per row; pure
    elementwise ops, so it runs inside a jitted/scanned decode step. A
    target slot that ALREADY maps a physical page keeps it and pops nothing
    — this makes allocation idempotent under REWIND (speculative decoding
    lowers ``lengths`` past pages it already owns; re-advancing must reuse
    them, not leak them and orphan stack entries). Exhausting the pool hands
    out the trash page (physical 0) for the overflowing slots —
    jit-compatible, no branch — but the overflow is RECORDED: ``free_top``
    keeps advancing past the stack size, so ``pool_overflowed(cache)`` is
    True afterwards. Callers either bound capacity up front (generate()
    validates prompt+max_new against the pool) or assert ``pool_overflowed``
    host-side after their loop.
    """
    b, max_pages = cache.page_table.shape
    n_pages = n_pages.astype(jnp.int32)
    have = (cache.lengths + cache.page_size - 1) // cache.page_size  # filled slots

    j = jnp.arange(max_pages)[None, :]  # logical slot index
    target = (j >= have[:, None]) & (j < (have + n_pages)[:, None])
    need = target & (cache.page_table == 0)  # skip slots that kept a page
    # Pop order: row-major over needed slots.
    flat = need.reshape(-1)
    order = jnp.cumsum(flat.astype(jnp.int32)) - 1  # pop index per needed slot
    src = (cache.free_top + order).reshape(b, max_pages)
    total = cache.free_stack.shape[0]
    pages = jnp.where(
        need & (src < total), cache.free_stack[jnp.minimum(src, total - 1)], 0
    )
    table = jnp.where(need, pages, cache.page_table)
    return cache._replace(
        page_table=table, free_top=cache.free_top + jnp.sum(need)
    )


def _token_slots(page_table, start, valid_len, s, ps):
    """(pp, ss) [b·s] physical page / in-page slot per token; invalid → trash."""
    b = page_table.shape[0]
    t = jnp.arange(s)[None, :]  # [1, s]
    pos = start[:, None] + t  # absolute position [b, s]
    logical = pos // ps
    slot = pos % ps
    valid = t < valid_len[:, None]
    max_pages = page_table.shape[1]
    phys = jnp.take_along_axis(
        page_table, jnp.minimum(logical, max_pages - 1), axis=1
    )  # [b, s]
    pp = jnp.where(valid, phys, 0).reshape(b * s)  # invalid → trash page
    ss = slot.reshape(b * s)
    return pp, ss


def write_tokens(
    k_pages: jnp.ndarray,  # [P, kh, ps, hd] one layer's pages
    v_pages: jnp.ndarray,
    k: jnp.ndarray,  # [b, s, kh, hd] new keys (roped)
    v: jnp.ndarray,
    page_table: jnp.ndarray,  # [b, max_pages]
    start: jnp.ndarray,  # [b] first token position to write
    valid_len: jnp.ndarray,  # [b] number of real tokens in k/v per row
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter s tokens per row into their pages; invalid tokens → trash page.

    One scatter per array, token-indexed on (page, slot): all kv heads of a
    token land in its page's contiguous [kh, ·, hd] stripe (page-major
    layout, module docstring)."""
    b, s, kh, hd = k.shape
    ps = k_pages.shape[2]
    pp, ss = _token_slots(page_table, start, valid_len, s, ps)
    return (
        k_pages.at[pp, :, ss, :].set(k.reshape(b * s, kh, hd).astype(k_pages.dtype)),
        v_pages.at[pp, :, ss, :].set(v.reshape(b * s, kh, hd).astype(v_pages.dtype)),
    )


def write_tokens_quant(
    k_pages: jnp.ndarray,  # [P, kh, ps, hd] int8, one layer's pages
    v_pages: jnp.ndarray,
    k_scales: jnp.ndarray,  # [P, kh, 1, ps] f32
    v_scales: jnp.ndarray,
    k_q: jnp.ndarray,  # [b, s, kh, hd] int8 new keys (quantize_kv output)
    k_s: jnp.ndarray,  # [b, s, kh] f32 per-row scales
    v_q: jnp.ndarray,
    v_s: jnp.ndarray,
    page_table: jnp.ndarray,
    start: jnp.ndarray,
    valid_len: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Scatter pre-quantized token rows + scales into the int8 page pool.

    Takes runtime/quant_kv.quantize_kv outputs rather than quantizing here:
    the prefill path also needs the int8 roundtrip of the fresh k/v for its
    attend (paged_generate._paged_attention), so quantization happens exactly
    once at the call site."""
    b, s, kh, hd = k_q.shape
    ps = k_pages.shape[2]
    pp, ss = _token_slots(page_table, start, valid_len, s, ps)
    return (
        k_pages.at[pp, :, ss, :].set(k_q.reshape(b * s, kh, hd)),
        v_pages.at[pp, :, ss, :].set(v_q.reshape(b * s, kh, hd)),
        k_scales.at[pp, :, 0, ss].set(k_s.reshape(b * s, kh).astype(k_scales.dtype)),
        v_scales.at[pp, :, 0, ss].set(v_s.reshape(b * s, kh).astype(v_scales.dtype)),
    )


# -- cross-replica KV wire format --------------------------------------------
#
# A request's committed pages serialized for transfer between replicas — the
# seam prefill/decode disaggregation and the fleet's shared prefix cache ride
# (docs/FLEET.md "Tiered serving and KV streaming"). One opaque blob:
#
#   header  | ids (int32 × tokens) | k pages | v pages [| k_scale | v_scale]
#
# The fixed little-endian header pins the pool geometry (layers, kv heads,
# page size, head dim) and precision kind, so an importer can refuse a
# payload from a mismatched model BEFORE touching the device, and a version
# bump never silently misparses old payloads. ``ids`` are the token ids whose
# KV the pages hold: the importer matches them against ITS OWN tokenization
# of the request (runtime/prefix_cache.common_token_prefix) and uses only the
# matched prefix — a payload can never graft wrong-token KV onto a prompt,
# tokenizer drift just shortens the match. Page payloads are page-major
# [L, n, kh, ps, hd] exactly as pooled, so import is one scatter per array.

KV_WIRE_MAGIC = b"EMKV"
KV_WIRE_VERSION = 1
_WIRE_HEADER = struct.Struct("<4sHBBHHHHII")
#: ``kind`` byte: the pool's element precision. int8 implies the payload
#: also carries the per-token scale planes; the float kinds cover every
#: activation_dtype the unquantized pool is built with.
_KIND_BF16 = 0
_KIND_INT8 = 1
_KIND_F32 = 2
_KIND_F16 = 3
_KIND_BY_DTYPE = {
    "bfloat16": _KIND_BF16, "int8": _KIND_INT8,
    "float32": _KIND_F32, "float16": _KIND_F16,
}


class KVWireError(ValueError):
    """A KV transfer payload that cannot be imported: corrupt, truncated,
    version-mismatched, or from an incompatible pool geometry. Gateways map
    this to a structured 400 (client/peer input, never a 500)."""


class KVWirePayload(NamedTuple):
    """Decoded transfer payload: header fields + host-side page arrays."""

    kind: int  # _KIND_BF16 | _KIND_INT8
    layers: int
    kv_heads: int
    page_size: int
    head_dim: int
    n_pages: int
    tokens: int  # committed token count the pages hold
    ids: np.ndarray  # [tokens] int32 — the tokens' ids
    k: np.ndarray  # [L, n_pages, kh, ps, hd]
    v: np.ndarray
    k_scale: np.ndarray | None  # int8 pools: [L, n_pages, kh, 1, ps] f32
    v_scale: np.ndarray | None


def _pool_kind(cache) -> int:
    name = jnp.dtype(cache.k.dtype).name
    try:
        return _KIND_BY_DTYPE[name]
    except KeyError:
        raise KVWireError(f"pool dtype {name!r} has no wire encoding") from None


def _wire_np_dtype(kind: int):
    if kind == _KIND_INT8:
        return np.int8
    if kind == _KIND_F32:
        return np.float32
    if kind == _KIND_F16:
        return np.float16
    import ml_dtypes

    return ml_dtypes.bfloat16


def export_pages(cache, pages: Sequence[int], tokens: int, ids) -> bytes:
    """Serialize ``tokens`` committed tokens living in physical ``pages`` of
    ``cache`` (in logical order) into the wire format. ``ids`` are those
    tokens' ids (length == tokens). Zero-token exports are legal (header +
    empty payload). The page gather pads onto the pow2 ladder (trash page)
    so export shapes key a bounded compile set."""
    ids = np.asarray(ids, np.int32).reshape(-1)
    tokens = int(tokens)
    pages = [int(p) for p in pages]
    if tokens < 0 or ids.size != tokens:
        raise ValueError(
            f"export_pages: ids carries {ids.size} tokens, header says {tokens}"
        )
    ps = cache.page_size
    if tokens > len(pages) * ps:
        raise ValueError(
            f"export_pages: {tokens} tokens do not fit {len(pages)} pages "
            f"of {ps}"
        )
    kind = _pool_kind(cache)
    L, _, kh, _, hd = cache.k.shape
    header = _WIRE_HEADER.pack(
        KV_WIRE_MAGIC, KV_WIRE_VERSION, kind, 0, L, kh, ps, hd,
        len(pages), tokens,
    )
    parts = [header, ids.tobytes()]
    if pages:
        n = len(pages)
        padded = bucket_pow2(n, floor=POW2_FLOOR)
        idx = np.zeros((padded,), np.int32)  # pad with the trash page
        idx[:n] = pages
        jidx = jnp.asarray(idx)
        arrays = [cache.k, cache.v]
        if kind == _KIND_INT8:
            arrays += [cache.k_scale, cache.v_scale]
        for arr in arrays:
            parts.append(np.asarray(arr[:, jidx])[:, :n].tobytes())
    return b"".join(parts)


def decode_wire(buf: bytes) -> KVWirePayload:
    """Parse + validate one transfer payload. Raises :class:`KVWireError`
    on anything malformed — magic, version, kind, or a byte count that
    disagrees with the header's geometry (truncation/corruption)."""
    if len(buf) < _WIRE_HEADER.size:
        raise KVWireError(
            f"payload too short for the wire header "
            f"({len(buf)} < {_WIRE_HEADER.size} bytes)"
        )
    magic, version, kind, _, L, kh, ps, hd, n_pages, tokens = (
        _WIRE_HEADER.unpack_from(buf)
    )
    if magic != KV_WIRE_MAGIC:
        raise KVWireError(f"bad magic {magic!r} (want {KV_WIRE_MAGIC!r})")
    if version != KV_WIRE_VERSION:
        raise KVWireError(
            f"wire version {version} unsupported (this build speaks "
            f"{KV_WIRE_VERSION})"
        )
    if kind not in _KIND_BY_DTYPE.values():
        raise KVWireError(f"unknown pool kind {kind}")
    if tokens > n_pages * ps:
        raise KVWireError(
            f"header claims {tokens} tokens in {n_pages} pages of {ps}"
        )
    off = _WIRE_HEADER.size
    ids_bytes = tokens * 4
    page_elems = L * n_pages * kh * ps * hd
    dtype = _wire_np_dtype(kind)
    page_bytes = page_elems * np.dtype(dtype).itemsize
    scale_elems = L * n_pages * kh * ps
    scale_bytes = scale_elems * 4 if kind == _KIND_INT8 else 0
    want = off + ids_bytes + 2 * page_bytes + 2 * scale_bytes
    if len(buf) != want:
        raise KVWireError(
            f"payload is {len(buf)} bytes, header geometry needs {want} "
            "(truncated or corrupt)"
        )
    ids = np.frombuffer(buf, np.int32, count=tokens, offset=off)
    off += ids_bytes
    shape = (L, n_pages, kh, ps, hd)
    k = np.frombuffer(buf, dtype, count=page_elems, offset=off).reshape(shape)
    off += page_bytes
    v = np.frombuffer(buf, dtype, count=page_elems, offset=off).reshape(shape)
    off += page_bytes
    k_scale = v_scale = None
    if kind == _KIND_INT8:
        sshape = (L, n_pages, kh, 1, ps)
        k_scale = np.frombuffer(
            buf, np.float32, count=scale_elems, offset=off).reshape(sshape)
        off += scale_bytes
        v_scale = np.frombuffer(
            buf, np.float32, count=scale_elems, offset=off).reshape(sshape)
    return KVWirePayload(
        kind=kind, layers=L, kv_heads=kh, page_size=ps, head_dim=hd,
        n_pages=n_pages, tokens=tokens, ids=ids, k=k, v=v,
        k_scale=k_scale, v_scale=v_scale,
    )


def check_wire_compat(payload: KVWirePayload, cache) -> None:
    """Raise :class:`KVWireError` unless ``payload`` matches the destination
    pool's geometry and precision — the import-side gate that turns a
    cross-model transfer into a structured refusal instead of silent KV
    corruption."""
    kind = _pool_kind(cache)
    L, _, kh, ps, hd = cache.k.shape
    mine = (kind, L, kh, ps, hd)
    theirs = (payload.kind, payload.layers, payload.kv_heads,
              payload.page_size, payload.head_dim)
    if mine != theirs:
        names = ("kind", "layers", "kv_heads", "page_size", "head_dim")
        diffs = ", ".join(
            f"{n}={t} (pool has {m})"
            for n, t, m in zip(names, theirs, mine) if t != m
        )
        raise KVWireError(f"payload geometry mismatch: {diffs}")


# Donated in-place page scatter: import must not copy the multi-GB pool per
# transfer. Shapes bucket on the pow2 ladder (callers pad with the trash
# page, whose writes are harmless by design), so compile variants stay
# O(log pages).
@partial(jax.jit, donate_argnums=(0,))
def _splice_pages_arr(pages, phys, data):
    return pages.at[:, phys].set(data.astype(pages.dtype))


def splice_imported(cache, payload: KVWirePayload, phys: Sequence[int]):
    """Write the first ``len(phys)`` payload pages into physical pages
    ``phys`` of ``cache`` (donated, in place) and return the updated cache.
    Callers import fewer pages than the payload carries when their token
    match ends early — the tail pages simply stay on the free list."""
    check_wire_compat(payload, cache)
    n = len(phys)
    if n == 0:
        return cache
    if n > payload.n_pages:
        raise KVWireError(
            f"import wants {n} pages, payload carries {payload.n_pages}"
        )
    padded = bucket_pow2(n, floor=POW2_FLOOR)
    idx = np.zeros((padded,), np.int32)  # pad with the trash page
    idx[:n] = [int(p) for p in phys]
    jidx = jnp.asarray(idx)

    def pad(arr):
        out = np.zeros((arr.shape[0], padded) + arr.shape[2:], arr.dtype)
        out[:, :n] = arr[:, :n]
        return jnp.asarray(out)

    upd = dict(
        k=_splice_pages_arr(cache.k, jidx, pad(payload.k)),
        v=_splice_pages_arr(cache.v, jidx, pad(payload.v)),
    )
    if payload.kind == _KIND_INT8:
        upd["k_scale"] = _splice_pages_arr(
            cache.k_scale, jidx, pad(payload.k_scale))
        upd["v_scale"] = _splice_pages_arr(
            cache.v_scale, jidx, pad(payload.v_scale))
    return cache._replace(**upd)


def gather_dense(
    pages: jnp.ndarray,  # [P, kh, ps, hd]
    page_table: jnp.ndarray,  # [b, max_pages]
) -> jnp.ndarray:
    """Materialize the dense [b, max_pages*ps, kh, hd] view (XLA fallback /
    test oracle; the Pallas kernel never does this)."""
    P, kh, ps, hd = pages.shape
    picked = pages[page_table]  # [b, max_pages, kh, ps, hd]
    b, mp = page_table.shape
    return picked.transpose(0, 1, 3, 2, 4).reshape(b, mp * ps, kh, hd)


def gather_dense_scales(
    scales: jnp.ndarray,  # [P, kh, 1, ps]
    page_table: jnp.ndarray,  # [b, max_pages]
) -> jnp.ndarray:
    """Dense [b, max_pages*ps, kh] view of the per-token quant scales
    (oracle/fallback companion of gather_dense for the int8 pool)."""
    P, kh, _, ps = scales.shape
    picked = scales[page_table]  # [b, mp, kh, 1, ps]
    b, mp = page_table.shape
    return picked[:, :, :, 0, :].transpose(0, 1, 3, 2).reshape(b, mp * ps, kh)
