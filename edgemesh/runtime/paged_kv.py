"""Block-paged HBM-resident KV cache — the HeadInfer analog (BASELINE.json
configs[3], SURVEY.md §5.7).

HeadInfer scales context on small GPUs by offloading KV heads to host DRAM;
the TPU reinterpretation keeps the cache HBM-resident, paged, and head-wise
sharded: page arrays are laid out head-major ``[layers, kv_heads, pages,
page_size, head_dim]`` so a ``P(None, "tp")`` sharding slices contiguous
memory per chip, and the paged-attention kernel walks each sequence's page
table instead of a dense ``[b, max_seq]`` slab.

Everything here is functional and statically shaped so the decode loop jits
once (the design rule the whole runtime follows, models/transformer.py):

- ``PagedKVCache`` carries the page arrays, one page table shared by all
  layers, per-row lengths, and the free-page stack.
- Physical page 0 is the TRASH page: writes for padded/invalid positions land
  there, reads of unallocated table slots DMA it harmlessly (always masked).
- ``allocate`` pops pages for rows that need them — callable INSIDE a scanned
  decode step (pure array ops, no data-dependent shapes).

The reference has no cache management at all — HF ``generate`` reallocates
per call (``Code/C-DAC Server/combiner_fp.py:338-347``); this module is what
lets one preallocated HBM pool serve many variable-length sequences.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from edgemesh.models.transformer import ModelConfig


class PagedKVCache(NamedTuple):
    """k/v: [L, kv_heads, total_pages, page_size, head_dim].

    ``page_table``: [b, max_pages] int32 — physical page of each logical page
    (0 = unallocated → trash page). ``lengths``: [b] tokens written per row.
    ``free_stack``: [total_pages] int32 physical page ids; ``free_top`` is the
    next unpopped stack index (monotone within one batch's lifetime; the host
    rebuilds the stack between serving batches).
    """

    k: jnp.ndarray
    v: jnp.ndarray
    page_table: jnp.ndarray
    lengths: jnp.ndarray
    free_stack: jnp.ndarray
    free_top: jnp.ndarray

    @property
    def page_size(self) -> int:
        return self.k.shape[3]

    @property
    def max_pages(self) -> int:
        return self.page_table.shape[1]


def init_paged_cache(
    cfg: ModelConfig,
    batch: int,
    total_pages: int,
    page_size: int = 64,
    max_pages: int | None = None,
    dtype=None,
) -> PagedKVCache:
    """Preallocate the page pool. ``total_pages`` includes the trash page."""
    dtype = dtype or cfg.activation_dtype
    max_pages = max_pages or (cfg.max_seq_len + page_size - 1) // page_size
    shape = (cfg.num_layers, cfg.num_kv_heads, total_pages, page_size, cfg.head_size)
    return PagedKVCache(
        k=jnp.zeros(shape, dtype),
        v=jnp.zeros(shape, dtype),
        page_table=jnp.zeros((batch, max_pages), jnp.int32),
        lengths=jnp.zeros((batch,), jnp.int32),
        free_stack=jnp.arange(total_pages, dtype=jnp.int32),  # entry 0 = trash
        free_top=jnp.asarray(1, jnp.int32),  # skip the trash page
    )


def pool_overflowed(cache: PagedKVCache) -> bool:
    """Host-side overflow check: True if any allocate() ran past the free
    stack. Those rows were handed the trash page — their KV beyond the
    overflow point is invalid and results must be discarded."""
    return int(cache.free_top) > cache.free_stack.shape[0]


def pages_needed(lengths: jnp.ndarray, new_tokens: jnp.ndarray, page_size: int) -> jnp.ndarray:
    """How many fresh pages each row needs to hold ``new_tokens`` more tokens."""
    have = (lengths + page_size - 1) // page_size
    want = (lengths + new_tokens + page_size - 1) // page_size
    return want - have


def allocate(cache: PagedKVCache, n_pages: jnp.ndarray) -> PagedKVCache:
    """Pop ``n_pages[i]`` pages for row i and append them to its table.

    Statically bounded by ``max_pages`` logical slots per row; pure gathers
    and scatters, so it runs inside a jitted/scanned decode step. Exhausting
    the pool hands out the trash page (physical 0) for the overflowing rows —
    jit-compatible, no branch — but the overflow is RECORDED: ``free_top``
    keeps advancing past the stack size, so ``pool_overflowed(cache)`` is
    True afterwards. Callers either bound capacity up front (generate()
    validates prompt+max_new against the pool) or assert ``pool_overflowed``
    host-side after their loop.
    """
    b, max_pages = cache.page_table.shape
    n_pages = n_pages.astype(jnp.int32)
    # Row i draws stack entries free_top + offset[i] .. + n[i]-1.
    offset = jnp.cumsum(n_pages) - n_pages  # exclusive prefix sum
    have = (cache.lengths + cache.page_size - 1) // cache.page_size  # filled slots

    j = jnp.arange(max_pages)[None, :]  # candidate new logical slot index
    take = j < n_pages[:, None]  # [b, max_pages]
    src = cache.free_top + offset[:, None] + j  # stack position per slot
    total = cache.free_stack.shape[0]
    pages = jnp.where(
        (src < total) & take, cache.free_stack[jnp.minimum(src, total - 1)], 0
    )
    slots = have[:, None] + j  # target logical slot
    rows = jnp.broadcast_to(jnp.arange(b)[:, None], (b, max_pages))
    # Non-taken entries scatter out of bounds and are dropped (XLA OOB-scatter
    # semantics made explicit) — they must not touch any real table slot.
    table = cache.page_table.at[jnp.where(take, rows, b), slots].set(
        pages, mode="drop"
    )
    return cache._replace(
        page_table=table, free_top=cache.free_top + jnp.sum(n_pages)
    )


def _flat_scatter(pages: jnp.ndarray, flat_pos: jnp.ndarray, values: jnp.ndarray) -> jnp.ndarray:
    """Scatter values[kh, n, hd] into pages[kh, P, ps, hd] at flat token
    positions flat_pos[n] (page*page_size + slot)."""
    kh, P, ps, hd = pages.shape
    flat = pages.reshape(kh, P * ps, hd)
    flat = flat.at[:, flat_pos, :].set(values)
    return flat.reshape(kh, P, ps, hd)


def write_tokens(
    k_pages: jnp.ndarray,  # [kh, P, ps, hd] one layer's pages
    v_pages: jnp.ndarray,
    k: jnp.ndarray,  # [b, s, kh, hd] new keys (roped)
    v: jnp.ndarray,
    page_table: jnp.ndarray,  # [b, max_pages]
    start: jnp.ndarray,  # [b] first token position to write
    valid_len: jnp.ndarray,  # [b] number of real tokens in k/v per row
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter s tokens per row into their pages; invalid tokens → trash page."""
    b, s, kh, hd = k.shape
    ps = k_pages.shape[2]
    t = jnp.arange(s)[None, :]  # [1, s]
    pos = start[:, None] + t  # absolute position [b, s]
    logical = pos // ps
    slot = pos % ps
    valid = t < valid_len[:, None]
    max_pages = page_table.shape[1]
    phys = jnp.take_along_axis(
        page_table, jnp.minimum(logical, max_pages - 1), axis=1
    )  # [b, s]
    flat_pos = jnp.where(valid, phys * ps + slot, 0)  # 0.. = trash page slots
    flat_pos = flat_pos.reshape(b * s)
    kv_kh_first = k.transpose(2, 0, 1, 3).reshape(kh, b * s, hd)
    vv_kh_first = v.transpose(2, 0, 1, 3).reshape(kh, b * s, hd)
    return (
        _flat_scatter(k_pages, flat_pos, kv_kh_first.astype(k_pages.dtype)),
        _flat_scatter(v_pages, flat_pos, vv_kh_first.astype(v_pages.dtype)),
    )


def gather_dense(
    pages: jnp.ndarray,  # [kh, P, ps, hd]
    page_table: jnp.ndarray,  # [b, max_pages]
) -> jnp.ndarray:
    """Materialize the dense [b, max_pages*ps, kh, hd] view (XLA fallback /
    test oracle; the Pallas kernel never does this)."""
    kh, P, ps, hd = pages.shape
    picked = pages[:, page_table, :, :]  # [kh, b, max_pages, ps, hd]
    b, mp = page_table.shape
    return picked.transpose(1, 2, 3, 0, 4).reshape(b, mp * ps, kh, hd)
