"""Block-paged HBM-resident KV cache — the HeadInfer analog (BASELINE.json
configs[3], SURVEY.md §5.7).

HeadInfer scales context on small GPUs by offloading KV heads to host DRAM;
the TPU reinterpretation keeps the cache HBM-resident, paged, and head-wise
sharded: page arrays are laid out page-major ``[layers, pages, kv_heads,
page_size, head_dim]`` so one physical page holds every kv head's slice
contiguously — the paged-attention kernel then fetches a whole page in ONE
kh·ps·hd DMA per grid step (the r2 head-major layout forced kh separate
ps·hd DMAs, ~8 KB each, too small for HBM bandwidth) — and a
``P(None, None, "tp")`` sharding still slices the pool head-wise per chip.
The kernel walks each sequence's page table instead of a dense
``[b, max_seq]`` slab.

Everything here is functional and statically shaped so the decode loop jits
once (the design rule the whole runtime follows, models/transformer.py):

- ``PagedKVCache`` carries the page arrays, one page table shared by all
  layers, per-row lengths, and the free-page stack.
- Physical page 0 is the TRASH page: writes for padded/invalid positions land
  there, reads of unallocated table slots DMA it harmlessly (always masked).
- ``allocate`` pops pages for rows that need them — callable INSIDE a scanned
  decode step (pure array ops, no data-dependent shapes).

The reference has no cache management at all — HF ``generate`` reallocates
per call (``Code/C-DAC Server/combiner_fp.py:338-347``); this module is what
lets one preallocated HBM pool serve many variable-length sequences.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from edgemesh.models.transformer import ModelConfig


class PagedKVCache(NamedTuple):
    """k/v: [L, total_pages, kv_heads, page_size, head_dim].

    ``page_table``: [b, max_pages] int32 — physical page of each logical page
    (0 = unallocated → trash page). ``lengths``: [b] tokens written per row.
    ``free_stack``: [total_pages] int32 physical page ids; ``free_top`` is the
    next unpopped stack index (monotone within one batch's lifetime; the host
    rebuilds the stack between serving batches).
    """

    k: jnp.ndarray
    v: jnp.ndarray
    page_table: jnp.ndarray
    lengths: jnp.ndarray
    free_stack: jnp.ndarray
    free_top: jnp.ndarray

    @property
    def page_size(self) -> int:
        return self.k.shape[3]

    @property
    def max_pages(self) -> int:
        return self.page_table.shape[1]


def init_paged_cache(
    cfg: ModelConfig,
    batch: int,
    total_pages: int,
    page_size: int = 64,
    max_pages: int | None = None,
    dtype=None,
) -> PagedKVCache:
    """Preallocate the page pool. ``total_pages`` includes the trash page."""
    dtype = dtype or cfg.activation_dtype
    max_pages = max_pages or (cfg.max_seq_len + page_size - 1) // page_size
    shape = (cfg.num_layers, total_pages, cfg.num_kv_heads, page_size, cfg.head_size)
    return PagedKVCache(
        k=jnp.zeros(shape, dtype),
        v=jnp.zeros(shape, dtype),
        page_table=jnp.zeros((batch, max_pages), jnp.int32),
        lengths=jnp.zeros((batch,), jnp.int32),
        free_stack=jnp.arange(total_pages, dtype=jnp.int32),  # entry 0 = trash
        free_top=jnp.asarray(1, jnp.int32),  # skip the trash page
    )


class QuantPagedKVCache(NamedTuple):
    """Int8 page pool: k/v int8 [L, P, kh, ps, hd]; k_scale/v_scale fp32
    [L, P, kh, 1, ps] (one symmetric absmax scale per written token row,
    runtime/quant_kv.quantize_kv — the [·, 1, ps] shape keeps the kernel's
    per-page scale read a 2D [1, ps] vector). Halves the page-walk DMA bytes
    on top of the page-major layout, marrying the two long-context levers
    (SURVEY.md §5.7: HeadInfer-analog paging + int8 KV). Table/length/free
    bookkeeping is identical to PagedKVCache, so allocate()/pages_needed()
    serve both."""

    k: jnp.ndarray
    v: jnp.ndarray
    k_scale: jnp.ndarray
    v_scale: jnp.ndarray
    page_table: jnp.ndarray
    lengths: jnp.ndarray
    free_stack: jnp.ndarray
    free_top: jnp.ndarray

    @property
    def page_size(self) -> int:
        return self.k.shape[3]

    @property
    def max_pages(self) -> int:
        return self.page_table.shape[1]


def init_quant_paged_cache(
    cfg: ModelConfig,
    batch: int,
    total_pages: int,
    page_size: int = 64,
    max_pages: int | None = None,
) -> QuantPagedKVCache:
    """Preallocate the int8 page pool. ``total_pages`` includes the trash page."""
    max_pages = max_pages or (cfg.max_seq_len + page_size - 1) // page_size
    shape = (cfg.num_layers, total_pages, cfg.num_kv_heads, page_size, cfg.head_size)
    sshape = (cfg.num_layers, total_pages, cfg.num_kv_heads, 1, page_size)
    return QuantPagedKVCache(
        k=jnp.zeros(shape, jnp.int8),
        v=jnp.zeros(shape, jnp.int8),
        k_scale=jnp.zeros(sshape, jnp.float32),
        v_scale=jnp.zeros(sshape, jnp.float32),
        page_table=jnp.zeros((batch, max_pages), jnp.int32),
        lengths=jnp.zeros((batch,), jnp.int32),
        free_stack=jnp.arange(total_pages, dtype=jnp.int32),
        free_top=jnp.asarray(1, jnp.int32),
    )


def pool_overflowed(cache: PagedKVCache) -> bool:
    """Host-side overflow check: True if any allocate() ran past the free
    stack. Those rows were handed the trash page — their KV beyond the
    overflow point is invalid and results must be discarded."""
    return int(cache.free_top) > cache.free_stack.shape[0]


def pages_needed(lengths: jnp.ndarray, new_tokens: jnp.ndarray, page_size: int) -> jnp.ndarray:
    """How many fresh pages each row needs to hold ``new_tokens`` more tokens."""
    have = (lengths + page_size - 1) // page_size
    want = (lengths + new_tokens + page_size - 1) // page_size
    return want - have


def allocate(cache: PagedKVCache, n_pages: jnp.ndarray) -> PagedKVCache:
    """Pop pages so row i's next ``n_pages[i]`` logical slots are backed.

    Statically bounded by ``max_pages`` logical slots per row; pure
    elementwise ops, so it runs inside a jitted/scanned decode step. A
    target slot that ALREADY maps a physical page keeps it and pops nothing
    — this makes allocation idempotent under REWIND (speculative decoding
    lowers ``lengths`` past pages it already owns; re-advancing must reuse
    them, not leak them and orphan stack entries). Exhausting the pool hands
    out the trash page (physical 0) for the overflowing slots —
    jit-compatible, no branch — but the overflow is RECORDED: ``free_top``
    keeps advancing past the stack size, so ``pool_overflowed(cache)`` is
    True afterwards. Callers either bound capacity up front (generate()
    validates prompt+max_new against the pool) or assert ``pool_overflowed``
    host-side after their loop.
    """
    b, max_pages = cache.page_table.shape
    n_pages = n_pages.astype(jnp.int32)
    have = (cache.lengths + cache.page_size - 1) // cache.page_size  # filled slots

    j = jnp.arange(max_pages)[None, :]  # logical slot index
    target = (j >= have[:, None]) & (j < (have + n_pages)[:, None])
    need = target & (cache.page_table == 0)  # skip slots that kept a page
    # Pop order: row-major over needed slots.
    flat = need.reshape(-1)
    order = jnp.cumsum(flat.astype(jnp.int32)) - 1  # pop index per needed slot
    src = (cache.free_top + order).reshape(b, max_pages)
    total = cache.free_stack.shape[0]
    pages = jnp.where(
        need & (src < total), cache.free_stack[jnp.minimum(src, total - 1)], 0
    )
    table = jnp.where(need, pages, cache.page_table)
    return cache._replace(
        page_table=table, free_top=cache.free_top + jnp.sum(need)
    )


def _token_slots(page_table, start, valid_len, s, ps):
    """(pp, ss) [b·s] physical page / in-page slot per token; invalid → trash."""
    b = page_table.shape[0]
    t = jnp.arange(s)[None, :]  # [1, s]
    pos = start[:, None] + t  # absolute position [b, s]
    logical = pos // ps
    slot = pos % ps
    valid = t < valid_len[:, None]
    max_pages = page_table.shape[1]
    phys = jnp.take_along_axis(
        page_table, jnp.minimum(logical, max_pages - 1), axis=1
    )  # [b, s]
    pp = jnp.where(valid, phys, 0).reshape(b * s)  # invalid → trash page
    ss = slot.reshape(b * s)
    return pp, ss


def write_tokens(
    k_pages: jnp.ndarray,  # [P, kh, ps, hd] one layer's pages
    v_pages: jnp.ndarray,
    k: jnp.ndarray,  # [b, s, kh, hd] new keys (roped)
    v: jnp.ndarray,
    page_table: jnp.ndarray,  # [b, max_pages]
    start: jnp.ndarray,  # [b] first token position to write
    valid_len: jnp.ndarray,  # [b] number of real tokens in k/v per row
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter s tokens per row into their pages; invalid tokens → trash page.

    One scatter per array, token-indexed on (page, slot): all kv heads of a
    token land in its page's contiguous [kh, ·, hd] stripe (page-major
    layout, module docstring)."""
    b, s, kh, hd = k.shape
    ps = k_pages.shape[2]
    pp, ss = _token_slots(page_table, start, valid_len, s, ps)
    return (
        k_pages.at[pp, :, ss, :].set(k.reshape(b * s, kh, hd).astype(k_pages.dtype)),
        v_pages.at[pp, :, ss, :].set(v.reshape(b * s, kh, hd).astype(v_pages.dtype)),
    )


def write_tokens_quant(
    k_pages: jnp.ndarray,  # [P, kh, ps, hd] int8, one layer's pages
    v_pages: jnp.ndarray,
    k_scales: jnp.ndarray,  # [P, kh, 1, ps] f32
    v_scales: jnp.ndarray,
    k_q: jnp.ndarray,  # [b, s, kh, hd] int8 new keys (quantize_kv output)
    k_s: jnp.ndarray,  # [b, s, kh] f32 per-row scales
    v_q: jnp.ndarray,
    v_s: jnp.ndarray,
    page_table: jnp.ndarray,
    start: jnp.ndarray,
    valid_len: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Scatter pre-quantized token rows + scales into the int8 page pool.

    Takes runtime/quant_kv.quantize_kv outputs rather than quantizing here:
    the prefill path also needs the int8 roundtrip of the fresh k/v for its
    attend (paged_generate._paged_attention), so quantization happens exactly
    once at the call site."""
    b, s, kh, hd = k_q.shape
    ps = k_pages.shape[2]
    pp, ss = _token_slots(page_table, start, valid_len, s, ps)
    return (
        k_pages.at[pp, :, ss, :].set(k_q.reshape(b * s, kh, hd)),
        v_pages.at[pp, :, ss, :].set(v_q.reshape(b * s, kh, hd)),
        k_scales.at[pp, :, 0, ss].set(k_s.reshape(b * s, kh).astype(k_scales.dtype)),
        v_scales.at[pp, :, 0, ss].set(v_s.reshape(b * s, kh).astype(v_scales.dtype)),
    )


def gather_dense(
    pages: jnp.ndarray,  # [P, kh, ps, hd]
    page_table: jnp.ndarray,  # [b, max_pages]
) -> jnp.ndarray:
    """Materialize the dense [b, max_pages*ps, kh, hd] view (XLA fallback /
    test oracle; the Pallas kernel never does this)."""
    P, kh, ps, hd = pages.shape
    picked = pages[page_table]  # [b, max_pages, kh, ps, hd]
    b, mp = page_table.shape
    return picked.transpose(0, 1, 3, 2, 4).reshape(b, mp * ps, kh, hd)


def gather_dense_scales(
    scales: jnp.ndarray,  # [P, kh, 1, ps]
    page_table: jnp.ndarray,  # [b, max_pages]
) -> jnp.ndarray:
    """Dense [b, max_pages*ps, kh] view of the per-token quant scales
    (oracle/fallback companion of gather_dense for the int8 pool)."""
    P, kh, _, ps = scales.shape
    picked = scales[page_table]  # [b, mp, kh, 1, ps]
    b, mp = page_table.shape
    return picked[:, :, :, 0, :].transpose(0, 1, 3, 2).reshape(b, mp * ps, kh)
