"""Checkpoint / resume via orbax — the subsystem the reference lacks entirely
(SURVEY.md §5.4: an evaluation interrupted at sample 999 restarts from zero;
weights only exist as HF ``save_pretrained`` snapshots, download.py:20-24).

Three layers of durability here:

- **Weights / train state** (this module): orbax PyTree checkpoints. Sharded
  arrays save and restore with their ``NamedSharding`` preserved; restoring
  onto a DIFFERENT mesh layout just needs the target sharding tree
  (``restore(..., template=...)`` with device_put'd leaves or abstract
  shapes), which is how a training run moves between chip counts.
- **Eval progress**: already durable — the harness appends one JSON line per
  sample and resumes by replay (eval/harness.py).
- **Serving**: ``snapshot_for_serving``/``restore_for_serving`` give the
  health-checked REST loop (serve/rest.py) a deterministic restart point
  (SURVEY.md §5.3's failure-recovery requirement; inference-only, so params
  + config are the whole state).
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp

from edgemesh.models.transformer import ModelConfig


def _as_path(path: str | Path) -> Path:
    return Path(path).expanduser().resolve()


def _as_abstract(template: Any) -> Any:
    """Template pytree → jax.ShapeDtypeStruct leaves (shardings preserved);
    leaves that are already abstract pass through."""
    return jax.tree.map(
        lambda x: x
        if isinstance(x, jax.ShapeDtypeStruct)
        else jax.ShapeDtypeStruct(
            x.shape, x.dtype, sharding=getattr(x, "sharding", None)
        ),
        template,
    )


def save_pytree(path: str | Path, tree: Any) -> None:
    """Write one pytree (params or full train state) as an orbax checkpoint.
    Overwrites any existing checkpoint at ``path``."""
    path = _as_path(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, tree, force=True)
    ckptr.wait_until_finished()


def restore_pytree(path: str | Path, template: Any | None = None) -> Any:
    """Restore a pytree. With ``template`` (a pytree of arrays or
    jax.ShapeDtypeStruct with shardings), leaves land directly in the target
    placement/dtype; without it, leaves restore host-resident as saved."""
    path = _as_path(path)
    ckptr = ocp.StandardCheckpointer()
    if template is None:
        return ckptr.restore(path)
    return ckptr.restore(path, _as_abstract(template))


class TrainCheckpointManager:
    """Rotating step checkpoints for training loops (keep the latest N).

    Thin wrapper over ocp.CheckpointManager so training code stays one-call:
    ``mgr.save(step, state)`` / ``state, step = mgr.restore_latest(state)``.
    """

    def __init__(self, directory: str | Path, max_to_keep: int = 3):
        self._mgr = ocp.CheckpointManager(
            _as_path(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state: Any) -> None:
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        self._mgr.wait_until_finished()

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore_latest(self, template: Any) -> tuple[Any, int] | None:
        """Restore the newest checkpoint into ``template``'s placements, or
        None when the directory has no checkpoints (fresh run)."""
        step = self._mgr.latest_step()
        if step is None:
            return None
        state = self._mgr.restore(
            step, args=ocp.args.StandardRestore(_as_abstract(template))
        )
        return state, step

    def close(self):
        self._mgr.close()


# ---------------------------------------------------------------------------
# Serving snapshots: params + the exact ModelConfig, restartable in one call
# ---------------------------------------------------------------------------


def snapshot_for_serving(directory: str | Path, cfg: ModelConfig, params: Any) -> None:
    """Persist everything a serving process needs to come back identically."""
    directory = _as_path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    (directory / "model_config.json").write_text(
        json.dumps(dataclasses.asdict(cfg), indent=2)
    )
    save_pytree(directory / "params", params)


def restore_for_serving(
    directory: str | Path, mesh=None
) -> tuple[ModelConfig, Any]:
    """Load (cfg, params) from a serving snapshot. With ``mesh``, params are
    placed straight onto it via the standard param shardings."""
    directory = _as_path(directory)
    cfg_path = directory / "model_config.json"
    if not cfg_path.exists():
        raise FileNotFoundError(f"no serving snapshot at {directory}")
    cfg = ModelConfig(**json.loads(cfg_path.read_text()))
    params = restore_pytree(directory / "params")
    if mesh is not None:
        from edgemesh.parallel.sharding import shard_params

        params = shard_params(params, cfg, mesh)
    return cfg, params
