"""Model family presets and HF config sniffing.

The three families mirror the models the reference evaluates (ACL paper §4.2;
loaders at ``Code/C-DAC Server/combiner_fp.py:274-284``): Phi-2, Pythia-1B,
Llama-3.2-1B-Instruct. Each preset fixes the architecture dials of
:class:`~edgemesh.models.transformer.ModelConfig`; size fields come from the
checkpoint's config.json (hf_ingest) or from :class:`~edgemesh.config.ModelSpec`
overrides for synthetic models.
"""

from __future__ import annotations

import json
from pathlib import Path

from edgemesh.models.transformer import ModelConfig

# Architecture dials only — size fields filled per checkpoint.
FAMILY_PRESETS: dict[str, dict] = {
    # Llama 2/3 lineage: RMSNorm, SwiGLU, GQA, full rotary, no biases.
    "llama": dict(
        norm="rms",
        activation="silu",
        parallel_block=False,
        shared_input_norm=False,
        rotary_fraction=1.0,
        qkv_bias=False,
        out_bias=False,
        lm_head_bias=False,
        tie_embeddings=True,  # Llama-3.2-1B ties; larger Llamas override to False
    ),
    # Pythia / GPT-NeoX: LayerNorm+bias, GELU, parallel residual with TWO input
    # norms, rotary_pct=0.25, biases everywhere, untied embed_out.
    "neox": dict(
        norm="ln",
        activation="gelu",
        parallel_block=True,
        shared_input_norm=False,
        rotary_fraction=0.25,
        qkv_bias=True,
        out_bias=True,
        lm_head_bias=False,
        tie_embeddings=False,
    ),
    # Phi-2: LayerNorm+bias, GELU(tanh), parallel block with ONE shared input
    # norm, partial rotary (32 of 80 dims = 0.4), biases incl. lm_head.
    "phi2": dict(
        norm="ln",
        activation="gelu_tanh",
        parallel_block=True,
        shared_input_norm=True,
        rotary_fraction=0.4,
        qkv_bias=True,
        out_bias=True,
        lm_head_bias=True,
        tie_embeddings=False,
    ),
    # Mistral: the llama dialect plus sliding-window attention (the 7B's
    # window is 4096). BASELINE.json's HeadInfer-analog config names
    # Mistral-7B; size/window fields come from the checkpoint.
    "mistral": dict(
        norm="rms",
        activation="silu",
        parallel_block=False,
        shared_input_norm=False,
        rotary_fraction=1.0,
        qkv_bias=False,
        out_bias=False,
        lm_head_bias=False,
        tie_embeddings=False,
    ),
    # Mixtral (8x7B / 8x22B): the mistral dialect with the dense SwiGLU MLP
    # replaced by a top-k routed MoE (ops/moe.py) — num_experts /
    # experts_per_token come from the checkpoint (num_local_experts /
    # num_experts_per_tok). Routing math matches HF exactly: softmax over
    # ALL experts, top-k, renormalize over the selected k.
    "mixtral": dict(
        norm="rms",
        activation="silu",
        parallel_block=False,
        shared_input_norm=False,
        rotary_fraction=1.0,
        qkv_bias=False,
        out_bias=False,
        lm_head_bias=False,
        tie_embeddings=False,
    ),
    # Qwen2/2.5: the llama dialect plus attention qkv biases; small variants
    # (0.5B/1.5B) tie embeddings (checkpoint's tie_word_embeddings decides).
    "qwen2": dict(
        norm="rms",
        activation="silu",
        parallel_block=False,
        shared_input_norm=False,
        rotary_fraction=1.0,
        qkv_bias=True,
        out_bias=False,
        lm_head_bias=False,
        tie_embeddings=True,
    ),
    # Qwen3: the llama dialect with per-head QK-RMSNorm (before RoPE)
    # replacing qwen2's qkv biases; explicit head_dim; small variants tie
    # embeddings (checkpoint's tie_word_embeddings decides).
    "qwen3": dict(
        norm="rms",
        activation="silu",
        parallel_block=False,
        shared_input_norm=False,
        rotary_fraction=1.0,
        qkv_bias=False,
        out_bias=False,
        lm_head_bias=False,
        tie_embeddings=True,
        qk_norm=True,
    ),
    # Phi-3: the llama dialect (RMSNorm/SwiGLU/GQA/full rotary, no biases,
    # untied head) with FUSED qkv_proj and gate_up_proj checkpoint weights
    # (split at ingest) and an always-on sliding window (mini-4k: 2047).
    "phi3": dict(
        norm="rms",
        activation="silu",
        parallel_block=False,
        shared_input_norm=False,
        rotary_fraction=1.0,
        qkv_bias=False,
        out_bias=False,
        lm_head_bias=False,
        tie_embeddings=False,
    ),
    # Gemma (v1): RMSNorm with unit offset (weights store scale-1), GeGLU
    # (gated gelu_tanh MLP), embeddings scaled by sqrt(hidden), wide fixed
    # head_dim (256 — NOT hidden/heads), always-tied LM head.
    "gemma": dict(
        norm="rms",
        norm_unit_offset=True,
        activation="gelu_tanh",
        gated_mlp=True,
        embed_scale=True,
        parallel_block=False,
        shared_input_norm=False,
        rotary_fraction=1.0,
        qkv_bias=False,
        out_bias=False,
        lm_head_bias=False,
        tie_embeddings=True,
    ),
    # Gemma 2: gemma's dials PLUS post-sublayer norms, attention-score and
    # final-logit soft caps, a fixed query scale, and sliding windows on
    # alternate (even) layers only. The flash prefill kernel honors all
    # three attention dials (soft cap / query scale / per-half window).
    "gemma2": dict(
        norm="rms",
        norm_unit_offset=True,
        activation="gelu_tanh",
        gated_mlp=True,
        embed_scale=True,
        post_block_norms=True,
        parallel_block=False,
        shared_input_norm=False,
        rotary_fraction=1.0,
        qkv_bias=False,
        out_bias=False,
        lm_head_bias=False,
        tie_embeddings=True,
        alt_sliding_window=True,
        attn_soft_cap=50.0,
        logit_soft_cap=30.0,
    ),
    # Falcon (7B dialect): LayerNorm+bias norms, gelu MLP, PARALLEL block
    # with one shared input norm (like phi2), full rotary, MULTI-QUERY
    # attention (num_kv_heads=1), no linear biases, tied head. The
    # new-decoder variants (40B / Falcon2) switch to dual input norms
    # (shared_input_norm=False) + GQA via config_from_checkpoint.
    "falcon": dict(
        norm="ln",
        activation="gelu",
        parallel_block=True,
        shared_input_norm=True,
        rotary_fraction=1.0,
        qkv_bias=False,
        out_bias=False,
        lm_head_bias=False,
        tie_embeddings=True,
    ),
    # GPT-2: pre-LN LayerNorm+bias, gelu_new (tanh), LEARNED absolute
    # position embeddings (no rotary), fused c_attn qkv with biases
    # (Conv1D [in, out] storage — no transpose at ingest), always-tied head.
    "gpt2": dict(
        norm="ln",
        activation="gelu_tanh",
        parallel_block=False,
        shared_input_norm=False,
        rotary_fraction=0.0,
        learned_positions=True,
        qkv_bias=True,
        out_bias=True,
        lm_head_bias=False,
        tie_embeddings=True,
    ),
}

_HF_MODEL_TYPE_TO_FAMILY = {
    "llama": "llama",
    "gpt_neox": "neox",
    "phi": "phi2",
    "mistral": "mistral",
    "mixtral": "mixtral",
    "qwen2": "qwen2",
    "qwen3": "qwen3",
    "gemma": "gemma",
    "gemma2": "gemma2",
    "phi3": "phi3",
    "gpt2": "gpt2",
    "falcon": "falcon",
    # Encoder family (BERT/MiniLM/sentence-BERT): bidirectional, post-LN,
    # learned positions — its own forward in models/encoder.py, NOT a
    # decoder preset. sniff_family recognizes it so ingest dispatches (or
    # refuses) with a precise message instead of a KeyError.
    "bert": "bert",
}


def sniff_family(checkpoint_dir: str | Path) -> str:
    """Read the HF config.json ``model_type`` and map to an edgemesh family."""
    cfg_path = Path(checkpoint_dir) / "config.json"
    with open(cfg_path) as f:
        model_type = json.load(f).get("model_type", "")
    try:
        return _HF_MODEL_TYPE_TO_FAMILY[model_type]
    except KeyError:
        raise ValueError(
            f"unsupported HF model_type {model_type!r} in {cfg_path}; "
            f"supported: {sorted(_HF_MODEL_TYPE_TO_FAMILY)}"
        ) from None


def config_for_family(
    family: str,
    *,
    vocab_size: int,
    hidden_size: int,
    num_layers: int,
    num_heads: int,
    num_kv_heads: int | None = None,
    intermediate_size: int | None = None,
    max_seq_len: int = 2048,
    **overrides,
) -> ModelConfig:
    if family not in FAMILY_PRESETS:
        raise ValueError(f"unknown family {family!r}; supported: {sorted(FAMILY_PRESETS)}")
    preset = dict(FAMILY_PRESETS[family])
    preset.update(overrides)
    return ModelConfig(
        vocab_size=vocab_size,
        hidden_size=hidden_size,
        num_layers=num_layers,
        num_heads=num_heads,
        num_kv_heads=num_kv_heads or num_heads,
        intermediate_size=intermediate_size or 4 * hidden_size,
        max_seq_len=max_seq_len,
        **preset,
    )


def tiny_config(family: str = "llama", **overrides) -> ModelConfig:
    """A minutes-not-hours config for tests and CPU smoke runs."""
    defaults = dict(
        vocab_size=256,
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2 if family == "llama" else (1 if family == "falcon" else 4),
        intermediate_size=128,
        max_seq_len=128,
        dtype="float32",
    )
    defaults.update(overrides)
    return config_for_family(family, **defaults)
