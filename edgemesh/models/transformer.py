"""Generic decoder-only transformer as pure functions over a param pytree.

One implementation covers the reference's three model families
(SURVEY.md §7 hard part (c)): Llama-3.2 (RMSNorm/SwiGLU/GQA/full rotary),
Pythia/GPT-NeoX (LayerNorm/GELU/parallel-residual/rotary_pct=0.25), and
Phi-2 (LayerNorm/GELU-tanh/parallel-block/rotary fraction 0.4). The reference
loaded these via HF ``from_pretrained`` (``Code/C-DAC Server/combiner_fp.py:274-284``);
here the architecture is expressed natively so XLA sees one traced program.

TPU-first choices:
- Layers are STACKED (every param leaf carries a leading ``num_layers`` axis)
  and the forward runs ``lax.scan`` over them: one layer's HLO compiled once,
  not ``L`` inlined copies — fast compiles, and the natural substrate for
  pipeline-stage splitting (scan over per-stage layer blocks).
- All shapes static; the decode loop (runtime/generate.py) jits once.
- Matmuls run in bf16 on the MXU with fp32 softmax/norm islands.
- Params are a plain dict pytree → ``jax.sharding.NamedSharding`` trees map
  directly onto it (edgemesh/parallel/sharding.py).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from edgemesh.ops.attention import LayerKV, attend, write_decode, write_prefill
from edgemesh.ops.norms import layer_norm, rms_norm
from edgemesh.ops.rope import apply_rope
from edgemesh.utils.platform import on_tpu

Params = dict[str, Any]


@dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 32000
    hidden_size: int = 2048
    num_layers: int = 16
    num_heads: int = 32
    num_kv_heads: int = 8
    intermediate_size: int = 8192
    max_seq_len: int = 2048
    head_dim: int | None = None  # defaults to hidden_size // num_heads

    # Family dials
    norm: str = "rms"  # rms | ln
    norm_eps: float = 1e-5
    # Gemma: RMSNorm scales by (1 + weight) — weights store the DELTA from
    # identity (HF GemmaRMSNorm).
    norm_unit_offset: bool = False
    activation: str = "silu"  # silu (SwiGLU) | gelu | gelu_tanh
    # Gated (GLU-style) MLP: gate/up/down instead of up/down. None = derive
    # from activation (silu → gated, the Llama convention); Gemma sets True
    # with gelu_tanh (GeGLU).
    gated_mlp: bool | None = None
    # Gemma: embedding output multiplied by sqrt(hidden_size) (the tied LM
    # head is NOT scaled).
    embed_scale: bool = False
    parallel_block: bool = False  # Phi-2/NeoX style: attn & mlp from one input
    shared_input_norm: bool = False  # Phi-2: ONE norm feeds both attn and mlp
    # Gemma-2: extra norms on the SUBLAYER OUTPUTS before the residual adds
    # (post_attention_layernorm / post_feedforward_layernorm, with the MLP
    # input normed by pre_feedforward_layernorm) — params attn_post_norm /
    # mlp_post_norm alongside attn_norm / mlp_norm.
    post_block_norms: bool = False
    # Gemma-2: attention-score soft cap (attn_logit_softcapping, 50.0) and a
    # fixed query scale (query_pre_attn_scalar^-0.5 instead of head_dim^-0.5;
    # 0 = default head_dim scaling).
    attn_soft_cap: float = 0.0
    query_pre_attn_scalar: float = 0.0
    # Per-head RMSNorm on q and k (over head_dim, before RoPE) — the
    # Qwen3/Olmo2-generation stabilization. Weights: q_norm/k_norm scale
    # leaves of shape [head_dim] per layer.
    qk_norm: bool = False
    rotary_fraction: float = 1.0
    # GPT-2: learned absolute position embeddings (wpe table added to the
    # token embedding) instead of rotary — set with rotary_fraction=0.0.
    learned_positions: bool = False
    rope_theta: float = 10000.0
    # HF rope_scaling block (Llama-3.x context extension): "" = none.
    rope_scaling_type: str = ""  # "" | linear | llama3
    rope_scaling_factor: float = 1.0
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    rope_original_max_position: int = 8192
    qkv_bias: bool = False
    out_bias: bool = False  # attn output proj + mlp projections
    lm_head_bias: bool = False
    tie_embeddings: bool = False
    logit_soft_cap: float = 0.0
    # Sliding-window attention (Mistral): each query sees at most the last
    # ``sliding_window`` positions. 0 = full causal attention. Every
    # attention path honors it — XLA attend masks; the flash and paged
    # kernels additionally skip COMPUTE for blocks/pages wholly outside the
    # window (O(s*w) prefill MXU work; paged-page DMAs still walk the whole
    # table — the grid is static).
    sliding_window: int = 0
    # Gemma-2: the window applies only to ALTERNATE layers (even layers
    # sliding, odd layers full attention). The layer scan runs over PAIRS so
    # each half keeps a STATIC window. Requires even num_layers.
    alt_sliding_window: bool = False

    # Mixture of Experts (0 experts = dense MLP). The expert dim shards over
    # the mesh's "ep" axis; see ops/moe.py.
    num_experts: int = 0
    experts_per_token: int = 2
    expert_capacity_factor: float = 1.25

    # Precision
    dtype: str = "bfloat16"
    remat: bool = False
    # Int8 execution path once params are quantized (ops/int8.py):
    #   w8a16       — weight-only; dequant folded into the matmul epilogue.
    #   w8a8        — dynamic activation quant, int8xint8->int32 MXU via XLA.
    #   w8a8_pallas — fused Pallas kernel (quantize + dot + rescale in VMEM);
    #                 falls back to w8a8 where shapes don't tile.
    #   w8a8_pallas_pre — activations quantized once in XLA (fused into the
    #                 producing op); Pallas kernel streams int8 on both sides
    #                 and accumulates natively in int32.
    quant_mode: str = "w8a16"
    # Per-PHASE override: prefill compiles as its own program, so it can run
    # a different int8 path than decode ("" = same as quant_mode). Decode is
    # HBM-bound (the XLA dynamic path measured fastest there); prefill is
    # MXU-bound at large M, where the fused Pallas kernel's big tiles win —
    # runtime/generate swaps the cfg between the two programs, and
    # precision "int8_w8a8_auto" measures BOTH phases and sets each to its
    # winner (ops/int8.measure_w8a8_mode).
    prefill_quant_mode: str = ""

    # Attention backend: "auto" = Pallas flash kernel for prefill on TPU,
    # XLA einsum elsewhere; "flash" forces the kernel (interpreted off-TPU);
    # "xla" forces the einsum path.
    attention_impl: str = "auto"

    @property
    def head_size(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @property
    def rope_scaling(self):
        """Hashable scaling tuple for ops.rope, or None when unscaled."""
        if not self.rope_scaling_type:
            return None
        return (
            self.rope_scaling_type,
            self.rope_scaling_factor,
            self.rope_low_freq_factor,
            self.rope_high_freq_factor,
            self.rope_original_max_position,
        )

    @property
    def rotary_dim(self) -> int:
        # Round to even; HF families use even rotary dims (e.g. Phi-2: 32).
        rd = int(self.head_size * self.rotary_fraction)
        return rd - (rd % 2)

    @property
    def query_scale(self) -> float | None:
        """Attention score scale: Gemma-2's fixed query_pre_attn_scalar^-0.5
        when set, else None (attend defaults to head_dim^-0.5). EVERY attend
        caller must consume this — a backend using the default scale on a
        fixed-scale config produces silently wrong logits."""
        if self.query_pre_attn_scalar > 0:
            return self.query_pre_attn_scalar**-0.5
        return None

    @property
    def gated(self) -> bool:
        """Whether the MLP is gated (gate/up/down); see ``gated_mlp``."""
        if self.gated_mlp is not None:
            return self.gated_mlp
        return self.activation == "silu"

    @property
    def activation_dtype(self):
        return jnp.dtype(self.dtype)

    def replace(self, **kw) -> "ModelConfig":
        return dataclasses.replace(self, **kw)


class KVCache(NamedTuple):
    """Whole-model cache: k/v are [num_layers, batch, max_seq, kv_heads, head_dim];
    ``lengths`` is the per-row filled length [batch]."""

    k: jnp.ndarray
    v: jnp.ndarray
    lengths: jnp.ndarray


def init_kv_cache(cfg: ModelConfig, batch: int, max_seq: int | None = None, dtype=None) -> KVCache:
    max_seq = max_seq or cfg.max_seq_len
    dtype = dtype or cfg.activation_dtype
    shape = (cfg.num_layers, batch, max_seq, cfg.num_kv_heads, cfg.head_size)
    return KVCache(
        k=jnp.zeros(shape, dtype=dtype),
        v=jnp.zeros(shape, dtype=dtype),
        lengths=jnp.zeros((batch,), dtype=jnp.int32),
    )


# ---------------------------------------------------------------------------
# Initialization (random weights; HF checkpoint ingest lives in hf_ingest.py)
# ---------------------------------------------------------------------------


def _dense_init(key, in_dim: int, out_dim: int, dtype, bias: bool) -> Params:
    scale = in_dim**-0.5
    p: Params = {
        "kernel": (jax.random.normal(key, (in_dim, out_dim), jnp.float32) * scale).astype(dtype)
    }
    if bias:
        p["bias"] = jnp.zeros((out_dim,), dtype)
    return p


def _norm_init(cfg: ModelConfig, dtype) -> Params:
    p: Params = {"scale": jnp.ones((cfg.hidden_size,), dtype)}
    if cfg.norm == "ln":
        p["bias"] = jnp.zeros((cfg.hidden_size,), dtype)
    return p


def init_params(cfg: ModelConfig, rng: jax.Array) -> Params:
    """Random init with every layer leaf stacked along a leading L axis."""
    dtype = cfg.activation_dtype
    h, nh, kh, hd = cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    inter = cfg.intermediate_size
    keys = jax.random.split(rng, 16)

    def stack_layers(make_one):
        layer_keys = jax.random.split(keys[0], cfg.num_layers)
        return jax.vmap(make_one)(layer_keys)

    def one_layer(key) -> Params:
        ks = jax.random.split(key, 8)
        layer: Params = {
            "attn_norm": _norm_init(cfg, dtype),
            "q": _dense_init(ks[0], h, nh * hd, dtype, cfg.qkv_bias),
            "k": _dense_init(ks[1], h, kh * hd, dtype, cfg.qkv_bias),
            "v": _dense_init(ks[2], h, kh * hd, dtype, cfg.qkv_bias),
            "o": _dense_init(ks[3], nh * hd, h, dtype, cfg.out_bias),
        }
        if cfg.qk_norm:
            layer["q_norm"] = {"scale": jnp.ones((hd,), dtype)}
            layer["k_norm"] = {"scale": jnp.ones((hd,), dtype)}
        if not cfg.shared_input_norm:
            layer["mlp_norm"] = _norm_init(cfg, dtype)
        if cfg.post_block_norms:
            layer["attn_post_norm"] = _norm_init(cfg, dtype)
            layer["mlp_post_norm"] = _norm_init(cfg, dtype)
        if cfg.num_experts > 0:
            from edgemesh.ops.moe import init_moe_layer

            layer["moe"] = init_moe_layer(cfg, ks[4])
            return layer
        if cfg.gated:
            layer["gate"] = _dense_init(ks[4], h, inter, dtype, cfg.out_bias)
        layer["up"] = _dense_init(ks[5], h, inter, dtype, cfg.out_bias)
        layer["down"] = _dense_init(ks[6], inter, h, dtype, cfg.out_bias)
        return layer

    params: Params = {
        "embed": {
            "weight": (jax.random.normal(keys[1], (cfg.vocab_size, h), jnp.float32) * 0.02).astype(dtype)
        },
        "layers": stack_layers(one_layer),
        "final_norm": _norm_init(cfg, dtype),
    }
    if cfg.learned_positions:
        params["pos_embed"] = {
            "weight": (jax.random.normal(keys[3], (cfg.max_seq_len, h), jnp.float32) * 0.02).astype(dtype)
        }
    if not cfg.tie_embeddings:
        params["lm_head"] = _dense_init(keys[2], h, cfg.vocab_size, dtype, cfg.lm_head_bias)
    return params


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def embed_tokens(
    cfg: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,
    positions: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Token-embedding lookup, quantization-aware.

    With an int8 embedding (ops/int8.quantize_embedding) the gather reads
    int8 rows + one fp32 scale per row and dequantizes on the VPU — b·s rows
    of traffic either way, but the table held in HBM at half size. The single
    entry point for every forward path (single-chip scan, pipeline stages,
    4D SPMD, paged decode).

    ``positions`` is required for learned-position families (GPT-2): the
    wpe row is added to the token row here so the rest of the stack stays
    position-mechanism-agnostic (rotary families ignore it)."""
    embed = params["embed"]
    if "weight_q" in embed:
        rows = embed["weight_q"][tokens].astype(jnp.float32)
        x = (rows * embed["scales"][tokens][..., None]).astype(cfg.activation_dtype)
    else:
        x = embed["weight"][tokens].astype(cfg.activation_dtype)
    if cfg.embed_scale:
        # Gemma: sqrt(h) cast through the model dtype first (HF multiplies by
        # a bf16 normalizer tensor — matching the rounding keeps logit parity).
        x = x * jnp.asarray(cfg.hidden_size**0.5, cfg.activation_dtype)
    if cfg.learned_positions:
        if positions is None:
            raise ValueError(
                "cfg.learned_positions requires embed_tokens(..., positions=...)"
            )
        x = x + params["pos_embed"]["weight"][positions].astype(cfg.activation_dtype)
    return x


def dense(p: Params, x: jnp.ndarray, quant_mode: str = "w8a16") -> jnp.ndarray:
    """Linear layer; dispatches to the int8/int4 path when the param leaf is
    quantized ({"kernel_q", "scales"} from ops/int8.py; {"kernel_q4", …}
    from ops/int4.py) and applies the SmoothQuant activation division when a
    "smooth" leaf is present. ``quant_mode`` (a trace-time constant from
    ModelConfig) selects between the w8a16 epilogue-dequant matmul, the XLA
    w8a8 dynamic-quant matmul, and the fused Pallas w8a8 kernel; int4 is
    always weight-only (w4a16)."""
    if "kernel_q4" in p:
        from edgemesh.ops.int4 import int4_matmul

        y = int4_matmul(x, p["kernel_q4"], p["scales"])
    elif "kernel_q" in p:
        from edgemesh.ops import int8 as int8_ops

        if "smooth" in p:
            x = x / p["smooth"].astype(x.dtype)
        if quant_mode == "w8a8":
            y = int8_ops.int8_matmul_dynamic(x, p["kernel_q"], p["scales"])
        elif quant_mode == "w8a8_pallas":
            y = int8_ops.int8_matmul_fused(
                x, p["kernel_q"], p["scales"],
                interpret=not on_tpu(),
            )
        elif quant_mode == "w8a8_pallas_pre":
            y = int8_ops.int8_matmul_prequant(
                x, p["kernel_q"], p["scales"],
                interpret=not on_tpu(),
            )
        elif quant_mode == "w8a16":
            y = int8_ops.int8_matmul(x, p["kernel_q"], p["scales"])
        else:
            raise ValueError(f"unknown quant_mode {quant_mode!r}")
    else:
        y = x @ p["kernel"]
    if "bias" in p:
        y = y + p["bias"]
    if "lora_a" in p:
        # LoRA finetuning forward (ops/lora.py): activation-side low-rank
        # delta over the frozen kernel. Inference merges instead (zero cost).
        from edgemesh.ops.lora import apply_lora_dense

        y = apply_lora_dense(p, x, y)
    return y


def _apply_norm(cfg: ModelConfig, p: Params, x: jnp.ndarray) -> jnp.ndarray:
    if cfg.norm == "rms":
        scale = p["scale"]
        if cfg.norm_unit_offset:  # Gemma stores the delta from identity
            scale = scale.astype(jnp.float32) + 1.0
        return rms_norm(x, scale, cfg.norm_eps)
    return layer_norm(x, p["scale"], p["bias"], cfg.norm_eps)


def _mlp(cfg: ModelConfig, layer: Params, x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """FFN block → (y, aux). ``aux`` is the MoE load-balance loss (0 for the
    dense path) so the training loss can see it without re-running routers."""
    if cfg.num_experts > 0:
        from edgemesh.ops.moe import moe_mlp

        return moe_mlp(cfg, layer["moe"], x)
    zero = jnp.zeros((), jnp.float32)
    return dense(layer["down"], mlp_hidden(cfg, layer, x), cfg.quant_mode), zero


def mlp_hidden(cfg: ModelConfig, layer: Params, x: jnp.ndarray) -> jnp.ndarray:
    """The dense FFN up to (not including) the down projection — the seam
    the tensor-parallel engine needs to decompose ``down`` into chunks whose
    collectives overlap the next chunk's matmul (parallel/tp_infer.py).
    MoE blocks have no single down projection and stay on :func:`_mlp`."""
    qm = cfg.quant_mode
    if cfg.gated:
        return _activate(cfg, dense(layer["gate"], x, qm)) * dense(layer["up"], x, qm)
    return _activate(cfg, dense(layer["up"], x, qm))


def _activate(cfg: ModelConfig, x: jnp.ndarray) -> jnp.ndarray:
    if cfg.activation == "silu":
        return jax.nn.silu(x)
    return jax.nn.gelu(x, approximate=cfg.activation == "gelu_tanh")


def _use_flash(cfg: ModelConfig) -> bool:
    """Trace-time choice of prefill attention backend (cfg is a static jit arg).

    "auto" only picks the Pallas kernel on a single-device TPU process:
    under multi-chip GSPMD (plain jit over NamedSharding arrays) XLA cannot
    auto-partition a pallas_call, so the einsum path — which partitions
    cleanly — stays the default there. Distribution code that runs per-shard
    (shard_map bodies, where pallas sees local arrays) opts in explicitly
    with attention_impl="flash".
    """
    if cfg.attention_impl == "xla":
        return False
    if cfg.attention_impl == "flash":
        return True
    return on_tpu() and jax.device_count() == 1


def qkv_proj(
    cfg: ModelConfig, layer: Params, x: jnp.ndarray, positions: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Projected + roped q/k/v heads — the single source of truth shared by
    the dense-cache path below and the paged path (runtime/paged_generate.py)."""
    b, s, _ = x.shape
    nh, kh, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    q = dense(layer["q"], x, cfg.quant_mode).reshape(b, s, nh, hd)
    k = dense(layer["k"], x, cfg.quant_mode).reshape(b, s, kh, hd)
    v = dense(layer["v"], x, cfg.quant_mode).reshape(b, s, kh, hd)
    if cfg.qk_norm:  # Qwen3-style per-head RMSNorm, before RoPE
        q = rms_norm(q, layer["q_norm"]["scale"], cfg.norm_eps)
        k = rms_norm(k, layer["k_norm"]["scale"], cfg.norm_eps)
    if cfg.rotary_dim > 0:
        q = apply_rope(q, positions, cfg.rotary_dim, cfg.rope_theta, cfg.rope_scaling)
        k = apply_rope(k, positions, cfg.rotary_dim, cfg.rope_theta, cfg.rope_scaling)
    return q, k, v


def _attention(
    cfg: ModelConfig,
    layer: Params,
    x: jnp.ndarray,  # [b, s, h]
    positions: jnp.ndarray,  # [b, s]
    cache: LayerKV,
    kv_valid: jnp.ndarray,  # [b, max_seq]
    lengths: jnp.ndarray,  # [b] (write offsets for decode)
    is_decode: bool,
) -> tuple[jnp.ndarray, LayerKV]:
    out, cache = attention_core(
        cfg, layer, x, positions, cache, kv_valid, lengths, is_decode
    )
    return dense(layer["o"], out, cfg.quant_mode), cache


def attention_core(
    cfg: ModelConfig,
    layer: Params,
    x: jnp.ndarray,  # [b, s, h]
    positions: jnp.ndarray,  # [b, s]
    cache: LayerKV,
    kv_valid: jnp.ndarray,  # [b, max_seq]
    lengths: jnp.ndarray,  # [b] (write offsets for decode)
    is_decode: bool,
) -> tuple[jnp.ndarray, LayerKV]:
    """Everything up to (not including) the output projection — returns the
    attended heads flattened to [b, s, nh*hd] plus the cache state. The seam
    the tensor-parallel engine uses to chunk the ``o`` projection so each
    chunk's collective overlaps the next chunk's matmul
    (parallel/tp_infer.py)."""
    b, s, _ = x.shape
    nh, kh, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    q, k, v = qkv_proj(cfg, layer, x, positions)

    if is_decode:
        cache = write_decode(cache, k, v, lengths)
    else:
        cache = write_prefill(cache, k, v)

    if not is_decode and _use_flash(cfg):
        # Prefill starts from an empty cache (write_prefill writes at offset
        # 0), so the freshly computed k/v ARE the full visible prefix — the
        # flash kernel attends over them without re-reading the cache, and
        # the [s, s] score matrix never hits HBM.
        from edgemesh.ops.flash_attention import flash_attention

        kv_lens = jnp.sum(kv_valid, axis=1).astype(jnp.int32)
        out = flash_attention(
            q, k, v, kv_lens, causal=True, scale=cfg.query_scale,
            interpret=cfg.attention_impl == "flash" and not on_tpu(),
            sliding_window=cfg.sliding_window, soft_cap=cfg.attn_soft_cap,
        )
    else:
        out = attend(
            q, cache, positions, kv_valid, scale=cfg.query_scale,
            sliding_window=cfg.sliding_window, soft_cap=cfg.attn_soft_cap,
        )
    return out.reshape(b, s, nh * hd), cache


def _layer_fn(
    cfg: ModelConfig,
    x: jnp.ndarray,
    layer: Params,
    layer_kv,
    positions: jnp.ndarray,
    kv_valid: jnp.ndarray,
    lengths: jnp.ndarray,
    is_decode: bool,
    attention=_attention,
    mlp=_mlp,
) -> tuple[jnp.ndarray, Any, jnp.ndarray]:
    """One transformer block → (x, kv_state, moe_aux). ``attention`` and
    ``mlp`` are pluggable module-level callables with _attention's/_mlp's
    signatures so alternate backends reuse the exact residual wiring of all
    three families: the paged KV cache (runtime/paged_generate.py) swaps
    ``attention``; the tensor-parallel shard_map engine
    (parallel/tp_infer.py) swaps both to psum partial outputs over ``tp``
    before the residual add. ``layer_kv`` is whatever state pytree the
    attention backend carries. ``moe_aux`` is the layer's load-balance loss
    (0 for dense MLPs).
    """
    if cfg.parallel_block:
        # Phi-2 (shared_input_norm=True): y = x + attn(ln(x)) + mlp(ln(x))
        # NeoX parallel residual:         y = x + attn(ln1(x)) + mlp(ln2(x))
        attn_in = _apply_norm(cfg, layer["attn_norm"], x)
        mlp_in = attn_in if cfg.shared_input_norm else _apply_norm(cfg, layer["mlp_norm"], x)
        attn_out, layer_kv = attention(cfg, layer, attn_in, positions, cache=layer_kv,
                                       kv_valid=kv_valid, lengths=lengths, is_decode=is_decode)
        mlp_out, aux = mlp(cfg, layer, mlp_in)
        return x + attn_out + mlp_out, layer_kv, aux
    # Sequential (Llama): x += attn(norm(x)); x += mlp(norm(x)).
    # Gemma-2 (post_block_norms) additionally norms each sublayer OUTPUT
    # before its residual add: x += post_norm(attn(norm(x))) etc.
    attn_out, layer_kv = attention(
        cfg, layer, _apply_norm(cfg, layer["attn_norm"], x), positions,
        cache=layer_kv, kv_valid=kv_valid, lengths=lengths, is_decode=is_decode,
    )
    if cfg.post_block_norms:
        attn_out = _apply_norm(cfg, layer["attn_post_norm"], attn_out)
    x = x + attn_out
    mlp_out, aux = mlp(cfg, layer, _apply_norm(cfg, layer["mlp_norm"], x))
    if cfg.post_block_norms:
        mlp_out = _apply_norm(cfg, layer["mlp_post_norm"], mlp_out)
    return x + mlp_out, layer_kv, aux


def lm_head_logits(cfg: ModelConfig, params: Params, x: jnp.ndarray) -> jnp.ndarray:
    """final_norm → (tied or untied) LM head → optional logit soft-cap.

    The single definition shared by the single-chip forward, the pipeline
    engine, and the 4D SPMD train step — head handling changes land in all
    three at once."""
    x = _apply_norm(cfg, params["final_norm"], x)
    if cfg.tie_embeddings or "lm_head" not in params:
        embed = params["embed"]
        if "weight_q" in embed:
            # Tied int8 head: w8a16 epilogue over the int8 rows — the dequant
            # (per-vocab-row scale) folds into the matmul output, halving the
            # head's HBM read vs the bf16 table.
            y = jnp.matmul(
                x, embed["weight_q"].T.astype(cfg.activation_dtype),
                preferred_element_type=jnp.float32,
            )
            logits = (y * embed["scales"].astype(jnp.float32)).astype(cfg.activation_dtype)
        else:
            logits = x @ embed["weight"].T.astype(cfg.activation_dtype)
    else:
        logits = dense(params["lm_head"], x, cfg.quant_mode)
    if cfg.logit_soft_cap > 0:
        logits = cfg.logit_soft_cap * jnp.tanh(logits / cfg.logit_soft_cap)
    return logits


def _forward(
    cfg: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,  # [b, s]
    positions: jnp.ndarray,  # [b, s]
    cache: KVCache,
    kv_valid: jnp.ndarray,  # [b, max_seq]
    is_decode: bool,
    attention=_attention,
    mlp=_mlp,
) -> tuple[jnp.ndarray, KVCache, jnp.ndarray]:
    """Shared prefill/decode body: scan one compiled layer over stacked
    params. Returns (logits, cache, summed moe aux loss)."""
    x, new_cache, aux_sum = _scan_layers(
        cfg, params, tokens, positions, cache, kv_valid, is_decode, attention, mlp
    )
    logits = lm_head_logits(cfg, params, x)
    return logits, new_cache, aux_sum


def _scan_layers(
    cfg: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,
    positions: jnp.ndarray,
    cache: KVCache,
    kv_valid: jnp.ndarray,
    is_decode: bool,
    attention=_attention,
    mlp=_mlp,
) -> tuple[jnp.ndarray, KVCache, jnp.ndarray]:
    """embed → layer scan; returns PRE-final-norm hidden states [b, s, h]
    (lm_head_logits applies the final norm) plus cache and moe aux."""
    x = embed_tokens(cfg, params, tokens, positions)

    def one_layer(fn_cfg, h, layer, k_l, v_l):
        fn = _layer_fn
        if cfg.remat:
            fn = jax.checkpoint(fn, static_argnums=(0, 7, 8, 9))
        return fn(fn_cfg, h, layer, LayerKV(k_l, v_l), positions, kv_valid,
                  cache.lengths, is_decode, attention, mlp)

    def body(layer_cfg, carry, scanned):
        h, aux_sum = carry
        layer, k_l, v_l = scanned
        h, new_kv, aux = one_layer(layer_cfg, h, layer, k_l, v_l)
        return (h, aux_sum + aux), (new_kv.k, new_kv.v)

    (x, aux_sum), (new_k, new_v) = layer_scan_alt_windows(
        cfg, body, (x, jnp.zeros((), jnp.float32)),
        (params["layers"], cache.k, cache.v),
    )
    new_lengths = jnp.max(positions, axis=1) + 1
    return x, KVCache(new_k, new_v, new_lengths), aux_sum


def layer_scan_alt_windows(cfg: ModelConfig, body, init_carry, xs):
    """``lax.scan`` over the stacked layer axis, honoring Gemma-2's
    alternating sliding windows when configured.

    ``body(layer_cfg, carry, xs_slice) -> (carry, outs)`` with ``outs`` a
    tuple of per-layer arrays; ``xs`` is a tuple of pytrees whose leaves
    carry a leading layer axis. Without alternation this is a plain scan
    with ``layer_cfg = cfg``. With it, layers scan in PAIRS — the even
    member keeps ``cfg`` (windowed), the odd runs ``sliding_window=0`` — so
    each half's window stays a STATIC per-call constant (one compiled pair
    body, no traced windows). The single source of the pair trick for the
    dense scan, the int8-KV scan (runtime/quant_kv.py), and the pipeline
    stage scan (parallel/pipeline.py); callers whose leading axis is a
    stage-local slice must start on an even global layer (the pipeline
    engine enforces even layers-per-stage)."""
    n = jax.tree.leaves(xs)[0].shape[0]
    if not (cfg.alt_sliding_window and cfg.sliding_window > 0):
        return jax.lax.scan(lambda c, sl: body(cfg, c, sl), init_carry, xs)
    if n % 2:
        raise ValueError(f"alt_sliding_window needs an even layer count, got {n}")
    full_cfg = cfg.replace(sliding_window=0)

    def pair(a):
        return a.reshape(n // 2, 2, *a.shape[1:])

    def pair_body(carry, scanned):
        even = jax.tree.map(lambda a: a[0], scanned)
        odd = jax.tree.map(lambda a: a[1], scanned)
        carry, outs_e = body(cfg, carry, even)
        carry, outs_o = body(full_cfg, carry, odd)
        return carry, tuple(jnp.stack([e, o]) for e, o in zip(outs_e, outs_o))

    carry, outs = jax.lax.scan(pair_body, init_carry, jax.tree.map(pair, xs))
    return carry, tuple(a.reshape(n, *a.shape[2:]) for a in outs)


@partial(jax.jit, static_argnums=(0,))
def forward_hidden(
    cfg: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,  # [b, s] right-padded
    lengths: jnp.ndarray,  # [b] true lengths
) -> jnp.ndarray:
    """Final-norm contextual hidden states [b, s, hidden] — the encoder view
    of a decoder model, used by the model-based embedding metrics
    (eval/embedder.py): mean-pooled for sentence cosine, per-position for
    BERTScore token matching (reference analog: the sentence-transformer +
    roberta encoders, combiner_fp.py:302-316,421)."""
    b, s = tokens.shape
    cache = init_kv_cache(cfg, b, s)
    positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    positions = jnp.minimum(positions, (jnp.maximum(lengths, 1) - 1)[:, None])
    kv_valid = jnp.arange(s)[None, :] < lengths[:, None]
    x, _, _ = _scan_layers(cfg, params, tokens, positions, cache, kv_valid, is_decode=False)
    return _apply_norm(cfg, params["final_norm"], x)


@partial(jax.jit, static_argnums=(0,))
def forward_prefill(
    cfg: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,  # [b, s] right-padded prompts
    lengths: jnp.ndarray,  # [b] true prompt lengths
    cache: KVCache,
) -> tuple[jnp.ndarray, KVCache]:
    """Run the full prompt; returns logits at the LAST REAL token [b, vocab]
    and the filled cache."""
    b, s = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    max_seq = cache.k.shape[2]
    kv_valid = jnp.arange(max_seq)[None, :] < lengths[:, None]
    # Clamp padded positions to the last real position so their (ignored)
    # rope/mask values stay in range.
    positions = jnp.minimum(positions, (lengths - 1)[:, None])
    logits, cache, _ = _forward(cfg, params, tokens, positions, cache, kv_valid, is_decode=False)
    last = logits[jnp.arange(b), lengths - 1]
    return last, KVCache(cache.k, cache.v, lengths)


@partial(jax.jit, static_argnums=(0,))
def forward_decode(
    cfg: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,  # [b] one new token per row
    cache: KVCache,
) -> tuple[jnp.ndarray, KVCache]:
    """One autoregressive step. Returns next-token logits [b, vocab]."""
    b = tokens.shape[0]
    positions = cache.lengths[:, None]  # [b, 1] — position of the new token
    max_seq = cache.k.shape[2]
    kv_valid = jnp.arange(max_seq)[None, :] <= cache.lengths[:, None]
    logits, new_cache, _ = _forward(
        cfg, params, tokens[:, None], positions, cache, kv_valid, is_decode=True
    )
    return logits[:, 0], KVCache(new_cache.k, new_cache.v, cache.lengths + 1)


@partial(jax.jit, static_argnums=(0,))
def forward_verify(
    cfg: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,  # [b, s] chunk of already-chosen tokens per row
    cache: KVCache,
) -> tuple[jnp.ndarray, KVCache]:
    """Chunk-append decode: process ``s`` tokens per row in ONE forward,
    writing their K/V at each row's current length and attending causally
    within the chunk + over the cached prefix. The target-model verification
    step of speculative decoding (runtime/speculative.py) — one MXU-friendly
    [b*s] matmul instead of s sequential decode steps. Returns logits for
    every chunk position [b, s, vocab] and the cache advanced by s (callers
    rewind rejected suffixes by lowering ``lengths``; stale slots are
    re-written by the next chunk and masked by kv_valid meanwhile)."""
    b, s = tokens.shape
    positions = cache.lengths[:, None] + jnp.arange(s)[None, :]  # [b, s]
    max_seq = cache.k.shape[2]
    kv_valid = jnp.arange(max_seq)[None, :] < (cache.lengths + s)[:, None]
    logits, new_cache, _ = _forward(
        cfg, params, tokens, positions, cache, kv_valid, is_decode=True
    )
    return logits, KVCache(new_cache.k, new_cache.v, cache.lengths + s)
