"""Decoder-only model families: generic transformer + Llama/NeoX(Pythia)/Phi-2 presets."""

from edgemesh.models.transformer import (  # noqa: F401
    KVCache,
    ModelConfig,
    forward_decode,
    forward_prefill,
    init_kv_cache,
    init_params,
)
from edgemesh.models.families import FAMILY_PRESETS, config_for_family  # noqa: F401
