"""BERT-family bidirectional encoder (MiniLM / BERT / sentence-transformers).

The reference scores its semantic metrics with two downloaded encoders: a
sentence-transformers MiniLM for cosine similarity
(``Code/C-DAC Server/combiner_fp.py:312-316,421``) and a roberta-backed
BERTScore (``:302-305``); its downloader snapshots ``all-MiniLM-L6-v2``
(``Code/C-DAC Server/download.py:26-28,43``). This module is the edgemesh
ingest + forward for that model class, so ``ModelEmbedder``
(eval/embedder.py) can host a real MiniLM-class checkpoint and produce
cosine/BERTScore numbers comparable to the reference's.

Architecturally BERT is NOT a dial set on the decoder (models/transformer.py):
it is bidirectional (no causal mask, no KV cache), post-LayerNorm
(norms AFTER each residual add, not before), and uses learned absolute
position + token-type embeddings instead of rotary. Forcing those through the
decoder's pre-norm residual wiring would contort both; the encoder gets its
own ~self-contained forward instead, sharing the TPU-first design rules:
stacked layer params + ``lax.scan`` (one compiled layer body), static
shapes, fp32 norm/softmax islands, matmuls in the configured dtype.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass
from functools import partial
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from edgemesh.ops.norms import layer_norm

Params = dict[str, Any]


@dataclass(frozen=True)
class EncoderConfig:
    vocab_size: int = 30522
    hidden_size: int = 384
    num_layers: int = 6
    num_heads: int = 12
    intermediate_size: int = 1536
    max_seq_len: int = 512  # max_position_embeddings
    type_vocab_size: int = 2
    norm_eps: float = 1e-12
    activation: str = "gelu"  # gelu | gelu_tanh | relu
    dtype: str = "float32"  # metric fidelity over MXU speed for tiny encoders

    @property
    def head_size(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def activation_dtype(self):
        return jnp.dtype(self.dtype)

    def replace(self, **kw) -> "EncoderConfig":
        return dataclasses.replace(self, **kw)


def _dense_init(key, in_dim: int, out_dim: int, dtype) -> Params:
    k = (jax.random.normal(key, (in_dim, out_dim), jnp.float32) * in_dim**-0.5).astype(dtype)
    return {"kernel": k, "bias": jnp.zeros((out_dim,), dtype)}


def _norm_init(cfg: EncoderConfig, dtype) -> Params:
    return {"scale": jnp.ones((cfg.hidden_size,), dtype),
            "bias": jnp.zeros((cfg.hidden_size,), dtype)}


def init_params(cfg: EncoderConfig, rng: jax.Array) -> Params:
    """Random init, every layer leaf stacked along a leading L axis."""
    dtype = cfg.activation_dtype
    h, inter = cfg.hidden_size, cfg.intermediate_size
    keys = jax.random.split(rng, 8)

    def one_layer(key) -> Params:
        ks = jax.random.split(key, 6)
        return {
            "q": _dense_init(ks[0], h, h, dtype),
            "k": _dense_init(ks[1], h, h, dtype),
            "v": _dense_init(ks[2], h, h, dtype),
            "o": _dense_init(ks[3], h, h, dtype),
            "attn_norm": _norm_init(cfg, dtype),
            "up": _dense_init(ks[4], h, inter, dtype),
            "down": _dense_init(ks[5], inter, h, dtype),
            "mlp_norm": _norm_init(cfg, dtype),
        }

    emb = 0.02 * jax.random.normal(keys[1], (cfg.vocab_size, h), jnp.float32)
    pos = 0.02 * jax.random.normal(keys[2], (cfg.max_seq_len, h), jnp.float32)
    typ = 0.02 * jax.random.normal(keys[3], (cfg.type_vocab_size, h), jnp.float32)
    return {
        "embed": {
            "word": emb.astype(dtype),
            "position": pos.astype(dtype),
            "token_type": typ.astype(dtype),
            "norm": _norm_init(cfg, dtype),
        },
        "layers": jax.vmap(one_layer)(jax.random.split(keys[0], cfg.num_layers)),
    }


def _activate(cfg: EncoderConfig, x: jnp.ndarray) -> jnp.ndarray:
    if cfg.activation == "relu":
        return jax.nn.relu(x)
    return jax.nn.gelu(x, approximate=cfg.activation == "gelu_tanh")


def _dense(p: Params, x: jnp.ndarray) -> jnp.ndarray:
    return x @ p["kernel"] + p["bias"]


def _post_ln(cfg: EncoderConfig, p: Params, x: jnp.ndarray) -> jnp.ndarray:
    return layer_norm(x, p["scale"], p["bias"], cfg.norm_eps)


def _layer(cfg: EncoderConfig, layer: Params, x: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """One post-LN block: x = LN(x + attn(x)); x = LN(x + mlp(x)).

    ``mask`` is [b, s] validity; attention is bidirectional over valid
    positions only (padding is excluded as both query context and key)."""
    b, s, h = x.shape
    nh, hd = cfg.num_heads, cfg.head_size
    q = _dense(layer["q"], x).reshape(b, s, nh, hd)
    k = _dense(layer["k"], x).reshape(b, s, nh, hd)
    v = _dense(layer["v"], x).reshape(b, s, nh, hd)
    scores = jnp.einsum("bqnd,bknd->bnqk", q, k).astype(jnp.float32) * hd**-0.5
    scores = jnp.where(mask[:, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    attn = jnp.einsum("bnqk,bknd->bqnd", probs, v).reshape(b, s, h)
    x = _post_ln(cfg, layer["attn_norm"], x + _dense(layer["o"], attn))
    mlp = _dense(layer["down"], _activate(cfg, _dense(layer["up"], x)))
    return _post_ln(cfg, layer["mlp_norm"], x + mlp)


@partial(jax.jit, static_argnums=(0,))
def forward_hidden(
    cfg: EncoderConfig,
    params: Params,
    tokens: jnp.ndarray,  # [b, s] right-padded
    lengths: jnp.ndarray,  # [b] true lengths
) -> jnp.ndarray:
    """Contextual hidden states [b, s, hidden] — the same protocol as the
    decoder's forward_hidden (models/transformer.py), so ModelEmbedder hosts
    either interchangeably."""
    b, s = tokens.shape
    dtype = cfg.activation_dtype
    emb = params["embed"]
    x = (
        emb["word"][tokens]
        + emb["position"][jnp.arange(s)][None, :, :]
        + emb["token_type"][jnp.zeros((b, s), jnp.int32)]
    ).astype(dtype)
    x = _post_ln(cfg, emb["norm"], x)
    mask = jnp.arange(s)[None, :] < lengths[:, None]

    def body(h, layer):
        return _layer(cfg, layer, h, mask), None

    x, _ = jax.lax.scan(body, x, params["layers"])
    return x


# ---------------------------------------------------------------------------
# HF checkpoint ingest (model_type == "bert": BERT, MiniLM, sentence-BERT)
# ---------------------------------------------------------------------------


def config_from_checkpoint(ckpt: str | Path, **overrides) -> EncoderConfig:
    ckpt = Path(ckpt)
    with open(ckpt / "config.json") as f:
        hf = json.load(f)
    pe_type = hf.get("position_embedding_type", "absolute")
    if pe_type != "absolute":
        # Fail at ingest, not with silently wrong embeddings downstream.
        raise ValueError(
            f"unsupported position_embedding_type {pe_type!r} in "
            f"{ckpt / 'config.json'}; the bert family supports 'absolute'"
        )
    act = hf.get("hidden_act", "gelu")
    if act not in ("gelu", "gelu_new", "gelu_pytorch_tanh", "relu"):
        raise ValueError(f"unsupported hidden_act {act!r} for the bert family")
    kw = dict(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        intermediate_size=hf["intermediate_size"],
        max_seq_len=hf.get("max_position_embeddings", 512),
        type_vocab_size=hf.get("type_vocab_size", 2),
        norm_eps=hf.get("layer_norm_eps", 1e-12),
        activation={"gelu_new": "gelu_tanh", "gelu_pytorch_tanh": "gelu_tanh"}.get(act, act),
    )
    kw.update(overrides)
    return EncoderConfig(**kw)


def load_encoder(ckpt: str | Path, cfg: EncoderConfig | None = None,
                 dtype=None) -> tuple[EncoderConfig, Params]:
    """Load an HF bert-family checkpoint directory into
    (EncoderConfig, stacked param tree). Accepts both bare ``BertModel``
    key naming and the ``bert.``-prefixed task-head variants
    (BertForMaskedLM etc.); task heads and the pooler are dropped —
    sentence-transformers MiniLM mean-pools token states, as does
    ModelEmbedder."""
    from edgemesh.models.hf_ingest import _load_raw_tensors

    ckpt = Path(ckpt)
    cfg = cfg or config_from_checkpoint(ckpt)
    dtype = dtype or cfg.activation_dtype
    raw = _load_raw_tensors(ckpt)
    raw = {k.removeprefix("bert."): v for k, v in raw.items()}
    L = cfg.num_layers

    def stack(fmt: str, transpose: bool) -> jnp.ndarray:
        mats = [raw[fmt.format(i)] for i in range(L)]
        if transpose:
            mats = [np.ascontiguousarray(m.T) for m in mats]
        return jnp.asarray(np.stack(mats), dtype)

    def stacked_dense(name: str) -> Params:
        return {
            "kernel": stack("encoder.layer.{}." + name + ".weight", True),
            "bias": stack("encoder.layer.{}." + name + ".bias", False),
        }

    def stacked_norm(name: str) -> Params:
        return {
            "scale": stack("encoder.layer.{}." + name + ".weight", False),
            "bias": stack("encoder.layer.{}." + name + ".bias", False),
        }

    params: Params = {
        "embed": {
            "word": jnp.asarray(raw["embeddings.word_embeddings.weight"], dtype),
            "position": jnp.asarray(raw["embeddings.position_embeddings.weight"], dtype),
            "token_type": jnp.asarray(raw["embeddings.token_type_embeddings.weight"], dtype),
            "norm": {
                "scale": jnp.asarray(raw["embeddings.LayerNorm.weight"], dtype),
                "bias": jnp.asarray(raw["embeddings.LayerNorm.bias"], dtype),
            },
        },
        "layers": {
            "q": stacked_dense("attention.self.query"),
            "k": stacked_dense("attention.self.key"),
            "v": stacked_dense("attention.self.value"),
            "o": stacked_dense("attention.output.dense"),
            "attn_norm": stacked_norm("attention.output.LayerNorm"),
            "up": stacked_dense("intermediate.dense"),
            "down": stacked_dense("output.dense"),
            "mlp_norm": stacked_norm("output.LayerNorm"),
        },
    }
    return cfg, params
