"""HF-checkpoint → edgemesh param pytree ingestion (host-side, no torch on TPU).

Replaces the reference's ``AutoModelForCausalLM.from_pretrained(...,
device_map="auto")`` loaders (``Code/C-DAC Server/combiner_fp.py:274-284``)
with: read safetensors straight into numpy, remap names per family, stack the
layer axis, and ``jax.device_put`` the tree into (sharded) HBM
(edgemesh.parallel.sharding.shard_params — the BASELINE.json north star's
"materialises weights directly into HBM via jax.device_put").

Name maps cover the reference's three model families (ACL paper §4.2) —
Llama (Llama-3.2-1B-Instruct), GPT-NeoX (Pythia-1B), Phi (Phi-2) — plus
Mistral, Mixtral (routed MoE), Qwen2, Qwen3 (QK-norm), Gemma, Gemma-2,
Phi-3, GPT-2, and Falcon (families.py registry; each pinned against HF
logits in tests/test_hf_parity.py).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

import jax.numpy as jnp
import numpy as np

from edgemesh.models.families import sniff_family
from edgemesh.models.transformer import ModelConfig
from edgemesh.models.families import config_for_family

Params = dict[str, Any]


def _load_raw_tensors(ckpt: Path) -> dict[str, np.ndarray]:
    """Read all tensors from safetensors (single or index-sharded) or a
    pytorch_model.bin fallback, as numpy."""
    from safetensors import safe_open

    files: list[Path]
    index = ckpt / "model.safetensors.index.json"
    single = ckpt / "model.safetensors"
    if index.exists():
        with open(index) as f:
            weight_map = json.load(f)["weight_map"]
        files = sorted({ckpt / fname for fname in weight_map.values()})
    elif single.exists():
        files = [single]
    else:
        st_files = sorted(ckpt.glob("*.safetensors"))
        if st_files:
            files = st_files
        else:
            bin_path = ckpt / "pytorch_model.bin"
            if bin_path.exists():
                import torch

                sd = torch.load(bin_path, map_location="cpu", weights_only=True)
                return {k: v.float().numpy() if v.dtype == torch.bfloat16 else v.numpy() for k, v in sd.items()}
            raise FileNotFoundError(f"no safetensors/bin weights under {ckpt}")

    out: dict[str, np.ndarray] = {}
    for fpath in files:
        with safe_open(fpath, framework="np") as f:
            for key in f.keys():
                out[key] = f.get_tensor(key)
    return out


def _rope_scaling_kw(hf: dict, ckpt: Path) -> dict:
    """Parse an HF rope_scaling block into ModelConfig fields (rope-consuming
    families: llama, mistral). Llama-3.2 ships {"rope_type": "llama3",
    factor, low_freq_factor, high_freq_factor,
    original_max_position_embeddings}; older checkpoints use
    {"type": "linear", factor}."""
    rs = hf.get("rope_scaling") or {}
    if not rs:
        return {}
    rs_type = rs.get("rope_type", rs.get("type", "linear"))
    if rs_type not in ("linear", "llama3", "default", "none", ""):
        # Fail at ingest, not from inside the first jitted forward
        # (ops/rope.py would raise there, far from the cause).
        raise ValueError(
            f"unsupported rope_scaling type {rs_type!r} in "
            f"{ckpt / 'config.json'}; supported: linear, llama3"
        )
    return dict(
        rope_scaling_type=rs_type,
        rope_scaling_factor=float(rs.get("factor", 1.0)),
        rope_low_freq_factor=float(rs.get("low_freq_factor", 1.0)),
        rope_high_freq_factor=float(rs.get("high_freq_factor", 4.0)),
        rope_original_max_position=int(
            rs.get("original_max_position_embeddings", 8192)
        ),
    )


def config_from_checkpoint(ckpt: str | Path, **overrides) -> ModelConfig:
    """Build a ModelConfig from the checkpoint's HF config.json."""
    ckpt = Path(ckpt)
    family = sniff_family(ckpt)
    if family == "bert":
        raise ValueError(
            f"{ckpt} is a bert-family ENCODER checkpoint (no LM head / decode "
            "path); load it via models.encoder.load_encoder — e.g. point the "
            "eval config's `embedder:` at it for cosine/BERTScore"
        )
    with open(ckpt / "config.json") as f:
        hf = json.load(f)

    if family in ("llama", "mistral", "mixtral", "qwen2", "qwen3", "gemma", "gemma2", "phi3"):
        # One config dialect: mistral adds sliding-window attention, mixtral
        # adds routed experts on top of that, qwen2 adds qkv biases (preset),
        # gemma adds unit-offset norms / GeGLU / embed scaling (preset) and a
        # wide fixed head_dim, phi3 adds fused checkpoint weights (split at
        # load) + an always-on sliding window.
        kw = dict(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            num_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
            intermediate_size=hf["intermediate_size"],
            max_seq_len=min(hf.get("max_position_embeddings", 4096), 8192),
            rope_theta=float(hf.get("rope_theta", 10000.0)),
            norm_eps=hf.get("rms_norm_eps", 1e-5),
            tie_embeddings=hf.get("tie_word_embeddings", family in ("gemma", "gemma2")),
        )
        if family == "mistral":
            # null in newer configs (full attention); 4096 on the 7B v0.1.
            kw["sliding_window"] = int(hf.get("sliding_window") or 0)
        elif family == "mixtral":
            kw["sliding_window"] = int(hf.get("sliding_window") or 0)
            E = int(hf["num_local_experts"])
            k = int(hf["num_experts_per_tok"])
            kw["num_experts"] = E
            kw["experts_per_token"] = k
            # HF's MixtralSparseMoeBlock never drops tokens; the GShard
            # default factor (1.25) WOULD under routing imbalance, silently
            # diverging from the checkpoint's own behavior. E/k makes
            # capacity = num_tokens — mathematically dropless — at the cost
            # of a [T, E, T] dispatch tensor (fine to ~2k-token prefills;
            # long-prompt serving chunks prefill anyway). Override via
            # config_from_checkpoint(..., expert_capacity_factor=...) to
            # trade exactness for dispatch memory.
            kw["expert_capacity_factor"] = float(E) / k
        elif family == "qwen2":
            # Qwen2's use_sliding_window applies the window only to layers
            # >= max_window_layers (lower layers attend fully); this runtime
            # has one window for all layers, so approximating would silently
            # truncate the lower layers' context — same fail-at-ingest policy
            # as unconsumed rope_scaling below. Production Qwen2 configs ship
            # it disabled.
            if hf.get("use_sliding_window"):
                raise ValueError(
                    f"use_sliding_window=true in {ckpt / 'config.json'} is not "
                    "supported (per-layer windowing, max_window_layers="
                    f"{hf.get('max_window_layers')}); disable it or use a "
                    "full-attention checkpoint"
                )
        elif family == "qwen3":
            # Explicit head_dim (may differ from hidden/heads); same
            # per-layer-window refusal policy as qwen2.
            kw["head_dim"] = int(
                hf.get("head_dim") or hf["hidden_size"] // hf["num_attention_heads"]
            )
            if hf.get("use_sliding_window"):
                raise ValueError(
                    f"use_sliding_window=true in {ckpt / 'config.json'} is not "
                    "supported (per-layer windowing); disable it or use a "
                    "full-attention checkpoint"
                )
        elif family == "gemma":
            kw["head_dim"] = int(hf.get("head_dim", 256))
        elif family == "gemma2":
            kw["head_dim"] = int(hf.get("head_dim", 256))
            kw["sliding_window"] = int(hf.get("sliding_window") or 0)
            kw["query_pre_attn_scalar"] = float(hf.get("query_pre_attn_scalar", 256))
            kw["attn_soft_cap"] = float(hf.get("attn_logit_softcapping") or 0.0)
            kw["logit_soft_cap"] = float(hf.get("final_logit_softcapping") or 0.0)
        elif family == "phi3":
            kw["sliding_window"] = int(hf.get("sliding_window") or 0)
        kw.update(_rope_scaling_kw(hf, ckpt))
    elif family == "neox":
        kw = dict(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            num_kv_heads=hf["num_attention_heads"],
            intermediate_size=hf["intermediate_size"],
            max_seq_len=min(hf.get("max_position_embeddings", 2048), 8192),
            rope_theta=float(hf.get("rotary_emb_base", 10000.0)),
            rotary_fraction=float(hf.get("rotary_pct", 0.25)),
            norm_eps=hf.get("layer_norm_eps", 1e-5),
            parallel_block=bool(hf.get("use_parallel_residual", True)),
            tie_embeddings=hf.get("tie_word_embeddings", False),
        )
    elif family == "phi2":
        kw = dict(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            num_kv_heads=hf.get("num_key_value_heads") or hf["num_attention_heads"],
            intermediate_size=hf["intermediate_size"],
            max_seq_len=min(hf.get("max_position_embeddings", 2048), 8192),
            rope_theta=float(hf.get("rope_theta", 10000.0)),
            rotary_fraction=float(hf.get("partial_rotary_factor", 0.4)),
            norm_eps=hf.get("layer_norm_eps", 1e-5),
        )
    elif family == "falcon":
        if hf.get("alibi"):
            raise ValueError(
                f"alibi=true in {ckpt / 'config.json'} is not supported "
                "(rotary-position falcon checkpoints only)"
            )
        new_dec = bool(hf.get("new_decoder_architecture"))
        if new_dec:
            kv = int(hf.get("num_kv_heads") or hf["num_attention_heads"])
        elif hf.get("multi_query", True):
            kv = 1
        else:
            kv = hf["num_attention_heads"]
        parallel = bool(hf.get("parallel_attn", True))
        f_act = hf.get("activation", "gelu")
        f_act_map = {"gelu": "gelu", "gelu_new": "gelu_tanh", "gelu_pytorch_tanh": "gelu_tanh"}
        if f_act not in f_act_map:
            raise ValueError(
                f"activation {f_act!r} in {ckpt / 'config.json'} is not "
                f"supported for falcon; supported: {sorted(f_act_map)}"
            )
        # Norm arrangement varies by lineage: 7B = ONE shared input norm;
        # 40B-style new-decoder = dual ln_attn/ln_mlp; rw (parallel_attn
        # false) = sequential pre-norms; Falcon2-11B = new-decoder with
        # num_ln_in_parallel_attn=1 (shared again).
        dual_ln = new_dec and int(hf.get("num_ln_in_parallel_attn") or 2) == 2
        kw = dict(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["hidden_size"],
            num_layers=hf["num_hidden_layers"],
            num_heads=hf["num_attention_heads"],
            num_kv_heads=kv,
            intermediate_size=hf.get("ffn_hidden_size") or 4 * hf["hidden_size"],
            max_seq_len=min(hf.get("max_position_embeddings", 2048), 8192),
            rope_theta=float(hf.get("rope_theta", 10000.0)),
            norm_eps=hf.get("layer_norm_epsilon", 1e-5),
            parallel_block=parallel,
            shared_input_norm=parallel and not dual_ln,
            qkv_bias=bool(hf.get("bias", False)),
            out_bias=bool(hf.get("bias", False)),
            tie_embeddings=hf.get("tie_word_embeddings", True),
            activation=f_act_map[f_act],
        )
        kw.update(_rope_scaling_kw(hf, ckpt))
    elif family == "gpt2":
        # GPT2Config dials: n_embd/n_layer/n_head/n_positions; the wpe table
        # bounds max_seq_len (learned positions cannot extrapolate). Every
        # score-scaling / activation variant the runtime does not implement
        # fails HERE, not as silently wrong logits (same policy as the
        # qwen2 use_sliding_window and rope_scaling guards).
        if hf.get("scale_attn_by_inverse_layer_idx"):
            raise ValueError(
                f"scale_attn_by_inverse_layer_idx=true in {ckpt / 'config.json'}"
                " is not supported (per-layer score scaling)"
            )
        if not hf.get("scale_attn_weights", True):
            raise ValueError(
                f"scale_attn_weights=false in {ckpt / 'config.json'} is not "
                "supported (unscaled attention scores)"
            )
        act = hf.get("activation_function", "gelu_new")
        act_map = {"gelu_new": "gelu_tanh", "gelu_pytorch_tanh": "gelu_tanh", "gelu": "gelu"}
        if act not in act_map:
            raise ValueError(
                f"activation_function {act!r} in {ckpt / 'config.json'} is not "
                f"supported for gpt2; supported: {sorted(act_map)}"
            )
        kw = dict(
            vocab_size=hf["vocab_size"],
            hidden_size=hf["n_embd"],
            num_layers=hf["n_layer"],
            num_heads=hf["n_head"],
            num_kv_heads=hf["n_head"],
            intermediate_size=hf.get("n_inner") or 4 * hf["n_embd"],
            max_seq_len=int(hf.get("n_positions", hf.get("n_ctx", 1024))),
            norm_eps=hf.get("layer_norm_epsilon", 1e-5),
            activation=act_map[act],
        )
    else:  # pragma: no cover
        raise ValueError(family)
    rs = hf.get("rope_scaling") or {}
    rs_type = rs.get("rope_type", rs.get("type", ""))
    if family not in ("llama", "mistral", "mixtral", "qwen2", "qwen3", "gemma", "gemma2", "phi3", "falcon") and rs and rs_type not in ("default", "none", ""):
        # The neox/phi2 forward paths don't consume a scaling block; ignoring
        # a frequency-changing one would silently produce wrong logits for a
        # long-context variant. No-op types (newer HF configs emit
        # {"rope_type": "default"}) load fine.
        raise ValueError(
            f"rope_scaling type {rs_type!r} in {ckpt / 'config.json'} is not "
            f"supported for the {family} family"
        )
    kw.update(overrides)
    return config_for_family(family, **kw)


def _stack(arrs: list[np.ndarray], dtype) -> jnp.ndarray:
    return jnp.asarray(np.stack(arrs), dtype=dtype)


def _layer_stack(raw: dict[str, np.ndarray], fmt: str, num_layers: int, dtype, transpose: bool) -> jnp.ndarray:
    """Stack one per-layer tensor family along a new leading L axis.

    ``transpose`` converts torch nn.Linear's [out, in] storage into edgemesh's
    [in, out] kernels.
    """
    mats = [raw[fmt.format(i)] for i in range(num_layers)]
    if transpose:
        mats = [np.ascontiguousarray(m.T) for m in mats]
    return _stack(mats, dtype)


def load_params(ckpt: str | Path, cfg: ModelConfig | None = None, dtype=None) -> tuple[ModelConfig, Params]:
    """Load an HF checkpoint directory into (ModelConfig, stacked param tree)."""
    ckpt = Path(ckpt)
    family = sniff_family(ckpt)
    if family == "bert":
        raise ValueError(
            f"{ckpt} is a bert-family ENCODER checkpoint; use "
            "models.encoder.load_encoder (decoder runtime cannot host it)"
        )
    cfg = cfg or config_from_checkpoint(ckpt)
    dtype = dtype or cfg.activation_dtype
    raw = _load_raw_tensors(ckpt)

    if family == "phi3":
        params = _map_llama(raw, cfg, dtype, presplit=_split_phi3_fused)
    elif family == "mixtral":
        params = _map_llama(raw, cfg, dtype, ffn=_moe_ffn)
    elif family in ("llama", "mistral", "qwen2", "qwen3", "gemma", "gemma2"):  # identical weight naming
        params = _map_llama(raw, cfg, dtype)
    elif family == "neox":
        params = _map_neox(raw, cfg, dtype)
    elif family == "gpt2":
        params = _map_gpt2(raw, cfg, dtype)
    elif family == "falcon":
        params = _map_falcon(raw, cfg, dtype)
    else:
        params = _map_phi2(raw, cfg, dtype)
    return cfg, params


# -- per-family name maps ----------------------------------------------------


def _split_phi3_fused(raw: dict[str, np.ndarray], cfg: ModelConfig) -> dict[str, np.ndarray]:
    """Phi-3 fuses attention qkv and MLP gate/up in the checkpoint
    (``qkv_proj.weight`` rows [q; k; v], ``gate_up_proj.weight`` rows
    [gate; up]); split them into the llama naming so one map serves both."""
    nh, kh, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_size
    inter = cfg.intermediate_size
    out = dict(raw)
    for i in range(cfg.num_layers):
        qkv = out.pop(f"model.layers.{i}.self_attn.qkv_proj.weight")
        q_rows, k_rows = nh * hd, kh * hd
        out[f"model.layers.{i}.self_attn.q_proj.weight"] = qkv[:q_rows]
        out[f"model.layers.{i}.self_attn.k_proj.weight"] = qkv[q_rows : q_rows + k_rows]
        out[f"model.layers.{i}.self_attn.v_proj.weight"] = qkv[q_rows + k_rows :]
        gu = out.pop(f"model.layers.{i}.mlp.gate_up_proj.weight")
        out[f"model.layers.{i}.mlp.gate_proj.weight"] = gu[:inter]
        out[f"model.layers.{i}.mlp.up_proj.weight"] = gu[inter:]
    return out


def _dense_ffn(raw: dict[str, np.ndarray], cfg: ModelConfig, dtype) -> Params:
    """The llama-dialect dense SwiGLU FFN entries (default ``ffn`` hook)."""
    L = cfg.num_layers
    return {
        "gate": {"kernel": _layer_stack(raw, "model.layers.{}.mlp.gate_proj.weight", L, dtype, True)},
        "up": {"kernel": _layer_stack(raw, "model.layers.{}.mlp.up_proj.weight", L, dtype, True)},
        "down": {"kernel": _layer_stack(raw, "model.layers.{}.mlp.down_proj.weight", L, dtype, True)},
    }


def _map_llama(raw: dict[str, np.ndarray], cfg: ModelConfig, dtype, presplit=None, ffn=_dense_ffn) -> Params:
    if presplit is not None:
        raw = presplit(raw, cfg)
    L = cfg.num_layers

    def layer_stack(fmt: str, transpose: bool) -> jnp.ndarray:
        return _layer_stack(raw, fmt, L, dtype, transpose)

    layers: Params = {
        "attn_norm": {"scale": layer_stack("model.layers.{}.input_layernorm.weight", False)},
        "mlp_norm": {"scale": layer_stack("model.layers.{}.post_attention_layernorm.weight", False)},
        "q": {"kernel": layer_stack("model.layers.{}.self_attn.q_proj.weight", True)},
        "k": {"kernel": layer_stack("model.layers.{}.self_attn.k_proj.weight", True)},
        "v": {"kernel": layer_stack("model.layers.{}.self_attn.v_proj.weight", True)},
        "o": {"kernel": layer_stack("model.layers.{}.self_attn.o_proj.weight", True)},
        **ffn(raw, cfg, dtype),
    }
    if "model.layers.0.post_feedforward_layernorm.weight" in raw:  # Gemma-2
        layers["mlp_norm"] = {
            "scale": layer_stack("model.layers.{}.pre_feedforward_layernorm.weight", False)
        }
        layers["attn_post_norm"] = {
            "scale": layer_stack("model.layers.{}.post_attention_layernorm.weight", False)
        }
        layers["mlp_post_norm"] = {
            "scale": layer_stack("model.layers.{}.post_feedforward_layernorm.weight", False)
        }
    if "model.layers.0.self_attn.q_norm.weight" in raw:  # Qwen3 QK-norm
        layers["q_norm"] = {
            "scale": layer_stack("model.layers.{}.self_attn.q_norm.weight", False)
        }
        layers["k_norm"] = {
            "scale": layer_stack("model.layers.{}.self_attn.k_norm.weight", False)
        }
    if "model.layers.0.self_attn.q_proj.bias" in raw:  # Qwen2 qkv biases
        for name, proj in (("q", "q_proj"), ("k", "k_proj"), ("v", "v_proj")):
            layers[name]["bias"] = layer_stack(
                "model.layers.{}.self_attn." + proj + ".bias", False
            )
    params: Params = {
        "embed": {"weight": jnp.asarray(raw["model.embed_tokens.weight"], dtype)},
        "layers": layers,
        "final_norm": {"scale": jnp.asarray(raw["model.norm.weight"], dtype)},
    }
    if not cfg.tie_embeddings and "lm_head.weight" in raw:
        params["lm_head"] = {"kernel": jnp.asarray(np.ascontiguousarray(raw["lm_head.weight"].T), dtype)}
    return params


def _moe_ffn(raw: dict[str, np.ndarray], cfg: ModelConfig, dtype) -> Params:
    """Mixtral's routed-MoE FFN entries (``ffn`` hook for _map_llama). HF
    stores per-layer ``block_sparse_moe.gate`` (the router, a Linear [E, h])
    and per-expert ``experts.{e}.{w1,w3,w2}`` (gate/up/down in llama terms,
    each nn.Linear [out, in]); edgemesh stacks them to router [L, h, E]
    (fp32 — routing softmax islands stay fp32, ops/moe.py) and gate/up
    [L, E, h, inter], down [L, E, inter, h]."""
    L, E = cfg.num_layers, cfg.num_experts

    def expert_stack(w: str) -> jnp.ndarray:
        mats = [
            [
                np.ascontiguousarray(
                    raw[f"model.layers.{i}.block_sparse_moe.experts.{e}.{w}.weight"].T
                )
                for e in range(E)
            ]
            for i in range(L)
        ]
        return jnp.asarray(np.stack([np.stack(row) for row in mats]), dtype)

    return {
        "moe": {
            "router": {
                "kernel": _layer_stack(
                    raw, "model.layers.{}.block_sparse_moe.gate.weight", L,
                    jnp.float32, True,
                )
            },
            "gate": expert_stack("w1"),
            "up": expert_stack("w3"),
            "down": expert_stack("w2"),
        }
    }


def _map_neox(raw: dict[str, np.ndarray], cfg: ModelConfig, dtype) -> Params:
    L, nh, hd, h = cfg.num_layers, cfg.num_heads, cfg.head_size, cfg.hidden_size

    def split_qkv(i: int) -> tuple[np.ndarray, ...]:
        """NeoX fuses qkv head-major: rows are [head0: q|k|v, head1: q|k|v, …]."""
        w = raw[f"gpt_neox.layers.{i}.attention.query_key_value.weight"]  # [3*h, h]
        b = raw[f"gpt_neox.layers.{i}.attention.query_key_value.bias"]  # [3*h]
        w = w.reshape(nh, 3, hd, h)
        b = b.reshape(nh, 3, hd)
        qw, kw, vw = (np.ascontiguousarray(w[:, j].reshape(nh * hd, h).T) for j in range(3))
        qb, kb, vb = (np.ascontiguousarray(b[:, j].reshape(nh * hd)) for j in range(3))
        return qw, kw, vw, qb, kb, vb

    qkv = [split_qkv(i) for i in range(L)]

    def layer_stack(fmt: str, transpose: bool) -> jnp.ndarray:
        return _layer_stack(raw, fmt, L, dtype, transpose)

    layers: Params = {
        "attn_norm": {
            "scale": layer_stack("gpt_neox.layers.{}.input_layernorm.weight", False),
            "bias": layer_stack("gpt_neox.layers.{}.input_layernorm.bias", False),
        },
        "mlp_norm": {
            "scale": layer_stack("gpt_neox.layers.{}.post_attention_layernorm.weight", False),
            "bias": layer_stack("gpt_neox.layers.{}.post_attention_layernorm.bias", False),
        },
        "q": {"kernel": _stack([t[0] for t in qkv], dtype), "bias": _stack([t[3] for t in qkv], dtype)},
        "k": {"kernel": _stack([t[1] for t in qkv], dtype), "bias": _stack([t[4] for t in qkv], dtype)},
        "v": {"kernel": _stack([t[2] for t in qkv], dtype), "bias": _stack([t[5] for t in qkv], dtype)},
        "o": {
            "kernel": layer_stack("gpt_neox.layers.{}.attention.dense.weight", True),
            "bias": layer_stack("gpt_neox.layers.{}.attention.dense.bias", False),
        },
        "up": {
            "kernel": layer_stack("gpt_neox.layers.{}.mlp.dense_h_to_4h.weight", True),
            "bias": layer_stack("gpt_neox.layers.{}.mlp.dense_h_to_4h.bias", False),
        },
        "down": {
            "kernel": layer_stack("gpt_neox.layers.{}.mlp.dense_4h_to_h.weight", True),
            "bias": layer_stack("gpt_neox.layers.{}.mlp.dense_4h_to_h.bias", False),
        },
    }
    return {
        "embed": {"weight": jnp.asarray(raw["gpt_neox.embed_in.weight"], dtype)},
        "layers": layers,
        "final_norm": {
            "scale": jnp.asarray(raw["gpt_neox.final_layer_norm.weight"], dtype),
            "bias": jnp.asarray(raw["gpt_neox.final_layer_norm.bias"], dtype),
        },
        "lm_head": {"kernel": jnp.asarray(np.ascontiguousarray(raw["embed_out.weight"].T), dtype)},
    }


def _map_falcon(raw: dict[str, np.ndarray], cfg: ModelConfig, dtype) -> Params:
    """Falcon name map. The fused query_key_value rows are GROUPED per kv
    head — ``(kh, groups+2, hd)`` blocks of [q…q, k, v] — which covers all
    three lineages with one reshape: multi-query 7B is kh=1 (one group of
    [q×nh, k, v]), new-decoder 40B/Falcon2 is true GQA, and the kh==nh
    checkpoints (rw / MHA new-decoder) degenerate to per-head [q, k, v]
    interleave. Norm names pick the lineage: ln_attn/ln_mlp (dual),
    input_layernorm alone (shared, 7B), or input_layernorm +
    post_attention_layernorm (sequential rw)."""
    if "transformer.word_embeddings.weight" in raw:
        raw = {
            (k[len("transformer."):] if k.startswith("transformer.") else k): v
            for k, v in raw.items()
        }
    L, nh, kh, hd, h = (
        cfg.num_layers, cfg.num_heads, cfg.num_kv_heads, cfg.head_size,
        cfg.hidden_size,
    )
    gq = nh // kh
    has_qkv_bias = "h.0.self_attention.query_key_value.bias" in raw

    def split_qkv(i: int):
        w = raw[f"h.{i}.self_attention.query_key_value.weight"]  # [(gq+2)*kh*hd, h]
        w = w.reshape(kh, gq + 2, hd, h)
        qw = np.ascontiguousarray(w[:, :gq].reshape(kh * gq * hd, h).T)
        kw_ = np.ascontiguousarray(w[:, gq].reshape(kh * hd, h).T)
        vw = np.ascontiguousarray(w[:, gq + 1].reshape(kh * hd, h).T)
        if has_qkv_bias:
            b = raw[f"h.{i}.self_attention.query_key_value.bias"].reshape(kh, gq + 2, hd)
            return qw, kw_, vw, (
                np.ascontiguousarray(b[:, :gq].reshape(kh * gq * hd)),
                np.ascontiguousarray(b[:, gq].reshape(kh * hd)),
                np.ascontiguousarray(b[:, gq + 1].reshape(kh * hd)),
            )
        return qw, kw_, vw, None

    qkv = [split_qkv(i) for i in range(L)]

    def layer_stack(fmt: str, transpose: bool) -> jnp.ndarray:
        return _layer_stack(raw, fmt, L, dtype, transpose)

    def norm(fmt_base: str) -> Params:
        return {
            "scale": layer_stack(fmt_base + ".weight", False),
            "bias": layer_stack(fmt_base + ".bias", False),
        }

    def dense_maybe_bias(name: str) -> Params:
        out: Params = {"kernel": layer_stack("h.{}." + name + ".weight", True)}
        if f"h.0.{name}.bias" in raw:
            out["bias"] = layer_stack("h.{}." + name + ".bias", False)
        return out

    layers: Params = {
        "q": {"kernel": _stack([t[0] for t in qkv], dtype)},
        "k": {"kernel": _stack([t[1] for t in qkv], dtype)},
        "v": {"kernel": _stack([t[2] for t in qkv], dtype)},
        "o": dense_maybe_bias("self_attention.dense"),
        "up": dense_maybe_bias("mlp.dense_h_to_4h"),
        "down": dense_maybe_bias("mlp.dense_4h_to_h"),
    }
    if has_qkv_bias:
        for j, name in enumerate(("q", "k", "v")):
            layers[name]["bias"] = _stack([t[3][j] for t in qkv], dtype)
    if "h.0.ln_attn.weight" in raw:  # dual input norms (parallel, 40B-style)
        layers["attn_norm"] = norm("h.{}.ln_attn")
        layers["mlp_norm"] = norm("h.{}.ln_mlp")
    elif cfg.shared_input_norm:  # 7B: one norm feeds attn AND mlp
        layers["attn_norm"] = norm("h.{}.input_layernorm")
    else:  # sequential rw lineage
        layers["attn_norm"] = norm("h.{}.input_layernorm")
        layers["mlp_norm"] = norm("h.{}.post_attention_layernorm")
    params: Params = {
        "embed": {"weight": jnp.asarray(raw["word_embeddings.weight"], dtype)},
        "layers": layers,
        "final_norm": {
            "scale": jnp.asarray(raw["ln_f.weight"], dtype),
            "bias": jnp.asarray(raw["ln_f.bias"], dtype),
        },
    }
    if not cfg.tie_embeddings and "lm_head.weight" in raw:
        params["lm_head"] = {
            "kernel": jnp.asarray(np.ascontiguousarray(raw["lm_head.weight"].T), dtype)
        }
    return params


def _map_gpt2(raw: dict[str, np.ndarray], cfg: ModelConfig, dtype) -> Params:
    """GPT-2 name map. Two checkpoint quirks: (1) tensors may or may not carry
    a ``transformer.`` prefix (GPT2LMHeadModel state_dict does, the hub's
    bare safetensors don't); (2) Conv1D stores weights [in, out] — already
    edgemesh's kernel layout, so unlike the nn.Linear families there is NO
    transpose. The fused c_attn columns split [q | k | v]."""
    if "transformer.wte.weight" in raw:
        raw = {
            k[len("transformer."):]: v
            for k, v in raw.items()
            if k.startswith("transformer.")
        }
    L, h = cfg.num_layers, cfg.hidden_size

    def split_cols(fmt: str, j: int, width: int) -> list[np.ndarray]:
        return [
            np.ascontiguousarray(raw[fmt.format(i)][..., j * width : (j + 1) * width])
            for i in range(L)
        ]

    def qkv(j: int) -> Params:
        return {
            "kernel": _stack(split_cols("h.{}.attn.c_attn.weight", j, h), dtype),
            "bias": _stack(split_cols("h.{}.attn.c_attn.bias", j, h), dtype),
        }

    def conv1d(name: str) -> Params:
        return {
            "kernel": _layer_stack(raw, "h.{}." + name + ".weight", L, dtype, False),
            "bias": _layer_stack(raw, "h.{}." + name + ".bias", L, dtype, False),
        }

    layers: Params = {
        "attn_norm": {
            "scale": _layer_stack(raw, "h.{}.ln_1.weight", L, dtype, False),
            "bias": _layer_stack(raw, "h.{}.ln_1.bias", L, dtype, False),
        },
        "mlp_norm": {
            "scale": _layer_stack(raw, "h.{}.ln_2.weight", L, dtype, False),
            "bias": _layer_stack(raw, "h.{}.ln_2.bias", L, dtype, False),
        },
        "q": qkv(0),
        "k": qkv(1),
        "v": qkv(2),
        "o": conv1d("attn.c_proj"),
        "up": conv1d("mlp.c_fc"),
        "down": conv1d("mlp.c_proj"),
    }
    return {
        "embed": {"weight": jnp.asarray(raw["wte.weight"], dtype)},
        "pos_embed": {"weight": jnp.asarray(raw["wpe.weight"], dtype)},
        "layers": layers,
        "final_norm": {
            "scale": jnp.asarray(raw["ln_f.weight"], dtype),
            "bias": jnp.asarray(raw["ln_f.bias"], dtype),
        },
    }


def _map_phi2(raw: dict[str, np.ndarray], cfg: ModelConfig, dtype) -> Params:
    L = cfg.num_layers

    def layer_stack(fmt: str, transpose: bool) -> jnp.ndarray:
        return _layer_stack(raw, fmt, L, dtype, transpose)

    def dense(name: str) -> Params:
        return {
            "kernel": layer_stack("model.layers.{}." + name + ".weight", True),
            "bias": layer_stack("model.layers.{}." + name + ".bias", False),
        }

    layers: Params = {
        "attn_norm": {
            "scale": layer_stack("model.layers.{}.input_layernorm.weight", False),
            "bias": layer_stack("model.layers.{}.input_layernorm.bias", False),
        },
        "q": dense("self_attn.q_proj"),
        "k": dense("self_attn.k_proj"),
        "v": dense("self_attn.v_proj"),
        "o": dense("self_attn.dense"),
        "up": dense("mlp.fc1"),
        "down": dense("mlp.fc2"),
    }
    return {
        "embed": {"weight": jnp.asarray(raw["model.embed_tokens.weight"], dtype)},
        "layers": layers,
        "final_norm": {
            "scale": jnp.asarray(raw["model.final_layernorm.weight"], dtype),
            "bias": jnp.asarray(raw["model.final_layernorm.bias"], dtype),
        },
        "lm_head": {
            "kernel": jnp.asarray(np.ascontiguousarray(raw["lm_head.weight"].T), dtype),
            "bias": jnp.asarray(raw["lm_head.bias"], dtype),
        },
    }
