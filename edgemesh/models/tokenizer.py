"""Tokenizers: HF wrapper (host-side, the explicitly-allowed Rust tokenizers)
plus a dependency-free byte tokenizer for synthetic models and tests.

The reference tokenizes via each model's HF tokenizer
(``Code/C-DAC Server/combiner_fp.py:276``). Per BASELINE.json's north star,
tokenization stays host-side HF — it is not a device concern.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np


def _host_ids(ids) -> list[int]:
    """One bulk device→host transfer, then plain Python ints.

    Iterating a jax device array directly makes every ``int(i)`` its own
    readback — ~0.13s EACH over the tunneled TPU (measured: retiring one
    32-token serving request cost ~4s in decode alone). Every decode path
    funnels through here so no caller can reintroduce that."""
    return np.asarray(ids).tolist()


class ByteTokenizer:
    """Deterministic byte-level tokenizer (vocab 256 + BOS/EOS/PAD) for
    synthetic models, tests, and CLI smoke runs — no files needed."""

    vocab_size = 259
    bos_id = 256
    eos_id = 257
    pad_id = 258

    def encode(self, text: str, max_len: int | None = None) -> list[int]:
        ids = [self.bos_id] + list(text.encode("utf-8", errors="replace"))
        if max_len is not None:  # `is not None`, so max_len=0 truncates to []
            ids = ids[: max(0, max_len)]
        return ids

    def decode(self, ids) -> str:
        data = bytes(i for i in _host_ids(ids) if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """Thin wrapper over a local HF tokenizer directory (no hub access)."""

    def __init__(self, path: str | Path):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(str(path), local_files_only=True)
        if self._tok.pad_token_id is None:
            self._tok.pad_token = self._tok.eos_token

    @property
    def vocab_size(self) -> int:
        return len(self._tok)

    @property
    def eos_id(self) -> int:
        return self._tok.eos_token_id

    @property
    def pad_id(self) -> int:
        return self._tok.pad_token_id

    def encode(self, text: str, max_len: int | None = None) -> list[int]:
        ids = self._tok.encode(text, truncation=max_len is not None, max_length=max_len)
        return ids

    def decode(self, ids) -> str:
        return self._tok.decode(_host_ids(ids), skip_special_tokens=True)


def load_tokenizer(path: str | Path | None):
    if path:
        return HFTokenizer(path)
    return ByteTokenizer()


def encode_batch(tokenizer, texts: list[str], max_len: int | None = None):
    """Tokenize + right-pad a text batch to the batch max → (tokens
    [n, width] int32, lengths [n] int32). Used by SmoothQuant calibration;
    the agent batcher and training builder keep their own padding (they pad
    to shape BUCKETS, not the batch max, to bound jit specializations)."""
    import jax.numpy as jnp

    ids_list = [tokenizer.encode(t, max_len=max_len) for t in texts]
    width = max(len(ids) for ids in ids_list)
    pad = getattr(tokenizer, "pad_id", 0)
    tokens = jnp.asarray(
        [ids + [pad] * (width - len(ids)) for ids in ids_list], jnp.int32
    )
    lengths = jnp.asarray([len(ids) for ids in ids_list], jnp.int32)
    return tokens, lengths
