"""Training: causal-LM loss + sharded train step.

The reference is inference-only (finetuning was left unstarted on its roadmap,
SURVEY.md §7 "out of scope"), but edgemesh ships a mesh-sharded training step
so the framework is complete on TPU terms: same model code, same sharding
rules, optax optimizer, gradients and optimizer state sharded like the params
(scaling-book recipe — XLA inserts the psums for the dp-axis gradient
reduction and the tp-axis activation collectives from the shardings alone).
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from edgemesh.models.transformer import (
    ModelConfig,
    _forward,
    init_kv_cache,
)

Params = dict[str, Any]


class TrainState(NamedTuple):
    params: Params
    opt_state: Any
    step: jnp.ndarray


def forward_train_aux(
    cfg: ModelConfig, params: Params, tokens: jnp.ndarray, lengths: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Full-sequence logits [b, s, vocab] plus the summed MoE load-balance
    aux loss (0 for dense models); cache written then discarded."""
    # The Pallas flash kernel has no VJP (scratch-mutating online softmax);
    # training differentiates this forward, so pin the XLA attention path.
    # Inference prefill (runtime/generate.py) keeps cfg's choice.
    if cfg.attention_impl != "xla":
        cfg = cfg.replace(attention_impl="xla")
    b, s = tokens.shape
    cache = init_kv_cache(cfg, b, s)
    positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    kv_valid = jnp.arange(s)[None, :] < lengths[:, None]
    logits, _, aux = _forward(cfg, params, tokens, positions, cache, kv_valid, is_decode=False)
    return logits, aux


def forward_train(cfg: ModelConfig, params: Params, tokens: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
    return forward_train_aux(cfg, params, tokens, lengths)[0]


def causal_lm_loss(
    cfg: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,
    lengths: jnp.ndarray,
    moe_aux_weight: float = 0.01,
) -> jnp.ndarray:
    """Mean next-token cross-entropy over real (unpadded) positions, plus the
    weighted MoE load-balance term when the model is routed (Switch eq. 4)."""
    logits, aux = forward_train_aux(cfg, params, tokens, lengths)
    logits = logits[:, :-1]
    targets = tokens[:, 1:]
    b, s = targets.shape
    mask = (jnp.arange(s)[None, :] < (lengths - 1)[:, None]).astype(jnp.float32)
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), targets
    )
    loss = jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    if cfg.num_experts > 0:
        loss = loss + moe_aux_weight * aux
    return loss


def make_optimizer(lr: float = 1e-4, weight_decay: float = 0.01) -> optax.GradientTransformation:
    return optax.adamw(lr, weight_decay=weight_decay)


def init_train_state(cfg: ModelConfig, params: Params, optimizer) -> TrainState:
    return TrainState(params, optimizer.init(params), jnp.zeros((), jnp.int32))


def make_train_step(cfg: ModelConfig, optimizer):
    """Returns a jittable (state, tokens, lengths) -> (state, loss) step.

    Under a mesh, callers place params/opt_state with
    edgemesh.parallel.sharding.param_pspecs and the batch with
    batch_sharding; jit propagates the shardings through grads and updates.
    """

    @partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, tokens: jnp.ndarray, lengths: jnp.ndarray):
        loss, grads = jax.value_and_grad(
            lambda p: causal_lm_loss(cfg, p, tokens, lengths)
        )(state.params)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return train_step
