"""Training: causal-LM loss + sharded train step.

The reference is inference-only (finetuning was left unstarted on its roadmap,
SURVEY.md §7 "out of scope"), but edgemesh ships a mesh-sharded training step
so the framework is complete on TPU terms: same model code, same sharding
rules, optax optimizer, gradients and optimizer state sharded like the params
(scaling-book recipe — XLA inserts the psums for the dp-axis gradient
reduction and the tp-axis activation collectives from the shardings alone).
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from edgemesh.models.transformer import (
    ModelConfig,
    _forward,
    init_kv_cache,
)

log = logging.getLogger("edgemesh.training")

Params = dict[str, Any]


class TrainState(NamedTuple):
    params: Params
    opt_state: Any
    step: jnp.ndarray


def forward_train_aux(
    cfg: ModelConfig, params: Params, tokens: jnp.ndarray, lengths: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Full-sequence logits [b, s, vocab] plus the summed MoE load-balance
    aux loss (0 for dense models); cache written then discarded."""
    # The Pallas flash kernel has no VJP (scratch-mutating online softmax);
    # training differentiates this forward, so pin the XLA attention path.
    # Inference prefill (runtime/generate.py) keeps cfg's choice.
    if cfg.attention_impl != "xla":
        cfg = cfg.replace(attention_impl="xla")
    b, s = tokens.shape
    cache = init_kv_cache(cfg, b, s)
    positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    kv_valid = jnp.arange(s)[None, :] < lengths[:, None]
    logits, _, aux = _forward(cfg, params, tokens, positions, cache, kv_valid, is_decode=False)
    return logits, aux


def forward_train(cfg: ModelConfig, params: Params, tokens: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
    return forward_train_aux(cfg, params, tokens, lengths)[0]


def causal_lm_loss(
    cfg: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,
    lengths: jnp.ndarray,
    moe_aux_weight: float = 0.01,
) -> jnp.ndarray:
    """Mean next-token cross-entropy over real (unpadded) positions, plus the
    weighted MoE load-balance term when the model is routed (Switch eq. 4)."""
    logits, aux = forward_train_aux(cfg, params, tokens, lengths)
    logits = logits[:, :-1]
    targets = tokens[:, 1:]
    b, s = targets.shape
    mask = (jnp.arange(s)[None, :] < (lengths - 1)[:, None]).astype(jnp.float32)
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), targets
    )
    loss = jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    if cfg.num_experts > 0:
        loss = loss + moe_aux_weight * aux
    return loss


def make_optimizer(lr: float = 1e-4, weight_decay: float = 0.01) -> optax.GradientTransformation:
    return optax.adamw(lr, weight_decay=weight_decay)


def init_train_state(cfg: ModelConfig, params: Params, optimizer) -> TrainState:
    return TrainState(params, optimizer.init(params), jnp.zeros((), jnp.int32))


def run_training(run_cfg) -> dict[str, Any]:
    """Config-driven finetuning loop: ``edgemesh train`` (cli.py).

    The model comes from ``agents[0].model`` (synthetic random-init or HF
    checkpoint), the corpus from the Natural Questions CSV (each row becomes
    one "Question/Answer" LM sequence through the agent's tokenizer), the
    mesh from ``mesh:`` (dp x tp auto-sharded placement), checkpoints rotate
    under ``train.checkpoint_dir`` and a rerun resumes from the latest.
    Returns {first_loss, final_loss, steps_run, resumed_from}.
    """
    import numpy as np
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from edgemesh.agents.orchestrator import _materialize
    from edgemesh.config import AgentSpec
    from edgemesh.eval.data import load_qa_csv, resolve_dataset_path
    from edgemesh.parallel.mesh import build_mesh
    from edgemesh.parallel.sharding import batch_sharding, shard_params
    from edgemesh.utils.tracing import trace

    ts = run_cfg.train
    spec = run_cfg.agents[0] if run_cfg.agents else AgentSpec()
    if spec.model.precision not in ("bf16", "fp16", "fp32"):
        raise ValueError(
            f"training needs a float precision, got {spec.model.precision!r} "
            "(quantized weights are an inference-time transform)"
        )
    cfg, params, tokenizer = _materialize(spec.model, spec.role)
    if ts.seq_len > cfg.max_seq_len:
        raise ValueError(f"train.seq_len {ts.seq_len} > max_seq_len {cfg.max_seq_len}")

    # Corpus: Q/A rows (or a {"text": ...} JSONL via train.corpus_jsonl) →
    # fixed-length LM sequences. Split selection (skip_samples/num_samples)
    # lets each model train on its own rows — the complementary-knowledge
    # setup of docs/QUALITY.md.
    if ts.corpus_jsonl:
        import json as _json

        with open(ts.corpus_jsonl) as f:
            texts = [_json.loads(line)["text"] for line in f if line.strip()]
    else:
        samples = load_qa_csv(resolve_dataset_path(run_cfg.eval.dataset_path))
        texts = [f"Question: {s.question}\nAnswer: {s.answer}" for s in samples]
    texts = texts[ts.skip_samples:]
    if ts.num_samples:
        texts = texts[: ts.num_samples]
    if not texts:
        raise ValueError(
            f"empty train split (skip_samples={ts.skip_samples}, "
            f"num_samples={ts.num_samples})"
        )
    pad = getattr(tokenizer, "pad_id", 0)
    eos = getattr(tokenizer, "eos_id", None)
    rows, lens = [], []
    for text in texts:
        # Reserve one slot for EOS so the model learns to STOP after the
        # answer — without it generation always runs to max_new_tokens and
        # trailing babble wrecks precision-style metrics.
        ids = tokenizer.encode(text, max_len=ts.seq_len - (eos is not None))
        if eos is not None:
            ids = ids + [eos]
        rows.append(ids + [pad] * (ts.seq_len - len(ids)))
        lens.append(len(ids))
    rows_np = np.asarray(rows, np.int32)
    lens_np = np.asarray(lens, np.int32)

    mesh = None
    ms = run_cfg.mesh
    if ms.dp * ms.tp > 1:
        mesh = build_mesh(dp=ms.dp, tp=ms.tp)
    lora_rank = spec.model.lora_rank
    if lora_rank > 0:
        from edgemesh.ops.lora import (
            init_lora_params,
            lora_num_params,
            make_lora_optimizer,
        )

        optimizer = make_lora_optimizer(ts.lr, ts.weight_decay)
        lora = init_lora_params(
            params, lora_rank, spec.model.lora_alpha,
            spec.model.lora_targets, jax.random.PRNGKey(run_cfg.seed),
        )
        log.info(
            "lora: rank %d over %s (%d adapter params; base frozen)",
            lora_rank, spec.model.lora_targets, lora_num_params(lora),
        )
    else:
        optimizer = make_optimizer(ts.lr, ts.weight_decay)
    if mesh is not None:
        params = shard_params(params, cfg, mesh)
    # With LoRA the TrainState carries ONLY the adapter tree (checkpoints
    # are the kilobyte-scale adapters; base weights come from the model
    # spec at restore time — orchestrator._materialize merges them).
    state = init_train_state(cfg, lora if lora_rank > 0 else params, optimizer)
    if mesh is not None:

        def place(x):
            # optimizer.init's mu/nu inherit the params' shardings; fresh
            # leaves (step counters) land on one device — on a sub-mesh that
            # mixes device sets inside one jit ("incompatible devices").
            # Replicate anything not already on THIS mesh.
            s = getattr(x, "sharding", None)
            if isinstance(s, NamedSharding) and s.mesh.devices.tolist() == mesh.devices.tolist():
                return x
            return jax.device_put(x, NamedSharding(mesh, P()))

        state = jax.tree.map(place, state)
    if lora_rank > 0:
        lora_step = make_lora_train_step(cfg, optimizer)

        def step_fn(st, tokens, lengths):
            return lora_step(st, params, tokens, lengths)
    else:
        step_fn = make_train_step(cfg, optimizer)

    mgr = resumed_from = None
    if ts.checkpoint_dir:
        from edgemesh.runtime.checkpoint import TrainCheckpointManager

        mgr = TrainCheckpointManager(ts.checkpoint_dir)
        restored = mgr.restore_latest(state) if ts.resume else None
        if restored is not None:
            state, resumed_from = restored
            log.info("resumed from step %d", resumed_from)

    first_loss = final_loss = None
    start = min(int(state.step), ts.steps)  # resume at/past steps: no-op run
    for step in range(start, ts.steps):
        # Per-step seeded draw (not one sequential stream): a resumed run
        # continues the batch sequence instead of replaying it from draw 0.
        idx = np.random.default_rng((run_cfg.seed, step)).integers(
            0, len(rows_np), ts.batch_size
        )
        tokens = jnp.asarray(rows_np[idx])
        lengths = jnp.asarray(lens_np[idx])
        if mesh is not None:
            tokens = jax.device_put(tokens, batch_sharding(mesh))
            lengths = jax.device_put(lengths, NamedSharding(mesh, P("dp")))
        with trace("edgemesh/train_step"):
            state, loss = step_fn(state, tokens, lengths)
        # Keep loss on device in the hot loop — float() would force a
        # host sync per step and defeat async dispatch.
        if first_loss is None:
            first_loss = loss
        final_loss = loss
        if (step + 1) % ts.log_every == 0 or step + 1 == ts.steps:
            log.info("step %d/%d loss %.4f", step + 1, ts.steps, float(loss))
        if mgr is not None and ((step + 1) % ts.checkpoint_every == 0 or step + 1 == ts.steps):
            mgr.save(step + 1, state)
    if mgr is not None:
        mgr.close()
    return {
        "first_loss": None if first_loss is None else float(first_loss),
        "final_loss": None if final_loss is None else float(final_loss),
        "steps_run": ts.steps - start,
        "resumed_from": resumed_from,
        "lora_rank": lora_rank,
    }


def make_lora_train_step(cfg: ModelConfig, optimizer):
    """(state, base_params, tokens, lengths) -> (state, loss) where
    ``state.params`` is the ADAPTER tree only (ops/lora.py split design).

    The base params enter as a plain argument — never differentiated, so
    XLA prunes every frozen-weight gradient from the backward; adamw state
    exists only for the adapters. ``attach_lora`` grafts the adapter leaves
    into the forward tree structurally; gradients flow back through the
    activation-side ``(x @ A) @ B`` term alone."""
    from edgemesh.ops.lora import attach_lora

    @partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, base_params: Params, tokens, lengths):
        def loss_fn(lora):
            return causal_lm_loss(cfg, attach_lora(base_params, lora), tokens, lengths)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        lora = optax.apply_updates(state.params, updates)
        return TrainState(lora, opt_state, state.step + 1), loss

    return train_step


def make_train_step(cfg: ModelConfig, optimizer):
    """Returns a jittable (state, tokens, lengths) -> (state, loss) step.

    Under a mesh, callers place params/opt_state with
    edgemesh.parallel.sharding.param_pspecs and the batch with
    batch_sharding; jit propagates the shardings through grads and updates.
    """

    @partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, tokens: jnp.ndarray, lengths: jnp.ndarray):
        loss, grads = jax.value_and_grad(
            lambda p: causal_lm_loss(cfg, p, tokens, lengths)
        )(state.params)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return train_step
