"""Rotary position embeddings with partial-rotary support.

Family coverage (SURVEY.md §7 hard part (c) — attention layouts differ):
- Llama: full rotary (fraction 1.0), interleaved GPT-NeoX "half-split" layout.
- Pythia / GPT-NeoX: rotary_pct 0.25 — only the first quarter of each head dim
  is rotated.
- Phi-2: partial rotary (fraction 0.4 of head_dim).

Computed in fp32 for numerical parity with HF, applied in the activation dtype.
Sin/cos tables are built once per call from positions — under jit this is a
cheap fused gather, not a host round-trip.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(
    rotary_dim: int, theta: float = 10000.0
) -> jnp.ndarray:
    """Inverse frequencies for the rotated sub-dimension. Shape [rotary_dim//2]."""
    exponent = jnp.arange(0, rotary_dim, 2, dtype=jnp.float32) / rotary_dim
    return 1.0 / (theta ** exponent)


def apply_rope(
    x: jnp.ndarray,  # [batch, seq, heads, head_dim]
    positions: jnp.ndarray,  # [batch, seq] int32
    rotary_dim: int,
    theta: float = 10000.0,
) -> jnp.ndarray:
    """Rotate the first ``rotary_dim`` channels of each head; pass the rest through.

    Uses the half-split (NeoX) convention shared by Llama/Pythia/Phi-2 in HF:
    the rotated block is split into two halves [x1, x2] and mapped to
    [x1*cos - x2*sin, x2*cos + x1*sin].
    """
    dtype = x.dtype
    inv_freq = rope_frequencies(rotary_dim, theta)  # [rd/2]
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [b, s, rd/2]
    cos = jnp.cos(angles)[:, :, None, :]  # [b, s, 1, rd/2]
    sin = jnp.sin(angles)[:, :, None, :]

    x_rot = x[..., :rotary_dim].astype(jnp.float32)
    x_pass = x[..., rotary_dim:]
    half = rotary_dim // 2
    x1, x2 = x_rot[..., :half], x_rot[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(dtype)
    if x_pass.shape[-1] == 0:
        return rotated
    return jnp.concatenate([rotated, x_pass], axis=-1)
