"""Rotary position embeddings with partial-rotary support.

Family coverage (SURVEY.md §7 hard part (c) — attention layouts differ):
- Llama: full rotary (fraction 1.0), interleaved GPT-NeoX "half-split" layout.
- Pythia / GPT-NeoX: rotary_pct 0.25 — only the first quarter of each head dim
  is rotated.
- Phi-2: partial rotary (fraction 0.4 of head_dim).

Computed in fp32 for numerical parity with HF, applied in the activation dtype.
Sin/cos tables are built once per call from positions — under jit this is a
cheap fused gather, not a host round-trip.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

# RopeScaling = (type, factor, low_freq_factor, high_freq_factor,
#                original_max_position) — a plain hashable tuple so it can
# ride a frozen ModelConfig into jit static args. type: "linear" | "llama3".
RopeScaling = tuple[str, float, float, float, int]


def rope_frequencies(
    rotary_dim: int,
    theta: float = 10000.0,
    scaling: RopeScaling | None = None,
) -> jnp.ndarray:
    """Inverse frequencies for the rotated sub-dimension. Shape [rotary_dim//2].

    ``scaling`` applies HF-style context extension: "linear" divides all
    frequencies by the factor; "llama3" (Llama-3.x checkpoints' rope_scaling
    block) rescales only wavelengths past the original context — long
    wavelengths divide by the factor, short ones pass through, mid-band
    interpolates smoothly between the two.
    """
    exponent = jnp.arange(0, rotary_dim, 2, dtype=jnp.float32) / rotary_dim
    inv_freq = 1.0 / (theta ** exponent)
    if scaling is None or scaling[0] in ("", "none", "default"):
        return inv_freq
    kind, factor, low_ff, high_ff, orig_max = scaling
    if kind == "linear":
        return inv_freq / factor
    if kind == "llama3":
        low_wavelen = orig_max / low_ff
        high_wavelen = orig_max / high_ff
        wavelen = 2.0 * math.pi / inv_freq
        smooth = (orig_max / wavelen - low_ff) / (high_ff - low_ff)
        mid = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
        return jnp.where(
            wavelen > low_wavelen,
            inv_freq / factor,
            jnp.where(wavelen < high_wavelen, inv_freq, mid),
        )
    raise ValueError(f"unknown rope scaling type {kind!r}")


def apply_rope(
    x: jnp.ndarray,  # [batch, seq, heads, head_dim]
    positions: jnp.ndarray,  # [batch, seq] int32
    rotary_dim: int,
    theta: float = 10000.0,
    scaling: RopeScaling | None = None,
) -> jnp.ndarray:
    """Rotate the first ``rotary_dim`` channels of each head; pass the rest through.

    Uses the half-split (NeoX) convention shared by Llama/Pythia/Phi-2 in HF:
    the rotated block is split into two halves [x1, x2] and mapped to
    [x1*cos - x2*sin, x2*cos + x1*sin].
    """
    dtype = x.dtype
    inv_freq = rope_frequencies(rotary_dim, theta, scaling)  # [rd/2]
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [b, s, rd/2]
    cos = jnp.cos(angles)[:, :, None, :]  # [b, s, 1, rd/2]
    sin = jnp.sin(angles)[:, :, None, :]

    x_rot = x[..., :rotary_dim].astype(jnp.float32)
    x_pass = x[..., rotary_dim:]
    half = rotary_dim // 2
    x1, x2 = x_rot[..., :half], x_rot[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(dtype)
    if x_pass.shape[-1] == 0:
        return rotated
    return jnp.concatenate([rotated, x_pass], axis=-1)
