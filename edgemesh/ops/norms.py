"""Normalization layers as pure functions.

The three supported families split on norm type: Llama uses RMSNorm, Pythia
(GPT-NeoX) and Phi-2 use LayerNorm with bias. Reductions are done in fp32 and
cast back, which XLA fuses into the surrounding elementwise chain — one of the
HBM-bandwidth wins over the reference's eager torch path (which materializes
each intermediate; reference forward is plain HF ``model.generate``,
``Code/C-DAC Server/combiner_fp.py:338-347``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    return (y * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    bias: jnp.ndarray,
    eps: float = 1e-5,
) -> jnp.ndarray:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) / jnp.sqrt(var + eps)
    return (y * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)
