"""Mixture-of-Experts MLP with top-k capacity routing (GShard/Switch style).

The reference PLANNED expert models but never built them: the results
workbook's ``Expert Models`` sheet lays out 13 text-expert domains x
quant/base x routing mode = 52 configs (SURVEY.md §2.3, EP row). This module
is the device-level half of that plan — a routed MoE FFN whose expert dim
shards over the mesh's ``ep`` axis. (The request-level half — routing whole
questions to expert *agents* — is agents/experts.py.)

TPU-first design:
- Everything is dense one-hot einsum algebra (dispatch [T, E, C] tensors), no
  data-dependent shapes: the MXU sees three big matmuls per expert layer and
  XLA inserts the all-to-alls when the expert dim is sharded over ``ep``.
- Static capacity ``C = ceil(T/E * k * capacity_factor)``: overflowed tokens
  fall back to the residual stream (combine weight 0), the standard
  drop-token policy.
- Router math in fp32 (softmax islands), expert FFN in the model dtype.
- Aux load-balance loss (Switch eq. 4: E * Σ_e fraction_e · meanprob_e) is
  returned alongside so the training loss can penalize routing collapse.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from edgemesh.models.transformer import ModelConfig, Params, _activate


def init_moe_layer(cfg: ModelConfig, key: jax.Array) -> Params:
    """Per-layer MoE params: router + E stacked expert FFNs.

    Shapes (within one layer; init_params stacks a leading num_layers axis):
    router.kernel [h, E]; gate/up [E, h, inter]; down [E, inter, h].
    """
    h, inter, E = cfg.hidden_size, cfg.intermediate_size, cfg.num_experts
    dtype = cfg.activation_dtype
    ks = jax.random.split(key, 4)
    scale_in = h**-0.5
    scale_out = inter**-0.5
    p: Params = {
        "router": {"kernel": (jax.random.normal(ks[0], (h, E), jnp.float32) * scale_in).astype(jnp.float32)},
        "up": (jax.random.normal(ks[1], (E, h, inter), jnp.float32) * scale_in).astype(dtype),
        "down": (jax.random.normal(ks[2], (E, inter, h), jnp.float32) * scale_out).astype(dtype),
    }
    if cfg.gated:
        p["gate"] = (jax.random.normal(ks[3], (E, h, inter), jnp.float32) * scale_in).astype(dtype)
    return p


def expert_capacity(cfg: ModelConfig, num_tokens: int) -> int:
    return max(
        1,
        int(
            math.ceil(
                num_tokens / cfg.num_experts
                * cfg.experts_per_token
                * cfg.expert_capacity_factor
            )
        ),
    )


def route_tokens(
    cfg: ModelConfig, router_kernel: jnp.ndarray, xt: jnp.ndarray, capacity: int
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k capacity routing for ``xt`` [T, h] → (combine [T, E, C] fp32,
    aux scalar). Shared by the single-program MoE below and the manual 4D
    SPMD path (parallel/spmd.py), which slices the combine tensor down to
    its ``ep``-local experts. Deterministic in T-order (GShard slot-by-slot
    position assignment)."""
    E, k = cfg.num_experts, cfg.experts_per_token
    T = xt.shape[0]
    C = capacity

    logits = xt.astype(jnp.float32) @ router_kernel  # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, expert_idx = jax.lax.top_k(probs, k)  # [T, k]
    gate_vals = gate_vals / jnp.maximum(jnp.sum(gate_vals, -1, keepdims=True), 1e-9)

    # Slot-by-slot position assignment (k is a small static int): a token's
    # position inside its expert counts all prior-slot dispatches first, the
    # GShard discipline that makes capacity deterministic.
    combine = jnp.zeros((T, E, C), jnp.float32)
    counts = jnp.zeros((E,), jnp.float32)  # tokens already placed per expert
    for slot in range(k):
        m = jax.nn.one_hot(expert_idx[:, slot], E, dtype=jnp.float32)  # [T, E]
        pos = jnp.cumsum(m, axis=0) - 1.0 + counts[None, :]  # [T, E]
        keep = (pos < C) * m  # dropped tokens lose this slot
        pos_oh = jax.nn.one_hot(
            jnp.clip(pos, 0, C - 1).astype(jnp.int32), C, dtype=jnp.float32
        )  # [T, E, C]
        combine = combine + gate_vals[:, slot, None, None] * keep[:, :, None] * pos_oh
        counts = counts + jnp.sum(m, axis=0)

    # Load-balance loss over ALL k routing slots (GShard-style mean of
    # one-hots across slots; Switch eq. 4 is the k=1 special case). Counting
    # only slot 0 would leave routing collapse in later slots invisible to
    # the penalty when experts_per_token > 1.
    frac = jnp.mean(jax.nn.one_hot(expert_idx, E, dtype=jnp.float32), axis=(0, 1))
    meanprob = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac * meanprob)
    return combine, aux


def _expert_mm(moe: Params, name: str, spec: str, x: jnp.ndarray) -> jnp.ndarray:
    """Per-expert matmul over a (possibly int8-quantized) stacked weight.

    Quantized experts ({name}_q int8 + {name}_scales [E, out], written by
    ops/int8.quantize_params' moe branch) dequantize in the epilogue —
    w8a16 style, same contract as int8.int8_matmul: the int8→dtype convert
    feeds the MXU and the per-out-channel scale folds into the product."""
    if f"{name}_q" in moe:
        w_q = moe[f"{name}_q"]
        # fp32 accumulate + fp32 scale fold, single cast at the end — the
        # same numerics as int8.int8_matmul's epilogue (accumulating in
        # bf16 would stack rounding on top of the int8 noise).
        y = jnp.einsum(
            spec, x, w_q.astype(x.dtype), preferred_element_type=jnp.float32
        )
        return (y * moe[f"{name}_scales"][:, None, :]).astype(x.dtype)
    return jnp.einsum(spec, x, moe[name])


def moe_mlp(cfg: ModelConfig, moe: Params, x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Routed FFN. x: [b, s, h] → ([b, s, h], scalar aux load-balance loss)."""
    b, s, h = x.shape
    T = b * s
    C = expert_capacity(cfg, T)
    xt = x.reshape(T, h)

    combine, aux = route_tokens(cfg, moe["router"]["kernel"], xt, C)
    dispatch = (combine > 0).astype(cfg.activation_dtype)  # [T, E, C]
    expert_in = jnp.einsum(
        "tec,th->ech", dispatch, xt.astype(cfg.activation_dtype)
    )  # [E, C, h]

    if cfg.gated:
        hidden = _activate(
            cfg, _expert_mm(moe, "gate", "ech,ehi->eci", expert_in)
        ) * _expert_mm(moe, "up", "ech,ehi->eci", expert_in)
    else:
        hidden = _activate(cfg, _expert_mm(moe, "up", "ech,ehi->eci", expert_in))
    expert_out = _expert_mm(moe, "down", "eci,eih->ech", hidden)  # [E, C, h]

    y = jnp.einsum(
        "tec,ech->th", combine.astype(cfg.activation_dtype), expert_out
    ).reshape(b, s, h)
    return y.astype(x.dtype), aux
