"""TPU compute ops: norms, rotary embeddings, attention, sampling, int8 kernels."""
