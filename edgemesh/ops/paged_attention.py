"""Paged decode attention: one Pallas kernel walks each sequence's page table.

Companion to runtime/paged_kv.py (the HeadInfer-analog paged KV cache,
BASELINE.json configs[3]). Dense decode attention reads a ``[b, max_seq]``
HBM slab per layer whatever the actual lengths; this kernel reads only the
pages a sequence owns, discovered through the page table at DMA-issue time
via scalar prefetch (pallas_guide.md §PrefetchScalarGridSpec — the index_map
of K/V blocks dereferences the prefetched table, so the DMA engine fetches
physical page ``table[b, p]`` directly; no gather materializes).

Grid ``(batch, max_pages)``; pages are innermost/sequential and accumulate
online-softmax state in VMEM scratch, exactly like ops/flash_attention.py.
The page pool is page-major ``[total_pages, kv_heads, page_size, head_dim]``
so ONE grid step fetches every kv head's slice of a page in a single
contiguous DMA (kh·ps·hd elements — 64 KB for a Llama-1B bf16 page of 64
tokens) instead of the pre-r3 head-major walk whose ``(b, kh, pages)`` grid
issued kh× as many DMAs of ps·hd (8 KB) each — too small to reach HBM
bandwidth, which measured the paged path at half the dense backend's
throughput. GQA rides inside the step: a static loop over kv heads does the
``groups``-row flash update per head against its slice of the page block.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from edgemesh.ops.flash_attention import NEG_INF, _round_up

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False


def _flash_page_update(
    q, k, v, mask, scale, soft_cap, m_scr, l_scr, acc_scr, rows, nrows,
    ks_row=None, vs_row=None,
):
    """One page's online-softmax update for ``nrows`` query rows against a
    [ps, hd] K/V slice — THE shared body of the decode and chunk kernels
    (their grids and masks differ; this must not). ``ks_row``/``vs_row``
    ([1, ps] f32) mark int8 pages: scales fold in after each matmul."""
    quant = ks_row is not None
    if quant:
        q = q.astype(jnp.float32)
        k = k.astype(jnp.float32)
        v = v.astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if quant:
        s = s * ks_row
    if soft_cap > 0:  # Gemma-2 score squashing, pre-mask (attend parity)
        s = soft_cap * jnp.tanh(s / soft_cap)
    s = jnp.where(mask, s, NEG_INF)
    m_prev = m_scr[rows, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    pr = jnp.where(mask, jnp.exp(s - m_new), 0.0)
    alpha = jnp.exp(m_prev - m_new)
    m_scr[rows, :] = jnp.broadcast_to(m_new, (nrows, 128))
    l_new = alpha * l_scr[rows, :1] + jnp.sum(pr, axis=1, keepdims=True)
    l_scr[rows, :] = jnp.broadcast_to(l_new, (nrows, 128))
    if quant:
        pv = jax.lax.dot_general(
            pr * vs_row, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    else:
        pv = jax.lax.dot_general(
            pr.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    acc_scr[rows, :] = alpha * acc_scr[rows, :] + pv


def _paged_kernel(
    *refs,  # table, len, [layer,] (scalar prefetch) then
    # q, k, v, [k_scale, v_scale,] [fk, fv, [fks, fvs],] o, m, l, acc
    n_scalars: int,
    page_size: int,
    scale: float,
    window: int,
    soft_cap: float,
    kv_heads: int,
    gp: int,
    quantized: bool,
    fold_fresh: bool,
):
    # q_ref   VMEM [1, kh, gp, hd]
    # k_ref   VMEM [1, kh, ps, hd] — physical page table[b, p], all kv heads
    #         (int8 when quantized, with ks/vs VMEM [1, kh, 1, ps] f32 scales)
    # fk_ref  VMEM [1, kh, 1, hd] — current token's K, not yet in any page
    #         (fold_fresh mode: the hoisted-write decode path, see
    #         runtime/paged_generate + ops/paged_write)
    # o_ref   VMEM [1, kh, gp, hd]
    # scratch VMEM [kh*gp, 128] f32 ×2 (m, l) + [kh*gp, hd] f32 (acc)
    refs = list(refs)
    table_ref, len_ref = refs[0], refs[1]  # layer scalar (if any) only
    refs = refs[n_scalars:]  # feeds the index maps — skip it here
    q_ref, k_ref, v_ref = refs[:3]
    refs = refs[3:]
    ks_ref = vs_ref = fk_ref = fv_ref = fks_ref = fvs_ref = None
    if quantized:
        ks_ref, vs_ref = refs[:2]
        refs = refs[2:]
    if fold_fresh:
        fk_ref, fv_ref = refs[:2]
        refs = refs[2:]
        if quantized:
            fks_ref, fvs_ref = refs[:2]
            refs = refs[2:]
    o_ref, m_scr, l_scr, acc_scr = refs
    bb = pl.program_id(0)
    p = pl.program_id(1)
    npg = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    kvlen = len_ref[bb]

    if window > 0:
        # Windowed grid: the host shrank the page axis to the slots that can
        # intersect the window, and the K/V index_map walks LOGICAL page
        # first_live + p — recompute that logical index here so the column
        # numbers match what the DMA fetched. Out-of-window pages are never
        # DMA'd at all (the grid doesn't visit them).
        lp = jnp.maximum(kvlen - window, 0) // page_size + p
    else:
        lp = p
    live = lp * page_size < kvlen

    @pl.when(live)
    def _update():
        col = lp * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (gp, page_size), 1
        )
        # fold_fresh: the current token (position kvlen-1) lives in fk/fv,
        # not the pages — its page slot is stale garbage, mask it out here
        # and fold it in at the last grid step instead. Same math, same
        # normalization; only the accumulation order differs.
        mask = col < (kvlen - 1 if fold_fresh else kvlen)
        if window > 0:
            mask = jnp.logical_and(mask, col >= kvlen - window)
        # Static loop over kv heads: each head's groups query rows flash-update
        # against that head's [ps, hd] slice of the page block. 2D ops only —
        # the same shapes the head-major kernel lowered — sliced out of the
        # shared scratch at static offsets. For int8 pages the per-row scales
        # fold in after each matmul (HBM only ever holds the int8 copy; the
        # int8→f32 converts fuse into the MXU operand read).
        for h in range(kv_heads):
            _flash_page_update(
                q_ref[0, h], k_ref[0, h], v_ref[0, h], mask, scale, soft_cap,
                m_scr, l_scr, acc_scr, slice(h * gp, (h + 1) * gp), gp,
                ks_row=ks_ref[0, h] if quantized else None,
                vs_row=vs_ref[0, h] if quantized else None,
            )

    @pl.when(p == npg - 1)
    def _finish():
        if fold_fresh:
            # Virtual page: one more flash update against the current
            # token's own K/V (always visible to its query — the window
            # trivially contains position kvlen-1). The token is padded to
            # 8 slots (Mosaic can't lower K=1 dots); slots 1.. are masked.
            first = jax.lax.broadcasted_iota(jnp.int32, (gp, 8), 1) == 0
            for h in range(kv_heads):
                _flash_page_update(
                    q_ref[0, h], fk_ref[0, h], fv_ref[0, h], first, scale,
                    soft_cap, m_scr, l_scr, acc_scr,
                    slice(h * gp, (h + 1) * gp), gp,
                    ks_row=fks_ref[0, h] if quantized else None,
                    vs_row=fvs_ref[0, h] if quantized else None,
                )
        for h in range(kv_heads):
            rows = slice(h * gp, (h + 1) * gp)
            out = acc_scr[rows, :] / jnp.maximum(l_scr[rows, :1], 1e-30)
            o_ref[0, h] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "interpret", "check", "sliding_window", "soft_cap"),
)
def paged_decode_attention(
    q: jnp.ndarray,  # [b, num_heads, head_dim] — one query token per row
    k_pages: jnp.ndarray,  # [total_pages, kv_heads, page_size, head_dim]
    v_pages: jnp.ndarray,  # (or [L, P, kh, ps, hd] with ``layer`` set)
    page_table: jnp.ndarray,  # [b, max_pages] int32
    kv_lens: jnp.ndarray,  # [b] int32 — valid tokens per row (incl. current)
    scale: float | None = None,
    interpret: bool = False,
    check: bool = False,
    sliding_window: int = 0,
    soft_cap: float = 0.0,
    k_scales: jnp.ndarray | None = None,  # [P, kh, 1, ps] f32 (int8 pool)
    v_scales: jnp.ndarray | None = None,
    layer: jnp.ndarray | None = None,  # scalar int32: 5D full-pool mode
    fresh_k: jnp.ndarray | None = None,  # [b, kh, hd] — current token's K/V,
    fresh_v: jnp.ndarray | None = None,  # NOT yet written to any page
    fresh_ks: jnp.ndarray | None = None,  # [b, kh] f32 (quant pool fresh)
    fresh_vs: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Attention of one decode token per row over its paged KV prefix.

    Returns [b, num_heads, head_dim] in q's dtype. Unallocated table slots
    point at the trash page (physical 0); they are DMA'd but fully masked.
    ``sliding_window`` w > 0 (Mistral/Gemma-2) restricts the query to its
    last w positions — AND shrinks the page grid to the ceil(w/ps)+1 slots
    that can intersect the window, so out-of-window pages are never DMA'd
    (the index_map dereferences logical page first_live + p per row).
    ``soft_cap`` > 0 squashes scaled scores to cap·tanh(s/cap) pre-mask,
    and a non-None ``scale`` carries Gemma-2's fixed query scale — both
    matching ops/attention.attend exactly.

    ``k_scales``/``v_scales`` (both or neither) mark the pool as int8
    (runtime/paged_kv.QuantPagedKVCache): pages dequantize inside the
    kernel via per-token-row scales folded in after each matmul, so the
    page walk streams half the bytes.

    ``layer`` (with 5D ``k_pages`` [L, P, kh, ps, hd]) addresses one layer
    of the full stacked pool directly in the block index_map — the layer
    scan then never materializes an 18 MB pool slice per layer (the
    hoisted-write decode path, ops/paged_write.py docstring).

    ``fresh_k``/``fresh_v`` carry the CURRENT token's K/V when the caller
    has not yet written it to the pages (hoisted-write mode): the kernel
    masks the current position out of the page walk and folds these in as
    a virtual single-token page at the last grid step. ``kv_lens`` still
    counts the current token. Identical math to attending over the written
    page; only the flash accumulation order differs.

    ``check=True`` emits checkify contract asserts (page-table entries inside
    the physical pool, kv_lens within table capacity, finite queries) — run
    through ops.checks.checked (§5.2).
    """
    if not HAVE_PALLAS:  # pragma: no cover
        raise RuntimeError("pallas unavailable")
    quantized = k_scales is not None
    fold_fresh = fresh_k is not None
    full_pool = k_pages.ndim == 5
    if full_pool and layer is None:
        raise ValueError("5D page pools need the `layer` index")
    if not full_pool and layer is not None:
        raise ValueError(
            "`layer` only applies to 5D [L, P, kh, ps, hd] pools; a 4D pool "
            "would silently misread table entries as absolute flat indices"
        )
    if check:
        from edgemesh.ops.checks import check_paged_inputs

        # For stacked pools validate against one layer's [P, kh, ps, hd]
        # view — table entries and kv_lens bounds are per-layer quantities.
        check_paged_inputs(
            q, k_pages[0] if full_pool else k_pages, page_table, kv_lens
        )
    b, nh, hd = q.shape
    kh, ps = k_pages.shape[-3], k_pages.shape[-2]
    groups = nh // kh
    max_pages = page_table.shape[1]
    scale = scale if scale is not None else hd**-0.5

    gp = _round_up(groups, 8)  # sublane-align the q rows
    hp = hd if hd % 64 == 0 else _round_up(hd, 128)
    qg = q.reshape(b, kh, groups, hd)
    qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - groups), (0, hp - hd)))
    if hp != hd:
        pad = [(0, 0)] * (k_pages.ndim - 1) + [(0, hp - hd)]
        k_pages = jnp.pad(k_pages, pad)
        v_pages = jnp.pad(v_pages, pad)

    # 5D pools collapse to 4D [L*P, kh, ps, hd] (a free leading-dim merge —
    # a true 5D operand cost a full-pool relayout copy per call on this
    # backend, measured +0.6 ms at 0.57 GB) and the layer becomes a page
    # offset: physical block index = layer * P + table[bb, p].
    if full_pool:
        P = k_pages.shape[1]
        k_pages = k_pages.reshape((-1,) + k_pages.shape[2:])
        v_pages = v_pages.reshape((-1,) + v_pages.shape[2:])
        if quantized:
            k_scales = k_scales.reshape((-1,) + k_scales.shape[2:])
            v_scales = v_scales.reshape((-1,) + v_scales.shape[2:])
        off = lambda scalars: scalars[2][0] * P
    else:
        off = lambda scalars: 0

    if sliding_window > 0:
        # Only pages intersecting [kvlen-w, kvlen) can contribute: the first
        # may be partial (+1) and the last may be partial (+1) → w//ps + 2
        # slots bound the live span for every row.
        npages = min(max_pages, sliding_window // ps + 2)

        def kv_map(bb, p, *scalars):
            table, lens = scalars[0], scalars[1]
            first_live = jnp.maximum(lens[bb] - sliding_window, 0) // ps
            # Clamp: near capacity first_live+p can step past the table; the
            # clamped duplicate fetch is masked dead in the kernel (live=False
            # once lp*ps >= kvlen).
            return (off(scalars)
                    + table[bb, jnp.minimum(first_live + p, max_pages - 1)],
                    0, 0, 0)
    else:
        npages = max_pages

        def kv_map(bb, p, *scalars):
            return (off(scalars) + scalars[0][bb, p], 0, 0, 0)

    def q_map(bb, p, *scalars):
        return (bb, 0, 0, 0)

    grid = (b, npages)
    kernel = functools.partial(
        _paged_kernel, n_scalars=3 if full_pool else 2, page_size=ps,
        scale=scale, window=sliding_window, soft_cap=soft_cap, kv_heads=kh,
        gp=gp, quantized=quantized, fold_fresh=fold_fresh,
    )
    kv_block = (1, kh, ps, hp)
    sc_block = (1, kh, 1, ps)
    in_specs = [
        pl.BlockSpec((1, kh, gp, hp), q_map),
        pl.BlockSpec(kv_block, kv_map),
        pl.BlockSpec(kv_block, kv_map),
    ]
    operands = [qg, k_pages, v_pages]
    if quantized:
        # Scale blocks ride the same page index_map; [1, ps] per head.
        in_specs += [pl.BlockSpec(sc_block, kv_map), pl.BlockSpec(sc_block, kv_map)]
        operands += [k_scales, v_scales]
    if fold_fresh:
        # 8 virtual slots (only slot 0 real — K=1 dots don't lower).
        fkp = jnp.pad(fresh_k.reshape(b, kh, 1, hd),
                      ((0, 0), (0, 0), (0, 7), (0, hp - hd)))
        fvp = jnp.pad(fresh_v.reshape(b, kh, 1, hd),
                      ((0, 0), (0, 0), (0, 7), (0, hp - hd)))
        in_specs += [
            pl.BlockSpec((1, kh, 8, hp), q_map),
            pl.BlockSpec((1, kh, 8, hp), q_map),
        ]
        operands += [fkp.astype(k_pages.dtype), fvp.astype(v_pages.dtype)]
        if quantized:
            in_specs += [
                pl.BlockSpec((1, kh, 1, 8), q_map),
                pl.BlockSpec((1, kh, 1, 8), q_map),
            ]
            operands += [
                jnp.pad(fresh_ks.reshape(b, kh, 1, 1), ((0, 0), (0, 0), (0, 0), (0, 7))).astype(jnp.float32),
                jnp.pad(fresh_vs.reshape(b, kh, 1, 1), ((0, 0), (0, 0), (0, 0), (0, 7))).astype(jnp.float32),
            ]
    scalars = [page_table.astype(jnp.int32), kv_lens.astype(jnp.int32)]
    if full_pool:
        scalars.append(jnp.reshape(layer, (1,)).astype(jnp.int32))
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(scalars),
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, kh, gp, hp), q_map),
            scratch_shapes=[
                pltpu.VMEM((kh * gp, 128), jnp.float32),
                pltpu.VMEM((kh * gp, 128), jnp.float32),
                pltpu.VMEM((kh * gp, hp), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kh, gp, hp), q.dtype),
        interpret=interpret,
    )(*scalars, *operands)
    return out[:, :, :groups, :hd].reshape(b, nh, hd)


def _paged_chunk_kernel(
    table_ref,  # SMEM [b, max_pages] int32 (scalar prefetch)
    start_ref,  # SMEM [b] int32 — tokens in pages BEFORE this chunk
    len_ref,  # SMEM [b] int32 — final tokens incl. the chunk
    *refs,  # q, k, v, [k_scale, v_scale,] o, m_scr, l_scr, acc_scr
    page_size: int,
    scale: float,
    soft_cap: float,
    kv_heads: int,
    rq: int,
    groups: int,
    quantized: bool,
):
    # q_ref   VMEM [1, kh, rq, hd] — rq = cq*groups query rows (padded)
    # k_ref   VMEM [1, kh, ps, hd] — physical page table[b, p]
    #         (int8 when quantized, with ks/vs VMEM [1, kh, 1, ps] f32)
    # o_ref   VMEM [1, kh, rq, hd]
    # scratch VMEM [kh*rq, 128] f32 ×2 (m, l) + [kh*rq, hd] f32 (acc)
    if quantized:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = refs
    bb = pl.program_id(0)
    p = pl.program_id(1)
    npg = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    start = start_ref[bb]
    kvlen = len_ref[bb]
    live = p * page_size < kvlen

    @pl.when(live)
    def _update():
        col = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (rq, page_size), 1
        )
        # Query row r is chunk token r // groups: causal over the prefix +
        # its own position (the chunk's K/V are already in the pages).
        c = jax.lax.broadcasted_iota(jnp.int32, (rq, page_size), 0) // groups
        mask = col < jnp.minimum(start + c + 1, kvlen)
        for h in range(kv_heads):
            _flash_page_update(
                q_ref[0, h], k_ref[0, h], v_ref[0, h], mask, scale, soft_cap,
                m_scr, l_scr, acc_scr, slice(h * rq, (h + 1) * rq), rq,
                ks_row=ks_ref[0, h] if quantized else None,
                vs_row=vs_ref[0, h] if quantized else None,
            )

    @pl.when(p == npg - 1)
    def _finish():
        for h in range(kv_heads):
            rows = slice(h * rq, (h + 1) * rq)
            out = acc_scr[rows, :] / jnp.maximum(l_scr[rows, :1], 1e-30)
            o_ref[0, h] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret", "soft_cap"))
def paged_chunk_attention(
    q: jnp.ndarray,  # [b, cq, num_heads, head_dim] — chunk queries per row
    k_pages: jnp.ndarray,  # [total_pages, kv_heads, page_size, head_dim]
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [b, max_pages] int32
    start: jnp.ndarray,  # [b] tokens in pages before the chunk
    kv_lens: jnp.ndarray,  # [b] final tokens incl. the chunk
    scale: float | None = None,
    interpret: bool = False,
    soft_cap: float = 0.0,
    k_scales: jnp.ndarray | None = None,  # [P, kh, 1, ps] f32 (int8 pool)
    v_scales: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Chunk-query page walk: ``cq`` query tokens per row attend over the
    row's paged prefix + the chunk's own (already-written) K/V, causally.
    The kernel-grade path for chunk appends (speculative verify, suffix
    prefill) that the gather-based oracle otherwise serves — same
    ``(b, pages)`` grid as decode, query rows = chunk×groups per kv head.
    Full-causal only (no sliding window; callers fall back to the gather
    path for windowed configs); ``k_scales``/``v_scales`` mark an int8
    pool, dequantized in-kernel exactly like decode. Padded chunk rows
    compute garbage that callers discard — their columns stay masked
    within kv_lens, so no NaNs propagate. OPT-IN
    (EDGEMESH_PAGED_CHUNK_KERNEL=1): on-chip measurement found it slower
    than the gather oracle at verify-chunk shapes — numbers in
    runtime/paged_generate._use_chunk_kernel."""
    if not HAVE_PALLAS:  # pragma: no cover
        raise RuntimeError("pallas unavailable")
    quantized = k_scales is not None
    b, cq, nh, hd = q.shape
    _, kh, ps, _ = k_pages.shape
    groups = nh // kh
    max_pages = page_table.shape[1]
    scale = scale if scale is not None else hd**-0.5

    rq = _round_up(cq * groups, 8)
    hp = hd if hd % 64 == 0 else _round_up(hd, 128)
    # [b, cq, kh, groups, hd] → [b, kh, cq*groups, hd]: row r = token r//groups.
    qg = q.reshape(b, cq, kh, groups, hd).transpose(0, 2, 1, 3, 4)
    qg = qg.reshape(b, kh, cq * groups, hd)
    qg = jnp.pad(qg, ((0, 0), (0, 0), (0, rq - cq * groups), (0, hp - hd)))
    if hp != hd:
        k_pages = jnp.pad(k_pages, ((0, 0), (0, 0), (0, 0), (0, hp - hd)))
        v_pages = jnp.pad(v_pages, ((0, 0), (0, 0), (0, 0), (0, hp - hd)))

    def kv_map(bb, p, table, start, lens):
        return (table[bb, p], 0, 0, 0)

    kernel = functools.partial(
        _paged_chunk_kernel, page_size=ps, scale=scale, soft_cap=soft_cap,
        kv_heads=kh, rq=rq, groups=groups, quantized=quantized,
    )
    in_specs = [
        pl.BlockSpec((1, kh, rq, hp), lambda bb, p, table, start, lens: (bb, 0, 0, 0)),
        pl.BlockSpec((1, kh, ps, hp), kv_map),
        pl.BlockSpec((1, kh, ps, hp), kv_map),
    ]
    operands = [qg, k_pages, v_pages]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, kh, 1, ps), kv_map),
            pl.BlockSpec((1, kh, 1, ps), kv_map),
        ]
        operands += [k_scales, v_scales]
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(b, max_pages),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, kh, rq, hp), lambda bb, p, table, start, lens: (bb, 0, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((kh * rq, 128), jnp.float32),
                pltpu.VMEM((kh * rq, 128), jnp.float32),
                pltpu.VMEM((kh * rq, hp), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kh, rq, hp), q.dtype),
        interpret=interpret,
    )(
        page_table.astype(jnp.int32), start.astype(jnp.int32),
        kv_lens.astype(jnp.int32), *operands,
    )
    out = out[:, :, : cq * groups, :hd].reshape(b, kh, cq, groups, hd)
    return out.transpose(0, 2, 1, 3, 4).reshape(b, cq, nh, hd)


# ---------------------------------------------------------------------------
# Ragged paged attention: one launch for a mixed prefill+decode batch
# ---------------------------------------------------------------------------

# Query tokens per ragged block. Each packed segment is padded (internally —
# callers pass real cu_q_lens) to a multiple of this, so every block's rows
# belong to exactly ONE sequence and the Q/O BlockSpecs stay identity maps.
# 8 matches the sublane tile and the decode kernel's virtual-page width.
_RAGGED_BQT = 8


def _ragged_kernel(
    *refs,  # table, len, qlen, acu, blkseq, [layer,] (scalar prefetch) then
    # q, k, v, [ks, vs,] [fk, fv, [fks, fvs],] o, m, l, acc
    n_scalars: int,
    page_size: int,
    scale: float,
    window: int,
    soft_cap: float,
    kv_heads: int,
    groups: int,
    npages: int,
    nseq: int,
    quantized: bool,
    fold_fresh: bool,
):
    # q_ref   VMEM [1, kh, rq, hd] — rq = BQT*groups rows; row r is the
    #         block's token r // groups (same convention as the chunk kernel)
    # k_ref   VMEM [1, kh, ps, hd] — physical page table[seq, p], all kv heads
    # fk_ref  VMEM [1, kh, BQT, hd] — one PACKED BLOCK of the chunk's own K,
    #         not yet in any page (fresh axis of the grid; fold_fresh mode)
    # o_ref   VMEM [1, kh, rq, hd]
    # scratch VMEM [kh*rq, 128] f32 ×2 (m, l) + [kh*rq, hd] f32 (acc)
    bqt = _RAGGED_BQT
    refs = list(refs)
    table_ref, len_ref, qlen_ref, acu_ref, blkseq_ref = refs[:5]
    refs = refs[n_scalars:]
    q_ref, k_ref, v_ref = refs[:3]
    refs = refs[3:]
    ks_ref = vs_ref = fk_ref = fv_ref = fks_ref = fvs_ref = None
    if quantized:
        ks_ref, vs_ref = refs[:2]
        refs = refs[2:]
    if fold_fresh:
        fk_ref, fv_ref = refs[:2]
        refs = refs[2:]
        if quantized:
            fks_ref, fvs_ref = refs[:2]
            refs = refs[2:]
    o_ref, m_scr, l_scr, acc_scr = refs
    g = pl.program_id(0)
    p = pl.program_id(1)
    npg = pl.num_programs(1)
    rq = bqt * groups

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    seq = blkseq_ref[g]
    kvlen = len_ref[seq]
    qlen = qlen_ref[seq]
    qstart = kvlen - qlen  # tokens committed to pages before this chunk
    tok0 = g * bqt - acu_ref[seq]  # block's first token index in its segment
    live_blk = g * bqt < acu_ref[nseq]
    # Page columns visible from the table walk: the committed prefix only in
    # fold_fresh mode (the chunk itself rides the fresh axis), the full
    # causal prefix when the chunk is already written to its pages.
    limit = qstart if fold_fresh else kvlen

    # Per-row segment-token index / absolute position (row r = token r//groups).
    tseg1 = tok0 + jax.lax.broadcasted_iota(jnp.int32, (rq, 1), 0) // groups

    @pl.when(live_blk & (p < npages) & (p * page_size < limit))
    def _pages():
        col = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (rq, page_size), 1
        )
        pos = qstart + tseg1  # [rq, 1]
        mask = (tseg1 < qlen) & (
            col < limit if fold_fresh else col <= jnp.minimum(pos, kvlen - 1)
        )
        if window > 0:
            mask = jnp.logical_and(mask, col > pos - window)
        for h in range(kv_heads):
            _flash_page_update(
                q_ref[0, h], k_ref[0, h], v_ref[0, h], mask, scale, soft_cap,
                m_scr, l_scr, acc_scr, slice(h * rq, (h + 1) * rq), rq,
                ks_row=ks_ref[0, h] if quantized else None,
                vs_row=vs_ref[0, h] if quantized else None,
            )

    if fold_fresh:
        f = p - npages
        fsame = blkseq_ref[jnp.clip(f, 0, pl.num_programs(0) - 1)] == seq

        @pl.when(live_blk & (p >= npages) & (f <= g) & fsame)
        def _fresh():
            # Key token index within the segment for each fresh-block slot.
            kseg = f * bqt - acu_ref[seq] + jax.lax.broadcasted_iota(
                jnp.int32, (rq, bqt), 1
            )
            mask = (tseg1 < qlen) & (kseg >= 0) & (kseg < qlen) & (kseg <= tseg1)
            if window > 0:
                mask = jnp.logical_and(mask, kseg > tseg1 - window)
            for h in range(kv_heads):
                _flash_page_update(
                    q_ref[0, h], fk_ref[0, h], fv_ref[0, h], mask, scale,
                    soft_cap, m_scr, l_scr, acc_scr,
                    slice(h * rq, (h + 1) * rq), rq,
                    ks_row=fks_ref[0, h] if quantized else None,
                    vs_row=fvs_ref[0, h] if quantized else None,
                )

    @pl.when(p == npg - 1)
    def _finish():
        for h in range(kv_heads):
            rows = slice(h * rq, (h + 1) * rq)
            out = acc_scr[rows, :] / jnp.maximum(l_scr[rows, :1], 1e-30)
            o_ref[0, h] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "interpret", "check", "sliding_window", "soft_cap"),
)
def ragged_paged_attention(
    q: jnp.ndarray,  # [T, num_heads, head_dim] — packed token-major queries
    k_pages: jnp.ndarray,  # [total_pages, kv_heads, page_size, head_dim]
    v_pages: jnp.ndarray,  # (or [L, P, kh, ps, hd] with ``layer`` set)
    page_table: jnp.ndarray,  # [b, max_pages] int32
    kv_lens: jnp.ndarray,  # [b] int32 — final tokens per seq INCL. its chunk
    cu_q_lens: jnp.ndarray,  # [b+1] int32 — cumulative query counts; seq i's
    # queries are q rows [cu_q_lens[i], cu_q_lens[i+1]) (zero-length rows ok)
    scale: float | None = None,
    interpret: bool = False,
    check: bool = False,
    sliding_window: int = 0,
    soft_cap: float = 0.0,
    k_scales: jnp.ndarray | None = None,  # [P, kh, 1, ps] f32 (int8 pool)
    v_scales: jnp.ndarray | None = None,
    layer: jnp.ndarray | None = None,  # scalar int32: 5D full-pool mode
    fresh_k: jnp.ndarray | None = None,  # [T, kh, hd] packed chunk K/V, NOT
    fresh_v: jnp.ndarray | None = None,  # yet written to any page
    fresh_ks: jnp.ndarray | None = None,  # [T, kh] f32 (quant pool fresh)
    fresh_vs: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """ONE kernel launch for a ragged batch of mixed prefill chunks and
    decode rows over the page table (the TPU Ragged Paged Attention design,
    arXiv 2604.15464): ``q`` is the token-major concatenation of every
    sequence's variable-length query segment — a 1-token decode row and a
    512-token prefill chunk ride the same grid — and ``(kv_lens, page_table,
    cu_q_lens)`` is the only metadata. No per-segment dispatch exists:
    serving admission prefill and resident decode share this launch
    (serve/continuous.py).

    Per sequence ``i`` with ``ql = cu_q_lens[i+1] - cu_q_lens[i]`` queries,
    query ``j`` sits at absolute position ``kv_lens[i] - ql + j`` and
    attends causally over the sequence's paged prefix plus the chunk's own
    earlier tokens. Returns [T, num_heads, head_dim] in q's dtype (rows of
    zero-length sequences and the packed tail are garbage — callers slice
    by cu_q_lens).

    Internally segments are re-packed to 8-token-aligned blocks (two cheap
    [T]-row gathers bracket the launch) so each grid block belongs to ONE
    sequence and the grid is ``(q_blocks, pages [+ fresh blocks])`` — total
    page-walk DMA is the per-sequence walk the decode kernel already does,
    now shared by every segment shape in the batch.

    ``fresh_k``/``fresh_v`` carry the chunk's OWN K/V (packed exactly like
    q) when the caller has not yet written it to the pages (the hoisted-
    write serving path): the page walk masks to the committed prefix and the
    chunk attends to itself through a third grid axis of packed fresh
    blocks. ``sliding_window``/``soft_cap``/``k_scales``/``layer`` follow
    paged_decode_attention's contracts (the window here is mask-only: the
    ragged grid does not shrink the page axis).

    ``check=True`` emits checkify contract asserts (ops.checks.
    check_ragged_inputs) — run through ops.checks.checked (§5.2).
    """
    if not HAVE_PALLAS:  # pragma: no cover
        raise RuntimeError("pallas unavailable")
    quantized = k_scales is not None
    fold_fresh = fresh_k is not None
    full_pool = k_pages.ndim == 5
    if full_pool and layer is None:
        raise ValueError("5D page pools need the `layer` index")
    if not full_pool and layer is not None:
        raise ValueError("`layer` only applies to 5D [L, P, kh, ps, hd] pools")
    if check:
        from edgemesh.ops.checks import check_ragged_inputs

        check_ragged_inputs(
            q, k_pages[0] if full_pool else k_pages, page_table, kv_lens,
            cu_q_lens,
        )
    bqt = _RAGGED_BQT
    T, nh, hd = q.shape
    kh, ps = k_pages.shape[-3], k_pages.shape[-2]
    groups = nh // kh
    b, max_pages = page_table.shape
    scale = scale if scale is not None else hd**-0.5
    hp = hd if hd % 64 == 0 else _round_up(hd, 128)

    cu = cu_q_lens.astype(jnp.int32)
    q_lens = cu[1:] - cu[:-1]
    kv_lens = kv_lens.astype(jnp.int32)

    # Aligned re-pack: segment i moves to rows [acu[i], acu[i]+q_lens[i]) with
    # acu[i] a multiple of bqt, so every block has one owner. Tp is the static
    # worst case (each segment padded by < bqt).
    Tp = _round_up(T, bqt) + b * bqt
    nblk = Tp // bqt
    acu = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(((q_lens + bqt - 1) // bqt) * bqt)]
    ).astype(jnp.int32)
    rows = jnp.arange(Tp, dtype=jnp.int32)
    seq_al = jnp.clip(jnp.searchsorted(acu, rows, side="right") - 1, 0, b - 1)
    src = jnp.clip(cu[seq_al] + rows - acu[seq_al], 0, T - 1)
    blkseq = jnp.clip(
        jnp.searchsorted(acu, jnp.arange(nblk, dtype=jnp.int32) * bqt,
                         side="right") - 1,
        0, b - 1,
    ).astype(jnp.int32)

    rq = bqt * groups
    qg = jnp.take(q, src, axis=0).reshape(nblk, bqt, kh, groups, hd)
    qg = qg.transpose(0, 2, 1, 3, 4).reshape(nblk, kh, rq, hd)
    qg = jnp.pad(qg, ((0, 0), (0, 0), (0, 0), (0, hp - hd)))
    if hp != hd:
        pad = [(0, 0)] * (k_pages.ndim - 1) + [(0, hp - hd)]
        k_pages = jnp.pad(k_pages, pad)
        v_pages = jnp.pad(v_pages, pad)

    # 5D pools collapse to 4D with the layer as a page offset, exactly like
    # paged_decode_attention (free leading-dim merge).
    if full_pool:
        P = k_pages.shape[1]
        k_pages = k_pages.reshape((-1,) + k_pages.shape[2:])
        v_pages = v_pages.reshape((-1,) + v_pages.shape[2:])
        if quantized:
            k_scales = k_scales.reshape((-1,) + k_scales.shape[2:])
            v_scales = v_scales.reshape((-1,) + v_scales.shape[2:])
        off = lambda scalars: scalars[5][0] * P
    else:
        off = lambda scalars: 0

    def q_map(g, p, *scalars):
        return (g, 0, 0, 0)

    def kv_map(g, p, *scalars):
        table, lens, qlens, acu_s, bsq = scalars[:5]
        seq = bsq[g]
        live = g * bqt < acu_s[b]
        lim = lens[seq] - (qlens[seq] if fold_fresh else 0)
        # Clamp dead pages (and the fresh-axis steps) onto the row's last
        # live page: consecutive duplicate indices cost one DMA, so the walk
        # never streams trash pages.
        pmax = jnp.maximum((lim + ps - 1) // ps - 1, 0)
        p_eff = jnp.where(live, jnp.minimum(p, pmax), 0)
        return (off(scalars) + table[seq, p_eff], 0, 0, 0)

    def fresh_map(g, p, *scalars):
        bsq = scalars[4]
        f = p - max_pages
        ok = (f >= 0) & (f <= g) & (bsq[jnp.clip(f, 0, nblk - 1)] == bsq[g])
        return (jnp.where(ok, f, g), 0, 0, 0)

    grid = (nblk, max_pages + (nblk if fold_fresh else 0))
    kernel = functools.partial(
        _ragged_kernel, n_scalars=6 if full_pool else 5, page_size=ps,
        scale=scale, window=sliding_window, soft_cap=soft_cap, kv_heads=kh,
        groups=groups, npages=max_pages, nseq=b, quantized=quantized,
        fold_fresh=fold_fresh,
    )
    in_specs = [
        pl.BlockSpec((1, kh, rq, hp), q_map),
        pl.BlockSpec((1, kh, ps, hp), kv_map),
        pl.BlockSpec((1, kh, ps, hp), kv_map),
    ]
    operands = [qg, k_pages, v_pages]
    if quantized:
        sc_block = (1, kh, 1, ps)
        in_specs += [pl.BlockSpec(sc_block, kv_map), pl.BlockSpec(sc_block, kv_map)]
        operands += [k_scales, v_scales]
    if fold_fresh:
        fkp = jnp.take(fresh_k, src, axis=0).reshape(nblk, bqt, kh, hd)
        fkp = fkp.transpose(0, 2, 1, 3)
        fvp = jnp.take(fresh_v, src, axis=0).reshape(nblk, bqt, kh, hd)
        fvp = fvp.transpose(0, 2, 1, 3)
        fkp = jnp.pad(fkp, ((0, 0), (0, 0), (0, 0), (0, hp - hd)))
        fvp = jnp.pad(fvp, ((0, 0), (0, 0), (0, 0), (0, hp - hd)))
        in_specs += [
            pl.BlockSpec((1, kh, bqt, hp), fresh_map),
            pl.BlockSpec((1, kh, bqt, hp), fresh_map),
        ]
        operands += [fkp.astype(k_pages.dtype), fvp.astype(v_pages.dtype)]
        if quantized:
            fksp = jnp.take(fresh_ks, src, axis=0).reshape(nblk, bqt, kh)
            fksp = fksp.transpose(0, 2, 1)[:, :, None, :]
            fvsp = jnp.take(fresh_vs, src, axis=0).reshape(nblk, bqt, kh)
            fvsp = fvsp.transpose(0, 2, 1)[:, :, None, :]
            in_specs += [
                pl.BlockSpec((1, kh, 1, bqt), fresh_map),
                pl.BlockSpec((1, kh, 1, bqt), fresh_map),
            ]
            operands += [fksp.astype(jnp.float32), fvsp.astype(jnp.float32)]
    scalars = [
        page_table.astype(jnp.int32), kv_lens, q_lens.astype(jnp.int32),
        acu, blkseq,
    ]
    if full_pool:
        scalars.append(jnp.reshape(layer, (1,)).astype(jnp.int32))
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(scalars),
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, kh, rq, hp), q_map),
            scratch_shapes=[
                pltpu.VMEM((kh * rq, 128), jnp.float32),
                pltpu.VMEM((kh * rq, 128), jnp.float32),
                pltpu.VMEM((kh * rq, hp), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((nblk, kh, rq, hp), q.dtype),
        interpret=interpret,
    )(*scalars, *operands)
    # Aligned → real re-pack: row t of the result is aligned row
    # acu[seq(t)] + (t - cu[seq(t)]).
    out = out.reshape(nblk, kh, bqt, groups, hp).transpose(0, 2, 1, 3, 4)
    out = out.reshape(Tp, nh, hp)[:, :, :hd]
    treal = jnp.arange(T, dtype=jnp.int32)
    seq_re = jnp.clip(jnp.searchsorted(cu, treal, side="right") - 1, 0, b - 1)
    src_al = jnp.clip(acu[seq_re] + treal - cu[seq_re], 0, Tp - 1)
    return jnp.take(out, src_al, axis=0)


def ragged_paged_attention_xla(
    q: jnp.ndarray,  # [T, nh, hd] packed token-major
    k_pages: jnp.ndarray,  # [P, kh, ps, hd] (one layer)
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [b, max_pages]
    kv_lens: jnp.ndarray,  # [b]
    cu_q_lens: jnp.ndarray,  # [b+1]
    scale: float | None = None,
    sliding_window: int = 0,
    soft_cap: float = 0.0,
    k_scales: jnp.ndarray | None = None,
    v_scales: jnp.ndarray | None = None,
    fresh_k: jnp.ndarray | None = None,  # [T, kh, hd] packed (not yet written)
    fresh_v: jnp.ndarray | None = None,
    fresh_ks: jnp.ndarray | None = None,
    fresh_vs: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """XLA fallback / oracle for :func:`ragged_paged_attention`: gather the
    dense view per sequence, overlay the (optionally fresh) chunk, unpack the
    ragged queries to a padded [b, T] batch, and run the reference ``attend``.
    Same contract, gather bandwidth instead of a page walk."""
    from edgemesh.ops.attention import LayerKV, attend
    from edgemesh.runtime.paged_kv import gather_dense, gather_dense_scales

    T, nh, hd = q.shape
    b = page_table.shape[0]
    cu = cu_q_lens.astype(jnp.int32)
    q_lens = cu[1:] - cu[:-1]
    kv_lens = kv_lens.astype(jnp.int32)
    start = kv_lens - q_lens

    dense_k = gather_dense(k_pages, page_table)  # [b, S, kh, hd]
    dense_v = gather_dense(v_pages, page_table)
    if k_scales is not None:
        ks = gather_dense_scales(k_scales, page_table)
        vs = gather_dense_scales(v_scales, page_table)
        dense_k = (dense_k.astype(jnp.float32) * ks[..., None]).astype(q.dtype)
        dense_v = (dense_v.astype(jnp.float32) * vs[..., None]).astype(q.dtype)
    S = dense_k.shape[1]
    cols = jnp.arange(S, dtype=jnp.int32)[None, :]  # [1, S]
    if fresh_k is not None:
        # Overlay the chunk region [start, kv_len) with the packed fresh
        # rows (dequantized for int8 pools — what decode will read back).
        if fresh_ks is not None:
            fk = (fresh_k.astype(jnp.float32) * fresh_ks[..., None]).astype(q.dtype)
            fv = (fresh_v.astype(jnp.float32) * fresh_vs[..., None]).astype(q.dtype)
        else:
            fk, fv = fresh_k.astype(q.dtype), fresh_v.astype(q.dtype)
        in_chunk = (cols >= start[:, None]) & (cols < kv_lens[:, None])
        fidx = jnp.clip(cu[:-1, None] + cols - start[:, None], 0, T - 1)
        dense_k = jnp.where(in_chunk[..., None, None], fk[fidx], dense_k)
        dense_v = jnp.where(in_chunk[..., None, None], fv[fidx], dense_v)

    # Padded [b, T] query view: row i, slot j = packed row cu[i] + j.
    offs = jnp.arange(T, dtype=jnp.int32)[None, :]
    qidx = jnp.clip(cu[:-1, None] + offs, 0, T - 1)  # [b, T]
    qp = jnp.take(q, qidx.reshape(-1), axis=0).reshape(b, T, nh, hd)
    positions = start[:, None] + offs
    kv_valid = cols < kv_lens[:, None]
    out = attend(
        qp, LayerKV(dense_k, dense_v), positions, kv_valid, scale,
        sliding_window=sliding_window, soft_cap=soft_cap,
    )
    # Repack [b, T] → [T] token-major.
    treal = jnp.arange(T, dtype=jnp.int32)
    seq = jnp.clip(jnp.searchsorted(cu, treal, side="right") - 1, 0, b - 1)
    return out[seq, treal - cu[seq]]


def paged_decode_attention_xla(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    kv_lens: jnp.ndarray,
    scale: float | None = None,
    sliding_window: int = 0,
    soft_cap: float = 0.0,
    k_scales: jnp.ndarray | None = None,
    v_scales: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """XLA fallback / oracle: gather the dense view, then masked attention."""
    from edgemesh.ops.attention import LayerKV, attend
    from edgemesh.runtime.paged_kv import gather_dense, gather_dense_scales

    b, nh, hd = q.shape
    dense_k = gather_dense(k_pages, page_table)
    dense_v = gather_dense(v_pages, page_table)
    if k_scales is not None:
        ks = gather_dense_scales(k_scales, page_table)  # [b, max_seq, kh]
        vs = gather_dense_scales(v_scales, page_table)
        dense_k = (dense_k.astype(jnp.float32) * ks[..., None]).astype(q.dtype)
        dense_v = (dense_v.astype(jnp.float32) * vs[..., None]).astype(q.dtype)
    max_seq = dense_k.shape[1]
    kv_valid = jnp.arange(max_seq)[None, :] < kv_lens[:, None]
    positions = (kv_lens - 1)[:, None]
    out = attend(
        q[:, None], LayerKV(dense_k, dense_v), positions, kv_valid, scale,
        sliding_window=sliding_window, soft_cap=soft_cap,
    )
    return out[:, 0]
