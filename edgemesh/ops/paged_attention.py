"""Paged decode attention: one Pallas kernel walks each sequence's page table.

Companion to runtime/paged_kv.py (the HeadInfer-analog paged KV cache,
BASELINE.json configs[3]). Dense decode attention reads a ``[b, max_seq]``
HBM slab per layer whatever the actual lengths; this kernel reads only the
pages a sequence owns, discovered through the page table at DMA-issue time
via scalar prefetch (pallas_guide.md §PrefetchScalarGridSpec — the index_map
of K/V blocks dereferences the prefetched table, so the DMA engine fetches
physical page ``table[b, p]`` directly; no gather materializes).

Grid ``(batch, kv_heads, max_pages)``; pages are innermost/sequential and
accumulate online-softmax state in VMEM scratch, exactly like
ops/flash_attention.py. GQA: the ``groups`` query heads of one kv head ride
the sublane dim of a single ``[groups, head_dim]`` q block.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from edgemesh.ops.flash_attention import NEG_INF, _round_up

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False


def _paged_kernel(
    table_ref,  # SMEM [b, max_pages] int32 (scalar prefetch)
    len_ref,  # SMEM [b] int32 (scalar prefetch)
    q_ref,  # VMEM [1, 1, gp, hd]
    k_ref,  # VMEM [1, 1, ps, hd] — physical page table[b, p]
    v_ref,  # VMEM [1, 1, ps, hd]
    o_ref,  # VMEM [1, 1, gp, hd]
    m_scr,  # VMEM [gp, 128] f32
    l_scr,  # VMEM [gp, 128] f32
    acc_scr,  # VMEM [gp, hd] f32
    *,
    page_size: int,
    scale: float,
    window: int,
    soft_cap: float,
):
    bb = pl.program_id(0)
    p = pl.program_id(2)
    npg = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    kvlen = len_ref[bb]

    if window > 0:
        # Windowed grid: the host shrank the page axis to the slots that can
        # intersect the window, and the K/V index_map walks LOGICAL page
        # first_live + p — recompute that logical index here so the column
        # numbers match what the DMA fetched. Out-of-window pages are never
        # DMA'd at all (the grid doesn't visit them), unlike the pre-r3
        # kernel which fetched the whole table and only skipped compute.
        lp = jnp.maximum(kvlen - window, 0) // page_size + p
    else:
        lp = p
    live = lp * page_size < kvlen

    @pl.when(live)
    def _update():
        q = q_ref[0, 0]  # [gp, hd]
        k = k_ref[0, 0]  # [ps, hd]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [gp, ps]
        if soft_cap > 0:  # Gemma-2 score squashing, pre-mask (attend parity)
            s = soft_cap * jnp.tanh(s / soft_cap)
        col = lp * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = col < kvlen
        if window > 0:
            mask = jnp.logical_and(mask, col >= kvlen - window)
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        pr = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_new = alpha * l_scr[:, :1] + jnp.sum(pr, axis=1, keepdims=True)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        pv = jax.lax.dot_general(
            pr.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[:] = alpha * acc_scr[:] + pv

    @pl.when(p == npg - 1)
    def _finish():
        out = acc_scr[:] / jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "interpret", "check", "sliding_window", "soft_cap"),
)
def paged_decode_attention(
    q: jnp.ndarray,  # [b, num_heads, head_dim] — one query token per row
    k_pages: jnp.ndarray,  # [kv_heads, total_pages, page_size, head_dim]
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [b, max_pages] int32
    kv_lens: jnp.ndarray,  # [b] int32 — valid tokens per row (incl. current)
    scale: float | None = None,
    interpret: bool = False,
    check: bool = False,
    sliding_window: int = 0,
    soft_cap: float = 0.0,
) -> jnp.ndarray:
    """Attention of one decode token per row over its paged KV prefix.

    Returns [b, num_heads, head_dim] in q's dtype. Unallocated table slots
    point at the trash page (physical 0); they are DMA'd but fully masked.
    ``sliding_window`` w > 0 (Mistral/Gemma-2) restricts the query to its
    last w positions — AND shrinks the page grid to the ceil(w/ps)+1 slots
    that can intersect the window, so out-of-window pages are never DMA'd
    (the index_map dereferences logical page first_live + p per row).
    ``soft_cap`` > 0 squashes scaled scores to cap·tanh(s/cap) pre-mask,
    and a non-None ``scale`` carries Gemma-2's fixed query scale — both
    matching ops/attention.attend exactly.

    ``check=True`` emits checkify contract asserts (page-table entries inside
    the physical pool, kv_lens within table capacity, finite queries) — run
    through ops.checks.checked (§5.2).
    """
    if not HAVE_PALLAS:  # pragma: no cover
        raise RuntimeError("pallas unavailable")
    if check:
        from edgemesh.ops.checks import check_paged_inputs

        check_paged_inputs(q, k_pages, page_table, kv_lens)
    b, nh, hd = q.shape
    kh, _, ps, _ = k_pages.shape
    groups = nh // kh
    max_pages = page_table.shape[1]
    scale = scale if scale is not None else hd**-0.5

    gp = _round_up(groups, 8)  # sublane-align the q rows
    hp = hd if hd % 64 == 0 else _round_up(hd, 128)
    qg = q.reshape(b, kh, groups, hd)
    qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - groups), (0, hp - hd)))
    if hp != hd:
        k_pages = jnp.pad(k_pages, ((0, 0), (0, 0), (0, 0), (0, hp - hd)))
        v_pages = jnp.pad(v_pages, ((0, 0), (0, 0), (0, 0), (0, hp - hd)))

    if sliding_window > 0:
        # Only pages intersecting [kvlen-w, kvlen) can contribute: the first
        # may be partial (+1) and the last may be partial (+1) → w//ps + 2
        # slots bound the live span for every row.
        npages = min(max_pages, sliding_window // ps + 2)

        def kv_map(bb, h, p, table, lens):
            first_live = jnp.maximum(lens[bb] - sliding_window, 0) // ps
            # Clamp: near capacity first_live+p can step past the table; the
            # clamped duplicate fetch is masked dead in the kernel (live=False
            # once lp*ps >= kvlen).
            return (h, table[bb, jnp.minimum(first_live + p, max_pages - 1)], 0, 0)
    else:
        npages = max_pages

        def kv_map(bb, h, p, table, lens):
            return (h, table[bb, p], 0, 0)

    grid = (b, kh, npages)
    kernel = functools.partial(
        _paged_kernel, page_size=ps, scale=scale, window=sliding_window,
        soft_cap=soft_cap,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, 1, gp, hp), lambda bb, h, p, table, lens: (bb, h, 0, 0)
                ),
                pl.BlockSpec((1, 1, ps, hp), kv_map),
                pl.BlockSpec((1, 1, ps, hp), kv_map),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, gp, hp), lambda bb, h, p, table, lens: (bb, h, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((gp, 128), jnp.float32),
                pltpu.VMEM((gp, 128), jnp.float32),
                pltpu.VMEM((gp, hp), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kh, gp, hp), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), kv_lens.astype(jnp.int32), qg, k_pages, v_pages)
    return out[:, :, :groups, :hd].reshape(b, nh, hd)


def paged_decode_attention_xla(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    kv_lens: jnp.ndarray,
    scale: float | None = None,
    sliding_window: int = 0,
    soft_cap: float = 0.0,
) -> jnp.ndarray:
    """XLA fallback / oracle: gather the dense view, then masked attention."""
    from edgemesh.ops.attention import LayerKV, attend
    from edgemesh.runtime.paged_kv import gather_dense

    b, nh, hd = q.shape
    dense_k = gather_dense(k_pages, page_table)
    dense_v = gather_dense(v_pages, page_table)
    max_seq = dense_k.shape[1]
    kv_valid = jnp.arange(max_seq)[None, :] < kv_lens[:, None]
    positions = (kv_lens - 1)[:, None]
    out = attend(
        q[:, None], LayerKV(dense_k, dense_v), positions, kv_valid, scale,
        sliding_window=sliding_window, soft_cap=soft_cap,
    )
    return out[:, 0]
