"""Paged decode attention: one Pallas kernel walks each sequence's page table.

Companion to runtime/paged_kv.py (the HeadInfer-analog paged KV cache,
BASELINE.json configs[3]). Dense decode attention reads a ``[b, max_seq]``
HBM slab per layer whatever the actual lengths; this kernel reads only the
pages a sequence owns, discovered through the page table at DMA-issue time
via scalar prefetch (pallas_guide.md §PrefetchScalarGridSpec — the index_map
of K/V blocks dereferences the prefetched table, so the DMA engine fetches
physical page ``table[b, p]`` directly; no gather materializes).

Grid ``(batch, max_pages)``; pages are innermost/sequential and accumulate
online-softmax state in VMEM scratch, exactly like ops/flash_attention.py.
The page pool is page-major ``[total_pages, kv_heads, page_size, head_dim]``
so ONE grid step fetches every kv head's slice of a page in a single
contiguous DMA (kh·ps·hd elements — 64 KB for a Llama-1B bf16 page of 64
tokens) instead of the pre-r3 head-major walk whose ``(b, kh, pages)`` grid
issued kh× as many DMAs of ps·hd (8 KB) each — too small to reach HBM
bandwidth, which measured the paged path at half the dense backend's
throughput. GQA rides inside the step: a static loop over kv heads does the
``groups``-row flash update per head against its slice of the page block.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from edgemesh.ops.flash_attention import NEG_INF, _round_up

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False


def _flash_page_update(
    q, k, v, mask, scale, soft_cap, m_scr, l_scr, acc_scr, rows, nrows,
    ks_row=None, vs_row=None,
):
    """One page's online-softmax update for ``nrows`` query rows against a
    [ps, hd] K/V slice — THE shared body of the decode and chunk kernels
    (their grids and masks differ; this must not). ``ks_row``/``vs_row``
    ([1, ps] f32) mark int8 pages: scales fold in after each matmul."""
    quant = ks_row is not None
    if quant:
        q = q.astype(jnp.float32)
        k = k.astype(jnp.float32)
        v = v.astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if quant:
        s = s * ks_row
    if soft_cap > 0:  # Gemma-2 score squashing, pre-mask (attend parity)
        s = soft_cap * jnp.tanh(s / soft_cap)
    s = jnp.where(mask, s, NEG_INF)
    m_prev = m_scr[rows, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    pr = jnp.where(mask, jnp.exp(s - m_new), 0.0)
    alpha = jnp.exp(m_prev - m_new)
    m_scr[rows, :] = jnp.broadcast_to(m_new, (nrows, 128))
    l_new = alpha * l_scr[rows, :1] + jnp.sum(pr, axis=1, keepdims=True)
    l_scr[rows, :] = jnp.broadcast_to(l_new, (nrows, 128))
    if quant:
        pv = jax.lax.dot_general(
            pr * vs_row, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    else:
        pv = jax.lax.dot_general(
            pr.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    acc_scr[rows, :] = alpha * acc_scr[rows, :] + pv


def _paged_kernel(
    table_ref,  # SMEM [b, max_pages] int32 (scalar prefetch)
    len_ref,  # SMEM [b] int32 (scalar prefetch)
    *refs,  # q, k, v, [k_scale, v_scale,] o, m_scr, l_scr, acc_scr
    page_size: int,
    scale: float,
    window: int,
    soft_cap: float,
    kv_heads: int,
    gp: int,
    quantized: bool,
):
    # q_ref   VMEM [1, kh, gp, hd]
    # k_ref   VMEM [1, kh, ps, hd] — physical page table[b, p], all kv heads
    #         (int8 when quantized, with ks/vs VMEM [1, kh, 1, ps] f32 scales)
    # o_ref   VMEM [1, kh, gp, hd]
    # scratch VMEM [kh*gp, 128] f32 ×2 (m, l) + [kh*gp, hd] f32 (acc)
    if quantized:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = refs
    bb = pl.program_id(0)
    p = pl.program_id(1)
    npg = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    kvlen = len_ref[bb]

    if window > 0:
        # Windowed grid: the host shrank the page axis to the slots that can
        # intersect the window, and the K/V index_map walks LOGICAL page
        # first_live + p — recompute that logical index here so the column
        # numbers match what the DMA fetched. Out-of-window pages are never
        # DMA'd at all (the grid doesn't visit them).
        lp = jnp.maximum(kvlen - window, 0) // page_size + p
    else:
        lp = p
    live = lp * page_size < kvlen

    @pl.when(live)
    def _update():
        col = lp * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (gp, page_size), 1
        )
        mask = col < kvlen
        if window > 0:
            mask = jnp.logical_and(mask, col >= kvlen - window)
        # Static loop over kv heads: each head's groups query rows flash-update
        # against that head's [ps, hd] slice of the page block. 2D ops only —
        # the same shapes the head-major kernel lowered — sliced out of the
        # shared scratch at static offsets. For int8 pages the per-row scales
        # fold in after each matmul (HBM only ever holds the int8 copy; the
        # int8→f32 converts fuse into the MXU operand read).
        for h in range(kv_heads):
            _flash_page_update(
                q_ref[0, h], k_ref[0, h], v_ref[0, h], mask, scale, soft_cap,
                m_scr, l_scr, acc_scr, slice(h * gp, (h + 1) * gp), gp,
                ks_row=ks_ref[0, h] if quantized else None,
                vs_row=vs_ref[0, h] if quantized else None,
            )

    @pl.when(p == npg - 1)
    def _finish():
        for h in range(kv_heads):
            rows = slice(h * gp, (h + 1) * gp)
            out = acc_scr[rows, :] / jnp.maximum(l_scr[rows, :1], 1e-30)
            o_ref[0, h] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "interpret", "check", "sliding_window", "soft_cap"),
)
def paged_decode_attention(
    q: jnp.ndarray,  # [b, num_heads, head_dim] — one query token per row
    k_pages: jnp.ndarray,  # [total_pages, kv_heads, page_size, head_dim]
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [b, max_pages] int32
    kv_lens: jnp.ndarray,  # [b] int32 — valid tokens per row (incl. current)
    scale: float | None = None,
    interpret: bool = False,
    check: bool = False,
    sliding_window: int = 0,
    soft_cap: float = 0.0,
    k_scales: jnp.ndarray | None = None,  # [P, kh, 1, ps] f32 (int8 pool)
    v_scales: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Attention of one decode token per row over its paged KV prefix.

    Returns [b, num_heads, head_dim] in q's dtype. Unallocated table slots
    point at the trash page (physical 0); they are DMA'd but fully masked.
    ``sliding_window`` w > 0 (Mistral/Gemma-2) restricts the query to its
    last w positions — AND shrinks the page grid to the ceil(w/ps)+1 slots
    that can intersect the window, so out-of-window pages are never DMA'd
    (the index_map dereferences logical page first_live + p per row).
    ``soft_cap`` > 0 squashes scaled scores to cap·tanh(s/cap) pre-mask,
    and a non-None ``scale`` carries Gemma-2's fixed query scale — both
    matching ops/attention.attend exactly.

    ``k_scales``/``v_scales`` (both or neither) mark the pool as int8
    (runtime/paged_kv.QuantPagedKVCache): pages dequantize inside the
    kernel via per-token-row scales folded in after each matmul, so the
    page walk streams half the bytes.

    ``check=True`` emits checkify contract asserts (page-table entries inside
    the physical pool, kv_lens within table capacity, finite queries) — run
    through ops.checks.checked (§5.2).
    """
    if not HAVE_PALLAS:  # pragma: no cover
        raise RuntimeError("pallas unavailable")
    if check:
        from edgemesh.ops.checks import check_paged_inputs

        check_paged_inputs(q, k_pages, page_table, kv_lens)
    quantized = k_scales is not None
    b, nh, hd = q.shape
    _, kh, ps, _ = k_pages.shape
    groups = nh // kh
    max_pages = page_table.shape[1]
    scale = scale if scale is not None else hd**-0.5

    gp = _round_up(groups, 8)  # sublane-align the q rows
    hp = hd if hd % 64 == 0 else _round_up(hd, 128)
    qg = q.reshape(b, kh, groups, hd)
    qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - groups), (0, hp - hd)))
    if hp != hd:
        k_pages = jnp.pad(k_pages, ((0, 0), (0, 0), (0, 0), (0, hp - hd)))
        v_pages = jnp.pad(v_pages, ((0, 0), (0, 0), (0, 0), (0, hp - hd)))

    if sliding_window > 0:
        # Only pages intersecting [kvlen-w, kvlen) can contribute: the first
        # may be partial (+1) and the last may be partial (+1) → w//ps + 2
        # slots bound the live span for every row.
        npages = min(max_pages, sliding_window // ps + 2)

        def kv_map(bb, p, table, lens):
            first_live = jnp.maximum(lens[bb] - sliding_window, 0) // ps
            # Clamp: near capacity first_live+p can step past the table; the
            # clamped duplicate fetch is masked dead in the kernel (live=False
            # once lp*ps >= kvlen).
            return (table[bb, jnp.minimum(first_live + p, max_pages - 1)], 0, 0, 0)
    else:
        npages = max_pages

        def kv_map(bb, p, table, lens):
            return (table[bb, p], 0, 0, 0)

    grid = (b, npages)
    kernel = functools.partial(
        _paged_kernel, page_size=ps, scale=scale, window=sliding_window,
        soft_cap=soft_cap, kv_heads=kh, gp=gp, quantized=quantized,
    )
    in_specs = [
        pl.BlockSpec((1, kh, gp, hp), lambda bb, p, table, lens: (bb, 0, 0, 0)),
        pl.BlockSpec((1, kh, ps, hp), kv_map),
        pl.BlockSpec((1, kh, ps, hp), kv_map),
    ]
    operands = [qg, k_pages, v_pages]
    if quantized:
        # Scale blocks ride the same page index_map; [1, ps] per head.
        in_specs += [
            pl.BlockSpec((1, kh, 1, ps), kv_map),
            pl.BlockSpec((1, kh, 1, ps), kv_map),
        ]
        operands += [k_scales, v_scales]
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, kh, gp, hp), lambda bb, p, table, lens: (bb, 0, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((kh * gp, 128), jnp.float32),
                pltpu.VMEM((kh * gp, 128), jnp.float32),
                pltpu.VMEM((kh * gp, hp), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kh, gp, hp), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), kv_lens.astype(jnp.int32), *operands)
    return out[:, :, :groups, :hd].reshape(b, nh, hd)


def _paged_chunk_kernel(
    table_ref,  # SMEM [b, max_pages] int32 (scalar prefetch)
    start_ref,  # SMEM [b] int32 — tokens in pages BEFORE this chunk
    len_ref,  # SMEM [b] int32 — final tokens incl. the chunk
    *refs,  # q, k, v, [k_scale, v_scale,] o, m_scr, l_scr, acc_scr
    page_size: int,
    scale: float,
    soft_cap: float,
    kv_heads: int,
    rq: int,
    groups: int,
    quantized: bool,
):
    # q_ref   VMEM [1, kh, rq, hd] — rq = cq*groups query rows (padded)
    # k_ref   VMEM [1, kh, ps, hd] — physical page table[b, p]
    #         (int8 when quantized, with ks/vs VMEM [1, kh, 1, ps] f32)
    # o_ref   VMEM [1, kh, rq, hd]
    # scratch VMEM [kh*rq, 128] f32 ×2 (m, l) + [kh*rq, hd] f32 (acc)
    if quantized:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr = refs
    bb = pl.program_id(0)
    p = pl.program_id(1)
    npg = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    start = start_ref[bb]
    kvlen = len_ref[bb]
    live = p * page_size < kvlen

    @pl.when(live)
    def _update():
        col = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (rq, page_size), 1
        )
        # Query row r is chunk token r // groups: causal over the prefix +
        # its own position (the chunk's K/V are already in the pages).
        c = jax.lax.broadcasted_iota(jnp.int32, (rq, page_size), 0) // groups
        mask = col < jnp.minimum(start + c + 1, kvlen)
        for h in range(kv_heads):
            _flash_page_update(
                q_ref[0, h], k_ref[0, h], v_ref[0, h], mask, scale, soft_cap,
                m_scr, l_scr, acc_scr, slice(h * rq, (h + 1) * rq), rq,
                ks_row=ks_ref[0, h] if quantized else None,
                vs_row=vs_ref[0, h] if quantized else None,
            )

    @pl.when(p == npg - 1)
    def _finish():
        for h in range(kv_heads):
            rows = slice(h * rq, (h + 1) * rq)
            out = acc_scr[rows, :] / jnp.maximum(l_scr[rows, :1], 1e-30)
            o_ref[0, h] = out.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret", "soft_cap"))
def paged_chunk_attention(
    q: jnp.ndarray,  # [b, cq, num_heads, head_dim] — chunk queries per row
    k_pages: jnp.ndarray,  # [total_pages, kv_heads, page_size, head_dim]
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [b, max_pages] int32
    start: jnp.ndarray,  # [b] tokens in pages before the chunk
    kv_lens: jnp.ndarray,  # [b] final tokens incl. the chunk
    scale: float | None = None,
    interpret: bool = False,
    soft_cap: float = 0.0,
    k_scales: jnp.ndarray | None = None,  # [P, kh, 1, ps] f32 (int8 pool)
    v_scales: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Chunk-query page walk: ``cq`` query tokens per row attend over the
    row's paged prefix + the chunk's own (already-written) K/V, causally.
    The kernel-grade path for chunk appends (speculative verify, suffix
    prefill) that the gather-based oracle otherwise serves — same
    ``(b, pages)`` grid as decode, query rows = chunk×groups per kv head.
    Full-causal only (no sliding window; callers fall back to the gather
    path for windowed configs); ``k_scales``/``v_scales`` mark an int8
    pool, dequantized in-kernel exactly like decode. Padded chunk rows
    compute garbage that callers discard — their columns stay masked
    within kv_lens, so no NaNs propagate. OPT-IN
    (EDGEMESH_PAGED_CHUNK_KERNEL=1): on-chip measurement found it slower
    than the gather oracle at verify-chunk shapes — numbers in
    runtime/paged_generate._use_chunk_kernel."""
    if not HAVE_PALLAS:  # pragma: no cover
        raise RuntimeError("pallas unavailable")
    quantized = k_scales is not None
    b, cq, nh, hd = q.shape
    _, kh, ps, _ = k_pages.shape
    groups = nh // kh
    max_pages = page_table.shape[1]
    scale = scale if scale is not None else hd**-0.5

    rq = _round_up(cq * groups, 8)
    hp = hd if hd % 64 == 0 else _round_up(hd, 128)
    # [b, cq, kh, groups, hd] → [b, kh, cq*groups, hd]: row r = token r//groups.
    qg = q.reshape(b, cq, kh, groups, hd).transpose(0, 2, 1, 3, 4)
    qg = qg.reshape(b, kh, cq * groups, hd)
    qg = jnp.pad(qg, ((0, 0), (0, 0), (0, rq - cq * groups), (0, hp - hd)))
    if hp != hd:
        k_pages = jnp.pad(k_pages, ((0, 0), (0, 0), (0, 0), (0, hp - hd)))
        v_pages = jnp.pad(v_pages, ((0, 0), (0, 0), (0, 0), (0, hp - hd)))

    def kv_map(bb, p, table, start, lens):
        return (table[bb, p], 0, 0, 0)

    kernel = functools.partial(
        _paged_chunk_kernel, page_size=ps, scale=scale, soft_cap=soft_cap,
        kv_heads=kh, rq=rq, groups=groups, quantized=quantized,
    )
    in_specs = [
        pl.BlockSpec((1, kh, rq, hp), lambda bb, p, table, start, lens: (bb, 0, 0, 0)),
        pl.BlockSpec((1, kh, ps, hp), kv_map),
        pl.BlockSpec((1, kh, ps, hp), kv_map),
    ]
    operands = [qg, k_pages, v_pages]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, kh, 1, ps), kv_map),
            pl.BlockSpec((1, kh, 1, ps), kv_map),
        ]
        operands += [k_scales, v_scales]
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(b, max_pages),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, kh, rq, hp), lambda bb, p, table, start, lens: (bb, 0, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((kh * rq, 128), jnp.float32),
                pltpu.VMEM((kh * rq, 128), jnp.float32),
                pltpu.VMEM((kh * rq, hp), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, kh, rq, hp), q.dtype),
        interpret=interpret,
    )(
        page_table.astype(jnp.int32), start.astype(jnp.int32),
        kv_lens.astype(jnp.int32), *operands,
    )
    out = out[:, :, : cq * groups, :hd].reshape(b, kh, cq, groups, hd)
    return out.transpose(0, 2, 1, 3, 4).reshape(b, cq, nh, hd)


def paged_decode_attention_xla(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    kv_lens: jnp.ndarray,
    scale: float | None = None,
    sliding_window: int = 0,
    soft_cap: float = 0.0,
    k_scales: jnp.ndarray | None = None,
    v_scales: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """XLA fallback / oracle: gather the dense view, then masked attention."""
    from edgemesh.ops.attention import LayerKV, attend
    from edgemesh.runtime.paged_kv import gather_dense, gather_dense_scales

    b, nh, hd = q.shape
    dense_k = gather_dense(k_pages, page_table)
    dense_v = gather_dense(v_pages, page_table)
    if k_scales is not None:
        ks = gather_dense_scales(k_scales, page_table)  # [b, max_seq, kh]
        vs = gather_dense_scales(v_scales, page_table)
        dense_k = (dense_k.astype(jnp.float32) * ks[..., None]).astype(q.dtype)
        dense_v = (dense_v.astype(jnp.float32) * vs[..., None]).astype(q.dtype)
    max_seq = dense_k.shape[1]
    kv_valid = jnp.arange(max_seq)[None, :] < kv_lens[:, None]
    positions = (kv_lens - 1)[:, None]
    out = attend(
        q[:, None], LayerKV(dense_k, dense_v), positions, kv_valid, scale,
        sliding_window=sliding_window, soft_cap=soft_cap,
    )
    return out[:, 0]
