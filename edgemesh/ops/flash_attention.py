"""Blockwise causal flash attention as a Pallas TPU kernel.

The reference computes attention inside HF ``model.generate``
(``Code/C-DAC Server/combiner_fp.py:338-347``) and has no long-context
support (SURVEY.md §5.7). Here prefill attention is a single Pallas kernel
with online softmax so the [s, s] score matrix never materializes in HBM —
the hook that makes long-context (ring attention over the sp axis) cheap.

Kernel design (pallas_guide.md):
- Grid ``(batch, kv_heads, q_blocks, kv_blocks)``; the kv axis is innermost
  and sequential, accumulating the online-softmax state (running max ``m``,
  normalizer ``l``, unnormalized output ``acc``) in VMEM scratch across grid
  steps — same accumulate-across-grid idiom as ops/int8.py's matmul.
- GQA is grouped INSIDE the kernel: one invocation handles all ``groups``
  query heads of its kv head, so each K/V block is DMA'd once per kv head
  (not once per query head) and the Q·Kᵀ matmul has an MXU-friendly
  ``groups*block_q`` row dimension.
- Query positions are never shipped as a tensor: under ``causal=True`` the
  position of row ``r`` is ``q_offset + r`` (offset is one SMEM scalar per
  batch row — ring-attention shards pass their global offset); under
  ``causal=False`` every query sees the whole valid prefix (the decode /
  cross-shard case).
- Scores/softmax in fp32 (VPU), QK^T and PV on the MXU via
  ``preferred_element_type``; inputs stay bf16.
- head_dim stays unpadded when it is a clean lane count (64/128/256...);
  odd sizes (Phi-2's 80) pad to 128. Seq dims pad to block multiples; padded
  kv columns are masked via ``kv_lens``, padded q rows are sliced off host-side.
- Fully-masked kv blocks (beyond the causal frontier or past ``kv_lens``)
  skip their compute via ``@pl.when`` — ~2x fewer MXU ops for causal.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG_INF = -1e30

try:  # pallas import is deferred-safe: CPU wheels ship it, interpret mode runs it
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _flash_kernel(
    qoff_ref,  # SMEM [b, 1] int32 — global position of each row's query 0
    kvlen_ref,  # SMEM [b, 1] int32 — valid kv prefix length per batch row
    q_ref,  # VMEM [1, 1, groups, block_q, hd]
    k_ref,  # VMEM [1, 1, block_k, hd]
    v_ref,  # VMEM [1, 1, block_k, hd]
    o_ref,  # VMEM [1, 1, groups, block_q, hd]
    m_scr,  # VMEM [groups*block_q, 128] f32 — running row max (lane-broadcast)
    l_scr,  # VMEM [groups*block_q, 128] f32 — running normalizer
    acc_scr,  # VMEM [groups*block_q, hd] f32 — unnormalized output
    *,
    scale: float,
    groups: int,
    block_q: int,
    block_k: int,
    causal: bool,
    window: int,
    soft_cap: float,
):
    bb = pl.program_id(0)
    i = pl.program_id(2)
    j = pl.program_id(3)
    nj = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    kvlen = kvlen_ref[bb, 0]
    block_start = j * block_k
    if causal:
        # Query row r (within the block) sits at position qoff + i*block_q + r.
        row_pos0 = qoff_ref[bb, 0] + i * block_q
        live = jnp.logical_and(
            block_start <= row_pos0 + block_q - 1, block_start < kvlen
        )
        if window > 0:
            # Sliding window: the earliest column any row of this q block can
            # see is row_pos0 - window + 1; kv blocks entirely before it are
            # dead — the skip is what makes long windowed prefill O(s*w).
            live = jnp.logical_and(live, block_start + block_k > row_pos0 - window + 1)
    else:
        live = block_start < kvlen

    @pl.when(live)
    def _update():
        hd = q_ref.shape[-1]
        q = q_ref[0, 0].reshape(groups * block_q, hd)
        k = k_ref[0, 0]  # [block_k, hd]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [groups*block_q, block_k]
        if soft_cap > 0:  # Gemma-2: squash scores before masking/softmax
            s = soft_cap * jnp.tanh(s / soft_cap)
        col = block_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = col < kvlen
        if causal:
            # Row r of the flattened (group, q) dim is query row r % block_q.
            qpos = row_pos0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) % block_q
            mask = jnp.logical_and(mask, col <= qpos)
            if window > 0:
                mask = jnp.logical_and(mask, col > qpos - window)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]  # [groups*block_q, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # Masked entries give exp(NEG_INF - m); when m itself is NEG_INF the
        # difference is 0 → exp=1, so mask p explicitly.
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[:] = alpha * acc_scr[:] + pv

    @pl.when(j == nj - 1)
    def _finish():
        # Every real query row sees at least slot 0 (kv_lens >= 1), so l > 0;
        # rows that are entirely padding are sliced off host-side.
        hd = o_ref.shape[-1]
        out = acc_scr[:] / jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = out.reshape(groups, block_q, hd).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=(
        "scale", "causal", "block_q", "block_k", "interpret", "check",
        "sliding_window", "soft_cap",
    ),
)
def flash_attention(
    q: jnp.ndarray,  # [b, s, num_heads, head_dim]
    k: jnp.ndarray,  # [b, skv, kv_heads, head_dim]
    v: jnp.ndarray,  # [b, skv, kv_heads, head_dim]
    kv_lens: jnp.ndarray,  # [b] int32 — valid kv prefix per row
    q_offsets: jnp.ndarray | None = None,  # [b] int32 — position of query row 0
    scale: float | None = None,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 512,
    interpret: bool = False,
    soft_cap: float = 0.0,
    check: bool = False,
    sliding_window: int = 0,
) -> jnp.ndarray:
    """Causal flash attention; numerics match ops.attention.attend.

    Under ``causal=True`` query row ``r`` of batch row ``b`` sits at absolute
    position ``q_offsets[b] + r`` and sees kv slot ``j`` iff
    ``j <= position and j < kv_lens[b]``. Under ``causal=False`` every query
    sees the full valid prefix ``j < kv_lens[b]`` (decode: the new token's
    position is ``kv_lens-1``, so its causal window IS the valid prefix).
    Returns [b, s, num_heads, head_dim] in q's dtype.

    ``sliding_window`` w > 0 (Mistral; causal only) restricts each query to
    its last w positions; kv blocks wholly outside the window are skipped,
    so windowed prefill compute is O(s·w) instead of O(s²).

    ``check=True`` emits checkify contract asserts on kv_lens/q_offsets
    bounds and Q/K finiteness — run through ops.checks.checked (§5.2).
    """
    if not HAVE_PALLAS:  # pragma: no cover
        raise RuntimeError("pallas unavailable")
    if sliding_window > 0 and not causal:
        raise ValueError("sliding_window requires causal=True")
    b, s, nh, hd = q.shape
    skv, kh = k.shape[1], k.shape[2]
    groups = nh // kh
    scale = scale if scale is not None else hd**-0.5
    if q_offsets is None:
        q_offsets = jnp.zeros((b,), jnp.int32)
    if check:
        from edgemesh.ops.checks import check_flash_inputs

        check_flash_inputs(q, k, kv_lens, q_offsets)

    block_q = min(block_q, _round_up(s, 16))
    block_k = min(block_k, _round_up(skv, 16))
    sp = _round_up(s, block_q)
    mp = _round_up(skv, block_k)
    # Lane dim: keep as-is when already a clean lane count, else pad to 128.
    hp = hd if hd % 64 == 0 else _round_up(hd, 128)

    # Head-major 5D layout [b, kh, groups, s, hd]: each (kv-head, q-block)
    # tile is a clean stack of `groups` 2D matrices.
    qt = jnp.pad(
        q.transpose(0, 2, 1, 3).reshape(b, kh, groups, s, hd),
        ((0, 0), (0, 0), (0, 0), (0, sp - s), (0, hp - hd)),
    )
    kt = jnp.pad(
        k.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, mp - skv), (0, hp - hd))
    )
    vt = jnp.pad(
        v.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, mp - skv), (0, hp - hd))
    )
    qoff2d = q_offsets.astype(jnp.int32)[:, None]  # [b, 1] full-array SMEM blocks
    kvlen2d = kv_lens.astype(jnp.int32)[:, None]

    grid = (b, kh, sp // block_q, mp // block_k)
    kernel = functools.partial(
        _flash_kernel, scale=scale, groups=groups, block_q=block_q,
        block_k=block_k, causal=causal, window=sliding_window,
        soft_cap=soft_cap,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((b, 1), lambda bb, h, i, j: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((b, 1), lambda bb, h, i, j: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (1, 1, groups, block_q, hp), lambda bb, h, i, j: (bb, h, 0, i, 0)
            ),
            pl.BlockSpec((1, 1, block_k, hp), lambda bb, h, i, j: (bb, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, hp), lambda bb, h, i, j: (bb, h, j, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, groups, block_q, hp), lambda bb, h, i, j: (bb, h, 0, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, kh, groups, sp, hp), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((groups * block_q, 128), jnp.float32),
            pltpu.VMEM((groups * block_q, 128), jnp.float32),
            pltpu.VMEM((groups * block_q, hp), jnp.float32),
        ],
        interpret=interpret,
    )(qoff2d, kvlen2d, qt, kt, vt)
    out = out.reshape(b, nh, sp, hp)[:, :, :s, :hd]
    return out.transpose(0, 2, 1, 3)
