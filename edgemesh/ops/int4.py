"""Int4 weight-only quantization (w4a16) with group-wise scales.

Beyond the reference's int8 (bitsandbytes ``load_in_8bit``,
``Code/Quantised Models/models_quant_updated.py:30-38``): int4 halves the
weight bytes AGAIN (vs int8) — decode is HBM-bandwidth-bound, so weight bytes
are the throughput ceiling, and int4's ~4x memory cut vs fp16 more than
doubles the reference's published ~38% (Table 3, 14.8→9.19 GB).

Two scale granularities, selected by ``group_size``:
- 0 (per-channel): one scale per output column — the dequant folds into the
  matmul epilogue exactly like ops/int8.py's w8a16 path. Fastest; coarsest.
- g>0 (grouped): one scale per (g-sized input slice, output column) — the
  standard int4 quality remedy (GPTQ/AWQ-style grouping). The contraction is
  segmented per group (einsum over a G axis) because a scale that varies
  along the contraction dim cannot fold into the epilogue.

Storage is two nibbles packed per int8 byte along the contraction axis
(``kernel_q4`` [in/2, out]), NOT XLA's native s4 dtype: s4 arrays cannot be
passed as jit arguments on some PJRT backends (observed on the tunneled TPU:
the argument-relayout path recurses until RecursionError), while packed int8
is bulletproof everywhere and occupies identical HBM. The unpack (two
arithmetic shifts + an interleave) runs on the VPU inside the jitted matmul;
decode is bandwidth-bound, so the extra vector work is free next to the
halved weight stream. Weights quantize at load time via
``quantize_params_int4``; ``models/transformer.dense`` dispatches on the
``kernel_q4`` key, so int4 composes with every decode path (dense KV, paged,
speculative, TP engine).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from edgemesh.ops.int8 import Params

INT4_MAX = 7.0


def pack_int4(q: jnp.ndarray) -> jnp.ndarray:
    """Pack int8 values in [-8, 7] two-per-byte along axis 0 (even in_dim):
    row ``i`` of the result holds row ``2i`` (low nibble) and row ``2i+1``
    (high nibble).

    ADJACENT pairing on purpose, and the matmul strides the ACTIVATIONS
    (``x[..., 0::2] @ lo + x[..., 1::2] @ hi``) rather than re-interleaving
    the weights: the nibble shifts + int8→bf16 converts stay elementwise on
    the packed array, so they FUSE into the MXU operand read (a layout that
    needs an interleave/concat materializes the full unpacked weight in HBM
    every decode step — measured 40 GB/s effective vs hundreds fused), and a
    contiguous block of packed rows maps to a contiguous block of global
    weight rows, so row-sharding the packed axis over ``tp`` stays correct
    in the per-shard (shard_map) engines."""
    lo = q[0::2] & 0x0F
    hi = q[1::2] & 0x0F
    return (lo | (hi << 4)).astype(jnp.int8)


def unpack_int4_halves(packed: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[in/2, out] packed → (even global rows, odd global rows), each int8,
    via sign-extending arithmetic shifts (elementwise — fusable)."""
    lo = jnp.left_shift(packed, 4) >> 4  # sign-extend the low nibble
    hi = packed >> 4  # arithmetic shift sign-extends the high nibble
    return lo, hi


def unpack_int4(packed: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`pack_int4`: [in/2, out] int8 → [in, out] int8."""
    lo, hi = unpack_int4_halves(packed)
    in2, out = packed.shape
    return jnp.stack([lo, hi], axis=1).reshape(in2 * 2, out)


def quantize_weight_int4(
    kernel: jnp.ndarray, group_size: int = 64
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int4 quantization of a [in, out] kernel.

    Returns (packed nibbles [in/2, out] int8, fp32 scales [G, out]) with
    G = in/group_size (G=1 when group_size=0 → per-channel)."""
    kf = kernel.astype(jnp.float32)
    in_dim, out = kf.shape[-2], kf.shape[-1]
    if kf.ndim != 2:
        raise ValueError(f"int4 quantization expects a 2D kernel, got {kf.shape}")
    if in_dim % 2:
        raise ValueError(f"int4 packing needs an even in_dim, got {in_dim}")
    if group_size <= 0:
        groups = 1
    else:
        if group_size % 2:
            raise ValueError(f"group_size must be even (nibble pairing), got {group_size}")
        if in_dim % group_size:
            raise ValueError(f"in_dim {in_dim} not divisible by group_size {group_size}")
        groups = in_dim // group_size
    kg = kf.reshape(groups, in_dim // groups, out)
    absmax = jnp.max(jnp.abs(kg), axis=1, keepdims=True)  # [G, 1, out]
    scales = jnp.maximum(absmax / INT4_MAX, 1e-8)
    q = jnp.clip(jnp.round(kg / scales), -7, 7).astype(jnp.int8)
    return pack_int4(q.reshape(in_dim, out)), jnp.squeeze(scales, axis=1)


def dequantize_weight_int4(
    packed: jnp.ndarray, scales: jnp.ndarray, dtype=jnp.bfloat16
) -> jnp.ndarray:
    q = unpack_int4(packed)
    in_dim, out = q.shape
    groups = scales.shape[0]
    qg = q.astype(jnp.float32).reshape(groups, in_dim // groups, out)
    return (qg * scales[:, None, :]).reshape(in_dim, out).astype(dtype)


def int4_matmul(x: jnp.ndarray, packed: jnp.ndarray, scales: jnp.ndarray) -> jnp.ndarray:
    """w4a16: y = x @ dequant(unpack(packed)) without a dequantized weight in
    HBM. The even/odd global-row halves each contract against the matching
    activation stride — two half-K matmuls whose shifts/converts fuse into
    the operand stream. Per-channel (G=1) folds the scale into the epilogue;
    grouped segments the contraction over G (einsum), trading throughput for
    the finer scales."""
    half, out = packed.shape
    in_dim = half * 2
    groups = scales.shape[0]
    if groups == 1:
        *lead, _ = x.shape
        m = 1
        for d in lead:
            m *= d
        tiles = _int4_kernel_tiles(max(m, 1), half, out)
        if tiles is not None:
            # Fused kernel: ONE HBM pass over the packed array (the XLA
            # formulation below streams it twice — once per nibble half).
            tm, tn, tk2 = tiles
            y2 = pallas_int4_matmul(
                x.reshape(m, in_dim), packed, scales[0],
                tile_m=tm, tile_n=tn, tile_k2=tk2,
            )
            return y2.reshape(*lead, out)
    lo, hi = unpack_int4_halves(packed)
    x_even, x_odd = x[..., 0::2], x[..., 1::2]
    if groups == 1:
        y = jnp.matmul(x_even, lo.astype(x.dtype), preferred_element_type=jnp.float32)
        y = y + jnp.matmul(x_odd, hi.astype(x.dtype), preferred_element_type=jnp.float32)
        return (y * scales[0].astype(jnp.float32)).astype(x.dtype)
    gs = in_dim // groups  # even: quantize_weight_int4 enforces gs % 2 == 0
    *lead, _ = x.shape
    xe = x_even.reshape(*lead, groups, gs // 2)
    xo = x_odd.reshape(*lead, groups, gs // 2)
    lo_g = lo.reshape(groups, gs // 2, out).astype(x.dtype)
    hi_g = hi.reshape(groups, gs // 2, out).astype(x.dtype)
    part = jnp.einsum(
        "...gi,gio->...go", xe, lo_g, preferred_element_type=jnp.float32
    ) + jnp.einsum(
        "...gi,gio->...go", xo, hi_g, preferred_element_type=jnp.float32
    )  # [..., G, out]
    y = jnp.sum(part * scales.astype(jnp.float32), axis=-2)
    return y.astype(x.dtype)


try:  # Pallas import is TPU/CPU-interpret only; keep module importable anywhere
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False


def _int4_matmul_kernel(xe_ref, xo_ref, w_ref, wscale_ref, out_ref, acc_ref):
    """One (TM, TN) output tile; grid walks (M/TM, N/TN, K2/TK2) with the
    PACKED contraction dim minor. The packed tile is read from HBM ONCE and
    both nibble halves dot against their activation stride from VMEM — the
    whole point: the XLA two-matmul formulation fuses the unpack into each
    matmul's operand read, so it streams the packed array TWICE per step
    (int4 decode measured ~1.3× the weight traffic of int8 despite half the
    bytes). Sign-extension happens on the VPU via int32 shifts."""
    k_step = pl.program_id(2)
    nk = pl.num_programs(2)

    p32 = w_ref[:].astype(jnp.int32)
    lo = ((p32 << 28) >> 28).astype(xe_ref.dtype)  # even global rows
    hi = ((p32 << 24) >> 28).astype(xe_ref.dtype)  # odd global rows
    prod = jax.lax.dot_general(
        xe_ref[:], lo, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    prod = prod + jax.lax.dot_general(
        xo_ref[:], hi, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    if nk == 1:  # single K stripe: no scratch round-trip (the decode case)
        out_ref[:] = (
            prod * wscale_ref[0, :].astype(jnp.float32)
        ).astype(out_ref.dtype)
        return

    @pl.when(k_step == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += prod

    @pl.when(k_step == nk - 1)
    def _finish():
        out_ref[:] = (
            acc_ref[:] * wscale_ref[0, :].astype(jnp.float32)
        ).astype(out_ref.dtype)


def pallas_int4_matmul(
    x: jnp.ndarray,  # [M, K] activation (any float dtype)
    packed: jnp.ndarray,  # [K/2, N] int8 nibble pairs
    scales: jnp.ndarray,  # [N] fp32 per-column (per-channel only)
    *,
    tile_m: int = 128,
    tile_n: int = 512,
    tile_k2: int = 2048,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused w4a16 matmul: one HBM pass over the packed nibbles, in-kernel
    sign-extension, two MXU dots per tile from VMEM. Shapes must tile
    evenly (``int4_matmul`` falls back to the XLA path otherwise)."""
    if not _HAVE_PALLAS:  # pragma: no cover
        raise RuntimeError("pallas unavailable")
    m, k = x.shape
    k2, n = packed.shape
    assert k == 2 * k2, (k, k2)
    tile_m = min(tile_m, m)
    tile_n = min(tile_n, n)
    tile_k2 = min(tile_k2, k2)
    assert m % tile_m == 0 and n % tile_n == 0 and k2 % tile_k2 == 0, (m, n, k2)

    xe, xo = x[:, 0::2], x[:, 1::2]  # [M, K/2] each, matching packed rows
    grid = (m // tile_m, n // tile_n, k2 // tile_k2)
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    return pl.pallas_call(
        _int4_matmul_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_m, tile_k2), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((tile_m, tile_k2), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((tile_k2, tile_n), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, tile_n), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((tile_m, tile_n), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((tile_m, tile_n), jnp.float32)],
        interpret=interpret,
        **kwargs,
    )(xe, xo, packed, scales.reshape(1, -1))


# Trace-time routing constant (same discipline as the paged chunk kernel):
# "1" (default) routes per-channel int4 matmuls through the fused Pallas
# kernel on TPU; "0" keeps the XLA two-matmul path everywhere.
import os as _os

_INT4_KERNEL = _os.environ.get("EDGEMESH_INT4_KERNEL", "1") == "1"


def _pick_tile(dim: int, prefs: tuple[int, ...]) -> int | None:
    """Largest preferred tile that divides ``dim`` (dim itself if smaller
    than every preference and aligned)."""
    if dim <= prefs[-1]:
        return dim
    for t in prefs:
        if dim % t == 0:
            return t
    return None


def _int4_kernel_tiles(m: int, k2: int, n: int):
    """(tile_m, tile_n, tile_k2) for the fused kernel, or None when the
    shape cannot tile — the caller then keeps the XLA path. Mirrors (and
    therefore can never trip) pallas_int4_matmul's divisibility asserts."""
    from edgemesh.utils.platform import on_tpu

    if not (_INT4_KERNEL and _HAVE_PALLAS and on_tpu()):
        return None
    if m % 8 or k2 % 128 or n % 128:
        return None
    tm = _pick_tile(m, (128, 64, 32, 16, 8))
    tn = _pick_tile(n, (512, 256, 128))
    tk2 = _pick_tile(k2, (2048, 1024, 512, 256, 128))
    if tm is None or tn is None or tk2 is None:
        return None
    return tm, tn, tk2


def quantize_params_int4(params: Params, group_size: int = 64) -> Params:
    """Walk the param pytree; replace every dense {kernel[, bias]} with
    {kernel_q4 (packed int8 [.., in/2, out]), scales [.., G, out][, bias]}.
    Same nn.Linear boundary as the int8 walk (embeddings/norms stay
    high-precision); dense() dispatches on the ``kernel_q4`` key.
    Layer-stacked [L, in, out] kernels quantize per layer via vmap."""

    def quant(kernel):
        if kernel.ndim == 3:  # [L, in, out] scan-stacked
            gs = group_size if kernel.shape[1] % max(group_size, 1) == 0 else 0
            return jax.vmap(lambda k: quantize_weight_int4(k, gs))(kernel)
        gs = group_size if kernel.shape[0] % max(group_size, 1) == 0 else 0
        return quantize_weight_int4(kernel, gs)

    def walk(node, path=()):
        if isinstance(node, dict):
            if path[-1:] == ("router",):
                # MoE router stays fp32 (same rationale as the int8 walk).
                return node
            if "kernel" in node:
                q, scales = quant(node["kernel"])
                out: Params = {"kernel_q4": q, "scales": scales}
                if "bias" in node:
                    out["bias"] = node["bias"]
                return out
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        return node

    # One jitted program for the whole pytree keeps every intermediate inside
    # XLA (cheap fusion; no per-leaf eager dispatches on a slow tunnel).
    return jax.jit(walk)(params)
