"""Int4 weight-only quantization (w4a16) with group-wise scales.

Beyond the reference's int8 (bitsandbytes ``load_in_8bit``,
``Code/Quantised Models/models_quant_updated.py:30-38``): int4 halves the
weight bytes AGAIN (vs int8) — decode is HBM-bandwidth-bound, so weight bytes
are the throughput ceiling, and int4's ~4x memory cut vs fp16 more than
doubles the reference's published ~38% (Table 3, 14.8→9.19 GB).

Two scale granularities, selected by ``group_size``:
- 0 (per-channel): one scale per output column — the dequant folds into the
  matmul epilogue exactly like ops/int8.py's w8a16 path. Fastest; coarsest.
- g>0 (grouped): one scale per (g-sized input slice, output column) — the
  standard int4 quality remedy (GPTQ/AWQ-style grouping). The contraction is
  segmented per group (einsum over a G axis) because a scale that varies
  along the contraction dim cannot fold into the epilogue.

Storage is JAX's native ``int4`` dtype (XLA s4) — no hand-rolled nibble
packing; TPU HBM stores s4 packed. Weights quantize at load time via
``quantize_params_int4``; ``models/transformer.dense`` dispatches on the
kernel dtype, so int4 composes with every decode path (dense KV, paged,
speculative, TP engine).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from edgemesh.ops.int8 import Params

INT4_MAX = 7.0


def quantize_weight_int4(
    kernel: jnp.ndarray, group_size: int = 64
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int4 quantization of a [in, out] kernel.

    Returns (int4 kernel [in, out], fp32 scales [G, out]) with
    G = in/group_size (G=1 when group_size=0 → per-channel)."""
    kf = kernel.astype(jnp.float32)
    in_dim, out = kf.shape[-2], kf.shape[-1]
    if kf.ndim != 2:
        raise ValueError(f"int4 quantization expects a 2D kernel, got {kf.shape}")
    if group_size <= 0:
        groups = 1
    else:
        if in_dim % group_size:
            raise ValueError(f"in_dim {in_dim} not divisible by group_size {group_size}")
        groups = in_dim // group_size
    kg = kf.reshape(groups, in_dim // groups, out)
    absmax = jnp.max(jnp.abs(kg), axis=1, keepdims=True)  # [G, 1, out]
    scales = jnp.maximum(absmax / INT4_MAX, 1e-8)
    q = jnp.clip(jnp.round(kg / scales), -7, 7).astype(jnp.int4)
    return q.reshape(in_dim, out), jnp.squeeze(scales, axis=1)


def dequantize_weight_int4(
    q: jnp.ndarray, scales: jnp.ndarray, dtype=jnp.bfloat16
) -> jnp.ndarray:
    in_dim, out = q.shape
    groups = scales.shape[0]
    qg = q.astype(jnp.float32).reshape(groups, in_dim // groups, out)
    return (qg * scales[:, None, :]).reshape(in_dim, out).astype(dtype)


def int4_matmul(x: jnp.ndarray, w_q: jnp.ndarray, scales: jnp.ndarray) -> jnp.ndarray:
    """w4a16: y = x @ dequant(w_q) without materializing the dequantized
    weight in HBM. Per-channel (G=1) folds the scale into the epilogue;
    grouped segments the contraction over G."""
    in_dim, out = w_q.shape
    groups = scales.shape[0]
    if groups == 1:
        y = jnp.matmul(x, w_q.astype(x.dtype), preferred_element_type=jnp.float32)
        return (y * scales[0].astype(jnp.float32)).astype(x.dtype)
    gs = in_dim // groups
    *lead, _ = x.shape
    xg = x.reshape(*lead, groups, gs)
    wg = w_q.reshape(groups, gs, out).astype(x.dtype)
    part = jnp.einsum(
        "...gi,gio->...go", xg, wg, preferred_element_type=jnp.float32
    )  # [..., G, out]
    y = jnp.sum(part * scales.astype(jnp.float32), axis=-2)
    return y.astype(x.dtype)


def quantize_params_int4(params: Params, group_size: int = 64) -> Params:
    """Walk the param pytree; replace every dense {kernel[, bias]} with
    {kernel_q (int4), scales [G, out][, bias]}. Same nn.Linear boundary as
    the int8 walk (embeddings/norms stay high-precision); dense() dispatches
    on the kernel dtype. Layer-stacked [L, in, out] kernels quantize per
    layer via vmap."""

    def quant(kernel):
        if kernel.ndim == 3:  # [L, in, out] scan-stacked
            gs = group_size if kernel.shape[1] % max(group_size, 1) == 0 else 0
            return jax.vmap(lambda k: quantize_weight_int4(k, gs))(kernel)
        gs = group_size if kernel.shape[0] % max(group_size, 1) == 0 else 0
        return quantize_weight_int4(kernel, gs)

    def walk(node):
        if isinstance(node, dict):
            if "kernel" in node:
                q, scales = quant(node["kernel"])
                out: Params = {"kernel_q": q, "scales": scales}
                if "bias" in node:
                    out["bias"] = node["bias"]
                return out
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(params)
