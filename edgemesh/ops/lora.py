"""LoRA: low-rank adapter finetuning over frozen base weights.

The reference's roadmap left finetuning unstarted (the xlsx "After
Finetuning" rows are empty — SURVEY.md §7), and its target hardware is
memory-starved edge devices (Jetson Orin Nano 8GB, paper §4.4). LoRA is the
finetuning method that actually fits that envelope: train two rank-r
factors per projection (~0.5% of the weights), keep the base frozen, merge
for inference at zero serving cost.

TPU-first design decisions:
- **Split trees, not masked optimizers.** The adapter pytree is separate
  from the base params. ``jax.value_and_grad`` runs over the adapter tree
  only, so XLA dead-code-eliminates every frozen dW computation in the
  backward — the FLOP/memory win that is LoRA's point — and optimizer
  state (adamw mu/nu) exists only for adapter leaves. Checkpoints are the
  adapter tree alone: kilobytes, the portable finetuning artifact.
- **Adapters ride the stacked-layer layout.** Model layers are stacked
  ``[L, in, out]`` for ``lax.scan`` (models/transformer.py); adapters
  follow as ``lora_a [L, in, r]`` / ``lora_b [L, r, out]`` / per-layer
  ``lora_scale [L]``, so the same scan slices them with zero special
  cases. ``dense()`` applies ``y += (x @ A) @ B * scale`` whenever the
  leaves are present — the activation-side form is O(tokens·(in+out)·r),
  never materializing the [in, out] delta.
- **Merge before quantize.** For inference the adapters fold into the
  base kernel (``W + scale·A@B``) BEFORE any int8/int4 transform
  (agents/orchestrator.py does precision transforms after checkpoint
  restore), so quantization sees the finetuned weights and serving runs
  the unmodified fast paths.

The frozen ``lora_scale`` leaf (alpha/rank, stored so checkpoints are
self-describing) is excluded from updates via ``optax.multi_transform``
with ``set_to_zero`` — see :func:`make_lora_optimizer`.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax

Params = dict[str, Any]

DEFAULT_TARGETS = ("q", "k", "v", "o")


def parse_targets(targets: str | tuple[str, ...] | list[str]) -> tuple[str, ...]:
    if isinstance(targets, str):
        targets = tuple(t.strip() for t in targets.split(",") if t.strip())
    return tuple(targets)


def init_lora_params(
    params: Params,
    rank: int,
    alpha: float,
    targets: str | tuple[str, ...] = DEFAULT_TARGETS,
    key: jax.Array | None = None,
) -> Params:
    """Build the adapter pytree for the dense layer projections in
    ``targets`` (names under params["layers"]: q/k/v/o/gate/up/down).

    ``lora_a`` is gaussian (std 1/rank), ``lora_b`` zeros — the adapted
    model starts exactly at the base model. MoE expert weights are not
    adapted (routed [L, E, in, out] experts would need per-expert factors;
    the dense projections are where LoRA earns its keep).
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    if rank <= 0:
        raise ValueError(f"lora rank must be positive, got {rank}")
    targets = parse_targets(targets)
    layers = params.get("layers", {})
    out: Params = {}
    for i, name in enumerate(sorted(targets)):
        leaf = layers.get(name)
        if not isinstance(leaf, dict) or "kernel" not in leaf:
            available = sorted(
                k for k, v in layers.items()
                if isinstance(v, dict) and "kernel" in v
            )
            raise ValueError(
                f"lora target {name!r} is not a dense layer leaf; "
                f"available: {available}"
            )
        kernel = leaf["kernel"]  # [L, in, out] stacked (or [in, out])
        stacked = kernel.ndim == 3
        lead = (kernel.shape[0],) if stacked else ()
        d_in, d_out = kernel.shape[-2], kernel.shape[-1]
        a = jax.random.normal(
            jax.random.fold_in(key, i), (*lead, d_in, rank), jnp.float32
        ) * (1.0 / rank)
        out[name] = {
            "lora_a": a.astype(kernel.dtype),
            "lora_b": jnp.zeros((*lead, rank, d_out), kernel.dtype),
            "lora_scale": jnp.full(lead or (), alpha / rank, jnp.float32),
        }
    return {"layers": out}


def attach_lora(params: Params, lora: Params) -> Params:
    """Merge the adapter leaves into the param tree structurally (no
    arithmetic): each targeted layer leaf gains lora_a/lora_b/lora_scale,
    which ``models.transformer.dense`` applies on the activation side.
    Used inside the training loss so gradients flow only through ``lora``."""
    layers = dict(params["layers"])
    for name, leaves in lora["layers"].items():
        layers[name] = {**layers[name], **leaves}
    return {**params, "layers": layers}


def merge_lora(params: Params, lora: Params) -> Params:
    """Fold adapters into the base kernels: W' = W + scale · A @ B.

    The returned tree has the original structure (no adapter leaves) — the
    zero-serving-cost form. Precision transforms (int8/int4) quantize W'
    downstream, so the finetuned delta survives quantization."""
    layers = dict(params["layers"])
    for name, leaves in lora["layers"].items():
        base = layers[name]
        kernel = base["kernel"]
        a = leaves["lora_a"].astype(jnp.float32)
        b = leaves["lora_b"].astype(jnp.float32)
        scale = leaves["lora_scale"].astype(jnp.float32)
        delta = jnp.einsum("...ir,...ro->...io", a, b)
        if delta.ndim == 3:  # stacked layers: per-layer scale [L]
            delta = delta * scale[:, None, None]
        else:
            delta = delta * scale
        merged = (kernel.astype(jnp.float32) + delta).astype(kernel.dtype)
        layers[name] = {**base, "kernel": merged}
    return {**params, "layers": layers}


def apply_lora_dense(p: Params, x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Activation-side adapter: y + (x @ A) @ B · scale. Called from
    ``dense()`` when the (scan-sliced) layer leaf carries adapter leaves."""
    a = p["lora_a"].astype(x.dtype)
    b = p["lora_b"].astype(x.dtype)
    return y + ((x @ a) @ b) * p["lora_scale"].astype(x.dtype)


def make_lora_optimizer(
    lr: float = 1e-4, weight_decay: float = 0.01
) -> optax.GradientTransformation:
    """adamw over lora_a/lora_b; ``lora_scale`` is frozen (set_to_zero) so
    the recorded alpha/rank can never drift from what the forward used."""

    def labels(tree: Params) -> Params:
        def walk(node, name=""):
            if isinstance(node, dict):
                return {k: walk(v, k) for k, v in node.items()}
            return "freeze" if name == "lora_scale" else "train"

        return walk(tree)

    return optax.multi_transform(
        {
            "train": optax.adamw(lr, weight_decay=weight_decay),
            "freeze": optax.set_to_zero(),
        },
        labels,
    )


def lora_num_params(lora: Params) -> int:
    return sum(
        leaf.size
        for leaf in jax.tree.leaves(lora)
    )
