"""On-device token sampling: temperature / top-k / top-p / repetition penalty.

Parity target: the exact sampling knob set every reference runner forwards to
HF ``model.generate`` (``Code/C-DAC Server/combiner_fp.py:338-347``;
defaults in ``config_2.yaml:11-14``). Unlike the reference — where sampling
runs inside torch on GPU but the loop returns to Python every call — the whole
transform here is jit-compatible and lives inside the decode ``lax.scan``/
``while_loop``, so the token loop never leaves the device.

Repetition penalty follows the CTRL/HF convention: positive logits are divided
by the penalty, negative multiplied, for every token present in the context.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from edgemesh.config import SamplingParams

NEG_INF = jnp.finfo(jnp.float32).min


def apply_repetition_penalty(
    logits: jnp.ndarray,  # [batch, vocab] float32
    token_mask: jnp.ndarray,  # [batch, vocab] bool — tokens seen in context
    penalty: float,
) -> jnp.ndarray:
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(token_mask, penalized, logits)


def apply_top_k(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    if k <= 0:
        return logits
    vocab = logits.shape[-1]
    k = min(k, vocab)
    kth = jax.lax.top_k(logits, k)[0][..., -1:]  # [batch, 1]
    return jnp.where(logits < kth, NEG_INF, logits)


def apply_top_p(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    """Nucleus filtering. Keeps the smallest prefix of the sorted distribution
    whose cumulative probability exceeds ``p`` (always keeping the top token)."""
    if p >= 1.0:
        return logits
    if p <= 0.0:  # degenerate nucleus: keep only the argmax token
        top = jnp.max(logits, axis=-1, keepdims=True)
        return jnp.where(logits < top, NEG_INF, logits)
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]  # descending
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumprobs = jnp.cumsum(probs, axis=-1)
    # mask sorted positions whose cumulative prob (exclusive) already >= p
    exclusive = cumprobs - probs
    sorted_keep = exclusive < p
    # threshold logit = smallest kept logit
    threshold = jnp.min(
        jnp.where(sorted_keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits < threshold, NEG_INF, logits)


def apply_min_p(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    """min-p filtering: keep tokens with prob >= p * max_prob. Scale-relative
    (unlike top-p's mass-cumulative cutoff), so a confident distribution
    prunes aggressively and a flat one keeps many candidates. Row order is
    irrelevant — works on raw logits or a sorted candidate set alike."""
    if p <= 0.0:
        return logits
    # prob_i >= p·max_prob  ⇔  logit_i >= max_logit + log(p): one
    # max-reduce instead of a vocab-wide softmax on the decode hot path.
    import math

    threshold = jnp.max(logits, axis=-1, keepdims=True) + math.log(p)
    return jnp.where(logits < threshold, NEG_INF, logits)


def _top_p_on_sorted(sorted_logits: jnp.ndarray, p: float) -> jnp.ndarray:
    """Nucleus mask over an already descending-sorted candidate row: identical
    maths to ``apply_top_p`` minus the vocab-wide sort."""
    if p >= 1.0:
        return sorted_logits
    if p <= 0.0:  # degenerate nucleus: keep only the top candidate
        keep = jnp.arange(sorted_logits.shape[-1]) == 0
        return jnp.where(keep, sorted_logits, NEG_INF)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    exclusive = jnp.cumsum(probs, axis=-1) - probs
    return jnp.where(exclusive < p, sorted_logits, NEG_INF)


def sample_token(
    rng: jax.Array,
    logits: jnp.ndarray,  # [batch, vocab]
    params: SamplingParams,
    token_mask: jnp.ndarray | None = None,  # [batch, vocab] bool
) -> jnp.ndarray:
    """One sampling step. ``params`` fields are Python scalars → static under jit.

    When top-k is active (the reference's default, k=50: config_2.yaml:11-14)
    everything after the single ``lax.top_k`` runs on the [batch, k] candidate
    set: nucleus filtering needs no vocab-wide sort (softmax over the top-k
    values equals softmax over the top-k-masked vocab — the discarded entries
    carry NEG_INF) and the Gumbel draw is over k values, not the vocab. Same
    distribution as filter-then-categorical on the full vocab, measured ~2.7 ms
    cheaper per decode step at Llama-3 vocab (128256) on one v5e chip — about
    half the round-1 decode step time.
    """
    logits = logits.astype(jnp.float32)
    if params.do_sample and 0 < params.top_k < logits.shape[-1]:
        # Candidate-set fast path: draw from the SAME (idx, probs) view that
        # speculative decoding scores against (filtered_candidates), so the
        # two can never drift apart.
        idx, probs = filtered_candidates(logits, params, token_mask)
        choice = jax.random.categorical(rng, jnp.log(jnp.maximum(probs, 1e-30)), axis=-1)
        return jnp.take_along_axis(idx, choice[..., None], axis=-1)[..., 0]
    if params.repetition_penalty != 1.0 and token_mask is not None:
        logits = apply_repetition_penalty(logits, token_mask, params.repetition_penalty)
    if not params.do_sample:
        return jnp.argmax(logits, axis=-1)
    if params.temperature != 1.0:
        logits = logits / max(params.temperature, 1e-6)
    # HF warper order: TopP then MinP. Min-p's keep-set after top-p equals
    # HF's exactly — softmax renormalization over the top-p survivors scales
    # every prob by the same factor, so prob_i/max_prob (what min-p
    # thresholds) depends only on logit differences, and top-p always keeps
    # the argmax, so the max-reduce in apply_min_p is unchanged by the
    # NEG_INF-masked tail.
    logits = apply_top_p(logits, params.top_p)  # no top-k: vocab-wide nucleus
    logits = apply_min_p(logits, params.min_p)
    return jax.random.categorical(rng, logits, axis=-1)


def filtered_candidates(
    logits: jnp.ndarray,  # [..., vocab]
    params: SamplingParams,
    token_mask: jnp.ndarray | None = None,  # [..., vocab] bool
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Sparse candidate view of the post-filter sampling distribution:
    ``(idx, probs)`` of shape [..., k], probs normalized over the kept
    nucleus and zero outside it. ``sample_token`` draws from exactly this
    distribution; speculative decoding (runtime/speculative.py) needs the
    distribution itself — for acceptance ratios and residual resampling —
    and the ≤k-sized support keeps all of that off the full vocab.

    Greedy (``do_sample=False``) degenerates to k=1 with probability 1 on
    the argmax, which makes speculative acceptance exact token equality.
    Sampled mode requires ``top_k > 0`` (bounded support); the reference
    always samples with top_k=50 (config_2.yaml:11-14).
    """
    logits = logits.astype(jnp.float32)
    if params.repetition_penalty != 1.0 and token_mask is not None:
        logits = apply_repetition_penalty(logits, token_mask, params.repetition_penalty)
    if not params.do_sample:
        idx = jnp.argmax(logits, axis=-1)[..., None]
        return idx, jnp.ones_like(idx, jnp.float32)
    if not 0 < params.top_k < logits.shape[-1]:
        raise ValueError(
            "filtered_candidates needs bounded support: set top_k in "
            f"[1, vocab) (got {params.top_k})"
        )
    if params.temperature != 1.0:
        logits = logits / max(params.temperature, 1e-6)
    if params.approx_top_k:
        # TPU-native approximate MIPS (recall ~0.95 at k=50 over a 128k
        # vocab) instead of exact top_k's sort-based lowering — this op
        # runs EVERY decode step on [batch, vocab]. aggregate_to_topk
        # (the default) re-ranks the recalled candidates exactly, so the
        # returned rows are still descending-sorted as _top_p_on_sorted
        # requires; only the tail membership can differ from exact top-k.
        vals, idx = jax.lax.approx_max_k(logits, params.top_k)
    else:
        vals, idx = jax.lax.top_k(logits, params.top_k)
    vals = _top_p_on_sorted(vals, params.top_p)
    vals = apply_min_p(vals, params.min_p)  # row-order-free: sorted view ok
    probs = jax.nn.softmax(vals, axis=-1)
    probs = jnp.where(vals > NEG_INF / 2, probs, 0.0)
    probs = probs / jnp.maximum(jnp.sum(probs, axis=-1, keepdims=True), 1e-30)
    return idx, probs


class TokenMaskState(NamedTuple):
    """Running [batch, vocab] presence mask for repetition penalty, updated
    on-device as tokens are emitted."""

    mask: jnp.ndarray

    @staticmethod
    def init(batch: int, vocab: int) -> "TokenMaskState":
        return TokenMaskState(jnp.zeros((batch, vocab), dtype=bool))

    def add(self, tokens: jnp.ndarray) -> "TokenMaskState":
        """tokens: [batch] int32 — mark as seen."""
        mask = self.mask.at[jnp.arange(tokens.shape[0]), tokens].set(True)
        return TokenMaskState(mask)

    def add_sequence(self, tokens: jnp.ndarray, valid: jnp.ndarray) -> "TokenMaskState":
        """tokens: [batch, seq]; valid: [batch, seq] bool — bulk prompt ingest.

        Uses a max-scatter (bool OR) so duplicate (batch, token) indices can
        only turn the bit ON: with .set, a pad slot sharing its id with a real
        prompt token could race the True update and drop it (scatter order is
        unspecified for conflicting indices).
        """
        batch, seq = tokens.shape
        b_idx = jnp.broadcast_to(jnp.arange(batch)[:, None], (batch, seq))
        mask = self.mask.at[b_idx, tokens].max(valid)
        return TokenMaskState(mask)
