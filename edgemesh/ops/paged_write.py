"""In-place paged-pool token writes — the Pallas twin of paged_kv.write_tokens.

Why this exists (measured 2026-07-31, round 4): the decode step originally
scattered each layer's fresh K/V into its page slice with a data-dependent
``.at[pp, :, ss, :].set`` INSIDE the layer scan. XLA:TPU lowers that
multi-dimensional scatter catastrophically (a standalone 0.29 GB-target
scatter measured ~65 ms ≈ 4.5 GB/s), and the decode-loop carry then paid
layout-conversion copies of the whole pool every step: an 8-slot serving
pool at Llama-1B shapes decoded at 11.2 ms/step vs 3.1 ms dense — the whole
round-3 paged-vs-dense tax (VERDICT weakness #3) plus most of the serving
gap (#1) traced to this one write. The dense cache never hit it because its
scatter's leading index is an iota (a batched in-row dynamic-update-slice,
which TPU lowers well); the paged destination page is data-dependent.

The replacement is ONE ``pallas_call`` per decode step, after the layer
scan (runtime/paged_generate._paged_forward_decode_hoisted):

- Grid ``(batch, layers)``; each step read-modify-writes the row's CURRENT
  page in one layer: page block in, vectorized ``where`` merge at the
  token's slot, block out. Block traffic is layers × batch × 2 × 64 KB
  ≈ 16 MB/step — noise next to the weight stream.
- The pool rides in as the flat ``[layers*pages, kh, ps, hd]`` view (a
  leading-dim merge — a free bitcast under TPU tiled layouts; merging the
  MINOR dims instead measured as a real full-pool copy) with
  ``input_output_aliases`` pinning it in place, and the index_map
  dereferences ``layer * P + table[row]`` exactly like the decode
  attention kernel walks its pages.
- Layouts stay canonical end to end. This matters as much as the aliasing:
  an earlier variant that reshaped minor dims fed the loop carry an exotic
  layout and XLA silently converted the WHOLE pool back per iteration.

The reference has no analog (its HF runtime reallocates the cache per call,
``Code/C-DAC Server/combiner_fp.py:338-347``); this is pure TPU-native
serving machinery.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False


def _rmw_kernel(
    pages_ref,  # SMEM [b] int32 — physical page per row (scalar prefetch)
    slots_ref,  # SMEM [b] int32 — in-page slot per row (scalar prefetch)
    kf_ref,  # VMEM block [1, 1, kh, 1, hd] — fresh K for (layer, row)
    vf_ref,
    k_in,  # block [1, kh, ps, hd] — the row's current page (aliased in/out)
    v_in,
    k_out,
    v_out,
):
    i = pl.program_id(0)
    slot = slots_ref[i]
    shape = k_in.shape[1:]  # [kh, ps, hd]
    iot = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    kt = jnp.broadcast_to(kf_ref[0, 0], shape).astype(k_out.dtype)
    vt = jnp.broadcast_to(vf_ref[0, 0], shape).astype(v_out.dtype)
    k_out[0] = jnp.where(iot == slot, kt, k_in[0])
    v_out[0] = jnp.where(iot == slot, vt, v_in[0])


def _rmw_scale_kernel(
    pages_ref,
    slots_ref,
    ksf_ref,  # VMEM block [1, 1, kh, 1, 1] f32 — fresh K scale (layer, row)
    vsf_ref,
    ks_in,  # block [1, kh, 1, ps] f32 (aliased in/out)
    vs_in,
    ks_out,
    vs_out,
):
    i = pl.program_id(0)
    slot = slots_ref[i]
    shape = ks_in.shape[1:]  # [kh, 1, ps]
    iot = jax.lax.broadcasted_iota(jnp.int32, shape, 2)
    kt = jnp.broadcast_to(ksf_ref[0, 0], shape)
    vt = jnp.broadcast_to(vsf_ref[0, 0], shape)
    ks_out[0] = jnp.where(iot == slot, kt, ks_in[0])
    vs_out[0] = jnp.where(iot == slot, vt, vs_in[0])


def _rmw_chunk_kernel(
    pages_ref,  # SMEM [b, npg] int32 — physical page per (row, chunk page)
    off_ref,  # SMEM [b] int32 — start % ps per row
    vlen_ref,  # SMEM [b] int32 — valid chunk tokens per row
    kf_ref,  # VMEM block [1, 1, 1, kh, ps, hd] — page-aligned fresh K
    vf_ref,
    k_in,  # block [1, kh, ps, hd] (aliased in/out)
    v_in,
    k_out,
    v_out,
    *,
    page_size: int,
):
    i = pl.program_id(0)
    p = pl.program_id(2)
    shape = k_in.shape[1:]  # [kh, ps, hd]
    j = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    t = p * page_size + j - off_ref[i]  # chunk-token index at each slot
    hit = (t >= 0) & (t < vlen_ref[i])
    k_out[0] = jnp.where(hit, kf_ref[0, 0, 0].astype(k_out.dtype), k_in[0])
    v_out[0] = jnp.where(hit, vf_ref[0, 0, 0].astype(v_out.dtype), v_in[0])


def _rmw_chunk_scale_kernel(
    pages_ref,
    off_ref,
    vlen_ref,
    ksf_ref,  # VMEM block [1, 1, 1, kh, 1, ps] f32
    vsf_ref,
    ks_in,  # block [1, kh, 1, ps] f32 (aliased in/out)
    vs_in,
    ks_out,
    vs_out,
    *,
    page_size: int,
):
    i = pl.program_id(0)
    p = pl.program_id(2)
    shape = ks_in.shape[1:]  # [kh, 1, ps]
    j = jax.lax.broadcasted_iota(jnp.int32, shape, 2)
    t = p * page_size + j - off_ref[i]
    hit = (t >= 0) & (t < vlen_ref[i])
    ks_out[0] = jnp.where(hit, ksf_ref[0, 0, 0], ks_in[0])
    vs_out[0] = jnp.where(hit, vsf_ref[0, 0, 0], vs_in[0])


def _align_chunk(fresh: jnp.ndarray, off: jnp.ndarray, npg: int, ps: int):
    """[L, b, s, kh, hd] → [L, b, npg, kh, ps, hd]: slot (p, j) of row i
    holds chunk token ``p*ps + j - off[i]`` (clamped; the kernel masks
    out-of-range slots), so kernel blocks never need unaligned fresh
    reads."""
    L, b, s, kh, hd = fresh.shape
    t = jnp.arange(npg * ps)[None, :] - off[:, None]  # [b, npg*ps]
    tc = jnp.clip(t, 0, s - 1)
    g = jnp.take_along_axis(fresh, tc[None, :, :, None, None], axis=2)
    return g.reshape(L, b, npg, ps, kh, hd).transpose(0, 1, 2, 4, 3, 5)


def _align_chunk_scales(scales: jnp.ndarray, off: jnp.ndarray, npg: int, ps: int):
    """[L, b, s, kh] f32 → [L, b, npg, kh, 1, ps]."""
    L, b, s, kh = scales.shape
    t = jnp.arange(npg * ps)[None, :] - off[:, None]
    tc = jnp.clip(t, 0, s - 1)
    g = jnp.take_along_axis(scales, tc[None, :, :, None], axis=2)
    return g.reshape(L, b, npg, ps, kh).transpose(0, 1, 2, 4, 3)[:, :, :, :, None, :]


def write_chunk_all_layers(
    cache,
    fresh_k: jnp.ndarray,  # [L, b, s, kh, hd] (int8 for the quant pool)
    fresh_v: jnp.ndarray,
    start: jnp.ndarray,  # [b] tokens already present per row
    valid_len: jnp.ndarray,  # [b] real chunk tokens per row (≤ s)
    fresh_ks: jnp.ndarray | None = None,  # [L, b, s, kh] f32 (quant pool)
    fresh_vs: jnp.ndarray | None = None,
    interpret: bool = False,
):
    """Write an s-token chunk per row into its pages, every layer at once,
    in place — the prefill/suffix/verify twin of write_decode_all_layers
    (identical indexing to write_tokens(start, valid_len), minus the
    scatter). Each (row, layer, chunk-page) grid step read-modify-writes one
    page block; a chunk straddles at most ceil(s/ps)+1 pages."""
    if not HAVE_PALLAS:  # pragma: no cover
        raise RuntimeError("pallas unavailable")
    L, P, kh, ps, hd = cache.k.shape
    b, s = fresh_k.shape[1], fresh_k.shape[2]
    quant = fresh_ks is not None
    npg = -(-s // ps) + 1
    lp0 = (start // ps).astype(jnp.int32)
    off = (start % ps).astype(jnp.int32)
    pidx = jnp.minimum(
        lp0[:, None] + jnp.arange(npg, dtype=jnp.int32)[None, :],
        cache.page_table.shape[1] - 1,
    )
    pages = jnp.take_along_axis(cache.page_table, pidx, axis=1).astype(jnp.int32)

    def pool_map(i, l, p, pages, off, vlen):
        return (l * P + pages[i, p], 0, 0, 0)

    def fresh_map(i, l, p, pages, off, vlen):
        return (l, i, p, 0, 0, 0)

    k4 = cache.k.reshape(L * P, kh, ps, hd)
    v4 = cache.v.reshape(L * P, kh, ps, hd)
    kf = _align_chunk(fresh_k.astype(cache.k.dtype), off, npg, ps)
    vf = _align_chunk(fresh_v.astype(cache.v.dtype), off, npg, ps)

    new_k, new_v = pl.pallas_call(
        functools.partial(_rmw_chunk_kernel, page_size=ps),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(b, L, npg),
            in_specs=[
                pl.BlockSpec((1, 1, 1, kh, ps, hd), fresh_map),
                pl.BlockSpec((1, 1, 1, kh, ps, hd), fresh_map),
                pl.BlockSpec((1, kh, ps, hd), pool_map),
                pl.BlockSpec((1, kh, ps, hd), pool_map),
            ],
            out_specs=[
                pl.BlockSpec((1, kh, ps, hd), pool_map),
                pl.BlockSpec((1, kh, ps, hd), pool_map),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(k4.shape, k4.dtype),
            jax.ShapeDtypeStruct(v4.shape, v4.dtype),
        ],
        input_output_aliases={5: 0, 6: 1},
        interpret=interpret,
    )(pages, off, valid_len.astype(jnp.int32), kf, vf, k4, v4)
    upd = dict(
        k=new_k.reshape(L, P, kh, ps, hd), v=new_v.reshape(L, P, kh, ps, hd)
    )

    if quant:
        ks4 = cache.k_scale.reshape(L * P, kh, 1, ps)
        vs4 = cache.v_scale.reshape(L * P, kh, 1, ps)
        ksf = _align_chunk_scales(fresh_ks.astype(jnp.float32), off, npg, ps)
        vsf = _align_chunk_scales(fresh_vs.astype(jnp.float32), off, npg, ps)
        new_ks, new_vs = pl.pallas_call(
            functools.partial(_rmw_chunk_scale_kernel, page_size=ps),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=3,
                grid=(b, L, npg),
                in_specs=[
                    pl.BlockSpec((1, 1, 1, kh, 1, ps), fresh_map),
                    pl.BlockSpec((1, 1, 1, kh, 1, ps), fresh_map),
                    pl.BlockSpec((1, kh, 1, ps), pool_map),
                    pl.BlockSpec((1, kh, 1, ps), pool_map),
                ],
                out_specs=[
                    pl.BlockSpec((1, kh, 1, ps), pool_map),
                    pl.BlockSpec((1, kh, 1, ps), pool_map),
                ],
            ),
            out_shape=[
                jax.ShapeDtypeStruct(ks4.shape, jnp.float32),
                jax.ShapeDtypeStruct(vs4.shape, jnp.float32),
            ],
            input_output_aliases={5: 0, 6: 1},
            interpret=interpret,
        )(pages, off, valid_len.astype(jnp.int32), ksf, vsf, ks4, vs4)
        upd["k_scale"] = new_ks.reshape(L, P, kh, 1, ps)
        upd["v_scale"] = new_vs.reshape(L, P, kh, 1, ps)
    return cache._replace(**upd)


def write_decode_all_layers(
    cache,
    fresh_k: jnp.ndarray,  # [L, b, kh, hd] (int8 for the quant pool)
    fresh_v: jnp.ndarray,
    fresh_ks: jnp.ndarray | None = None,  # [L, b, kh] f32 (quant pool only)
    fresh_vs: jnp.ndarray | None = None,
    interpret: bool = False,
):
    """Write one token per row into its current page, every layer at once,
    in place. Returns the cache with k/v (and scales) updated; lengths and
    page_table pass through untouched — callers advance lengths themselves
    (forward_decode_paged's contract).

    The row's destination is ``(table[i, lengths[i] // ps], lengths[i] % ps)``
    — identical indexing to write_tokens(start=lengths, valid_len=1), minus
    the scatter. Rows whose table slot is unallocated write the trash page
    (physical 0), same as the scatter path.
    """
    if not HAVE_PALLAS:  # pragma: no cover
        raise RuntimeError("pallas unavailable")
    L, P, kh, ps, hd = cache.k.shape
    b = cache.lengths.shape[0]
    quant = fresh_ks is not None
    logical = jnp.minimum(cache.lengths // ps, cache.page_table.shape[1] - 1)
    pages = jnp.take_along_axis(cache.page_table, logical[:, None], axis=1)[:, 0]
    pages = pages.astype(jnp.int32)
    slots = (cache.lengths % ps).astype(jnp.int32)

    def pool_map(i, l, pages, slots):
        return (l * P + pages[i], 0, 0, 0)

    def fresh_map(i, l, pages, slots):
        return (l, i, 0, 0, 0)

    k4 = cache.k.reshape(L * P, kh, ps, hd)
    v4 = cache.v.reshape(L * P, kh, ps, hd)
    kf = fresh_k.reshape(L, b, kh, 1, hd).astype(cache.k.dtype)
    vf = fresh_v.reshape(L, b, kh, 1, hd).astype(cache.v.dtype)

    new_k, new_v = pl.pallas_call(
        _rmw_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, L),
            in_specs=[
                pl.BlockSpec((1, 1, kh, 1, hd), fresh_map),
                pl.BlockSpec((1, 1, kh, 1, hd), fresh_map),
                pl.BlockSpec((1, kh, ps, hd), pool_map),
                pl.BlockSpec((1, kh, ps, hd), pool_map),
            ],
            out_specs=[
                pl.BlockSpec((1, kh, ps, hd), pool_map),
                pl.BlockSpec((1, kh, ps, hd), pool_map),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(k4.shape, k4.dtype),
            jax.ShapeDtypeStruct(v4.shape, v4.dtype),
        ],
        input_output_aliases={4: 0, 5: 1},
        interpret=interpret,
    )(pages, slots, kf, vf, k4, v4)
    upd = dict(
        k=new_k.reshape(L, P, kh, ps, hd), v=new_v.reshape(L, P, kh, ps, hd)
    )

    if quant:
        ks4 = cache.k_scale.reshape(L * P, kh, 1, ps)
        vs4 = cache.v_scale.reshape(L * P, kh, 1, ps)
        ksf = fresh_ks.reshape(L, b, kh, 1, 1).astype(jnp.float32)
        vsf = fresh_vs.reshape(L, b, kh, 1, 1).astype(jnp.float32)
        new_ks, new_vs = pl.pallas_call(
            _rmw_scale_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(b, L),
                in_specs=[
                    pl.BlockSpec((1, 1, kh, 1, 1), fresh_map),
                    pl.BlockSpec((1, 1, kh, 1, 1), fresh_map),
                    pl.BlockSpec((1, kh, 1, ps), pool_map),
                    pl.BlockSpec((1, kh, 1, ps), pool_map),
                ],
                out_specs=[
                    pl.BlockSpec((1, kh, 1, ps), pool_map),
                    pl.BlockSpec((1, kh, 1, ps), pool_map),
                ],
            ),
            out_shape=[
                jax.ShapeDtypeStruct(ks4.shape, jnp.float32),
                jax.ShapeDtypeStruct(vs4.shape, jnp.float32),
            ],
            input_output_aliases={4: 0, 5: 1},
            interpret=interpret,
        )(pages, slots, ksf, vsf, ks4, vs4)
        upd["k_scale"] = new_ks.reshape(L, P, kh, 1, ps)
        upd["v_scale"] = new_vs.reshape(L, P, kh, 1, ps)
    return cache._replace(**upd)
