"""Attention against a fixed-capacity HBM-resident KV cache, with GQA.

TPU design notes:
- The cache is a statically-shaped [batch, max_seq, kv_heads, head_dim] array
  per layer, preallocated in HBM. Every decode step attends over the full
  capacity with a validity mask — static shapes keep one compiled XLA program
  for the whole autoregressive loop (no recompiles, the analog of the
  reference's per-call ``model.generate`` that re-enters Python each sample,
  ``Code/C-DAC Server/combiner_fp.py:338-347``).
- Scores/softmax run in fp32 on the MXU/VPU; activations stay bf16.
- GQA is expressed as a 5-D einsum (query heads grouped over kv heads) so XLA
  never materializes repeated K/V.
- Head-wise sharding of the cache over the mesh's model axis is the
  HeadInfer-analog (BASELINE.json configs[3]): instead of offloading KV heads
  to host DRAM like HeadInfer does on small GPUs, each chip keeps only its
  heads' cache slices in HBM.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import nn

NEG_INF = -1e30


class LayerKV(NamedTuple):
    """Single layer's cache slices: k/v are [batch, max_seq, kv_heads, head_dim]."""

    k: jnp.ndarray
    v: jnp.ndarray


def write_prefill(cache: LayerKV, k: jnp.ndarray, v: jnp.ndarray) -> LayerKV:
    """Write a right-padded prompt's K/V at offset 0. k/v: [b, s, kh, d]."""
    seq = k.shape[1]
    return LayerKV(
        cache.k.at[:, :seq].set(k.astype(cache.k.dtype)),
        cache.v.at[:, :seq].set(v.astype(cache.v.dtype)),
    )


def write_decode(cache: LayerKV, k: jnp.ndarray, v: jnp.ndarray, lengths: jnp.ndarray) -> LayerKV:
    """Scatter ``s`` new K/V rows per batch element starting at its current
    length — s=1 is the autoregressive step, s>1 the speculative-verify
    chunk append (runtime/speculative.py).

    k/v: [b, s, kh, d]; lengths: [b] int32 (pre-increment write offset; row
    ``b`` writes slots ``lengths[b] .. lengths[b]+s-1``).
    """
    batch, s = k.shape[:2]
    b_idx = jnp.arange(batch)[:, None]  # [b, 1]
    pos = lengths[:, None] + jnp.arange(s)[None, :]  # [b, s]
    return LayerKV(
        cache.k.at[b_idx, pos].set(k.astype(cache.k.dtype)),
        cache.v.at[b_idx, pos].set(v.astype(cache.v.dtype)),
    )


def attend(
    q: jnp.ndarray,  # [b, s, num_heads, head_dim]
    cache: LayerKV,  # k/v [b, max_seq, kv_heads, head_dim]
    q_positions: jnp.ndarray,  # [b, s] int32 — absolute position of each query
    kv_valid: jnp.ndarray,  # [b, max_seq] bool — slots containing real tokens
    scale: float | None = None,
    sliding_window: int = 0,
    soft_cap: float = 0.0,
) -> jnp.ndarray:
    """Causal attention of queries against the full cache.

    Returns [b, s, num_heads, head_dim] in q's dtype. A cache slot j is visible
    to query at position p iff it holds a real token and j <= p — and, with
    ``sliding_window`` w > 0 (Mistral), additionally j > p - w.
    ``soft_cap`` > 0 (Gemma-2) squashes scores to cap·tanh(score/cap) before
    masking.
    """
    b, s, num_heads, head_dim = q.shape
    kv_heads = cache.k.shape[2]
    groups = num_heads // kv_heads
    scale = scale if scale is not None else head_dim**-0.5

    # Keep q/k/v in their storage dtype (bf16 on TPU → MXU path, no fp32 copy
    # of the cache in HBM); accumulate the matmuls in fp32 via
    # preferred_element_type, and do mask/softmax in fp32.
    qg = q.reshape(b, s, kv_heads, groups, head_dim)
    scores = jnp.einsum(
        "bskgd,bmkd->bskgm", qg, cache.k, preferred_element_type=jnp.float32
    ) * scale
    if soft_cap > 0:
        scores = soft_cap * jnp.tanh(scores / soft_cap)
    max_seq = cache.k.shape[1]
    slot_pos = jnp.arange(max_seq)[None, None, :]  # [1, 1, m]
    causal = slot_pos <= q_positions[:, :, None]  # [b, s, m]
    mask = causal & kv_valid[:, None, :]  # [b, s, m]
    if sliding_window > 0:
        mask = mask & (slot_pos > q_positions[:, :, None] - sliding_window)
    scores = jnp.where(mask[:, :, None, None, :], scores, NEG_INF)
    weights = nn.softmax(scores, axis=-1).astype(cache.v.dtype)
    out = jnp.einsum(
        "bskgm,bmkd->bskgd", weights, cache.v, preferred_element_type=jnp.float32
    )
    return out.reshape(b, s, num_heads, head_dim).astype(q.dtype)
