"""SmoothQuant calibration: collect activation scales for int8 smoothing.

``ops/int8.quantize_params`` has accepted ``smooth_scales`` since round 1
(W' = W·s, x' = x/s migrates activation outliers into weights — the
SmoothQuant recipe; the reference even collected the paper,
``.MISSING_LARGE_BLOBS:3``), but nothing computed the scales. This module
closes that: run a calibration batch through the model layer by layer
(unrolled Python loop over the stacked layer axis — calibration is offline,
clarity beats speed) and record the per-in-channel absmax of the inputs to
the channel-heavy matmuls (q/k/v from the attention norm, gate/up from the
MLP norm). The o/down projections are left unsmoothed: their inputs are
attention/GLU internals with mild channel spread, and quantize_params
simply skips leaves absent from the scales tree.

Why activations only (not the |W|^(1-alpha) denominator): quantize_params
applies ``s = act_absmax^alpha`` — the single-knob variant. With alpha=0.5
this is SmoothQuant's symmetric setting when weight ranges are roughly
uniform across channels, and it keeps calibration weight-independent.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from edgemesh.models.transformer import (
    ModelConfig,
    Params,
    _apply_norm,
    embed_tokens,
    init_kv_cache,
    _layer_fn,
)
from edgemesh.ops.attention import LayerKV


def collect_activation_scales(
    cfg: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,  # [b, s] calibration prompts (right-padded)
    lengths: jnp.ndarray,  # [b]
) -> Params:
    """Per-layer, per-in-channel activation absmax for the smoothable
    denses. Returns a tree shaped for ``quantize_params(smooth_scales=...)``:
    ``{"layers": {"q": [L, h], "k": …, "v": …, "gate": [L, h], "up": [L, h]}}``
    (gate only for gated MLPs; shared_input_norm families reuse the attn
    stats for the MLP)."""
    b, s = tokens.shape
    L = cfg.num_layers
    cache = init_kv_cache(cfg, b, s)
    positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    positions = jnp.minimum(positions, (jnp.maximum(lengths, 1) - 1)[:, None])
    kv_valid = jnp.arange(s)[None, :] < lengths[:, None]
    token_valid = kv_valid[..., None]  # [b, s, 1] — exclude pad rows from stats

    from edgemesh.models.transformer import _mlp

    # One pass: attention inputs read from the norm directly; MLP inputs
    # captured via _layer_fn's pluggable mlp hook, which sees the exact
    # tensor the gate/up denses consume — including the sequential
    # families' norm(x + attn_out), which only exists mid-layer.
    mlp_stats: list[jnp.ndarray] = []

    def capturing_mlp(cfg_, layer_, x_):
        mlp_stats.append(_channel_absmax(x_, token_valid))
        return _mlp(cfg_, layer_, x_)

    x = embed_tokens(cfg, params, tokens, positions)
    attn_stats = []
    for i in range(L):
        layer = jax.tree.map(lambda a: a[i], params["layers"])
        attn_in = _apply_norm(cfg, layer["attn_norm"], x)
        attn_stats.append(_channel_absmax(attn_in, token_valid))
        x, _, _ = _layer_fn(
            cfg, x, layer, LayerKV(cache.k[i], cache.v[i]), positions,
            kv_valid, cache.lengths, False, mlp=capturing_mlp,
        )

    out: Params = {
        "q": jnp.stack(attn_stats),
        "k": jnp.stack(attn_stats),
        "v": jnp.stack(attn_stats),
        "up": jnp.stack(mlp_stats),
    }
    if cfg.gated:
        out["gate"] = jnp.stack(mlp_stats)
    return {"layers": out}


def _channel_absmax(x: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    return jnp.max(jnp.abs(x.astype(jnp.float32)) * valid, axis=(0, 1))


def calibrate_and_quantize(
    cfg: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,
    lengths: jnp.ndarray,
    alpha: float = 0.5,
) -> Params:
    """One-call flow: collect activation scales on the calibration batch,
    then quantize with smoothing (the int8 runners' load path analog)."""
    from edgemesh.ops.int8 import quantize_params

    scales = collect_activation_scales(cfg, params, tokens, lengths)
    return quantize_params(params, smooth_scales=scales, alpha=alpha)
