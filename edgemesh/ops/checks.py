"""Kernel-contract asserts via checkify (SURVEY.md §5.2).

JAX's functional purity supplies the race-freedom the reference never had to
think about (its only concurrency is a gRPC thread pool,
``Code/gRPC/server.py:14``), but the Pallas kernels still carry
data-dependent contracts a caller can violate under jit with NO error —
only silently wrong numbers:

- paged attention: a page-table entry outside the physical pool makes the
  DMA engine fetch whatever lives at that block index (ops/paged_attention.py
  dereferences ``table[b, p]`` at DMA-issue time); an oversized ``kv_lens``
  un-masks trash-page columns.
- flash attention: ``kv_lens`` beyond the padded kv extent un-masks padding;
  non-finite Q/K poisons the online-softmax running max forever.
- fused int8 matmul: non-positive / non-finite weight scales turn the
  epilogue rescale into NaN/garbage amplification.

Each kernel wrapper takes ``check=True`` (static) to emit these as
``checkify.check`` assertions. They are free when off (the default), and
when on they raise precise errors through ``checked()``:

    from edgemesh.ops.checks import checked
    out = checked(lambda q, t: paged_decode_attention(q, ..., check=True))(q, t)

Under eager execution ``check=True`` raises directly; under jit the caller
wraps with ``checked``/``checkify.checkify`` (checkify functionalizes the
checks; an unwrapped jitted call with checks on fails at trace time with a
clear checkify error rather than running unvalidated).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.experimental import checkify


def checked(fn):
    """Run ``fn`` with its checkify.check assertions live: functionalize,
    call, and re-raise any tripped check host-side. Composes with jit —
    ``checked(jitted_fn)`` is the debug entry point for every kernel here."""
    cfn = checkify.checkify(fn, errors=checkify.user_checks)

    def run(*args, **kwargs):
        err, out = cfn(*args, **kwargs)
        err.throw()
        return out

    return run


def check_flash_inputs(q, k, kv_lens, q_offsets) -> None:
    skv = k.shape[1]
    checkify.check(jnp.all(kv_lens >= 0), "flash_attention: negative kv_lens")
    checkify.check(
        jnp.all(kv_lens <= skv),
        "flash_attention: kv_lens exceeds kv extent {s} (padding would be "
        "un-masked)", s=jnp.int32(skv),
    )
    checkify.check(jnp.all(q_offsets >= 0), "flash_attention: negative q_offsets")
    checkify.check(
        jnp.all(jnp.isfinite(q.astype(jnp.float32))),
        "flash_attention: non-finite query activations",
    )
    checkify.check(
        jnp.all(jnp.isfinite(k.astype(jnp.float32))),
        "flash_attention: non-finite key activations",
    )


def check_paged_inputs(q, k_pages, page_table, kv_lens) -> None:
    total_pages = k_pages.shape[0]  # page-major pool [P, kh, ps, hd]
    page_size = k_pages.shape[2]
    max_tokens = page_table.shape[1] * page_size
    checkify.check(
        jnp.all((page_table >= 0) & (page_table < total_pages)),
        "paged_attention: page-table entry outside the {n}-page physical pool "
        "(the DMA would fetch unrelated memory)", n=jnp.int32(total_pages),
    )
    checkify.check(
        jnp.all((kv_lens >= 1) & (kv_lens <= max_tokens)),
        "paged_attention: kv_lens outside [1, {m}] (table capacity)",
        m=jnp.int32(max_tokens),
    )
    checkify.check(
        jnp.all(jnp.isfinite(q.astype(jnp.float32))),
        "paged_attention: non-finite query activations",
    )


def check_ragged_inputs(q, k_pages, page_table, kv_lens, cu_q_lens) -> None:
    total_pages = k_pages.shape[0]
    page_size = k_pages.shape[2]
    max_tokens = page_table.shape[1] * page_size
    q_lens = cu_q_lens[1:] - cu_q_lens[:-1]
    checkify.check(
        jnp.all((page_table >= 0) & (page_table < total_pages)),
        "ragged_paged_attention: page-table entry outside the {n}-page "
        "physical pool (the DMA would fetch unrelated memory)",
        n=jnp.int32(total_pages),
    )
    # kv_len 0 is legal here (zero-length rows ride masked-dead, per the
    # kernel contract) — only the capacity bound and negatives are errors.
    checkify.check(
        jnp.all((kv_lens >= 0) & (kv_lens <= max_tokens)),
        "ragged_paged_attention: kv_lens outside [0, {m}] (table capacity)",
        m=jnp.int32(max_tokens),
    )
    checkify.check(
        jnp.all(q_lens >= 0) & (cu_q_lens[0] == 0),
        "ragged_paged_attention: cu_q_lens must be non-decreasing from 0",
    )
    checkify.check(
        cu_q_lens[-1] <= q.shape[0],
        "ragged_paged_attention: cu_q_lens[-1] exceeds the packed query "
        "rows {t} (segments would read other sequences' queries)",
        t=jnp.int32(q.shape[0]),
    )
    checkify.check(
        jnp.all(q_lens <= kv_lens),
        "ragged_paged_attention: a segment's query count exceeds its kv_len "
        "(queries would sit at negative positions)",
    )
    checkify.check(
        jnp.all(jnp.isfinite(q.astype(jnp.float32))),
        "ragged_paged_attention: non-finite query activations",
    )


def check_int8_inputs(x, w_q, scales) -> None:
    checkify.check(
        jnp.all(jnp.isfinite(scales) & (scales > 0)),
        "int8_matmul: weight scales must be finite and positive",
    )
    checkify.check(
        jnp.all(jnp.isfinite(x.astype(jnp.float32))),
        "int8_matmul: non-finite activations",
    )
