"""Int8 quantization: per-channel weight quant + MXU-native int8 matmul.

Parity + perf target (SURVEY.md §6): the reference quantizes with
bitsandbytes ``load_in_8bit`` (``Code/Quantised Models/models_quant_updated.py:30-38``)
and pays a 2.5× THROUGHPUT REGRESSION for it on A100 (Combo 67.2 → 26.39
tok/s, paper Table 3) because the CUDA path dequantizes on the fly in
separate kernels. The TPU design avoids that by feeding the MXU int8×int8 →
int32 directly (both operands quantized), so int8 is FASTER than bf16, not
slower — the BASELINE.json headline (decode tok/s at int8 ≥ bf16).

Three execution paths, one numerical contract:
- ``int8_matmul`` (w8a16): weight-only — dequant folds into the matmul's
  epilogue. Used where activation range is hostile (small batch decode).
- ``int8_matmul_dynamic`` (w8a8): dynamic per-row activation quant; the MXU
  sees int8×int8. XLA path via ``lax.dot_general(..., preferred_element_type=int32)``.
- ``pallas_int8_matmul``: fused Pallas kernel (quantize + int8 dot + rescale
  in one VMEM round-trip), grid-tiled for the 128×128 MXU. Off by default on
  CPU (tests run it with interpret=True).

SmoothQuant-style activation smoothing (the reference's missing blob
``2211.10438v7.pdf`` is the SmoothQuant paper, ``.MISSING_LARGE_BLOBS:3``) is
applied at quantization time when calibration scales are provided:
W' = W * s, x' = x / s migrates activation outliers into weights.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

Params = dict[str, Any]

INT8_MAX = 127.0


# ---------------------------------------------------------------------------
# Weight quantization (load-time transform over the param pytree)
# ---------------------------------------------------------------------------


def quantize_weight(kernel: jnp.ndarray, axis: int = -2) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-output-channel int8 quantization of a [in, out] (or
    [L, in, out]) kernel. Returns (int8 kernel, fp32 scales broadcastable over
    the contraction axis)."""
    kf = kernel.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(kf), axis=axis, keepdims=True)
    scales = jnp.maximum(absmax / INT8_MAX, 1e-8)
    q = jnp.clip(jnp.round(kf / scales), -127, 127).astype(jnp.int8)
    return q, jnp.squeeze(scales, axis=axis)


def quantize_params(
    params: Params,
    smooth_scales: Params | None = None,
    alpha: float = 0.5,
) -> Params:
    """Walk the param pytree; replace every dense {kernel[, bias]} with
    {kernel_q, scales[, bias]}. Embeddings and norms stay high-precision
    (matching the reference runners, where bitsandbytes also only hits
    nn.Linear — same boundary as try.py:205's quantize_dynamic({nn.Linear}))."""

    def walk(node, path=()):
        if isinstance(node, dict):
            if path[-1:] == ("router",):
                # MoE router stays fp32: routing softmax islands need full
                # precision (ops/moe.py reads router.kernel directly, and a
                # quantized argmax over near-tied experts flips routes).
                return node
            if path[-1:] == ("moe",):
                # Expert FFN weights are the bulk of an MoE model (~96% of
                # Mixtral-8x7B); they store as raw [L, E, in, out] arrays,
                # not {"kernel"} dicts, so quantize them here. Same
                # nn.Linear boundary as everywhere else — HF's experts ARE
                # nn.Linear (w1/w2/w3). moe_mlp dequantizes in the expert
                # matmul epilogue (w8a16 style).
                out = {"router": node["router"]}
                for name in ("gate", "up", "down"):
                    if name in node:
                        q, scales = quantize_weight(node[name])
                        out[f"{name}_q"] = q
                        out[f"{name}_scales"] = scales
                return out
            if "kernel" in node:
                kernel = node["kernel"]
                if smooth_scales is not None:
                    s = _lookup(smooth_scales, path)
                    if s is not None:
                        s = jnp.power(jnp.maximum(s, 1e-5), alpha)
                        kernel = kernel * s[..., :, None]
                q, scales = quantize_weight(kernel)
                out: Params = {"kernel_q": q, "scales": scales}
                if "bias" in node:
                    out["bias"] = node["bias"]
                if smooth_scales is not None and s is not None:
                    out["smooth"] = s
                return out
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        return node

    return walk(params)


def quantize_embedding(params: Params) -> Params:
    """Quantize the token embedding to int8 with per-vocab-row scales.

    Separate from :func:`quantize_params` (which matches the reference's
    nn.Linear-only boundary, ``try.py:205``) because it changes BOTH ends of
    the model: the input lookup becomes a gather-dequant (reads b·s rows —
    negligible), and the TIED lm head (``transformer.lm_head_logits``) becomes
    a w8a16 epilogue matmul over the int8 rows. On Llama-3.2-1B the tied bf16
    embedding is 525 MB read once per decode step — ~35% of all weight
    traffic in an otherwise-int8 model — so quantizing it is the single
    largest decode-bandwidth lever after quantize_params. Per-row scales make
    the gather and the head matmul see bit-identical dequantized values.
    """
    embed = params.get("embed", {})
    if "weight" not in embed:
        return params
    # [V, H] reduced over H → one scale per vocab row; the same axis serves
    # the tied head matmul (out-channel = vocab row).
    q, scales = quantize_weight(embed["weight"], axis=-1)
    out = dict(params)
    out["embed"] = {"weight_q": q, "scales": scales}
    return out


def embedding_table(embed: Params, dtype=jnp.bfloat16) -> jnp.ndarray:
    """Dense [V, H] view of a (possibly quantized) embedding subtree."""
    if "weight_q" in embed:
        return (
            embed["weight_q"].astype(jnp.float32) * embed["scales"][:, None]
        ).astype(dtype)
    return embed["weight"]


def _lookup(tree: Params, path: tuple) -> jnp.ndarray | None:
    node = tree
    for p in path:
        if not isinstance(node, dict) or p not in node:
            return None
        node = node[p]
    return node if not isinstance(node, dict) else None


def is_quantized(params: Params) -> bool:
    """True if any dense leaf in the pytree carries an int8 kernel."""
    found = False

    def walk(node):
        nonlocal found
        if isinstance(node, dict):
            if "kernel_q" in node or "kernel_q4" in node:
                found = True
            else:
                for v in node.values():
                    walk(v)

    walk(params)
    return found


def dequantize_weight(q: jnp.ndarray, scales: jnp.ndarray, dtype=jnp.bfloat16) -> jnp.ndarray:
    return (q.astype(jnp.float32) * scales[..., None, :]).astype(dtype)


# ---------------------------------------------------------------------------
# Matmul paths
# ---------------------------------------------------------------------------


def int8_matmul(x: jnp.ndarray, w_q: jnp.ndarray, scales: jnp.ndarray) -> jnp.ndarray:
    """w8a16: y = (x @ w_q) * scales with the dequant folded into the epilogue.

    The int8→activation-dtype convert feeds the MXU directly; XLA fuses the
    per-column scale multiply into the matmul output, so no dequantized weight
    copy ever lands in HBM (the reference's bitsandbytes path materializes
    exactly that copy per layer — its Table 3 regression)."""
    y = jnp.matmul(x, w_q.astype(x.dtype), preferred_element_type=jnp.float32)
    return (y * scales.astype(jnp.float32)).astype(x.dtype)


def quantize_activations(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Dynamic symmetric per-row (per-token) int8 quantization."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax / INT8_MAX, 1e-8)
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def int8_matmul_dynamic(x: jnp.ndarray, w_q: jnp.ndarray, scales: jnp.ndarray) -> jnp.ndarray:
    """w8a8: dynamic activation quant + native int8×int8→int32 MXU matmul."""
    x_q, x_scale = quantize_activations(x)
    acc = lax.dot_general(
        x_q,
        w_q,
        (((x_q.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return (acc.astype(jnp.float32) * x_scale * scales.astype(jnp.float32)).astype(x.dtype)


# ---------------------------------------------------------------------------
# Pallas fused w8a8 kernel
# ---------------------------------------------------------------------------


def _int8_matmul_kernel(x_ref, w_ref, wscale_ref, out_ref, acc_ref):
    """One (TM, TN) output tile; grid walks (M/TM, N/TN, K/TK) with K minor.

    Per K-step: quantize the x tile to int8 on the VPU, int8×int8 dot on the
    MXU into the int32-ish fp32 accumulator; on the last K step apply the
    per-column weight scale and write out. Activation scale is per-row within
    the tile (computed per K-block, folded immediately — block-local dynamic
    quantization). When the whole contraction fits one K stripe (nk == 1,
    the common decode case: tile_k == K) the accumulator round-trip is
    skipped entirely — quantize → dot → scale → store."""
    k_step = pl.program_id(2)
    nk = pl.num_programs(2)

    x_blk = x_ref[:].astype(jnp.float32)  # [TM, TK]
    absmax = jnp.max(jnp.abs(x_blk), axis=1, keepdims=True)
    x_scale = jnp.maximum(absmax / INT8_MAX, 1e-8)
    x_q = jnp.clip(jnp.round(x_blk / x_scale), -127, 127).astype(jnp.int8)
    prod = jax.lax.dot_general(
        x_q, w_ref[:], (((1,), (0,)), ((), ())), preferred_element_type=jnp.int32
    )

    if nk == 1:  # single K stripe: no scratch init/read/write
        out_ref[:] = (
            prod.astype(jnp.float32) * x_scale
            * wscale_ref[0, :].astype(jnp.float32)
        ).astype(out_ref.dtype)
        return

    @pl.when(k_step == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += prod.astype(jnp.float32) * x_scale

    @pl.when(k_step == nk - 1)
    def _finish():
        out_ref[:] = (acc_ref[:] * wscale_ref[0, :].astype(jnp.float32)).astype(out_ref.dtype)


try:  # Pallas import is TPU/CPU-interpret only; keep module importable anywhere
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pl = None
    pltpu = None


def pallas_int8_matmul(
    x: jnp.ndarray,  # [M, K] activation (any float dtype)
    w_q: jnp.ndarray,  # [K, N] int8
    scales: jnp.ndarray,  # [N] fp32 per-column
    *,
    tile_m: int = 128,
    tile_n: int = 128,
    tile_k: int = 512,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused dynamic-quant int8 matmul as a Pallas TPU kernel.

    Shapes must tile evenly (callers pad); tiles default to MXU-friendly
    128×128 output blocks with a 512-deep K stripe (int8 min tile is (32,128),
    pallas_guide.md Tiling Constraints)."""
    if pl is None:
        raise RuntimeError("pallas unavailable")
    m, k = x.shape
    k2, n = w_q.shape
    assert k == k2, (k, k2)
    tile_m = min(tile_m, m)
    tile_n = min(tile_n, n)
    tile_k = min(tile_k, k)
    assert m % tile_m == 0 and n % tile_n == 0 and k % tile_k == 0, (m, n, k)

    grid = (m // tile_m, n // tile_n, k // tile_k)
    kwargs = {}
    if not interpret:
        # M/N tiles are independent (parallel); K carries the accumulator
        # (arbitrary) — lets Mosaic pipeline the weight-stripe DMAs.
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    return pl.pallas_call(
        _int8_matmul_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_m, tile_k), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((tile_k, tile_n), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, tile_n), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((tile_m, tile_n), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((tile_m, tile_n), jnp.float32)],
        interpret=interpret,
        **kwargs,
    )(x, w_q, scales.reshape(1, -1))


def _int8_prequant_kernel(xq_ref, xs_ref, w_ref, wscale_ref, out_ref, acc_ref):
    """Pre-quantized w8a8 tile: both operands arrive int8; the MXU dot
    accumulates natively in int32 (no per-step float work at all), and the
    single epilogue fold applies per-row activation scale × per-column weight
    scale. Compared to :func:`_int8_matmul_kernel` this moves the activation
    quantization OUT of the kernel (XLA fuses it into the producing op), so:
    (a) x tiles stream as int8 — 2-4× less activation DMA than bf16/f32,
    (b) no VPU quantize repeated per N-tile × K-step,
    (c) the accumulator round-trips VMEM as int32, matching the XLA
    ``int8_matmul_dynamic`` numerics exactly (whole-row scales, int32 sum)."""
    k_step = pl.program_id(2)
    nk = pl.num_programs(2)

    prod = jax.lax.dot_general(
        xq_ref[:], w_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )

    if nk == 1:  # single K stripe: dot → scale → store, no scratch at all
        out_ref[:] = (
            prod.astype(jnp.float32)
            * xs_ref[:].astype(jnp.float32)
            * wscale_ref[0, :].astype(jnp.float32)
        ).astype(out_ref.dtype)
        return

    @pl.when(k_step == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += prod

    @pl.when(k_step == nk - 1)
    def _finish():
        out_ref[:] = (
            acc_ref[:].astype(jnp.float32)
            * xs_ref[:].astype(jnp.float32)
            * wscale_ref[0, :].astype(jnp.float32)
        ).astype(out_ref.dtype)


def pallas_int8_prequant_matmul(
    x_q: jnp.ndarray,  # [M, K] int8 (already quantized)
    x_scale: jnp.ndarray,  # [M, 1] fp32 per-row
    w_q: jnp.ndarray,  # [K, N] int8
    scales: jnp.ndarray,  # [N] fp32 per-column
    out_dtype=jnp.bfloat16,
    *,
    tile_m: int = 128,
    tile_n: int = 128,
    tile_k: int = 512,
    interpret: bool = False,
) -> jnp.ndarray:
    """int8×int8→int32 Pallas matmul over pre-quantized operands."""
    if pl is None:
        raise RuntimeError("pallas unavailable")
    m, k = x_q.shape
    k2, n = w_q.shape
    assert k == k2, (k, k2)
    tile_m = min(tile_m, m)
    tile_n = min(tile_n, n)
    tile_k = min(tile_k, k)
    assert m % tile_m == 0 and n % tile_n == 0 and k % tile_k == 0, (m, n, k)

    grid = (m // tile_m, n // tile_n, k // tile_k)
    kwargs = {}
    if not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    return pl.pallas_call(
        _int8_prequant_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_m, tile_k), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((tile_m, 1), lambda i, j, kk: (i, 0)),
            pl.BlockSpec((tile_k, tile_n), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, tile_n), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((tile_m, tile_n), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((tile_m, tile_n), jnp.int32)],
        interpret=interpret,
        **kwargs,
    )(x_q, x_scale, w_q, scales.reshape(1, -1))


def _select_tiles(m: int, k: int, n: int) -> tuple[int | None, int | None, int]:
    """Shared tile-selection policy for both Pallas w8a8 wrappers — these
    constants are tuned from on-chip measurement (see the comments in
    :func:`int8_matmul_fused`), so keeping one copy means a retune can
    never leave the measured-auto-pick comparing a tuned kernel against a
    stale one. Returns (tile_k, tile_n, pad_to); tile_k/tile_n are None
    when the shape does not tile onto the MXU grid."""
    tile_k = next((t for t in (2048, 1024, 512, 256, 128) if k % t == 0), None)
    # Decode-shaped calls (tiny M) amortize per-grid-step overhead over few
    # output rows, so wider N tiles (fewer steps, larger weight-stripe DMAs)
    # help; 2 MB per int8 stripe keeps double-buffering within VMEM.
    n_opts = (1024, 512, 256, 128) if m <= 32 else (512, 256, 128)
    tile_n = next(
        (t for t in n_opts if n % t == 0 and (tile_k or 0) * t <= 2**21), None
    )
    # Pad M to the sublane multiple: 32 for headroom on small decode
    # batches, 128 once a full MXU tile's worth of rows exists.
    pad_to = 128 if m > 32 else 32
    return tile_k, tile_n, pad_to


def int8_matmul_prequant(
    x: jnp.ndarray,  # [..., K] activation
    w_q: jnp.ndarray,  # [K, N] int8
    scales: jnp.ndarray,  # [N]
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """Model-facing entry for the pre-quantized Pallas w8a8 path
    (``quant_mode="w8a8_pallas_pre"``).

    The per-row activation quantization happens here in XLA-land — the
    compiler fuses the absmax/round/clip into the producing op's epilogue —
    and the kernel consumes int8 on both sides. Numerics match the XLA
    ``int8_matmul_dynamic`` path exactly (same whole-row scales, same int32
    accumulation), unlike the block-local-quant ``int8_matmul_fused``.
    Falls back to the XLA path when shapes do not tile onto the MXU grid."""
    *lead, k = x.shape
    n = w_q.shape[1]
    x2 = x.reshape(-1, k)
    m = x2.shape[0]
    tile_k, tile_n, pad_to = _select_tiles(m, k, n)
    if pl is None or tile_k is None or tile_n is None or m == 0:
        y = int8_matmul_dynamic(x2, w_q, scales)
        return y.reshape(*lead, n)
    x_q, x_scale = quantize_activations(x2)
    m_pad = -m % pad_to
    if m_pad:
        x_q = jnp.pad(x_q, ((0, m_pad), (0, 0)))
        x_scale = jnp.pad(x_scale, ((0, m_pad), (0, 0)), constant_values=1.0)
    tile_m = min(128, x_q.shape[0])
    y = pallas_int8_prequant_matmul(
        x_q, x_scale, w_q, scales, out_dtype=x.dtype,
        tile_m=tile_m, tile_n=tile_n, tile_k=tile_k, interpret=interpret,
    )
    if m_pad:
        y = y[:m]
    return y.reshape(*lead, n)


def measure_w8a8_mode(
    params: Params, batch: int = 8, repeats: int = 3, seq: int = 1
) -> str:
    """Measurement-driven w8a8 path selection (ADR in docs/PERFORMANCE.md).

    Times the XLA dynamic-quant path against both Pallas kernels (block-local
    fused quant, and pre-quantized int8-in) on THIS param tree's actual dense
    shapes at decode-like batch (``seq`` > 1 measures the PREFILL regime:
    M = batch*seq rows — the per-phase selection of
    ModelConfig.prefill_quant_mode), and returns the fastest ``quant_mode``
    ("w8a8", "w8a8_pallas", or "w8a8_pallas_pre"). Rationale: at decode
    sizes both paths stream the same int8 weight bytes from HBM — fusion can
    only match, not beat, the XLA path's bandwidth bound, and round-2
    on-chip measurement had the kernel ~19% behind (2102 vs 2580 tok/s,
    artifacts/bench_2026-07-30_r2.json) — so the shipped default for
    ``precision: int8_w8a8_auto`` is whatever wins on the deployed shapes,
    never an unmeasured path. Off-TPU this returns "w8a8" without measuring
    (interpret-mode timings are meaningless).
    """
    import time

    from edgemesh.utils.platform import device_sync, on_tpu

    if not on_tpu() or pl is None:
        return "w8a8"

    shapes: dict[tuple, tuple] = {}

    def walk(node):
        if isinstance(node, dict):
            if "kernel_q" in node:
                wq = node["kernel_q"]
                w = wq[0] if wq.ndim == 3 else wq
                s = node["scales"][0] if node["scales"].ndim == 2 else node["scales"]
                shapes.setdefault(tuple(w.shape), (w, s))
            else:
                for v in node.values():
                    walk(v)

    walk(params)
    if not shapes:
        return "w8a8"
    mats = list(shapes.values())
    xs = [
        jax.random.normal(
            jax.random.PRNGKey(0), (batch * seq, w.shape[0]), jnp.bfloat16
        )
        for w, _ in mats
    ]

    def run_xla(xs):
        return [int8_matmul_dynamic(x, w, s) for x, (w, s) in zip(xs, mats)]

    def run_pallas(xs):
        return [int8_matmul_fused(x, w, s) for x, (w, s) in zip(xs, mats)]

    def run_pallas_pre(xs):
        return [int8_matmul_prequant(x, w, s) for x, (w, s) in zip(xs, mats)]

    timings: dict[str, float] = {}
    for name, fn in (
        ("w8a8", run_xla),
        ("w8a8_pallas", run_pallas),
        ("w8a8_pallas_pre", run_pallas_pre),
    ):
        f = jax.jit(fn)
        device_sync(f(xs))  # compile + warm
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            device_sync(f(xs))
            best = min(best, time.perf_counter() - t0)
        timings[name] = best
    return min(timings, key=timings.__getitem__)


def int8_matmul_fused(
    x: jnp.ndarray,  # [..., K] activation
    w_q: jnp.ndarray,  # [K, N] int8
    scales: jnp.ndarray,  # [N]
    *,
    interpret: bool = False,
    check: bool = False,
) -> jnp.ndarray:
    """Model-facing entry for the fused Pallas w8a8 kernel.

    Handles what the raw kernel cannot: ND activations (collapsed to [M, K]),
    M padded up to the kernel's sublane tiling, and a tile-compatibility
    check — when K/N do not tile onto the MXU grid (or Pallas is
    unavailable), falls back to the XLA ``int8_matmul_dynamic`` path, which
    computes the same w8a8 contraction with whole-row activation scales.

    Numerics note: the kernel quantizes activations per (row, K-block) while
    the XLA path quantizes per whole row, so the two differ by normal int8
    rounding, not bit-exactly.

    ``check=True`` emits checkify contract asserts (positive finite scales,
    finite activations) — run through ops.checks.checked (§5.2).
    """
    if check:
        from edgemesh.ops.checks import check_int8_inputs

        check_int8_inputs(x, w_q, scales)
    *lead, k = x.shape
    n = w_q.shape[1]
    x2 = x.reshape(-1, k)
    m = x2.shape[0]
    # Large tiles cut HBM re-reads (each x row band is re-read N/tile_n
    # times, each w stripe M/tile_m times): measured on-chip at M=2048
    # (K=2048, N=8192), 128/128/512 tiles ran 22 TF vs 41 TF with
    # 128/512/2048 — within 10% of the XLA w8a8 path.
    tile_k, tile_n, pad_to = _select_tiles(m, k, n)
    if pl is None or tile_k is None or tile_n is None or m == 0:
        y = int8_matmul_dynamic(x2, w_q, scales)
        return y.reshape(*lead, n)
    m_pad = -m % pad_to
    if m_pad:
        x2 = jnp.pad(x2, ((0, m_pad), (0, 0)))
    tile_m = min(128, x2.shape[0])
    y = pallas_int8_matmul(
        x2, w_q, scales, tile_m=tile_m, tile_n=tile_n, tile_k=tile_k,
        interpret=interpret,
    )
    if m_pad:
        y = y[:m]
    return y.reshape(*lead, n)
