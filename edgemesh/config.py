"""Dataclass configuration system: YAML file + CLI mirror with explicit-None merge.

Capability parity notes (reference = parthabp55/LLM-for-Distributed-Egde-Devices):

- The reference replicates a YAML-load + per-key argparse override block in every
  runner (``Code/C-DAC Server/combiner_fp.py:380-410``). It has two override
  idioms: the correct ``if args.x is not None`` merge (combiner_fp.py:404-410)
  and a buggy ``args.x or cfg[x]`` variant that silently drops falsy CLI values
  (``Code/Base Models/Llama_bf16_updated.py:154-161``). edgemesh keeps ONLY the
  ``is not None`` semantics, implemented once.
- The reference's sampling knob set (max_new_tokens / temperature / top_k /
  top_p / repetition_penalty, ``Code/C-DAC Server/config_2.yaml:11-14``) is
  preserved verbatim in :class:`SamplingParams`.
- The reference hardcodes three roles (phi / pythia / refiner + an embedder,
  combiner_fp.py:413-421); edgemesh generalizes them to a list of
  :class:`AgentSpec`.
- New (TPU-native, no reference analog): :class:`MeshSpec` — the
  ``jax.sharding.Mesh`` axis sizes that replace the reference's static-IP
  Jetson cluster map (``Code/gRPC/README.md:9-14``).
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

import yaml

# ---------------------------------------------------------------------------
# Leaf config dataclasses
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SamplingParams:
    """Generation knobs — the exact set the reference exposes in YAML+CLI.

    Frozen (hashable) so a SamplingParams can be a jit static argument: the
    decode loop specializes on it at trace time and the knobs cost nothing at
    runtime.
    """

    max_new_tokens: int = 100
    temperature: float = 0.7
    top_k: int = 50
    top_p: float = 0.9
    # min-p filtering (arXiv:2407.01082): drop tokens whose probability is
    # below min_p x the top token's probability — a confidence-relative
    # cutoff that adapts where fixed top-k/top-p over- or under-prune.
    # 0 disables (the reference predates the technique).
    min_p: float = 0.0
    repetition_penalty: float = 1.2
    do_sample: bool = True
    seed: int = 0
    # Opt-in: use lax.approx_max_k (the TPU-native MIPS op, recall ~0.95 at
    # k=50) instead of exact lax.top_k's sort-based lowering for the
    # candidate-set fast path. The kept set can differ from HF's exact
    # top-k in the recall tail, so OFF by default — a throughput dial for
    # serving where exact HF parity is not required.
    approx_top_k: bool = False

    def __post_init__(self):
        if not 0.0 <= self.min_p <= 1.0:
            # min_p > 1 would mask even the argmax: every row goes NEG_INF
            # and categorical degrades to a uniform draw over the vocab —
            # silent garbage, so fail fast (HF's MinPLogitsWarper does too).
            raise ValueError(f"min_p must be in [0, 1], got {self.min_p}")

    def greedy(self) -> "SamplingParams":
        return dataclasses.replace(self, do_sample=False)


@dataclass
class ModelSpec:
    """One model to materialize on the mesh.

    ``family`` selects the architecture dialect (llama / neox / phi2 / mistral /
    qwen2 / gemma / gemma2 / phi3); ``auto``
    sniffs it from the checkpoint's HF config.json. ``precision`` mirrors the
    reference's base-vs-quant runner pairs (fp16/bf16 loaders in
    ``Code/Base Models``, int8 in ``Code/Quantised Models``).
    """

    path: str = ""
    # HF hub id for `edgemesh download --src <hub-cache>` materialization
    # (e.g. "microsoft/phi-2"); defaults to the basename of ``path``.
    hub_id: str = ""
    family: str = "auto"  # auto | llama | neox | phi2 | mistral | mixtral | qwen2 | qwen3 | gemma | gemma2 | phi3 | falcon | gpt2
    # bf16 | fp16 | fp32 | int8 (weight-only w8a16) | int8_w8a8 (dynamic
    # activation quant, int8xint8 MXU) | int8_w8a8_pallas (fused kernel) |
    # int8_w8a8_pallas_pre (activations pre-quantized in XLA, int8-in
    # kernel) | int8_w8a8_auto (measure the w8a8 paths on this model's
    # shapes at build and run the winner — ops/int8.measure_w8a8_mode)
    precision: str = "bf16"
    # Architecture overrides for synthetic (random-init) models; ignored when
    # loading a real checkpoint.
    vocab_size: int | None = None
    num_layers: int | None = None
    hidden_size: int | None = None
    num_heads: int | None = None
    num_kv_heads: int | None = None
    intermediate_size: int | None = None
    max_seq_len: int | None = None
    # Sliding-window attention (Mistral); None = family/checkpoint default.
    sliding_window: int | None = None
    # Routed-MoE dials for synthetic models (mixtral family or any preset
    # with experts); real checkpoints read num_local_experts /
    # num_experts_per_tok from config.json and ignore these.
    num_experts: int | None = None
    experts_per_token: int | None = None
    # Int4 scale granularity: 0 = per-channel (fastest), g>0 = grouped
    # (GPTQ/AWQ-style quality remedy; must be even). See ops/int4.py.
    int4_group_size: int = 64
    # Load finetuned weights from an `edgemesh train` checkpoint directory
    # (train.checkpoint_dir): the latest step's params replace the
    # synthetic/HF init BEFORE any precision transform, so int8/int4 rows
    # quantize the TRAINED weights. Architecture fields must match the
    # training run's model spec.
    train_checkpoint: str = ""
    # LoRA finetuning (ops/lora.py). rank > 0 switches `edgemesh train` to
    # adapter training (base frozen, checkpoints hold only the adapters) and
    # tells inference restore to rebuild + MERGE the adapters from
    # ``train_checkpoint`` before any precision transform. alpha/targets
    # must match between the training run and the serving spec.
    lora_rank: int = 0
    lora_alpha: float = 16.0
    # Comma-separated dense projections to adapt (q/k/v/o/gate/up/down).
    lora_targets: str = "q,k,v,o"
    # Path to a FULL train checkpoint (an `edgemesh train` run with
    # lora_rank 0) restored as the FROZEN BASE before anything else — the
    # LoRA-finetune-a-trained-model flow: train with lora_rank > 0 +
    # lora_base to adapt that model, then serve with the same lora_base +
    # train_checkpoint pointed at the ADAPTER run. Without it, adapters
    # train/merge over the spec's synthetic or HF init.
    lora_base: str = ""
    # SmoothQuant calibration for int8 precisions: path to a text file of
    # calibration prompts (one per line). When set, quantization smooths
    # activation outliers into the weights using these prompts' statistics
    # (ops/smoothquant.py). Empty = plain quantization.
    calibration: str = ""
    # Quantize the token embedding to int8 alongside int8/int4 precisions
    # (ops/int8.quantize_embedding). With tied embeddings the LM head reads
    # the whole table every decode step, so this halves that stream; off by
    # default nowhere that matters — set False to keep the reference's exact
    # nn.Linear-only quantization boundary (try.py:205).
    quantize_embed: bool = True


@dataclass
class AgentSpec:
    """Role → model binding in the multi-agent ensemble.

    Generalizes the reference's fixed phi/pythia/refiner trio
    (combiner_fp.py:413-418). ``role`` is free-form; the orchestrator treats
    ``refiner`` specially (it merges the other agents' answers, mirroring
    refine_summary, combiner_fp.py:355-377).
    """

    role: str = "qa"
    model: ModelSpec = field(default_factory=ModelSpec)
    sampling: SamplingParams = field(default_factory=SamplingParams)
    # "" means "unset": the orchestrator resolves a role-appropriate default
    # (QA vs refiner). Any non-empty string is used verbatim.
    prompt_template: str = ""
    # Optional draft model for speculative decoding (runtime/speculative.py):
    # must share the main model's tokenizer/vocab. None = plain decode.
    draft: ModelSpec | None = None
    # Draft tokens proposed per verify chunk when ``draft`` is set.
    spec_gamma: int = 4


@dataclass
class MeshSpec:
    """Device-mesh axis sizes: the TPU-native replacement for the reference's
    per-device gRPC stub map. Axes: data / model(tensor) / pipeline / sequence.
    """

    dp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.tp * self.pp * self.sp


@dataclass
class EvalSpec:
    """Evaluation harness settings (reference L5; combiner_fp.py:429-474)."""

    # Resolution order (first hit wins): this field if non-empty, else the
    # EDGEMESH_DATASET env var, else the known local snapshot locations.
    # Empty default keeps the config portable across machines instead of
    # baking one host's filesystem layout into the dataclass.
    dataset_path: str = ""
    dataset_split: str = "train[:1000]"
    num_samples: int = 1000
    batch_size: int = 1
    output_jsonl: str = "results.jsonl"
    resume: bool = True
    metrics: list[str] = field(
        default_factory=lambda: [
            "rouge1", "rouge2", "rougeL", "avg_rouge",
            "bleu", "cosine", "confidence", "bertscore", "tps",
        ]
    )


@dataclass
class TrainSpec:
    """Finetuning loop settings (edgemesh.training.run_training).

    The reference never started finetuning (its roadmap's "After Finetuning"
    rows are empty — SURVEY.md §7 out-of-scope note); edgemesh ships the
    loop so the framework is complete on TPU terms: same model code, mesh
    shardings from MeshSpec, optax adamw, rotating orbax checkpoints."""

    steps: int = 100
    batch_size: int = 8
    seq_len: int = 128
    lr: float = 1e-4
    weight_decay: float = 0.01
    # Train-split selection over the QA corpus: skip the first
    # ``skip_samples`` rows, then take ``num_samples`` (0 = the rest).
    # Disjoint splits per model are the complementary-knowledge setup of
    # the quality experiment (docs/QUALITY.md).
    num_samples: int = 0
    skip_samples: int = 0
    # Alternate corpus: a JSONL of {"text": ...} rows trained as plain LM
    # sequences instead of the QA CSV's Question/Answer format. Used e.g.
    # to train a refiner on refiner-formatted prompts built from the QA
    # models' own drafts (docs/QUALITY.md stage 2). Split selection above
    # applies to these rows too.
    corpus_jsonl: str = ""
    # "" disables checkpointing; otherwise rotating step checkpoints land
    # here and a rerun resumes from the latest.
    checkpoint_dir: str = ""
    checkpoint_every: int = 50
    log_every: int = 10
    resume: bool = True


@dataclass
class EdgeMeshConfig:
    """Top-level run config."""

    agents: list[AgentSpec] = field(default_factory=list)
    mesh: MeshSpec = field(default_factory=MeshSpec)
    eval: EvalSpec = field(default_factory=EvalSpec)
    train: TrainSpec = field(default_factory=TrainSpec)
    # Embedder for the cosine/bertscore metrics: "" = deterministic hashing
    # fallback; "synthetic" = pinned tiny model through the JAX stack;
    # a path = ingested HF checkpoint (MiniLM-analog). eval/embedder.py.
    embedder: str = ""
    log_level: str = "INFO"
    seed: int = 0


# ---------------------------------------------------------------------------
# YAML <-> dataclass plumbing
# ---------------------------------------------------------------------------


# Nested-dataclass fields, dispatched by name (annotations are strings under
# `from __future__ import annotations`, so name dispatch is the reliable path;
# add an entry when adding a nested spec field).
_NESTED_FIELDS: dict[str, type] = {}


def _from_dict(cls, data: dict[str, Any]):
    """Recursively build a dataclass from a plain dict; unknown keys raise."""
    if not dataclasses.is_dataclass(cls):
        return data
    kwargs: dict[str, Any] = {}
    hints = {f.name: f for f in dataclasses.fields(cls)}
    for key, value in (data or {}).items():
        if key not in hints:
            raise KeyError(f"unknown config key {key!r} for {cls.__name__}")
        if key == "agents":
            kwargs[key] = [_from_dict(AgentSpec, v) for v in value]
        elif key in _NESTED_FIELDS and isinstance(value, dict):
            kwargs[key] = _from_dict(_NESTED_FIELDS[key], value)
        else:
            kwargs[key] = value
    return cls(**kwargs)


_NESTED_FIELDS.update(
    model=ModelSpec, sampling=SamplingParams, mesh=MeshSpec, eval=EvalSpec,
    draft=ModelSpec, train=TrainSpec,
)


def to_dict(cfg) -> dict[str, Any]:
    return dataclasses.asdict(cfg)


def _flatten(d: dict[str, Any], prefix: str = "") -> dict[str, Any]:
    out: dict[str, Any] = {}
    for k, v in d.items():
        key = f"{prefix}.{k}" if prefix else k
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def _set_dotted(cfg, dotted: str, value: Any) -> None:
    parts = dotted.split(".")
    obj = cfg
    for p in parts[:-1]:
        obj = getattr(obj, p)
    leaf = parts[-1]
    current = getattr(obj, leaf)
    if current is not None and value is not None:
        value = type(current)(value) if not isinstance(value, type(current)) else value
    # object.__setattr__ so overrides also reach frozen leaves (SamplingParams).
    object.__setattr__(obj, leaf, value)


def load_config(path: str | Path | None = None, overrides: dict[str, Any] | None = None) -> EdgeMeshConfig:
    """Load YAML (optional) and apply dotted-key overrides with ``is not None``
    merge semantics (the correct reference idiom, combiner_fp.py:404-410)."""
    cfg = EdgeMeshConfig()
    if path is not None:
        with open(path) as f:
            raw = yaml.safe_load(f) or {}
        cfg = _from_dict(EdgeMeshConfig, raw)
    for key, value in (overrides or {}).items():
        if value is not None:  # None == "not given on CLI" → keep YAML value
            _set_dotted(cfg, key, value)
    return cfg


def build_arg_parser() -> argparse.ArgumentParser:
    """CLI mirror of every scalar config key, as dotted flags.

    The reference re-declares ~15 argparse flags in each of its eight runner
    mains (combiner_fp.py:381-396); here the parser is generated from the
    dataclass tree once.
    """
    parser = argparse.ArgumentParser(prog="edgemesh")
    parser.add_argument("--config", type=str, default=None, help="YAML config path")
    flat = _flatten(to_dict(EdgeMeshConfig()))
    for key, default in flat.items():
        if key.startswith("agents."):
            continue  # list-valued; configure agents via YAML
        argtype = type(default) if default is not None else str
        if argtype is bool:
            parser.add_argument(f"--{key}", type=lambda s: s.lower() in ("1", "true", "yes"), default=None)
        elif argtype is list:
            continue
        else:
            parser.add_argument(f"--{key}", type=argtype, default=None)
    return parser


def config_from_cli(argv: list[str] | None = None) -> EdgeMeshConfig:
    parser = build_arg_parser()
    args, _ = parser.parse_known_args(argv)
    overrides = {k: v for k, v in vars(args).items() if k != "config"}
    return load_config(args.config, overrides)
