"""Quality metrics, implemented from scratch in numpy/python (no network, no
GPU): ROUGE-1/2/L, BLEU, embedding cosine similarity, BERTScore-style greedy
token matching, and helpers for confidence / tokens-per-sec.

Parity map against the reference's metric suite (C9, SURVEY.md §2.1 — the same
~40 lines appear in every runner, e.g. ``Code/C-DAC Server/combiner_fp.py:288-325``):

- ``evaluate_rouge``/``mean_rouge`` (rouge_score pkg)  → :func:`rouge_scores`
- ``evaluate_bleu`` (HF evaluate "bleu")               → :func:`bleu`
- ``cosine_similarity`` (sentence-transformers)        → :func:`cosine_similarity`
  over any embedder callable; :class:`HashingEmbedder` is the no-download
  fallback.
- ``evaluate_bertscore`` (bert-score pkg)              → :func:`bertscore`
  (greedy max-sim token matching, Zhang et al. 2020) over any token-embedding
  callable.
- ``confidence_score`` (mean per-token max softmax)    → computed inside the
  decode loop (edgemesh/runtime/generate.py) — no second forward pass.
- tokens/sec → ``GenerateResult.tokens_per_sec`` (generated-only convention,
  combiner_fp.py:349).

ROUGE follows the rouge_score package's definition (F1 of n-gram overlap /
LCS, with Porter stemming like its ``use_stemmer=True`` default in the
reference) so aggregate numbers are comparable to BASELINE.md Tables 1–2.
"""

from __future__ import annotations

import math
import re
from collections import Counter
from collections.abc import Callable, Sequence

import numpy as np

# ---------------------------------------------------------------------------
# Tokenization + Porter stemmer (compact standard implementation)
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"[a-z0-9]+")


def _porter_stem(w: str) -> str:
    """Compact Porter stemmer (1980 algorithm, steps 1a-5b)."""
    if len(w) <= 2:
        return w

    def cons(word, i):
        c = word[i]
        if c in "aeiou":
            return False
        if c == "y":
            return i == 0 or not cons(word, i - 1)
        return True

    def measure(stem):
        m, prev_vowel = 0, False
        for i in range(len(stem)):
            v = not cons(stem, i)
            if not v and prev_vowel:
                m += 1
            prev_vowel = v
        return m

    def has_vowel(stem):
        return any(not cons(stem, i) for i in range(len(stem)))

    def ends_double_cons(word):
        return len(word) >= 2 and word[-1] == word[-2] and cons(word, len(word) - 1)

    def cvc(word):
        if len(word) < 3:
            return False
        return (
            cons(word, len(word) - 3)
            and not cons(word, len(word) - 2)
            and cons(word, len(word) - 1)
            and word[-1] not in "wxy"
        )

    # Step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif w.endswith("ss"):
        pass
    elif w.endswith("s"):
        w = w[:-1]
    # Step 1b
    flag = False
    if w.endswith("eed"):
        if measure(w[:-3]) > 0:
            w = w[:-1]
    elif w.endswith("ed") and has_vowel(w[:-2]):
        w, flag = w[:-2], True
    elif w.endswith("ing") and has_vowel(w[:-3]):
        w, flag = w[:-3], True
    if flag:
        if w.endswith(("at", "bl", "iz")):
            w += "e"
        elif ends_double_cons(w) and not w.endswith(("l", "s", "z")):
            w = w[:-1]
        elif measure(w) == 1 and cvc(w):
            w += "e"
    # Step 1c
    if w.endswith("y") and has_vowel(w[:-1]):
        w = w[:-1] + "i"
    # Step 2
    for suf, rep in (
        ("ational", "ate"), ("tional", "tion"), ("enci", "ence"), ("anci", "ance"),
        ("izer", "ize"), ("abli", "able"), ("alli", "al"), ("entli", "ent"),
        ("eli", "e"), ("ousli", "ous"), ("ization", "ize"), ("ation", "ate"),
        ("ator", "ate"), ("alism", "al"), ("iveness", "ive"), ("fulness", "ful"),
        ("ousness", "ous"), ("aliti", "al"), ("iviti", "ive"), ("biliti", "ble"),
    ):
        if w.endswith(suf):
            if measure(w[: -len(suf)]) > 0:
                w = w[: -len(suf)] + rep
            break
    # Step 3
    for suf, rep in (
        ("icate", "ic"), ("ative", ""), ("alize", "al"), ("iciti", "ic"),
        ("ical", "ic"), ("ful", ""), ("ness", ""),
    ):
        if w.endswith(suf):
            if measure(w[: -len(suf)]) > 0:
                w = w[: -len(suf)] + rep
            break
    # Step 4
    for suf in (
        "al", "ance", "ence", "er", "ic", "able", "ible", "ant", "ement",
        "ment", "ent", "ou", "ism", "ate", "iti", "ous", "ive", "ize",
    ):
        if w.endswith(suf):
            stem = w[: -len(suf)]
            if measure(stem) > 1:
                w = stem
            break
    else:
        if w.endswith("ion") and measure(w[:-3]) > 1 and w[:-3].endswith(("s", "t")):
            w = w[:-3]
    # Step 5a
    if w.endswith("e"):
        stem = w[:-1]
        m = measure(stem)
        if m > 1 or (m == 1 and not cvc(stem)):
            w = stem
    # Step 5b
    if ends_double_cons(w) and w.endswith("l") and measure(w) > 1:
        w = w[:-1]
    return w


def tokenize(text: str, stem: bool = True) -> list[str]:
    toks = _TOKEN_RE.findall(text.lower())
    return [_porter_stem(t) for t in toks] if stem else toks


# ---------------------------------------------------------------------------
# ROUGE
# ---------------------------------------------------------------------------


def _f1(matches: float, pred_total: float, ref_total: float) -> float:
    if pred_total == 0 or ref_total == 0 or matches == 0:
        return 0.0
    p = matches / pred_total
    r = matches / ref_total
    return 2 * p * r / (p + r)


def _ngrams(tokens: Sequence[str], n: int) -> Counter:
    return Counter(tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1))


def _lcs_len(a: Sequence[str], b: Sequence[str]) -> int:
    if not a or not b:
        return 0
    prev = [0] * (len(b) + 1)
    for x in a:
        cur = [0]
        for j, y in enumerate(b, 1):
            cur.append(prev[j - 1] + 1 if x == y else max(prev[j], cur[-1]))
        prev = cur
    return prev[-1]


def rouge_scores(prediction: str, reference: str, stem: bool = True) -> dict[str, float]:
    """ROUGE-1/2/L F1 + their mean (the reference's ``mean_rouge``,
    combiner_fp.py:298-299)."""
    pred = tokenize(prediction, stem)
    ref = tokenize(reference, stem)
    out: dict[str, float] = {}
    for n, name in ((1, "rouge1"), (2, "rouge2")):
        pc, rc = _ngrams(pred, n), _ngrams(ref, n)
        matches = sum((pc & rc).values())
        out[name] = _f1(matches, max(sum(pc.values()), 0), max(sum(rc.values()), 0))
    lcs = _lcs_len(pred, ref)
    out["rougeL"] = _f1(lcs, len(pred), len(ref))
    out["avg_rouge"] = (out["rouge1"] + out["rouge2"] + out["rougeL"]) / 3
    return out


# ---------------------------------------------------------------------------
# BLEU (Papineni et al. 2002, matching HF evaluate's defaults: max_order=4,
# no smoothing — the reference's evaluate_bleu, combiner_fp.py:307-310)
# ---------------------------------------------------------------------------


def bleu(
    prediction: str,
    references: str | Sequence[str],
    max_order: int = 4,
    smooth: bool = False,
) -> float:
    if isinstance(references, str):
        references = [references]
    pred = tokenize(prediction, stem=False)
    refs = [tokenize(r, stem=False) for r in references]
    if not pred:
        return 0.0

    precisions = []
    for n in range(1, max_order + 1):
        pc = _ngrams(pred, n)
        max_ref: Counter = Counter()
        for r in refs:
            rc = _ngrams(r, n)
            for g, c in rc.items():
                max_ref[g] = max(max_ref[g], c)
        matches = sum(min(c, max_ref[g]) for g, c in pc.items())
        total = max(len(pred) - n + 1, 0)
        if smooth:
            precisions.append((matches + 1) / (total + 1))
        else:
            precisions.append(matches / total if total > 0 else 0.0)

    if min(precisions) <= 0:
        return 0.0
    log_avg = sum(math.log(p) for p in precisions) / max_order
    ref_len = min(refs, key=lambda r: abs(len(r) - len(pred)))
    bp = 1.0 if len(pred) > len(ref_len) else math.exp(1 - len(ref_len) / max(len(pred), 1))
    return bp * math.exp(log_avg)


# ---------------------------------------------------------------------------
# Embedding-based metrics
# ---------------------------------------------------------------------------

Embedder = Callable[[list[str]], np.ndarray]  # texts -> [n, d]
TokenEmbedder = Callable[[str], tuple[list[str], np.ndarray]]  # text -> (tokens, [t, d])


class HashingEmbedder:
    """Deterministic no-download embedder: L2-normalized char-ngram hashing TF
    vectors. Stands in for the reference's sentence-transformers MiniLM
    (combiner_fp.py:421) when no local model is available; any callable with
    the same signature (e.g. a JAX/torch encoder) drops in."""

    def __init__(self, dim: int = 512, ngram: tuple[int, int] = (3, 5)):
        self.dim = dim
        self.ngram = ngram

    def _vector(self, text: str) -> np.ndarray:
        # crc32, not builtin hash(): stable across processes (PYTHONHASHSEED).
        from zlib import crc32

        v = np.zeros(self.dim, dtype=np.float64)
        s = " ".join(tokenize(text, stem=False))
        for n in range(self.ngram[0], self.ngram[1] + 1):
            for i in range(len(s) - n + 1):
                v[crc32(s[i : i + n].encode()) % self.dim] += 1.0
        norm = np.linalg.norm(v)
        return v / norm if norm > 0 else v

    def __call__(self, texts: list[str]) -> np.ndarray:
        return np.stack([self._vector(t) for t in texts])

    def embed_tokens(self, text: str) -> tuple[list[str], np.ndarray]:
        toks = tokenize(text, stem=False)
        if not toks:
            return [], np.zeros((0, self.dim))
        return toks, np.stack([self._vector(t) for t in toks])


def cosine_similarity(
    prediction: str, reference: str, embedder: Embedder | None = None
) -> float:
    embedder = embedder or HashingEmbedder()
    vecs = embedder([prediction, reference])
    a, b = vecs[0], vecs[1]
    denom = np.linalg.norm(a) * np.linalg.norm(b)
    if denom == 0:
        return 0.0
    return float(np.dot(a, b) / denom)


def bertscore(
    prediction: str,
    reference: str,
    token_embedder: TokenEmbedder | None = None,
) -> dict[str, float]:
    """BERTScore-style greedy matching (Zhang et al., ICLR 2020): recall =
    mean over reference tokens of max cosine sim to any candidate token;
    precision symmetric; F1 harmonic mean. The reference calls the bert-score
    package with a roberta model (combiner_fp.py:302-305); here the contextual
    encoder is pluggable and defaults to the hashing embedder."""
    token_embedder = token_embedder or HashingEmbedder().embed_tokens
    _, pe = token_embedder(prediction)
    _, re_ = token_embedder(reference)
    if pe.shape[0] == 0 or re_.shape[0] == 0:
        return {"precision": 0.0, "recall": 0.0, "f1": 0.0}
    pe = pe / np.clip(np.linalg.norm(pe, axis=1, keepdims=True), 1e-9, None)
    re_ = re_ / np.clip(np.linalg.norm(re_, axis=1, keepdims=True), 1e-9, None)
    sim = pe @ re_.T  # [p, r]
    precision = float(np.mean(np.max(sim, axis=1)))
    recall = float(np.mean(np.max(sim, axis=0)))
    f1 = 0.0 if precision + recall == 0 else 2 * precision * recall / (precision + recall)
    return {"precision": precision, "recall": recall, "f1": f1}
