"""Per-sample evaluation loop with zero-fill error policy, JSONL persistence,
resume, and aggregate report.

Mirrors the reference's L5 loop (``combiner_fp.py:429-474``) with the two
upgrades SURVEY.md §5.4 calls for: per-sample results are persisted
incrementally (an interrupted 1,000-sample run resumes instead of restarting
from zero — the reference restarts) and the error policy (metric failure →
zero-filled row, run continues; combiner_fp.py:448-454) is explicit instead of
a bare ``except:``.
"""

from __future__ import annotations

import json
import logging
import time
from collections.abc import Callable
from pathlib import Path
from typing import Any

import numpy as np

from edgemesh.eval.data import QASample
from edgemesh.eval.metrics import (
    HashingEmbedder,
    bertscore,
    bleu,
    cosine_similarity,
    rouge_scores,
)

log = logging.getLogger("edgemesh.eval")

# answer_fn: question -> dict with at least {"answer": str}; optionally
# {"tps": float, "confidence": float, "ttft_s": float, ...} merged into the row.
AnswerFn = Callable[[str], dict[str, Any]]

METRIC_KEYS = [
    "rouge1", "rouge2", "rougeL", "avg_rouge",
    "bertscore", "bleu", "cosine", "confidence", "tps",
]


def _validate_metrics(metrics: list[str] | None) -> None:
    """Reject typo'd metric names. Called at run_eval ENTRY (outside the
    per-sample zero-fill try/except — a bad name must fail fast, not burn
    1000 generate calls producing all-zero rows) and in score_sample for
    direct callers."""
    if metrics is None:
        return
    unknown = set(metrics) - set(METRIC_KEYS)
    if unknown:
        raise ValueError(f"unknown metrics {sorted(unknown)}; choose from {METRIC_KEYS}")


def score_sample(
    prediction: str, reference: str, embedder=None, metrics: list[str] | None = None
) -> dict[str, float]:
    """Score one prediction. ``metrics`` (None = all) selects which metric
    families actually run, so e.g. dropping bertscore/cosine skips the
    embedding work entirely."""
    _validate_metrics(metrics)
    want = set(metrics) if metrics is not None else set(METRIC_KEYS)
    embedder = embedder or _default_embedder()
    row: dict[str, float] = {}
    if want & {"rouge1", "rouge2", "rougeL", "avg_rouge"}:
        row.update(rouge_scores(prediction, reference))
    if "bleu" in want:
        row["bleu"] = bleu(prediction, reference)
    if "cosine" in want:
        row["cosine"] = cosine_similarity(prediction, reference, embedder)
    if "bertscore" in want:
        row["bertscore"] = bertscore(
            prediction, reference, getattr(embedder, "embed_tokens", None)
        )["f1"]
    return row


_EMBEDDER = None


def _default_embedder():
    global _EMBEDDER
    if _EMBEDDER is None:
        _EMBEDDER = HashingEmbedder()
    return _EMBEDDER


def _load_done(jsonl_path: Path) -> dict[int, dict]:
    done: dict[int, dict] = {}
    if jsonl_path.exists():
        with open(jsonl_path) as f:
            for line in f:
                line = line.strip()
                if line:
                    row = json.loads(line)
                    done[row["index"]] = row
    return done


def run_eval(
    samples: list[QASample],
    answer_fn: AnswerFn,
    output_jsonl: str | Path = "results.jsonl",
    resume: bool = True,
    embedder=None,
    log_every: int = 25,
    metrics: list[str] | None = None,
    answer_batch_fn=None,  # list[str] -> list[dict]; enables batch_size > 1
    batch_size: int = 1,
) -> dict[str, float]:
    """Evaluate ``answer_fn`` over ``samples``; returns the aggregate-mean
    report (the analog of the reference's final np.mean block,
    combiner_fp.py:465-474).

    Resume only reuses a persisted row when its question matches the current
    sample (a results.jsonl left over from a DIFFERENT dataset/run is
    re-answered, not silently merged), and the report aggregates exactly the
    rows of THIS sample list.

    With ``answer_batch_fn`` and ``batch_size > 1``, pending samples are
    answered ``batch_size`` at a time in one batched generate (decode is
    HBM-bound, so the whole batch costs barely more than one sample);
    scoring, persistence order, resume, and the zero-fill policy are
    unchanged (a failed batch call zero-fills exactly its samples).
    """
    _validate_metrics(metrics)  # fail fast — not inside the zero-fill loop
    out_path = Path(output_jsonl)
    done = _load_done(out_path) if resume else {}
    # A persisted row is reusable only if it is for the SAME question, is not
    # a zero-filled error row (transient failures get retried on resume), and
    # was scored with at least the metrics requested now.
    want_scored = (set(metrics) if metrics is not None else set(METRIC_KEYS)) & {
        "rouge1", "rouge2", "rougeL", "avg_rouge", "bleu", "cosine", "bertscore"
    }
    usable = {
        s.index
        for s in samples
        if s.index in done
        and done[s.index].get("question") == s.question
        and "error" not in done[s.index]
        and "answer" in done[s.index]
    }
    reused = {i for i in usable if want_scored <= set(done[i])}
    # Rows whose answer is valid but were scored with FEWER metrics than now
    # requested: re-score the persisted answer — never re-run the model (the
    # expensive step) just to add a metric column.
    rescore = usable - reused
    stale = sum(1 for s in samples if s.index in done and s.index not in usable)
    if stale:
        log.warning("%d persisted rows are unusable (mismatched question or "
                    "error row) and will be re-answered", stale)
    if rescore:
        log.info("resuming: %d persisted answers re-scored for newly requested "
                 "metrics (no regeneration)", len(rescore))
    if reused:
        log.info("resuming: %d/%d samples already scored", len(reused), len(samples))

    t_start = time.perf_counter()
    rows: dict[int, dict] = {i: done[i] for i in reused}
    n_scored = len(rows)
    use_batch = answer_batch_fn is not None and batch_size > 1

    with open(out_path, "a" if resume else "w") as sink:

        def emit(row: dict) -> None:
            nonlocal n_scored
            sink.write(json.dumps(row) + "\n")
            sink.flush()
            rows[row["index"]] = row
            n_scored += 1
            if (n_scored % log_every) == 0:
                log.info("scored %d/%d", n_scored, len(samples))

        def score_and_emit(sample: QASample, result: dict | None, error=None) -> None:
            row: dict[str, Any] = {"index": sample.index, "question": sample.question}
            try:
                if error is not None:
                    raise error
                row["answer"] = result.get("answer", "")
                for k in ("tps", "confidence", "ttft_s", "batch_size", "compiled"):
                    if k in result:
                        row[k] = result[k]
                row.update(
                    {
                        k: v
                        for k, v in score_sample(
                            row["answer"], sample.answer, embedder, metrics
                        ).items()
                        if k not in row
                    }
                )
            except Exception as exc:  # zero-fill policy (combiner_fp.py:448-454)
                log.warning("sample %d failed: %s", sample.index, exc)
                row.update({k: 0.0 for k in (metrics or METRIC_KEYS)})
                row.setdefault("answer", "")
                row["error"] = str(exc)
            emit(row)

        pending: list[QASample] = []

        def flush_pending() -> None:
            if not pending:
                return
            batch = list(pending)
            pending.clear()
            try:
                results = answer_batch_fn([s.question for s in batch])
                if len(results) != len(batch):
                    raise RuntimeError(
                        f"answer_batch returned {len(results)} results "
                        f"for {len(batch)} questions"
                    )
            except Exception as exc:  # zero-fill exactly this batch
                for s in batch:
                    score_and_emit(s, None, error=exc)
                return
            for s, result in zip(batch, results):
                score_and_emit(s, result)

        for sample in samples:
            if sample.index in reused:
                continue
            if sample.index in rescore:
                row = dict(done[sample.index])
                try:
                    row.update(score_sample(row["answer"], sample.answer, embedder, metrics))
                except Exception as exc:  # zero-fill policy: combiner_fp.py:448-454
                    log.warning("rescore failed on sample %d: %s", sample.index, exc)
                    row.update({m: 0.0 for m in (metrics or METRIC_KEYS) if m not in row})
                    row["error"] = str(exc)
                emit(row)
                continue
            if use_batch:
                pending.append(sample)
                if len(pending) >= batch_size:
                    flush_pending()
                continue
            try:
                result = answer_fn(sample.question)
                score_and_emit(sample, result)
            except Exception as exc:
                score_and_emit(sample, None, error=exc)
        flush_pending()

    report = aggregate(list(rows.values()))
    report["wall_time_s"] = time.perf_counter() - t_start
    report["num_samples"] = len(rows)
    return report


def aggregate(rows: list[dict]) -> dict[str, float]:
    """Mean of every metric column (the reference's np.mean block,
    combiner_fp.py:465-474) plus p50/p95 latency percentiles for the
    throughput columns — the BASELINE.json latency metric is p50 TTFT, which
    a bare mean can't report.

    Latency percentiles cover STEADY-STATE rows only: calls whose measured
    window included an XLA compile (the agent flags them ``compiled``) are
    excluded and reported separately as ``ttft_s_compile_max`` /
    ``num_compile_rows`` — otherwise segment-initial compiles masquerade as
    a serving tail (round-2 flagship artifact: p95 6.7s vs p50 0.09s, all
    of it compile time). If every row compiled (tiny smoke runs), the full
    pool is used so percentiles don't vanish."""
    report: dict[str, float] = {}
    for key in METRIC_KEYS:
        vals = [r[key] for r in rows if key in r and r[key] is not None]
        if vals:
            report[key] = float(np.mean(vals))
    steady = [r for r in rows if not r.get("compiled")]
    pool = steady or rows
    for key in ("tps", "ttft_s"):
        vals = [r[key] for r in pool if key in r and r[key] is not None]
        if vals:
            report[f"{key}_p50"] = float(np.percentile(vals, 50))
            report[f"{key}_p95"] = float(np.percentile(vals, 95))
    compile_ttfts = [r["ttft_s"] for r in rows
                     if r.get("compiled") and r.get("ttft_s") is not None]
    if compile_ttfts:
        report["ttft_s_compile_max"] = float(max(compile_ttfts))
        report["num_compile_rows"] = float(len(compile_ttfts))
    return report
