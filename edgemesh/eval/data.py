"""Dataset loading for the golden-metric harness.

The reference evaluates on a fixed 1,000-pair Natural Questions snapshot,
loaded either via HF datasets (``combiner_fp.py:413``) or raw CSV
(``try.py:292``). Here the CSV path is primary (no network): columns
``query,answer``, as in ``Code/Dataset/natural_questions_1000.csv``.
"""

from __future__ import annotations

import csv
import os
from dataclasses import dataclass
from pathlib import Path

# Known snapshot locations, probed in order when no explicit path is given
# (reference layout first: Code/Dataset/natural_questions_1000.csv and its
# byte-identical C-DAC Server copy).
_DEFAULT_DATASET_CANDIDATES = (
    "data/natural_questions_1000.csv",
    "/root/reference/Code/Dataset/natural_questions_1000.csv",
    "/root/reference/Code/C-DAC Server/natural_questions_1000.csv",
)


def resolve_dataset_path(configured: str = "") -> str:
    """Resolve the eval CSV: explicit config wins, then $EDGEMESH_DATASET,
    then the known local snapshot locations."""
    for cand in (configured, os.environ.get("EDGEMESH_DATASET", "")):
        if cand:
            return cand
    for cand in _DEFAULT_DATASET_CANDIDATES:
        if Path(cand).exists():
            return cand
    raise FileNotFoundError(
        "no QA dataset found: set eval.dataset_path, $EDGEMESH_DATASET, or "
        f"place the CSV at one of {_DEFAULT_DATASET_CANDIDATES}"
    )


@dataclass
class QASample:
    index: int
    question: str
    answer: str


def load_qa_csv(path: str | Path, limit: int | None = None) -> list[QASample]:
    """Load query/answer pairs; native C++ parser when built, stdlib fallback
    (both RFC 4180 — parity covered by tests/test_native.py)."""
    try:
        return _load_qa_csv_native(path, limit)
    except (RuntimeError, FileNotFoundError):
        pass
    return _load_qa_csv_py(path, limit)


def _load_qa_csv_native(path: str | Path, limit: int | None) -> list[QASample]:
    from edgemesh.runtime.native import NativeCSV

    table = NativeCSV(path)  # raises RuntimeError when the lib is unavailable
    try:
        header = [h.lower() for h in table.header()]
        qcol = next((i for i, h in enumerate(header) if h in ("query", "question")), None)
        acol = next((i for i, h in enumerate(header) if h in ("answer", "answers")), None)
        if qcol is None or acol is None:
            raise ValueError(f"expected query/answer columns, got {header}")
        samples = []
        for r in range(1, table.num_rows):
            ncols = table.num_cols(r)
            if ncols == 0:  # blank line (csv.reader's [] row) — skip like DictReader
                continue
            if limit is not None and len(samples) >= limit:
                break
            q = table.cell(r, qcol) if qcol < ncols else ""
            a = table.cell(r, acol) if acol < ncols else ""
            samples.append(QASample(len(samples), q, a))
        return samples
    finally:
        table.close()


def _load_qa_csv_py(path: str | Path, limit: int | None = None) -> list[QASample]:
    samples: list[QASample] = []
    with open(path, newline="", encoding="utf-8") as f:
        reader = csv.DictReader(f)
        cols = {c.lower(): c for c in reader.fieldnames or []}
        qcol = cols.get("query") or cols.get("question")
        acol = cols.get("answer") or cols.get("answers")
        if not qcol or not acol:
            raise ValueError(f"expected query/answer columns, got {reader.fieldnames}")
        for i, row in enumerate(reader):
            if limit is not None and i >= limit:
                break
            samples.append(QASample(i, row[qcol], row[acol]))
    return samples


def load_qa(
    path: str | Path, split: str = "train", limit: int | None = None
) -> list[QASample]:
    """Unified loader for both of the reference's dataset dialects: raw CSV
    (``try.py:292``) and HF datasets (``combiner_fp.py:413``). A ``.csv``
    path takes the native/stdlib CSV parser; anything else — a
    ``save_to_disk`` directory or a locally-cached hub id like
    ``sentence-transformers/natural-questions`` — goes through HF datasets
    in OFFLINE mode (this environment has no egress; a cache miss raises
    rather than dials out)."""
    if str(path).endswith(".csv"):
        return load_qa_csv(path, limit)
    return load_qa_hf(path, split, limit)


def load_qa_hf(
    name_or_dir: str | Path, split: str = "train", limit: int | None = None
) -> list[QASample]:
    """HF-datasets loading from LOCAL storage only (combiner_fp.py:413
    parity — the reference calls load_dataset over the network; here
    HF_DATASETS_OFFLINE pins the lookup to the on-disk cache)."""
    import re

    os.environ.setdefault("HF_DATASETS_OFFLINE", "1")
    from datasets import load_dataset, load_from_disk

    p = Path(str(name_or_dir))
    if p.is_dir() and (
        (p / "dataset_info.json").exists() or (p / "dataset_dict.json").exists()
    ):
        # save_to_disk layout: apply the split's [a:b] slice OURSELVES so a
        # spec like "train[500:]" means the same rows here as it does on the
        # load_dataset branch (silently dropping it would eval wrong rows).
        m = re.fullmatch(r"(\w+)(?:\[(-?\d*):(-?\d*)\])?", split or "train")
        if m is None:
            raise ValueError(f"unsupported split spec {split!r} for a "
                             "save_to_disk dataset (use name[a:b])")
        base_split, start, stop = m.group(1), m.group(2), m.group(3)
        ds = load_from_disk(str(p))
        if not hasattr(ds, "features"):  # DatasetDict: pick the split
            ds = ds[base_split]
        if start or stop:
            idx = range(len(ds))[slice(int(start) if start else None,
                                       int(stop) if stop else None)]
            ds = ds.select(idx)
    else:
        ds = load_dataset(str(name_or_dir), split=split)
    cols = set(ds.column_names)
    qcol = next((c for c in ("query", "question") if c in cols), None)
    acol = "answer" if "answer" in cols else None
    if qcol is None or acol is None:
        raise ValueError(
            f"dataset {name_or_dir} needs query/question + answer columns, "
            f"got {sorted(cols)}"
        )
    n = len(ds) if limit is None else min(limit, len(ds))
    ds = ds.select(range(n))
    questions, answers = ds[qcol], ds[acol]  # bulk column reads (Arrow-fast)
    return [
        QASample(index=i, question=str(q), answer=str(a))
        for i, (q, a) in enumerate(zip(questions, answers))
    ]
