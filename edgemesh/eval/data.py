"""Dataset loading for the golden-metric harness.

The reference evaluates on a fixed 1,000-pair Natural Questions snapshot,
loaded either via HF datasets (``combiner_fp.py:413``) or raw CSV
(``try.py:292``). Here the CSV path is primary (no network): columns
``query,answer``, as in ``Code/Dataset/natural_questions_1000.csv``.
"""

from __future__ import annotations

import csv
from dataclasses import dataclass
from pathlib import Path


@dataclass
class QASample:
    index: int
    question: str
    answer: str


def load_qa_csv(path: str | Path, limit: int | None = None) -> list[QASample]:
    samples: list[QASample] = []
    with open(path, newline="", encoding="utf-8") as f:
        reader = csv.DictReader(f)
        cols = {c.lower(): c for c in reader.fieldnames or []}
        qcol = cols.get("query") or cols.get("question")
        acol = cols.get("answer") or cols.get("answers")
        if not qcol or not acol:
            raise ValueError(f"expected query/answer columns, got {reader.fieldnames}")
        for i, row in enumerate(reader):
            if limit is not None and i >= limit:
                break
            samples.append(QASample(i, row[qcol], row[acol]))
    return samples
