"""Model-based text embeddings through the edgemesh JAX stack.

The reference scores semantic metrics with two downloaded encoders: a
sentence-transformer MiniLM for cosine similarity (combiner_fp.py:312-316,
:421) and a roberta-backed BERTScore (:302-305). This module provides the
same capability through edgemesh's OWN model runtime — any ingested
checkpoint (or a pinned synthetic model) yields sentence vectors and
contextual token vectors from its final-norm hidden states
(models/transformer.forward_hidden). The deterministic HashingEmbedder
(eval/metrics.py) remains the explicit no-model fallback.

Pointing the config's ``embedder:`` at a bert-family checkpoint (MiniLM /
BERT / sentence-BERT — models/encoder.py, sniffed by model_type) hosts the
reference's actual encoder class, making cosine/BERTScore numerically
comparable to BASELINE.md Tables 1-2. Decoder checkpoints and the pinned
synthetic model also work but yield a RELATIVE signal only (same embedder
across all systems under eval, not MiniLM-comparable values).
"""

from __future__ import annotations

from typing import Any

import numpy as np

import jax.numpy as jnp


def _pad_bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class ModelEmbedder:
    """Sentence + token embeddings from any edgemesh model.

    Implements the metrics-suite embedder protocol:
    - ``__call__(texts) -> [n, d]`` L2-normalized sentence vectors
      (mean-pooled over valid positions);
    - ``embed_tokens(text) -> (tokens, [t, d])`` contextual per-token
      vectors for BERTScore greedy matching.

    Sequences pad to a small set of static buckets so jit compiles once per
    bucket, not per length (XLA static-shape discipline).
    """

    def __init__(
        self,
        cfg: Any,
        params: Any,
        tokenizer: Any,
        max_len: int = 128,
        buckets: tuple[int, ...] = (16, 32, 64, 128),
        forward_fn: Any = None,
    ):
        """``forward_fn(cfg, params, tokens, lengths) -> [b, s, d]`` defaults
        to the decoder's forward_hidden; the bert-family encoder passes its
        own (models/encoder.forward_hidden) — same protocol, bidirectional."""
        if forward_fn is None:
            from edgemesh.models.transformer import forward_hidden

            forward_fn = forward_hidden
        self.cfg = cfg
        self.params = params
        self.tokenizer = tokenizer
        self.max_len = min(max_len, cfg.max_seq_len)
        kept = tuple(b for b in buckets if b < self.max_len)
        # The top bucket is always exactly max_len, so no text the tokenizer
        # kept gets silently truncated by bucket rounding.
        self.buckets = kept + (self.max_len,)
        self._forward = forward_fn
        self.dim = cfg.hidden_size

    # -- internals ---------------------------------------------------------

    def _encode(self, text: str) -> list[int]:
        ids = self.tokenizer.encode(text, max_len=self.max_len)
        return ids if ids else [getattr(self.tokenizer, "pad_id", 0)]

    def _hidden(self, ids_batch: list[list[int]]) -> tuple[np.ndarray, np.ndarray]:
        """Returns (hidden [n, s, d] fp32, lengths [n])."""
        pad = getattr(self.tokenizer, "pad_id", 0)
        lengths = np.array([len(ids) for ids in ids_batch], np.int32)
        s = _pad_bucket(int(lengths.max()), self.buckets)
        tokens = np.full((len(ids_batch), s), pad, np.int32)
        for i, ids in enumerate(ids_batch):
            tokens[i, : min(len(ids), s)] = ids[:s]
        lengths = np.minimum(lengths, s)
        hid = self._forward(
            self.cfg, self.params, jnp.asarray(tokens), jnp.asarray(lengths)
        )
        return np.asarray(hid, np.float32), lengths

    # -- protocol ----------------------------------------------------------

    def __call__(self, texts: list[str]) -> np.ndarray:
        ids = [self._encode(t) for t in texts]
        hid, lengths = self._hidden(ids)
        s = hid.shape[1]
        mask = (np.arange(s)[None, :] < lengths[:, None]).astype(np.float32)
        pooled = (hid * mask[:, :, None]).sum(axis=1) / np.maximum(
            mask.sum(axis=1, keepdims=True), 1.0
        )
        norm = np.linalg.norm(pooled, axis=1, keepdims=True)
        return pooled / np.clip(norm, 1e-9, None)

    def embed_tokens(self, text: str) -> tuple[list[str], np.ndarray]:
        ids = self._encode(text)
        hid, lengths = self._hidden([ids])
        n = int(lengths[0])
        toks = [self.tokenizer.decode([i]) for i in ids[:n]]
        return toks, hid[0, :n]


def build_embedder(spec: str = "", max_len: int = 128):
    """Resolve the config's ``embedder`` key:

    - ""            → HashingEmbedder (deterministic no-model fallback)
    - "synthetic"   → ModelEmbedder over a pinned tiny random-init model
                      (stable across runs/processes; relative signal only)
    - anything else → ModelEmbedder over the HF checkpoint at that path;
                      bert-family checkpoints (MiniLM et al., sniffed by
                      model_type) load through the bidirectional encoder,
                      decoder families through the decoder runtime
    """
    from edgemesh.eval.metrics import HashingEmbedder

    if not spec:
        return HashingEmbedder()
    if spec == "synthetic":
        import jax

        from edgemesh.models.families import tiny_config
        from edgemesh.models.tokenizer import load_tokenizer
        from edgemesh.models.transformer import init_params

        tokenizer = load_tokenizer(None)
        cfg = tiny_config(
            "llama", vocab_size=tokenizer.vocab_size + 1, hidden_size=128,
            num_layers=2, num_heads=4, num_kv_heads=4, intermediate_size=256,
            max_seq_len=max(max_len, 128), dtype="float32",
        )
        params = init_params(cfg, jax.random.PRNGKey(1234))
        return ModelEmbedder(cfg, params, tokenizer, max_len=max_len)
    from edgemesh.models.families import sniff_family
    from edgemesh.models.tokenizer import load_tokenizer

    tokenizer = load_tokenizer(spec)
    if sniff_family(spec) == "bert":
        from edgemesh.models import encoder

        cfg, params = encoder.load_encoder(spec)
        return ModelEmbedder(cfg, params, tokenizer, max_len=max_len,
                             forward_fn=encoder.forward_hidden)
    from edgemesh.models.hf_ingest import load_params

    cfg, params = load_params(spec)
    return ModelEmbedder(cfg, params, tokenizer, max_len=max_len)
