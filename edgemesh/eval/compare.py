"""Paired comparison of two evaluation runs with bootstrap confidence bands.

The reference compared configurations by pasting aggregate logs into a
spreadsheet and eyeballing deltas (``Others/Distributed LLM Evaluations and
Results - Partha.xlsx``, the system of record for its Tables 1–3) — no
per-sample pairing, no uncertainty. This module does the comparison
properly: rows pair by sample ``index`` (both runs score the SAME
questions), the per-metric delta is the mean of per-sample differences, and
a paired bootstrap over samples gives a 95% interval — so "ensemble beats
single" or "int8 preserves quality" (the paper's Tables 1–2 claims) become
statements with error bars instead of bare means.

``python -m edgemesh.cli compare a.jsonl b.jsonl`` prints one JSON report.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np

# Quality/latency metrics eligible for comparison (numeric row fields).
METRICS = (
    "rouge1", "rouge2", "rougeL", "avg_rouge",
    "bertscore", "bleu", "cosine", "confidence", "tps",
)


def load_rows(path: str | Path) -> dict[int, dict]:
    rows: dict[int, dict] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                row = json.loads(line)
                rows[int(row["index"])] = row
    if not rows:
        raise ValueError(f"{path} contains no result rows")
    return rows


def compare_runs(
    path_a: str | Path,
    path_b: str | Path,
    metrics: tuple[str, ...] = METRICS,
    n_boot: int = 2000,
    seed: int = 0,
) -> dict:
    """Paired per-metric comparison of run B against run A (delta = B − A).

    Returns {metric: {a, b, delta, ci95: [lo, hi], better}} over the common
    sample indices, plus pairing bookkeeping. ``better`` is True when the
    95% interval clears zero in B's favor, False when it clears in A's,
    None when the interval spans zero (no significant difference)."""
    rows_a = load_rows(path_a)
    rows_b = load_rows(path_b)
    common = sorted(set(rows_a) & set(rows_b))
    if not common:
        raise ValueError("runs share no sample indices — nothing to pair")
    # Zero-filled ERROR rows are excluded outright: their 0.0 "scores" are
    # infra failures, and pairing them against real scores would report a
    # significant quality delta that is actually an OOM (the harness
    # likewise refuses to resume from error rows). The exclusion is COUNTED
    # so a mostly-failed run cannot masquerade as a clean comparison.
    clean = [
        i for i in common
        if "error" not in rows_a[i] and "error" not in rows_b[i]
    ]
    if not clean:
        raise ValueError(
            f"all {len(common)} paired rows carry errors in at least one "
            "run — nothing comparable; re-run the evals"
        )
    rng = np.random.default_rng(seed)
    out: dict = {
        "n_common": len(common),
        "excluded_error_rows": len(common) - len(clean),
        "only_a": len(rows_a) - len(common),
        "only_b": len(rows_b) - len(common),
        "metrics": {},
    }
    for m in metrics:
        # Rows are allowed to be heterogeneous (the harness only writes tps/
        # confidence when the answer_fn reports them) — pair only indices
        # where BOTH runs have the metric instead of trusting the first row.
        paired = [i for i in clean if m in rows_a[i] and m in rows_b[i]]
        if not paired:
            continue
        a = np.asarray([float(rows_a[i][m]) for i in paired])
        b = np.asarray([float(rows_b[i][m]) for i in paired])
        d = b - a
        boot_idx = rng.integers(0, len(paired), size=(n_boot, len(paired)))
        boots = d[boot_idx].mean(axis=1)
        lo, hi = float(np.quantile(boots, 0.025)), float(np.quantile(boots, 0.975))
        better = True if lo > 0 else False if hi < 0 else None
        out["metrics"][m] = {
            "a": round(float(a.mean()), 6),
            "b": round(float(b.mean()), 6),
            "delta": round(float(d.mean()), 6),
            "ci95": [round(lo, 6), round(hi, 6)],
            "better": better,
            "n": len(paired),
        }
    return out
