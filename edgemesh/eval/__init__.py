"""Evaluation harness: the eight-metric suite + golden-dataset loop.

This is the reference's acceptance harness (its L5 layer, SURVEY.md §1) as a
proper module instead of ~40 lines copy-pasted into eight runners (C9 in
SURVEY.md §2.1).
"""

from edgemesh.eval.metrics import (  # noqa: F401
    bleu,
    cosine_similarity,
    rouge_scores,
)
