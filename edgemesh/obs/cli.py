"""``edgemesh obs`` — offline span-log inspection and registry dumps.

Subcommands (all operate on the span JSONL the engines write via
``span_log=``, no backend or server required):

- ``tail <spans.jsonl> [-n N] [--event E]``: last N records, one compact
  human line each (rid, status, generated, queue/TTFT/latency).
- ``summary <spans.jsonl>``: replay the log into a fresh registry and print
  a JSON aggregate report (request counts by status, token totals, latency
  histograms as count/sum/mean) plus percentile estimates — including
  TTFT/TPOT p50/p99 and the SLO goodput ratio when the log carries the
  ``slo_result`` field (older logs report them as null, exit 0).
- ``prom <spans.jsonl>``: the same replay, rendered as Prometheus text
  exposition — byte-for-byte the format a live ``/metrics`` scrape serves,
  so offline logs and live scrapes feed the same dashboards.
- ``trace <trace_id> --logs router.jsonl replica0.jsonl ...``: assemble
  ONE request's spans across every process that touched it (router record
  + replica engine records + compile events) into a single tree with
  clock-skew correction, plus the critical-path split (wire vs queue vs
  prefill vs decode vs retry-wasted — obs/trace.py). Unique id prefixes
  are accepted; ambiguous prefixes list the candidates.
- ``loadreport <report.json>``: render an ``edgemesh loadgen`` report —
  the goodput-vs-offered-load bar chart with the saturation knee marked
  (curve documents), or the aggregate + per-tenant table (single runs).
- ``replay <spans...> --out workload.json``: reconstruct a replayable
  open-loop workload from recorded spans (arrivals from ``ts_submit``,
  prompt lengths, tenant mix, session grouping; ``--speed`` time-scales)
  — drive it with ``edgemesh loadgen --replay workload.json``.
- ``routes [--json]``: render the live wire contract
  (``serve/httputil.WIRE_CONTRACT``) — every HTTP route the fleet fabric
  speaks, with method, servers, required/forwarded headers, payload keys,
  and the structured error-kind vocabulary. The same table the wire
  analysis pass (EM501-EM506, docs/ANALYSIS.md) enforces statically, so
  this printout IS the protocol doc, generated-verifiable.
- ``compute <spans.jsonl> [--diff B] [--json]``: the compute observatory
  table (obs/compute.py) — per-boundary sampled device time with share,
  mean/p50 launch time, roofline fraction, cost-model flops rate, and top
  shape buckets, plus the speculative round-attribution block when the
  log carries ``spec_rounds`` records. ``--diff B`` compares two logs
  boundary-by-boundary (B/A mean ratio). A log with no launch records
  prints an explicit empty report and exits 0.
- ``quality <spans.jsonl>``: the quality observatory table
  (obs/quality.py) — per-engine/tenant/replica answer-confidence
  distributions, cross-branch agreement, the golden-set canary table,
  and the quality-drift incident timeline with the degraded replicas
  named. A log with no quality signal prints an explicit empty report
  and exits 0 (pre-quality logs — same contract as ``compute``/``mem``).
- ``incident <dumpdir>``: join an incident directory's flight-recorder
  dumps (every replica's ring, plus ``--logs`` router spans) into one
  postmortem document: trigger window marked, per-tenant goodput
  before/during/after, per-replica critical-path split in the window
  (obs/flight.py).

Wherever a span log is expected, a DIRECTORY is accepted too: it expands
to every ``*.jsonl`` inside (one level) — incident dump directories would
make spelling each file out untenable.

An empty or all-malformed span log is an answer, not an error: ``summary``
prints an explicit ``"requests": 0`` report and every subcommand exits 0
(malformed lines are counted on stderr).

Exit status: 0 on success, 1 when ``trace`` finds no matching id (or
``incident`` finds no dump header), 2 on usage errors (missing file).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from edgemesh.obs.spans import SPAN_RECORD_EVENT, replay_spans


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="edgemesh obs",
        description="tail/summarize request-span JSONL logs; dump registry "
        "snapshots (docs/OBSERVABILITY.md)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    tail = sub.add_parser("tail", help="print the last N span records")
    tail.add_argument("path")
    tail.add_argument("-n", type=int, default=10, dest="count")
    tail.add_argument("--event", default=None,
                      help="filter by record event (default: all)")
    summ = sub.add_parser("summary",
                          help="replay spans into aggregate JSON")
    summ.add_argument("path")
    prom = sub.add_parser("prom",
                          help="replay spans into Prometheus exposition text")
    prom.add_argument("path")
    tr = sub.add_parser(
        "trace",
        help="assemble one trace id across span logs (skew-corrected tree "
        "+ critical path)")
    tr.add_argument("trace_id", help="full trace id or a unique prefix")
    tr.add_argument("--logs", nargs="+", required=True, metavar="JSONL",
                    help="span logs from every process: the router's "
                    "--span-log plus each replica's")
    lr = sub.add_parser(
        "loadreport",
        help="render an `edgemesh loadgen` report (single run or "
        "goodput-vs-offered-load curve) as human text")
    lr.add_argument("path", help="report JSON written by `edgemesh loadgen`")
    lr.add_argument("--json", action="store_true", dest="as_json",
                    help="print the machine-readable report document "
                    "(curve documents gain knee fields if absent) instead "
                    "of the human chart")
    rp = sub.add_parser(
        "replay",
        help="reconstruct a replayable open-loop workload from recorded "
        "spans (drive it: `edgemesh loadgen --replay <out>`)")
    rp.add_argument("paths", nargs="+", metavar="SPANS",
                    help="span JSONL logs and/or directories of them "
                    "(flight dumps work verbatim)")
    rp.add_argument("--out", required=True,
                    help="write the workload document here")
    rp.add_argument("--speed", type=float, default=1.0,
                    help="time-scale factor: 2.0 replays twice as fast "
                    "(default 1.0 = real time)")
    rp.add_argument("--sessions", type=int, default=4,
                    help="synthetic sessions per tenant for records "
                    "without a recorded session id (default 4)")
    rp.add_argument("--no-max-new", action="store_true",
                    help="drop the per-request max_new budgets (required "
                    "when replaying at non-continuous or speculative "
                    "replicas — the gateway 400s the field there)")
    rt = sub.add_parser(
        "routes",
        help="render the wire contract table (every fleet-fabric HTTP "
        "route: method, servers, headers, payload keys, error kinds)")
    rt.add_argument("--json", action="store_true", dest="as_json",
                    help="print the machine-readable contract rows "
                    "(httputil.contract_rows()) instead of the table")
    inc = sub.add_parser(
        "incident",
        help="assemble an incident directory's flight dumps into one "
        "postmortem timeline (trigger window, per-tenant goodput, "
        "per-replica critical path)")
    inc.add_argument("dumpdir",
                     help="the incident directory (<flight-dir>/<id>) — or "
                     "any mix of dump files/dirs")
    inc.add_argument("--logs", nargs="*", default=[], metavar="JSONL",
                     help="extra span logs to join (the router's "
                     "--span-log adds its incident/timeline records)")
    inc.add_argument("--window-s", type=float, default=10.0,
                     help="half-width of the trigger window (default 10s)")
    comp = sub.add_parser(
        "compute",
        help="per-boundary device-time ledger table from launch records "
        "(obs/compute.py): share of device time, roofline fraction, "
        "cost-model flops/bytes, speculative round attribution")
    comp.add_argument("path", help="span JSONL log or directory of them")
    comp.add_argument("--diff", default=None, metavar="SPANS",
                      help="second span log: print per-boundary deltas "
                      "(the second log vs the first)")
    comp.add_argument("--json", action="store_true", dest="as_json",
                      help="print the machine-readable rollup "
                      "(compute.summarize_compute) instead of the table")
    mem = sub.add_parser(
        "mem",
        help="page-lifecycle ledger table from pool_mem records "
        "(obs/memory.py): per-tenant residency and peaks, internal/"
        "external fragmentation, conservation breaks, leaks, and the "
        "last digest's exhaustion forecast / HBM drift")
    mem.add_argument("path", help="span JSONL log or directory of them")
    mem.add_argument("--diff", default=None, metavar="SPANS",
                     help="second span log: print per-tenant/per-cause "
                     "deltas (the second log vs the first)")
    mem.add_argument("--json", action="store_true", dest="as_json",
                     help="print the machine-readable rollup "
                     "(memory.summarize_mem) instead of the table")
    qual = sub.add_parser(
        "quality",
        help="answer-quality table from span/flight records "
        "(obs/quality.py): confidence distributions per engine/tenant/"
        "replica, branch agreement, the canary table, and the "
        "quality-drift incident timeline")
    qual.add_argument("path", help="span JSONL log or directory of them")
    qual.add_argument("--json", action="store_true", dest="as_json",
                      help="print the machine-readable rollup "
                      "(quality.summarize_quality) instead of the table")
    return p


def _expand_logs(paths) -> list[Path]:
    """Expand each path: a directory becomes every ``*.jsonl`` directly
    inside it (sorted); files pass through. Incident dump directories are
    the motivating case — one dump file per replica."""
    out: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            out.extend(sorted(p.glob("*.jsonl")))
        else:
            out.append(p)
    return out


def _read(path: str) -> list[dict]:
    """Read one span log — or every ``*.jsonl`` in a directory."""
    from edgemesh.utils.tracing import JsonlLogger

    records: list[dict] = []
    malformed = 0
    for p in _expand_logs([path]):
        logger = JsonlLogger(p)
        records.extend(logger.read())
        malformed += logger.malformed
    if malformed:
        print(f"note: skipped {malformed} malformed line(s)",
              file=sys.stderr)
    return records


def _fmt_s(v) -> str:
    return "-" if v is None else f"{v * 1e3:.1f}ms"


def cmd_tail(path: str, count: int, event: str | None) -> int:
    records = _read(path)
    if event:
        records = [r for r in records if r.get("event") == event]
    for r in records[-count:]:
        if r.get("event") == SPAN_RECORD_EVENT:
            names = ">".join(s["name"] for s in r.get("spans", ()))
            print(
                f"rid={r.get('rid')} [{r.get('engine')}] "
                f"{r.get('status')} generated={r.get('generated')} "
                f"queue={_fmt_s(r.get('queue_s'))} "
                f"ttft={_fmt_s(r.get('ttft_s'))} "
                f"latency={_fmt_s(r.get('latency_s'))} spans={names}"
            )
        else:
            print(json.dumps(r))
    return 0


def cmd_summary(path: str) -> int:
    records = _read(path)
    registry = replay_spans(records)
    spans = [r for r in records if r.get("event") == SPAN_RECORD_EVENT]
    lats = sorted(r["latency_s"] for r in spans
                  if r.get("latency_s") is not None)
    ttfts = sorted(r["ttft_s"] for r in spans if r.get("ttft_s") is not None)
    # TPOT = the record's mean inter-token latency (itl_s). SLO fields are
    # None on logs that predate them — an old log is an answer, not an
    # error, and the report shape stays stable either way.
    tpots = sorted(r["itl_s"] for r in spans if r.get("itl_s") is not None)
    classified = [r["slo_result"] for r in spans
                  if r.get("slo_result") is not None]
    goodput = (
        round(sum(1 for c in classified if c == "good") / len(classified), 4)
        if classified else None
    )
    # Per-tenant goodput from the records' tenant field. Pre-tenant logs
    # (no such key) report null here and exit 0 — an old log is an answer,
    # not an error, exactly like the pre-SLO fields above.
    by_tenant: dict[str, list[int]] = {}
    for r in spans:
        if r.get("tenant") is not None and r.get("slo_result") is not None:
            cell = by_tenant.setdefault(str(r["tenant"]), [0, 0])
            cell[1] += 1
            if r["slo_result"] == "good":
                cell[0] += 1
    tenants = {
        t: {"classified": c, "good": g, "goodput_ratio": round(g / c, 4)}
        for t, (g, c) in sorted(by_tenant.items())
    } or None
    # Capacity-model rows (docs/OBSERVABILITY.md "The capacity model"):
    # flight-recorder snapshots carry the full load digest (capacity +
    # pool blocks), and the router's --admission auto log carries
    # admission_tune records (limit + live knee). Newest wins. Logs from
    # before the capacity model simply report null here and exit 0 — the
    # same forward-compat contract as the pre-SLO and pre-tenant fields.
    capacity = pool = knee = None
    for r in records:
        if isinstance(r.get("capacity"), dict):
            capacity = r["capacity"]
            if isinstance(r.get("pool"), dict):
                pool = r["pool"]
        if r.get("event") == "admission_tune":
            knee = {
                "action": r.get("action"),
                "limit": r.get("limit"),
                "rate_scale": r.get("rate_scale"),
                "knee_offered_rps": r.get("knee_offered_rps"),
                "knee_goodput_rps": r.get("knee_goodput_rps"),
                "collapsed": r.get("collapsed"),
            }

    def pct(xs: list[float], q: float):
        if not xs:
            return None
        return round(xs[min(len(xs) - 1, int(q * len(xs)))], 6)

    # Compute-ledger rollup (obs/compute.py): per-boundary device time /
    # roofline + speculative round attribution. Null on pre-compute logs
    # and exit 0 — the same old-log contract as every block above.
    from edgemesh.obs.compute import summarize_compute

    compute = summarize_compute(records)
    # Memory-observatory rollup (obs/memory.py): per-tenant residency,
    # fragmentation, conservation/leak tripwires. Null on pre-mem logs.
    from edgemesh.obs.memory import summarize_mem

    mem = summarize_mem(records)
    # Quality-observatory rollup (obs/quality.py): confidence/agreement
    # distributions, canary table, drift timeline. Null on pre-quality
    # logs.
    from edgemesh.obs.quality import summarize_quality

    quality = summarize_quality(records)

    print(json.dumps({
        "records": len(records),
        "requests": len(spans),
        "capacity": capacity,
        "pool": pool,
        "knee": knee,
        "latency_s_p50": pct(lats, 0.50),
        "latency_s_p95": pct(lats, 0.95),
        "ttft_s_p50": pct(ttfts, 0.50),
        "ttft_s_p95": pct(ttfts, 0.95),
        "ttft_s_p99": pct(ttfts, 0.99),
        "tpot_s_p50": pct(tpots, 0.50),
        "tpot_s_p99": pct(tpots, 0.99),
        "slo_classified": len(classified),
        "slo_goodput_ratio": goodput,
        "tenants": tenants,
        "compute": compute,
        "mem": mem,
        "quality": quality,
        "metrics": registry.summary(),
    }, indent=2))
    return 0


def _fmt_frac(v) -> str:
    return "-" if v is None else f"{v:.2f}"


def _fmt_flops(v) -> str:
    if v is None:
        return "-"
    for unit, scale in (("T", 1e12), ("G", 1e9), ("M", 1e6)):
        if v >= scale:
            return f"{v / scale:.1f}{unit}"
    return f"{v:.0f}"


def _compute_table(summ: dict) -> list[str]:
    lines = [f"{'BOUNDARY':<16} {'LAUNCH':>7} {'MEAS':>5} {'DEVICE':>9} "
             f"{'SHARE':>6} {'MEAN':>9} {'P50':>9} {'ROOFL':>5} "
             f"{'FLOP/S':>7}  KEYS"]
    for name, c in sorted(
            summ["boundaries"].items(),
            key=lambda kv: -(kv[1].get("device_s") or 0.0)):
        share = c.get("share")
        keys = ",".join(list(c.get("top_keys") or ())[:3])
        lines.append(
            f"{name:<16} "
            f"{'-' if c.get('launches') is None else c['launches']:>7} "
            f"{c.get('measured', 0):>5} "
            f"{c.get('device_s', 0.0):>8.3f}s "
            f"{'-' if share is None else f'{share * 100:.1f}%':>6} "
            f"{_fmt_s(c.get('mean_s')):>9} {_fmt_s(c.get('p50_s')):>9} "
            f"{_fmt_frac(c.get('roofline_fraction')):>5} "
            f"{_fmt_flops(c.get('achieved_flops_s')):>7}  {keys}"
        )
    lines.append(
        f"total: {summ['total_device_s']:.3f}s sampled device time over "
        f"{summ['launch_records']} launch record(s)")
    spec = summ.get("spec_rounds")
    if spec:
        lines.append("")
        lines.append(
            f"spec rounds: {spec.get('rounds')} rounds, "
            f"accepted {spec.get('accepted')}/{spec.get('proposed')} "
            f"(rate {_fmt_frac(spec.get('accept_rate'))}, "
            f"{spec.get('accepted_per_round')} tok/round)"
        )
        lines.append(
            f"  round={_fmt_s(spec.get('round_s'))} "
            f"draft={_fmt_s(spec.get('draft_s'))} "
            f"verify={_fmt_s(spec.get('verify_s'))} "
            f"(draft_frac={spec.get('draft_frac')}, "
            f"split: {spec.get('split')})"
        )
    return lines


def cmd_compute(path: str, diff: str | None = None,
                as_json: bool = False) -> int:
    """Per-boundary device-time table from a span log's launch records.
    A log with no compute records is an answer, not an error: prints an
    explicit empty report and exits 0 (pre-compute logs — same contract
    as summary's pre-SLO fields)."""
    from edgemesh.obs.compute import diff_compute, summarize_compute

    if diff is not None and not Path(diff).exists():
        print(f"error: no such span log: {diff}", file=sys.stderr)
        return 2
    summ = summarize_compute(_read(path))
    if diff is not None:
        other = summarize_compute(_read(diff))
        doc = diff_compute(summ, other)
        if as_json:
            print(json.dumps(doc, indent=2))
            return 0
        if not doc["boundaries"]:
            print("no launch records in either log — nothing to diff")
            return 0
        print(f"{'BOUNDARY':<16} {'A MEAN':>9} {'B MEAN':>9} {'B/A':>6} "
              f"{'A SHARE':>8} {'B SHARE':>8} {'A ROOFL':>7} {'B ROOFL':>7}")
        for name, c in doc["boundaries"].items():
            ratio = c.get("ratio")
            print(
                f"{name:<16} {_fmt_s(c.get('a_mean_s')):>9} "
                f"{_fmt_s(c.get('b_mean_s')):>9} "
                f"{'-' if ratio is None else f'{ratio:.2f}x':>6} "
                f"{_fmt_frac(c.get('a_share')):>8} "
                f"{_fmt_frac(c.get('b_share')):>8} "
                f"{_fmt_frac(c.get('a_roofline')):>7} "
                f"{_fmt_frac(c.get('b_roofline')):>7}"
            )
        return 0
    if as_json:
        print(json.dumps(summ, indent=2))
        return 0
    if summ is None:
        print("no launch records — a pre-compute log, or the ledger was "
              "disabled (EDGEMESH_COMPUTE_SAMPLE=0)")
        return 0
    print("\n".join(_compute_table(summ)))
    return 0


def _last_mem_digest(records: list[dict]) -> dict | None:
    """The newest flight-snapshot digest ``mem`` block in the log — where
    the live-only rows (exhaustion forecast, HBM drift) ride, since the
    per-transition records deliberately do not recompute them."""
    mem = None
    for r in records:
        if isinstance(r.get("mem"), dict):
            mem = r["mem"]
    return mem


def _mem_table(summ: dict, digest: dict | None) -> list[str]:
    lines = [
        f"pool: total={summ.get('total_pages') or '-'} pages  "
        f"peak_resident={summ.get('peak_resident_pages')}  "
        f"last_free={summ.get('last_free_pages')}  "
        f"conservation_breaks={summ.get('conservation_breaks')}"
    ]
    tenants = summ.get("tenants") or {}
    if tenants:
        lines.append(f"{'TENANT':<16} {'PAGES':>7} {'PEAK':>7}")
        for name, cell in tenants.items():
            lines.append(f"{name:<16} {cell.get('pages'):>7} "
                         f"{cell.get('peak_pages'):>7}")
    events = summ.get("events") or {}
    if events:
        lines.append(f"{'CAUSE':<16} {'EVENTS':>7} {'PAGES':>7}")
        for name, cell in events.items():
            lines.append(f"{name:<16} {cell.get('count'):>7} "
                         f"{cell.get('pages'):>7}")
    for leak in summ.get("leaks") or []:
        lines.append(
            f"LEAK rid={leak.get('rid')} tenant={leak.get('tenant')} "
            f"pages={leak.get('pages')} age={_fmt_s(leak.get('age_s'))}"
        )
    if digest is not None:
        frag = digest.get("frag") or {}
        lines.append(
            f"frag: internal={frag.get('internal_pages')} pages "
            f"(by cause: {frag.get('internal_by_cause')}) "
            f"external={frag.get('external_pages')}"
        )
        lines.append(
            f"forecast: {_fmt_s(digest.get('forecast_s'))} to exhaustion "
            f"(per_row_worst={digest.get('per_row_worst')}, "
            f"free={digest.get('free_pages')})"
        )
        drift = digest.get("drift")
        if drift is not None:
            lines.append(
                f"hbm drift: {drift.get('drift_bytes')} bytes vs ledger "
                f"(in_use={drift.get('hbm_bytes_in_use')}, "
                f"page={drift.get('page_bytes')} B)"
            )
    return lines


def cmd_mem(path: str, diff: str | None = None, as_json: bool = False) -> int:
    """Page-lifecycle table from a span log's pool_mem records. A log with
    no pool records is an answer, not an error: prints an explicit empty
    report and exits 0 (pre-mem logs — the same contract as compute's
    pre-ledger logs)."""
    from edgemesh.obs.memory import diff_mem, summarize_mem

    if diff is not None and not Path(diff).exists():
        print(f"error: no such span log: {diff}", file=sys.stderr)
        return 2
    records = _read(path)
    summ = summarize_mem(records)
    if diff is not None:
        other = summarize_mem(_read(diff))
        doc = diff_mem(summ, other)
        if as_json:
            print(json.dumps(doc, indent=2))
            return 0
        if summ is None and other is None:
            print("no pool records in either log — nothing to diff")
            return 0
        print(f"peak resident: {doc['a_peak_resident_pages']} → "
              f"{doc['b_peak_resident_pages']} "
              f"({doc['peak_ratio'] or '-'}x)")
        print(f"{'TENANT':<16} {'A PEAK':>7} {'B PEAK':>7}")
        for name, cell in doc["tenants"].items():
            print(f"{name:<16} {cell.get('a_peak_pages') or '-':>7} "
                  f"{cell.get('b_peak_pages') or '-':>7}")
        print(f"{'CAUSE':<16} {'A PAGES':>8} {'B PAGES':>8}")
        for name, cell in doc["events"].items():
            print(f"{name:<16} {cell.get('a_pages') or '-':>8} "
                  f"{cell.get('b_pages') or '-':>8}")
        print(f"conservation breaks: {doc['a_conservation_breaks']} → "
              f"{doc['b_conservation_breaks']}")
        return 0
    if as_json:
        print(json.dumps(summ, indent=2))
        return 0
    if summ is None:
        print("no pool records — a pre-mem log, a dense backend, or the "
              "ledger was disabled (EDGEMESH_MEM_LEDGER=0)")
        return 0
    print("\n".join(_mem_table(summ, _last_mem_digest(records))))
    return 0


def _quality_table(summ: dict) -> list[str]:
    lines = [f"quality records: {summ['quality_records']}"]

    def dist_rows(title: str, cells: dict | None) -> None:
        if not cells:
            return
        lines.append(f"{title:<16} {'N':>6} {'MEAN':>6} {'MIN':>6} "
                     f"{'P50':>6} {'P95':>6}")
        for name, c in cells.items():
            lines.append(
                f"{name:<16} {c['n']:>6} {c['mean']:>6.3f} {c['min']:>6.3f} "
                f"{c['p50']:>6.3f} {c['p95']:>6.3f}"
            )

    conf = summ.get("confidence") or {}
    dist_rows("ENGINE", conf.get("engines"))
    dist_rows("TENANT", conf.get("tenants"))
    dist_rows("REPLICA", conf.get("replicas"))
    agreement = summ.get("agreement")
    if agreement:
        lines.append(
            f"agreement: n={agreement['n']} mean={agreement['mean']:.3f} "
            f"min={agreement['min']:.3f} p50={agreement['p50']:.3f}"
        )
    canary = summ.get("canary")
    if canary:
        lines.append(f"{'CANARY':<16} {'PROBES':>7} {'MEAN':>6} {'MIN':>6} "
                     f"{'LAST':>6}  POOL")
        for rid, c in canary.items():
            smin = c["score_min"]
            slast = c["score_last"]
            lines.append(
                f"{rid:<16} {c['probes']:>7} {c['score_mean']:>6.3f} "
                f"{'-' if smin is None else format(smin, '.3f'):>6} "
                f"{'-' if slast is None else format(slast, '.3f'):>6}"
                f"  {c.get('pool') or '-'}"
            )
    for d in summ.get("drift_incidents") or []:
        lines.append(
            f"DRIFT {d.get('incident_id') or '?'} "
            f"replica={d.get('replica') or '?'} ts={d.get('ts')}"
        )
    degraded = summ.get("degraded_replicas")
    if degraded:
        lines.append(f"degraded replicas: {', '.join(degraded)}")
    return lines


def cmd_quality(path: str, as_json: bool = False) -> int:
    """Quality-observatory table from a span log's quality/canary/drift
    records. A log with no quality signal is an answer, not an error:
    prints an explicit empty report and exits 0 (pre-quality logs — the
    same contract as compute's and mem's pre-ledger logs)."""
    from edgemesh.obs.quality import summarize_quality

    summ = summarize_quality(_read(path))
    if as_json:
        print(json.dumps(summ, indent=2))
        return 0
    if summ is None:
        print("no quality records — a pre-quality log, or the tracker was "
              "disabled (EDGEMESH_QUALITY=0)")
        return 0
    print("\n".join(_quality_table(summ)))
    return 0


def cmd_prom(path: str) -> int:
    sys.stdout.write(replay_spans(_read(path)).render())
    return 0


def _fmt_tenant_rows(tenants: dict, indent: str = "  ") -> list[str]:
    rows = [f"{indent}{'TENANT':<14} {'SCHED':>6} {'OK':>5} {'SHED':>5} "
            f"{'RATELIM':>8} {'GOODPUT':>8} {'P99':>9}"]
    for name, cell in sorted(tenants.items()):
        gp = cell.get("goodput_ratio")
        p99 = cell.get("latency_s_p99")
        rows.append(
            f"{indent}{name:<14} {cell.get('scheduled', 0):>6} "
            f"{cell.get('ok', 0):>5} {cell.get('shed', 0):>5} "
            f"{cell.get('ratelimited', 0):>8} "
            f"{'-' if gp is None else f'{gp:.3f}':>8} "
            f"{'-' if p99 is None else f'{p99 * 1e3:.0f}ms':>9}"
        )
    return rows


def cmd_loadreport(path: str, as_json: bool = False) -> int:
    """Human rendering of a loadgen report: for a curve document, a
    goodput-vs-offered-load bar chart with the knee marked; for a single
    run, the aggregate + per-tenant table. ``--json`` instead prints the
    machine-readable document — curve documents written before the knee
    fields (or assembled by hand from raw points) gain them here via the
    same ``find_knee`` the sweep uses, so scripts always see the keys."""
    with open(path) as f:
        doc = json.load(f)
    if as_json:
        if "points" in doc and "knee_offered_rps" not in doc:
            from edgemesh.loadgen.curve import find_knee

            doc = {**doc, **find_knee(doc["points"])}
        print(json.dumps(doc, indent=2))
        return 0
    lines: list[str] = []
    if "points" in doc:  # curve document (run_curve schema)
        points = doc["points"]
        knee = doc.get("knee_offered_rps")
        peak = max((p.get("goodput_rps") or 0.0 for p in points),
                   default=0.0) or 1.0
        lines.append("goodput vs offered load "
                     f"(SLO: answered within {doc.get('slo_latency_s')}s "
                     "of the scheduled arrival)")
        lines.append("")
        for p in points:
            gp = p.get("goodput_rps") or 0.0
            bar = "#" * max(1, round(32 * gp / peak)) if gp > 0 else ""
            marker = "  <-- knee" if p["offered_rps"] == knee else ""
            lines.append(
                f"  {p['offered_rps']:>8.2f} rps offered | "
                f"{gp:>8.2f} rps good | {bar:<32}{marker}"
            )
        lines.append("")
        lines.append(
            f"knee: {knee} rps offered -> {doc.get('knee_goodput_rps')} rps "
            f"goodput; past-knee collapse: "
            f"{'YES' if doc.get('collapsed') else 'no'}"
        )
        last = points[-1] if points else None
        if last and last.get("tenants"):
            lines.append("")
            lines.append(f"per-tenant at {last['offered_rps']} rps offered:")
            lines.extend(_fmt_tenant_rows(last["tenants"]))
    else:  # single-run report (summarize schema)
        lines.append(
            f"open-loop run: {doc.get('scheduled')} scheduled over "
            f"{doc.get('duration_s')}s ({doc.get('offered_rps')} rps), "
            f"goodput {doc.get('goodput_rps')} rps "
            f"(ratio {doc.get('goodput_ratio')})"
        )
        lines.append(
            f"  ok={doc.get('ok')} shed={doc.get('shed')} "
            f"ratelimited={doc.get('ratelimited')} errors={doc.get('errors')} "
            f"p50={_fmt_s(doc.get('latency_s_p50'))} "
            f"p99={_fmt_s(doc.get('latency_s_p99'))} "
            f"launch_skew={_fmt_s(doc.get('max_launch_skew_s'))}"
        )
        if doc.get("tenants"):
            lines.append("")
            lines.extend(_fmt_tenant_rows(doc["tenants"]))
    print("\n".join(lines))
    return 0


def cmd_trace(trace_id: str, logs: list[str]) -> int:
    from edgemesh.obs.trace import load_trace

    missing = [p for p in logs if not Path(p).exists()]
    if missing:
        print(f"error: no such span log: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    doc = load_trace(trace_id, _expand_logs(logs))
    if doc["tree"] is None:
        candidates = doc.get("candidates", [])
        if candidates:
            print(f"error: trace id prefix {trace_id!r} is ambiguous: "
                  f"{', '.join(candidates)}", file=sys.stderr)
        else:
            print(f"error: no records for trace {trace_id!r} in "
                  f"{len(logs)} log(s)", file=sys.stderr)
        return 1
    print(json.dumps(doc, indent=2))
    return 0


def cmd_replay(paths: list[str], out: str, speed: float, sessions: int,
               include_max_new: bool) -> int:
    from edgemesh.loadgen.workload import Workload

    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        print(f"error: no such span log: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    records: list[dict] = []
    from edgemesh.utils.tracing import JsonlLogger

    for p in _expand_logs(paths):
        records.extend(JsonlLogger(p).read())
    try:
        wl = Workload.from_spans(records, speed=speed,
                                 sessions_per_tenant=sessions,
                                 include_max_new=include_max_new)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    doc = wl.to_doc()
    with open(out, "w") as f:
        f.write(json.dumps(doc, indent=2) + "\n")
    print(json.dumps({
        "out": out, "requests": len(wl.requests),
        "duration_s": wl.meta.get("duration_s"),
        "speed": speed, "tenants": wl.meta.get("tenants"),
    }, indent=2))
    return 0


def cmd_routes(as_json: bool = False) -> int:
    """Render the wire contract — the one declaration of every HTTP route
    the fleet fabric speaks (serve/httputil.WIRE_CONTRACT). ``--json``
    prints the same rows ``httputil.contract_rows()`` returns, so scripts
    and docs consume the identical shape the lint pass enforces."""
    from edgemesh.serve import httputil

    rows = httputil.contract_rows()
    if as_json:
        print(json.dumps({"routes": rows}, indent=2))
        return 0
    for row in rows:
        path = row["path"] + ("…" if row["prefix"] else "")
        print(f"{row['method']:4s} {path:20s} [{', '.join(row['servers'])}]")
        if row["required_headers"]:
            strict = "  (strict: a call with no headers at all flags)" \
                if row["strict_headers"] else ""
            print(f"       requires:  {', '.join(row['required_headers'])}"
                  f"{strict}")
        if row["forwarded_headers"]:
            print(f"       forwards:  {', '.join(row['forwarded_headers'])}")
        if row["request_keys"]:
            print(f"       body keys: {', '.join(row['request_keys'])}")
        if row["error_kinds"]:
            print(f"       err kinds: {', '.join(row['error_kinds'])}")
    print(f"{len(rows)} routes — enforced by `edgemesh lint --select EM5xx` "
          "(docs/ANALYSIS.md)")
    return 0


def cmd_incident(dumpdir: str, logs: list[str], window_s: float) -> int:
    from edgemesh.obs.flight import assemble_incident

    missing = [p for p in [dumpdir, *logs] if not Path(p).exists()]
    if missing:
        print(f"error: no such dump/log: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    doc = assemble_incident(_expand_logs([dumpdir, *logs]),
                            window_s=window_s)
    if doc["incident_id"] is None:
        print(f"error: no flight_dump header in {dumpdir!r} — not an "
              "incident dump directory?", file=sys.stderr)
        return 1
    print(json.dumps(doc, indent=2))
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "trace":
        return cmd_trace(args.trace_id, args.logs)
    if args.cmd == "replay":
        return cmd_replay(args.paths, args.out, args.speed, args.sessions,
                          include_max_new=not args.no_max_new)
    if args.cmd == "incident":
        return cmd_incident(args.dumpdir, args.logs, args.window_s)
    if args.cmd == "routes":
        return cmd_routes(as_json=args.as_json)
    if not Path(args.path).exists():
        kind = "report" if args.cmd == "loadreport" else "span log"
        print(f"error: no such {kind}: {args.path}", file=sys.stderr)
        return 2
    if args.cmd == "loadreport":
        return cmd_loadreport(args.path, as_json=args.as_json)
    if args.cmd == "tail":
        return cmd_tail(args.path, args.count, args.event)
    if args.cmd == "summary":
        return cmd_summary(args.path)
    if args.cmd == "compute":
        return cmd_compute(args.path, diff=args.diff, as_json=args.as_json)
    if args.cmd == "mem":
        return cmd_mem(args.path, diff=args.diff, as_json=args.as_json)
    if args.cmd == "quality":
        return cmd_quality(args.path, as_json=args.as_json)
    return cmd_prom(args.path)


if __name__ == "__main__":
    sys.exit(main())
