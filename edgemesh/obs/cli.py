"""``edgemesh obs`` — offline span-log inspection and registry dumps.

Subcommands (all operate on the span JSONL the engines write via
``span_log=``, no backend or server required):

- ``tail <spans.jsonl> [-n N] [--event E]``: last N records, one compact
  human line each (rid, status, generated, queue/TTFT/latency).
- ``summary <spans.jsonl>``: replay the log into a fresh registry and print
  a JSON aggregate report (request counts by status, token totals, latency
  histograms as count/sum/mean) plus percentile estimates — including
  TTFT/TPOT p50/p99 and the SLO goodput ratio when the log carries the
  ``slo_result`` field (older logs report them as null, exit 0).
- ``prom <spans.jsonl>``: the same replay, rendered as Prometheus text
  exposition — byte-for-byte the format a live ``/metrics`` scrape serves,
  so offline logs and live scrapes feed the same dashboards.
- ``trace <trace_id> --logs router.jsonl replica0.jsonl ...``: assemble
  ONE request's spans across every process that touched it (router record
  + replica engine records + compile events) into a single tree with
  clock-skew correction, plus the critical-path split (wire vs queue vs
  prefill vs decode vs retry-wasted — obs/trace.py). Unique id prefixes
  are accepted; ambiguous prefixes list the candidates.

An empty or all-malformed span log is an answer, not an error: ``summary``
prints an explicit ``"requests": 0`` report and every subcommand exits 0
(malformed lines are counted on stderr).

Exit status: 0 on success, 1 when ``trace`` finds no matching id, 2 on
usage errors (missing file).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from edgemesh.obs.spans import SPAN_RECORD_EVENT, replay_spans


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="edgemesh obs",
        description="tail/summarize request-span JSONL logs; dump registry "
        "snapshots (docs/OBSERVABILITY.md)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    tail = sub.add_parser("tail", help="print the last N span records")
    tail.add_argument("path")
    tail.add_argument("-n", type=int, default=10, dest="count")
    tail.add_argument("--event", default=None,
                      help="filter by record event (default: all)")
    summ = sub.add_parser("summary",
                          help="replay spans into aggregate JSON")
    summ.add_argument("path")
    prom = sub.add_parser("prom",
                          help="replay spans into Prometheus exposition text")
    prom.add_argument("path")
    tr = sub.add_parser(
        "trace",
        help="assemble one trace id across span logs (skew-corrected tree "
        "+ critical path)")
    tr.add_argument("trace_id", help="full trace id or a unique prefix")
    tr.add_argument("--logs", nargs="+", required=True, metavar="JSONL",
                    help="span logs from every process: the router's "
                    "--span-log plus each replica's")
    return p


def _read(path: str) -> list[dict]:
    from edgemesh.utils.tracing import JsonlLogger

    logger = JsonlLogger(path)
    records = logger.read()
    if logger.malformed:
        print(f"note: skipped {logger.malformed} malformed line(s)",
              file=sys.stderr)
    return records


def _fmt_s(v) -> str:
    return "-" if v is None else f"{v * 1e3:.1f}ms"


def cmd_tail(path: str, count: int, event: str | None) -> int:
    records = _read(path)
    if event:
        records = [r for r in records if r.get("event") == event]
    for r in records[-count:]:
        if r.get("event") == SPAN_RECORD_EVENT:
            names = ">".join(s["name"] for s in r.get("spans", ()))
            print(
                f"rid={r.get('rid')} [{r.get('engine')}] "
                f"{r.get('status')} generated={r.get('generated')} "
                f"queue={_fmt_s(r.get('queue_s'))} "
                f"ttft={_fmt_s(r.get('ttft_s'))} "
                f"latency={_fmt_s(r.get('latency_s'))} spans={names}"
            )
        else:
            print(json.dumps(r))
    return 0


def cmd_summary(path: str) -> int:
    records = _read(path)
    registry = replay_spans(records)
    spans = [r for r in records if r.get("event") == SPAN_RECORD_EVENT]
    lats = sorted(r["latency_s"] for r in spans
                  if r.get("latency_s") is not None)
    ttfts = sorted(r["ttft_s"] for r in spans if r.get("ttft_s") is not None)
    # TPOT = the record's mean inter-token latency (itl_s). SLO fields are
    # None on logs that predate them — an old log is an answer, not an
    # error, and the report shape stays stable either way.
    tpots = sorted(r["itl_s"] for r in spans if r.get("itl_s") is not None)
    classified = [r["slo_result"] for r in spans
                  if r.get("slo_result") is not None]
    goodput = (
        round(sum(1 for c in classified if c == "good") / len(classified), 4)
        if classified else None
    )

    def pct(xs: list[float], q: float):
        if not xs:
            return None
        return round(xs[min(len(xs) - 1, int(q * len(xs)))], 6)

    print(json.dumps({
        "records": len(records),
        "requests": len(spans),
        "latency_s_p50": pct(lats, 0.50),
        "latency_s_p95": pct(lats, 0.95),
        "ttft_s_p50": pct(ttfts, 0.50),
        "ttft_s_p95": pct(ttfts, 0.95),
        "ttft_s_p99": pct(ttfts, 0.99),
        "tpot_s_p50": pct(tpots, 0.50),
        "tpot_s_p99": pct(tpots, 0.99),
        "slo_classified": len(classified),
        "slo_goodput_ratio": goodput,
        "metrics": registry.summary(),
    }, indent=2))
    return 0


def cmd_prom(path: str) -> int:
    sys.stdout.write(replay_spans(_read(path)).render())
    return 0


def cmd_trace(trace_id: str, logs: list[str]) -> int:
    from edgemesh.obs.trace import load_trace

    missing = [p for p in logs if not Path(p).exists()]
    if missing:
        print(f"error: no such span log: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    doc = load_trace(trace_id, logs)
    if doc["tree"] is None:
        candidates = doc.get("candidates", [])
        if candidates:
            print(f"error: trace id prefix {trace_id!r} is ambiguous: "
                  f"{', '.join(candidates)}", file=sys.stderr)
        else:
            print(f"error: no records for trace {trace_id!r} in "
                  f"{len(logs)} log(s)", file=sys.stderr)
        return 1
    print(json.dumps(doc, indent=2))
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "trace":
        return cmd_trace(args.trace_id, args.logs)
    if not Path(args.path).exists():
        print(f"error: no such span log: {args.path}", file=sys.stderr)
        return 2
    if args.cmd == "tail":
        return cmd_tail(args.path, args.count, args.event)
    if args.cmd == "summary":
        return cmd_summary(args.path)
    return cmd_prom(args.path)


if __name__ == "__main__":
    sys.exit(main())
