"""SLO goodput instrumentation + streaming latency estimation.

Raw tok/s is the wrong serving headline: a fleet can post a huge aggregate
throughput while every interactive user waits three seconds for a first
token. The serving-quality number that matters is **SLO goodput** — the
fraction of requests that met their latency targets (ROADMAP "million-user
load harness"; the TPI-LLM / profiling-driven-edge line reports the same
way). Two targets define interactive quality:

- **TTFT** (time to first token): submit → first decoded token.
- **TPOT** (time per output token): mean inter-token latency after the
  first token — the streaming "typing speed".

Three pieces, all jax-free (same import contract as the rest of
``edgemesh.obs``):

- :class:`SloTarget` — the configurable targets (env:
  ``EDGEMESH_SLO_TTFT_S`` / ``EDGEMESH_SLO_TPOT_S``).
- :class:`SloTracker` — classifies each finished request against the
  target and feeds ``edgemesh_slo_requests_total{engine,result}`` plus the
  ``edgemesh_slo_goodput_ratio{engine}`` gauge. ``SpanTracker`` owns one
  per engine (obs/spans.py) and stamps the classification into the span
  JSONL record (``slo_result``) so ``edgemesh obs summary`` can report
  goodput offline.
- :class:`DecayingQuantile` — a time-decayed bucketed latency estimator
  (counts halve every ``half_life_s``) whose ``quantile(q)`` the fleet
  router reads to auto-tune its hedge delay from the LIVE p95 instead of a
  fixed threshold (fleet/router.py).

:class:`StreamMeter` adapts the raw streaming path
(``runtime/generate_stream``) onto the same histograms: per-chunk elapsed
timestamps become TTFT/TPOT observations under ``engine="stream"``.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass

from edgemesh.obs.metrics import (
    INTER_TOKEN_BUCKETS,
    LATENCY_BUCKETS,
    Registry,
    bounded_label,
    get_registry,
)

#: Default interactive targets: a first token within 2 s and a sustained
#: 5 tok/s typing speed. Override per deployment via env or constructor.
DEFAULT_TTFT_S = 2.0
DEFAULT_TPOT_S = 0.2

#: Every value the ``result`` label can take: ``good`` met both targets;
#: ``ttft``/``tpot``/``ttft_tpot`` name what was missed; ``error`` is a
#: request that never finished cleanly (always a miss).
SLO_RESULTS = ("good", "ttft", "tpot", "ttft_tpot", "error")


@dataclass(frozen=True)
class SloTarget:
    """One serving-quality contract: TTFT and TPOT ceilings in seconds."""

    ttft_s: float = DEFAULT_TTFT_S
    tpot_s: float = DEFAULT_TPOT_S

    @classmethod
    def from_env(cls) -> "SloTarget":
        """Targets from ``EDGEMESH_SLO_TTFT_S``/``EDGEMESH_SLO_TPOT_S``
        (falling back to the defaults) — how a replica subprocess is
        configured without new CLI plumbing at every call site."""
        def _f(name: str, default: float) -> float:
            raw = os.environ.get(name)
            if not raw:
                return default
            try:
                v = float(raw)
            except ValueError:
                return default
            return v if v > 0 else default

        return cls(ttft_s=_f("EDGEMESH_SLO_TTFT_S", DEFAULT_TTFT_S),
                   tpot_s=_f("EDGEMESH_SLO_TPOT_S", DEFAULT_TPOT_S))


class SloTracker:
    """Classifies finished requests against an :class:`SloTarget` and
    exposes the running goodput ratio as registry metrics."""

    def __init__(self, registry: Registry | None = None,
                 engine: str = "continuous",
                 target: SloTarget | None = None):
        self.registry = registry if registry is not None else get_registry()
        self.engine = engine
        self.target = target if target is not None else SloTarget.from_env()
        self._requests = self.registry.counter(
            "edgemesh_slo_requests_total",
            "Requests classified against the TTFT/TPOT SLO target, by result",
            ("engine", "result"))
        # Family handle only — the labeled child is created on the first
        # classification, so an idle engine scrapes NO goodput sample
        # instead of a misleading 0.0.
        self._goodput_family = self.registry.gauge(
            "edgemesh_slo_goodput_ratio",
            "Fraction of classified requests that met BOTH SLO targets",
            ("engine",))
        self._target_gauge = self.registry.gauge(
            "edgemesh_slo_target_seconds",
            "The active SLO target, by kind (ttft/tpot)", ("engine", "kind"))
        self._target_gauge.labels(engine=engine, kind="ttft").set(self.target.ttft_s)
        self._target_gauge.labels(engine=engine, kind="tpot").set(self.target.tpot_s)
        # Per-tenant twins of the aggregate families above. A SEPARATE
        # family (not a third label on edgemesh_slo_requests_total): the
        # aggregate family predates tenancy and a family cannot be
        # re-registered with a new labelset — and single-tenant deployments
        # keep scraping exactly what they scraped before. Tenant values are
        # bounded through obs.metrics.bounded_label (EM112) in count().
        self._tenant_requests = self.registry.counter(
            "edgemesh_slo_tenant_requests_total",
            "Per-tenant SLO classifications (tenant bounded via "
            "bounded_label)", ("engine", "tenant", "result"))
        self._tenant_goodput = self.registry.gauge(
            "edgemesh_slo_tenant_goodput_ratio",
            "Per-tenant fraction of classified requests meeting BOTH SLO "
            "targets", ("engine", "tenant"))
        self._lock = threading.Lock()
        self._good = 0
        self._classified = 0
        # tenant -> [good, classified]; bounded because keys are
        # bounded_label outputs.
        self._by_tenant: dict[str, list[int]] = {}

    def classify(self, status: str, ttft_s: float | None,
                 tpot_s: float | None) -> str:
        """Pure classification — no counting. A request that produced no
        first token (``ttft_s`` None) missed TTFT by definition; ``tpot_s``
        None (single-token answers) cannot miss TPOT."""
        if status != "ok":
            return "error"
        miss_ttft = ttft_s is None or ttft_s > self.target.ttft_s
        miss_tpot = tpot_s is not None and tpot_s > self.target.tpot_s
        if miss_ttft and miss_tpot:
            return "ttft_tpot"
        if miss_ttft:
            return "ttft"
        if miss_tpot:
            return "tpot"
        return "good"

    def record(self, status: str, ttft_s: float | None,
               tpot_s: float | None, tenant: str | None = None) -> str:
        result = self.classify(status, ttft_s, tpot_s)
        self.count(result, tenant=tenant)
        return result

    def count(self, result: str, tenant: str | None = None) -> None:
        """Feed one pre-classified result (the live path after
        :meth:`classify`; also the replay path — ``replay_spans`` counts
        the ``slo_result`` stamped into each span record). ``tenant`` is
        the raw request-derived tenant string (or None on pre-tenant
        traffic/logs): it is normalized through ``bounded_label`` here, so
        callers never have to worry about cardinality."""
        self._requests.labels(engine=self.engine, result=result).inc()
        with self._lock:
            self._classified += 1
            if result == "good":
                self._good += 1
            ratio = self._good / self._classified
        self._goodput_family.labels(engine=self.engine).set(ratio)
        if tenant is None:
            return
        label = bounded_label(tenant)
        self._tenant_requests.labels(
            engine=self.engine, tenant=label, result=result).inc()
        with self._lock:
            cell = self._by_tenant.setdefault(label, [0, 0])
            cell[1] += 1
            if result == "good":
                cell[0] += 1
            tratio = cell[0] / cell[1]
        self._tenant_goodput.labels(engine=self.engine, tenant=label).set(tratio)

    def goodput_ratio(self) -> float | None:
        with self._lock:
            if not self._classified:
                return None
            return self._good / self._classified

    def tenant_goodput(self) -> dict[str, dict]:
        """Per-tenant {classified, good, goodput_ratio} — what ``/fleetz``
        and ``/statusz`` print. Empty until tenant-tagged traffic arrives."""
        with self._lock:
            return {
                t: {"classified": c, "good": g,
                    "goodput_ratio": round(g / c, 4)}
                for t, (g, c) in sorted(self._by_tenant.items())
            }


# ---------------------------------------------------------------------------
# Decayed latency quantiles (the router's hedge auto-tuner)
# ---------------------------------------------------------------------------

#: Geometric bucket bounds 0.5 ms → ~100 s: fine enough that a p95 read is
#: within ~30% of the true value, coarse enough that decay costs one array
#: scale per observation.
_DECAY_BOUNDS = tuple(0.0005 * (1.3 ** i) for i in range(48))


class DecayingQuantile:
    """Bucketed latency distribution whose counts halve every
    ``half_life_s`` — a sliding-window percentile without storing samples.

    ``quantile(q)`` answers from the decayed counts with linear
    interpolation inside the winning bucket, or ``None`` until at least
    ``min_weight`` worth of (decayed) observations accumulated — an
    estimator with three samples must not arm a hedge."""

    def __init__(self, half_life_s: float = 60.0,
                 bounds: tuple[float, ...] = _DECAY_BOUNDS,
                 min_weight: float = 16.0,
                 now=time.monotonic):
        self.half_life_s = float(half_life_s)
        self.bounds = tuple(bounds)
        self.min_weight = float(min_weight)
        self._now = now
        self._lock = threading.Lock()
        self._counts = [0.0] * (len(self.bounds) + 1)  # last = overflow
        self._last_decay = now()

    def _decay_locked(self) -> None:  # guarded by: _lock
        t = self._now()
        dt = t - self._last_decay
        if dt <= 0:
            return
        scale = 0.5 ** (dt / self.half_life_s)
        self._counts = [c * scale for c in self._counts]
        self._last_decay = t

    def observe(self, value: float) -> None:
        with self._lock:
            self._decay_locked()
            for i, b in enumerate(self.bounds):
                if value <= b:
                    self._counts[i] += 1.0
                    return
            self._counts[-1] += 1.0

    def weight(self) -> float:
        with self._lock:
            self._decay_locked()
            return sum(self._counts)

    def quantile(self, q: float) -> float | None:
        with self._lock:
            self._decay_locked()
            total = sum(self._counts)
            if total < self.min_weight:
                return None
            target = q * total
            acc = 0.0
            for i, c in enumerate(self._counts):
                if c <= 0:
                    continue
                if acc + c >= target:
                    lo = self.bounds[i - 1] if i > 0 else 0.0
                    hi = (self.bounds[i] if i < len(self.bounds)
                          else self.bounds[-1])
                    frac = min(1.0, max(0.0, (target - acc) / c))
                    return lo + (hi - lo) * frac
                acc += c
            return self.bounds[-1]


# ---------------------------------------------------------------------------
# Raw streaming path → the same TTFT/TPOT histograms
# ---------------------------------------------------------------------------

# One SloTracker per (registry, engine), cached ON the registry object: a
# StreamMeter is per-stream (it holds per-stream TTFT state), but the
# goodput ratio is a RUNNING fraction — a fresh tracker per stream would
# reset the gauge to the last stream's lone 0/1 verdict, contradicting the
# slo_requests_total counters right next to it.
_shared_slo_lock = threading.Lock()


def _shared_slo(registry: Registry, engine: str,
                target: SloTarget | None) -> SloTracker:
    with _shared_slo_lock:
        cache = registry.__dict__.setdefault("_edgemesh_slo_trackers", {})
        tracker = cache.get(engine)
        if tracker is None:
            tracker = cache[engine] = SloTracker(registry, engine=engine,
                                                 target=target)
        return tracker


class StreamMeter:
    """Feeds ``generate_stream``'s per-chunk elapsed timestamps into the
    serving TTFT/TPOT histograms (``engine="stream"``) and the SLO tracker.

    One meter per stream; single-consumer (a generator is). TTFT is the
    elapsed time at the first token-bearing chunk — for chunked streaming
    that is the first yield the CLIENT can observe, which is the honest
    user-facing number. TPOT observations are per-chunk
    ``Δelapsed / tokens`` weighted by token count, so a segment costs one
    histogram lock acquisition, not one per token."""

    def __init__(self, registry: Registry | None = None,
                 engine: str = "stream", target: SloTarget | None = None):
        reg = registry if registry is not None else get_registry()
        self._ttft = reg.histogram(
            "edgemesh_ttft_seconds",
            "submit() to first decoded token", ("engine",),
            buckets=LATENCY_BUCKETS).labels(engine=engine)
        self._tpot = reg.histogram(
            "edgemesh_inter_token_seconds",
            "Mean per-token decode latency after the first token",
            ("engine",), buckets=INTER_TOKEN_BUCKETS).labels(engine=engine)
        # Shared per (registry, engine): the goodput ratio must accumulate
        # across streams, not reset with each meter. The first meter's
        # target wins for that registry+engine.
        self.slo = _shared_slo(reg, engine, target)
        self._ttft_s: float | None = None
        self._last_elapsed = 0.0
        self._tokens = 0

    def chunk(self, elapsed_s: float, new_tokens: int) -> None:
        new_tokens = int(new_tokens)
        if new_tokens > 0 and self._ttft_s is None:
            # First token-bearing chunk: TTFT only. Its elapsed window mixes
            # prefill with decode, so per-token credit starts next chunk.
            self._ttft_s = elapsed_s
            self._ttft.observe(elapsed_s)
        elif new_tokens > 0:
            per_tok = (elapsed_s - self._last_elapsed) / new_tokens
            self._tpot.observe(per_tok, count=new_tokens)
        if new_tokens > 0:
            self._last_elapsed = elapsed_s
            self._tokens += new_tokens

    def finish(self, status: str = "ok") -> str:
        tpot = None
        if self._ttft_s is not None and self._tokens > 1:
            tpot = (self._last_elapsed - self._ttft_s) / (self._tokens - 1)
        return self.slo.record(status, self._ttft_s, tpot)
