"""The quality observatory: answer quality as a live serving signal.

Every observatory before this one (latency, bytes, pages, FLOPs) watches
*how fast* the fleet answers; none watches *how well*. A replica serving
a corrupted checkpoint — noise in the head, a truncated weight file —
passes /readyz, meets its latency SLOs, and looks healthy to every
anomaly detector while answering garbage. This module closes that blind
spot with signals the serving path already has in hand:

- **confidence** — the paper's own metric (mean per-token max softmax),
  plus the per-request *minimum* step confidence: a single collapsed
  step in an otherwise-confident answer is a finding the mean hides.
  Computed device-side inside the decode loop (runtime/generate.py) as
  one elementwise tail on the softmax the sampler already materializes —
  no extra launch, no second forward.
- **entropy** — mean per-token distribution entropy (nats): the dual of
  confidence, separating "confidently wrong vocabulary" (low entropy,
  low confidence is impossible) from "head is noise" (entropy near
  ``log(vocab)``).
- **agreement** — pairwise token-F1 between independent answers to the
  SAME question (the ensemble coordinator's QA drafts, the canary
  prober's reference answers), via the eval harness's tokenizer so the
  number is comparable to the offline ROUGE/BLEU tables.

:class:`QualityTracker` is the engine-side sink (one per engine, same
shape as the compute/memory ledgers): histograms + per-tenant goodness
gauges under the EM111/EM112 naming rules, EWMAs for ``stats()`` and the
load digest's ``quality`` block, and the feed into the anomaly monitor's
:class:`~edgemesh.obs.anomaly.QualityDriftDetector` (the ``quality_drift``
incident). ``EDGEMESH_QUALITY=0`` disables it — the overhead-gate off
arm benchmarks.py flips (same <= 1.02 bar as the flight recorder).

Offline, :func:`summarize_quality` rebuilds the same views from span
logs / flight dumps (``edgemesh obs quality``, the ``quality`` block of
``obs summary``) with the standing compatibility contract: pre-quality
logs summarize to None (rc 0), unknown keys on future records are
ignored.

Importing this module never imports jax (the obs package contract).
"""

from __future__ import annotations

import math
import os
import threading
from typing import Any, Callable, Iterable

from edgemesh.obs.metrics import Registry, bounded_label, get_registry

#: Span-log event name for canary probe results (obs JSONL vocabulary —
#: EM113): one record per golden-set probe, written by the fleet's
#: :class:`~edgemesh.fleet.canary.CanaryProber`.
CANARY_RECORD_EVENT = "canary"

#: ``EDGEMESH_QUALITY=0`` disables the tracker entirely.
ENABLE_ENV = "EDGEMESH_QUALITY"

#: Histogram buckets for signals living on [0, 1] (confidence, agreement,
#: canary scores) — the latency defaults would put everything in one bin.
UNIT_BUCKETS = tuple(round(i / 20, 2) for i in range(1, 21))

#: Token-entropy buckets (nats): log(vocab) for a 32k vocab is ~10.4, so
#: a geometric ladder to ~12 covers greedy-certain through uniform-noise.
ENTROPY_BUCKETS = (0.02, 0.05, 0.1, 0.2, 0.4, 0.8, 1.5, 3.0, 6.0, 12.0)

#: EWMA smoothing for the digest-facing aggregates (matches the span
#: tracker's load-digest convention: recent-weighted, cheap to update).
EWMA_ALPHA = 0.2


def _env_enabled() -> bool:
    return os.environ.get(ENABLE_ENV, "1") != "0"


class QualityTracker:
    """Per-engine sink for the decode loop's quality signals.

    The engine calls :meth:`on_retire` once per terminal request (from
    ``_retire``, inside its own lock is fine — the tracker carries its
    own so the read side can run on gateway threads). Everything here is
    host-side float math on numbers the device already reduced.
    """

    def __init__(self, registry: Registry | None = None,
                 engine: str = "continuous",
                 low_confidence: float = 0.2,
                 anomaly_source: Callable[[], Any] | None = None,
                 enabled: bool | None = None):
        self.registry = registry or get_registry()
        self.engine = engine
        #: Below this mean confidence a request counts as "low" — the
        #: per-tenant goodness denominator (not the drift rule: drift is
        #: judged against the replica's own baseline, not a constant).
        self.low_confidence = float(low_confidence)
        self.enabled = _env_enabled() if enabled is None else bool(enabled)
        self._anomaly_source = anomaly_source
        self._lock = threading.Lock()
        self._seen = False
        self._requests = 0
        self._low = 0
        self._conf_ewma: float | None = None
        self._conf_min_seen: float | None = None
        self._ent_ewma: float | None = None
        self._tenant: dict[str, list[float]] = {}  # label -> [n, low, ewma]
        reg = self.registry
        self._conf_hist = reg.histogram(
            "edgemesh_quality_confidence",
            "Per-request mean max-softmax confidence (decode loop)",
            ("engine",), buckets=UNIT_BUCKETS)
        self._ent_hist = reg.histogram(
            "edgemesh_quality_entropy",
            "Per-request mean token entropy in nats (decode loop)",
            ("engine",), buckets=ENTROPY_BUCKETS)
        self._requests_total = reg.counter(
            "edgemesh_quality_requests_total",
            "Requests with quality signals, split by goodness band",
            ("engine", "band"))
        self._tenant_gauge = reg.gauge(
            "edgemesh_quality_tenant_confidence",
            "Recent-weighted mean confidence per tenant (bounded labels)",
            ("engine", "tenant"))

    # -- the feed ------------------------------------------------------------

    def on_retire(self, quality: dict | None,
                  tenant: str | None = None) -> None:
        """One terminal request's quality block (the dict ``_retire``
        stamps on the span: ``{confidence_mean, confidence_min,
        entropy_mean, tokens}``). None (aborted before any decode step,
        quality disabled device-side) is a no-op."""
        if not self.enabled or not isinstance(quality, dict):
            return
        conf = quality.get("confidence_mean")
        if not isinstance(conf, (int, float)) or not math.isfinite(conf):
            return
        conf = float(conf)
        conf_min = quality.get("confidence_min")
        ent = quality.get("entropy_mean")
        label = bounded_label(tenant)
        low = conf < self.low_confidence
        with self._lock:
            self._seen = True
            self._requests += 1
            self._low += int(low)
            self._conf_ewma = (
                conf if self._conf_ewma is None
                else EWMA_ALPHA * conf + (1 - EWMA_ALPHA) * self._conf_ewma
            )
            if isinstance(conf_min, (int, float)) and math.isfinite(conf_min):
                self._conf_min_seen = (
                    float(conf_min) if self._conf_min_seen is None
                    else min(self._conf_min_seen, float(conf_min))
                )
            if isinstance(ent, (int, float)) and math.isfinite(ent):
                self._ent_ewma = (
                    float(ent) if self._ent_ewma is None
                    else EWMA_ALPHA * float(ent)
                    + (1 - EWMA_ALPHA) * self._ent_ewma
                )
            cell = self._tenant.setdefault(label, [0.0, 0.0, conf])
            cell[0] += 1
            cell[1] += int(low)
            cell[2] = EWMA_ALPHA * conf + (1 - EWMA_ALPHA) * cell[2]
            tenant_ewma = cell[2]
        self._conf_hist.labels(engine=self.engine).observe(conf)
        if isinstance(ent, (int, float)) and math.isfinite(ent):
            self._ent_hist.labels(engine=self.engine).observe(float(ent))
        self._requests_total.labels(
            engine=self.engine, band="low" if low else "ok").inc()
        self._tenant_gauge.labels(
            engine=self.engine, tenant=label).set(tenant_ewma)
        if self._anomaly_source is not None:
            try:
                monitor = self._anomaly_source()
            except Exception:
                monitor = None
            if monitor is not None:
                monitor.on_quality(conf, detail={
                    "engine": self.engine, "tenant": label,
                    "confidence": round(conf, 4),
                })

    # -- read side -----------------------------------------------------------

    def rollup(self) -> dict:
        """Cumulative aggregate for ``stats()`` / bench JSON. Falsy ({})
        before the first signal — a spec engine (no quality feed) or a
        disabled tracker never grows the key."""
        with self._lock:
            if not self._seen:
                return {}
            return {
                "engine": self.engine,
                "requests": self._requests,
                "low_confidence_requests": self._low,
                "confidence_ewma": round(self._conf_ewma, 4),
                "confidence_min_seen": (
                    None if self._conf_min_seen is None
                    else round(self._conf_min_seen, 4)),
                "entropy_ewma": (
                    None if self._ent_ewma is None
                    else round(self._ent_ewma, 4)),
                "tenants": {
                    t: {"requests": int(n), "low": int(low),
                        "confidence_ewma": round(ewma, 4)}
                    for t, (n, low, ewma) in sorted(self._tenant.items())
                },
            }

    def digest_quality(self) -> dict | None:
        """The load digest's ``quality`` block. None until a signal has
        been seen — pre-quality consumers (and old routers) read exactly
        the digest they always did."""
        if not self.enabled:
            return None
        with self._lock:
            if not self._seen:
                return None
            return {
                "requests": self._requests,
                "confidence_ewma": round(self._conf_ewma, 4),
                "entropy_ewma": (
                    None if self._ent_ewma is None
                    else round(self._ent_ewma, 4)),
                "low_fraction": round(self._low / max(1, self._requests), 4),
            }


# ---------------------------------------------------------------------------
# Agreement (pairwise token-F1 over the eval harness's tokenizer)
# ---------------------------------------------------------------------------


def token_f1(prediction: str, reference: str) -> float:
    """Unigram token F1 between two answers — the agreement/canary score.

    Rides :func:`edgemesh.eval.metrics.tokenize` (Porter-stemmed, same as
    the offline ROUGE path) so online canary scores and offline eval
    tables speak one vocabulary. Two empty answers agree (1.0): an
    ensemble whose branches all said nothing is unanimous, not broken —
    the *length* attr on the branch span carries that finding.
    """
    from collections import Counter

    from edgemesh.eval.metrics import _f1, tokenize

    pred = Counter(tokenize(prediction or ""))
    ref = Counter(tokenize(reference or ""))
    if not pred and not ref:
        return 1.0
    matches = sum((pred & ref).values())
    return _f1(matches, sum(pred.values()), sum(ref.values()))


def pairwise_agreement(answers: Iterable[str]) -> float | None:
    """Mean pairwise :func:`token_f1` over >= 2 answers; None otherwise
    (one branch has nobody to agree with — never fabricate a 1.0)."""
    texts = [a if isinstance(a, str) else "" for a in answers]
    if len(texts) < 2:
        return None
    total, pairs = 0.0, 0
    for i in range(len(texts)):
        for j in range(i + 1, len(texts)):
            total += token_f1(texts[i], texts[j])
            pairs += 1
    return round(total / pairs, 4)


# ---------------------------------------------------------------------------
# Offline analysis (span logs / flight dumps) — `edgemesh obs quality`
# ---------------------------------------------------------------------------


def _quantiles(values: list[float]) -> dict | None:
    if not values:
        return None
    vs = sorted(values)

    def q(p: float) -> float:
        return round(vs[min(len(vs) - 1, int(p * len(vs)))], 4)

    return {"n": len(vs), "mean": round(sum(vs) / len(vs), 4),
            "min": round(vs[0], 4), "p50": q(0.5), "p95": q(0.95)}


def summarize_quality(records: Iterable[dict]) -> dict | None:
    """Quality rollup from span-log / flight-dump records — the offline
    twin of :meth:`QualityTracker.rollup` plus the fleet views only a log
    can hold: per-replica confidence (flight dumps carry the replica on
    their header), the canary table, and the quality-drift timeline.

    Returns None when no record carries a quality signal: a pre-quality
    log is an answer, not an error (the CLI prints null and exits 0).
    Unknown keys on future records are ignored; known-but-missing keys
    read as None — both directions pinned in tests/test_obs.py.
    """
    per_engine: dict[str, list[float]] = {}
    per_tenant: dict[str, list[float]] = {}
    per_replica: dict[str, list[float]] = {}
    agreements: list[float] = []
    canary: dict[str, dict] = {}
    drift: list[dict] = []
    n = 0
    replica = None  # set by flight_dump headers, stamps following records
    for rec in records:
        if not isinstance(rec, dict):
            continue
        event = rec.get("event")
        if event == "flight_dump":
            replica = rec.get("replica") or replica
            kind = rec.get("kind")
            origin = rec.get("origin_kind")
            if kind == "quality_drift" or origin == "quality_drift":
                drift.append({
                    "ts": rec.get("trigger_ts") or rec.get("ts"),
                    "incident_id": rec.get("incident_id"),
                    "replica": (rec.get("source") or rec.get("replica")),
                    "kind": origin or kind,
                })
            continue
        if event == "incident":
            if rec.get("kind") == "quality_drift":
                drift.append({
                    "ts": rec.get("ts"), "incident_id": rec.get("id"),
                    "replica": rec.get("source"), "kind": "quality_drift",
                })
            continue
        if event == CANARY_RECORD_EVENT:
            rid = str(rec.get("replica") or "?")
            score = rec.get("score")
            if not isinstance(score, (int, float)):
                continue
            n += 1
            cell = canary.setdefault(rid, {
                "probes": 0, "sum": 0.0, "min": None,
                "last": None, "pool": rec.get("pool")})
            cell["probes"] += 1
            cell["sum"] += float(score)
            cell["min"] = (float(score) if cell["min"] is None
                           else min(cell["min"], float(score)))
            cell["last"] = round(float(score), 4)
            continue
        quality = rec.get("quality")
        if isinstance(quality, dict):
            conf = quality.get("confidence_mean")
            if isinstance(conf, (int, float)) and math.isfinite(conf):
                n += 1
                conf = float(conf)
                per_engine.setdefault(
                    str(rec.get("engine") or "?"), []).append(conf)
                per_tenant.setdefault(
                    str(rec.get("tenant") or "default"), []).append(conf)
                rep = rec.get("_replica") or replica
                if rep is not None:
                    per_replica.setdefault(str(rep), []).append(conf)
        agreement = rec.get("agreement")
        if isinstance(agreement, (int, float)) and math.isfinite(agreement):
            n += 1
            agreements.append(float(agreement))
        # Router/ensemble records carry agreement inside span attrs too.
        for span in rec.get("spans") or []:
            if not isinstance(span, dict):
                continue
            sa = span.get("agreement")
            if isinstance(sa, (int, float)) and math.isfinite(sa):
                n += 1
                agreements.append(float(sa))
    if n == 0:
        return None
    return {
        "quality_records": n,
        "confidence": {
            "engines": {e: _quantiles(v)
                        for e, v in sorted(per_engine.items())} or None,
            "tenants": {t: _quantiles(v)
                        for t, v in sorted(per_tenant.items())} or None,
            "replicas": {r: _quantiles(v)
                         for r, v in sorted(per_replica.items())} or None,
        },
        "agreement": _quantiles(agreements),
        "canary": {
            rid: {"probes": c["probes"],
                  "score_mean": round(c["sum"] / c["probes"], 4),
                  "score_min": (None if c["min"] is None
                                else round(c["min"], 4)),
                  "score_last": c["last"],
                  "pool": c["pool"]}
            for rid, c in sorted(canary.items())
        } or None,
        "drift_incidents": sorted(
            drift, key=lambda d: d.get("ts") or 0) or None,
        "degraded_replicas": sorted(
            {str(d["replica"]) for d in drift if d.get("replica")}) or None,
    }
