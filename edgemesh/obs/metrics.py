"""Labeled metrics registry with Prometheus text exposition.

The serving stack's single source of aggregate truth (docs/OBSERVABILITY.md
has the full metric catalog). Three instrument kinds, all thread-safe and
label-aware:

- ``Counter``: monotone totals (requests, tokens, restarts).
- ``Gauge``: last-written values (KV pages free/reserved, spec acceptance).
- ``Histogram``: serving-latency distributions with fixed bucket bounds —
  TTFT, inter-token latency, queue wait. Buckets are cumulative (Prometheus
  semantics), and ``observe(value, count=n)`` supports weighted observation
  so a segment crediting n tokens costs one lock acquisition, not n.

No third-party client library: exposition is the plain text format
(``# HELP`` / ``# TYPE`` / ``name{labels} value``, histograms as
``_bucket{le=...}``/``_sum``/``_count``), which is all a Prometheus scrape
needs. No jax import at module scope — the supervisor and the ``edgemesh
obs`` CLI must stay importable without a backend.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Callable, Iterable

# Serving-tuned bucket bounds. End-to-end latencies (queue wait, TTFT,
# request latency, prefill) span ~1 ms interactive to ~60 s batch-overload;
# inter-token latency sits an order of magnitude lower.
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)
INTER_TOKEN_BUCKETS = (
    0.0005, 0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0,
)


def _fmt(v: float) -> str:
    """Prometheus sample-value formatting: integers without a trailing
    ``.0``, floats via repr-shortest, infinities as ``+Inf``/``-Inf``."""
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(float(v)) if isinstance(v, float) else str(v)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(names: tuple[str, ...], values: tuple[str, ...],
               extra: tuple[tuple[str, str], ...] = ()) -> str:
    pairs = [f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)]
    pairs += [f'{n}="{_escape_label(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _Counter:
    __slots__ = ("value", "_lock")

    def __init__(self, lock: threading.RLock):
        self.value = 0.0
        self._lock = lock

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counters only go up (inc by {amount})")
        with self._lock:
            self.value += amount


class _Gauge:
    __slots__ = ("value", "_lock")

    def __init__(self, lock: threading.RLock):
        self.value = 0.0
        self._lock = lock

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class _Histogram:
    __slots__ = ("buckets", "counts", "sum", "count", "_lock")

    def __init__(self, buckets: tuple[float, ...], lock: threading.RLock):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # last = +Inf overflow
        self.sum = 0.0
        self.count = 0
        self._lock = lock

    def observe(self, value: float, count: int = 1) -> None:
        if count < 1:
            return
        with self._lock:
            self.sum += value * count
            self.count += count
            for i, b in enumerate(self.buckets):
                if value <= b:
                    self.counts[i] += count
                    return
            self.counts[-1] += count

    def cumulative(self) -> list[int]:
        """Per-bucket cumulative counts (Prometheus ``le`` semantics),
        +Inf last — always equal to ``count``."""
        out, acc = [], 0
        with self._lock:
            for c in self.counts:
                acc += c
                out.append(acc)
        return out


class _Family:
    """One named metric of one type, holding a child per label-value tuple."""

    def __init__(self, name: str, mtype: str, help: str,
                 labelnames: tuple[str, ...], lock: threading.RLock,
                 buckets: tuple[float, ...] = LATENCY_BUCKETS):
        self.name = name
        self.type = mtype
        self.help = help
        self.labelnames = labelnames
        self.buckets = buckets
        self._lock = lock
        self._children: dict[tuple[str, ...], Any] = {}

    def labels(self, **labelvalues: str):
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name} takes labels {self.labelnames}, "
                f"got {tuple(labelvalues)}"
            )
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if self.type == "counter":
                    child = _Counter(self._lock)
                elif self.type == "gauge":
                    child = _Gauge(self._lock)
                else:
                    child = _Histogram(self.buckets, self._lock)
                self._children[key] = child
        return child

    # Label-less families act as their own single child.
    def _default(self):
        return self.labels()

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    def set(self, value: float) -> None:
        self._default().set(value)

    def observe(self, value: float, count: int = 1) -> None:
        self._default().observe(value, count)

    def remove(self, **labelvalues: str) -> bool:
        """Drop the child for one label-value tuple (idempotent). The
        registry hygiene seam: per-replica gauges (canary scores) must die
        with the replica or /metrics accretes series for every replica
        that ever registered. Counters/histograms are cumulative by
        contract — only call this for gauges keyed by entity identity."""
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name} takes labels {self.labelnames}, "
                f"got {tuple(labelvalues)}"
            )
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            return self._children.pop(key, None) is not None

    def items(self) -> list[tuple[tuple[str, ...], Any]]:
        with self._lock:
            return sorted(self._children.items())


class Registry:
    """Thread-safe collection of metric families + scrape-time collectors.

    Collectors are callables run (best-effort) at the top of every
    ``render()``/``snapshot()``/``summary()`` — the hook device gauges use
    to sample ``memory_stats()`` only when someone is actually looking.
    """

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._families: dict[str, _Family] = {}
        self._collectors: list[Callable[["Registry"], None]] = []

    # -- family constructors (idempotent) -----------------------------------

    def _family(self, name: str, mtype: str, help: str,
                labelnames: Iterable[str], **kw) -> _Family:
        labelnames = tuple(labelnames)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.type != mtype or fam.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} re-registered as {mtype}"
                        f"{labelnames} (was {fam.type}{fam.labelnames})"
                    )
                return fam
            fam = _Family(name, mtype, help, labelnames, self._lock, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Iterable[str] = ()) -> _Family:
        return self._family(name, "counter", help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Iterable[str] = ()) -> _Family:
        return self._family(name, "gauge", help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Iterable[str] = (),
                  buckets: tuple[float, ...] = LATENCY_BUCKETS) -> _Family:
        return self._family(name, "histogram", help, labelnames,
                            buckets=tuple(buckets))

    # -- collectors ----------------------------------------------------------

    def add_collector(self, fn: Callable[["Registry"], None]) -> None:
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def remove_collector(self, fn: Callable[["Registry"], None]) -> None:
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def _run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn(self)
            except Exception:  # a broken collector must not kill the scrape
                pass

    # -- output --------------------------------------------------------------

    def render(self) -> str:
        """Prometheus text exposition (content type
        ``text/plain; version=0.0.4``)."""
        self._run_collectors()
        lines: list[str] = []
        with self._lock:
            families = sorted(self._families.values(), key=lambda f: f.name)
        for fam in families:
            if not fam.items():
                continue
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.type}")
            for key, child in fam.items():
                base = _label_str(fam.labelnames, key)
                if fam.type in ("counter", "gauge"):
                    lines.append(f"{fam.name}{base} {_fmt(child.value)}")
                else:
                    cum = child.cumulative()
                    for b, c in zip((*fam.buckets, math.inf), cum):
                        le = _label_str(fam.labelnames, key,
                                        extra=(("le", _fmt(b)),))
                        lines.append(f"{fam.name}_bucket{le} {c}")
                    lines.append(f"{fam.name}_sum{base} {_fmt(child.sum)}")
                    lines.append(f"{fam.name}_count{base} {child.count}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict[str, Any]:
        """Full JSON-friendly dump: every family, every labeled child,
        histograms with per-bucket cumulative counts."""
        self._run_collectors()
        out: dict[str, Any] = {}
        with self._lock:
            families = list(self._families.values())
        for fam in families:
            samples = []
            for key, child in fam.items():
                labels = dict(zip(fam.labelnames, key))
                if fam.type in ("counter", "gauge"):
                    samples.append({"labels": labels, "value": child.value})
                else:
                    samples.append({
                        "labels": labels,
                        "sum": child.sum,
                        "count": child.count,
                        "buckets": dict(zip(
                            [_fmt(b) for b in (*fam.buckets, math.inf)],
                            child.cumulative(),
                        )),
                    })
            if samples:
                out[fam.name] = {"type": fam.type, "help": fam.help,
                                 "samples": samples}
        return out

    def summary(self, prefix: str = "") -> dict[str, Any]:
        """Compact flat view for result JSON: ``name{labels}`` → value for
        counters/gauges, ``{count, sum, mean}`` for histograms."""
        self._run_collectors()
        out: dict[str, Any] = {}
        with self._lock:
            families = list(self._families.values())
        for fam in families:
            if prefix and not fam.name.startswith(prefix):
                continue
            for key, child in fam.items():
                k = fam.name + _label_str(fam.labelnames, key)
                if fam.type in ("counter", "gauge"):
                    out[k] = child.value
                elif child.count:
                    out[k] = {
                        "count": child.count,
                        "sum": round(child.sum, 6),
                        "mean": round(child.sum / child.count, 6),
                    }
        return out


# ---------------------------------------------------------------------------
# Bounded label values (the tenant-cardinality guard)
# ---------------------------------------------------------------------------

#: Per-namespace cap on distinct label values minted from request data. A
#: metric label derived from client-controlled strings (tenant ids, session
#: ids) is an unbounded-cardinality bomb: every new value is a new time
#: series on every family that carries the label, and one abusive client
#: can mint millions. 32 covers every legitimate multi-tenant deployment
#: this repo targets; everything past the cap collapses into ``other``.
BOUNDED_LABEL_MAX = 32

#: The overflow bucket every out-of-budget value collapses into.
OTHER_LABEL = "other"

_bounded_lock = threading.Lock()
_bounded_seen: dict[str, set[str]] = {}


def bounded_label(value, namespace: str = "tenant",
                  allow: Iterable[str] | None = None,
                  max_values: int = BOUNDED_LABEL_MAX,
                  default: str = "default") -> str:
    """Normalize one raw request-derived string into a BOUNDED label value.

    This is the only sanctioned path from client-controlled data (tenant /
    session / user strings) to a metric label — edgelint EM112 flags
    ``.labels(tenant=...)`` values that do not flow through it. Rules:

    - ``None`` / empty / non-string → ``default`` (the single-tenant case
      keeps one stable series instead of none).
    - Values are sanitized to ``[a-zA-Z0-9_.:-]`` (other bytes → ``_``) and
      truncated to 64 chars — a label value must never smuggle exposition
      syntax or unbounded payload bytes into ``/metrics``.
    - With ``allow``, only listed values pass; everything else is
      ``OTHER_LABEL`` and the seen-set never grows.
    - Without an allowlist, the first ``max_values`` distinct values per
      ``namespace`` pass through; later ones collapse into ``OTHER_LABEL``
      (first-come keeps the legitimate steady-state tenants, the abuser who
      mints fresh ids per request lands in one bucket).
    """
    if not isinstance(value, str) or not value:
        return default
    cleaned = "".join(
        ch if (ch.isalnum() and ch.isascii()) or ch in "_.:-" else "_"
        for ch in value[:64]
    )
    if not cleaned:
        return default
    if allow is not None:
        return cleaned if cleaned in set(allow) else OTHER_LABEL
    with _bounded_lock:
        seen = _bounded_seen.setdefault(namespace, set())
        if cleaned in seen:
            return cleaned
        if len(seen) >= max_values:
            return OTHER_LABEL
        seen.add(cleaned)
        return cleaned


def reset_bounded_labels(namespace: str | None = None) -> None:
    """Forget the seen-sets (tests isolate through this; production never
    calls it — forgetting would re-admit values past the cap)."""
    with _bounded_lock:
        if namespace is None:
            _bounded_seen.clear()
        else:
            _bounded_seen.pop(namespace, None)


_default_registry = Registry()
_default_lock = threading.Lock()


def get_registry() -> Registry:
    """The process-wide default registry (what ``/metrics`` serves)."""
    return _default_registry


def set_registry(registry: Registry) -> Registry:
    """Swap the process default (tests install a fresh one for isolation).
    Returns the previous default."""
    global _default_registry
    with _default_lock:
        prev, _default_registry = _default_registry, registry
    return prev
