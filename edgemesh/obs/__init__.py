"""edgemesh.obs — unified telemetry for the serving stack.

Three pieces (docs/OBSERVABILITY.md is the operator-facing reference):

- ``metrics``: thread-safe labeled Counter/Gauge/Histogram registry with
  Prometheus text exposition (``Registry.render()``) — what ``GET /metrics``
  serves.
- ``spans``: request-lifecycle span trees (queued → prefill → decode
  segments → retire) recorded by the continuous engines, flushed as JSONL,
  and replayable into the same registry aggregates offline.
- ``device``: scrape-time gauges over ``jax.local_devices()``
  ``memory_stats()`` and live-buffer counts.
- ``trace``: distributed tracing — the ``X-Edgemesh-Trace`` context the
  fleet router propagates to replicas, cross-process trace assembly with
  clock-skew correction (``edgemesh obs trace``), and the JAX
  compile-telemetry hook.
- ``slo``: SLO goodput — TTFT/TPOT targets, per-request classification
  (``edgemesh_slo_goodput_ratio``), and the decayed latency quantiles the
  fleet router's hedge auto-tuner reads.

Importing this package never imports jax — device sampling defers the
import to scrape time, so the supervisor and the ``edgemesh obs`` CLI stay
backend-free.
"""

from edgemesh.obs.device import register_device_gauges  # noqa: F401
from edgemesh.obs.metrics import (  # noqa: F401
    INTER_TOKEN_BUCKETS,
    LATENCY_BUCKETS,
    OTHER_LABEL,
    Registry,
    bounded_label,
    get_registry,
    reset_bounded_labels,
    set_registry,
)
from edgemesh.obs.slo import (  # noqa: F401
    DecayingQuantile,
    SloTarget,
    SloTracker,
    StreamMeter,
)
from edgemesh.obs.spans import (  # noqa: F401
    RequestTrace,
    SpanTracker,
    replay_spans,
)
from edgemesh.obs.trace import (  # noqa: F401
    TRACE_HEADER,
    TraceContext,
    assemble_trace,
    critical_path,
    current_trace,
    install_compile_hook,
    load_trace,
    seconds_since_last_compile,
    uninstall_compile_hook,
    use_trace,
)
