"""edgemesh.obs — unified telemetry for the serving stack.

Three pieces (docs/OBSERVABILITY.md is the operator-facing reference):

- ``metrics``: thread-safe labeled Counter/Gauge/Histogram registry with
  Prometheus text exposition (``Registry.render()``) — what ``GET /metrics``
  serves.
- ``spans``: request-lifecycle span trees (queued → prefill → decode
  segments → retire) recorded by the continuous engines, flushed as JSONL,
  and replayable into the same registry aggregates offline.
- ``device``: scrape-time gauges over ``jax.local_devices()``
  ``memory_stats()`` and live-buffer counts.
- ``trace``: distributed tracing — the ``X-Edgemesh-Trace`` context the
  fleet router propagates to replicas, cross-process trace assembly with
  clock-skew correction (``edgemesh obs trace``), and the JAX
  compile-telemetry hook.
- ``slo``: SLO goodput — TTFT/TPOT targets, per-request classification
  (``edgemesh_slo_goodput_ratio``), and the decayed latency quantiles the
  fleet router's hedge auto-tuner reads.
- ``flight``: the always-on bounded flight-recorder ring (full-fidelity
  span records regardless of sampling) that dumps to an incident
  directory on trigger, plus the incident postmortem assembly.
- ``anomaly``: the triggers that fire it — SLO-miss burst vs a decayed
  baseline, admission-queue collapse, error spike, compile storm — and
  the fleet incident-id propagation seam.
- ``compute``: the compute observatory — per-launch device-time
  attribution over every jitted serving boundary (sampled fenced
  timings, once-per-compile cost_analysis capture, roofline scoring)
  plus the speculative round ledger.
- ``memory``: the memory observatory — the page-lifecycle PoolLedger
  every KV-pool transition reports through (per-tenant attribution,
  conservation invariant, leak tripwires, exhaustion forecast) plus the
  offline span-log twins.
- ``quality``: the quality observatory — per-request confidence/entropy
  from the decode loop, pairwise token-F1 agreement, per-tenant goodness
  gauges, the quality-drift incident feed, and the offline span-log
  twin (``edgemesh obs quality``).

Importing this package never imports jax — device sampling defers the
import to scrape time, so the supervisor and the ``edgemesh obs`` CLI stay
backend-free.
"""

from edgemesh.obs.anomaly import (  # noqa: F401
    AnomalyMonitor,
    CompileStormDetector,
    ErrorSpikeDetector,
    PoolLeakDetector,
    QualityDriftDetector,
    QueueCollapseDetector,
    SloBurstDetector,
)
from edgemesh.obs.compute import (  # noqa: F401
    LAUNCH_RECORD_EVENT,
    SPEC_ROUND_RECORD_EVENT,
    ComputeLedger,
    SpecRoundLedger,
    ambient_ledger,
    device_peaks,
    diff_compute,
    ledger_scope,
    roofline_fraction,
    spec_draft_frac,
    summarize_compute,
)
from edgemesh.obs.device import register_device_gauges  # noqa: F401
from edgemesh.obs.memory import (  # noqa: F401
    POOL_RECORD_EVENT,
    PoolLedger,
    diff_mem,
    replay_pool_record,
    summarize_mem,
)
from edgemesh.obs.flight import (  # noqa: F401
    FlightRecorder,
    assemble_incident,
)
from edgemesh.obs.metrics import (  # noqa: F401
    INTER_TOKEN_BUCKETS,
    LATENCY_BUCKETS,
    OTHER_LABEL,
    Registry,
    bounded_label,
    get_registry,
    reset_bounded_labels,
    set_registry,
)
from edgemesh.obs.quality import (  # noqa: F401
    CANARY_RECORD_EVENT,
    QualityTracker,
    pairwise_agreement,
    summarize_quality,
    token_f1,
)
from edgemesh.obs.slo import (  # noqa: F401
    DecayingQuantile,
    SloTarget,
    SloTracker,
    StreamMeter,
)
from edgemesh.obs.spans import (  # noqa: F401
    RequestTrace,
    SpanTracker,
    replay_spans,
)
from edgemesh.obs.trace import (  # noqa: F401
    TRACE_HEADER,
    TraceContext,
    assemble_trace,
    critical_path,
    current_trace,
    install_compile_hook,
    load_trace,
    seconds_since_last_compile,
    uninstall_compile_hook,
    use_trace,
)
