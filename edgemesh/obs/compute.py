"""The compute observatory: per-launch device-time attribution.

The serving stack is a handful of jitted boundaries (the dense decode
loop, the ragged boundary launch, the paged prefill/splice programs, the
speculative draft→verify round loop, the tp shard_map programs). Spans
see *requests* and the flight ring sees *events*, but nothing attributes
wall time to a specific launch, or prices a launch against an analytic
FLOP/byte budget — which is exactly why the 2.8x speculative loss and
the unpinned on-chip numbers stall on scarce hardware windows
(docs/PERFORMANCE.md). :class:`ComputeLedger` closes that gap with two
ingredients per boundary:

- a static **cost model**, captured once per compile key from
  ``jitted.lower(...).compile().cost_analysis()`` via the
  ``utils/compat.aot_cost_analysis`` shim (flops / bytes accessed /
  output bytes, each degrading to None where XLA withholds it). The key
  is the same identity the compile cache uses — the call-site's shape
  bucket — so a new key means a new compile, and ``compiles`` in the
  rollup counts exactly the distinct programs a boundary paid for.
- **measured device time** from a *sampled* sync: 1-in-N launches (the
  first post-compile launch, then every Nth) pay one
  ``utils/platform.device_sync`` fence — a real completion fence on the
  tunneled TPU platform, where ``block_until_ready`` returns early —
  and the measured seconds feed per-boundary EWMAs, the
  ``edgemesh_launch_seconds`` histogram, a ``launch`` span record, and
  (when attached) the flight ring. Steady-state dispatch stays async:
  the other N-1 launches cost two counter bumps. ``N`` comes from
  ``EDGEMESH_COMPUTE_SAMPLE`` (default 16; ``0`` disables the ledger
  entirely — the overhead-gate arm benchmarks.py flips).

Roofline: with a device peak model (``device_peaks``), a measured
launch's ``achieved_flops_s = flops / measured_s`` is scored against
``min(peak_flops_s, intensity * peak_bytes_s)`` where ``intensity =
flops / bytes_accessed`` — the classic roofline attainable. The
fraction is None wherever any input is unknown (CPU has no peak model;
XLA may withhold the cost table): the ledger never guesses.

Importing this module never imports jax (the obs package contract);
every device touch lives inside ``launch()`` and runs lazily.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from pathlib import Path
from typing import Any, Callable

from edgemesh.obs.metrics import Registry, get_registry
from edgemesh.obs.spans import EWMA_ALPHA

#: Span-log event names (the obs JSONL one-record-vocabulary — edgelint
#: EM113): one ``launch`` record per *measured* launch, one
#: ``spec_rounds`` record per measured speculative segment.
LAUNCH_RECORD_EVENT = "launch"
SPEC_ROUND_RECORD_EVENT = "spec_rounds"

#: 1-in-N launch sampling rate (see module docstring). 0 disables.
SAMPLE_ENV = "EDGEMESH_COMPUTE_SAMPLE"
DEFAULT_SAMPLE = 16

#: Launch durations sit well under the request-latency buckets: a CPU
#: test segment is ~1-100ms, an on-chip decode segment ~1-10ms, a cold
#: ragged boundary can reach seconds.
LAUNCH_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5,
)

# Peak (flops/s, HBM bytes/s) per accelerator generation, keyed on a
# substring of jax's device_kind. bf16 dense peaks — the serving
# forwards' unit of account. Absent kinds (CPU first among them) get no
# peak model and therefore no roofline fractions; the env overrides let
# a hardware window calibrate without a code change.
PEAK_FLOPS_ENV = "EDGEMESH_PEAK_FLOPS"
PEAK_BYTES_ENV = "EDGEMESH_PEAK_BYTES"
_DEVICE_PEAKS = (
    ("v6e", (918e12, 1.64e12)),
    ("v5p", (459e12, 2.765e12)),
    ("v5e", (197e12, 0.82e12)),
    ("v5 lite", (197e12, 0.82e12)),
    ("v4", (275e12, 1.23e12)),
)


def device_peaks() -> tuple[float, float] | None:
    """(peak_flops_s, peak_bytes_s) for the default device, or None when
    unknown (CPU, unrecognized kinds). Env overrides win; any probe
    failure degrades to None — the roofline column goes blank, the
    ledger keeps measuring."""
    try:
        env_f, env_b = os.environ.get(PEAK_FLOPS_ENV), os.environ.get(PEAK_BYTES_ENV)
        if env_f and env_b:
            return float(env_f), float(env_b)
        import jax

        kind = jax.devices()[0].device_kind.lower()
        for needle, peaks in _DEVICE_PEAKS:
            if needle in kind:
                return peaks
    except Exception:
        return None
    return None


def roofline_fraction(flops, bytes_accessed, measured_s,
                      peaks: tuple[float, float] | None) -> float | None:
    """achieved / attainable under the roofline model; None when any
    input is unknown or degenerate (the ledger reports no claim rather
    than a guess — same convention as the capacity model)."""
    if not peaks or not flops or not bytes_accessed or not measured_s:
        return None
    peak_flops_s, peak_bytes_s = peaks
    attainable = min(peak_flops_s, (flops / bytes_accessed) * peak_bytes_s)
    if attainable <= 0:
        return None
    return min(1.0, (flops / measured_s) / attainable)


class _Boundary:
    """Per-boundary ledger cell. Owned by the dispatching thread (the
    engine worker); the lock in ComputeLedger guards only cross-thread
    *reads* (rollup / digest from gateway threads)."""

    __slots__ = (
        "launches", "measured", "since_measure", "device_s", "ewma_s",
        "ewma_tok_s", "tokens", "costs", "key_counts", "last_measured_s",
        "roofline", "last_key",
    )

    def __init__(self) -> None:
        self.launches = 0
        self.measured = 0
        self.since_measure = 0
        self.device_s = 0.0
        self.ewma_s: float | None = None
        self.ewma_tok_s: float | None = None
        self.tokens = 0
        self.costs: dict[str, dict | None] = {}
        self.key_counts: dict[str, int] = {}
        self.last_measured_s: float | None = None
        self.last_key = "static"
        self.roofline: float | None = None


def _ewma(prev: float | None, x: float) -> float:
    return x if prev is None else prev + EWMA_ALPHA * (x - prev)


class ComputeLedger:
    """Launch ledger for one engine's jitted boundaries.

    ``launch(boundary, fn, *args, key=..., tokens=...)`` dispatches
    ``fn(*args)`` and does the ledger work around it; ``wrap`` curries a
    call-site into a drop-in callable. ``key`` is the call-site's shape
    bucket — the compile-cache identity (e.g. ``"c64s16"`` for a ragged
    boundary at cap 64 / s_cap 16); omitted means the boundary compiles
    once (``"static"``).
    """

    def __init__(self, registry: Registry | None = None,
                 engine: str = "continuous",
                 span_log: str | Path | None = None,
                 sample: int | None = None,
                 peaks: tuple[float, float] | None = None,
                 flight_source: Callable[[], Any] | None = None):
        self.registry = registry or get_registry()
        self.engine = engine
        if sample is None:
            sample = int(os.environ.get(SAMPLE_ENV, str(DEFAULT_SAMPLE)))
        self.sample = int(sample)
        self.enabled = self.sample > 0
        self._peaks = peaks if peaks is not None else device_peaks()
        self._flight_source = flight_source
        self._lock = threading.Lock()
        self._boundaries: dict[str, _Boundary] = {}
        self._log = None
        if span_log is not None and self.enabled:
            from edgemesh.utils.tracing import JsonlLogger

            self._log = JsonlLogger(span_log)
        reg = self.registry
        self._launches_total = reg.counter(
            "edgemesh_launches_total",
            "Jitted boundary launches dispatched", ("engine", "boundary"))
        self._launch_seconds = reg.histogram(
            "edgemesh_launch_seconds",
            "Sampled fenced launch wall time per boundary",
            ("engine", "boundary"), buckets=LAUNCH_BUCKETS)
        self._roofline_gauge = reg.gauge(
            "edgemesh_launch_roofline_ratio",
            "Last sampled achieved/attainable roofline fraction",
            ("engine", "boundary"))

    # -- dispatch seam ------------------------------------------------------

    def launch(self, boundary: str, fn, *args,
               key: str | None = None, tokens: int = 0,
               measure: bool | None = None):
        """Dispatch ``fn(*args)`` through the ledger. ``tokens`` credits
        generated/processed tokens to the boundary's throughput EWMA;
        ``measure=True`` forces the fence (standalone paths that sync
        anyway), ``None`` applies the 1-in-N sampling rule."""
        if not self.enabled:
            return fn(*args)
        st = self._boundaries.get(boundary)
        if st is None:
            with self._lock:
                st = self._boundaries.setdefault(boundary, _Boundary())
        k = key or "static"
        first_key = k not in st.costs
        specs = None
        if first_key:
            # Claim the key BEFORE dispatch and snapshot abstract shapes:
            # donated args are deleted by the launch itself, and a
            # concurrent rollup must never see a half-captured cost row.
            st.costs[k] = None
            specs = _arg_specs(args)
        st.launches += 1
        st.since_measure += 1
        st.key_counts[k] = st.key_counts.get(k, 0) + 1
        st.last_key = k
        if tokens:
            st.tokens += tokens
        self._launches_total.labels(engine=self.engine, boundary=boundary).inc()
        # Never time a first-key launch: it pays the compile, which would
        # poison the EWMA by orders of magnitude. The compile hook
        # (obs/trace.py) already owns compile-time attribution.
        do_measure = (not first_key) and (
            measure if measure is not None
            else (st.measured == 0 or st.since_measure >= self.sample)
        )
        t0 = time.perf_counter() if do_measure else 0.0
        out = fn(*args)
        if first_key:
            from edgemesh.utils.compat import aot_cost_analysis

            st.costs[k] = aot_cost_analysis(fn, specs)
        if do_measure:
            _fence(out)
            dt = time.perf_counter() - t0
            self._record(boundary, st, k, dt, tokens)
        return out

    def wrap(self, boundary: str, fn, key: str | None = None,
             key_fn: Callable[..., str] | None = None):
        """Drop-in instrumented callable for a fixed boundary.
        ``key_fn(*args)`` derives the shape bucket per call when the
        call-site's shapes vary (tp prefill pads per prompt bucket)."""
        if not self.enabled:
            return fn

        def wrapped(*args):
            k = key_fn(*args) if key_fn is not None else key
            return self.launch(boundary, fn, *args, key=k)

        return wrapped

    def _record(self, boundary: str, st: _Boundary, key: str,
                dt: float, tokens: int) -> None:
        st.measured += 1
        st.since_measure = 0
        st.device_s += dt
        st.ewma_s = _ewma(st.ewma_s, dt)
        if tokens and dt > 0:
            st.ewma_tok_s = _ewma(st.ewma_tok_s, tokens / dt)
        st.last_measured_s = dt
        cost = st.costs.get(key) or {}
        flops = cost.get("flops")
        achieved = flops / dt if flops and dt > 0 else None
        frac = roofline_fraction(
            flops, cost.get("bytes_accessed"), dt, self._peaks)
        if frac is not None:
            st.roofline = frac
            self._roofline_gauge.labels(
                engine=self.engine, boundary=boundary).set(frac)
        self._launch_seconds.labels(
            engine=self.engine, boundary=boundary).observe(dt)
        rec = {
            "engine": self.engine,
            "boundary": boundary,
            "key": key,
            "measured_s": round(dt, 6),
            "flops": flops,
            "bytes": cost.get("bytes_accessed"),
            "output_bytes": cost.get("output_bytes"),
            "achieved_flops_s": None if achieved is None else round(achieved, 1),
            "roofline_fraction": None if frac is None else round(frac, 4),
            "tokens": tokens,
            "launches": st.launches,
        }
        if self._log is not None:
            self._log.log(LAUNCH_RECORD_EVENT, **rec)
        self._flight(LAUNCH_RECORD_EVENT, rec)

    def _flight(self, event: str, rec: dict) -> None:
        if self._flight_source is None:
            return
        try:
            fl = self._flight_source()
            if fl is not None:
                fl.record(event, rec)
        except Exception:  # flight is best-effort by contract
            pass

    def consume_measured(self, boundary: str) -> float | None:
        """Pop the newest sampled measurement for ``boundary`` (None when
        no launch was measured since the last call). The speculative
        round ledger associates segment deltas with segment timings
        through this — both run on the engine worker."""
        st = self._boundaries.get(boundary)
        if st is None or st.last_measured_s is None:
            return None
        dt, st.last_measured_s = st.last_measured_s, None
        return dt

    # -- read side ----------------------------------------------------------

    def rollup(self) -> dict[str, dict]:
        """Per-boundary aggregate — what benchmarks attach to BENCH JSON
        and ``edgemesh obs compute`` renders from live state."""
        out: dict[str, dict] = {}
        with self._lock:
            items = list(self._boundaries.items())
        for b, st in items:
            cost = st.costs.get(st.last_key) or {}
            out[b] = {
                "launches": st.launches,
                "measured": st.measured,
                "compiles": len(st.costs),
                "device_s": round(st.device_s, 6),
                "ewma_launch_s": (
                    None if st.ewma_s is None else round(st.ewma_s, 6)),
                "roofline_fraction": st.roofline,
                "flops": cost.get("flops"),
                "bytes": cost.get("bytes_accessed"),
                "shape_buckets": dict(st.key_counts),
            }
        return out

    def digest_costs(self) -> dict[str, dict] | None:
        """The load digest's per-boundary cost block: measured launch
        EWMAs + throughput, compact enough to ship on every probe. None
        until something was measured — pre-compute consumers (and old
        routers) see exactly the digest they always did."""
        out: dict[str, dict] = {}
        with self._lock:
            items = list(self._boundaries.items())
        for b, st in items:
            if st.ewma_s is None:
                continue
            out[b] = {
                "ewma_launch_s": round(st.ewma_s, 6),
                "launches": st.launches,
                "tok_s": (
                    None if st.ewma_tok_s is None
                    else round(st.ewma_tok_s, 3)),
                "roofline": st.roofline,
            }
        return out or None

    def measured_tok_s(
            self, boundaries: tuple[str, ...] = ("decode_loop",),
    ) -> float | None:
        """Measured decode throughput (tok/s from fenced launch time)
        over the named DECODE boundaries — the capacity model's measured
        replacement for the host-EWMA-derived ``est_tok_s``. Explicitly
        scoped: prefill boundaries also credit tokens, at an order of
        magnitude higher tok/s, and must never inflate a decode
        capacity claim."""
        best = None
        with self._lock:
            for b in boundaries:
                st = self._boundaries.get(b)
                if st is None or st.ewma_tok_s is None:
                    continue
                if best is None or st.ewma_tok_s > best:
                    best = st.ewma_tok_s
        return None if best is None else round(best, 3)


class SpecRoundLedger:
    """Round-structure attribution for speculative decoding.

    The serving engine's draft→verify rounds run fused in ONE jitted
    while_loop (``runtime/speculative._spec_rounds``) — a host timer
    cannot split draft from verify inside it. The ledger therefore
    attributes at the granularity that is measurable without breaking
    the fusion: per-segment deltas of the device round/accept/propose
    counters, paired with the compute ledger's sampled launch time for
    that segment, split draft-vs-verify by the **analytic flops ratio**
    (``draft_frac``: gamma draft decode steps against one gamma+1-token
    verify, priced at 2·params flops/token — the standard dense decode
    estimate). The split is labeled, not hidden: ``summary()["split"]``
    says ``analytic-flops`` so a reader knows which numbers are measured
    (round counts, acceptance, segment seconds) and which are modeled
    (the draft/verify partition)."""

    def __init__(self, ledger: ComputeLedger | None = None,
                 engine: str = "speculative",
                 draft_frac: float | None = None):
        self._ledger = ledger
        self.engine = engine
        self.draft_frac = draft_frac
        self.rounds = 0
        self.accepted = 0
        self.proposed = 0
        self.segments = 0
        self.measured_segments = 0
        self.measured_s = 0.0
        self.measured_rounds = 0

    def on_segment(self, rounds: int, accepted: int, proposed: int,
                   measured_s: float | None = None) -> None:
        """Credit one processed segment's counter deltas. Negative deltas
        mean the pool (and its device counters) reset mid-flight — skip
        the segment rather than corrupt the ledger."""
        if rounds < 0 or accepted < 0 or proposed < 0:
            return
        self.segments += 1
        self.rounds += rounds
        self.accepted += accepted
        self.proposed += proposed
        if measured_s is None or rounds <= 0:
            return
        self.measured_segments += 1
        self.measured_s += measured_s
        self.measured_rounds += rounds
        ledger = self._ledger
        if ledger is not None and ledger._log is not None:
            df = self.draft_frac
            ledger._log.log(
                SPEC_ROUND_RECORD_EVENT,
                engine=self.engine, rounds=rounds, accepted=accepted,
                proposed=proposed, measured_s=round(measured_s, 6),
                round_s=round(measured_s / rounds, 6),
                draft_s=(None if df is None else round(measured_s * df, 6)),
                verify_s=(None if df is None else round(measured_s * (1 - df), 6)),
                draft_frac=df, split="analytic-flops",
            )

    def summary(self) -> dict[str, Any] | None:
        """The ``spec_round_ledger`` block (stats(), BENCH JSON). None
        before any round ran."""
        if self.rounds <= 0:
            return None
        df = self.draft_frac
        round_s = (
            self.measured_s / self.measured_rounds
            if self.measured_rounds else None
        )
        return {
            "rounds": self.rounds,
            "accepted": self.accepted,
            "proposed": self.proposed,
            "rejected": max(self.proposed - self.accepted, 0),
            "accept_rate": (
                round(self.accepted / self.proposed, 4) if self.proposed else None),
            "accepted_per_round": round(self.accepted / self.rounds, 3),
            "segments": self.segments,
            "measured_segments": self.measured_segments,
            "measured_s": round(self.measured_s, 6),
            "round_s": None if round_s is None else round(round_s, 6),
            "draft_s": (
                None if round_s is None or df is None
                else round(self.measured_s * df, 6)),
            "verify_s": (
                None if round_s is None or df is None
                else round(self.measured_s * (1 - df), 6)),
            "draft_frac": df,
            "split": "analytic-flops",
        }


def spec_draft_frac(target_params, draft_params, gamma: int) -> float | None:
    """Analytic draft share of one round's flops: gamma draft decode
    steps vs one (gamma+1)-token target verify, each priced at the dense
    2·params flops/token estimate. Param counts come from the live trees
    so quantized/tied variants price what they actually carry."""
    try:
        import jax

        def count(tree) -> float:
            return float(sum(
                x.size for x in jax.tree_util.tree_leaves(tree)
                if hasattr(x, "size")
            ))

        pt, pd = count(target_params), count(draft_params)
        draft = gamma * 2.0 * pd
        verify = (gamma + 1) * 2.0 * pt
        if draft + verify <= 0:
            return None
        return round(draft / (draft + verify), 4)
    except Exception:
        return None


# -- offline analysis (span logs → rollup) ----------------------------------


def _mean(xs: list[float]) -> float | None:
    return round(sum(xs) / len(xs), 6) if xs else None


def summarize_compute(records) -> dict | None:
    """Per-boundary rollup from span-log records — the offline twin of
    :meth:`ComputeLedger.rollup`, consumed by ``edgemesh obs compute``
    and the ``compute`` block of ``edgemesh obs summary``.

    Returns None when the log carries no compute records at all: a
    pre-compute log is an answer, not an error (the CLI prints null and
    exits 0 — same forward-compat contract as the pre-SLO span fields).
    Unknown keys on launch records are ignored and known-but-missing keys
    read as None, so logs written by NEWER builds summarize fine too —
    both directions are pinned in tests/test_compute.py.
    """
    bounds: dict[str, dict] = {}
    spec: dict | None = None
    n_launch = 0
    for rec in records:
        if not isinstance(rec, dict):
            continue
        event = rec.get("event")
        if event == LAUNCH_RECORD_EVENT:
            n_launch += 1
            b = str(rec.get("boundary") or "?")
            c = bounds.setdefault(b, {
                "engines": set(), "measured": 0, "device_s": 0.0,
                "samples": [], "launches": {}, "keys": {},
                "flops": None, "bytes": None, "output_bytes": None,
                "achieved": [], "roofline": [], "tokens": 0,
            })
            if rec.get("engine") is not None:
                c["engines"].add(str(rec["engine"]))
            dt = rec.get("measured_s")
            if isinstance(dt, (int, float)):
                c["measured"] += 1
                c["device_s"] += float(dt)
                c["samples"].append(float(dt))
            # ``launches`` is the cumulative dispatch counter at record
            # time — newest wins, summed across engines sharing a name.
            if isinstance(rec.get("launches"), int):
                c["launches"][rec.get("engine")] = rec["launches"]
            if rec.get("key") is not None:
                k = str(rec["key"])
                c["keys"][k] = c["keys"].get(k, 0) + 1
            for field in ("flops", "bytes", "output_bytes"):
                if isinstance(rec.get(field), (int, float)):
                    c[field] = float(rec[field])
            if isinstance(rec.get("achieved_flops_s"), (int, float)):
                c["achieved"].append(float(rec["achieved_flops_s"]))
            if isinstance(rec.get("roofline_fraction"), (int, float)):
                c["roofline"].append(float(rec["roofline_fraction"]))
            if isinstance(rec.get("tokens"), int):
                c["tokens"] += rec["tokens"]
        elif event == SPEC_ROUND_RECORD_EVENT:
            if spec is None:
                spec = {"records": 0, "rounds": 0, "accepted": 0,
                        "proposed": 0, "measured_s": 0.0, "draft_s": 0.0,
                        "verify_s": 0.0, "split_s": 0,
                        "draft_frac": None, "split": None}
            spec["records"] += 1
            for field in ("rounds", "accepted", "proposed"):
                if isinstance(rec.get(field), int):
                    spec[field] += rec[field]
            if isinstance(rec.get("measured_s"), (int, float)):
                spec["measured_s"] += float(rec["measured_s"])
            if isinstance(rec.get("draft_s"), (int, float)) and \
                    isinstance(rec.get("verify_s"), (int, float)):
                spec["draft_s"] += float(rec["draft_s"])
                spec["verify_s"] += float(rec["verify_s"])
                spec["split_s"] += 1
            if rec.get("draft_frac") is not None:
                spec["draft_frac"] = rec["draft_frac"]
            if rec.get("split") is not None:
                spec["split"] = rec["split"]
    if n_launch == 0 and spec is None:
        return None
    total = sum(c["device_s"] for c in bounds.values())
    out: dict[str, dict] = {}
    for b, c in sorted(bounds.items()):
        xs = sorted(c["samples"])
        launches = sum(c["launches"].values()) or None
        out[b] = {
            "engines": sorted(c["engines"]),
            "launches": launches,
            "measured": c["measured"],
            "device_s": round(c["device_s"], 6),
            "share": round(c["device_s"] / total, 4) if total > 0 else None,
            "mean_s": (round(c["device_s"] / c["measured"], 6)
                       if c["measured"] else None),
            "p50_s": xs[len(xs) // 2] if xs else None,
            "max_s": xs[-1] if xs else None,
            "flops": c["flops"],
            "bytes": c["bytes"],
            "achieved_flops_s": _mean(c["achieved"]),
            "roofline_fraction": _mean(c["roofline"]),
            "tokens": c["tokens"] or None,
            "top_keys": dict(sorted(c["keys"].items(),
                                    key=lambda kv: -kv[1])[:3]),
        }
    spec_out = None
    if spec is not None:
        rounds, prop = spec["rounds"], spec["proposed"]
        spec_out = {
            "records": spec["records"],
            "rounds": rounds,
            "accepted": spec["accepted"],
            "proposed": prop,
            "rejected": max(prop - spec["accepted"], 0),
            "accept_rate": round(spec["accepted"] / prop, 4) if prop else None,
            "accepted_per_round": (
                round(spec["accepted"] / rounds, 3) if rounds else None),
            "measured_s": round(spec["measured_s"], 6),
            "round_s": (round(spec["measured_s"] / rounds, 6)
                        if rounds and spec["measured_s"] else None),
            "draft_s": (round(spec["draft_s"], 6)
                        if spec["split_s"] else None),
            "verify_s": (round(spec["verify_s"], 6)
                         if spec["split_s"] else None),
            "draft_frac": spec["draft_frac"],
            "split": spec["split"],
        }
    return {
        "launch_records": n_launch,
        "total_device_s": round(total, 6),
        "boundaries": out,
        "spec_rounds": spec_out,
    }


def diff_compute(a: dict | None, b: dict | None) -> dict:
    """Per-boundary comparison of two :func:`summarize_compute` results
    (``edgemesh obs compute A --diff B``): mean launch time, share of
    device time, and roofline fraction side by side, with the B/A mean
    ratio where both sides measured. Boundaries present on only one side
    still get a row — a boundary appearing or vanishing between two runs
    IS the finding."""
    ab = (a or {}).get("boundaries") or {}
    bb = (b or {}).get("boundaries") or {}
    out: dict[str, dict] = {}
    for name in sorted(set(ab) | set(bb)):
        ca, cb = ab.get(name), bb.get(name)
        am = (ca or {}).get("mean_s")
        bm = (cb or {}).get("mean_s")
        out[name] = {
            "a_mean_s": am,
            "b_mean_s": bm,
            "ratio": (round(bm / am, 4)
                      if am and bm and am > 0 else None),
            "a_share": (ca or {}).get("share"),
            "b_share": (cb or {}).get("share"),
            "a_roofline": (ca or {}).get("roofline_fraction"),
            "b_roofline": (cb or {}).get("roofline_fraction"),
        }
    return {
        "boundaries": out,
        "a_total_device_s": (a or {}).get("total_device_s"),
        "b_total_device_s": (b or {}).get("total_device_s"),
    }


# -- ambient ledger (standalone runtime paths) ------------------------------

_AMBIENT: list[ComputeLedger] = []


@contextmanager
def ledger_scope(ledger: ComputeLedger):
    """Install ``ledger`` as the ambient ledger for standalone runtime
    paths (runtime/generate.py, runtime/speculative.py route their
    launches through :func:`ambient_ledger` when one is installed —
    benchmarks wrap whole stages in this)."""
    _AMBIENT.append(ledger)
    try:
        yield ledger
    finally:
        _AMBIENT.remove(ledger)


def ambient_ledger() -> ComputeLedger | None:
    return _AMBIENT[-1] if _AMBIENT else None


# -- lazy jax helpers -------------------------------------------------------

def _arg_specs(args):
    """Abstract (shape, dtype) snapshot of a call's arguments for the
    AOT cost capture — jax array leaves become ShapeDtypeStructs, static
    leaves pass through. Must run BEFORE dispatch: donation deletes the
    concrete buffers."""
    try:
        import jax

        def spec(x):
            if isinstance(x, jax.Array):
                try:  # keep shardings: a tp program's cost is per-shard
                    return jax.ShapeDtypeStruct(
                        x.shape, x.dtype, sharding=getattr(x, "sharding", None))
                except Exception:  # pre-sharding ShapeDtypeStruct signature
                    return jax.ShapeDtypeStruct(x.shape, x.dtype)
            return x

        return jax.tree_util.tree_map(spec, args)
    except Exception:
        return args


def _fence(out) -> None:
    """Completion fence on a launch's first array output leaf.
    ``device_sync`` (a 1-element readback), NOT ``block_until_ready``:
    the tunneled TPU platform returns from the latter before the program
    finishes (utils/platform.py)."""
    try:
        import jax

        from edgemesh.utils.platform import device_sync

        for leaf in jax.tree_util.tree_leaves(out):
            if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                device_sync(leaf)
                return
    except Exception:
        pass
