"""The fleet flight recorder: always-on capture, dump-on-trigger.

``trace_sample`` keeps steady-state span I/O sparse, which is exactly
wrong during an incident: the requests you most want at full fidelity are
the ones just *before* the anomaly, and by the time an operator notices,
the sampled-out records are gone. The flight recorder resolves that
tension the way avionics does — record everything, all the time, into a
bounded ring that costs one deque append per retirement, and write it to
disk only when something goes wrong:

- :class:`FlightRecorder` — a per-replica in-memory ring of full-fidelity
  span records (every retirement, sampled or not) plus periodic
  engine/load-digest snapshots, dumped as JSONL into an incident
  directory when an anomaly trigger (obs/anomaly.py) — or an incident id
  propagated by the fleet router — fires.
- :func:`assemble_incident` — the postmortem: join every replica's flight
  dump (plus any router span logs) into one timeline with the trigger
  window marked, per-tenant goodput before/during/after, and the
  trigger-window critical-path split per replica (reusing the
  ``obs.trace`` assembly + critical-path machinery).

Dump records reuse the engines' span vocabulary (``request_spans`` /
``pool_reset``) verbatim, so every existing offline tool — ``edgemesh obs
summary``/``trace``/``replay`` — works on a flight dump unchanged; the
recorder adds only ``flight_snapshot`` (digest samples) and one
``flight_dump`` header per file. All writes go through
``utils.tracing.JsonlLogger`` — one producer vocabulary, enforced by
edgelint EM113. No jax anywhere (the standing ``edgemesh.obs`` import
contract).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Callable, Iterable

from edgemesh.obs.metrics import Registry, get_registry

#: Periodic engine/load-digest sample riding the ring between spans.
SNAPSHOT_EVENT = "flight_snapshot"
#: One per dump file: incident identity, trigger kind, replica, ring stats.
DUMP_EVENT = "flight_dump"

#: Default ring capacity: at a healthy replica's ~1-10 req/s this holds
#: the last ~30 s to 5 min of full-fidelity records in < 1 MB of host
#: memory (docs/OBSERVABILITY.md "Ring sizing").
DEFAULT_CAPACITY = 256


def default_replica_label() -> str:
    """The replica identity stamped on dumps: ``EDGEMESH_REPLICA_ID`` when
    the deployment set one (the fleet e2e does), else a pid-derived label —
    dumps from different replicas of one incident must not collide in the
    shared incident directory."""
    return os.environ.get("EDGEMESH_REPLICA_ID") or f"pid-{os.getpid()}"


class FlightRecorder:
    """Bounded always-on record ring; JSONL dump only when triggered."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 registry: Registry | None = None,
                 replica: str | None = None,
                 snapshot_source: Callable[[], dict] | None = None,
                 snapshot_interval_s: float = 5.0):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.replica = replica or default_replica_label()
        #: Called (opportunistically, on the record path) at most once per
        #: ``snapshot_interval_s`` to sample the live load digest into the
        #: ring — the dump then shows queue depth / EWMAs alongside the
        #: spans they explain. Must be cheap and jax-free (load_digest is).
        self.snapshot_source = snapshot_source
        self.snapshot_interval_s = float(snapshot_interval_s)
        self._lock = threading.Lock()
        self._ring: deque[dict[str, Any]] = deque(maxlen=self.capacity)
        self._last_snapshot = 0.0  # guarded by: _lock
        self._dropped = 0  # guarded by: _lock
        reg = registry if registry is not None else get_registry()
        self._records_total = reg.counter(
            "edgemesh_flight_records_total",
            "Records appended to the flight ring, by event",
            ("event",))
        self._ring_gauge = reg.gauge(
            "edgemesh_flight_ring_records",
            "Records currently held in the flight ring")
        self._dumps_total = reg.counter(
            "edgemesh_flight_dumps_total",
            "Flight-ring dumps written, by trigger kind", ("kind",))

    def record(self, event: str, fields: dict[str, Any]) -> None:
        """Append one record (a *copy*, stamped with a wall ``ts`` when the
        fields carry none). Cheap enough for every retirement: one dict
        copy + deque append under a short lock. Also takes the periodic
        digest snapshot when the interval has elapsed — opportunistic, so
        an idle replica's ring simply stops moving instead of needing its
        own timer thread."""
        rec = {"ts": time.time(), "event": event, **fields}
        snap = None
        now = time.monotonic()
        with self._lock:
            if (
                self.snapshot_source is not None
                and now - self._last_snapshot >= self.snapshot_interval_s
            ):
                self._last_snapshot = now
                snap = True
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            self._ring.append(rec)
            size = len(self._ring)
        self._records_total.labels(event=event).inc()
        self._ring_gauge.set(size)
        if snap:
            # Outside the lock: snapshot_source may take the engine lock
            # (load_digest does), and holding ours across it would pair the
            # two in inconsistent order with the engine's own record calls.
            try:
                digest = dict(self.snapshot_source())
            except Exception:  # telemetry must never break the request path
                return
            with self._lock:
                self._ring.append(
                    {"ts": time.time(), "event": SNAPSHOT_EVENT,
                     "replica": self.replica, **digest})
            self._records_total.labels(event=SNAPSHOT_EVENT).inc()

    def snapshot_now(self, digest: dict[str, Any]) -> None:
        """Append one digest snapshot immediately (tests; trigger-time
        final sample before a dump)."""
        with self._lock:
            self._ring.append({"ts": time.time(), "event": SNAPSHOT_EVENT,
                               "replica": self.replica, **digest})
        self._records_total.labels(event=SNAPSHOT_EVENT).inc()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def peek(self) -> list[dict[str, Any]]:
        """A snapshot copy of the ring, oldest first (tests/inspection)."""
        with self._lock:
            return [dict(r) for r in self._ring]

    def dump(self, out_dir: str | Path, incident_id: str,
             kind: str = "manual", trigger_ts: float | None = None,
             detail: dict | None = None) -> Path:
        """Write the ring to ``<out_dir>/<incident_id>/flight-<replica>.jsonl``.

        The first record is a ``flight_dump`` header (incident id, trigger
        kind + wall timestamp, replica, ring fill/capacity/drop count);
        every ring record follows verbatim, original timestamps preserved.
        The ring is NOT cleared — a second trigger during the same incident
        re-dumps the fuller picture over the same file."""
        from edgemesh.utils.tracing import JsonlLogger

        with self._lock:
            records = [dict(r) for r in self._ring]
            dropped = self._dropped
        out = Path(out_dir) / incident_id / f"flight-{self.replica}.jsonl"
        out.parent.mkdir(parents=True, exist_ok=True)
        if out.exists():
            out.unlink()  # re-trigger: replace, never append duplicates
        logger = JsonlLogger(out)
        logger.log(
            DUMP_EVENT, incident_id=incident_id, kind=kind,
            replica=self.replica,
            trigger_ts=trigger_ts if trigger_ts is not None else time.time(),
            records=len(records), capacity=self.capacity, dropped=dropped,
            **(detail or {}),
        )
        for rec in records:
            # JsonlLogger.log stamps ts= then lets **fields override it, so
            # the ring record's original wall timestamp survives the dump.
            logger.log(rec.get("event", "record"),
                       **{k: v for k, v in rec.items() if k != "event"})
        self._dumps_total.labels(kind=kind).inc()
        return out


# ---------------------------------------------------------------------------
# Postmortem assembly (`edgemesh obs incident <dumpdir>`)
# ---------------------------------------------------------------------------


def _phase_bucket(records: list[dict]) -> dict[str, Any]:
    classified = [r["slo_result"] for r in records
                  if r.get("slo_result") is not None]
    good = sum(1 for c in classified if c == "good")
    lats = sorted(r["latency_s"] for r in records
                  if r.get("latency_s") is not None)
    by_tenant: dict[str, list[int]] = {}
    for r in records:
        if r.get("tenant") is not None and r.get("slo_result") is not None:
            cell = by_tenant.setdefault(str(r["tenant"]), [0, 0])
            cell[1] += 1
            if r["slo_result"] == "good":
                cell[0] += 1
    return {
        "requests": len(records),
        "classified": len(classified),
        "goodput_ratio": round(good / len(classified), 4) if classified else None,
        "latency_s_p50": (
            round(lats[min(len(lats) - 1, len(lats) // 2)], 6) if lats else None
        ),
        "tenants": {
            t: {"classified": c, "good": g,
                "goodput_ratio": round(g / c, 4)}
            for t, (g, c) in sorted(by_tenant.items())
        } or None,
    }


def _record_critical_path(rec: dict) -> dict[str, Any]:
    """One span record's queue/prefill/decode split through the PR 5
    machinery: assemble the (replica-only) tree for its trace id, then run
    the standard critical-path split over it."""
    from edgemesh.obs.trace import assemble_trace, critical_path

    doc = assemble_trace(rec.get("trace_id"), [rec])
    return critical_path(doc["tree"])


def assemble_incident(paths: Iterable[str | Path],
                      window_s: float = 10.0) -> dict[str, Any]:
    """Join flight dumps (and any extra span logs) into one incident doc.

    ``paths`` are JSONL files — typically every ``flight-*.jsonl`` in one
    incident directory, optionally plus the router's span log. Returns::

        {"incident_id", "kinds", "trigger_ts", "window_s", "replicas",
         "phases": {"before"/"during"/"after": {requests, goodput_ratio,
                                                tenants, ...}},
         "critical_path": {"window": {replica: {queue_s, prefill_s,
                                                decode_s, service_s,
                                                requests}},
                           "slowest_replica": ...},
         "timeline": [...]}

    The trigger window is ``[trigger_ts - window_s, trigger_ts + window_s]``
    around the earliest locally-fired trigger (propagated dumps carry the
    origin's timestamp, so every replica's window lines up). Requests are
    bucketed by their wall submit time (``ts_submit``); the per-replica
    critical-path totals cover requests whose window intersects the
    trigger window. ``tree`` is None when no dump header is present."""
    from edgemesh.obs.spans import SPAN_RECORD_EVENT
    from edgemesh.utils.tracing import JsonlLogger

    headers: list[dict] = []
    spans: list[dict] = []
    timeline: list[dict] = []
    for p in paths:
        replica = None
        recs = JsonlLogger(p).read()
        for rec in recs:
            if rec.get("event") == DUMP_EVENT:
                headers.append(rec)
                replica = rec.get("replica")
        for rec in recs:
            ev = rec.get("event")
            if ev == SPAN_RECORD_EVENT:
                r = dict(rec)
                r.setdefault("_replica", replica or Path(p).stem)
                spans.append(r)
            elif ev in (SNAPSHOT_EVENT, "pool_reset", "pool_mem",
                        "incident", DUMP_EVENT):
                timeline.append({
                    "ts": rec.get("ts"), "event": ev,
                    "replica": rec.get("replica", replica),
                    **{k: rec[k] for k in
                       ("incident_id", "kind", "queue_depth", "inflight",
                        "reason", "detail", "cause", "rid", "tenant",
                        "delta", "resident", "free")
                       if k in rec},
                })
    if not headers:
        return {"incident_id": None, "replicas": [], "trigger_ts": None,
                "phases": None, "critical_path": None, "timeline": []}
    # The earliest LOCAL trigger anchors the window; propagated dumps fall
    # back in when no local one made it into the directory.
    local = [h for h in headers if h.get("kind") != "propagated"]
    anchor = min(local or headers, key=lambda h: h.get("trigger_ts") or 0)
    trigger_ts = anchor.get("trigger_ts")
    w0, w1 = trigger_ts - window_s, trigger_ts + window_s
    phases = {"before": [], "during": [], "after": []}
    for rec in spans:
        ts = rec.get("ts_submit", rec.get("ts"))
        if ts is None:
            continue
        if ts < w0:
            phases["before"].append(rec)
        elif ts <= w1:
            phases["during"].append(rec)
        else:
            phases["after"].append(rec)
    # Per-replica critical-path totals over requests touching the window.
    per_replica: dict[str, dict[str, float]] = {}
    for rec in spans:
        t0 = rec.get("ts_submit")
        if t0 is None:
            continue
        t1 = t0 + (rec.get("latency_s") or 0.0)
        if t1 < w0 or t0 > w1:
            continue
        cp = _record_critical_path(rec)
        cell = per_replica.setdefault(str(rec["_replica"]), {
            "requests": 0, "queue_s": 0.0, "prefill_s": 0.0,
            "decode_s": 0.0, "service_s": 0.0,
        })
        cell["requests"] += 1
        for key in ("queue_s", "prefill_s", "decode_s"):
            cell[key] = round(cell[key] + (cp.get(key) or 0.0), 6)
        cell["service_s"] = round(
            cell["service_s"] + (cp.get("total_s") or 0.0), 6)
    slowest = max(per_replica,
                  key=lambda r: per_replica[r]["service_s"],
                  default=None)
    timeline.sort(key=lambda e: e.get("ts") or 0)
    return {
        "incident_id": anchor.get("incident_id"),
        "kinds": sorted({h.get("kind") for h in headers if h.get("kind")}),
        "trigger_ts": trigger_ts,
        "window_s": window_s,
        "replicas": sorted({h.get("replica") for h in headers
                            if h.get("replica")}),
        "phases": {name: _phase_bucket(recs)
                   for name, recs in phases.items()},
        "critical_path": {
            "window": {r: per_replica[r] for r in sorted(per_replica)},
            "slowest_replica": slowest,
        },
        "timeline": timeline,
    }
