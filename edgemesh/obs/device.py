"""Scrape-time device gauges: HBM/host memory stats and live buffers.

Registered as a registry collector, so ``jax.local_devices()`` and
``memory_stats()`` are sampled only when someone actually renders
``/metrics`` — never on the serving hot path. jax imports stay inside the
collector: importing ``edgemesh.obs`` must not initialize a backend (the
supervisor and the offline ``edgemesh obs`` CLI rely on that).

``memory_stats()`` availability is backend-dependent (TPU/GPU report
``bytes_in_use``/``bytes_limit``; CPU returns ``None`` or raises) — absent
stats simply produce no sample, the scrape itself never fails.
"""

from __future__ import annotations

from edgemesh.obs.metrics import Registry, get_registry

# memory_stats() key → our ``kind`` label. Only the serving-relevant subset:
# a full dump would be ~20 allocator internals per device.
_MEMORY_KINDS = {
    "bytes_in_use": "in_use",
    "bytes_limit": "limit",
    "peak_bytes_in_use": "peak",
    "bytes_reserved": "reserved",
}


def _collect_device_gauges(registry: Registry) -> None:
    import jax

    mem = registry.gauge(
        "edgemesh_device_memory_bytes",
        "Per-device allocator stats from memory_stats()",
        ("device", "kind"),
    )
    live = registry.gauge(
        "edgemesh_live_buffers",
        "Live jax arrays in this process (jax.live_arrays())",
    )
    n_dev = registry.gauge(
        "edgemesh_devices", "Addressable devices on this host"
    )
    devices = jax.local_devices()
    n_dev.set(len(devices))
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        for key, kind in _MEMORY_KINDS.items():
            if key in stats:
                mem.labels(device=str(d.id), kind=kind).set(stats[key])
    try:
        live.set(len(jax.live_arrays()))
    except Exception:
        pass


def register_device_gauges(registry: Registry | None = None) -> None:
    """Idempotent: add the device collector to ``registry`` (default: the
    process registry). Collectors dedupe by identity, so calling this per
    server start is safe."""
    (registry or get_registry()).add_collector(_collect_device_gauges)
