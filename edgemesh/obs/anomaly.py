"""Anomaly triggers: the detectors that fire the flight recorder.

The flight recorder (obs/flight.py) answers *what happened*; this module
answers *when to ask*. Six detectors, each fed by hooks the serving stack
already has — no new measurement, only new judgment:

- :class:`SloBurstDetector` — a burst of SLO misses in the recent request
  window, judged against a :class:`~edgemesh.obs.slo.DecayingQuantile`
  latency baseline: misses only count as a *burst* once the baseline knows
  what healthy looks like and the missing requests are genuinely outside
  it (or never finished). Steady-state slowness re-arms the baseline and
  stops re-firing — an incident is a *change*, not a state.
- :class:`QueueCollapseDetector` — the engine admission queue pinned above
  a depth bound for consecutive observations (fed on every submit).
- :class:`ErrorSpikeDetector` — N non-ok retirements inside a sliding
  wall-clock window.
- :class:`CompileStormDetector` — M distinct backend compiles inside a
  window (fed by the engine's compile hook): mid-serve shape churn is the
  silent latency cliff every postmortem should show.
- :class:`PoolLeakDetector` — KV pool pages still resident >= N seconds
  after their owning request retired (fed by the memory observatory's
  quiesce scan, obs/memory.py): the one failure the conservation counter
  alone cannot localize to a request.
- :class:`QualityDriftDetector` — the recent window of per-request
  confidence (quality observatory, obs/quality.py) collapsed relative to
  a decayed healthy baseline: the replica whose answers went bad while
  its latency stayed green. Same change-not-level philosophy as the SLO
  burst — degraded samples never feed the baseline, and a fire needs a
  healthy→degraded *transition*, so a replica that has always been
  mediocre is a dashboard fact, not an incident.

:class:`AnomalyMonitor` owns the detectors, counts
``edgemesh_anomaly_triggers_total{kind}``, and — when armed with a dump
directory — dumps the flight ring into ``<dir>/<incident_id>/`` with a
cooldown so a sustained anomaly produces one incident, not a dump per
request. ``note_incident`` is the fleet seam: the router propagates a
sibling replica's incident id here so every ring in the fleet lands in
the same incident directory (fleet/router.py ``observe_incident``).

Thresholds read ``EDGEMESH_ANOMALY_*`` env overrides so replica
subprocesses are configurable without new CLI plumbing at every call site
(same pattern as ``SloTarget.from_env``). No jax, stdlib only.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any

from edgemesh.obs.metrics import Registry, get_registry
from edgemesh.obs.slo import DecayingQuantile


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v > 0 else default


def _env_int(name: str, default: int) -> int:
    return int(_env_float(name, float(default)))


class SloBurstDetector:
    """SLO-miss burst vs a decayed-latency baseline.

    ``observe`` feeds every retirement's classification + latency. Good
    latencies feed the baseline quantile (counts halve every
    ``half_life_s``); a fire needs BOTH conditions:

    - at least ``min_misses`` of the last ``window`` classified requests
      missed, and the miss fraction is >= ``miss_ratio``;
    - the baseline has seen enough healthy traffic to judge
      (``DecayingQuantile.min_weight``), and the median latency of the
      recent misses exceeds ``burst_factor`` x the baseline p95 — or the
      misses never produced a latency at all (errors/timeouts).

    The baseline gate is what separates "this replica is just slow" (no
    fire: the spans and metrics already say so) from "this replica just
    *became* slow" (fire: the moments before are about to age out of the
    ring)."""

    kind = "slo_burst"

    def __init__(self, window: int = 24, min_misses: int = 8,
                 miss_ratio: float = 0.5, burst_factor: float = 2.0,
                 half_life_s: float = 120.0, min_weight: float = 8.0,
                 quantile: float = 0.95):
        self.window = int(window)
        self.min_misses = int(min_misses)
        self.miss_ratio = float(miss_ratio)
        self.burst_factor = float(burst_factor)
        self.quantile = float(quantile)
        self.baseline = DecayingQuantile(half_life_s=half_life_s,
                                         min_weight=min_weight)
        # (miss: bool, latency_s: float | None) per classified request.
        self._recent: deque[tuple[bool, float | None]] = deque(
            maxlen=self.window)
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls) -> "SloBurstDetector":
        return cls(
            window=_env_int("EDGEMESH_ANOMALY_SLO_WINDOW", 24),
            min_misses=_env_int("EDGEMESH_ANOMALY_SLO_MISSES", 8),
            miss_ratio=_env_float("EDGEMESH_ANOMALY_SLO_RATIO", 0.5),
            burst_factor=_env_float("EDGEMESH_ANOMALY_SLO_FACTOR", 2.0),
            half_life_s=_env_float("EDGEMESH_ANOMALY_SLO_HALF_LIFE_S", 120.0),
            min_weight=_env_float("EDGEMESH_ANOMALY_SLO_MIN_WEIGHT", 8.0),
        )

    def observe(self, slo_result: str, latency_s: float | None) -> bool:
        miss = slo_result != "good"
        if not miss and latency_s is not None:
            self.baseline.observe(latency_s)
        with self._lock:
            self._recent.append((miss, latency_s))
            recent = list(self._recent)
        misses = [lat for m, lat in recent if m]
        if len(misses) < self.min_misses:
            return False
        if len(misses) / len(recent) < self.miss_ratio:
            return False
        bound = self.baseline.quantile(self.quantile)
        if bound is None:
            return False  # no healthy baseline yet: slow != degraded
        timed = sorted(lat for lat in misses if lat is not None)
        if not timed:
            return True  # misses that never finished are past any baseline
        return timed[len(timed) // 2] > self.burst_factor * bound


class QueueCollapseDetector:
    """Admission queue pinned >= ``depth`` for ``consecutive`` samples."""

    kind = "queue_collapse"

    def __init__(self, depth: int = 32, consecutive: int = 4):
        self.depth = int(depth)
        self.consecutive = int(consecutive)
        self._streak = 0  # guarded by: _lock
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls) -> "QueueCollapseDetector":
        return cls(
            depth=_env_int("EDGEMESH_ANOMALY_QUEUE_DEPTH", 32),
            consecutive=_env_int("EDGEMESH_ANOMALY_QUEUE_CONSECUTIVE", 4),
        )

    def observe(self, queue_depth: int) -> bool:
        with self._lock:
            if queue_depth >= self.depth:
                self._streak += 1
            else:
                self._streak = 0
            return self._streak == self.consecutive


class ErrorSpikeDetector:
    """>= ``count`` non-ok retirements (errors/preemptions) within
    ``window_s`` seconds of wall time."""

    kind = "error_spike"

    def __init__(self, count: int = 5, window_s: float = 30.0):
        self.count = int(count)
        self.window_s = float(window_s)
        self._times: deque[float] = deque()  # guarded by: _lock
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls) -> "ErrorSpikeDetector":
        return cls(
            count=_env_int("EDGEMESH_ANOMALY_ERRORS", 5),
            window_s=_env_float("EDGEMESH_ANOMALY_ERROR_WINDOW_S", 30.0),
        )

    def observe(self, status: str, now: float | None = None) -> bool:
        if status == "ok":
            return False
        t = now if now is not None else time.monotonic()
        with self._lock:
            self._times.append(t)
            while self._times and t - self._times[0] > self.window_s:
                self._times.popleft()
            return len(self._times) == self.count


class CompileStormDetector:
    """>= ``count`` distinct backend compiles within ``window_s``. The
    first compile is the expected warmup and never counts — a storm is
    *re*compilation (shape churn, cache misses) while serving."""

    kind = "compile_storm"

    def __init__(self, count: int = 3, window_s: float = 60.0):
        self.count = int(count)
        self.window_s = float(window_s)
        self._times: deque[float] = deque()  # guarded by: _lock
        self._seen_first = False  # guarded by: _lock
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls) -> "CompileStormDetector":
        return cls(
            count=_env_int("EDGEMESH_ANOMALY_COMPILES", 3),
            window_s=_env_float("EDGEMESH_ANOMALY_COMPILE_WINDOW_S", 60.0),
        )

    def observe(self, now: float | None = None) -> bool:
        t = now if now is not None else time.monotonic()
        with self._lock:
            if not self._seen_first:
                self._seen_first = True
                return False
            self._times.append(t)
            while self._times and t - self._times[0] > self.window_s:
                self._times.popleft()
            return len(self._times) == self.count


class PoolLeakDetector:
    """Pages still resident after their owning request retired >= ``age_s``
    seconds ago (fed by the memory observatory's ``leak_scan``,
    obs/memory.py). Fires once per leaking request id: a leak is a
    permanent condition, and re-dumping the ring on every scan would bury
    the incident that matters — the first one, whose ring still holds the
    leaking request's spans."""

    kind = "pool_leak"

    def __init__(self, age_s: float = 30.0):
        self.age_s = float(age_s)
        self._fired: set[str] = set()  # guarded by: _lock
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls) -> "PoolLeakDetector":
        return cls(age_s=_env_float("EDGEMESH_ANOMALY_POOL_LEAK_S", 30.0))

    def observe(self, rid: str, retired_age_s: float) -> bool:
        if retired_age_s < self.age_s:
            return False
        with self._lock:
            if rid in self._fired:
                return False
            self._fired.add(rid)
            return True


#: Quantile bounds for signals on [0, 1] (confidence/agreement): the
#: latency-scale defaults in obs/slo.py top out at ~0.17, useless here.
QUALITY_BOUNDS = tuple(i / 64 for i in range(1, 65))


class QualityDriftDetector:
    """Recent-confidence collapse vs a decayed healthy baseline.

    ``observe`` feeds every retirement's mean confidence (the quality
    observatory's ``on_retire`` hook). A fire needs ALL of:

    - the baseline quantile has seen enough healthy traffic to judge
      (``DecayingQuantile.min_weight`` — counts halve every
      ``half_life_s``, so the notion of "healthy" tracks deploys);
    - at least ``min_count`` of the last ``window`` requests observed,
      and their mean confidence < ``drop_factor`` x the baseline median;
    - the detector is *armed*: it fires once per healthy→degraded
      transition and re-arms only after the window recovers. Sustained
      low quality is one incident, not a dump per cooldown.

    Degraded samples (below the drop line) never feed the baseline —
    otherwise the baseline would decay toward the degradation and
    declare it the new healthy.
    """

    kind = "quality_drift"

    def __init__(self, window: int = 16, min_count: int = 8,
                 drop_factor: float = 0.6, half_life_s: float = 300.0,
                 min_weight: float = 16.0):
        self.window = int(window)
        self.min_count = int(min_count)
        self.drop_factor = float(drop_factor)
        self.baseline = DecayingQuantile(half_life_s=half_life_s,
                                         bounds=QUALITY_BOUNDS,
                                         min_weight=min_weight)
        self._recent: deque[float] = deque(maxlen=self.window)
        self._armed = True  # guarded by: _lock
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls) -> "QualityDriftDetector":
        return cls(
            window=_env_int("EDGEMESH_ANOMALY_QUALITY_WINDOW", 16),
            min_count=_env_int("EDGEMESH_ANOMALY_QUALITY_COUNT", 8),
            drop_factor=_env_float("EDGEMESH_ANOMALY_QUALITY_DROP", 0.6),
            half_life_s=_env_float(
                "EDGEMESH_ANOMALY_QUALITY_HALF_LIFE_S", 300.0),
            min_weight=_env_float(
                "EDGEMESH_ANOMALY_QUALITY_MIN_WEIGHT", 16.0),
        )

    def observe(self, confidence: float) -> bool:
        c = float(confidence)
        bound = self.baseline.quantile(0.5)
        threshold = None if bound is None else self.drop_factor * bound
        if threshold is None or c >= threshold:
            self.baseline.observe(c)
        with self._lock:
            self._recent.append(c)
            if threshold is None or len(self._recent) < self.min_count:
                return False
            mean = sum(self._recent) / len(self._recent)
            if mean >= threshold:
                self._armed = True
                return False
            if not self._armed:
                return False
            self._armed = False
            return True


class AnomalyMonitor:
    """Detector fan-in → incident id → flight dump, with cooldown.

    ``flight`` is the replica's :class:`~edgemesh.obs.flight.
    FlightRecorder`; ``dump_dir`` is the (ideally fleet-shared) incident
    directory. With ``dump_dir=None`` the monitor still counts triggers —
    the metric is the alarm even when nothing lands on disk."""

    def __init__(self, flight=None, dump_dir=None,
                 registry: Registry | None = None,
                 slo_burst: SloBurstDetector | None = None,
                 queue_collapse: QueueCollapseDetector | None = None,
                 error_spike: ErrorSpikeDetector | None = None,
                 compile_storm: CompileStormDetector | None = None,
                 pool_leak: PoolLeakDetector | None = None,
                 quality_drift: QualityDriftDetector | None = None,
                 cooldown_s: float = 30.0):
        self.flight = flight
        self.dump_dir = dump_dir
        self.slo_burst = slo_burst or SloBurstDetector.from_env()
        self.queue_collapse = queue_collapse or QueueCollapseDetector.from_env()
        self.error_spike = error_spike or ErrorSpikeDetector.from_env()
        self.compile_storm = compile_storm or CompileStormDetector.from_env()
        self.pool_leak = pool_leak or PoolLeakDetector.from_env()
        self.quality_drift = quality_drift or QualityDriftDetector.from_env()
        self.cooldown_s = _env_float("EDGEMESH_ANOMALY_COOLDOWN_S",
                                     float(cooldown_s))
        reg = registry if registry is not None else get_registry()
        self._triggers = reg.counter(
            "edgemesh_anomaly_triggers_total",
            "Anomaly detectors fired, by kind (propagated = a sibling "
            "replica's incident id arrived via the router)", ("kind",))
        self._lock = threading.Lock()
        self._incidents: deque[dict] = deque(maxlen=16)  # guarded by: _lock
        self._dumped_ids: set[str] = set()  # guarded by: _lock
        self._last_dump_monotonic: float | None = None  # guarded by: _lock
        self._last_compile_marker: float | None = None  # guarded by: _lock

    # -- feed hooks ----------------------------------------------------------

    def on_retire(self, slo_result: str, latency_s: float | None,
                  status: str = "ok") -> None:
        """One retirement: SLO burst + error spike + (piggybacked) compile
        storm. Called by ``SpanTracker.retire`` — the one place every
        terminal request already passes through."""
        if self.slo_burst.observe(slo_result, latency_s):
            self.trigger(self.slo_burst.kind)
        if self.error_spike.observe(status):
            self.trigger(self.error_spike.kind)
        self._poll_compiles()

    def on_queue_depth(self, depth: int) -> None:
        if self.queue_collapse.observe(depth):
            self.trigger(self.queue_collapse.kind,
                         detail={"queue_depth": int(depth)})

    def on_pool_leak(self, rid: str, retired_age_s: float,
                     detail: dict | None = None) -> bool:
        """One leak candidate from the memory observatory's quiesce scan
        (obs/memory.py ``leak_scan``): pages whose owner retired
        ``retired_age_s`` ago and never came home. Fires the ``pool_leak``
        kind once per request id; the incident id propagates fleet-wide
        through the standard digest path, so the dump names the leaking
        replica and every sibling's ring lands beside it. Returns whether
        this candidate fired (the ledger logs fired leaks as records)."""
        if self.pool_leak.observe(rid, retired_age_s):
            self.trigger(self.pool_leak.kind,
                         detail={"rid": rid,
                                 "retired_age_s": round(retired_age_s, 3),
                                 **(detail or {})})
            return True
        return False

    def on_quality(self, confidence: float | None,
                   detail: dict | None = None) -> bool:
        """One terminal request's mean confidence from the quality
        observatory (obs/quality.py ``QualityTracker.on_retire``). Fires
        the ``quality_drift`` kind on a healthy→degraded transition; the
        incident id rides the load digest to the router like every other
        kind, so the fleet's rings land in one directory and the
        postmortem names the low-quality replica. Returns whether this
        sample fired."""
        if confidence is None:
            return False
        if self.quality_drift.observe(float(confidence)):
            self.trigger(self.quality_drift.kind, detail=detail)
            return True
        return False

    def on_compile(self) -> None:
        """Direct compile feed (when the compile hook is wired to the
        monitor); the retire-path poll below covers engines that are not."""
        if self.compile_storm.observe():
            self.trigger(self.compile_storm.kind)

    def _poll_compiles(self) -> None:
        """Derive compile events from the process-wide last-compile marker
        (obs/trace.py): a changed marker since the previous retirement is
        one distinct compile. Coarser than the direct feed — back-to-back
        compiles between two retirements collapse into one — but it costs
        nothing and needs no hook rewiring."""
        from edgemesh.obs.trace import seconds_since_last_compile

        since = seconds_since_last_compile()
        if since is None:
            return
        marker = time.monotonic() - since
        with self._lock:
            prev = self._last_compile_marker
            self._last_compile_marker = marker
        if prev is None or abs(marker - prev) > 1e-3:
            self.on_compile()

    # -- firing --------------------------------------------------------------

    def _mint_id(self) -> str:
        return (f"inc-{time.strftime('%Y%m%d-%H%M%S')}-"
                f"{os.urandom(3).hex()}")

    def trigger(self, kind: str, detail: dict | None = None) -> dict | None:
        """A detector fired: count it, and (cooldown permitting) dump the
        flight ring under a fresh incident id. Returns the incident record
        when a dump was written, else None."""
        self._triggers.labels(kind=kind).inc()
        now = time.monotonic()
        with self._lock:
            if (
                self._last_dump_monotonic is not None
                and now - self._last_dump_monotonic < self.cooldown_s
            ):
                return None
            self._last_dump_monotonic = now
            incident_id = self._mint_id()
            self._dumped_ids.add(incident_id)
        return self._dump(incident_id, kind, detail)

    def note_incident(self, incident_id: str, kind: str = "propagated",
                      detail: dict | None = None) -> dict | None:
        """Adopt an externally-propagated incident id (the router's
        broadcast): dump this replica's ring into the SAME incident
        directory. Idempotent per id; propagated dumps bypass the cooldown
        — a sibling's incident must capture this ring even if a local
        trigger just fired."""
        if not incident_id:
            return None
        with self._lock:
            if incident_id in self._dumped_ids:
                return None
            self._dumped_ids.add(incident_id)
            self._last_dump_monotonic = time.monotonic()
        self._triggers.labels(kind=kind).inc()
        return self._dump(incident_id, kind, detail)

    def _dump(self, incident_id: str, kind: str,
              detail: dict | None) -> dict | None:
        record: dict[str, Any] = {
            "id": incident_id, "kind": kind, "ts": time.time(),
            "detail": detail or None, "path": None,
        }
        if self.flight is not None and self.dump_dir is not None:
            try:
                path = self.flight.dump(self.dump_dir, incident_id,
                                        kind=kind, trigger_ts=record["ts"],
                                        detail=detail)
                record["path"] = str(path)
            except OSError:
                record["path"] = None  # a full disk must not fail serving
        with self._lock:
            self._incidents.append(record)
        return record

    # -- introspection -------------------------------------------------------

    def last_incident(self) -> dict | None:
        """The newest incident {id, kind, ts} — what the load digest ships
        to the fleet prober so the router can propagate it."""
        with self._lock:
            if not self._incidents:
                return None
            rec = self._incidents[-1]
            return {"id": rec["id"], "kind": rec["kind"], "ts": rec["ts"]}

    def incidents(self) -> list[dict]:
        with self._lock:
            return [dict(r) for r in self._incidents]
