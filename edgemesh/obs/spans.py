"""Request-lifecycle spans for the serving engines.

Every request through ``ContinuousEngine``/``SpeculativeContinuousEngine``
gets a span tree: ``queued`` (submit → admission start), ``prefill``
(admission start → splice complete), one ``decode`` span per segment that
credited it tokens, and a closing ``retire``. Timestamps are
``time.perf_counter`` values — monotonic within the process, which is what
span math needs; the flushed record carries a wall-clock ``ts`` anchor.

The tracker is the ONLY clock owner on the engine's request path (edgelint
EM107 enforces this for ``serve/``/``runtime/``): engines call the
lifecycle hooks and read the timestamps back off the trace. Each hook both
extends the span tree and feeds the metrics registry, and ``retire``
flushes one JSONL record per request (the repo's one-object-per-line
convention) carrying the raw observations — ``replay_spans`` rebuilds the
same registry aggregates from the log alone, which is what ``edgemesh obs
summary``/``prom`` do offline.
"""

from __future__ import annotations

import random
import threading
import time
from pathlib import Path
from typing import Any, Iterable

from edgemesh.obs.metrics import (
    INTER_TOKEN_BUCKETS,
    LATENCY_BUCKETS,
    Registry,
    get_registry,
)
from edgemesh.obs.slo import SLO_RESULTS, SloTarget, SloTracker

SPAN_RECORD_EVENT = "request_spans"
RESET_RECORD_EVENT = "pool_reset"

#: Load-digest EWMA smoothing: each observation carries 20% weight, so the
#: digest tracks a regime change within ~5 requests while a single outlier
#: moves it by at most a fifth (docs/OBSERVABILITY.md "Load digests").
EWMA_ALPHA = 0.2


class RequestTrace:
    """Mutable per-request span state; owned by the engine's slot/queue."""

    __slots__ = (
        "rid", "ts_unix", "t_submit", "t_admit_start", "t_start",
        "t_first_token", "t_last", "t_end", "generated", "segments",
        "spans", "status", "attrs", "tenant", "session",
        "trace_id", "span_id", "parent_span_id", "sampled",
    )

    def __init__(self, rid: int, t_submit: float):
        self.rid = rid
        self.ts_unix = time.time()
        self.t_submit = t_submit
        # Distributed-trace identity (obs/trace.py): filled by
        # SpanTracker.submit — propagated from the fleet router's attempt
        # span when the request arrived with an X-Edgemesh-Trace header,
        # minted locally otherwise. ``sampled`` gates the JSONL flush only;
        # metrics always count.
        self.trace_id: str | None = None
        self.span_id: str | None = None
        self.parent_span_id: str | None = None
        self.sampled = True
        # Tenant identity (X-Edgemesh-Tenant, propagated by the fleet
        # router): None for untagged traffic — the span record carries a
        # null and the per-tenant metric families stay untouched, so
        # pre-tenant logs and single-tenant deployments see zero change.
        self.tenant: str | None = None
        # Session identity (X-Edgemesh-Session, sent by the load
        # observatory's generator and propagated like the tenant): rides
        # the span record only — it is what lets `obs replay` rebuild the
        # shared-prefix session structure of recorded traffic. Never a
        # metric label (EM112 cardinality).
        self.session: str | None = None
        self.t_admit_start: float | None = None
        self.t_start: float | None = None  # admission (prefill) complete
        self.t_first_token: float | None = None
        self.t_last = t_submit  # last lifecycle event, decode-span left edge
        self.t_end: float | None = None
        self.generated = 0
        self.segments = 0
        self.spans: list[dict[str, Any]] = []
        self.status: str | None = None
        self.attrs: dict[str, Any] = {}

    def span(self, name: str, t0: float, t1: float, **attrs: Any) -> None:
        rec: dict[str, Any] = {"name": name, "t0": t0, "t1": t1}
        if attrs:
            rec.update(attrs)
        self.spans.append(rec)


class SpanTracker:
    """Registry + span-log sink for one engine's request lifecycle."""

    def __init__(self, registry: Registry | None = None,
                 span_log: str | Path | None = None,
                 engine: str = "continuous",
                 trace_sample: float = 1.0,
                 slo_target: SloTarget | None = None,
                 flight=None):
        self.registry = registry or get_registry()
        self.engine = engine
        # Flight recorder (obs/flight.py): when attached, EVERY retirement's
        # full span record rides the bounded in-memory ring — including the
        # ones trace_sample keeps out of the JSONL — so an anomaly dump has
        # the moments before the trigger at full fidelity. ``anomaly`` is
        # the optional AnomalyMonitor fed from retire (obs/anomaly.py);
        # both are plain attributes so serving wiring (and the bench's
        # recorder-on arm) can attach/detach them live.
        self.flight = flight
        self.anomaly = None
        # SLO classification (obs/slo.py): every retirement is judged
        # against the TTFT/TPOT target (``slo_target``, default from env)
        # and the verdict rides both the metrics and the span record.
        self.slo = SloTracker(self.registry, engine=engine, target=slo_target)
        # Latency EWMAs for the /loadz digest (load_digest): written by the
        # engine worker via the lifecycle hooks, read by gateway HTTP
        # threads — the lock keeps a digest read from pairing a new queue
        # EWMA with a half-updated prefill one.
        self._ewma_lock = threading.Lock()
        # prefill_tokens/decode_tokens split the digest by PHASE VOLUME:
        # the prefill tokens each admission actually computed (an imported
        # KV handle contributes only its suffix — remote prefixes must not
        # inflate a decode replica's prefill share) and the decode tokens
        # each retirement generated. The fleet's TierManager scores
        # replicas by this split (docs/FLEET.md "Tiered serving").
        self._ewma: dict[str, float | None] = {
            "queue": None, "prefill": None, "decode": None, "service": None,
            "prefill_tokens": None, "decode_tokens": None,
            # Inter-arrival seconds between submits: the ARRIVAL side of
            # the digest. 1/ewma_arrival_s is the offered load this replica
            # is seeing — what the fleet autoscaler sums into fleet demand
            # (fleet/autoscale.py), independent of how service is keeping
            # up. Updated in submit, so even a wedged engine whose
            # retirements stall keeps reporting honest arrivals.
            "arrival": None,
        }
        self._last_submit: float | None = None  # guarded by: _ewma_lock
        # Span-I/O sampling for locally-originated requests (requests that
        # arrive with a trace context inherit ITS sampled bit instead, so
        # the router's decision is honored end to end). Sampled-out
        # requests cost zero span I/O but still feed every metric.
        self.trace_sample = float(trace_sample)
        self._sample_rng = random.Random()
        self._log = None
        if span_log is not None:
            from edgemesh.utils.tracing import JsonlLogger

            self._log = JsonlLogger(span_log)
        reg, eng = self.registry, {"engine": engine}
        self._submitted = reg.counter(
            "edgemesh_requests_submitted_total",
            "Requests accepted by submit()", ("engine",)).labels(**eng)
        self._completed = reg.counter(
            "edgemesh_requests_completed_total",
            "Requests retired, by terminal status", ("engine", "status"))
        self._tokens = reg.counter(
            "edgemesh_tokens_generated_total",
            "Decode tokens credited to requests", ("engine",)).labels(**eng)
        self._segments = reg.counter(
            "edgemesh_segments_total",
            "Pool-wide decode segments dispatched", ("engine",)).labels(**eng)
        self._queue_wait = reg.histogram(
            "edgemesh_queue_wait_seconds",
            "submit() to admission start", ("engine",),
            buckets=LATENCY_BUCKETS).labels(**eng)
        self._prefill = reg.histogram(
            "edgemesh_prefill_seconds",
            "Admission prefill dispatch + splice wall time", ("engine",),
            buckets=LATENCY_BUCKETS).labels(**eng)
        self._ttft = reg.histogram(
            "edgemesh_ttft_seconds",
            "submit() to first decoded token", ("engine",),
            buckets=LATENCY_BUCKETS).labels(**eng)
        self._itl = reg.histogram(
            "edgemesh_inter_token_seconds",
            "Mean per-token decode latency after the first token",
            ("engine",), buckets=INTER_TOKEN_BUCKETS).labels(**eng)
        self._latency = reg.histogram(
            "edgemesh_request_latency_seconds",
            "submit() to retirement", ("engine",),
            buckets=LATENCY_BUCKETS).labels(**eng)
        self._resets = reg.counter(
            "edgemesh_pool_resets_total",
            "KV pool rebuilds (failed segment/admission recovery)",
            ("engine",)).labels(**eng)

    # -- lifecycle hooks (the engine's only clock) ---------------------------

    def now(self) -> float:
        return time.perf_counter()

    def submit(self, rid: int, trace_ctx=None,
               tenant: str | None = None,
               session: str | None = None) -> RequestTrace:
        """``trace_ctx`` is the propagated :class:`~edgemesh.obs.trace.
        TraceContext` from the fleet router's attempt span (None for
        locally-originated requests, which mint their own root).
        ``tenant`` is the raw ``X-Edgemesh-Tenant`` value (None when the
        request carried none) — normalization to a bounded label happens
        at the metric seam (obs/slo.py), never here, so the span record
        keeps the honest raw-ish string for offline attribution.
        ``session`` is the raw ``X-Edgemesh-Session`` value: span-record
        identity only (replay session grouping), never a metric label."""
        from edgemesh.obs.trace import TraceContext, sample

        trace = RequestTrace(rid, self.now())
        trace.tenant = tenant
        trace.session = session
        if trace_ctx is not None:
            trace.trace_id = trace_ctx.trace_id
            trace.parent_span_id = trace_ctx.span_id
            trace.sampled = trace_ctx.sampled
            ctx = trace_ctx.child()
        else:
            ctx = TraceContext.mint(
                sampled=sample(self.trace_sample, self._sample_rng)
            )
            trace.trace_id = ctx.trace_id
            trace.sampled = ctx.sampled
        trace.span_id = ctx.span_id
        self._submitted.inc()
        with self._ewma_lock:
            if self._last_submit is not None:
                dt = trace.t_submit - self._last_submit
                prev = self._ewma["arrival"]
                self._ewma["arrival"] = (
                    dt if prev is None
                    else EWMA_ALPHA * dt + (1.0 - EWMA_ALPHA) * prev
                )
            self._last_submit = trace.t_submit
        return trace

    def admit_start(self, trace: RequestTrace) -> None:
        """Admission picked the request off the queue (re-run on paged
        capacity re-queues — the last attempt wins the prefill span)."""
        trace.t_admit_start = self.now()

    def admitted(self, trace: RequestTrace, **attrs: Any) -> None:
        """Prefill spliced; the request is live in a slot."""
        now = self.now()
        t_adm = trace.t_admit_start if trace.t_admit_start is not None else now
        trace.span("queued", trace.t_submit, t_adm)
        trace.span("prefill", t_adm, now, **attrs)
        trace.t_start = now
        trace.t_last = now
        trace.attrs.update(attrs)
        self._queue_wait.observe(t_adm - trace.t_submit)
        self._prefill.observe(now - t_adm)
        # Computed prefill volume: the ragged path reports the tokens that
        # actually rode the boundary launch (``prefill_tokens`` — a warm or
        # imported admission's suffix), falling back to the full prompt.
        computed = attrs.get("prefill_tokens", attrs.get("prompt_tokens"))
        extra = {} if computed is None else {"prefill_tokens": float(computed)}
        self._ewma_update(queue=t_adm - trace.t_submit, prefill=now - t_adm,
                          **extra)

    def segment_dispatched(self) -> None:
        self._segments.inc()

    def tokens(self, trace: RequestTrace, n: int, **attrs: Any) -> None:
        """A drained segment credited ``n`` decode tokens to this request.
        ``attrs`` ride the decode span (e.g. ``collective_bytes`` — the tp
        serving engine's per-segment wire accounting, rolled up by
        ``obs.trace.critical_path``)."""
        now = self.now()
        if n > 0 and trace.t_first_token is None:
            trace.t_first_token = now
            self._ttft.observe(now - trace.t_submit)
        trace.span("decode", trace.t_last, now, tokens=int(n), **attrs)
        trace.segments += 1
        trace.generated += int(n)
        if n > 0:
            self._tokens.inc(n)
            self._ewma_update(decode=(now - trace.t_last) / n)
        trace.t_last = now

    def retire(self, trace: RequestTrace, status: str = "ok") -> float:
        """Close the trace, feed terminal aggregates, flush the JSONL record.
        Returns the retirement timestamp (the engine's ``t_end``)."""
        now = self.now()
        trace.t_end = now
        trace.status = status
        trace.span("retire", now, now)
        self._completed.labels(engine=self.engine, status=status).inc()
        itl = None
        if trace.t_first_token is not None and trace.generated > 1:
            itl = (now - trace.t_first_token) / (trace.generated - 1)
            self._itl.observe(itl, count=trace.generated - 1)
        self._latency.observe(now - trace.t_submit)
        self._ewma_update(service=now - trace.t_submit,
                          decode_tokens=float(trace.generated))
        # SLO verdict: TTFT and TPOT (mean inter-token) against the target.
        ttft = (
            None if trace.t_first_token is None
            else trace.t_first_token - trace.t_submit
        )
        slo_result = self.slo.record(status, ttft, itl, tenant=trace.tenant)
        # ONE record shape for both sinks (sampled JSONL + flight ring):
        # replay/assembly tooling must never see two vocabularies (EM113).
        record = dict(
            rid=trace.rid, engine=self.engine, status=status,
            tenant=trace.tenant, session=trace.session,
            trace_id=trace.trace_id, span_id=trace.span_id,
            parent_span_id=trace.parent_span_id,
            # Wall anchor for cross-process assembly: spans are
            # perf_counter values and spans[0].t0 == t_submit, so
            # wall(t) = ts_submit + (t - spans[0].t0) (obs/trace.py).
            ts_submit=trace.ts_unix,
            generated=trace.generated, segments=trace.segments,
            queue_s=(
                None if trace.t_admit_start is None
                else trace.t_admit_start - trace.t_submit
            ),
            prefill_s=(
                None if trace.t_start is None or trace.t_admit_start is None
                else trace.t_start - trace.t_admit_start
            ),
            ttft_s=ttft, itl_s=itl, latency_s=now - trace.t_submit,
            slo_result=slo_result,
            spans=trace.spans, **trace.attrs,
        )
        if self._log is not None and trace.sampled:
            self._log.log(SPAN_RECORD_EVENT, **record)
        if self.flight is not None:
            # Full fidelity regardless of the sampling bit: the ring exists
            # precisely for the records steady-state sampling drops.
            self.flight.record(SPAN_RECORD_EVENT, record)
        if self.anomaly is not None:
            self.anomaly.on_retire(slo_result, now - trace.t_submit,
                                   status=status)
        return now

    def pool_reset(self, reason: str = "") -> None:
        self._resets.inc()
        if self._log is not None:
            self._log.log(RESET_RECORD_EVENT, engine=self.engine,
                          reason=reason)
        if self.flight is not None:
            self.flight.record(RESET_RECORD_EVENT,
                               {"engine": self.engine, "reason": reason})

    # -- load digest (the /loadz feedback signal) ----------------------------

    def _ewma_update(self, **obs: float) -> None:
        with self._ewma_lock:
            for key, value in obs.items():
                prev = self._ewma[key]
                self._ewma[key] = (
                    value if prev is None
                    else EWMA_ALPHA * value + (1.0 - EWMA_ALPHA) * prev
                )

    def load_digest(self) -> dict[str, Any]:
        """The tracker's slice of the replica load digest: latency EWMAs
        (``None`` until first observed) + the running SLO goodput. The
        gateway merges in queue depth / inflight / the recent-compile flag
        (serve/rest.py ``/loadz``); the fleet prober ships the result to
        the router's :class:`~edgemesh.fleet.balancer.TelemetryBalancer`."""
        with self._ewma_lock:
            ew = dict(self._ewma)
            last_submit = self._last_submit
        # The arrival EWMA only updates on submit, so after traffic stops
        # it would report the last regime forever — and the autoscaler's
        # scale-DOWN branch would be unreachable. The gap since the last
        # submit is itself evidence: once it exceeds the EWMA, report the
        # gap (the effective inter-arrival keeps growing as the replica
        # sits idle).
        if ew["arrival"] is not None and last_submit is not None:
            gap = time.perf_counter() - last_submit
            if gap > ew["arrival"]:
                ew["arrival"] = gap
        rnd = {k: (None if v is None else round(v, 6)) for k, v in ew.items()}
        ratio = self.slo.goodput_ratio()
        return {
            "ewma_queue_s": rnd["queue"],
            "ewma_prefill_s": rnd["prefill"],
            "ewma_decode_s": rnd["decode"],
            "ewma_service_s": rnd["service"],
            # Phase-volume split (tokens, not seconds): what the fleet's
            # tier manager scores replicas by. None until first observed —
            # pre-split consumers ignore the extra keys by construction.
            "ewma_prefill_tokens": rnd["prefill_tokens"],
            "ewma_decode_tokens": rnd["decode_tokens"],
            # Arrival side: mean inter-arrival seconds (None until the
            # second submit). The autoscaler reads offered load as
            # 1/ewma_arrival_s per replica (docs/FLEET.md "Autoscaling").
            "ewma_arrival_s": rnd["arrival"],
            "slo_goodput_ratio": None if ratio is None else round(ratio, 4),
        }


def replay_spans(records: Iterable[dict] | str | Path,
                 registry: Registry | None = None) -> Registry:
    """Rebuild request-level registry aggregates from a span JSONL log.

    Accepts a path (read via ``JsonlLogger`` — torn trailing lines are
    skipped, not fatal) or an iterable of decoded records. Segment counters
    are pool-wide engine state and do not replay; everything observed per
    request (queue wait, prefill, TTFT, inter-token, latency, tokens,
    completions, pool resets) does — ``edgemesh obs summary`` and a live
    scrape agree on those families by construction.
    """
    registry = registry if registry is not None else Registry()
    trackers: dict[str, SpanTracker] = {}
    launch_cum: dict[tuple[str, str], int] = {}
    pool_state: dict = {}
    if isinstance(records, (str, Path)):
        from edgemesh.utils.tracing import JsonlLogger

        records = JsonlLogger(records).read()
    for rec in records:
        engine = rec.get("engine", "continuous")
        tr = trackers.get(engine)
        if tr is None:
            tr = trackers[engine] = SpanTracker(registry, engine=engine)
        event = rec.get("event")
        if event == RESET_RECORD_EVENT:
            tr._resets.inc()
            continue
        if event == "launch":
            # Per-launch ledger records (obs/compute.py) replay into the
            # same families a live scrape serves. Deferred import: compute
            # imports EWMA_ALPHA from this module. Null-safe throughout —
            # a record missing any field (or carrying unknown extras from
            # a newer build) still replays what it has.
            from edgemesh.obs.compute import LAUNCH_BUCKETS

            boundary = str(rec.get("boundary") or "?")
            # Records are 1-in-N sampled but carry the cumulative dispatch
            # counter — replaying the deltas (not the record count) keeps
            # the offline counter equal to what a live scrape would show.
            cum = rec.get("launches")
            prev = launch_cum.get((engine, boundary), 0)
            inc = (cum - prev if isinstance(cum, int) and cum > prev else 1)
            if isinstance(cum, int):
                launch_cum[(engine, boundary)] = max(cum, prev)
            registry.counter(
                "edgemesh_launches_total",
                "Jitted boundary launches dispatched",
                ("engine", "boundary"),
            ).labels(engine=engine, boundary=boundary).inc(inc)
            if isinstance(rec.get("measured_s"), (int, float)):
                registry.histogram(
                    "edgemesh_launch_seconds",
                    "Sampled fenced launch wall time per boundary",
                    ("engine", "boundary"), buckets=LAUNCH_BUCKETS,
                ).labels(engine=engine, boundary=boundary).observe(
                    float(rec["measured_s"]))
            if isinstance(rec.get("roofline_fraction"), (int, float)):
                registry.gauge(
                    "edgemesh_launch_roofline_ratio",
                    "Last sampled achieved/attainable roofline fraction",
                    ("engine", "boundary"),
                ).labels(engine=engine, boundary=boundary).set(
                    float(rec["roofline_fraction"]))
            continue
        if event == "pool_mem":
            # Page-lifecycle records (obs/memory.py) replay into the pool
            # families a live scrape serves — event counters, the
            # conservation tripwire, per-tenant residency gauges. Deferred
            # import: memory imports bounded_label from metrics only, but
            # the lazy pattern keeps this module jax-free-cheap to load.
            from edgemesh.obs.memory import replay_pool_record

            pool_state = replay_pool_record(registry, rec, pool_state)
            continue
        if event != SPAN_RECORD_EVENT:
            continue
        tr._submitted.inc()
        tr._completed.labels(
            engine=engine, status=rec.get("status") or "ok").inc()
        gen = int(rec.get("generated") or 0)
        if gen:
            tr._tokens.inc(gen)
        if rec.get("queue_s") is not None:
            tr._queue_wait.observe(rec["queue_s"])
        if rec.get("prefill_s") is not None:
            tr._prefill.observe(rec["prefill_s"])
        if rec.get("ttft_s") is not None:
            tr._ttft.observe(rec["ttft_s"])
        if rec.get("itl_s") is not None and gen > 1:
            tr._itl.observe(rec["itl_s"], count=gen - 1)
        if rec.get("latency_s") is not None:
            tr._latency.observe(rec["latency_s"])
        # SLO verdicts replay pre-classified (target-independent): logs
        # from before the slo_result field simply skip the family, and
        # pre-tenant records (no "tenant" key, or null) feed the aggregate
        # family only — the per-tenant twins stay untouched. Unknown keys
        # in FUTURE records are ignored by construction (every read here
        # is .get on a known key), which is the other half of the
        # forward-compat contract tests/test_obs.py pins.
        if rec.get("slo_result") in SLO_RESULTS:
            tr.slo.count(rec["slo_result"], tenant=rec.get("tenant"))
    return registry
