"""The memory observatory: page-lifecycle ledger for the paged KV pool.

The pool is the scarcest resource in the serving stack, yet until this
module it was observed as three coarse gauges (``pool_state``'s
occupancy / fragmentation / headroom). Nobody could answer *which tenant
holds the pool*, *is this occupancy a leak or load*, or *how many
seconds until exhaustion*. :class:`PoolLedger` closes that gap from ONE
seam: every page-pool transition the engine performs — worst-case
reserve at admission, per-boundary commit, COW template split,
``/kv/import`` splice, export scratch, trash-page padding, free at
retire/abort, pool rebuild — arrives as an attributed event
``{engine, request, tenant, cause}``, recorded beside the existing
stats under the engine lock (edgelint EM115 makes the seam load-bearing:
direct free-list mutation outside it is an error). From that stream the
ledger derives:

- **per-tenant residency**: ``edgemesh_pool_tenant_pages{engine,tenant}``
  gauges plus peak watermarks, every label minted through
  ``bounded_label`` (the EM112 cardinality contract);
- **fragmentation, decomposed**: *internal* = reserved-minus-committed
  pages (the worst-case admission head-room each live request is sitting
  on, split by originating cause) vs *external* = free pages that cannot
  form another worst-case admission (the admission-granularity
  remainder — a paged pool has no placement fragmentation, but admission
  quantizes in ``per_row_worst`` units);
- **a conservation invariant**: ``free + resident + reserved_overhead ==
  total`` checked at every engine quiesce; a violation increments the
  ``edgemesh_pool_conservation_breaks_total`` tripwire and logs a
  ``pool_mem`` record — the ledger never "fixes" the books;
- **a leak detector**: pages whose owning request retired ≥ N seconds
  ago. Fires the ``pool_leak`` anomaly kind (obs/anomaly.py), which
  dumps flight rings fleet-wide through the standard incident
  propagation path;
- **an exhaustion forecast**: time-to-empty from the arrival EWMA ×
  per-request worst-case pages, published in the load digest's ``mem``
  block and consumed by the admission controller (batch-lane deferral —
  fleet/admission.py) and the autoscaler (memory-pressure scale-up —
  fleet/autoscale.py). The forecast is reconciled against the device's
  own ``memory_stats`` so ledger-vs-HBM drift is itself a reported
  number rather than a silent assumption.

Offline twins :func:`summarize_mem` / :func:`diff_mem` rebuild the same
views from span logs (``edgemesh obs mem``), with the standing
forward/backward compatibility contract: logs without ``pool_mem``
records summarize to None (rc 0), unknown keys on future records are
ignored.

Importing this module never imports jax (the obs package contract); the
only device touch is the lazy ``memory_stats`` probe inside
:meth:`PoolLedger.digest_mem`, which degrades to None on CPU.
"""

from __future__ import annotations

import os
import threading
import time
from pathlib import Path
from typing import Any, Callable, Iterable

from edgemesh.obs.metrics import Registry, bounded_label, get_registry

#: Span-log event name (the obs JSONL one-record-vocabulary — edgelint
#: EM113): one ``pool_mem`` record per attributed pool transition.
POOL_RECORD_EVENT = "pool_mem"

#: ``EDGEMESH_MEM_LEDGER=0`` disables the ledger entirely — the
#: overhead-gate off arm benchmarks.py flips (PERFORMANCE.md pins the
#: on/off p50 ratio at <= 1.02, same contract as the compute ledger).
ENABLE_ENV = "EDGEMESH_MEM_LEDGER"

#: The transition vocabulary. Every event names the cause that moved the
#: pages; ``conservation_break`` and ``leak`` are derived findings that
#: ride the same record stream so offline replay sees them in order.
CAUSES = (
    "admit",      # worst-case reserve at (cold or staged) admission
    "cow",        # COW template split: pages popped to back a warm admit
    "import",     # /kv/import splice (donated scatter, trash-padded)
    "export",     # export scratch prefill (popped, walked, freed)
    "template",   # shared prefix template installation
    "retire",     # free at normal retirement
    "abort",      # free on failed/aborted admission or preemption
    "reset",      # pool rebuild: every resident page returns at once
)

#: Reserved request id for pages the engine itself holds (the shared
#: prefix template) — attributed to the ``system`` tenant.
TEMPLATE_RID = "__template__"
SYSTEM_TENANT = "system"


def _env_enabled() -> bool:
    return os.environ.get(ENABLE_ENV, "1") != "0"


class _Holding:
    """One owner's live page count (a request, or the template)."""

    __slots__ = ("rid", "tenant", "pages", "committed", "committed_tokens",
                 "cause", "retired_at")

    def __init__(self, rid, tenant: str, cause: str) -> None:
        self.rid = rid
        self.tenant = tenant
        self.pages = 0
        self.committed = 0
        self.committed_tokens = 0
        self.cause = cause
        self.retired_at: float | None = None


class PoolLedger:
    """Attributed page-lifecycle ledger for one engine's KV pool.

    The engine calls the ``on_*`` hooks from inside its own lock (the
    transitions and the free list must agree), but the ledger carries its
    own lock too: the read side (``digest_mem`` / ``rollup`` / CLI) runs
    on gateway threads, and the speculative engine's draft pool feeds a
    sibling ledger outside the main engine lock.
    """

    def __init__(self, registry: Registry | None = None,
                 engine: str = "continuous",
                 total_pages: int = 0,
                 page_size: int = 0,
                 per_row_worst: int = 0,
                 page_bytes: int = 0,
                 reserved_overhead: int = 1,
                 span_log: str | Path | None = None,
                 flight_source: Callable[[], Any] | None = None,
                 anomaly_source: Callable[[], Any] | None = None,
                 enabled: bool | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self.registry = registry or get_registry()
        self.engine = engine
        self.total_pages = int(total_pages or 0)
        self.page_size = int(page_size or 0)
        self.per_row_worst = int(per_row_worst or 0)
        #: Device bytes one pool page occupies (runtime/paged_kv.py
        #: ``page_nbytes``) — what prices the ledger against HBM.
        self.page_bytes = int(page_bytes or 0)
        #: Pages the pool holds back by construction (the trash page the
        #: free list never contains) — part of the conservation equation.
        self.reserved_overhead = int(reserved_overhead)
        self.enabled = _env_enabled() if enabled is None else bool(enabled)
        self._flight_source = flight_source
        self._anomaly_source = anomaly_source
        self._clock = clock
        self._lock = threading.Lock()
        self._holdings: dict[Any, _Holding] = {}
        self._tenant_pages: dict[str, int] = {}
        self._tenant_peaks: dict[str, int] = {}
        self._resident = 0
        self._peak_resident = 0
        self._events: dict[str, list[int]] = {}  # cause -> [count, pages]
        self._breaks = 0
        self._resets = 0
        self._seen = False
        self._hbm_base: tuple[float, int] | None = None
        self._last_free: int | None = None
        self._log = None
        if span_log is not None and self.enabled:
            from edgemesh.utils.tracing import JsonlLogger

            self._log = JsonlLogger(span_log)
        reg = self.registry
        self._tenant_gauge = reg.gauge(
            "edgemesh_pool_tenant_pages",
            "Pool pages currently resident, attributed per tenant",
            ("engine", "tenant"))
        self._tenant_peak_gauge = reg.gauge(
            "edgemesh_pool_tenant_peak_pages",
            "Peak resident-page watermark per tenant",
            ("engine", "tenant"))
        self._events_total = reg.counter(
            "edgemesh_pool_events_total",
            "Attributed page-pool transitions, by cause",
            ("engine", "cause"))
        self._pages_total = reg.counter(
            "edgemesh_pool_pages_moved_total",
            "Pages moved through the ledger seam, by cause",
            ("engine", "cause"))
        self._breaks_total = reg.counter(
            "edgemesh_pool_conservation_breaks_total",
            "Conservation-invariant violations (allocated + free != total)",
            ("engine",))
        self._leaked_gauge = reg.gauge(
            "edgemesh_pool_leaked_pages",
            "Pages still resident past the leak age bound, owner retired",
            ("engine",))
        self._forecast_gauge = reg.gauge(
            "edgemesh_pool_forecast_seconds",
            "Exhaustion forecast: seconds until the free list empties at "
            "the observed arrival rate × worst-case pages per request",
            ("engine",))

    # -- the transition seam -------------------------------------------------

    def _label(self, tenant: str | None) -> str:
        return bounded_label(tenant)

    def on_reserve(self, n: int, rid=None, tenant: str | None = None,
                   cause: str = "admit", free: int | None = None) -> None:
        """``n`` pages left the free list for ``rid`` (cause: admit / cow /
        import / export / template). ``free`` is the free-list length
        AFTER the pop when the caller has it at hand — it makes the span
        record self-contained for offline occupancy replay."""
        if not self.enabled or n <= 0:
            return
        label = self._label(tenant)
        with self._lock:
            self._seen = True
            h = self._holdings.get(rid)
            if h is None:
                h = self._holdings[rid] = _Holding(rid, label, cause)
            h.pages += n
            h.retired_at = None
            self._resident += n
            self._peak_resident = max(self._peak_resident, self._resident)
            t = self._tenant_pages.get(h.tenant, 0) + n
            self._tenant_pages[h.tenant] = t
            self._tenant_peaks[h.tenant] = max(
                self._tenant_peaks.get(h.tenant, 0), t)
            resident = self._resident
            if free is not None:
                self._last_free = int(free)
            cell = self._events.setdefault(cause, [0, 0])
            cell[0] += 1
            cell[1] += n
        self._events_total.labels(engine=self.engine, cause=cause).inc()
        self._pages_moved(cause, n)
        self._tenant_gauge.labels(engine=self.engine, tenant=h.tenant).set(t)
        self._tenant_peak_gauge.labels(
            engine=self.engine, tenant=h.tenant).set(self._tenant_peaks[h.tenant])
        self._emit(cause, n, rid, h.tenant, resident, free)

    def on_commit(self, rid, committed_pages: int | None = None,
                  add_tokens: int | None = None) -> None:
        """Per-boundary commit: ``rid``'s row has actually written into its
        private pages. ``add_tokens`` accumulates host-observed tokens
        (admission suffix, then each drained segment's emit count) and the
        ledger converts to pages; ``committed_pages`` sets an absolute
        floor directly. Pure dict update — cheap enough for every drained
        segment; the reserved-minus-committed remainder is the
        internal-fragmentation number the digest splits out."""
        if not self.enabled:
            return
        with self._lock:
            h = self._holdings.get(rid)
            if h is None:
                return
            if add_tokens is not None and self.page_size > 0:
                h.committed_tokens += max(0, int(add_tokens))
                committed_pages = -(-h.committed_tokens // self.page_size)
            if committed_pages is not None:
                h.committed = max(h.committed,
                                  min(int(committed_pages), h.pages))

    def on_free(self, n: int, rid=None, cause: str = "retire",
                free: int | None = None) -> None:
        """``n`` pages returned to the free list (cause: retire / abort /
        export). The owner's holding drains; a holding that empties is
        dropped (its leak clock never starts)."""
        if not self.enabled or n <= 0:
            return
        with self._lock:
            self._seen = True
            h = self._holdings.get(rid)
            label = h.tenant if h is not None else self._label(None)
            if h is not None:
                h.pages = max(0, h.pages - n)
                h.committed = min(h.committed, h.pages)
                if h.pages == 0:
                    self._holdings.pop(rid, None)
            self._resident = max(0, self._resident - n)
            t = max(0, self._tenant_pages.get(label, 0) - n)
            self._tenant_pages[label] = t
            resident = self._resident
            if free is not None:
                self._last_free = int(free)
            cell = self._events.setdefault(cause, [0, 0])
            cell[0] += 1
            cell[1] += n
        self._events_total.labels(engine=self.engine, cause=cause).inc()
        self._pages_moved(cause, n)
        self._tenant_gauge.labels(engine=self.engine, tenant=label).set(t)
        self._emit(cause, -n, rid, label, resident, free)

    def on_retired(self, rid) -> None:
        """The owning request retired. Pages still held start the leak
        clock; a clean retirement (pages already freed) is a no-op."""
        if not self.enabled:
            return
        with self._lock:
            h = self._holdings.get(rid)
            if h is not None and h.pages > 0 and h.retired_at is None:
                h.retired_at = self._clock()

    def on_reset(self, reason: str = "") -> None:
        """The pool was rebuilt (failed segment/admission recovery, cap
        regrow): every resident page returned at once. The books zero;
        the event records how many pages the reset reclaimed."""
        if not self.enabled:
            return
        with self._lock:
            self._seen = True
            reclaimed = self._resident
            self._holdings.clear()
            tenants = list(self._tenant_pages)
            self._tenant_pages = {t: 0 for t in tenants}
            self._resident = 0
            self._resets += 1
            self._last_free = None
            cell = self._events.setdefault("reset", [0, 0])
            cell[0] += 1
            cell[1] += reclaimed
        self._events_total.labels(engine=self.engine, cause="reset").inc()
        self._pages_moved("reset", reclaimed)
        for t in tenants:
            self._tenant_gauge.labels(engine=self.engine, tenant=t).set(0)
        self._emit("reset", -reclaimed, None, None, 0, None,
                   extra={"reason": reason})

    # -- derived findings ----------------------------------------------------

    def check_conservation(self, free_pages: int) -> bool:
        """The invariant, checked at quiesce: ``free + resident +
        reserved_overhead == total``. A break increments the tripwire
        counter and logs a ``pool_mem`` record carrying the discrepancy —
        the ledger reports the broken books, it never rebalances them."""
        if not self.enabled or self.total_pages <= 0:
            return True
        with self._lock:
            if not self._seen:
                return True
            resident = self._resident
            expected = self.total_pages - self.reserved_overhead
            diff = (int(free_pages) + resident) - expected
            self._last_free = int(free_pages)
            if diff == 0:
                return True
            self._breaks += 1
        self._breaks_total.labels(engine=self.engine).inc()
        self._emit("conservation_break", diff, None, None, resident,
                   int(free_pages),
                   extra={"expected": expected,
                          "total": self.total_pages})
        return False

    def leak_scan(self, now: float | None = None) -> list[dict]:
        """Holdings whose owner retired and whose pages are still
        resident. The age judgment (and the fire-once dedup) lives in the
        anomaly monitor's ``pool_leak`` detector; the ledger reports
        every candidate with its age and lets the monitor decide."""
        if not self.enabled:
            return []
        t = self._clock() if now is None else now
        leaks: list[dict] = []
        with self._lock:
            for h in self._holdings.values():
                if h.retired_at is None or h.pages <= 0:
                    continue
                leaks.append({
                    "rid": h.rid, "tenant": h.tenant, "pages": h.pages,
                    "age_s": round(max(0.0, t - h.retired_at), 3),
                    "cause": h.cause,
                })
        self._leaked_gauge.labels(engine=self.engine).set(
            sum(rec["pages"] for rec in leaks))
        if leaks and self._anomaly_source is not None:
            try:
                monitor = self._anomaly_source()
            except Exception:
                monitor = None
            if monitor is not None:
                for rec in leaks:
                    fired = monitor.on_pool_leak(
                        str(rec["rid"]), rec["age_s"],
                        detail={"engine": self.engine, **rec})
                    if fired:
                        self._emit("leak", rec["pages"], rec["rid"],
                                   rec["tenant"], None, None,
                                   extra={"age_s": rec["age_s"]})
        return leaks

    # -- read side -----------------------------------------------------------

    def forecast(self, free_pages: int,
                 arrival_ewma_s: float | None) -> float | None:
        """Seconds until the free list empties: each arriving request
        reserves ``per_row_worst`` pages, requests arrive every
        ``arrival_ewma_s`` seconds. None when either input is unknown —
        the forecast never guesses (capacity-model convention)."""
        if (not arrival_ewma_s or arrival_ewma_s <= 0
                or self.per_row_worst <= 0):
            return None
        pages_per_s = self.per_row_worst / float(arrival_ewma_s)
        return round(max(0, int(free_pages)) / pages_per_s, 3)

    def _frag_locked(self) -> dict:
        internal_by_cause: dict[str, int] = {}
        internal = 0
        for h in self._holdings.values():
            over = max(0, h.pages - h.committed)
            if over:
                internal += over
                internal_by_cause[h.cause] = (
                    internal_by_cause.get(h.cause, 0) + over)
        free = self._last_free
        external = (
            free % self.per_row_worst
            if free is not None and self.per_row_worst > 0 else None
        )
        return {
            "internal_pages": internal,
            "internal_by_cause": internal_by_cause,
            "external_pages": external,
        }

    def digest_mem(self, free_pages: int | None = None,
                   arrival_ewma_s: float | None = None) -> dict | None:
        """The load digest's ``mem`` block. None until the ledger has
        seen a transition — pre-mem consumers (and old routers) see
        exactly the digest they always did, and a dense-backend engine
        (no pool) never grows the key."""
        if not self.enabled:
            return None
        with self._lock:
            if not self._seen:
                return None
            if free_pages is not None:
                self._last_free = int(free_pages)
            free = self._last_free
            resident = self._resident
            committed = sum(h.committed for h in self._holdings.values())
            tenants = {t: p for t, p in sorted(self._tenant_pages.items())
                       if p > 0}
            leak_pages = sum(h.pages for h in self._holdings.values()
                             if h.retired_at is not None)
            leak_reqs = sum(1 for h in self._holdings.values()
                            if h.retired_at is not None and h.pages > 0)
            frag = self._frag_locked()
            breaks = self._breaks
        fc = None if free is None else self.forecast(free, arrival_ewma_s)
        if fc is not None:
            self._forecast_gauge.labels(engine=self.engine).set(fc)
        return {
            "total_pages": self.total_pages or None,
            "free_pages": free,
            "resident_pages": resident,
            "committed_pages": committed,
            "per_row_worst": self.per_row_worst or None,
            "tenants": tenants or None,
            "frag": frag,
            "leak": {"requests": leak_reqs, "pages": leak_pages},
            "forecast_s": fc,
            "drift": self._drift(resident),
            "conservation_breaks": breaks,
        }

    def rollup(self) -> dict:
        """Cumulative aggregate for ``stats()`` / BENCH JSON / ``edgemesh
        obs mem`` on live state. Falsy ({}) before the first transition."""
        with self._lock:
            if not self._seen:
                return {}
            frag = self._frag_locked()
            return {
                "engine": self.engine,
                "total_pages": self.total_pages or None,
                "free_pages": self._last_free,
                "resident_pages": self._resident,
                "peak_resident_pages": self._peak_resident,
                "events": {c: {"count": n, "pages": p}
                           for c, (n, p) in sorted(self._events.items())},
                "tenants": {
                    t: {"pages": self._tenant_pages.get(t, 0),
                        "peak_pages": pk}
                    for t, pk in sorted(self._tenant_peaks.items())
                },
                "frag": frag,
                "leaked_pages": sum(
                    h.pages for h in self._holdings.values()
                    if h.retired_at is not None),
                "conservation_breaks": self._breaks,
                "resets": self._resets,
            }

    # -- reconciliation ------------------------------------------------------

    def _drift(self, resident: int) -> dict | None:
        """Ledger-vs-HBM reconciliation: from a baseline captured at the
        first probe, device bytes-in-use should move by exactly
        ``delta_resident × page_bytes``. The residual IS the drift
        number. None wherever the device withholds ``memory_stats``
        (CPU) or the page size is unknown — reported, never guessed."""
        if self.page_bytes <= 0:
            return None
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats()
        except Exception:
            return None
        if not isinstance(stats, dict):
            return None
        in_use = stats.get("bytes_in_use")
        if not isinstance(in_use, (int, float)):
            return None
        with self._lock:
            if self._hbm_base is None:
                self._hbm_base = (float(in_use), int(resident))
            base_bytes, base_resident = self._hbm_base
        expected = base_bytes + (resident - base_resident) * self.page_bytes
        return {
            "hbm_bytes_in_use": int(in_use),
            "expected_bytes": int(expected),
            "drift_bytes": int(in_use - expected),
            "page_bytes": self.page_bytes,
        }

    # -- sinks ---------------------------------------------------------------

    def _pages_moved(self, cause: str, n: int) -> None:
        if n > 0:
            self._pages_total.labels(engine=self.engine, cause=cause).inc(n)

    def _emit(self, cause: str, delta: int, rid, tenant: str | None,
              resident: int | None, free: int | None,
              extra: dict | None = None) -> None:
        rec = {
            "engine": self.engine,
            "cause": cause,
            "delta": int(delta),
            "rid": rid,
            "tenant": tenant,
            "resident": resident,
            "free": free,
            "total": self.total_pages or None,
        }
        if extra:
            rec.update(extra)
        if self._log is not None:
            self._log.log(POOL_RECORD_EVENT, **rec)
        if self._flight_source is not None:
            try:
                fl = self._flight_source()
                if fl is not None:
                    fl.record(POOL_RECORD_EVENT, rec)
            except Exception:  # flight is best-effort by contract
                pass


# ---------------------------------------------------------------------------
# Offline analysis (span logs → rollup) — `edgemesh obs mem`
# ---------------------------------------------------------------------------


def summarize_mem(records: Iterable[dict]) -> dict | None:
    """Pool rollup from span-log records — the offline twin of
    :meth:`PoolLedger.rollup`, consumed by ``edgemesh obs mem`` and the
    ``mem`` block of ``edgemesh obs summary``.

    Returns None when the log carries no ``pool_mem`` records at all: a
    pre-mem log is an answer, not an error (the CLI prints null and
    exits 0). Unknown keys on future records are ignored and
    known-but-missing keys read as None — both compatibility directions
    are pinned in tests/test_memory.py.
    """
    n = 0
    events: dict[str, list[int]] = {}
    tenant_pages: dict[str, int] = {}
    tenant_peaks: dict[str, int] = {}
    engines: set[str] = set()
    peak_resident = 0
    last_resident = None
    last_free = None
    total = None
    breaks = 0
    leaks: list[dict] = []
    for rec in records:
        if not isinstance(rec, dict) or rec.get("event") != POOL_RECORD_EVENT:
            continue
        n += 1
        cause = str(rec.get("cause") or "?")
        delta = rec.get("delta")
        delta = int(delta) if isinstance(delta, int) else 0
        if rec.get("engine") is not None:
            engines.add(str(rec["engine"]))
        if cause == "conservation_break":
            breaks += 1
            continue
        if cause == "leak":
            leaks.append({"rid": rec.get("rid"),
                          "tenant": rec.get("tenant"),
                          "pages": abs(delta),
                          "age_s": rec.get("age_s")})
            continue
        cell = events.setdefault(cause, [0, 0])
        cell[0] += 1
        cell[1] += abs(delta)
        tenant = rec.get("tenant")
        if cause == "reset":
            tenant_pages = {t: 0 for t in tenant_pages}
        elif tenant is not None:
            t = str(tenant)
            cur = max(0, tenant_pages.get(t, 0) + delta)
            tenant_pages[t] = cur
            tenant_peaks[t] = max(tenant_peaks.get(t, 0), cur)
        if isinstance(rec.get("resident"), int):
            last_resident = rec["resident"]
            peak_resident = max(peak_resident, last_resident)
        if isinstance(rec.get("free"), int):
            last_free = rec["free"]
        if isinstance(rec.get("total"), int):
            total = rec["total"]
    if n == 0:
        return None
    return {
        "pool_records": n,
        "engines": sorted(engines),
        "total_pages": total,
        "peak_resident_pages": peak_resident,
        "last_resident_pages": last_resident,
        "last_free_pages": last_free,
        "events": {c: {"count": cnt, "pages": pages}
                   for c, (cnt, pages) in sorted(events.items())},
        "tenants": {
            t: {"pages": tenant_pages.get(t, 0), "peak_pages": pk}
            for t, pk in sorted(tenant_peaks.items())
        } or None,
        "conservation_breaks": breaks,
        "leaks": leaks or None,
    }


def diff_mem(a: dict | None, b: dict | None) -> dict:
    """Side-by-side comparison of two :func:`summarize_mem` results
    (``edgemesh obs mem A --diff B``): peak residency, per-tenant peaks,
    per-cause page volume, and the tripwire counters. A tenant or cause
    present on only one side still gets a row — residency appearing or
    vanishing between two runs IS the finding."""
    def cell(side: dict | None, *path):
        cur: Any = side or {}
        for key in path:
            if not isinstance(cur, dict):
                return None
            cur = cur.get(key)
        return cur

    tenants = sorted(set((cell(a, "tenants") or {}))
                     | set((cell(b, "tenants") or {})))
    causes = sorted(set((cell(a, "events") or {}))
                    | set((cell(b, "events") or {})))
    ap, bp = cell(a, "peak_resident_pages"), cell(b, "peak_resident_pages")
    return {
        "a_peak_resident_pages": ap,
        "b_peak_resident_pages": bp,
        "peak_ratio": (round(bp / ap, 4) if ap and bp else None),
        "tenants": {
            t: {"a_peak_pages": cell(a, "tenants", t, "peak_pages"),
                "b_peak_pages": cell(b, "tenants", t, "peak_pages")}
            for t in tenants
        },
        "events": {
            c: {"a_pages": cell(a, "events", c, "pages"),
                "b_pages": cell(b, "events", c, "pages")}
            for c in causes
        },
        "a_conservation_breaks": cell(a, "conservation_breaks"),
        "b_conservation_breaks": cell(b, "conservation_breaks"),
        "a_leaks": cell(a, "leaks"),
        "b_leaks": cell(b, "leaks"),
    }


def replay_pool_record(registry: Registry, rec: dict,
                       state: dict | None = None) -> dict:
    """Replay one ``pool_mem`` record into registry families — the seam
    ``obs/spans.replay_spans`` routes pool records through, so ``edgemesh
    obs summary``/``prom`` rebuild the same pool families a live scrape
    serves. ``state`` threads per-tenant residency between calls (the
    caller owns it; pass the returned dict back in)."""
    state = state if state is not None else {}
    engine = str(rec.get("engine") or "continuous")
    cause = str(rec.get("cause") or "?")
    registry.counter(
        "edgemesh_pool_events_total",
        "Attributed page-pool transitions, by cause",
        ("engine", "cause")).labels(engine=engine, cause=cause).inc()
    if cause == "conservation_break":
        registry.counter(
            "edgemesh_pool_conservation_breaks_total",
            "Conservation-invariant violations (allocated + free != total)",
            ("engine",)).labels(engine=engine).inc()
        return state
    delta = rec.get("delta")
    tenant = rec.get("tenant")
    if isinstance(delta, int) and tenant is not None and cause != "leak":
        # Records carry the already-bounded tenant, but a hand-edited or
        # foreign log must not mint unbounded label values on replay.
        label = bounded_label(str(tenant))
        key = (engine, label)
        cur = max(0, state.get(key, 0) + delta)
        state[key] = cur
        registry.gauge(
            "edgemesh_pool_tenant_pages",
            "Pool pages currently resident, attributed per tenant",
            ("engine", "tenant")).labels(engine=engine,
                                         tenant=label).set(cur)
    return state
