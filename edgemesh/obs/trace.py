"""Distributed tracing: one trace id from the fleet router to the engine.

PR 2's spans stop at the process boundary and the fleet router only sees
black-box attempt latencies — the profiling-driven placement line
(PAPERS.md: TPI-LLM, profiling-driven edge inference) needs per-stage,
per-device timing for a *single* request across every process it touched.
This module is that seam, in four pieces:

- **TraceContext**: a W3C ``traceparent``-compatible context
  (``00-<trace_id:32hex>-<span_id:16hex>-<flags:2hex>``, flag bit 0 =
  sampled) carried on the ``X-Edgemesh-Trace`` header. The router mints
  one per request and mints a *child* context per retry/hedge attempt;
  the replica gateway parses it and hands it to the engine's
  ``SpanTracker``, so the engine's queued/prefill/decode spans become
  children of the router's attempt span.
- **Cross-process assembly**: every process appends trace-stamped records
  to its own span JSONL (the router writes ``router_spans`` records, the
  engines stamp trace ids into their existing ``request_spans`` records);
  ``assemble_trace`` merges records for one trace id into a single tree,
  correcting per-process clock skew by anchoring each replica's window on
  the request/response edge of its parent attempt span (the symmetric
  NTP offset: ``((send − server_start) + (recv − server_end)) / 2``).
- **Critical path**: ``critical_path(tree)`` splits the client-observed
  latency into wire vs queue vs prefill vs decode vs retry-wasted time
  (plus an explicit residue) — the durations sum to the root span by
  construction.
- **Compile telemetry**: ``install_compile_hook`` registers a
  ``jax.monitoring`` duration listener (via the drift shim in
  ``utils/compat.py``) that counts compiles/recompiles as labeled
  metrics and, with a span log, emits ``compile`` records stamped with
  the ambient trace context (``current_trace``) so a first-request
  compile shows up inside that request's assembled trace.

No jax at module scope — the router and the ``edgemesh obs`` CLI stay
importable on hosts with no accelerator (same contract as the rest of
``edgemesh.obs``); only ``install_compile_hook`` touches jax, lazily.
"""

from __future__ import annotations

import contextvars
import os
import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Iterable

TRACE_HEADER = "X-Edgemesh-Trace"
#: Router-side record event (the engines keep ``request_spans``).
ROUTER_RECORD_EVENT = "router_spans"
#: JAX compile-duration record event.
COMPILE_RECORD_EVENT = "compile"

_VERSION = "00"


def _hex_id(nbytes: int, rng: random.Random | None = None) -> str:
    if rng is not None:
        return rng.getrandbits(nbytes * 8).to_bytes(nbytes, "big").hex()
    return os.urandom(nbytes).hex()


@dataclass(frozen=True)
class TraceContext:
    """One hop of a distributed trace: (trace_id, this hop's span_id)."""

    trace_id: str  # 32 lowercase hex chars
    span_id: str   # 16 lowercase hex chars
    sampled: bool = True

    @classmethod
    def mint(cls, sampled: bool = True,
             rng: random.Random | None = None) -> "TraceContext":
        """A fresh root context. ``rng`` is injectable for deterministic
        tests; production minting uses ``os.urandom`` — per-process seeded
        PRNGs would collide trace ids across replicas."""
        return cls(_hex_id(16, rng), _hex_id(8, rng), sampled)

    def child(self, rng: random.Random | None = None) -> "TraceContext":
        """Same trace, new span id — one per retry/hedge attempt."""
        return TraceContext(self.trace_id, _hex_id(8, rng), self.sampled)

    def to_header(self) -> str:
        return (
            f"{_VERSION}-{self.trace_id}-{self.span_id}-"
            f"{'01' if self.sampled else '00'}"
        )

    @classmethod
    def parse(cls, header: str | None) -> "TraceContext | None":
        """Parse an ``X-Edgemesh-Trace`` value. Malformed headers return
        ``None`` (W3C semantics: a broken context is dropped, never a 400 —
        tracing must not be able to fail a request)."""
        if not header:
            return None
        parts = header.strip().split("-")
        if len(parts) != 4:
            return None
        version, trace_id, span_id, flags = parts
        if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
            return None
        try:
            int(version, 16)
            int(trace_id, 16)
            int(span_id, 16)
            flag_bits = int(flags, 16)
        except ValueError:
            return None
        if set(trace_id) == {"0"} or set(span_id) == {"0"}:
            return None  # all-zero ids are invalid per traceparent
        return cls(trace_id.lower(), span_id.lower(), bool(flag_bits & 1))


# ---------------------------------------------------------------------------
# Ambient context (what the compile hook stamps onto its records)
# ---------------------------------------------------------------------------

_current: contextvars.ContextVar[TraceContext | None] = contextvars.ContextVar(
    "edgemesh_trace", default=None
)


def current_trace() -> TraceContext | None:
    return _current.get()


def sample(rate: float, rng: random.Random) -> bool:
    """One span-I/O sampling decision — THE definition, shared by the
    router and the replica trackers so their semantics cannot diverge.
    ``rate >= 1`` always samples without consuming the rng."""
    return rate >= 1.0 or rng.random() < rate


@contextmanager
def use_trace(ctx: TraceContext | None):
    """Bind ``ctx`` as the ambient trace for the duration of the block
    (a no-op when ``ctx`` is None)."""
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


# ---------------------------------------------------------------------------
# Cross-process assembly
# ---------------------------------------------------------------------------
#
# Two record clock conventions meet here:
# - router records carry wall-clock span edges directly (``clock: "wall"``);
# - engine records carry ``perf_counter`` edges plus a ``ts_submit`` wall
#   anchor (the queued span's t0 IS the submit instant), so
#   wall(t) = ts_submit + (t - spans[0].t0).
# Wall clocks across processes still skew; ``_attach_server`` corrects each
# replica record against its parent attempt span's request/response edge.

ENGINE_RECORD_EVENT = "request_spans"  # mirrors spans.SPAN_RECORD_EVENT


def record_wall_spans(rec: dict) -> list[dict[str, Any]]:
    """The record's spans with wall-clock ``t0``/``t1`` (copies)."""
    spans = [dict(s) for s in rec.get("spans", ())]
    if rec.get("clock") == "wall" or not spans:
        return spans
    anchor_wall = rec.get("ts_submit", rec.get("ts"))
    anchor = spans[0].get("t0")
    if anchor_wall is None or anchor is None:
        return spans
    for s in spans:
        for edge in ("t0", "t1"):
            if s.get(edge) is not None:
                s[edge] = anchor_wall + (s[edge] - anchor)
    return spans


def clock_offset(attempt: dict, w0: float, w1: float) -> float:
    """Symmetric-network clock offset mapping a replica's wall window
    ``[w0, w1]`` into the router's clock, anchored on the attempt span's
    request/response edges: the request left the router at ``attempt.t0``
    and the response landed at ``attempt.t1``, so under symmetric wire
    time the replica's clock is off by the mean edge disagreement."""
    t0, t1 = attempt.get("t0"), attempt.get("t1")
    if t0 is None:
        return 0.0
    if t1 is None:  # unfinished attempt: only the request edge anchors
        return t0 - w0
    return ((t0 - w0) + (t1 - w1)) / 2.0


def _node(name: str, t0, t1, **attrs: Any) -> dict[str, Any]:
    n: dict[str, Any] = {"name": name, "t0": t0, "t1": t1}
    n.update({k: v for k, v in attrs.items() if v is not None})
    n["children"] = []
    return n


def _attach_server(parent: dict, rec: dict, offset: float | None = None) -> dict:
    """Build a replica-side ``server`` node (queued/prefill/decode/retire
    children) under ``parent``, skew-corrected by ``offset`` (computed from
    the parent attempt's edges when not given)."""
    spans = record_wall_spans(rec)
    if not spans:
        return parent
    w0 = spans[0]["t0"]
    w1 = max(s["t1"] for s in spans if s.get("t1") is not None)
    if offset is None:
        offset = clock_offset(parent, w0, w1)
    server = _node(
        "server", w0 + offset, w1 + offset,
        process=rec.get("engine", "replica"),
        span_id=rec.get("span_id"),
        status=rec.get("status"),
        generated=rec.get("generated"),
        skew_s=round(offset, 6),
    )
    for s in spans:
        child = dict(s)
        child["t0"] = s["t0"] + offset
        if s.get("t1") is not None:
            child["t1"] = s["t1"] + offset
        child.setdefault("children", [])
        server["children"].append(child)
    parent["children"].append(server)
    return server


def assemble_trace(trace_id: str, records: Iterable[dict]) -> dict[str, Any]:
    """Merge every record stamped with ``trace_id`` into one span tree.

    Returns ``{"trace_id", "processes", "tree"}``; ``tree`` is None when no
    record matches. The router record (if present) forms the root with one
    child per attempt; each engine record attaches under the attempt whose
    span id it names as parent (skew-corrected), or under the root when its
    parent attempt never made it into the router record (an abandoned hedge
    loser can outlive the router's flush). Compile records attach to the
    node from the same source log (``load_trace`` stamps ``_log``)."""
    router_recs, engine_recs, compile_recs = [], [], []
    for rec in records:
        if rec.get("trace_id") != trace_id:
            continue
        ev = rec.get("event")
        if ev == ROUTER_RECORD_EVENT:
            router_recs.append(rec)
        elif ev == ENGINE_RECORD_EVENT:
            engine_recs.append(rec)
        elif ev == COMPILE_RECORD_EVENT:
            compile_recs.append(rec)
    processes = len(router_recs) + len(engine_recs)
    if processes == 0:
        return {"trace_id": trace_id, "processes": 0, "tree": None}

    by_log: dict[Any, dict] = {}
    if router_recs:
        rr = router_recs[0]
        spans = record_wall_spans(rr)
        root_span = spans[0] if spans else {"name": "request"}
        root = _node(
            "request", root_span.get("t0"), root_span.get("t1"),
            process="router", span_id=rr.get("span_id"),
            status=rr.get("status"), attempts=rr.get("attempts"),
        )
        attempts_by_id: dict[str, dict] = {}
        for s in spans[1:]:
            att = dict(s)
            att.setdefault("children", [])
            root["children"].append(att)
            if att.get("span_id"):
                attempts_by_id[att["span_id"]] = att
        by_log[rr.get("_log")] = root
        for rec in engine_recs:
            parent = attempts_by_id.get(rec.get("parent_span_id"), root)
            server = _attach_server(parent, rec)
            by_log[rec.get("_log")] = server
    else:
        # Replica-only view: synthesize a root spanning the engine records.
        first = engine_recs[0]
        spans = record_wall_spans(first)
        root = _node(
            "request", spans[0]["t0"] if spans else None,
            max((s["t1"] for s in spans if s.get("t1") is not None),
                default=None),
            process=first.get("engine", "replica"), synthetic=True,
        )
        for rec in engine_recs:
            server = _attach_server(root, rec, offset=0.0)
            by_log[rec.get("_log")] = server
    for rec in compile_recs:
        host = by_log.get(rec.get("_log"), root)
        t1 = rec.get("ts")
        dur = rec.get("duration_s") or 0.0
        host["children"].append(_node(
            "compile", None if t1 is None else t1 - dur, t1,
            event=rec.get("name"), duration_s=dur,
        ))
    return {"trace_id": trace_id, "processes": processes, "tree": root}


def critical_path(tree: dict | None) -> dict[str, Any]:
    """Where the client-observed time went, summing to the root span.

    ``retry_wasted_s`` is everything before the winning attempt started
    (failed attempts + backoff sleeps); ``wire_s`` is the winning attempt
    minus its server window (request + response network/parse time);
    queue/prefill/decode come from the winning replica's spans; ``other_s``
    is the explicit residue (span gaps, retirement → response write, router
    bookkeeping after the answer) so the parts always sum to ``total_s``.

    Collective phase (tensor-parallel serving): ``collective_bytes`` sums
    the per-span wire accounting the tp engine stamps on decode spans
    (exact analytic counts — parallel/collectives.py), and
    ``collective_s`` sums spans NAMED "collective" when a backend emits
    measured collective timings (profiling runs). ``collective_s`` is a
    sub-phase OF decode/prefill time, reported alongside the split, not
    added to the sum — the parts still total ``total_s`` without it.
    """
    empty = {
        "total_s": None, "retry_wasted_s": 0.0, "wire_s": 0.0,
        "queue_s": 0.0, "prefill_s": 0.0, "decode_s": 0.0, "other_s": 0.0,
        "collective_s": 0.0, "collective_bytes": 0,
    }
    if not tree or tree.get("t0") is None or tree.get("t1") is None:
        return empty
    total = tree["t1"] - tree["t0"]
    attempts = [c for c in tree.get("children", ()) if c.get("name") == "attempt"]
    # The winner is the attempt whose answer the client actually received
    # (``won``, stamped by the router) — an abandoned hedge loser can also
    # finish with outcome "ok" later, and its window describes the wrong
    # attempt. Records from before the marker fall back to last-ok.
    winner = None
    for att in attempts:
        if att.get("won"):
            winner = att
    if winner is None:
        for att in attempts:
            if att.get("outcome") == "ok":
                winner = att
    if winner is None:
        # No attempt spans (replica-only tree): treat the first server node
        # as the winner's window so queue/prefill/decode still split out.
        servers = [c for c in tree.get("children", ()) if c.get("name") == "server"]
        winner = servers[0] if servers else None
        if winner is None:
            return {**empty, "total_s": round(total, 6),
                    "other_s": round(total, 6)}
    retry_wasted = max(0.0, (winner.get("t0") or tree["t0"]) - tree["t0"])
    win_t1 = winner.get("t1") if winner.get("t1") is not None else tree["t1"]
    win_dur = max(0.0, win_t1 - winner["t0"])
    servers = [c for c in winner.get("children", ()) if c.get("name") == "server"]
    if winner.get("name") == "server":
        servers = [winner]
    queue = prefill = decode = collective = 0.0
    collective_bytes = 0
    wire = win_dur
    if servers:
        srv = servers[0]
        srv_dur = max(0.0, (srv.get("t1") or win_t1) - srv["t0"])
        wire = max(0.0, win_dur - srv_dur)
        for s in srv.get("children", ()):
            if isinstance(s.get("collective_bytes"), (int, float)):
                collective_bytes += int(s["collective_bytes"])
            if s.get("t1") is None or s.get("t0") is None:
                continue
            d = s["t1"] - s["t0"]
            if s.get("name") == "queued":
                queue += d
            elif s.get("name") == "prefill":
                prefill += d
            elif s.get("name") == "decode":
                decode += d
            elif s.get("name") == "collective":
                collective += d
    out = {
        "total_s": round(total, 6),
        "retry_wasted_s": round(retry_wasted, 6),
        "wire_s": round(wire, 6),
        "queue_s": round(queue, 6),
        "prefill_s": round(prefill, 6),
        "decode_s": round(decode, 6),
    }
    # Residue computed from the ROUNDED parts, so the published numbers sum
    # to the published total exactly — seven independently-rounded values
    # would drift by up to ~3.5e-6 otherwise. (collective_s is a sub-phase
    # of decode/prefill, deliberately outside the sum.)
    out["other_s"] = round(
        out["total_s"] - out["retry_wasted_s"] - out["wire_s"]
        - out["queue_s"] - out["prefill_s"] - out["decode_s"], 6,
    )
    out["collective_s"] = round(collective, 6)
    out["collective_bytes"] = collective_bytes
    return out


def load_trace(trace_id: str, paths: Iterable) -> dict[str, Any]:
    """Read span JSONL logs, resolve a (possibly unique-prefix) trace id,
    and assemble. Returns the ``assemble_trace`` document plus
    ``critical_path`` and the candidate ids when the prefix is ambiguous."""
    from edgemesh.utils.tracing import JsonlLogger

    records: list[dict] = []
    for p in paths:
        for rec in JsonlLogger(p).read():
            rec["_log"] = str(p)
            records.append(rec)
    ids = sorted({
        r["trace_id"] for r in records
        if isinstance(r.get("trace_id"), str)
    })
    matches = [t for t in ids if t == trace_id] or [
        t for t in ids if t.startswith(trace_id)
    ]
    if len(matches) != 1:
        return {"trace_id": trace_id, "processes": 0, "tree": None,
                "critical_path": critical_path(None),
                "candidates": matches}
    doc = assemble_trace(matches[0], records)
    doc["critical_path"] = critical_path(doc["tree"])
    return doc


# ---------------------------------------------------------------------------
# JAX compile telemetry
# ---------------------------------------------------------------------------


class CompileEventHook:
    """Counts jit compiles (and recompiles) into a registry and optionally
    logs them as trace-stamped ``compile`` span records.

    Fed ``jax.monitoring`` duration events; only ``/jax/core/compile/*``
    keys count. "Recompile" is per process and per event key: the first
    ``backend_compile`` is the expected warmup, every later one is a
    retrace/recompile worth noticing (shape churn, cache misses)."""

    #: the event key that means "XLA actually compiled a program"
    BACKEND_COMPILE = "backend_compile_duration"

    def __init__(self, registry=None, span_log=None):
        from edgemesh.obs.metrics import get_registry

        reg = registry if registry is not None else get_registry()
        self._compiles = reg.counter(
            "edgemesh_jax_compiles_total",
            "JAX compile-pipeline events observed, by event key", ("event",),
        )
        self._recompiles = reg.counter(
            "edgemesh_jax_recompiles_total",
            "backend_compile events beyond the first in this process "
            "(retraces / shape churn)",
        )
        self._duration = reg.histogram(
            "edgemesh_jax_compile_seconds",
            "JAX compile-pipeline event durations, by event key", ("event",),
        )
        self._cache_events = reg.counter(
            "edgemesh_compile_cache_events_total",
            "Persistent compilation-cache outcomes (hit = reused a shared "
            "cache entry; request = any cache lookup)", ("event",),
        )
        self._log = None
        if span_log is not None:
            from edgemesh.utils.tracing import JsonlLogger

            self._log = JsonlLogger(span_log)
        self._backend_compiles = 0
        self._lock = threading.Lock()

    def on_event(self, name: str, duration_s: float) -> None:
        if "/compile/" not in name:
            return
        key = name.rsplit("/", 1)[-1]
        self._compiles.labels(event=key).inc()
        self._duration.labels(event=key).observe(duration_s)
        if key == self.BACKEND_COMPILE:
            _mark_compile()
            with self._lock:
                self._backend_compiles += 1
                recompile = self._backend_compiles > 1
            if recompile:
                self._recompiles.inc()
        if self._log is not None and key == self.BACKEND_COMPILE:
            ctx = current_trace()
            self._log.log(
                COMPILE_RECORD_EVENT, name=key,
                duration_s=round(duration_s, 6),
                trace_id=ctx.trace_id if ctx is not None else None,
                parent_span_id=ctx.span_id if ctx is not None else None,
            )

    def on_cache_event(self, kind: str) -> None:
        """Persistent-compilation-cache outcome (``kind`` in hit/request):
        counted per registry so a warm-started replica's /metrics proves
        its compiles were disk-cache hits, not fresh XLA work."""
        self._cache_events.labels(event=kind).inc()


# One process-wide dispatcher: jax.monitoring listeners cannot be removed
# individually, so jax sees exactly one listener and hooks attach/detach
# from this list (engines detach on close()).
_hook_lock = threading.Lock()
_hooks: list[CompileEventHook] = []
_listener_registered = False

# Process-wide "a backend compile just happened" marker: the replica load
# digest (serve/rest.py /loadz) flags a recent compile so the fleet's
# telemetry balancer can treat the replica as warming up, not degraded.
_last_compile_monotonic: float | None = None

# Process-wide persistent-compilation-cache tally (jax.monitoring events —
# see utils/compat.register_cache_event_listener): what the load digest's
# ``compile_cache`` block and the autoscaler's warm-start proof read.
_cache_hits = 0  # guarded by: _hook_lock
_cache_requests = 0  # guarded by: _hook_lock

#: monitoring event-name suffix → the bounded label the counter uses
_CACHE_EVENT_KEYS = {"cache_hits": "hit",
                     "compile_requests_use_cache": "request"}


def _mark_compile() -> None:
    global _last_compile_monotonic
    with _hook_lock:
        _last_compile_monotonic = time.monotonic()


def seconds_since_last_compile() -> float | None:
    """Seconds since the last observed backend compile in this process
    (``None`` before the first one, or when the jax monitoring shim is
    unavailable)."""
    with _hook_lock:
        ts = _last_compile_monotonic
    return None if ts is None else time.monotonic() - ts


def _dispatch(name: str, duration_s: float) -> None:
    for hook in list(_hooks):
        try:
            hook.on_event(name, duration_s)
        except Exception:  # telemetry must never break a compile
            pass


def _dispatch_cache_event(name: str) -> None:
    global _cache_hits, _cache_requests
    if "/compilation_cache/" not in name:
        return
    kind = _CACHE_EVENT_KEYS.get(name.rsplit("/", 1)[-1])
    if kind is None:
        return
    with _hook_lock:
        if kind == "hit":
            _cache_hits += 1
        else:
            _cache_requests += 1
    for hook in list(_hooks):
        try:
            hook.on_cache_event(kind)
        except Exception:  # telemetry must never break a compile
            pass


def compile_cache_state() -> dict:
    """The process's persistent-compilation-cache block for the load digest
    (serve/rest.py ``/loadz``): whether a shared cache directory is
    configured (``utils.compat.enable_compilation_cache`` /
    ``--compile-cache-dir``) and the live hit/miss tally. Misses are
    derived (requests − hits) so the two monitoring event streams cannot
    drift apart in the report. Cheap: two config reads + one lock."""
    cache_dir = None
    try:
        import jax

        cache_dir = getattr(jax.config, "jax_compilation_cache_dir", None)
    except Exception:  # telemetry must survive a jax-less router process
        pass
    with _hook_lock:
        hits, requests = _cache_hits, _cache_requests
    return {
        "enabled": bool(cache_dir),
        "dir": cache_dir,
        "hits": hits,
        "misses": max(0, requests - hits),
    }


def install_compile_hook(registry=None, span_log=None) -> CompileEventHook:
    """Attach a :class:`CompileEventHook`. The first call registers the one
    process-wide ``jax.monitoring`` listener (via the ``utils.compat`` drift
    shim — a jax without monitoring hooks degrades to a hook that only
    counts what ``on_event`` is fed directly). Detach with
    :func:`uninstall_compile_hook` when the owning engine closes."""
    global _listener_registered
    hook = CompileEventHook(registry=registry, span_log=span_log)
    with _hook_lock:
        _hooks.append(hook)
        if not _listener_registered:
            from edgemesh.utils.compat import (
                register_cache_event_listener,
                register_compile_event_listener,
            )

            if register_compile_event_listener(_dispatch):
                # Cache-outcome events ride the same one-listener policy;
                # a jax without plain-event hooks just reports zero hits.
                register_cache_event_listener(_dispatch_cache_event)
                _listener_registered = True
    return hook


def uninstall_compile_hook(hook: CompileEventHook) -> None:
    with _hook_lock:
        if hook in _hooks:
            _hooks.remove(hook)
