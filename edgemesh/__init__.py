"""edgemesh — TPU-native distributed multi-agent LLM inference framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
``parthabp55/LLM-for-Distributed-Egde-Devices`` (see SURVEY.md):

- multi-agent LLM ensembling (QA agents + refiner) — ``edgemesh.agents``
- bf16/fp16/int8 inference with Pallas int8 kernels — ``edgemesh.ops``
- mesh distribution (DP/TP/PP/SP) over ICI/DCN collectives — ``edgemesh.parallel``
- decoder-only model families (Llama / GPT-NeoX(Pythia) / Phi-2) — ``edgemesh.models``
- eight-metric evaluation harness over Natural Questions — ``edgemesh.eval``
- serving front door + CLI — ``edgemesh.serve``, ``edgemesh.cli``

Where the reference moved tensors between Jetson edge devices over
gRPC/protobuf (reference ``Code/gRPC/server.py``), edgemesh maps each "edge
node" to a TPU chip on a pod slice and lets XLA emit ICI/DCN collectives from
``jax.sharding`` annotations. Heavy top-level imports are deferred: importing
``edgemesh`` itself does not import jax.
"""

__version__ = "0.1.0"

from edgemesh.config import (  # noqa: F401
    AgentSpec,
    EdgeMeshConfig,
    EvalSpec,
    MeshSpec,
    ModelSpec,
    SamplingParams,
    load_config,
)
