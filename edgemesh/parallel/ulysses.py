"""Ulysses-style sequence parallelism: all-to-all head↔sequence exchange.

The second of the two standard sequence/context-parallel schemes (DeepSpeed
Ulysses; the other — ring attention — is edgemesh/parallel/ring_attention.py).
Where the ring rotates K/V blocks ``sp`` hops around the mesh and accumulates
an online softmax, Ulysses performs ONE ``lax.all_to_all`` that re-shards
activations from sequence-split [b, s/sp, nh, hd] to head-split
[b, s, nh/sp, hd], runs ordinary full-sequence attention on the local head
group, and all-to-alls back. Communication volume is O(s·h/sp) per device
versus the ring's sp hops of O(s/sp·h_kv) — Ulysses wins when heads divide
cleanly and the interconnect favors fewer, larger transfers; the ring wins
at very long sequences (K/V blocks stream through VMEM-sized working sets)
and when num_heads < sp. Both are exact: pinned against the dense op in
tests/test_ulysses.py.

GQA note: the K/V head exchange needs ``kv_heads % sp == 0``; otherwise K/V
fall back to an all-gather over the sequence axis (queries still split their
heads — the common small-GQA regime where replicating the few KV heads is
cheaper than padding them).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from edgemesh.ops.attention import LayerKV, attend
from edgemesh.utils.compat import shard_map


def _full_seq_attend(
    q: jnp.ndarray,  # [b, s, nh_local, hd]
    k: jnp.ndarray,  # [b, s, kh_local, hd]
    v: jnp.ndarray,
    q_pos: jnp.ndarray,  # [b, s] global positions
    k_valid: jnp.ndarray,  # [b, s]
    scale: float,
    sliding_window: int = 0,
    soft_cap: float = 0.0,
) -> jnp.ndarray:
    """Full-sequence causal attention on the local head group — the dense op
    (ops/attention.attend) applied to the gathered arrays, window/soft-cap
    dials included.

    Contract: after the all-to-all the local K/V hold the FULL sequence in
    global slot order, and the sequence-split layout puts position ``j`` in
    slot ``j`` (positions are ``block_start + arange`` per shard — true for
    every consumer: the 4D SPMD program and the top-level wrapper below), so
    attend's slot-index causal mask is exactly the position mask."""
    return attend(q, LayerKV(k, v), q_pos, k_valid, scale=scale,
                  sliding_window=sliding_window, soft_cap=soft_cap)


def ulysses_attend_block(
    q_blk: jnp.ndarray,  # [b, s/sp, num_heads, head_dim] local seq block
    k_blk: jnp.ndarray,  # [b, s/sp, kv_heads, head_dim]
    v_blk: jnp.ndarray,
    pos_blk: jnp.ndarray,  # [b, s/sp] global positions of the local block
    valid_blk: jnp.ndarray,  # [b, s/sp]
    *,
    axis: str = "sp",
    sp: int,
    scale: float | None = None,
    sliding_window: int = 0,
    soft_cap: float = 0.0,
) -> jnp.ndarray:
    """Per-device body — callable inside ANY enclosing shard_map carrying the
    ``axis`` mesh axis (drop-in alternative to ring_attend_block; the 4D SPMD
    program selects between them via ``sp_impl``). Window/soft-cap semantics
    follow ops/attention.attend (Mistral windows, Gemma-2 caps)."""
    b, sq, num_heads, head_dim = q_blk.shape
    kv_heads = k_blk.shape[2]
    scale = scale if scale is not None else head_dim**-0.5
    if sp == 1:
        return _full_seq_attend(q_blk, k_blk, v_blk, pos_blk, valid_blk, scale,
                                sliding_window, soft_cap)
    if num_heads % sp:
        raise ValueError(f"ulysses needs num_heads {num_heads} % sp {sp} == 0")

    # seq-split → head-split: send each head group to its owner; receive the
    # full sequence (sender order == global block order) for the local group.
    q_g = lax.all_to_all(q_blk, axis, split_axis=2, concat_axis=1, tiled=True)
    if kv_heads % sp == 0:
        # Contiguous alignment: device d's q heads [d·nh/sp, (d+1)·nh/sp)
        # map onto exactly its kv heads [d·kh/sp, (d+1)·kh/sp) (global head
        # order is grouped by kv head), so local grouped pairing holds.
        k_g = lax.all_to_all(k_blk, axis, split_axis=2, concat_axis=1, tiled=True)
        v_g = lax.all_to_all(v_blk, axis, split_axis=2, concat_axis=1, tiled=True)
    else:  # small-GQA fallback: replicate the few KV heads across the axis
        k_all = lax.all_gather(k_blk, axis, axis=1, tiled=True)  # [b, s, kh, hd]
        v_all = lax.all_gather(v_blk, axis, axis=1, tiled=True)
        # Select each LOCAL q head's kv head from the full set (the local
        # block of q heads need not align with a kv-head boundary here).
        nh_local = num_heads // sp
        g_global = num_heads // kv_heads
        head0 = lax.axis_index(axis) * nh_local
        kv_idx = (head0 + jnp.arange(nh_local)) // g_global  # [nh_local]
        k_g = jnp.take(k_all, kv_idx, axis=2)  # [b, s, nh_local, hd] (g=1)
        v_g = jnp.take(v_all, kv_idx, axis=2)
    pos_g = lax.all_gather(pos_blk, axis, axis=1, tiled=True)  # [b, s]
    val_g = lax.all_gather(valid_blk, axis, axis=1, tiled=True)

    out = _full_seq_attend(q_g, k_g, v_g, pos_g, val_g, scale,
                           sliding_window, soft_cap)
    # head-split → seq-split: the inverse exchange.
    return lax.all_to_all(out, axis, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jnp.ndarray,  # [b, seq, num_heads, head_dim] — seq sharded over "sp"
    k: jnp.ndarray,  # [b, seq, kv_heads, head_dim]
    v: jnp.ndarray,
    positions: jnp.ndarray,  # [b, seq] global positions
    valid: jnp.ndarray,  # [b, seq]
    mesh: Mesh,
    scale: float | None = None,
    sliding_window: int = 0,
    soft_cap: float = 0.0,
) -> jnp.ndarray:
    """Exact causal attention with the sequence axis sharded over ``sp`` —
    same contract as ring_attention.ring_attention."""
    sp = mesh.shape["sp"]

    def local_fn(q_blk, k_blk, v_blk, pos_blk, valid_blk):
        return ulysses_attend_block(
            q_blk, k_blk, v_blk, pos_blk, valid_blk, axis="sp", sp=sp, scale=scale,
            sliding_window=sliding_window, soft_cap=soft_cap,
        )

    seq_spec = P(None, "sp")
    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(
            P(None, "sp", None, None),
            P(None, "sp", None, None),
            P(None, "sp", None, None),
            seq_spec,
            seq_spec,
        ),
        out_specs=P(None, "sp", None, None),
    )(q, k, v, positions, valid)
