"""Device mesh construction and multi-host initialization.

Replaces the reference's static-IP cluster map (``Code/gRPC/README.md:9-14``:
J1=192.168.1.100, J3=192.168.1.101, hand-configured netplan) with
``jax.sharding.Mesh`` axis algebra. Axis order puts ``tp`` innermost so
tensor-parallel collectives ride neighboring ICI links; ``dp`` is outermost so
data-parallel traffic (none at inference) would cross DCN last.

Axes:
- ``dp``: data parallel (batch)
- ``pp``: pipeline stages (layer split — the TPU analog of the reference's
  intended cross-Jetson model split, ``server.py:1``)
- ``sp``: sequence/context parallel (ring attention)
- ``ep``: expert parallel (MoE expert dim, ops/moe.py — the device-level
  realization of the reference's planned Expert Models sheet, SURVEY.md §2.3)
- ``tp``: tensor parallel (attention heads / MLP columns)
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "pp", "sp", "ep", "tp")


def build_mesh(
    dp: int = 1,
    pp: int = 1,
    sp: int = 1,
    tp: int = 1,
    ep: int = 1,
    devices: list | None = None,
) -> Mesh:
    """Build a 5-axis mesh over ``dp*pp*sp*ep*tp`` devices (defaults: all)."""
    devices = devices if devices is not None else jax.devices()
    need = dp * pp * sp * ep * tp
    if need > len(devices):
        raise ValueError(
            f"mesh {dp}x{pp}x{sp}x{ep}x{tp} needs {need} devices, have {len(devices)}"
        )
    arr = np.array(devices[:need]).reshape(dp, pp, sp, ep, tp)
    return Mesh(arr, AXES)


def auto_mesh(tp: int | None = None, devices: list | None = None) -> Mesh:
    """All devices on the ``tp`` axis by default — the right shape for
    single-model inference on one slice."""
    devices = devices if devices is not None else jax.devices()
    tp = tp or len(devices)
    return build_mesh(tp=tp, devices=devices)


def submeshes(n_groups: int, devices: list | None = None, tp: int | None = None) -> list[Mesh]:
    """Partition the slice into ``n_groups`` disjoint single-axis (tp) meshes —
    one per ensemble agent, so QA agents run CONCURRENTLY on their own chips
    (fixing the reference's sequential agent calls, combiner_fp.py:436-439)."""
    devices = devices if devices is not None else jax.devices()
    if n_groups <= 0:
        raise ValueError("n_groups must be positive")
    per = len(devices) // n_groups
    if per == 0:
        raise ValueError(f"{n_groups} groups need at least {n_groups} devices, have {len(devices)}")
    tp = tp or per
    if tp > per:
        raise ValueError(
            f"tp={tp} exceeds the {per}-device share of each of {n_groups} groups; "
            f"submeshes must be disjoint"
        )
    return [
        build_mesh(tp=tp, devices=devices[i * per : i * per + tp])
        for i in range(n_groups)
    ]


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host (DCN-spanning) initialization via ``jax.distributed``.

    The reference's analog is the hand-run server/client pair on each Jetson
    (``gRPC/README.md:31-44``); here one call per host wires the DCN fabric
    and jax.devices() becomes the global device list. No-ops when
    single-process (e.g. env vars absent)."""
    if coordinator_address is None:
        coordinator_address = os.environ.get("EDGEMESH_COORDINATOR")
    if coordinator_address is None:
        return  # single-host
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
