"""Quantized, overlappable collectives for the tensor-parallel serving stack.

The tp engine's decode step pays two full-precision ``lax.psum``s per layer
(attention-output and MLP down projections, parallel/tp_infer.py) — at tp8
that is the dominant non-matmul cost of every token, and none of it shrinks
on the wire. EQuARX (PAPERS.md: arXiv 2506.17615) shows an all-reduce can
run its ring in int8/fp8 with per-chunk scales at negligible quality cost;
:func:`qpsum` is that design on the shard_map shims:

    quantize → ppermute ring reduce-scatter (dequant-accumulate per hop)
             → quantized ring all-gather → dequantize

Every hop moves 1-byte elements instead of 2-byte bf16 — half the wire
bytes — and the explicit ring decomposes the all-reduce into ``world - 1``
independent ppermute steps XLA can overlap with unrelated compute (the
chunked-projection schedule in tp_infer exploits exactly that).

Contracts:
- ``qpsum`` is shard_map-body code: call it where ``lax.psum(x, axis)``
  is legal. It is registered with the EM4xx sharding rules
  (analysis/sharding.py ``_COLLECTIVES``/``_REDUCERS``) so an unbound
  axis or an unreduced-contraction hole is a lint error, and the
  ``collectives`` entry in ``SHARDING_CONTRACTS`` traces it under
  tp2/tp8/dp2xtp4 AbstractMesh layouts with no devices.
- ``dtype="bf16"`` and world size 1 fall back to plain ``lax.psum``
  (bit-exact, zero new numerics); so does a trailing dim the world size
  does not divide (ring chunking needs equal chunks).
- All shards produce bit-identical results (the final all-gather
  round-trips every chunk — including the locally-reduced one — through
  the same quantizer), so ``out_specs`` replication claims stay honest.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from edgemesh.utils.compat import axis_size

#: The serving knob's vocabulary (threaded TPInferenceEngine → engine
#: config → CLI): "psum" is the legacy full-precision join, "qpsum"
#: quantizes the wire, "qpsum_overlap" additionally chunks the projection
#: so collective i rides the ring while chunk i+1's matmul computes.
COLLECTIVE_MODES = ("psum", "qpsum", "qpsum_overlap")

#: Wire dtypes qpsum can ship. "bf16" means "don't quantize" — the plain
#: psum fallback, kept in the set so the ablation sweeps one knob.
COMM_DTYPES = ("int8", "fp8", "bf16")

_INT8_MAX = 127.0
_FP8_MAX = 448.0  # float8_e4m3fn finite max
_FP8 = getattr(jnp, "float8_e4m3fn", None)


def validate_collective_mode(mode: str, dtype: str) -> None:
    """One vocabulary check for every layer that threads the knob
    (TPInferenceEngine, ContinuousEngine, serve_rest, CLI)."""
    if mode not in COLLECTIVE_MODES:
        raise ValueError(
            f"unknown collective_mode {mode!r} (choose from {COLLECTIVE_MODES})"
        )
    if dtype not in COMM_DTYPES:
        raise ValueError(
            f"unknown comm dtype {dtype!r} (choose from {COMM_DTYPES})"
        )
    if dtype == "fp8" and _FP8 is None:
        raise ValueError(
            "comm dtype 'fp8' needs a jax with jnp.float8_e4m3fn; use 'int8'"
        )


def _quantize(x: jnp.ndarray, dtype: str) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-row symmetric quantization over the trailing dim: ``x`` is a ring
    chunk ``[..., c]``; the scale is one float32 per leading row — fine
    enough that one outlier channel only poisons its own row, coarse enough
    that the wire overhead is c:1. Near-zero chunks clamp the scale (1e-8)
    so zeros dequantize to exact zeros."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    if dtype == "int8":
        scale = jnp.maximum(absmax / _INT8_MAX, 1e-8)
        q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    else:  # fp8 e4m3: scale to the format's finite range, rounding is free
        scale = jnp.maximum(absmax / _FP8_MAX, 1e-8)
        q = (xf / scale).astype(_FP8)
    return q, scale


def _dequantize(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    return q.astype(jnp.float32) * scale


def qpsum(x: jnp.ndarray, axis_name: str, *, dtype: str = "int8") -> jnp.ndarray:
    """Quantized all-reduce over a shard_map mesh axis.

    Drop-in for ``lax.psum(x, axis_name)`` with the wire in ``dtype``
    (int8 | fp8 | bf16-passthrough). Accumulation is float32 on-chip; only
    the inter-chip hops are narrow. Result dtype matches ``x``.
    """
    if dtype == "bf16":
        return lax.psum(x, axis_name)
    if dtype == "fp8" and _FP8 is None:
        raise ValueError("fp8 collectives need jnp.float8_e4m3fn")
    world = axis_size(axis_name)
    h = x.shape[-1]
    if world == 1 or h % world or h == 0:
        # No ring to run (or chunks would be ragged): full-precision join.
        return lax.psum(x, axis_name)

    lead = x.shape[:-1]
    c = h // world
    # chunk-major view: chunks[j] is the j-th trailing-dim slice [*lead, c]
    chunks = jnp.moveaxis(x.reshape(*lead, world, c), -2, 0)
    idx = lax.axis_index(axis_name)
    right = [(i, (i + 1) % world) for i in range(world)]

    # Ring reduce-scatter: at step t device i ships its running partial for
    # chunk (i - t) mod world one hop right and folds its own copy of chunk
    # (i - t - 1) mod world into what arrives — after world-1 hops device i
    # holds chunk i fully reduced. Each hop re-quantizes the partial (the
    # EQuARX trade: error grows ~linearly in hops, wire bytes halve).
    acc = jnp.take(chunks, (idx - 1) % world, axis=0).astype(jnp.float32)
    for t in range(1, world):
        q, scale = _quantize(acc, dtype)
        q = lax.ppermute(q, axis_name, right)
        scale = lax.ppermute(scale, axis_name, right)
        local = jnp.take(chunks, (idx - t - 1) % world, axis=0)
        acc = local.astype(jnp.float32) + _dequantize(q, scale)

    # Quantized all-gather: every shard re-reads every chunk — including its
    # own — through the same quantizer, so all shards reassemble the SAME
    # bits (out_specs replication stays exact).
    q, scale = _quantize(acc, dtype)
    q_all = lax.all_gather(q, axis_name)  # [world, *lead, c]
    s_all = lax.all_gather(scale, axis_name)
    full = _dequantize(q_all, s_all)
    return jnp.moveaxis(full, 0, -2).reshape(*lead, h).astype(x.dtype)


# ---------------------------------------------------------------------------
# Wire accounting — the analytic byte counts behind
# edgemesh_collective_bytes_total{op,dtype} (serve/continuous.py) and the
# bench's wire-savings columns. Shapes are static at trace time, so the
# count is exact for what the collective ships, not an estimate.
# ---------------------------------------------------------------------------

_WIRE_ELEM_BYTES = {"bf16": 2, "int8": 1, "fp8": 1}


def collective_wire_bytes(
    shape: tuple[int, ...], world: int, mode: str, dtype: str = "int8"
) -> int:
    """Per-device wire bytes for ONE all-reduce of a ``shape`` array over a
    ``world``-sized axis.

    Both the plain psum (ring all-reduce lowering) and qpsum move each
    element ``2*(world-1)/world`` times; qpsum ships 1-byte elements plus a
    float32 per-row scale per hop, psum ships the activation dtype (bf16).
    """
    if world <= 1:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    if n == 0:
        return 0
    hops = 2 * (world - 1)  # reduce-scatter + all-gather, per device
    if mode == "psum" or dtype == "bf16" or shape[-1] % world:
        return n * _WIRE_ELEM_BYTES["bf16"] * hops // world
    chunk_elems = n // world
    rows = chunk_elems // (shape[-1] // world)  # leading rows per chunk
    payload = chunk_elems * _WIRE_ELEM_BYTES[dtype] + rows * 4  # + scales
    return payload * hops
