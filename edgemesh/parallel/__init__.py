"""Distribution layer: device mesh, sharding rules, pipeline, collectives.

This package is the TPU-native replacement for the reference's entire
``Code/gRPC`` communication fabric (SURVEY.md §2.3, §3.4): where the reference
wires Jetson edge nodes together with gRPC/protobuf over static-IP TCP
(``server.py:16``, ``client.py:8``, ``gRPC/README.md:9-14``), edgemesh maps
each "edge node" to a TPU chip in a ``jax.sharding.Mesh`` and lets XLA emit
ICI/DCN collectives from sharding annotations — no serialization, no sockets
in the data plane.
"""

from edgemesh.parallel.mesh import AXES, build_mesh, submeshes  # noqa: F401
from edgemesh.parallel.sharding import (  # noqa: F401
    cache_pspecs,
    param_pspecs,
    shard_cache,
    shard_params,
)
