"""Layer-split pipeline parallelism over the ``pp`` mesh axis.

This is the TPU-native realization of the reference's *intended* cross-device
model split: its gRPC fabric was built to "deploy models across Jetson and
high-power systems" (``Code/gRPC/server.py:1``, review-1 slide 9) but the
checked-in RPC never carries activations (SURVEY.md §2.3 "Device-level
distribution"). Here the split is real: layers are divided into ``pp``
contiguous stages, each stage lives on its own chip(s), and stage-to-stage
activation transfers are ``lax.ppermute`` hops over ICI emitted inside one
``jax.shard_map`` program — the BASELINE.json configs[2] shape
("layer-split pipeline across 4 nodes, gRPC → ICI send/recv").

Schedule: GPipe-style fill-drain. A batch is cut into ``num_micro``
microbatches; step ``t`` has stage ``s`` working on microbatch ``t - s``;
total ``num_micro + pp - 1`` steps. Each stage keeps the KV-cache block for
its own layers only, so cache HBM is also split ``pp``-ways.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from edgemesh.models.transformer import (
    KVCache,
    ModelConfig,
    _layer_fn,
    embed_tokens,
    layer_scan_alt_windows,
    lm_head_logits,
)
from edgemesh.ops.attention import LayerKV
from edgemesh.utils.compat import pcast, shard_map
from edgemesh.utils.platform import on_tpu

Params = dict[str, Any]


def shard_params_pipelined(params: Params, cfg: ModelConfig, mesh: Mesh) -> Params:
    """Place stacked layer params with the LAYER axis split over ``pp``
    (embedding / final norm / lm_head replicated)."""
    pp = mesh.shape["pp"]
    if cfg.num_layers % pp != 0:
        raise ValueError(f"num_layers {cfg.num_layers} not divisible by pp={pp}")

    def place(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    out: Params = {
        "embed": jax.tree.map(lambda x: place(x, P()), params["embed"]),
        "final_norm": jax.tree.map(lambda x: place(x, P()), params["final_norm"]),
        "layers": jax.tree.map(lambda x: place(x, P("pp")), params["layers"]),
    }
    if "pos_embed" in params:
        out["pos_embed"] = jax.tree.map(lambda x: place(x, P()), params["pos_embed"])
    if "lm_head" in params:
        out["lm_head"] = jax.tree.map(lambda x: place(x, P()), params["lm_head"])
    return out


def init_pipelined_cache(cfg: ModelConfig, batch: int, max_seq: int, mesh: Mesh) -> KVCache:
    shape = (cfg.num_layers, batch, max_seq, cfg.num_kv_heads, cfg.head_size)
    kv_sharding = NamedSharding(mesh, P("pp"))
    return KVCache(
        k=jax.device_put(jnp.zeros(shape, cfg.activation_dtype), kv_sharding),
        v=jax.device_put(jnp.zeros(shape, cfg.activation_dtype), kv_sharding),
        lengths=jax.device_put(jnp.zeros((batch,), jnp.int32), NamedSharding(mesh, P())),
    )


def _stage_pipeline_fn(
    cfg: ModelConfig,
    pp: int,
    num_micro: int,
    mb_size: int,
    is_decode: bool,
):
    """The per-device body run under shard_map over the ``pp`` axis."""

    def fn(stage_layers, k_blk, v_blk, x_mb, positions_mb, kv_valid_mb, lengths_mb):
        # stage_layers leaves: [L/pp, ...] — this stage's contiguous block.
        # k_blk/v_blk: [L/pp, B, max_seq, kh, hd].
        # x_mb: [num_micro, mb_size, S, H] (replicated input, embedded).
        stage = lax.axis_index("pp")
        seq_len = x_mb.shape[2]
        steps = num_micro + pp - 1

        def one_step(carry, t):
            k_blk, v_blk, recv, outputs = carry
            mb_idx = t - stage
            active = (mb_idx >= 0) & (mb_idx < num_micro)
            idx = jnp.clip(mb_idx, 0, num_micro - 1)

            x_in = jnp.where(stage == 0, x_mb[idx], recv)
            pos = positions_mb[idx]
            kvv = kv_valid_mb[idx]
            lens = lengths_mb[idx]
            row0 = idx * mb_size

            k_rows = lax.dynamic_slice_in_dim(k_blk, row0, mb_size, axis=1)
            v_rows = lax.dynamic_slice_in_dim(v_blk, row0, mb_size, axis=1)

            def layer_body(layer_cfg, h, scanned):
                layer, k_l, v_l = scanned
                h, new_kv, _ = _layer_fn(
                    layer_cfg, h, layer, LayerKV(k_l, v_l), pos, kvv, lens, is_decode
                )
                return h, (new_kv.k, new_kv.v)

            h, (nk, nv) = layer_scan_alt_windows(
                cfg, layer_body, x_in, (stage_layers, k_rows, v_rows)
            )

            # Only commit cache rows for genuinely active steps.
            nk = jnp.where(active, nk, k_rows)
            nv = jnp.where(active, nv, v_rows)
            k_blk = lax.dynamic_update_slice_in_dim(k_blk, nk, row0, axis=1)
            v_blk = lax.dynamic_update_slice_in_dim(v_blk, nv, row0, axis=1)

            # Hand activations to the next stage (non-cyclic: stage 0 gets zeros,
            # which it never reads — it consumes x_mb directly).
            send = lax.ppermute(h, "pp", [(i, i + 1) for i in range(pp - 1)])

            is_last = stage == pp - 1
            outputs = jnp.where(
                is_last & active, outputs.at[idx].set(h), outputs
            )
            return (k_blk, v_blk, send, outputs), None

        # The recv/outputs carries BECOME device-varying after the first step
        # (ppermute / stage-dependent writes); pcast the zero inits to the
        # same varying-manual-axes type so the scan carry types line up.
        init = (
            k_blk,
            v_blk,
            pcast(
                jnp.zeros((mb_size, seq_len, cfg.hidden_size), x_mb.dtype),
                "pp", to="varying",
            ),
            pcast(jnp.zeros_like(x_mb), "pp", to="varying"),
        )
        (k_blk, v_blk, _, outputs), _ = lax.scan(
            one_step, init, jnp.arange(steps)
        )
        # Only the last stage holds real outputs; psum replicates them to all.
        outputs = lax.psum(outputs, "pp")
        return k_blk, v_blk, outputs

    return fn


def make_pipeline_mapped(
    cfg: ModelConfig,
    mesh: Mesh,
    num_micro: int,
    mb_size: int,
    is_decode: bool,
):
    """The engine's core shard_map program: GPipe fill-drain over the ``pp``
    axis (layer blocks, per-stage KV, ppermute activation hops). Exposed at
    module level so the sharding dryrun (analysis/sharding.py
    SHARDING_CONTRACTS) traces the EXACT production spec set under an
    ``AbstractMesh`` — no devices required."""
    fn = _stage_pipeline_fn(cfg, mesh.shape["pp"], num_micro, mb_size, is_decode)
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(P("pp"), P("pp"), P("pp"), P(), P(), P(), P()),
        out_specs=(P("pp"), P("pp"), P()),
        # pallas_call outputs don't carry varying-manual-axes types, so
        # the vma checker rejects any stage body that runs the flash
        # kernel; the pcast inits degrade to no-ops with it off.
        check_vma=cfg.attention_impl != "flash",
    )


class PipelineEngine:
    """Pipelined model executor: prefill / decode / full-sequence forward.

    Cache note: unlike the single-chip path, each stage's HBM holds only the
    KV blocks of its own layers — the ``pp``-way analog of kv-head sharding.
    """

    def __init__(
        self,
        cfg: ModelConfig,
        params: Params,
        mesh: Mesh,
        num_micro: int = 4,
        attention_impl: str | None = None,
    ):
        pp = mesh.shape["pp"]
        if pp < 2:
            raise ValueError("PipelineEngine needs a pp axis of size >= 2")
        if cfg.alt_sliding_window and cfg.sliding_window > 0:
            # Each stage's pair scan needs to START on an even global layer
            # and hold whole pairs: layers-per-stage must be even. (An
            # indivisible num_layers/pp falls through to the divisibility
            # error below — the accurate diagnostic.)
            if cfg.num_layers % pp == 0 and (cfg.num_layers // pp) % 2:
                raise ValueError(
                    "alternating sliding windows need an even number of "
                    f"layers per stage (num_layers {cfg.num_layers} / pp {pp})"
                )
        # The stage body runs per-shard under shard_map, so Pallas kernels see
        # local arrays and apply directly — default to the flash kernel on
        # real TPU; pass "flash" explicitly to run it in interpret mode on a
        # CPU mesh, or "xla" to force the einsum attention.
        if attention_impl is None:
            attention_impl = (
                "flash" if on_tpu() else cfg.attention_impl
            )
        cfg = cfg.replace(attention_impl=attention_impl)
        self.cfg = cfg
        self.mesh = mesh
        self.pp = pp
        self.num_micro = num_micro
        self.params = shard_params_pipelined(params, cfg, mesh)
        # jit closures take params as an ARGUMENT (self only supplies statics);
        # making the method's `self` a static argnum would try to hash arrays.
        self._prefill_jit = jax.jit(self._prefill_impl)
        self._decode_jit = jax.jit(self._decode_impl)

    def init_cache(self, batch: int, max_seq: int) -> KVCache:
        return init_pipelined_cache(self.cfg, batch, max_seq, self.mesh)

    def _run_layers(
        self,
        params: Params,
        x: jnp.ndarray,  # [B, S, H] embedded
        positions: jnp.ndarray,  # [B, S]
        kv_valid: jnp.ndarray,  # [B, max_seq]
        cache: KVCache,
        is_decode: bool,
        num_micro: int,
    ) -> tuple[jnp.ndarray, KVCache]:
        cfg = self.cfg
        batch = x.shape[0]
        if batch % num_micro != 0:
            raise ValueError(f"batch {batch} not divisible by num_micro {num_micro}")
        mbs = batch // num_micro

        def to_mb(a):  # [B, ...] -> [M, mbs, ...]
            return a.reshape(num_micro, mbs, *a.shape[1:])

        mapped = make_pipeline_mapped(cfg, self.mesh, num_micro, mbs, is_decode)
        k, v, out_mb = mapped(
            params["layers"], cache.k, cache.v,
            to_mb(x), to_mb(positions), to_mb(kv_valid), to_mb(cache.lengths),
        )
        out = out_mb.reshape(batch, *out_mb.shape[2:])
        return out, KVCache(k, v, cache.lengths)

    def _logits(self, params: Params, hidden: jnp.ndarray) -> jnp.ndarray:
        return lm_head_logits(self.cfg, params, hidden)

    def _prefill_impl(self, params: Params, tokens: jnp.ndarray, lengths: jnp.ndarray, cache: KVCache):
        cfg = self.cfg
        b, s = tokens.shape
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        positions = jnp.minimum(positions, (lengths - 1)[:, None])
        max_seq = cache.k.shape[2]
        kv_valid = jnp.arange(max_seq)[None, :] < lengths[:, None]
        x = embed_tokens(cfg, params, tokens, positions)
        hidden, cache = self._run_layers(
            params, x, positions, kv_valid, cache, is_decode=False, num_micro=self.num_micro
        )
        logits = self._logits(params, hidden[jnp.arange(b), lengths - 1][:, None])[:, 0]
        return logits, KVCache(cache.k, cache.v, lengths)

    def _decode_impl(self, params: Params, tokens: jnp.ndarray, cache: KVCache):
        cfg = self.cfg
        max_seq = cache.k.shape[2]
        positions = cache.lengths[:, None]
        kv_valid = jnp.arange(max_seq)[None, :] <= cache.lengths[:, None]
        x = embed_tokens(cfg, params, tokens[:, None], positions)
        hidden, cache = self._run_layers(
            params, x, positions, kv_valid, cache, is_decode=True, num_micro=1
        )
        logits = self._logits(params, hidden)[:, 0]
        return logits, KVCache(cache.k, cache.v, cache.lengths + 1)

    def prefill(self, tokens: jnp.ndarray, lengths: jnp.ndarray, cache: KVCache):
        return self._prefill_jit(self.params, tokens, lengths, cache)

    def decode(self, tokens: jnp.ndarray, cache: KVCache):
        """One token per row. Microbatching degenerates to 1 for decode (a
        single token row set flushes through the pipe)."""
        return self._decode_jit(self.params, tokens, cache)

    def generate_greedy(self, tokens: jnp.ndarray, lengths: jnp.ndarray, max_new: int):
        """Greedy pipelined generation (host loop over jitted decode steps)."""
        b, s = tokens.shape
        cache = self.init_cache(b, s + max_new)
        logits, cache = self.prefill(tokens, lengths, cache)
        outs = []
        for _ in range(max_new):
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            outs.append(nxt)
            logits, cache = self.decode(nxt, cache)
        return jnp.stack(outs, axis=1)

    def forward_train(self, tokens: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
        """Full-sequence logits for loss computation (pipelined)."""
        cfg = self.cfg
        b, s = tokens.shape
        cache = self.init_cache(b, s)
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        kv_valid = jnp.arange(s)[None, :] < lengths[:, None]
        x = embed_tokens(cfg, self.params, tokens, positions)
        hidden, _ = self._run_layers(
            self.params, x, positions, kv_valid, cache, is_decode=False, num_micro=self.num_micro
        )
        return self._logits(self.params, hidden)
