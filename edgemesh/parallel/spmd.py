"""4D-parallel training step: dp x pp x sp x tp in ONE shard_map program.

This is the full scaling-book composition, written manually so every
collective is explicit and rides the intended fabric:

- ``dp``  (data):     batch split; gradient reduction is the final psum.
- ``pp``  (pipeline): contiguous layer blocks per stage; GPipe fill-drain
                      with ``lax.ppermute`` activation hops (the TPU-native
                      realization of the reference's intended cross-Jetson
                      model split, ``Code/gRPC/server.py:1`` — see
                      edgemesh/parallel/pipeline.py for the inference engine).
- ``sp``  (sequence): ring attention (edgemesh/parallel/ring_attention.py);
                      K/V blocks rotate around the ``sp`` ring inside every
                      attention layer.
- ``tp``  (tensor):   Megatron layout — q/k/v/gate/up column-sharded (heads
                      and MLP columns local), o/down row-sharded with an
                      explicit ``psum`` join.

The reference has NONE of these strategies (SURVEY.md §2.3: its only
parallelism is the model-level ensemble, and its "distribution" is a gRPC
timestamp PoC between Jetsons) — this module is where the TPU build goes
beyond parity to an actual 4D-parallel framework.

Differentiability: the whole per-device program (GPipe scan + ring scans +
psums) is transposed by JAX; ``jax.value_and_grad`` around the shard_map
yields gradients laid out exactly like the params, so the optax update runs
on sharded arrays without any reshard.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from edgemesh.models.transformer import (
    ModelConfig, _activate, _apply_norm, embed_tokens, layer_scan_alt_windows,
    lm_head_logits,
)
from edgemesh.ops.rope import apply_rope
from edgemesh.parallel.ring_attention import ring_attend_block
from edgemesh.training import TrainState
from edgemesh.utils.compat import axis_size, shard_map

Params = dict[str, Any]


# ---------------------------------------------------------------------------
# Param placement
# ---------------------------------------------------------------------------


def _dense_spec(col_shard: bool, has_bias: bool) -> Params:
    """Specs for one stacked dense {kernel: [L, in, out], bias?}: the layer
    axis is always split over pp; the tp split follows the Megatron role."""
    if col_shard:
        spec: Params = {"kernel": P("pp", None, "tp")}
        if has_bias:
            spec["bias"] = P("pp", "tp")
    else:
        spec = {"kernel": P("pp", "tp", None)}
        if has_bias:
            spec["bias"] = P("pp", None)
    return spec


def spmd_param_specs(cfg: ModelConfig) -> Params:
    """PartitionSpec tree (matching init_params structure) for the 4D layout."""
    layer: Params = {
        "attn_norm": {"scale": P("pp", None)},
        "q": _dense_spec(True, cfg.qkv_bias),
        "k": _dense_spec(True, cfg.qkv_bias),
        "v": _dense_spec(True, cfg.qkv_bias),
        "o": _dense_spec(False, cfg.out_bias),
    }
    if cfg.qk_norm:  # [L, head_dim] per-head norm scales, tp-replicated
        layer["q_norm"] = {"scale": P("pp", None)}
        layer["k_norm"] = {"scale": P("pp", None)}
    if cfg.norm == "ln":
        layer["attn_norm"]["bias"] = P("pp", None)
    if not cfg.shared_input_norm:
        layer["mlp_norm"] = dict(layer["attn_norm"])
    if cfg.post_block_norms:  # Gemma-2 post-sublayer norms
        layer["attn_post_norm"] = dict(layer["attn_norm"])
        layer["mlp_post_norm"] = dict(layer["attn_norm"])
    if cfg.num_experts > 0:
        # Stacked MoE leaves [L, E, ...]: expert dim over ep, FFN width over
        # tp (same Megatron roles as the dense MLP); fp32 router replicated —
        # every ep member routes identically and slices out its own experts.
        layer["moe"] = {
            "router": {"kernel": P("pp", None, None)},
            "up": P("pp", "ep", None, "tp"),
            "down": P("pp", "ep", "tp", None),
        }
        if cfg.gated:
            layer["moe"]["gate"] = P("pp", "ep", None, "tp")
    else:
        layer["down"] = _dense_spec(False, cfg.out_bias)
        if cfg.gated:
            layer["gate"] = _dense_spec(True, cfg.out_bias)
        layer["up"] = _dense_spec(True, cfg.out_bias)

    specs: Params = {
        "embed": {"weight": P()},
        "layers": layer,
        "final_norm": {"scale": P()},
    }
    if cfg.learned_positions:
        specs["pos_embed"] = {"weight": P()}
    if cfg.norm == "ln":
        specs["final_norm"]["bias"] = P()
    if not cfg.tie_embeddings:
        specs["lm_head"] = {"kernel": P()}
        if cfg.lm_head_bias:
            specs["lm_head"]["bias"] = P()
    return specs


def place_spmd(params: Params, cfg: ModelConfig, mesh: Mesh) -> Params:
    """Materialize a (host or single-device) param tree onto the 4D mesh."""
    specs = spmd_param_specs(cfg)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params,
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def _check_divisibility(cfg: ModelConfig, mesh: Mesh) -> None:
    pp, tp = mesh.shape["pp"], mesh.shape["tp"]
    if cfg.num_layers % pp:
        raise ValueError(f"num_layers {cfg.num_layers} % pp {pp} != 0")
    if cfg.num_heads % tp or cfg.num_kv_heads % tp:
        raise ValueError(
            f"heads ({cfg.num_heads}/{cfg.num_kv_heads}) must divide by tp {tp}"
        )
    if cfg.intermediate_size % tp:
        raise ValueError(f"intermediate {cfg.intermediate_size} % tp {tp} != 0")
    ep = mesh.shape.get("ep", 1)
    if cfg.num_experts > 0 and cfg.num_experts % ep:
        raise ValueError(f"num_experts {cfg.num_experts} % ep {ep} != 0")
    if cfg.alt_sliding_window and cfg.sliding_window > 0 and (cfg.num_layers // pp) % 2:
        # The pair scan keeps each half's window static; a stage must start
        # on an even GLOBAL layer, which even layers-per-stage guarantees
        # (same constraint as the pipeline inference engine).
        raise ValueError(
            f"alt_sliding_window needs an even layer count per pp stage, got "
            f"{cfg.num_layers}/{pp} = {cfg.num_layers // pp}"
        )


# ---------------------------------------------------------------------------
# Per-device layer (manual tensor parallel + ring attention)
# ---------------------------------------------------------------------------


def _col_dense(p: Params, x: jnp.ndarray) -> jnp.ndarray:
    """Column-sharded dense: kernel/bias hold only this device's columns."""
    y = x @ p["kernel"]
    if "bias" in p:
        y = y + p["bias"]
    return y


def _row_dense(p: Params, x: jnp.ndarray) -> jnp.ndarray:
    """Row-sharded dense: psum joins the partial products over tp; the
    (replicated) bias is added once, after the reduction."""
    y = lax.psum(x @ p["kernel"], "tp")
    if "bias" in p:
        y = y + p["bias"]
    return y


def _spmd_attention(
    cfg: ModelConfig,
    layer: Params,
    x: jnp.ndarray,  # [b, s_local, H] (tp-invariant)
    positions: jnp.ndarray,  # [b, s_local] global positions
    valid: jnp.ndarray,  # [b, s_local]
    sp: int,
    tp: int,
    sp_impl: str = "ring",
) -> jnp.ndarray:
    b, s, _ = x.shape
    nh_l = cfg.num_heads // tp
    kh_l = cfg.num_kv_heads // tp
    hd = cfg.head_size

    q = _col_dense(layer["q"], x).reshape(b, s, nh_l, hd)
    k = _col_dense(layer["k"], x).reshape(b, s, kh_l, hd)
    v = _col_dense(layer["v"], x).reshape(b, s, kh_l, hd)
    if cfg.qk_norm:  # Qwen3-style per-head RMSNorm, before RoPE
        from edgemesh.ops.norms import rms_norm

        q = rms_norm(q, layer["q_norm"]["scale"], cfg.norm_eps)
        k = rms_norm(k, layer["k_norm"]["scale"], cfg.norm_eps)
    if cfg.rotary_dim > 0:
        q = apply_rope(q, positions, cfg.rotary_dim, cfg.rope_theta, cfg.rope_scaling)
        k = apply_rope(k, positions, cfg.rotary_dim, cfg.rope_theta, cfg.rope_scaling)

    if sp_impl == "ulysses":
        from edgemesh.parallel.ulysses import ulysses_attend_block

        out = ulysses_attend_block(
            q, k, v, positions, valid, axis="sp", sp=sp, scale=cfg.query_scale,
            sliding_window=cfg.sliding_window, soft_cap=cfg.attn_soft_cap,
        )
    elif sp_impl == "ring":
        out = ring_attend_block(
            q, k, v, positions, valid, axis="sp", sp=sp, scale=cfg.query_scale,
            sliding_window=cfg.sliding_window, soft_cap=cfg.attn_soft_cap,
            pcast_accumulators=False,
        )
    else:
        raise ValueError(f"unknown sp_impl {sp_impl!r}; choose ring or ulysses")
    return _row_dense(layer["o"], out.reshape(b, s, nh_l * hd))


def _spmd_mlp(cfg: ModelConfig, layer: Params, x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """FFN under manual tp (and ep for MoE) → (y, aux load-balance loss)."""
    if cfg.num_experts > 0:
        return _spmd_moe_mlp(cfg, layer["moe"], x)
    if cfg.gated:
        hidden = _activate(cfg, _col_dense(layer["gate"], x)) * _col_dense(layer["up"], x)
    else:
        hidden = _activate(cfg, _col_dense(layer["up"], x))
    return _row_dense(layer["down"], hidden), jnp.zeros((), jnp.float32)


def _spmd_moe_mlp(cfg: ModelConfig, moe: Params, x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Expert-parallel MoE inside the manual 4D program.

    Token-replicated EP: activations are already replicated over ``ep`` (no
    batch/seq axis maps to it), so every ep member runs the identical fp32
    router (replicated kernel → identical top-k), slices the [T, E, C]
    combine tensor down to its OWN E/ep experts, runs only those FFNs
    (columns further split over ``tp``), and one psum over (ep, tp) joins
    expert groups and row-shards in a single reduction. Versus the
    auto-sharded path (ops/moe.py under param_pspecs) this trades the
    all-to-all dispatch for a [T, h] psum — the right trade at these T
    (GShard-style a2a wins only when T·h outgrows the expert weights).
    """
    from edgemesh.ops.moe import expert_capacity, route_tokens

    b, s, h = x.shape
    T = b * s
    C = expert_capacity(cfg, T)
    xt = x.reshape(T, h)
    ep = axis_size("ep")
    e_local = cfg.num_experts // ep
    e0 = lax.axis_index("ep") * e_local

    combine, aux = route_tokens(cfg, moe["router"]["kernel"], xt, C)
    combine_l = lax.dynamic_slice_in_dim(combine, e0, e_local, axis=1)  # [T, El, C]
    dispatch_l = (combine_l > 0).astype(cfg.activation_dtype)
    expert_in = jnp.einsum("tec,th->ech", dispatch_l, xt.astype(cfg.activation_dtype))

    if cfg.gated:
        hidden = _activate(
            cfg, jnp.einsum("ech,ehi->eci", expert_in, moe["gate"])
        ) * jnp.einsum("ech,ehi->eci", expert_in, moe["up"])
    else:
        hidden = _activate(cfg, jnp.einsum("ech,ehi->eci", expert_in, moe["up"]))
    expert_out = jnp.einsum("eci,eih->ech", hidden, moe["down"])  # [El, C, h] tp-partial

    y = jnp.einsum("tec,ech->th", combine_l.astype(cfg.activation_dtype), expert_out)
    y = lax.psum(y, ("ep", "tp"))  # join expert groups AND the tp row split
    return y.reshape(b, s, h).astype(x.dtype), aux


def _spmd_layer(
    cfg: ModelConfig,
    layer: Params,
    x: jnp.ndarray,
    positions: jnp.ndarray,
    valid: jnp.ndarray,
    sp: int,
    tp: int,
    sp_impl: str = "ring",
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One transformer layer → (x, moe aux), all family dials (mirrors
    transformer._layer_fn)."""
    if cfg.parallel_block:
        attn_in = _apply_norm(cfg, layer["attn_norm"], x)
        mlp_in = attn_in if cfg.shared_input_norm else _apply_norm(cfg, layer["mlp_norm"], x)
        mlp_out, aux = _spmd_mlp(cfg, layer, mlp_in)
        return (
            x
            + _spmd_attention(cfg, layer, attn_in, positions, valid, sp, tp, sp_impl)
            + mlp_out
        ), aux
    attn_out = _spmd_attention(
        cfg, layer, _apply_norm(cfg, layer["attn_norm"], x), positions, valid, sp, tp, sp_impl
    )
    if cfg.post_block_norms:  # Gemma-2: norm each sublayer OUTPUT pre-residual
        attn_out = _apply_norm(cfg, layer["attn_post_norm"], attn_out)
    x = x + attn_out
    mlp_out, aux = _spmd_mlp(cfg, layer, _apply_norm(cfg, layer["mlp_norm"], x))
    if cfg.post_block_norms:
        mlp_out = _apply_norm(cfg, layer["mlp_post_norm"], mlp_out)
    return x + mlp_out, aux


# ---------------------------------------------------------------------------
# The 4D program
# ---------------------------------------------------------------------------


def _make_device_fn(cfg: ModelConfig, mesh: Mesh, num_micro: int, moe_aux_weight: float = 0.01, sp_impl: str = "ring"):
    pp = mesh.shape["pp"]
    sp = mesh.shape["sp"]
    tp = mesh.shape["tp"]

    def device_fn(params: Params, tokens: jnp.ndarray, lengths: jnp.ndarray):
        # tokens: [b_local, s_local] (dp x sp shard); lengths: [b_local].
        stage = lax.axis_index("pp")
        sp_idx = lax.axis_index("sp")
        b_l, s_l = tokens.shape
        if b_l % num_micro:
            raise ValueError(f"local batch {b_l} % num_micro {num_micro} != 0")
        mbs = b_l // num_micro

        block_start = sp_idx * s_l
        positions = block_start + jnp.broadcast_to(jnp.arange(s_l)[None, :], (b_l, s_l))
        valid = positions < lengths[:, None]
        # Next-token targets: shift left within the block; the last column is
        # the FIRST token of the next sp block, fetched with one ppermute hop.
        nxt_first = lax.ppermute(
            tokens[:, :1], "sp", [((i + 1) % sp, i) for i in range(sp)]
        )
        targets = jnp.concatenate([tokens[:, 1:], nxt_first], axis=1)
        # A position p predicts p+1; valid iff p+1 < length. (The wrapped
        # garbage target at the global last column is always masked by this.)
        tmask = ((positions + 1) < lengths[:, None]).astype(jnp.float32)

        x = embed_tokens(cfg, params, tokens, positions)

        def to_mb(a):
            return a.reshape(num_micro, mbs, *a.shape[1:])

        x_mb, pos_mb, valid_mb = to_mb(x), to_mb(positions), to_mb(valid)
        tgt_mb, tmask_mb = to_mb(targets), to_mb(tmask)
        stage_layers = params["layers"]  # leaves already [L/pp, ...] per stage

        steps = num_micro + pp - 1
        is_last_stage = stage == pp - 1

        def one_step(carry, t):
            recv, loss_sum, cnt_sum, aux_sum = carry
            mb_idx = t - stage
            active = (mb_idx >= 0) & (mb_idx < num_micro)
            idx = jnp.clip(mb_idx, 0, num_micro - 1)

            h = jnp.where(stage == 0, x_mb[idx], recv)
            pos, kvv = pos_mb[idx], valid_mb[idx]

            def layer_step(layer_cfg, carry_l, layer):
                h, aux = carry_l
                h, a = _spmd_layer(layer_cfg, layer, h, pos, kvv, sp, tp, sp_impl)
                return (h, aux + a), ()

            # Gemma-2's alternating windows ride the shared pair scan (each
            # half's window a static constant); plain configs take the
            # ordinary one-layer scan inside the same helper. Stage layer
            # blocks start on even global layers (_check_divisibility).
            (h, aux_mb), _ = layer_scan_alt_windows(
                cfg, layer_step, (h, jnp.zeros((), jnp.float32)), stage_layers
            )
            # Bubble (fill/drain) steps run the layers on a clipped microbatch
            # index; their routing stats must not leak into the aux loss.
            aux_sum = aux_sum + jnp.where(active, aux_mb, 0.0)
            send = lax.ppermute(h, "pp", [(i, i + 1) for i in range(pp - 1)])

            # The LM-head matmul ([*, vocab] — the largest in the program) and
            # its CE only matter on the last stage's active steps; lax.cond
            # skips it (forward AND backward) on the other pp-1 stages and in
            # the fill/drain bubble instead of multiplying by zero.
            def ce_branch(h_in):
                ce = optax.softmax_cross_entropy_with_integer_labels(
                    lm_head_logits(cfg, params, h_in).astype(jnp.float32), tgt_mb[idx]
                )
                # Rank-1 accumulators (here and in the carry inits below):
                # grad-of-shard_map on pre-vma jax forwards KNOWN scalar
                # values (this count depends only on tokens/lengths) into the
                # backward map under an all-axes respec that requires
                # ndim >= 1 — a rank-0 residual aborts the whole backward.
                return (jnp.sum(ce * tmask_mb[idx])[None],
                        jnp.sum(tmask_mb[idx])[None])

            def skip_branch(h_in):
                return jnp.zeros((1,), jnp.float32), jnp.zeros((1,), jnp.float32)

            dl, dc = lax.cond(active & is_last_stage, ce_branch, skip_branch, h)
            loss_sum = loss_sum + dl
            cnt_sum = cnt_sum + dc
            return (send, loss_sum, cnt_sum, aux_sum), None

        init = (
            jnp.zeros((mbs, s_l, cfg.hidden_size), cfg.activation_dtype),
            jnp.zeros((1,), jnp.float32),
            jnp.zeros((1,), jnp.float32),
            jnp.zeros((1,), jnp.float32),
        )
        (_, loss_sum, cnt_sum, aux_sum), _ = lax.scan(one_step, init, jnp.arange(steps))

        # Loss lives on the last pp stage, sharded over dp x sp; tp members
        # already agree (activations are tp-invariant after every row psum).
        total = lax.psum(loss_sum, ("dp", "pp", "sp"))  # [1]
        count = lax.psum(cnt_sum, ("dp", "pp", "sp"))  # [1]
        loss = (total / jnp.maximum(count, 1.0))[0]
        if cfg.num_experts > 0:
            # psum over pp sums the per-stage LAYER blocks (correct: aux is a
            # per-layer sum, matching transformer._scan_layers); dp/sp shards
            # and microbatches routed DIFFERENT tokens, so those reduce as a
            # mean. ep/tp members compute identical aux — excluded from psum.
            dp_n, sp_n = mesh.shape["dp"], mesh.shape["sp"]
            aux = lax.psum(aux_sum, ("dp", "pp", "sp"))[0] / (dp_n * sp_n * num_micro)
            loss = loss + moe_aux_weight * aux
        return loss

    return device_fn


def make_spmd_loss(
    cfg: ModelConfig, mesh: Mesh, num_micro: int = 2, moe_aux_weight: float = 0.01,
    sp_impl: str = "ring",
):
    """Returns loss(params, tokens, lengths) -> scalar, where params follow
    spmd_param_specs layout and tokens are [B, S] split dp x sp. For MoE
    configs the scalar includes ``moe_aux_weight`` x the load-balance aux
    (same coefficient convention as training.make_train_step). ``sp_impl``
    picks the sequence-parallel scheme: "ring" (K/V rotation,
    parallel/ring_attention.py) or "ulysses" (all-to-all head↔seq exchange,
    parallel/ulysses.py) — both exact."""
    _check_divisibility(cfg, mesh)
    device_fn = _make_device_fn(cfg, mesh, num_micro, moe_aux_weight, sp_impl)
    specs = spmd_param_specs(cfg)

    def loss_fn(params: Params, tokens: jnp.ndarray, lengths: jnp.ndarray):
        return shard_map(
            device_fn,
            mesh=mesh,
            in_specs=(specs, P("dp", "sp"), P("dp")),
            out_specs=P(),
            check_vma=False,
        )(params, tokens, lengths)

    return loss_fn


def make_spmd_train_step(
    cfg: ModelConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    num_micro: int = 2,
):
    """Jitted 4D train step: (state, tokens, lengths) -> (state, loss).

    ``state.params`` must be placed with :func:`place_spmd`; gradients and
    optimizer state inherit the same shardings through jit."""
    loss_fn = make_spmd_loss(cfg, mesh, num_micro)

    @partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, tokens: jnp.ndarray, lengths: jnp.ndarray):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens, lengths)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return step
