"""4D-parallel training step: dp x pp x sp x tp in ONE shard_map program.

This is the full scaling-book composition, written manually so every
collective is explicit and rides the intended fabric:

- ``dp``  (data):     batch split; gradient reduction is the final psum.
- ``pp``  (pipeline): contiguous layer blocks per stage; GPipe fill-drain
                      with ``lax.ppermute`` activation hops (the TPU-native
                      realization of the reference's intended cross-Jetson
                      model split, ``Code/gRPC/server.py:1`` — see
                      edgemesh/parallel/pipeline.py for the inference engine).
- ``sp``  (sequence): ring attention (edgemesh/parallel/ring_attention.py);
                      K/V blocks rotate around the ``sp`` ring inside every
                      attention layer.
- ``tp``  (tensor):   Megatron layout — q/k/v/gate/up column-sharded (heads
                      and MLP columns local), o/down row-sharded with an
                      explicit ``psum`` join.

The reference has NONE of these strategies (SURVEY.md §2.3: its only
parallelism is the model-level ensemble, and its "distribution" is a gRPC
timestamp PoC between Jetsons) — this module is where the TPU build goes
beyond parity to an actual 4D-parallel framework.

Differentiability: the whole per-device program (GPipe scan + ring scans +
psums) is transposed by JAX; ``jax.value_and_grad`` around the shard_map
yields gradients laid out exactly like the params, so the optax update runs
on sharded arrays without any reshard.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from edgemesh.models.transformer import ModelConfig, _apply_norm, lm_head_logits
from edgemesh.ops.rope import apply_rope
from edgemesh.parallel.ring_attention import ring_attend_block
from edgemesh.training import TrainState

Params = dict[str, Any]


# ---------------------------------------------------------------------------
# Param placement
# ---------------------------------------------------------------------------


def _dense_spec(col_shard: bool, has_bias: bool) -> Params:
    """Specs for one stacked dense {kernel: [L, in, out], bias?}: the layer
    axis is always split over pp; the tp split follows the Megatron role."""
    if col_shard:
        spec: Params = {"kernel": P("pp", None, "tp")}
        if has_bias:
            spec["bias"] = P("pp", "tp")
    else:
        spec = {"kernel": P("pp", "tp", None)}
        if has_bias:
            spec["bias"] = P("pp", None)
    return spec


def spmd_param_specs(cfg: ModelConfig) -> Params:
    """PartitionSpec tree (matching init_params structure) for the 4D layout."""
    layer: Params = {
        "attn_norm": {"scale": P("pp", None)},
        "q": _dense_spec(True, cfg.qkv_bias),
        "k": _dense_spec(True, cfg.qkv_bias),
        "v": _dense_spec(True, cfg.qkv_bias),
        "o": _dense_spec(False, cfg.out_bias),
        "down": _dense_spec(False, cfg.out_bias),
    }
    if cfg.norm == "ln":
        layer["attn_norm"]["bias"] = P("pp", None)
    if not cfg.shared_input_norm:
        layer["mlp_norm"] = dict(layer["attn_norm"])
    if cfg.activation == "silu":
        layer["gate"] = _dense_spec(True, cfg.out_bias)
    layer["up"] = _dense_spec(True, cfg.out_bias)

    specs: Params = {
        "embed": {"weight": P()},
        "layers": layer,
        "final_norm": {"scale": P()},
    }
    if cfg.norm == "ln":
        specs["final_norm"]["bias"] = P()
    if not cfg.tie_embeddings:
        specs["lm_head"] = {"kernel": P()}
        if cfg.lm_head_bias:
            specs["lm_head"]["bias"] = P()
    return specs


def place_spmd(params: Params, cfg: ModelConfig, mesh: Mesh) -> Params:
    """Materialize a (host or single-device) param tree onto the 4D mesh."""
    specs = spmd_param_specs(cfg)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params,
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def _check_divisibility(cfg: ModelConfig, mesh: Mesh) -> None:
    pp, tp = mesh.shape["pp"], mesh.shape["tp"]
    if cfg.num_layers % pp:
        raise ValueError(f"num_layers {cfg.num_layers} % pp {pp} != 0")
    if cfg.num_heads % tp or cfg.num_kv_heads % tp:
        raise ValueError(
            f"heads ({cfg.num_heads}/{cfg.num_kv_heads}) must divide by tp {tp}"
        )
    if cfg.intermediate_size % tp:
        raise ValueError(f"intermediate {cfg.intermediate_size} % tp {tp} != 0")


# ---------------------------------------------------------------------------
# Per-device layer (manual tensor parallel + ring attention)
# ---------------------------------------------------------------------------


def _col_dense(p: Params, x: jnp.ndarray) -> jnp.ndarray:
    """Column-sharded dense: kernel/bias hold only this device's columns."""
    y = x @ p["kernel"]
    if "bias" in p:
        y = y + p["bias"]
    return y


def _row_dense(p: Params, x: jnp.ndarray) -> jnp.ndarray:
    """Row-sharded dense: psum joins the partial products over tp; the
    (replicated) bias is added once, after the reduction."""
    y = lax.psum(x @ p["kernel"], "tp")
    if "bias" in p:
        y = y + p["bias"]
    return y


def _spmd_attention(
    cfg: ModelConfig,
    layer: Params,
    x: jnp.ndarray,  # [b, s_local, H] (tp-invariant)
    positions: jnp.ndarray,  # [b, s_local] global positions
    valid: jnp.ndarray,  # [b, s_local]
    sp: int,
    tp: int,
) -> jnp.ndarray:
    b, s, _ = x.shape
    nh_l = cfg.num_heads // tp
    kh_l = cfg.num_kv_heads // tp
    hd = cfg.head_size

    q = _col_dense(layer["q"], x).reshape(b, s, nh_l, hd)
    k = _col_dense(layer["k"], x).reshape(b, s, kh_l, hd)
    v = _col_dense(layer["v"], x).reshape(b, s, kh_l, hd)
    if cfg.rotary_dim > 0:
        q = apply_rope(q, positions, cfg.rotary_dim, cfg.rope_theta, cfg.rope_scaling)
        k = apply_rope(k, positions, cfg.rotary_dim, cfg.rope_theta, cfg.rope_scaling)

    out = ring_attend_block(
        q, k, v, positions, valid, axis="sp", sp=sp, pcast_accumulators=False
    )
    return _row_dense(layer["o"], out.reshape(b, s, nh_l * hd))


def _spmd_mlp(cfg: ModelConfig, layer: Params, x: jnp.ndarray) -> jnp.ndarray:
    if cfg.num_experts > 0:
        raise NotImplementedError(
            "MoE runs under the auto-sharded path (ep axis in param_pspecs); "
            "the manual 4D SPMD program does not route experts yet"
        )
    if cfg.activation == "silu":
        hidden = jax.nn.silu(_col_dense(layer["gate"], x)) * _col_dense(layer["up"], x)
    else:
        hidden = _col_dense(layer["up"], x)
        hidden = jax.nn.gelu(hidden, approximate=cfg.activation == "gelu_tanh")
    return _row_dense(layer["down"], hidden)


def _spmd_layer(
    cfg: ModelConfig,
    layer: Params,
    x: jnp.ndarray,
    positions: jnp.ndarray,
    valid: jnp.ndarray,
    sp: int,
    tp: int,
) -> jnp.ndarray:
    """One transformer layer, all family dials (mirrors transformer._layer_fn)."""
    if cfg.parallel_block:
        attn_in = _apply_norm(cfg, layer["attn_norm"], x)
        mlp_in = attn_in if cfg.shared_input_norm else _apply_norm(cfg, layer["mlp_norm"], x)
        return (
            x
            + _spmd_attention(cfg, layer, attn_in, positions, valid, sp, tp)
            + _spmd_mlp(cfg, layer, mlp_in)
        )
    x = x + _spmd_attention(
        cfg, layer, _apply_norm(cfg, layer["attn_norm"], x), positions, valid, sp, tp
    )
    return x + _spmd_mlp(cfg, layer, _apply_norm(cfg, layer["mlp_norm"], x))


# ---------------------------------------------------------------------------
# The 4D program
# ---------------------------------------------------------------------------


def _make_device_fn(cfg: ModelConfig, mesh: Mesh, num_micro: int):
    pp = mesh.shape["pp"]
    sp = mesh.shape["sp"]
    tp = mesh.shape["tp"]

    def device_fn(params: Params, tokens: jnp.ndarray, lengths: jnp.ndarray):
        # tokens: [b_local, s_local] (dp x sp shard); lengths: [b_local].
        stage = lax.axis_index("pp")
        sp_idx = lax.axis_index("sp")
        b_l, s_l = tokens.shape
        if b_l % num_micro:
            raise ValueError(f"local batch {b_l} % num_micro {num_micro} != 0")
        mbs = b_l // num_micro

        block_start = sp_idx * s_l
        positions = block_start + jnp.broadcast_to(jnp.arange(s_l)[None, :], (b_l, s_l))
        valid = positions < lengths[:, None]
        # Next-token targets: shift left within the block; the last column is
        # the FIRST token of the next sp block, fetched with one ppermute hop.
        nxt_first = lax.ppermute(
            tokens[:, :1], "sp", [((i + 1) % sp, i) for i in range(sp)]
        )
        targets = jnp.concatenate([tokens[:, 1:], nxt_first], axis=1)
        # A position p predicts p+1; valid iff p+1 < length. (The wrapped
        # garbage target at the global last column is always masked by this.)
        tmask = ((positions + 1) < lengths[:, None]).astype(jnp.float32)

        x = params["embed"]["weight"][tokens].astype(cfg.activation_dtype)

        def to_mb(a):
            return a.reshape(num_micro, mbs, *a.shape[1:])

        x_mb, pos_mb, valid_mb = to_mb(x), to_mb(positions), to_mb(valid)
        tgt_mb, tmask_mb = to_mb(targets), to_mb(tmask)
        stage_layers = params["layers"]  # leaves already [L/pp, ...] per stage

        steps = num_micro + pp - 1
        is_last_stage = stage == pp - 1

        def one_step(carry, t):
            recv, loss_sum, cnt_sum = carry
            mb_idx = t - stage
            active = (mb_idx >= 0) & (mb_idx < num_micro)
            idx = jnp.clip(mb_idx, 0, num_micro - 1)

            h = jnp.where(stage == 0, x_mb[idx], recv)
            pos, kvv = pos_mb[idx], valid_mb[idx]

            def layer_step(h, layer):
                return _spmd_layer(cfg, layer, h, pos, kvv, sp, tp), None

            h, _ = lax.scan(layer_step, h, stage_layers)
            send = lax.ppermute(h, "pp", [(i, i + 1) for i in range(pp - 1)])

            # The LM-head matmul ([*, vocab] — the largest in the program) and
            # its CE only matter on the last stage's active steps; lax.cond
            # skips it (forward AND backward) on the other pp-1 stages and in
            # the fill/drain bubble instead of multiplying by zero.
            def ce_branch(h_in):
                ce = optax.softmax_cross_entropy_with_integer_labels(
                    lm_head_logits(cfg, params, h_in).astype(jnp.float32), tgt_mb[idx]
                )
                return jnp.sum(ce * tmask_mb[idx]), jnp.sum(tmask_mb[idx])

            def skip_branch(h_in):
                return jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)

            dl, dc = lax.cond(active & is_last_stage, ce_branch, skip_branch, h)
            loss_sum = loss_sum + dl
            cnt_sum = cnt_sum + dc
            return (send, loss_sum, cnt_sum), None

        init = (
            jnp.zeros((mbs, s_l, cfg.hidden_size), cfg.activation_dtype),
            jnp.zeros((), jnp.float32),
            jnp.zeros((), jnp.float32),
        )
        (_, loss_sum, cnt_sum), _ = lax.scan(one_step, init, jnp.arange(steps))

        # Loss lives on the last pp stage, sharded over dp x sp; tp members
        # already agree (activations are tp-invariant after every row psum).
        total = lax.psum(loss_sum, ("dp", "pp", "sp"))
        count = lax.psum(cnt_sum, ("dp", "pp", "sp"))
        return total / jnp.maximum(count, 1.0)

    return device_fn


def make_spmd_loss(cfg: ModelConfig, mesh: Mesh, num_micro: int = 2):
    """Returns loss(params, tokens, lengths) -> scalar, where params follow
    spmd_param_specs layout and tokens are [B, S] split dp x sp."""
    _check_divisibility(cfg, mesh)
    device_fn = _make_device_fn(cfg, mesh, num_micro)
    specs = spmd_param_specs(cfg)

    def loss_fn(params: Params, tokens: jnp.ndarray, lengths: jnp.ndarray):
        return jax.shard_map(
            device_fn,
            mesh=mesh,
            in_specs=(specs, P("dp", "sp"), P("dp")),
            out_specs=P(),
            check_vma=False,
        )(params, tokens, lengths)

    return loss_fn


def make_spmd_train_step(
    cfg: ModelConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    num_micro: int = 2,
):
    """Jitted 4D train step: (state, tokens, lengths) -> (state, loss).

    ``state.params`` must be placed with :func:`place_spmd`; gradients and
    optimizer state inherit the same shardings through jit."""
    loss_fn = make_spmd_loss(cfg, mesh, num_micro)

    @partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, tokens: jnp.ndarray, lengths: jnp.ndarray):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens, lengths)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return step
