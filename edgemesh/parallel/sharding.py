"""Sharding rules: PartitionSpec trees for params and KV cache.

The scaling-book recipe: annotate the param pytree with NamedShardings over
the mesh, hand jit sharded inputs, and let XLA insert the collectives —
an all-reduce (psum over ``tp``) after every row-sharded matmul, all-gather
where vocab-sharded logits meet sampling. Nothing here opens a socket; this
file IS the replacement for the reference's per-device gRPC stub map
(``Code/gRPC/client.py:7-11``).

Tensor-parallel layout (Megatron-style, per layer, over axis ``tp``):
- q/k/v kernels column-sharded (heads split across chips),
- attention output kernel row-sharded (psum joins head groups),
- MLP gate/up column-sharded, down row-sharded,
- norms replicated, embedding replicated,
- lm_head vocab-sharded (logits come out vocab-sharded; sampling reductions
  all-gather only the [batch, vocab] slice, never activations).

KV cache is kv-head-sharded over ``tp`` (the HeadInfer-analog of
BASELINE.json configs[3]: each chip's HBM holds only its heads' cache) and
batch-sharded over ``dp``.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from edgemesh.models.transformer import KVCache, ModelConfig

Params = dict[str, Any]


def _dense_pspec(col_shard: bool, has_bias: bool, tp_ok: bool) -> Params:
    """PartitionSpecs for one stacked dense layer {kernel: [L, in, out], bias?}."""
    tp = "tp" if tp_ok else None
    if col_shard:
        spec: Params = {"kernel": P(None, None, tp)}
        if has_bias:
            spec["bias"] = P(None, tp)
    else:  # row-sharded: in-dim split, output summed by XLA via psum
        spec = {"kernel": P(None, tp, None)}
        if has_bias:
            spec["bias"] = P(None, None)  # bias added once, replicated
    return spec


def _norm_pspec(cfg: ModelConfig, stacked: bool = True) -> Params:
    # Stacked layer norms are [L, H] (rank 2); the final norm is [H] (rank 1).
    p = P(None, None) if stacked else P(None)
    spec: Params = {"scale": p}
    if cfg.norm == "ln":
        spec["bias"] = p
    return spec


def param_pspecs(cfg: ModelConfig, mesh: Mesh) -> Params:
    """PartitionSpec tree matching init_params() structure exactly."""
    tp_size = mesh.shape["tp"]
    heads_ok = cfg.num_heads % tp_size == 0
    kv_ok = cfg.num_kv_heads % tp_size == 0
    inter_ok = cfg.intermediate_size % tp_size == 0
    vocab_ok = cfg.vocab_size % tp_size == 0

    layer: Params = {
        "attn_norm": _norm_pspec(cfg),
        "q": _dense_pspec(True, cfg.qkv_bias, heads_ok),
        "k": _dense_pspec(True, cfg.qkv_bias, kv_ok),
        "v": _dense_pspec(True, cfg.qkv_bias, kv_ok),
        "o": _dense_pspec(False, cfg.out_bias, heads_ok),
    }
    if cfg.qk_norm:
        # [L, head_dim] per-head norm scales: head-count-independent, so
        # they replicate under tp (each shard normalizes its own heads).
        layer["q_norm"] = {"scale": P(None, None)}
        layer["k_norm"] = {"scale": P(None, None)}
    if not cfg.shared_input_norm:
        layer["mlp_norm"] = _norm_pspec(cfg)
    if cfg.num_experts > 0:
        # MoE (stacked [L, E, ...]): expert dim on "ep", FFN width on "tp";
        # the fp32 router stays replicated (it is tiny and fully data-parallel).
        ep_ok = cfg.num_experts % mesh.shape.get("ep", 1) == 0
        e_ax = "ep" if ep_ok else None
        t_ax = "tp" if inter_ok else None
        layer["moe"] = {
            "router": {"kernel": P(None, None, None)},
            "up": P(None, e_ax, None, t_ax),
            "down": P(None, e_ax, t_ax, None),
        }
        if cfg.gated:
            layer["moe"]["gate"] = P(None, e_ax, None, t_ax)
    else:
        layer["down"] = _dense_pspec(False, cfg.out_bias, inter_ok)
        if cfg.gated:
            layer["gate"] = _dense_pspec(True, cfg.out_bias, inter_ok)
        layer["up"] = _dense_pspec(True, cfg.out_bias, inter_ok)

    specs: Params = {
        "embed": {"weight": P(None, None)},
        "layers": layer,
        "final_norm": _norm_pspec(cfg, stacked=False),
    }
    if cfg.learned_positions:
        specs["pos_embed"] = {"weight": P(None, None)}
    if not cfg.tie_embeddings:
        specs["lm_head"] = {
            "kernel": P(None, "tp" if vocab_ok else None),
        }
        if cfg.lm_head_bias:
            specs["lm_head"]["bias"] = P("tp" if vocab_ok else None)
    return specs


def quantized_pspecs(specs: Params) -> Params:
    """Map a pspec tree over the int8 param layout: each dense {kernel} becomes
    {kernel_q (same sharding), scales (sharded like the kernel's out dim)}."""

    def walk(node):
        if isinstance(node, dict):
            if "kernel" in node:
                kernel_spec = node["kernel"]
                out: Params = {
                    "kernel_q": kernel_spec,
                    # int4 nibble-packed kernel: same axes (adjacent-pair
                    # packing keeps a contiguous packed-row shard == a
                    # contiguous global-row shard, so the in-dim split is
                    # valid even in the per-shard shard_map engines)
                    "kernel_q4": kernel_spec,
                    # per-out-channel scales: kernel spec minus the in dim
                    "scales": P(*kernel_spec[:-2], kernel_spec[-1]),
                    # grouped int4 scales [.., G, out]: the G axis subdivides
                    # the contraction dim, so it inherits the kernel's in-dim
                    # sharding (keeps local group_size correct per shard)
                    "scales4": P(*kernel_spec[:-2], kernel_spec[-2], kernel_spec[-1]),
                    # per-in-channel smoothing vector: kernel spec minus the out dim
                    "smooth": P(*kernel_spec[:-1]),
                }
                if "bias" in node:
                    out["bias"] = node["bias"]
                return out
            if "router" in node:  # MoE subtree (experts quantize in-place)
                out = {"router": node["router"]}
                for name in ("gate", "up", "down"):
                    if name in node:
                        spec = node[name]  # [L, E, in, out]
                        out[name] = spec  # float experts (int4 path)
                        out[f"{name}_q"] = spec
                        # per-out-channel scales: spec minus the in dim
                        out[f"{name}_scales"] = P(*spec[:-2], spec[-1])
                return out
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(specs)


def pick_grouped_scales_spec(
    s_dict: Params, v, mesh: Mesh
) -> tuple[P, bool]:
    """Spec for a grouped int4 ``scales`` leaf ([.., G, out] — one rank above
    the int8 [.., out] spec in ``s_dict["scales"]``).

    Prefers ``scales4`` (G sharded with the kernel's in dim — required for
    the per-shard shard_map engines to see a consistent local group_size);
    when G does not divide the mesh axis (e.g. per-channel G=1), falls back
    to an unsharded G axis. Returns (spec, used_scales4)."""
    s = s_dict["scales"]
    s4 = s_dict.get("scales4")
    if isinstance(s4, P) and len(s4) <= getattr(v, "ndim", 0):
        ok = all(
            ax is None or v.shape[i] % mesh.shape[ax] == 0
            for i, ax in enumerate(s4)
        )
        if ok:
            return s4, True
    return P(*s[:-1], None, s[-1]), False


def cache_pspecs(cfg: ModelConfig, mesh: Mesh) -> KVCache:
    """KVCache sharding: [L, batch(dp), max_seq, kv_heads(tp), head_dim]."""
    kv_ok = cfg.num_kv_heads % mesh.shape["tp"] == 0
    kv = P(None, "dp", None, "tp" if kv_ok else None, None)
    return KVCache(k=kv, v=kv, lengths=P("dp"))


def shard_params(params: Params, cfg: ModelConfig, mesh: Mesh) -> Params:
    """Materialize params onto the mesh (jax.device_put with NamedShardings —
    the north star's replacement for the reference's ``device_map="auto"``
    accelerate placement, combiner_fp.py:282).

    Spec lookup is structural: any param leaf without an explicit spec (e.g.
    the optional SmoothQuant "smooth" vector when smoothing was skipped, or
    future extras) is placed fully replicated rather than crashing tree.map.
    """
    from edgemesh.ops.int8 import is_quantized

    specs = param_pspecs(cfg, mesh)
    if is_quantized(params):
        specs = quantized_pspecs(specs)

    def walk(p_node, s_node):
        if isinstance(p_node, dict):
            s_dict = s_node if isinstance(s_node, dict) else {}
            out = {}
            for k, v in p_node.items():
                s = s_dict.get(k)
                if (
                    k == "scales"
                    and isinstance(s, P)
                    and getattr(v, "ndim", 0) == len(s) + 1
                ):
                    # Grouped int4 scales carry an extra G axis before the
                    # out dim ([L, G, out] vs int8's [L, out]): shard G like
                    # the kernel's in dim where divisibility allows. (Under
                    # GSPMD any valid placement is correct; the consistency
                    # requirement bites only in the shard_map engines, which
                    # do their own strict check in tp_infer._specs.)
                    s, _ = pick_grouped_scales_spec(s_dict, v, mesh)
                out[k] = walk(v, s)
            return out
        spec = s_node if isinstance(s_node, P) else P()
        return jax.device_put(p_node, NamedSharding(mesh, spec))

    return walk(params, specs)


def shard_cache(cache: KVCache, cfg: ModelConfig, mesh: Mesh) -> KVCache:
    specs = cache_pspecs(cfg, mesh)
    return KVCache(
        k=jax.device_put(cache.k, NamedSharding(mesh, specs.k)),
        v=jax.device_put(cache.v, NamedSharding(mesh, specs.v)),
        lengths=jax.device_put(cache.lengths, NamedSharding(mesh, specs.lengths)),
    )


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Tokens/lengths: batch over dp, replicated over tp."""
    return NamedSharding(mesh, P("dp", None))


def paged_cache_pspecs(cfg: ModelConfig, mesh: Mesh, quant: bool = False):
    """Head-wise sharding of the paged KV pool (the HeadInfer analog,
    BASELINE.json configs[3]): page arrays are [L, pages, kv_heads, page_size,
    head_dim] (runtime/paged_kv.py), so P(None, None, "tp") slices each
    chip's HBM down to its own heads' stripe of every page — no resharding
    on attention. The page table, lengths, and free list are tiny and
    replicated (every chip walks the same table for its local heads).
    ``quant=True`` covers the int8 pool (QuantPagedKVCache): the per-token
    scale arrays [L, P, kh, 1, ps] shard on the same kh axis."""
    from edgemesh.runtime.paged_kv import PagedKVCache, QuantPagedKVCache

    kv_ok = cfg.num_kv_heads % mesh.shape["tp"] == 0
    kv = P(None, None, "tp" if kv_ok else None, None, None)
    if quant:
        return QuantPagedKVCache(
            k=kv, v=kv, k_scale=kv, v_scale=kv,
            page_table=P(), lengths=P(), free_stack=P(), free_top=P(),
        )
    return PagedKVCache(
        k=kv, v=kv, page_table=P(), lengths=P(), free_stack=P(), free_top=P()
    )


def shard_paged_cache(cache, cfg: ModelConfig, mesh: Mesh):
    from edgemesh.runtime.paged_kv import QuantPagedKVCache

    specs = paged_cache_pspecs(cfg, mesh, quant=isinstance(cache, QuantPagedKVCache))
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        cache, specs, is_leaf=lambda x: isinstance(x, P),
    )
