"""Tensor-parallel inference engine: Megatron layout under one shard_map.

The auto-sharded path (parallel/sharding.py + plain jit) lets XLA insert the
tp collectives, but XLA cannot auto-partition a ``pallas_call`` — so under
GSPMD the Pallas flash/paged kernels stay off and attention falls back to the
einsum path. This engine closes that gap (SURVEY.md §7 hard part (b),
VERDICT r1 weak #4): the whole forward runs *per shard* inside
``jax.shard_map``, where every array is local — each chip holds its own
attention-head group and MLP columns — so the Pallas kernels apply
unchanged to the local shapes, and the only cross-chip traffic is one
``psum`` over ``tp`` after the attention output projection and one after the
MLP down projection (the textbook Megatron pattern, riding ICI).

Reuses the exact family wiring of models/transformer.py by plugging
psum-wrapped ``attention``/``mlp`` callables into ``_forward`` — the local
config simply divides heads/FFN width by the tp degree. Works for bf16 and
all int8 quant modes (the fused w8a8 Pallas kernel also sees local shapes).

Reference analog: there is none — the reference's tensor compute never
crosses a device boundary (its gRPC fabric carries a timestamp,
``Code/gRPC/time_service.proto:9-14``); this is the TPU-native realization
of what that fabric was built for.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from edgemesh.models.transformer import (
    KVCache,
    ModelConfig,
    _attention,
    _forward,
    _mlp,
    attention_core,
    dense,
    mlp_hidden,
)
from edgemesh.ops.int8 import is_quantized
from edgemesh.parallel.collectives import (
    collective_wire_bytes,
    qpsum,
    validate_collective_mode,
)
from edgemesh.parallel.sharding import param_pspecs, quantized_pspecs
from edgemesh.utils.compat import shard_map
from edgemesh.utils.platform import on_tpu

Params = dict[str, Any]


def _attention_psum(cfg, layer, x, positions, cache, kv_valid, lengths, is_decode):
    out, new_kv = _attention(
        cfg, layer, x, positions, cache=cache, kv_valid=kv_valid,
        lengths=lengths, is_decode=is_decode,
    )
    return lax.psum(out, "tp"), new_kv


def _mlp_psum(cfg, layer, x):
    y, aux = _mlp(cfg, layer, x)
    return lax.psum(y, "tp"), lax.pmean(aux, "tp")


# ---------------------------------------------------------------------------
# Quantized / overlapped collective joins (collective_mode != "psum").
#
# The row-sharded projections ("o", "down") produce per-shard PARTIAL sums;
# "psum" joins them in full precision. "qpsum" joins through the quantized
# ring all-reduce (parallel/collectives.qpsum — half the wire bytes at
# int8/fp8). "qpsum_overlap" additionally decomposes the projection's
# OUTPUT dim into chunks: chunk i's collective is dataflow-independent of
# chunk i+1's matmul, so XLA's async collectives run the ring while the
# MXU computes the next output slice — the TPI-LLM-style comm/compute
# overlap, with the qpsum ring (explicit ppermutes) giving the scheduler
# maximal freedom. Output-dim (not contraction-dim) slicing is load-
# bearing: disjoint column slices are each a COMPLETE partial sum, so the
# k chunk joins together ship exactly the monolithic payload — a
# contraction split would all-reduce the full output k times, multiplying
# the wire it exists to shrink. The per-shard bias convention is preserved
# exactly: placement pre-divides "o"/"down" biases by tp (see _place), the
# bias slices with its columns, and the concatenation carries it once.
# ---------------------------------------------------------------------------


def _overlap_sliceable(p: Params) -> bool:
    """Dense params whose OUTPUT dim slices cleanly: plain kernels and
    per-channel int8. int4 (nibble-packed) and LoRA-adapted denses fall
    back to the monolithic qpsum join."""
    if "lora_a" in p or "kernel_q4" in p:
        return False
    return "kernel" in p or "kernel_q" in p


def _slice_dense(p: Params, lo: int, hi: int) -> Params:
    """The [lo:hi) OUTPUT-dim slice of a dense param dict. Slicing the
    output (not the contraction) keeps each chunk a COMPLETE partial sum
    over disjoint output columns, so the k per-chunk all-reduces together
    ship exactly the monolithic join's payload — chunking buys overlap, not
    extra wire. Per-output-channel scales and the (tp-pre-divided) bias
    slice with the columns; the SmoothQuant vector rides the (whole)
    contraction dim."""
    out: Params = {}
    if "kernel" in p:
        out["kernel"] = p["kernel"][:, lo:hi]
    else:
        out["kernel_q"] = p["kernel_q"][:, lo:hi]
        out["scales"] = p["scales"][lo:hi]
    if "smooth" in p:
        out["smooth"] = p["smooth"]
    if "bias" in p:
        out["bias"] = p["bias"][lo:hi]
    return out


def _pick_chunks(dim: int, n_chunks: int) -> int:
    """Largest chunk count <= n_chunks that divides the output dim
    (static: dim is a trace-time shape)."""
    k = max(1, min(int(n_chunks), int(dim)))
    while dim % k:
        k -= 1
    return k


def _collective_dense(
    p: Params,
    x: jnp.ndarray,
    mode: str,
    dtype: str,
    n_chunks: int,
    quant_mode: str,
) -> jnp.ndarray:
    """Row-sharded projection + tp join under the configured collective
    mode. ``x`` is the projection input [b, s, in_local]."""
    if mode == "qpsum" or not _overlap_sliceable(p):
        return qpsum(dense(p, x, quant_mode), "tp", dtype=dtype)
    kernel = p["kernel"] if "kernel" in p else p["kernel_q"]
    out_dim = kernel.shape[-1]
    k = _pick_chunks(out_dim, n_chunks)
    if k <= 1:
        return qpsum(dense(p, x, quant_mode), "tp", dtype=dtype)
    step = out_dim // k
    # Issue chunk i's collective before chunk i+1's matmul: the output
    # slices are independent, so each ring hides behind the next
    # contraction, and the concatenation reassembles the monolithic result.
    joined = [
        qpsum(
            dense(_slice_dense(p, i * step, (i + 1) * step), x, quant_mode),
            "tp", dtype=dtype,
        )
        for i in range(k)
    ]
    return jnp.concatenate(joined, axis=-1)


def _make_collective_fns(collective_mode: str, comm_dtype: str,
                         overlap_chunks: int):
    """(attention, mlp) callables for ``_forward`` under the given join
    mode. "psum" returns the module-level full-precision pair unchanged —
    the legacy path stays bit-identical and singly defined."""
    if collective_mode == "psum":
        return _attention_psum, _mlp_psum

    def attention_fn(cfg, layer, x, positions, cache, kv_valid, lengths,
                     is_decode):
        out, new_kv = attention_core(
            cfg, layer, x, positions, cache=cache, kv_valid=kv_valid,
            lengths=lengths, is_decode=is_decode,
        )
        y = _collective_dense(
            layer["o"], out, collective_mode, comm_dtype, overlap_chunks,
            cfg.quant_mode,
        )
        return y, new_kv

    def mlp_fn(cfg, layer, x):
        if cfg.num_experts > 0:
            # MoE has no single down projection to chunk; the expert-summed
            # output still rides the quantized wire.
            y, aux = _mlp(cfg, layer, x)
            return qpsum(y, "tp", dtype=comm_dtype), lax.pmean(aux, "tp")
        h = mlp_hidden(cfg, layer, x)
        y = _collective_dense(
            layer["down"], h, collective_mode, comm_dtype, overlap_chunks,
            cfg.quant_mode,
        )
        return y, lax.pmean(jnp.zeros((), jnp.float32), "tp")

    return attention_fn, mlp_fn


# ---------------------------------------------------------------------------
# Module-level builders — the engine's construction path, exposed so the
# sharding dryrun (analysis/sharding.py SHARDING_CONTRACTS) can trace the
# EXACT production shard_map program under an AbstractMesh with no devices.
# ---------------------------------------------------------------------------


def tp_local_config(cfg: ModelConfig, tp: int, attention_impl: str) -> ModelConfig:
    """The per-shard view: each chip runs a model with 1/tp of the heads
    and FFN columns. All family dials (norms, parallel_block, rope) carry
    over untouched."""
    if cfg.num_heads % tp or cfg.num_kv_heads % tp or cfg.intermediate_size % tp:
        raise ValueError(
            f"heads {cfg.num_heads}/{cfg.num_kv_heads} and FFN "
            f"{cfg.intermediate_size} must divide tp={tp}"
        )
    return cfg.replace(
        num_heads=cfg.num_heads // tp,
        num_kv_heads=cfg.num_kv_heads // tp,
        intermediate_size=cfg.intermediate_size // tp,
        head_dim=cfg.head_size,
        attention_impl=attention_impl,
    )


def tp_cache_specs() -> KVCache:
    """KV cache PartitionSpecs for the tp engine: batch over dp, kv heads
    over tp ([L, batch, max_seq, kv_heads, head_dim])."""
    return KVCache(
        k=P(None, "dp", None, "tp", None),
        v=P(None, "dp", None, "tp", None),
        lengths=P("dp"),
    )


def tp_param_specs(cfg: ModelConfig, params: Params, mesh: Mesh) -> Params:
    """in_specs mirroring the param pytree EXACTLY (shard_map requires it) —
    prune spec-only keys (e.g. the optional SmoothQuant "smooth" leaf when
    smoothing was skipped) and replicate any param key without a spec.

    Works on abstract params (``jax.eval_shape`` trees) too: only shapes
    and key sets are consulted, so the sharding dryrun shares this path.
    """
    tp = mesh.shape["tp"]
    specs = param_pspecs(cfg, mesh)
    if is_quantized(params):
        specs = quantized_pspecs(specs)
    # This engine keeps the LM head replicated: sampling needs the full
    # vocab row, and the [b, vocab] gather is cheap next to resharding
    # logits out of a vocab split every step.
    if "lm_head" in specs:
        specs["lm_head"] = jax.tree.map(
            lambda s: P(*([None] * len(s))), specs["lm_head"],
            is_leaf=lambda x: isinstance(x, P),
        )

    # Grouped int4 scales ([L, G, out], one rank above int8's) take the
    # scales4 spec so the G axis follows the kernel's in-dim sharding —
    # the per-shard group_size stays correct inside shard_map.
    from edgemesh.parallel.sharding import pick_grouped_scales_spec

    def align(p_node, s_node):
        if isinstance(p_node, dict):
            s_dict = s_node if isinstance(s_node, dict) else {}
            out = {}
            for k, v in p_node.items():
                s = s_dict.get(k)
                if (
                    k == "scales"
                    and isinstance(s, P)
                    and getattr(v, "ndim", 0) == len(s) + 1
                ):
                    s, used4 = pick_grouped_scales_spec(s_dict, v, mesh)
                    kernel_spec = s_dict.get("kernel_q4", P())
                    in_sharded = len(kernel_spec) >= 2 and kernel_spec[-2] is not None
                    if not used4 and in_sharded and v.shape[-2] > 1:
                        # This engine computes per-shard: a row-sharded
                        # packed kernel with replicated grouped scales
                        # would miscompute the local group_size.
                        raise ValueError(
                            f"int4 group count {v.shape[-2]} does not divide "
                            f"tp={tp}; use a group_size giving G % tp == 0 "
                            "or per-channel scales (group_size=0)"
                        )
                out[k] = align(v, s)
            return out
        return s_node if isinstance(s_node, P) else P()

    return align(params, specs)


def make_tp_mapped(
    cfg: ModelConfig,
    mesh: Mesh,
    param_specs: Params,
    attention_impl: str,
    is_decode: bool,
    collective_mode: str = "psum",
    comm_dtype: str = "int8",
    overlap_chunks: int = 4,
):
    """The engine's core shard_map program: per-shard ``_forward`` with
    collective-joined attention/MLP outputs (``collective_mode``: psum |
    qpsum | qpsum_overlap — see parallel/collectives.py). Callable under
    ``jax.eval_shape`` with an ``AbstractMesh`` — no devices required."""
    validate_collective_mode(collective_mode, comm_dtype)
    lcfg = tp_local_config(cfg, mesh.shape["tp"], attention_impl)
    cache_spec = tp_cache_specs()
    attention_fn, mlp_fn = _make_collective_fns(
        collective_mode, comm_dtype, overlap_chunks
    )

    def local(params, tokens, positions, kv_valid, k, v, lengths):
        cache = KVCache(k, v, lengths)
        logits, new_cache, _ = _forward(
            lcfg, params, tokens, positions, cache, kv_valid, is_decode,
            attention=attention_fn, mlp=mlp_fn,
        )
        return logits, new_cache.k, new_cache.v

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(
            param_specs, P("dp", None), P("dp", None), P("dp", None),
            cache_spec.k, cache_spec.v, P("dp"),
        ),
        out_specs=(P("dp", None, None), cache_spec.k, cache_spec.v),
        check_vma=False,
    )


class TPInferenceEngine:
    """Head/column-sharded single-model executor over a ``dp x tp`` mesh.

    ``attention_impl``: None keeps cfg's setting except on real TPU, where it
    defaults to "flash" — inside shard_map the kernel sees local arrays, so
    multi-chip no longer disables it. Pass "flash" explicitly to exercise the
    kernel in interpret mode on a CPU mesh (the CI path), or "xla" to force
    the einsum attention.

    ``collective_mode`` picks the tp join for the row-sharded projections
    (parallel/collectives.py): "psum" (full-precision, the legacy default),
    "qpsum" (int8/fp8 quantized ring all-reduce — half the wire bytes), or
    "qpsum_overlap" (qpsum + chunked projections so each chunk's ring hides
    behind the next chunk's matmul). ``comm_dtype``/``overlap_chunks``
    parameterize the quantized modes.
    """

    def __init__(
        self,
        cfg: ModelConfig,
        params: Params,
        mesh: Mesh,
        attention_impl: str | None = None,
        collective_mode: str = "psum",
        comm_dtype: str = "int8",
        overlap_chunks: int = 4,
    ):
        if attention_impl is None:
            attention_impl = (
                "flash" if on_tpu() else cfg.attention_impl
            )
        validate_collective_mode(collective_mode, comm_dtype)
        tp = mesh.shape["tp"]
        self.cfg = cfg
        self.mesh = mesh
        self.tp = tp
        self.lcfg = tp_local_config(cfg, tp, attention_impl)
        self.attention_impl = attention_impl
        self.collective_mode = collective_mode
        self.comm_dtype = comm_dtype
        self.overlap_chunks = int(overlap_chunks)
        self.param_specs = tp_param_specs(cfg, params, mesh)
        self.params = self._place(params)
        self.cache_spec = tp_cache_specs()
        self._mapped_prefill = self._make_mapped(is_decode=False)
        self._mapped_decode = self._make_mapped(is_decode=True)
        self._prefill_jit = jax.jit(self._make_step(is_decode=False))
        self._decode_jit = jax.jit(self._make_step(is_decode=True))

    # -- placement ---------------------------------------------------------

    def _place(self, params: Params) -> Params:
        tp = self.tp

        def walk(p_node, s_node, path=()):
            if isinstance(p_node, dict):
                return {
                    k: walk(v, s_node.get(k) if isinstance(s_node, dict) else None, path + (k,))
                    for k, v in p_node.items()
                }
            spec = s_node if isinstance(s_node, P) else P()
            # Row-sharded denses ("o", "down") produce partial sums that are
            # psum-joined across tp; their replicated biases would be added
            # tp times, so pre-divide them once here.
            if path[-1] == "bias" and len(path) >= 2 and path[-2] in ("o", "down"):
                p_node = p_node / tp
            return jax.device_put(p_node, NamedSharding(self.mesh, spec))

        return walk(params, self.param_specs)

    def init_cache(self, batch: int, max_seq: int | None = None) -> KVCache:
        cfg = self.cfg
        max_seq = max_seq or cfg.max_seq_len
        shape = (cfg.num_layers, batch, max_seq, cfg.num_kv_heads, cfg.head_size)
        return KVCache(
            k=jax.device_put(
                jnp.zeros(shape, cfg.activation_dtype),
                NamedSharding(self.mesh, self.cache_spec.k),
            ),
            v=jax.device_put(
                jnp.zeros(shape, cfg.activation_dtype),
                NamedSharding(self.mesh, self.cache_spec.v),
            ),
            lengths=jax.device_put(
                jnp.zeros((batch,), jnp.int32),
                NamedSharding(self.mesh, self.cache_spec.lengths),
            ),
        )

    # -- compiled steps ----------------------------------------------------

    def _make_mapped(self, is_decode: bool):
        return make_tp_mapped(
            self.cfg, self.mesh, self.param_specs, self.attention_impl,
            is_decode, collective_mode=self.collective_mode,
            comm_dtype=self.comm_dtype, overlap_chunks=self.overlap_chunks,
        )

    def _make_step(self, is_decode: bool):
        if is_decode:
            def decode_step(params, tokens, cache: KVCache):
                return self.decode_forward(self.cfg, params, tokens, cache)

            return decode_step

        def step(params, tokens, lengths, cache: KVCache):
            return self.prefill_forward(self.cfg, params, tokens, lengths, cache)

        return step

    # These two carry the transformer.forward_prefill/forward_decode
    # CALLING CONVENTIONS exactly (the leading cfg is accepted and ignored —
    # the engine's local config is baked into the mapped program), so the
    # continuous engine's dense backend can serve over this engine by
    # swapping them in for the single-chip forwards (serve/continuous.py
    # ``tp_engine=``: ``decode_forward`` is its ``decode_fn``). Traceable
    # inside an enclosing jit (the decode loop / bridge).

    def prefill_forward(self, cfg, params, tokens, lengths, cache: KVCache):
        b = tokens.shape[0]
        max_seq = cache.k.shape[2]
        s = tokens.shape[1]
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        positions = jnp.minimum(positions, (lengths - 1)[:, None])
        kv_valid = jnp.arange(max_seq)[None, :] < lengths[:, None]
        logits, k, v = self._mapped_prefill(
            params, tokens, positions, kv_valid, cache.k, cache.v, lengths
        )
        last = logits[jnp.arange(b), lengths - 1]
        return last, KVCache(k, v, lengths)

    def decode_forward(self, cfg, params, tokens, cache: KVCache):
        max_seq = cache.k.shape[2]
        positions = cache.lengths[:, None]
        kv_valid = jnp.arange(max_seq)[None, :] <= cache.lengths[:, None]
        logits, k, v = self._mapped_decode(
            params, tokens[:, None], positions, kv_valid,
            cache.k, cache.v, cache.lengths,
        )
        return logits[:, 0], KVCache(k, v, cache.lengths + 1)

    def prefill(self, tokens: jnp.ndarray, lengths: jnp.ndarray, cache: KVCache):
        return self._prefill_jit(self.params, tokens, lengths, cache)

    def decode(self, tokens: jnp.ndarray, cache: KVCache):
        return self._decode_jit(self.params, tokens, cache)

    def instrument(self, ledger) -> None:
        """Route this engine's two jitted shard_map programs through the
        compute ledger (obs/compute.ComputeLedger) as the ``tp_prefill``
        / ``tp_decode`` boundaries. Prefill keys by the padded prompt
        bucket (its compile identity); the decode step compiles once.
        The serving engine calls this when it attaches a tp engine, so
        tp prefills land in the same launch ledger as every other
        boundary; standalone callers (generate_greedy, benches) can call
        it themselves. Idempotent enough for one ledger: re-wrapping
        with a second ledger would double-count, so instrument once."""
        self._prefill_jit = ledger.wrap(
            "tp_prefill", self._prefill_jit,
            key_fn=lambda params, tokens, lengths, cache: f"p{tokens.shape[1]}",
        )
        self._decode_jit = ledger.wrap("tp_decode", self._decode_jit)

    def collective_accounting(self, batch: int = 1, seq: int = 1) -> dict:
        """Analytic per-step wire accounting for THIS engine's join mode:
        what one forward over [batch, seq] tokens ships per chip, per layer
        and in total (parallel/collectives.collective_wire_bytes — shapes
        are static, so these are exact counts, not estimates). Feeds
        ``edgemesh_collective_bytes_total{op,dtype}`` and the per-request
        span attrs in serve/continuous.py."""
        quantized = self.collective_mode != "psum" and self.comm_dtype != "bf16"
        op = "qpsum" if quantized else "psum"
        wire_dtype = self.comm_dtype if quantized else "bf16"
        mode = "qpsum" if quantized else "psum"
        h = self.cfg.hidden_size
        if self.collective_mode == "qpsum_overlap":
            # Output-dim chunking: k disjoint [b, s, h/k] joins whose
            # payloads sum to the monolithic join (plus k x the per-row
            # scale vectors) — count what actually ships per chunk.
            k = _pick_chunks(h, self.overlap_chunks)
            per = k * collective_wire_bytes(
                (batch, seq, h // k), self.tp, mode, wire_dtype,
            )
        else:
            per = collective_wire_bytes(
                (batch, seq, h), self.tp, mode, wire_dtype,
            )
        return {
            "op": op,
            "dtype": wire_dtype,
            "per_layer": {"attn_o": per, "mlp_down": per},
            "bytes_per_step": self.cfg.num_layers * 2 * per,
        }

    def generate_greedy(
        self, tokens: jnp.ndarray, lengths: jnp.ndarray, max_new: int
    ) -> jnp.ndarray:
        b, s = tokens.shape
        cache = self.init_cache(b, s + max_new)
        logits, cache = self.prefill(tokens, lengths, cache)
        outs = []
        for _ in range(max_new):
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            outs.append(nxt)
            logits, cache = self.decode(nxt, cache)
        return jnp.stack(outs, axis=1)
