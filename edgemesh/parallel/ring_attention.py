"""Ring attention: exact blockwise attention over a sequence-sharded mesh axis.

Long-context is absent from the reference (SURVEY.md §5.7 — prompts are single
questions, ``truncation=True``, combiner_fp.py:334; it collects HeadInfer but
implements nothing). edgemesh makes sequence/context parallelism first-class:
the sequence axis is sharded over the mesh's ``sp`` axis, each device holds
one contiguous Q/K/V block, and K/V blocks rotate around the ring with
``lax.ppermute`` while a running (flash-style) online softmax accumulates the
exact result — O(seq/sp) memory per chip, collectives riding ICI neighbor
links (Liu et al. 2023 ring attention; blockwise parallel transformers).

Causality is enforced at block granularity with global positions, so the
result is EXACTLY standard causal attention — pinned against the dense op in
tests.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from edgemesh.utils.compat import pcast, shard_map

NEG_INF = -1e30


def _block_attend_accumulate(
    q: jnp.ndarray,  # [b, sq, kh, g, d] fp32-scaled queries (local block)
    k: jnp.ndarray,  # [b, sk, kh, d] visiting K block
    v: jnp.ndarray,  # [b, sk, kh, d] visiting V block
    q_pos: jnp.ndarray,  # [b, sq] global positions of local queries
    k_pos: jnp.ndarray,  # [b, sk] global positions of visiting keys
    k_valid: jnp.ndarray,  # [b, sk] visiting keys hold real tokens
    m: jnp.ndarray,  # [b, sq, kh, g] running max
    l: jnp.ndarray,  # [b, sq, kh, g] running denominator
    o: jnp.ndarray,  # [b, sq, kh, g, d] running numerator
    sliding_window: int = 0,
    soft_cap: float = 0.0,
):
    """One online-softmax accumulation step (the flash-attention recurrence).
    Window/soft-cap semantics match the dense op (ops/attention.attend):
    key j visible to query p iff j <= p (and j > p - w); the cap squashes
    the scaled scores before masking."""
    scores = jnp.einsum("bqkgd,bskd->bqkgs", q, k, preferred_element_type=jnp.float32)
    if soft_cap > 0:
        scores = soft_cap * jnp.tanh(scores / soft_cap)
    mask = (k_pos[:, None, :] <= q_pos[:, :, None]) & k_valid[:, None, :]  # [b, sq, sk]
    if sliding_window > 0:
        mask = mask & (k_pos[:, None, :] > q_pos[:, :, None] - sliding_window)
    scores = jnp.where(mask[:, :, None, None, :], scores, NEG_INF)

    block_max = jnp.max(scores, axis=-1)  # [b, sq, kh, g]
    new_m = jnp.maximum(m, block_max)
    correction = jnp.exp(m - new_m)
    p = jnp.exp(scores - new_m[..., None])  # [b, sq, kh, g, sk]
    # Fully-masked blocks must contribute exactly zero (exp(NEG_INF - m) == 0).
    new_l = l * correction + jnp.sum(p, axis=-1)
    new_o = o * correction[..., None] + jnp.einsum(
        "bqkgs,bskd->bqkgd", p, v, preferred_element_type=jnp.float32
    )
    return new_m, new_l, new_o


def ring_attend_block(
    q_blk: jnp.ndarray,  # [b, sq, num_heads, head_dim] local query block
    k_blk: jnp.ndarray,  # [b, sq, kv_heads, head_dim] local key block
    v_blk: jnp.ndarray,
    pos_blk: jnp.ndarray,  # [b, sq] global positions of the local block
    valid_blk: jnp.ndarray,  # [b, sq] real-token mask of the local block
    *,
    axis: str = "sp",
    sp: int,
    scale: float | None = None,
    sliding_window: int = 0,
    soft_cap: float = 0.0,
    pcast_accumulators: bool = True,
) -> jnp.ndarray:
    """Per-device body of ring attention — callable inside ANY enclosing
    shard_map that carries the ``axis`` mesh axis (the 4D SPMD train step in
    edgemesh/parallel/spmd.py nests this inside its pp/tp program).

    ``sliding_window``/``soft_cap`` follow ops/attention.attend semantics
    (Mistral windows, Gemma-2 score caps). A window does not shorten the
    ring — every K/V block still makes all ``sp`` hops (the schedule is
    static) — but out-of-window blocks contribute exactly zero through the
    mask, preserving exactness.

    ``pcast_accumulators=False`` skips the varying-manual-axes cast for
    enclosing shard_maps running with check_vma=False."""
    b, sq, num_heads, head_dim = q_blk.shape
    kv_heads = k_blk.shape[2]
    groups = num_heads // kv_heads
    scale = scale if scale is not None else head_dim**-0.5
    qg = q_blk.reshape(b, sq, kv_heads, groups, head_dim).astype(jnp.float32) * scale

    # pcast: the m/l/o accumulators become device-varying once they mix
    # with ring-permuted K/V; their zero inits must carry the same
    # varying-manual-axes type for the scan carry to typecheck.
    m0 = jnp.full((b, sq, kv_heads, groups), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, sq, kv_heads, groups), jnp.float32)
    o0 = jnp.zeros((b, sq, kv_heads, groups, head_dim), jnp.float32)
    if pcast_accumulators:
        m0 = pcast(m0, axis, to="varying")
        l0 = pcast(l0, axis, to="varying")
        o0 = pcast(o0, axis, to="varying")

    right = [(i, (i + 1) % sp) for i in range(sp)]

    def ring_step(carry, _):
        k_c, v_c, kpos_c, kval_c, m, l, o = carry
        m, l, o = _block_attend_accumulate(
            qg, k_c.astype(jnp.float32), v_c.astype(jnp.float32),
            pos_blk, kpos_c, kval_c, m, l, o,
            sliding_window=sliding_window, soft_cap=soft_cap,
        )
        # rotate K/V blocks one hop around the ring (ICI neighbor traffic)
        k_c = lax.ppermute(k_c, axis, right)
        v_c = lax.ppermute(v_c, axis, right)
        kpos_c = lax.ppermute(kpos_c, axis, right)
        kval_c = lax.ppermute(kval_c, axis, right)
        return (k_c, v_c, kpos_c, kval_c, m, l, o), None

    (k_c, v_c, kpos_c, kval_c, m, l, o), _ = lax.scan(
        ring_step,
        (k_blk, v_blk, pos_blk, valid_blk, m0, l0, o0),
        None,
        length=sp,
    )
    out = o / jnp.maximum(l[..., None], 1e-30)
    return out.reshape(b, sq, num_heads, head_dim).astype(q_blk.dtype)


def ring_attention(
    q: jnp.ndarray,  # [b, seq, num_heads, head_dim] — seq sharded over "sp"
    k: jnp.ndarray,  # [b, seq, kv_heads, head_dim] — seq sharded over "sp"
    v: jnp.ndarray,
    positions: jnp.ndarray,  # [b, seq] global positions — sharded over "sp"
    valid: jnp.ndarray,  # [b, seq] real-token mask — sharded over "sp"
    mesh: Mesh,
    scale: float | None = None,
    sliding_window: int = 0,
    soft_cap: float = 0.0,
) -> jnp.ndarray:
    """Exact causal attention with the sequence axis sharded over ``sp``.

    Returns [b, seq, num_heads, head_dim], sharded like ``q``.
    """
    sp = mesh.shape["sp"]

    def local_fn(q_blk, k_blk, v_blk, pos_blk, valid_blk):
        return ring_attend_block(
            q_blk, k_blk, v_blk, pos_blk, valid_blk, axis="sp", sp=sp, scale=scale,
            sliding_window=sliding_window, soft_cap=soft_cap,
        )

    seq_spec = P(None, "sp")
    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(
            P(None, "sp", None, None),
            P(None, "sp", None, None),
            P(None, "sp", None, None),
            seq_spec,
            seq_spec,
        ),
        out_specs=P(None, "sp", None, None),
    )(q, k, v, positions, valid)
