"""Serving front door: the host-side REST gateway."""

from edgemesh.serve.rest import serve_rest  # noqa: F401
