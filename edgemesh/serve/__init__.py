"""Serving front door: the host-side REST gateway."""

from edgemesh.serve.rest import GatewayServer, serve_rest  # noqa: F401
